"""Training step: jit-compiled, sharded, donated.

The full step — forward (bf16), loss, backward, optax update — under one
``jit`` over the mesh: XLA lays every collective (attention-ring
ppermutes, TP psums, DP gradient all-reduce) onto ICI from the sharding
annotations alone, the §2.3 "GPU-aware, no host staging" property at
training scale. Master params/opt state stay f32 and are donated, so the
update is in-place in HBM.

Sharding flows from the *data*: params are placed with
models/sharding.py rules, optax moments inherit those shardings at init
(zeros_like preserves sharding), tokens are placed with batch_sharding —
jit then propagates from its inputs, with the activation constraints in
forward() pinning the interior. No separate opt-state sharding spec to
maintain.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax import lax

from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import trace as tracelib
from hpc_patterns_tpu.memory import kinds as kindslib
from hpc_patterns_tpu.models import sharding as shardlib
from hpc_patterns_tpu.models.transformer import TransformerConfig, init_params, loss_fn


def record_step_metrics(step: int, loss: float, dt_s: float,
                        tokens: int) -> None:
    """Per-step training telemetry into the process-wide registry
    (harness/metrics.py; no-op when disabled): loss/step-time/throughput
    gauges, a step-time histogram split by phase — step 0 is the
    compile-dominated step, so it lands in a ``train.compile_s`` gauge
    instead of polluting the steady-state ``train.step_s`` percentiles
    (the warmup-vs-timed discipline of harness.timing applied to the
    training loop)."""
    m = metricslib.get_metrics()
    if not m.enabled:
        return
    m.counter("train.steps").inc()
    m.gauge("train.loss").set(loss)
    m.gauge("train.step_time_s").set(dt_s)
    if dt_s > 0:
        m.gauge("train.tokens_per_s").set(tokens / dt_s)
    if step == 0:
        m.gauge("train.compile_s").set(dt_s)
    else:
        m.histogram("train.step_s").observe(dt_s)


def make_optimizer(learning_rate: float = 3e-4, weight_decay: float = 0.01,
                   grad_clip: float = 1.0, *, warmup_steps: int = 0,
                   total_steps: int = 0, schedule: str = "constant"):
    """adamw + global-norm clip, with the standard LR schedules:
    ``constant`` (default), or ``cosine`` — linear warmup over
    ``warmup_steps`` then cosine decay to 10% of peak at
    ``total_steps`` (required for cosine)."""
    if schedule == "constant":
        lr = (
            optax.linear_schedule(0.0, learning_rate, warmup_steps)
            if warmup_steps else learning_rate
        )
    elif schedule == "cosine":
        if total_steps <= warmup_steps:
            raise ValueError(
                f"cosine needs total_steps > warmup_steps, got "
                f"{total_steps} <= {warmup_steps}"
            )
        lr = optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=learning_rate,
            warmup_steps=warmup_steps, decay_steps=total_steps,
            end_value=0.1 * learning_rate,
        )
    else:
        raise ValueError(f"schedule {schedule!r} not in (constant, cosine)")
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(lr, weight_decay=weight_decay),
    )


def memory_kind_shardings(tree, kind: str):
    """Shardings of ``tree``'s (concrete) leaves retargeted to a JAX
    memory kind — the L2 allocator axis (SURVEY.md §2, ``-H/-D/-S``)
    applied to training state. Delegates to the single definition in
    ``memory/kinds.py`` (the residency subsystem's probe/sharding
    home); this name stays for its existing callers."""
    return kindslib.memory_kind_shardings(tree, kind)


def offload_opt_state(opt_state, kind: str = "pinned_host"):
    """Move the optimizer state to host memory. Adam moments are 2x the
    (f32) parameter footprint and are touched once per step — parking
    them in host RAM frees that HBM for batch/model/sequence headroom,
    at the cost of streaming them over PCIe each step. Pair with
    ``make_train_step(..., offload_opt_example=...)``.

    Gated on the SHARED placement probe (memory/kinds.py): a backend
    that cannot actually place buffers in ``kind`` gets the input back
    UNCHANGED with a printed note — previously this path paid the
    ``device_put`` (and on some backends raised) while delivering none
    of the offload's benefit, and callers could not tell."""
    leaves = jax.tree.leaves(opt_state)
    device = next(iter(leaves[0].devices())) if leaves else None
    if not kindslib.memory_kind_placement_works(device, kind):
        print(f"note: backend has no usable {kind!r} memory kind; "
              "optimizer state left in place (no offload benefit "
              "available here)")
        return opt_state
    return jax.device_put(opt_state, memory_kind_shardings(opt_state, kind))


def offload_shardings(opt_state_host):
    """(host_shardings, hbm_shardings) for a host-resident opt state —
    THE pull/push targets of the offloaded update, shared by
    make_train_step and the training benchmark so the streaming
    strategy cannot drift between what ships and what is measured."""
    host_sh = jax.tree.map(lambda x: x.sharding, opt_state_host)
    return host_sh, memory_kind_shardings(opt_state_host, "device")


def offload_example_shardings(example):
    """:func:`offload_shardings`, tolerant of the probe-gated identity
    fallback: when :func:`offload_opt_state` left the state IN PLACE
    (no usable pinned_host on this backend), the tiers collapse onto
    one memory — both targets are the example's own shardings, so the
    step's staging still runs as same-memory copies instead of dying
    inside ``with_memory_kind("device")`` with an error that looks
    unrelated to the note the user was shown. ONE definition for every
    step builder taking an ``offload_opt_example`` (make_train_step,
    pp.make_pp_train_step)."""
    leaves = jax.tree.leaves(example)
    pinned = bool(leaves) and all(
        getattr(x.sharding, "memory_kind", None) == "pinned_host"
        for x in leaves)
    if pinned:
        return offload_shardings(example)
    host_sh = jax.tree.map(lambda x: x.sharding, example)
    return host_sh, host_sh


def make_train_step(cfg: TransformerConfig, mesh=None, optimizer=None,
                    accum_steps: int = 1, offload_opt_example=None,
                    residency=None):
    """Returns jitted ``step(params, opt_state, tokens) -> (loss, params,
    opt_state)`` with param/opt-state donation (in-place HBM update).

    ``accum_steps > 1`` splits the batch into that many micro-batches
    and accumulates gradients over a ``lax.scan`` before the single
    optimizer update — same numbers as the big batch (mean of
    micro-means over equal splits), at 1/accum_steps the activation
    memory: the train-side memory lever alongside remat.

    ``offload_opt_example``: a host-resident optimizer state (from
    :func:`offload_opt_state`) whose shardings tell the step where the
    state lives — the update then pulls it to HBM, applies, and pushes
    it back, all inside the one jit (XLA schedules the transfers).

    ``residency``: a :class:`hpc_patterns_tpu.memory.ResidencyManager`
    — routes the offload through the tiered-memory subsystem instead
    of the in-jit all-or-nothing move: the host->HBM pull is
    DISPATCHED before the gradient phase and hides under it
    (accumulation-phase prefetch, with a measured ``mem.prefetch``
    window and overlap fraction), the update consumes the pulled
    state, and the push back to host rides a ``mem.evict`` window
    (docs/memory.md). Requires ``offload_opt_example``. Numerics are
    the single-jit path's (same gradient and update ops, staged).

    Pass ``params``/``opt_state`` created by :func:`init_train_state`
    (sharded when ``mesh`` is given); the same code path is the
    single-device oracle when ``mesh`` is None (the §4 test strategy:
    distributed result must match the local one).
    """
    optimizer = optimizer or make_optimizer()
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    grad_fn = jax.value_and_grad(partial(loss_fn, cfg=cfg, mesh=mesh))
    if offload_opt_example is not None:
        host_sh, hbm_sh = offload_example_shardings(offload_opt_example)
    else:
        host_sh = hbm_sh = None

    def accum_grads(params, tokens):
        if accum_steps == 1:
            return grad_fn(params, tokens)
        B = tokens.shape[0]
        if B % accum_steps:
            raise ValueError(
                f"batch {B} must divide by accum_steps {accum_steps}"
            )
        micro = tokens.reshape(accum_steps, B // accum_steps, -1)

        def accum(carry, mb):
            loss_sum, g_sum = carry
            loss, g = grad_fn(params, mb)
            return (
                loss_sum + loss,
                jax.tree.map(jnp.add, g_sum, g),
            ), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        (loss, grads), _ = lax.scan(
            accum, (jnp.zeros((), jnp.float32), zeros), micro
        )
        scale = 1.0 / accum_steps
        return loss * scale, jax.tree.map(lambda g: g * scale, grads)

    if residency is not None:
        if offload_opt_example is None:
            raise ValueError(
                "residency streaming needs offload_opt_example (a "
                "host-resident opt state from offload_opt_state)")
        return _make_streamed_step(optimizer, accum_grads, host_sh,
                                   hbm_sh, residency)

    def step(params, opt_state, tokens):
        if hbm_sh is not None:
            opt_state = jax.device_put(opt_state, hbm_sh)
        loss, grads = accum_grads(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if host_sh is not None:
            opt_state = jax.device_put(opt_state, host_sh)
        return loss, params, opt_state

    if host_sh is not None:
        # declare the host residency of the opt-state input/output so
        # donation pairs host buffers with host buffers
        jitted = jax.jit(
            step, donate_argnums=(0, 1),
            in_shardings=(None, host_sh, None),
            out_shardings=(None, None, host_sh),
        )
    else:
        jitted = jax.jit(step, donate_argnums=(0, 1))
    # under --trace, the flight recorder stamps a compile event (with
    # the triggering batch shapes) every time a call grows the jit
    # cache — a recompiling training loop is visible on the timeline
    # instead of showing up only as a slow step; without a recorder
    # the wrapper is a passthrough call. exec_memory stays off: the
    # AOT memory_analysis pass is a second full compile of the step
    # (use trace.record_executable_memory at an explicit AOT site)
    return tracelib.instrument_jit(jitted, "train.step")


def _make_streamed_step(optimizer, accum_grads, host_sh, hbm_sh,
                        residency):
    """The residency-managed offloaded step: two jits staged around
    the manager's instrumented transfers (see ``make_train_step``'s
    ``residency`` doc). The pull DISPATCHES first, the gradient-
    accumulation jit runs over it, and the pull's completion is
    OBSERVED (blocked) while that phase still executes — so the wait
    that remains is exactly the transfer time the accumulation failed
    to hide, and the ``mem.prefetch`` window + overlap fraction report
    it instead of asserting it."""
    import jax as _jax

    leaves = _jax.tree.leaves(host_sh)
    pinned = bool(leaves) and all(
        getattr(s, "memory_kind", None) == "pinned_host"
        for s in leaves)
    if not pinned:
        # degraded tier (no real pinned_host — offload_opt_state left
        # the state in place): the tiers collapse onto one memory, the
        # staging/measurement pipeline still runs — the CPU test shape
        hbm_sh = host_sh
    accum_jit = tracelib.instrument_jit(jax.jit(accum_grads),
                                        "train.accum")

    def apply_update(params, grads, opt_state):
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state

    # params + opt state donate (the in-place HBM update, as in the
    # fused step); grads do not — only some of their buffers could
    # alias an output, and the partial-donation warning would spam
    # every caller for a marginal win
    apply_jit = tracelib.instrument_jit(
        jax.jit(apply_update, donate_argnums=(0, 2)), "train.apply")

    def step(params, opt_state, tokens):
        import time

        # close the PREVIOUS step's mem.evict window first (its push
        # had a whole step to land, so this block is cheap) — without
        # it a traced run retains every step's host opt-state copy in
        # the manager's open-window list, unbounded
        residency.drain()
        opt_dev, handle = residency.pull_payload(
            opt_state, shardings=hbm_sh,
            attrs={"consumer": "train.accum"})
        t_acc0 = time.perf_counter()
        loss, grads = accum_jit(params, tokens)
        # observe the ACCUMULATION's completion first: the consumer
        # window must end when the hiding compute ended. Stamping it
        # after also waiting out the pull would extend the window over
        # the exposed wait and read ~100% overlap for a transfer the
        # accumulation barely covered — the one number this exists to
        # catch on chip
        jax.block_until_ready(loss)
        t_acc1 = time.perf_counter()
        # now the pull: any wait that remains is the UNHIDDEN time
        jax.block_until_ready(opt_dev)
        residency.complete_pull(handle,
                                chunk_windows=((t_acc0, t_acc1),))
        params, opt_dev = apply_jit(params, grads, opt_dev)
        opt_host = residency.push_payload(
            opt_dev, shardings=host_sh,
            attrs={"consumer": "train.apply"})
        return loss, params, opt_host

    return step


def init_train_state(key, cfg: TransformerConfig, mesh=None, optimizer=None):
    """(params, opt_state): f32 master params placed per the sharding
    rules; optax state inherits the placement (zeros_like preserves
    sharding).

    With a mesh, init runs *under jit with sharded out_shardings*, so
    each device materializes only its own shards — no single device ever
    holds the full f32 copy (the point of TP at flagship scale)."""
    optimizer = optimizer or make_optimizer()
    if mesh is None:
        params = init_params(key, cfg)
    else:
        # jaxlint: disable=recompile-hazard — init-time one-shot (once
        # per train state); out_shardings close over the runtime mesh
        params = jax.jit(
            lambda k: init_params(k, cfg),
            out_shardings=shardlib.param_shardings(mesh, cfg),
        )(key)
    opt_state = optimizer.init(params)
    return params, opt_state


def make_batch(key, cfg: TransformerConfig, batch: int, seq: int, mesh=None):
    """Synthetic token batch (benchmark fuel), sharded when mesh given."""
    tokens = jax.random.randint(key, (batch, seq), 0, cfg.vocab, jnp.int32)
    if mesh is not None:
        tokens = jax.device_put(tokens, shardlib.batch_sharding(mesh, cfg))
    return tokens
