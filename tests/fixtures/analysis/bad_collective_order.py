"""Known-bad: two code paths reach the same communicator with the same
collectives in different orders. If the predicate ever disagrees
across ranks — a config drift, a data-dependent threshold — rank A's
all_gather pairs with rank B's reduce_scatter: the mis-ordered
``MPI_Send/Recv`` cross."""


def gather_then_scatter_or_swapped(comm, x, big):
    if x.shape[0] > big:  # EXPECT: collective-order
        g = comm.all_gather(x)
        s = comm.reduce_scatter(x)
    else:
        s = comm.reduce_scatter(x)
        g = comm.all_gather(x)
    return g, s
