"""Open-loop load generation: seeded arrival processes for serving.

``bench_serving``'s original stream is CLOSED-loop: every request is
queued up front and a new one only makes progress when the engine frees
capacity — so the offered load adapts to the server and overload can
never happen. Real traffic is OPEN-loop: arrivals come on the *users'*
clock (the classic closed-vs-open distinction; under-provisioned
open-loop systems build queues and blow deadlines instead of politely
slowing the benchmark down). This module generates those arrival
schedules:

- **poisson** — memoryless arrivals at a constant mean rate (the
  steady-traffic null model);
- **bursty** — a two-phase Markov-modulated process: quiet periods at
  the base rate alternate with bursts at ``burst_factor`` times it
  (queue-depth spikes, the admission-control stressor);
- **diurnal** — a sinusoidally modulated rate (period ``period_s``,
  modulation depth ``depth``) sampled by thinning (peak-hour vs
  trough, the capacity-planning shape).

Every schedule is DETERMINISTIC given its parameters and seed, and
round-trips through JSON (:meth:`Schedule.to_json`) — so a chaos run's
exact traffic can be replayed against a fix, and a scenario row in a
benchmark names the schedule that produced it.

Requests carry a **priority class** (:class:`PriorityClass`: lower
``priority`` number = more important, the P0/P1 convention) with
per-class SLO targets (consumed by ``harness/slo.py``) and an optional
queue ``deadline_s`` (consumed by the engine's shedding policy). The
serving engine admits in priority order and — with ``preempt=True`` —
evicts lower classes under page pressure (``models/serving.py``).

Import-light (numpy only): schedules must be buildable from jax-free
drivers and launcher children.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Any, Sequence

import numpy as np


@dataclass(frozen=True)
class PriorityClass:
    """One traffic class. ``priority``: lower = more important (the
    engine admits lower numbers first and may preempt higher ones for
    them). ``weight``: relative share of arrivals. ``ttft_slo_s`` /
    ``tpot_slo_s``: the class's SLO targets (None = no target —
    trivially attained). ``deadline_s``: queue-time shedding deadline
    (None = never shed)."""
    name: str
    priority: int
    weight: float = 1.0
    ttft_slo_s: float | None = None
    tpot_slo_s: float | None = None
    deadline_s: float | None = None


@dataclass(frozen=True)
class ScheduledRequest:
    """One arrival: WHEN it enters (``t_arrival_s``, relative to the
    run start), what class it belongs to, and its shape (prompt
    length, generation budget). Prompt token CONTENT is the driver's
    job (seeded separately) — the schedule is shape + timing only, so
    one schedule replays against any vocabulary."""
    index: int
    t_arrival_s: float
    cls: str
    priority: int
    prompt_len: int
    max_new: int
    deadline_s: float | None = None


@dataclass(frozen=True)
class Schedule:
    """A replayable arrival schedule: the requests in arrival order
    plus the generating spec (provenance — a benchmark row can name
    exactly which traffic produced it)."""
    requests: tuple[ScheduledRequest, ...]
    spec: dict = field(default_factory=dict)

    @property
    def n(self) -> int:
        return len(self.requests)

    @property
    def duration_s(self) -> float:
        return self.requests[-1].t_arrival_s if self.requests else 0.0

    def to_json(self) -> str:
        return json.dumps({
            "spec": self.spec,
            "requests": [asdict(r) for r in self.requests],
        })

    @classmethod
    def from_json(cls, text: str) -> "Schedule":
        obj = json.loads(text)
        return cls(
            requests=tuple(ScheduledRequest(**r)
                           for r in obj.get("requests", [])),
            spec=dict(obj.get("spec", {})),
        )


# ---------------------------------------------------------------------------
# arrival processes (times only; all driven by one RandomState)
# ---------------------------------------------------------------------------


def poisson_times(n: int, rate_rps: float,
                  rng: np.random.RandomState) -> np.ndarray:
    """n arrival instants of a homogeneous Poisson process: cumulative
    exponential inter-arrivals at mean ``1/rate``."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    return np.cumsum(rng.exponential(1.0 / rate_rps, size=n))


def bursty_times(n: int, rate_rps: float, rng: np.random.RandomState,
                 *, burst_factor: float = 8.0,
                 mean_quiet_s: float = 1.0,
                 mean_burst_s: float = 0.25) -> np.ndarray:
    """Two-phase modulated Poisson: exponential quiet phases at the
    base rate alternating with exponential burst phases at
    ``burst_factor``× it. The phase sequence and the arrivals inside
    each phase all come from ``rng`` — one seed, one schedule."""
    if burst_factor < 1.0:
        raise ValueError(f"burst_factor must be >= 1, got {burst_factor}")
    times: list[float] = []
    t = 0.0
    burst = False
    while len(times) < n:
        phase = rng.exponential(mean_burst_s if burst else mean_quiet_s)
        rate = rate_rps * (burst_factor if burst else 1.0)
        # arrivals inside this phase: sequential exponentials until the
        # phase ends (keeps the draw count deterministic per phase)
        u = t
        while True:
            u += rng.exponential(1.0 / rate)
            if u > t + phase or len(times) >= n:
                break
            times.append(u)
        t += phase
        burst = not burst
    return np.asarray(times[:n])


def diurnal_times(n: int, rate_rps: float, rng: np.random.RandomState,
                  *, period_s: float = 60.0,
                  depth: float = 0.8) -> np.ndarray:
    """Sinusoidally modulated Poisson sampled by thinning: the
    instantaneous rate is ``rate*(1 + depth*sin(2πt/period))``;
    candidates are generated at the peak rate and accepted with
    probability rate(t)/peak — the standard exact thinning
    construction, deterministic given ``rng``."""
    if not 0.0 <= depth < 1.0:
        raise ValueError(f"depth must be in [0, 1), got {depth}")
    peak = rate_rps * (1.0 + depth)
    times: list[float] = []
    t = 0.0
    while len(times) < n:
        t += rng.exponential(1.0 / peak)
        rate_t = rate_rps * (1.0 + depth * np.sin(2 * np.pi * t / period_s))
        if rng.uniform() * peak <= rate_t:
            times.append(t)
    return np.asarray(times)


_PROCESSES = {
    "poisson": poisson_times,
    "bursty": bursty_times,
    "diurnal": diurnal_times,
}


# ---------------------------------------------------------------------------
# schedule assembly
# ---------------------------------------------------------------------------


def make_schedule(n: int, *, rate_rps: float,
                  classes: Sequence[PriorityClass],
                  prompt_lens: Sequence[int],
                  budgets: Sequence[int],
                  budget_probs: Sequence[float] | None = None,
                  process: str = "poisson", seed: int = 0,
                  **process_kw: Any) -> Schedule:
    """The one constructor: ``n`` arrivals from the named process, each
    assigned a class (by weight), a prompt length, and a budget — all
    from ONE seeded RandomState, so (params, seed) fully determine the
    schedule. ``process_kw`` passes through to the arrival process
    (``burst_factor``, ``period_s``, ...)."""
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    if not classes:
        raise ValueError("need at least one PriorityClass")
    gen = _PROCESSES.get(process)
    if gen is None:
        raise ValueError(f"unknown process {process!r} "
                         f"(known: {', '.join(sorted(_PROCESSES))})")
    rng = np.random.RandomState(seed)
    times = gen(n, rate_rps, rng, **process_kw)
    weights = np.asarray([c.weight for c in classes], np.float64)
    if weights.sum() <= 0:
        raise ValueError("class weights must sum > 0")
    weights = weights / weights.sum()
    cls_idx = rng.choice(len(classes), size=n, p=weights)
    plens = rng.choice(np.asarray(prompt_lens, np.int64), size=n)
    budgets_arr = np.asarray(budgets, np.int64)
    probs = (np.asarray(budget_probs, np.float64)
             if budget_probs is not None else None)
    news = rng.choice(budgets_arr, size=n, p=probs)
    reqs = []
    for i in range(n):
        c = classes[int(cls_idx[i])]
        reqs.append(ScheduledRequest(
            index=i, t_arrival_s=float(times[i]), cls=c.name,
            priority=c.priority, prompt_len=int(plens[i]),
            max_new=int(news[i]), deadline_s=c.deadline_s))
    spec = {"process": process, "n": n, "rate_rps": rate_rps,
            "seed": seed, "prompt_lens": list(map(int, prompt_lens)),
            "budgets": list(map(int, budgets)),
            "classes": [asdict(c) for c in classes], **process_kw}
    return Schedule(requests=tuple(reqs), spec=spec)


def staged_schedule(stages: Sequence[tuple[float, PriorityClass, int, int]],
                    spec: dict | None = None) -> Schedule:
    """An explicit hand-staged schedule — (t_arrival_s, class,
    prompt_len, max_new) tuples in arrival order. The deterministic
    building block for CI scenario smokes, where the preemption trigger
    must not depend on a random draw; still a :class:`Schedule`, so it
    serializes and replays exactly like a generated one."""
    reqs = []
    last = -np.inf
    for i, (t, c, plen, mnew) in enumerate(stages):
        if t < last:
            raise ValueError("staged arrivals must be non-decreasing")
        last = t
        reqs.append(ScheduledRequest(
            index=i, t_arrival_s=float(t), cls=c.name,
            priority=c.priority, prompt_len=int(plen),
            max_new=int(mnew), deadline_s=c.deadline_s))
    return Schedule(requests=tuple(reqs),
                    spec={"process": "staged", **(spec or {})})
