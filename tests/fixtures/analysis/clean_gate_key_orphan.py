"""Known-clean: every gate key, metric name, and span name consumed
here has a live producer in the same tree. Zero findings expected."""


class MetricSpec:
    def __init__(self, path, direction, gated=True, abs_slack=0.0):
        self.path, self.direction = path, direction
        self.gated, self.abs_slack = gated, abs_slack


SPECS = (
    MetricSpec("value", "higher"),
    MetricSpec("detail.engine_tok_s", "higher"),
    MetricSpec("detail.engine_bubble_frac", "lower", abs_slack=0.05),
)


def bench_detail(engine_result):
    """The bench child's detail dict — emits every gated key."""
    return {
        "value": engine_result["speedup"],
        "engine_tok_s": round(engine_result["tok_s"], 1),
        "engine_bubble_frac": round(engine_result["bubble_frac"], 4),
    }


def fit_engine(gauges, records):
    """An autofit-style consumer reading metric names by string."""
    tok_s = gauges.get("engine.tok_s")
    chunks = _windows(records, "engine.chunk")
    return tok_s, chunks


def _windows(records, name):
    return [r for r in records if r[0] == name]


def emit(metrics, rec, engine_result, t0, t1):
    metrics.gauge("engine.tok_s", engine_result["tok_s"])
    rec.mark_dispatch("engine.chunk", t0)
    rec.mark_complete("engine.chunk", t1)
