"""Library collectives: the XLA-native layer (MPI_Allreduce analog).

The reference offers both a hand-built ring AND the library collective so
their bandwidth can be compared (``AllreduceColl`` -> ``MPI_Allreduce`` on
device pointers, allreduce-mpi-sycl.cpp:61-67; comparison requirement in
SURVEY.md §2.3(b)). This module is the library side: thin, dtype-generic
wrappers over ``jax.lax`` collectives for use inside ``shard_map``, with
the reference's dtype-trait dispatch (mpi_datatype.hpp) riding on
:mod:`hpc_patterns_tpu.dtypes`.

On TPU these lower to XLA all-reduce / all-gather / reduce-scatter /
all-to-all over ICI (intra-slice) or DCN (multi-slice) on HBM-resident
shards — no host staging, the "GPU-aware" property (§2.3(a)).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

def _pprod(x, axis):
    """``prod`` reduction FALLBACK: XLA has no native pprod, so this is
    an all-gather followed by a local product — exact for ints, but a
    fundamentally different wire pattern from a ring reduction. That is
    why the fused route refuses it outright
    (:data:`hpc_patterns_tpu.comm.fused.FUSED_REDUCE_OPS` /
    ``_check_op``): a "fused prod" silently mapped onto the sum-shaped
    ring would return wrong data, not raise, and this fallback must
    stay the only prod route."""
    return lax.all_gather(x, axis).prod(axis=0)


# op name -> shard_map-level implementation; the reference hard-codes
# MPI_SUM (allreduce-mpi-sycl.cpp:66) but MPI's op table is part of the
# API shape being reproduced.
_REDUCE_OPS = {
    "sum": lax.psum,
    "max": lax.pmax,
    "min": lax.pmin,
    "mean": lax.pmean,
    "prod": _pprod,
}


def allreduce(x, axis: str, op: str = "sum"):
    """``MPI_Allreduce`` analog (allreduce-mpi-sycl.cpp:61-67): every rank
    gets the elementwise reduction across the mesh axis."""
    try:
        fn = _REDUCE_OPS[op]
    except KeyError:
        raise ValueError(f"unknown reduce op {op!r}; have {sorted(_REDUCE_OPS)}")
    return fn(x, axis)


def all_gather(x, axis: str, *, tiled: bool = True, gather_axis: int = 0):
    """``MPI_Allgather`` analog; tiled concatenates along ``gather_axis``."""
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_axis: int = 0, op: str = "sum"):
    """``MPI_Reduce_scatter`` analog via ``lax.psum_scatter``."""
    if op != "sum":
        raise ValueError("reduce_scatter supports op='sum' (XLA psum_scatter)")
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def all_to_all(x, axis: str, *, split_axis: int = 0, concat_axis: int = 0):
    """``MPI_Alltoall`` analog — the Ulysses sequence-parallel primitive."""
    return lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True)


def broadcast(x, axis: str, *, root: int = 0):
    """``MPI_Bcast`` analog: every rank gets root's shard."""
    return lax.all_gather(x, axis)[root]


def barrier_value(axis: str):
    """A cheap full-axis synchronization value (psum of 1); the closest
    XLA analog of ``MPI_Barrier`` — collectives are the only cross-shard
    ordering points in the XLA program order."""
    return lax.psum(jnp.ones((), jnp.int32), axis)
