"""Async host→device data pipeline: the IO side of the framework.

The reference has no data loader (pure benchmarks), but its concurrency
suite exists to prove copies overlap compute (sycl_con.cpp) — this
module applies that proven overlap to the training input pipeline: a
background thread stages the next batch(es) to device while the current
step runs, so the M2D transfer the concurrency app measures is hidden
behind the train step. JAX async dispatch does the rest (device_put
returns immediately; the train step's first use blocks on arrival).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator

import jax

_STOP = object()


class PrefetchLoader:
    """Wrap a host-batch iterable; yield device-resident batches with
    ``depth`` transfers in flight (double buffering at depth=2 — the
    concurrency suite's M2D/compute overlap, applied to input data).

    ``place`` maps a host batch to device (default: ``jax.device_put``
    with no target — jit inputs; pass e.g. a NamedSharding placer for
    mesh layouts).
    """

    def __init__(
        self,
        batches: Iterable,
        *,
        depth: int = 2,
        place: Callable | None = None,
    ):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self._batches = batches
        self._depth = depth
        self._place = place or jax.device_put

    def __iter__(self) -> Iterator:
        q: queue.Queue = queue.Queue(maxsize=self._depth)
        error: list[BaseException] = []
        stop = threading.Event()

        def put(item) -> bool:
            # bounded put that gives up when the consumer is gone, so an
            # early consumer exit can never wedge the worker on a full
            # queue (it would otherwise pin staged device buffers)
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for b in self._batches:
                    if stop.is_set():
                        return
                    # device_put here, on the worker thread: the transfer
                    # is in flight while the consumer computes
                    if not put(self._place(b)):
                        return
            except BaseException as e:  # noqa: BLE001 — re-raised on main
                error.append(e)
            finally:
                put(_STOP)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _STOP:
                    break
                yield item
            if error:
                raise error[0]
        finally:
            stop.set()
            while True:  # unblock a worker mid-put and drop staged refs
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5)


def synthetic_tokens(key, *, batch: int, seq: int, vocab: int, steps: int):
    """Host-side synthetic token batches (benchmark fuel for the
    trainer), one numpy array per step."""
    import numpy as np

    rng = np.random.default_rng(int(jax.random.randint(key, (), 0, 2**31 - 1)))
    for _ in range(steps):
        yield rng.integers(0, vocab, size=(batch, seq), dtype="int32")


TOKEN_FILE_DTYPES = ("uint16", "uint32", "int32")


def write_token_file(path, tokens, dtype: str = "uint16") -> None:
    """Write a flat token stream as a raw binary file (the standard
    pre-tokenized corpus format: one dtype, no header). uint16 covers
    vocabs to 65535 at half the footprint of int32."""
    import numpy as np

    if dtype not in TOKEN_FILE_DTYPES:
        raise ValueError(f"dtype {dtype!r} not in {TOKEN_FILE_DTYPES}")
    arr = np.asarray(tokens).reshape(-1)
    if arr.size == 0:
        raise ValueError("empty token stream (nothing to write)")
    info = np.iinfo(dtype)
    if arr.min() < info.min or arr.max() > info.max:
        raise ValueError(f"token values outside {dtype} range")
    arr.astype(dtype).tofile(path)


def memmap_tokens(path, *, batch: int, seq: int, dtype: str = "uint16",
                  steps: int | None = None, seed: int = 0,
                  sequential: bool = False, vocab: int | None = None):
    """Batches of (batch, seq) int32 windows from a raw binary token
    file, via ``np.memmap`` — the file is paged in on demand, never
    loaded whole (the host-RAM analog of the flash kernels'
    HBM-bounded streaming). Random windows by default (i.i.d. training
    batches); ``sequential`` walks the file in order (eval).
    ``steps=None`` iterates forever. ``vocab`` validates every yielded
    id against the model's range (an out-of-range id would otherwise be
    silently clamped by XLA's gather and train on garbage). Feed through
    :class:`PrefetchLoader` to hide the page-in + H2D copy behind the
    step."""
    import numpy as np

    if dtype not in TOKEN_FILE_DTYPES:
        raise ValueError(f"dtype {dtype!r} not in {TOKEN_FILE_DTYPES}")
    data = np.memmap(path, dtype=dtype, mode="r")
    n = data.shape[0]
    if n < seq:
        raise ValueError(f"token file has {n} tokens < seq = {seq}")
    n_starts = n - seq + 1  # start n-seq (the last full window) included
    rng = np.random.default_rng(seed)
    pos = 0
    i = 0
    while steps is None or i < steps:
        if sequential:
            # wrap at a whole-window stride, not n_starts: wrapping mid-
            # window would misalign every later window and double-count
            # tokens near the file start during long evals (the tail
            # remainder < seq tokens is dropped instead)
            wrap = (n // seq) * seq
            starts = (pos + np.arange(batch) * seq) % wrap
            pos = (pos + batch * seq) % wrap
        else:
            starts = rng.integers(0, n_starts, size=batch)
        out = np.stack([data[s:s + seq] for s in starts])
        if vocab is not None and out.max() >= vocab:
            raise ValueError(
                f"token id {int(out.max())} >= vocab {vocab} in {path} "
                "(wrong --vocab or wrong --data-dtype?)"
            )
        yield out.astype("int32")
        i += 1
