"""Known-bad: the PR 2 ``_dispatch_chunk`` donation-alias bug, minimized.

``pos_start`` is a zero-copy host view of ``self.pos``; ``_chunk_step``
DONATES ``self.pos``, so an executable honoring the donation (round 6:
cache-loaded CPU executables, and TPU always) reuses the buffer for the
post-chunk cursors — the "snapshot" mutates under the host's feet and
the collect bookkeeping built on it corrupts.

Lines carrying ``EXPECT: <rule>[, <rule>]`` markers are the golden
findings tests/test_analysis.py asserts, line-exact.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, donate_argnums=(1, 2))
def _chunk_step(params, cache, pos):
    return cache * params, pos + 1


class Engine:
    def __init__(self):
        self.params = jnp.ones((4,))
        self.cache = jnp.zeros((4,))
        self.pos = jnp.zeros((4,), jnp.int32)

    def _dispatch_chunk(self):
        pos_start = np.asarray(self.pos)  # EXPECT: donation-alias, host-sync-in-dispatch
        self.cache, self.pos = _chunk_step(
            self.params, self.cache, self.pos)
        return pos_start


def dunder_array_form(engine):
    snap = engine.pos.__array__()  # EXPECT: donation-alias
    engine.cache, engine.pos = _chunk_step(
        engine.params, engine.cache, engine.pos)
    return snap


def loop_carried(engine, xs):
    # the donation is TEXTUALLY before the view, but they share the
    # loop: iteration N's view is still live when iteration N+1's
    # donation clobbers the buffer — the serving-loop shape
    snaps = []
    for _ in xs:
        engine.cache, engine.pos = _chunk_step(
            engine.params, engine.cache, engine.pos)
        snap = np.asarray(engine.pos)  # EXPECT: donation-alias
        snaps.append(snap)
    return snaps
