"""Device-side KV migration: the serving plane's handoff as a paired
remote-DMA kernel on the fused tier.

The plane's other two transports stage the bundle through the host —
``migrate_pages`` is a cross-device ``device_put`` (XLA picks the
route), the launched plane ships base64 over TCP. This module moves
the handoff *into* a Pallas kernel: one SPMD ``pallas_call`` over a
2-device mesh ``[src, dst]`` in which the source rank
``make_async_remote_copy``-s the bundle's KV pages (and scale pools,
when the cache is quantized) chunk-by-chunk straight into the
destination rank's output buffer — the GPU-initiated-communication
direction (Intel SHMEM, arXiv 2409.20476; stream-aware MPI, arXiv
2306.15773) applied to the TPU's ICI. Byte-exactness is the plane's
existing migration oracle: prefill→migrate→decode equals the colocated
engine, greedy and sampled, at every pool dtype.

Slot discipline (the pallaslint ledger audits this file like the ring
kernels): every page chunk gets a DEDICATED send/recv semaphore pair
(no alternating-buffer hazard — each chunk reads a distinct input
slice and lands in a distinct output slice), all recvs are awaited
before the first send-wait, and every DMA's send semaphore is drained
before the kernel returns, so no transfer outlives its scratch.

Symmetry note: both ranks run the same program, so the destination
issues the mirror-image copy back into the source's buffer. That
back-copy is the source's own payload (the kernel is an exchange), is
byte-inert, and keeps the kernel a single SPMD program — the form the
dma-discharge interpreter and Mosaic's collective matcher both accept.

Entry points mirror the socket plane's (``serving_plane/service.py``):
:func:`send_migration` runs on the dispatch side and returns the
bundle re-homed to the destination device with ``transport="dma"``;
:func:`recv_migration` is the install-side acceptance check. Both are
dispatch-critical under jaxlint's host-sync rule — neither reads a
device value back.
"""

from __future__ import annotations

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hpc_patterns_tpu import topology
from hpc_patterns_tpu.ops.tiling import (
    collective_id as _registered_collective_id,
    default_interpret,
    tpu_compiler_params,
)

#: the transient 2-device mesh axis the send/recv pair binds
MIGRATION_AXIS = "_mig"

#: pages per DMA chunk: small enough that a chunk's landing overlaps
#: the next chunk's issue, large enough to amortize descriptor cost
PAGE_CHUNK = 4

#: compiled-path VMEM budget: input payload slab + the same-shape
#: output buffer live in VMEM simultaneously (2x the payload), which
#: :func:`dma_reachable`'s byte gate keeps under this cap — benchmark
#: pool shapes are ~MBs (pallaslint's estimator prices the same 2x)
_VMEM_LIMIT = 64 * 1024 * 1024


class MigrationDmaError(RuntimeError):
    """The DMA transport cannot serve this (src, dst, payload) — the
    router's loud-fallback ladder catches exactly this type and drops
    to ``device_put`` (then wire)."""


def dma_reachable(src_device, dst_device) -> tuple[bool, str]:
    """(ok, reason): can the paired kernel run between these two
    devices? Needs two DISTINCT committed devices on one platform —
    device-less (host-shared) replicas and cross-platform pairs fall
    back. A True verdict still leaves the per-bundle VMEM byte gate in
    :func:`send_migration`."""
    if src_device is None or dst_device is None:
        return False, "replica has no committed device (host-shared)"
    if src_device == dst_device:
        return False, "src and dst share one device (colocated)"
    if src_device.platform != dst_device.platform:
        return (False, f"cross-platform pair "
                f"({src_device.platform} -> {dst_device.platform})")
    return True, ""


# one compiled exchange per (devices, shape, dtype, chunking, mode):
# migrations repeat the same pool geometry every round, so the plane
# pays one trace per payload shape, not one per bundle
_XFER_CACHE: dict = {}


def _exchange_fn(src_device, dst_device, n_pages: int, row: int,
                 dtype, page_chunk: int, interpret: bool):
    key = (src_device.id, dst_device.id, n_pages, row, str(dtype),
           page_chunk, interpret)
    hit = _XFER_CACHE.get(key)
    if hit is not None:
        return hit
    chunks = -(-n_pages // page_chunk)
    mesh = Mesh(np.asarray([src_device, dst_device]), (MIGRATION_AXIS,))
    cid = _registered_collective_id("comm.fused.migration")

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = lax.axis_index(MIGRATION_AXIS)
        dst = lax.rem(me + 1, 2)
        dmas = []
        for c in range(chunks):
            lo = c * page_chunk
            span = min(page_chunk, n_pages - lo)
            d = pltpu.make_async_remote_copy(
                src_ref=x_ref.at[pl.ds(lo, span)],
                dst_ref=o_ref.at[pl.ds(lo, span)],
                send_sem=send_sem.at[c], recv_sem=recv_sem.at[c],
                device_id=dst,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            d.start()
            dmas.append(d)
        for d in dmas:
            d.wait_recv()
        for d in dmas:
            d.wait_send()

    def local(l):
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n_pages, row), dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA((chunks,)),
                            pltpu.SemaphoreType.DMA((chunks,))],
            compiler_params=tpu_compiler_params(
                has_side_effects=True, collective_id=cid,
                vmem_limit_bytes=_VMEM_LIMIT),
            interpret=interpret,
        )(l[0])
        return out[None]

    spec = P(MIGRATION_AXIS, None, None)
    fn = jax.jit(topology.shard_map(local, mesh=mesh, in_specs=spec,
                                    out_specs=spec))
    sharding = NamedSharding(mesh, spec)
    _XFER_CACHE[key] = (fn, sharding)
    return fn, sharding


def _transfer_array(arr, src_device, dst_device, *, page_chunk: int,
                    interpret: bool):
    """One payload array (leading dim = pages) DMA'd src -> dst;
    returns the destination-committed copy with the original shape."""
    shape = arr.shape
    n_pages = int(shape[0])
    row = int(np.prod(shape[1:], dtype=np.int64)) if len(shape) > 1 else 1
    if n_pages == 0 or row == 0:
        return jax.device_put(arr, dst_device)
    if 2 * arr.nbytes > _VMEM_LIMIT:
        raise MigrationDmaError(
            f"payload slab {arr.nbytes} B needs "
            f"{2 * arr.nbytes} B VMEM (> {_VMEM_LIMIT} B budget)")
    fn, sharding = _exchange_fn(src_device, dst_device, n_pages, row,
                                arr.dtype, page_chunk, interpret)
    x = jnp.reshape(arr, (n_pages, row))
    # both ranks hold a same-shape slab: the source's is the payload,
    # the destination's is the (overwritten) landing buffer
    x2 = jax.device_put(jnp.stack([x, jnp.zeros_like(x)]), sharding)
    out = fn(x2)
    shard = [s.data for s in out.addressable_shards
             if s.device == dst_device][0]
    return jnp.reshape(shard, shape)


def send_migration(bundle, src_device, dst_device, *,
                   page_chunk: int = PAGE_CHUNK,
                   interpret: bool | None = None):
    """DMA every payload array of ``bundle`` (K/V pools and, when the
    cache is quantized, their scale pools — whatever keys
    ``export_migration`` gathered) from ``src_device`` to
    ``dst_device`` through the paired kernel, and return the bundle
    re-homed there with ``transport="dma"``. Raises
    :class:`MigrationDmaError` when the pair is not DMA-reachable or a
    slab exceeds the VMEM budget — the router's fallback ladder."""
    ok, reason = dma_reachable(src_device, dst_device)
    if not ok:
        raise MigrationDmaError(f"not DMA-reachable: {reason}")
    if interpret is None:
        interpret = default_interpret()
    payload = {
        name: tuple(
            _transfer_array(a, src_device, dst_device,
                            page_chunk=page_chunk, interpret=interpret)
            for a in arrs)
        for name, arrs in bundle.pages_payload.items()
    }
    return replace(bundle, pages_payload=payload, transport="dma")


def recv_migration(bundle, device):
    """Install-side acceptance check (the socket plane's
    ``recv_migration`` analog): the bundle must have arrived over the
    DMA transport with every payload array already committed to the
    installing replica's device — device METADATA checks only, no
    readback (this runs inside the decode replica's dispatch path)."""
    if bundle.transport != "dma":
        raise MigrationDmaError(
            f"bundle seq {bundle.seq} arrived with "
            f"transport={bundle.transport!r}, expected 'dma'")
    if device is None:
        raise MigrationDmaError(
            "installing replica has no committed device")
    for name, arrs in bundle.pages_payload.items():
        for i, a in enumerate(arrs):
            devs = getattr(a, "devices", None)
            if devs is None or device not in a.devices():
                raise MigrationDmaError(
                    f"payload {name}[{i}] of bundle seq {bundle.seq} "
                    f"not resident on installing device {device}")
    return bundle
