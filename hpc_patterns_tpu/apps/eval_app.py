"""Eval app: perplexity of a checkpoint (or fresh params) over a corpus.

Completes the model lifecycle triad (train_app → eval_app → generate):
sequential windows from a memmap token file (or synthetic fuel), the
masked causal NLL shared with training (transformer.masked_causal_nll —
eval and train loss semantics cannot drift), jitted forward only, mean
NLL → perplexity. Self-validating: NLL must be finite, and an untrained
model's perplexity must be within a factor of the uniform bound (vocab)
— the analytic-oracle idea applied to evaluation.
"""

from __future__ import annotations

import math
import sys

import jax
import jax.numpy as jnp

from hpc_patterns_tpu import topology
from hpc_patterns_tpu.apps import common
from hpc_patterns_tpu.harness import RunLog, Verdict
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness.cli import base_parser
from hpc_patterns_tpu.models import TransformerConfig, init_params
from hpc_patterns_tpu.models.transformer import loss_fn


def build_parser():
    p = base_parser(__doc__.splitlines()[0])
    p.add_argument("--data", default=None, metavar="TOKENS.bin",
                   help="raw binary token file (sequential windows); "
                        "default: synthetic fuel")
    p.add_argument("--data-dtype", default="uint16",
                   choices=["uint16", "uint32", "int32"])
    p.add_argument("--checkpoint-dir", default=None,
                   help="restore params saved by train_app "
                        "--checkpoint-dir; default: fresh init")
    p.add_argument("--batches", type=int, default=8)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=256)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-kv-heads", type=int, default=0)
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("--attention", default="full")
    p.add_argument("--pos-embed", default="learned",
                   choices=["learned", "rope"])
    p.add_argument("--loss-chunk", type=int, default=0, metavar="C",
                   help="online-logsumexp NLL over vocab chunks of C "
                        "(must divide --vocab): the (B,T,V) f32 logits "
                        "never materialize — evaluate long sequences at "
                        "full vocabulary (0 = dense)")
    return p


def run(args) -> int:
    log = RunLog(args.log, truncate=not args.log_append)
    topology.init_distributed_from_env()
    try:
        cfg = TransformerConfig(
            vocab=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
            n_layers=args.n_layers, d_ff=4 * args.d_model, max_seq=args.seq,
            attention=args.attention, n_kv_heads=args.n_kv_heads,
            pos_embed=args.pos_embed, loss_chunk=args.loss_chunk,
        )
    except ValueError as e:
        log.print(f"ERROR: {e}")
        log.print("FAILURE")
        return 1
    params = init_params(jax.random.PRNGKey(0), cfg)
    if args.checkpoint_dir:
        from hpc_patterns_tpu.utils.checkpoint import restore_params

        try:
            restored, step = restore_params(args.checkpoint_dir)
        except (FileNotFoundError, ValueError, KeyError) as e:
            log.print(f"ERROR: cannot restore {args.checkpoint_dir}: {e}")
            log.print("FAILURE")
            return 1
        want = jax.tree.map(lambda x: (x.shape, str(x.dtype)), params)
        got = jax.tree.map(lambda x: (x.shape, str(x.dtype)), restored)
        if want != got:
            log.print("ERROR: checkpoint shapes/dtypes do not match the "
                      "CLI model config (wrong --d-model/--n-layers/"
                      "--vocab/--pos-embed?)")
            log.print("FAILURE")
            return 1
        params = restored
        log.print(f"restored step {step} from {args.checkpoint_dir}")

    if args.data:
        from hpc_patterns_tpu.utils.data import memmap_tokens

        source = memmap_tokens(args.data, batch=args.batch, seq=args.seq,
                               dtype=args.data_dtype, steps=args.batches,
                               sequential=True, vocab=cfg.vocab)
    else:
        from hpc_patterns_tpu.utils.data import synthetic_tokens

        source = synthetic_tokens(jax.random.PRNGKey(1), batch=args.batch,
                                  seq=args.seq, vocab=cfg.vocab,
                                  steps=args.batches)

    # loss_fn owns the dense-vs-chunked branch (cfg.loss_chunk), so eval
    # and train NLL semantics cannot drift; no experts here, so the MoE
    # aux term loss_fn would add is identically zero
    nll_fn = jax.jit(lambda p, t: loss_fn(p, t, cfg))
    m = metricslib.get_metrics()
    nlls = []
    for b in source:
        with m.span("eval.batch"):
            # float() blocks on the device, closing the span honestly
            nlls.append(float(nll_fn(params, jnp.asarray(b))))
    mean_nll = sum(nlls) / len(nlls)
    ppl = math.exp(mean_nll)
    m.gauge("eval.mean_nll").set(mean_nll)
    m.gauge("eval.perplexity").set(ppl)

    finite = all(math.isfinite(x) for x in nlls)
    if args.checkpoint_dir is None:
        # untrained params ~ uniform predictor: ppl near vocab, both
        # bounds checked (an impossibly low fresh-init ppl means a
        # masking/leakage bug, not a good model)
        sane = cfg.vocab / 20 <= ppl <= 20 * cfg.vocab
    else:
        # a real checkpoint must beat (or at worst match) uniform
        sane = 1.0 < ppl <= 20 * cfg.vocab
    ok = finite and sane
    log.emit(kind="result", name="eval", success=ok, batches=len(nlls),
             mean_nll=mean_nll, perplexity=ppl, vocab=cfg.vocab)
    log.print(f"eval {len(nlls)} batches: nll {mean_nll:.4f}, "
              f"perplexity {ppl:.1f} (vocab {cfg.vocab})")
    verdict = Verdict(success=ok, messages=("SUCCESS" if ok else "FAILURE",))
    log.print(verdict.summary_line())
    return verdict.exit_code


def main(argv=None) -> int:
    return common.run_instrumented(run, build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
