"""Known-clean: the blessed jit lifetimes — module level, decorator,
build-once factory, memoized wrapper, hashable static args."""

from functools import partial

import jax

_double = jax.jit(lambda v: v * 2)

_CACHE: dict = {}


@partial(jax.jit, static_argnames=("sizes",))
def bucketed(x, *, sizes):
    return x


def uses_module_jit(x):
    return _double(x)


def factory(scale):
    # built once per factory call, returned for reuse — the
    # make_train_step shape
    step = jax.jit(lambda v: v * scale)
    return step


def memoized(key):
    fn = _CACHE.get(key)
    if fn is None:
        fn = jax.jit(lambda v: v + key)
        _CACHE[key] = fn
    return fn


def hashable_static(x):
    return bucketed(x, sizes=(16, 32))
