"""Tests for topology (C8 parity: devices.hpp rank->device policies,
fission fallback, mesh construction)."""

import jax
import pytest

from hpc_patterns_tpu import topology


def test_get_devices_platform_filter():
    ds = topology.get_devices("cpu")
    assert len(ds) == 8
    with pytest.raises(topology.TopologyError):
        topology.get_devices("nonexistent-platform")


def test_fission_never_fails():
    # reference semantics: finest partition, whole-device fallback
    # (devices.hpp:28-38)
    assert len(topology.fission()) == 8
    assert topology.fission([]) == []


def test_assign_device_modulo_when_oversubscribed():
    # ranks > devices -> rank % n (devices.hpp:47)
    ds = topology.get_devices()
    n = len(ds)
    for rank in range(2 * n):
        assert topology.assign_device(rank, 2 * n, ds) == ds[rank % n]


def test_assign_device_block_when_undersubscribed():
    # devices >= ranks -> contiguous blocks (devices.hpp:49-53)
    ds = topology.get_devices()  # 8
    assert topology.assign_device(0, 2, ds) == ds[0]
    assert topology.assign_device(1, 2, ds) == ds[4]
    assert topology.devices_for_rank(1, 2, ds) == list(ds[4:8])
    assert topology.devices_for_rank(0, 4, ds) == list(ds[0:2])


def test_assign_device_bad_args():
    ds = topology.get_devices()
    with pytest.raises(ValueError):
        topology.assign_device(3, 2, ds)
    with pytest.raises(topology.TopologyError):
        topology.assign_device(0, 1, [])


def test_make_mesh_explicit_and_auto():
    m = topology.make_mesh({"dp": 2, "tp": 4})
    assert m.shape == {"dp": 2, "tp": 4}
    # -1 auto sentinel (sycl_con.cpp CLI convention)
    m = topology.make_mesh({"dp": -1, "tp": 2})
    assert m.shape == {"dp": 4, "tp": 2}
    m = topology.make_mesh({"a": -1, "b": -1, "c": 2})
    assert m.shape == {"a": 4, "b": 1, "c": 2}


def test_make_mesh_rejects_nondividing():
    with pytest.raises(topology.TopologyError):
        topology.make_mesh({"dp": 3})
    with pytest.raises(topology.TopologyError):
        topology.make_mesh({"dp": 2})  # uses 2 of 8 with no auto axis


def test_single_device_mesh_and_info():
    m = topology.single_device_mesh(("dp", "tp"))
    assert m.shape == {"dp": 1, "tp": 1}
    info = topology.TopologyInfo.detect()
    assert info.n_devices == 8
    assert info.platform == "cpu"
    assert info.n_hosts == 1


def test_group_by_host():
    groups = topology.group_by_host()
    assert sum(len(v) for v in groups.values()) == 8
    assert set(groups) == {jax.devices()[0].process_index}
