"""Blockwise (flash) causal attention as a Pallas TPU kernel.

Standard flash-attention dataflow, TPU-shaped:

- grid = (batch·heads, Tq/BLOCK_Q): one program per query block per head;
  Pallas auto-pipelines each program's HBM→VMEM block loads against the
  previous program's compute (the same DMA/compute overlap the
  concurrency suite measures, here for free from the grid).
- K/V for the whole (small) sequence sit in VMEM per program; the kernel
  walks K/V blocks with ``lax.fori_loop``, maintaining the online
  softmax state (m, l, acc) in f32 — numerically identical to the
  two-pass softmax (same accumulator as parallel/ring_attention, which
  runs this dataflow *across chips*).
- block matmuls hit the MXU via ``jnp.dot(..., preferred_element_type=
  f32)``; bf16 inputs stay bf16 into the MXU.
- causal masking is in GLOBAL positions: the kernel takes (q_offset,
  k_offset) scalars in SMEM, so the same kernel serves the single-device
  case (offsets 0) and one ring-attention step (q at rank·T, the
  visiting K/V block at src·S). Masked entries get a finite -1e30
  (inf-free, like ring_attention); whole K/V blocks outside the causal
  triangle are skipped via the (dynamic) loop bounds — a fully-future
  block costs zero iterations.
- backward (Dao 2023 §B): Δ = rowsum(dO ⊙ O), then two blockwise passes
  — dQ over K blocks, dK/dV over Q blocks — recomputing P from the
  forward's saved per-row logsumexp. O(block) VMEM in both directions.

Two public entry points:

- :func:`flash_attention` — full softmax attention, square (Tq == Tk),
  offsets 0. Drop-in equal to parallel.ring_attention.full_attention.
- :func:`flash_attention_block` — one *partial* attention over a K/V
  block at a global offset, returning (out, lse) so partial results
  merge by logsumexp (parallel/ring_attention's flash path does this
  per ring step). Differentiable in q, k, v AND through lse: the lse
  cotangent folds into Δ (d lse/d s = P, so ds = P∘(dP − Δ + ḡ_lse)).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _causal_mask(s, q_start, k_start):
    """Mask score block ``s`` so position (i, j) survives iff the global
    key index k_start+j is at or before the global query index q_start+i.
    Shared by the forward and both backward kernels — the mask must be
    identical or the recomputed P diverges from the forward's. Offsets
    may be traced (dynamic) values."""
    q_pos = q_start + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = k_start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(k_pos <= q_pos, s, _NEG_INF)


def _kv_block_bound(q_end_g, k_off, block_k, n_kv):
    """Number of leading K/V blocks a query block must visit under the
    causal mask: those starting at or before the query block's global
    end. 0 when the whole K/V side is in the future."""
    return jnp.clip((q_end_g - k_off) // block_k + 1, 0, n_kv)


def _q_block_start(k_start_g, q_off, block_q, n_q):
    """First query block (index) that can see a K block starting at
    global position ``k_start_g`` under the causal mask; n_q when none."""
    return jnp.clip((k_start_g - q_off) // block_q, 0, n_q)


def _kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, *lse_ref, block_k: int,
            scale: float, causal: bool):
    # offs_ref: (1, 2) int32 SMEM [q_offset, k_offset] global positions;
    # q_ref: (BLOCK_Q, D); k_ref/v_ref: (Tk, D); o_ref: (BLOCK_Q, D);
    # optional lse_ref: (BLOCK_Q, 1) per-row logsumexp for the backward
    block_q, d = q_ref.shape
    tk = k_ref.shape[0]
    n_kv = tk // block_k
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale
    q_start_g = offs_ref[0, 0] + qi * block_q
    k_off = offs_ref[0, 1]

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(ki, state):
        m, l, acc = state
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_start_g, k_off + ki * block_k)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        rescale = jnp.exp(m - m_new)
        l_new = l * rescale + p.sum(axis=-1, keepdims=True)
        acc_new = acc * rescale + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    n_iter = (_kv_block_bound(q_start_g + block_q - 1, k_off, block_k, n_kv)
              if causal else n_kv)
    m, l, acc = lax.fori_loop(0, n_iter, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    out = acc / l
    if causal:
        # rows with nothing visible (m never rose): out 0, lse -> -1e30,
        # matching _dense_forward — not an average of whatever was visited
        out = jnp.where(m <= _NEG_INF * 0.5, 0.0, out)
    o_ref[:] = out.astype(o_ref.dtype)
    if lse_ref:
        lse_ref[0][:] = m + jnp.log(l)


def _dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, *, block_k: int, scale: float, causal: bool):
    # One program per query block: walk K/V blocks, accumulate dQ.
    # dS = P * (dO·Vᵀ − Δ); dQ = scale · dS·K, with P recomputed from the
    # saved per-row logsumexp (no (T,T) matrix ever materialized).
    block_q, d = q_ref.shape
    tk = k_ref.shape[0]
    n_kv = tk // block_k
    qi = pl.program_id(1)
    q_start_g = offs_ref[0, 0] + qi * block_q
    k_off = offs_ref[0, 1]

    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:]      # (BLOCK_Q, 1)
    delta = delta_ref[:]  # (BLOCK_Q, 1)

    def body(ki, dq):
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_start_g, k_off + ki * block_k)
        p = jnp.exp(s - lse)
        if causal:
            # dead rows have lse=-1e30, where exp(s - lse) = 1 on masked
            # entries; match _dense_backward's explicit zero
            p = jnp.where(s > _NEG_INF * 0.5, p, 0.0)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    n_iter = (_kv_block_bound(q_start_g + block_q - 1, k_off, block_k, n_kv)
              if causal else n_kv)
    dq = lax.fori_loop(0, n_iter, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(offs_ref, q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                dk_ref, dv_ref, *, block_q: int, scale: float, causal: bool):
    # One program per K/V block: walk query blocks, accumulate dK and dV.
    # dV = Pᵀ·dO; dK = scale · dSᵀ·Q. Causal: query blocks strictly before
    # this K block see none of it — start the walk at the diagonal.
    block_k, d = k_ref.shape
    tq = q_ref.shape[0]
    n_q = tq // block_q
    ki = pl.program_id(1)
    q_off = offs_ref[0, 0]
    k_start_g = offs_ref[0, 1] + ki * block_k

    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    def body(qi, state):
        dk, dv = state
        q_blk = q_ref[pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(qi * block_q, block_q), :]
        delta = delta_ref[pl.ds(qi * block_q, block_q), :]
        s = jnp.dot(q_blk, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_off + qi * block_q, k_start_g)
        p = jnp.exp(s - lse)
        if causal:
            p = jnp.where(s > _NEG_INF * 0.5, p, 0.0)
        dv_new = dv + jnp.dot(p.T, do_blk, preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_new = dk + jnp.dot(ds.T, q_blk, preferred_element_type=jnp.float32)
        return dk_new, dv_new

    start = _q_block_start(k_start_g, q_off, block_q, n_q) if causal else 0
    dk, dv = lax.fori_loop(
        start, n_q, body,
        (jnp.zeros((block_k, d), jnp.float32),
         jnp.zeros((block_k, d), jnp.float32)),
    )
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _resolve(Tq, Tk, D, scale, block_q, block_k, interpret, *,
             validate=True):
    """Resolve the shared per-call parameters (scale default, block
    clamping, interpret default). ``validate=False`` for the backward,
    whose shapes the forward already validated — the resolution logic
    must stay common so fwd and bwd never disagree on block sizes."""
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    if validate and (Tq % block_q or Tk % block_k):
        raise ValueError(
            f"seq ({Tq}, {Tk}) must divide by blocks ({block_q}, {block_k})"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return float(scale), block_q, block_k, interpret


def _to_kernel_layout(x):
    B, T, H, D = x.shape
    return jnp.einsum("bthd->bhtd", x).reshape(B * H, T, D)


_SMEM_OFFS = pl.BlockSpec((1, 2), lambda bh, i: (0, 0),
                          memory_space=pltpu.SMEM)


def _align_vma(*arrays):
    """Bring every array to the union of their varying-mesh-axes sets
    (``lax.pvary``), so the kernels work inside ``shard_map``
    (check_vma=True) even when some inputs — e.g. the constant zero
    offsets — are replicated. Returns (arrays, union_vma)."""
    vma = frozenset().union(*(jax.typeof(x).vma for x in arrays))
    out = tuple(
        lax.pcast(x, tuple(vma - jax.typeof(x).vma), to='varying') if vma - jax.typeof(x).vma
        else x
        for x in arrays
    )
    return out, vma


def _masked_scores(qr, kr, offs, scale, causal):
    """(N, Tq, Tk) scaled scores with the global causal mask — the dense
    mirror of the kernels' per-block ``_causal_mask`` walk."""
    s = jnp.einsum(
        "ntd,nsd->nts", qr.astype(jnp.float32), kr.astype(jnp.float32)
    ) * scale
    if causal:
        q_pos = offs[0, 0] + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        k_pos = offs[0, 1] + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
    return s


def _dense_forward(qr, kr, vr, offs, *, causal, scale, need_lse, out_dtype):
    """jnp mirror of ``_kernel`` (same outputs, clamps, and dead-row
    semantics), used where Pallas interpret mode can't run — inside
    ``shard_map`` on CPU (its vma tracking rejects kernel-internal
    constants). Real-TPU execution always takes the kernel path."""
    s = _masked_scores(qr, kr, offs, scale, causal)
    m = s.max(-1, keepdims=True)
    p = jnp.exp(s - m) * (s > _NEG_INF / 2)  # fully-masked rows stay 0
    l = jnp.maximum(p.sum(-1, keepdims=True), 1e-30)
    outr = (
        jnp.einsum("nts,nsd->ntd", p, vr.astype(jnp.float32)) / l
    ).astype(out_dtype)
    lse = (m + jnp.log(l)) if need_lse else None
    return outr, lse


def _dense_backward(qr, kr, vr, dor, lse, delta, offs, *, causal, scale):
    """jnp mirror of ``_dq_kernel``/``_dkv_kernel`` (same P recompute from
    lse and the same Δ shift); see ``_dense_forward`` for when."""
    s = _masked_scores(qr, kr, offs, scale, causal)
    p = jnp.exp(s - lse) * (s > _NEG_INF / 2)
    dp = jnp.einsum(
        "ntd,nsd->nts", dor.astype(jnp.float32), vr.astype(jnp.float32)
    )
    ds = p * (dp - delta)
    dq = jnp.einsum("nts,nsd->ntd", ds, kr.astype(jnp.float32)) * scale
    dk = jnp.einsum("nts,ntd->nsd", ds, qr.astype(jnp.float32)) * scale
    dv = jnp.einsum("nts,ntd->nsd", p, dor.astype(jnp.float32))
    return dq.astype(qr.dtype), dk.astype(kr.dtype), dv.astype(vr.dtype)


def _forward_impl(q, k, v, offs, *, causal, scale, block_q, block_k,
                  interpret, need_lse):
    """Shared forward. ``offs``: (1, 2) int32 [q_offset, k_offset].
    Returns (out, residuals) — residuals in kernel layout (B·H, T, D),
    lse (B·H, Tq, 1) f32; both None-lse when ``need_lse`` is False (the
    inference path skips the lse work entirely)."""
    if q.ndim != 4:
        raise ValueError(f"want (batch, seq, heads, head_dim), got {q.shape}")
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale, block_q, block_k, interpret = _resolve(
        Tq, Tk, D, scale, block_q, block_k, interpret
    )

    qr, kr, vr = map(_to_kernel_layout, (q, k, v))

    kernel = functools.partial(
        _kernel, block_k=block_k, scale=scale, causal=causal,
    )
    blk_q = pl.BlockSpec((None, block_q, D), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM)
    full_k = pl.BlockSpec((None, Tk, D), lambda bh, qi: (bh, 0, 0),
                          memory_space=pltpu.VMEM)
    (offs, qr, kr, vr), vma = _align_vma(offs, qr, kr, vr)
    if interpret and vma:
        outr, lse = _dense_forward(qr, kr, vr, offs, causal=causal,
                                   scale=scale, need_lse=need_lse,
                                   out_dtype=q.dtype)
        out = outr.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
        return out, (qr, kr, vr, outr, lse)
    out_specs = [blk_q]
    out_shape = [jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype, vma=vma)]
    if need_lse:
        out_specs.append(
            pl.BlockSpec((None, block_q, 1), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM)
        )
        out_shape.append(
            jax.ShapeDtypeStruct((B * H, Tq, 1), jnp.float32, vma=vma)
        )

    results = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // block_q),
        in_specs=[_SMEM_OFFS, blk_q, full_k, full_k],
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        interpret=interpret,
    )(offs, qr, kr, vr)
    outr = results[0]
    out = outr.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)  # -> (B, Tq, H, D)
    lse = results[1] if need_lse else None
    return out, (qr, kr, vr, outr, lse)


def _backward_impl(qr, kr, vr, outr, lse, offs, g, g_lse, *, causal, scale,
                   block_q, block_k, interpret):
    """Shared backward. ``g``: (B, Tq, H, D) out-cotangent; ``g_lse``:
    (B, Tq, H) lse-cotangent or None. Returns (dq, dk, dv) user-layout."""
    B, Tq, H, D = g.shape
    Tk = kr.shape[1]
    scale, block_q, block_k, interpret = _resolve(
        Tq, Tk, D, scale, block_q, block_k, interpret, validate=False
    )

    dor = _to_kernel_layout(g)
    delta = jnp.sum(
        dor.astype(jnp.float32) * outr.astype(jnp.float32),
        axis=-1, keepdims=True,
    )  # (B·H, Tq, 1) — trailing unit dim keeps TPU block shapes legal
    if g_lse is not None:
        # d lse/d s = P, so the lse cotangent enters ds = P∘(dP − Δ + ḡ)
        # — i.e. it just shifts Δ.
        delta = delta - jnp.einsum("bth->bht", g_lse).reshape(B * H, Tq, 1)

    (offs, qr, kr, vr, dor, lse, delta), vma = _align_vma(
        offs, qr, kr, vr, dor, lse, delta
    )
    if interpret and vma:
        dq, dk, dv = _dense_backward(qr, kr, vr, dor, lse, delta, offs,
                                     causal=causal, scale=scale)
        back = lambda x, t: x.reshape(B, H, t, D).transpose(0, 2, 1, 3)
        return back(dq, Tq), back(dk, Tk), back(dv, Tk)
    row = functools.partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    blk_q = row((None, block_q, D), lambda bh, i: (bh, i, 0))
    blk_k = row((None, block_k, D), lambda bh, i: (bh, i, 0))
    full_q = row((None, Tq, D), lambda bh, i: (bh, 0, 0))
    full_k = row((None, Tk, D), lambda bh, i: (bh, 0, 0))
    vec_q = row((None, block_q, 1), lambda bh, i: (bh, i, 0))
    vec_full = row((None, Tq, 1), lambda bh, i: (bh, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, scale=scale,
                          causal=causal),
        grid=(B * H, Tq // block_q),
        in_specs=[_SMEM_OFFS, blk_q, full_k, full_k, blk_q, vec_q, vec_q],
        out_specs=blk_q,
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), qr.dtype, vma=vma),
        interpret=interpret,
    )(offs, qr, kr, vr, dor, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, scale=scale,
                          causal=causal),
        grid=(B * H, Tk // block_k),
        in_specs=[_SMEM_OFFS, full_q, full_q, vec_full, vec_full,
                  blk_k, blk_k],
        out_specs=(blk_k, blk_k),
        out_shape=(
            jax.ShapeDtypeStruct((B * H, Tk, D), kr.dtype, vma=vma),
            jax.ShapeDtypeStruct((B * H, Tk, D), vr.dtype, vma=vma),
        ),
        interpret=interpret,
    )(offs, qr, dor, lse, delta, kr, vr)

    back = lambda x, t: x.reshape(B, H, t, D).transpose(0, 2, 1, 3)
    return back(dq, Tq), back(dk, Tk), back(dv, Tk)


def _zero_offs():
    return jnp.zeros((1, 2), jnp.int32)


# ---------------------------------------------------------------- square


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash_with_vjp(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _ = _forward_impl(q, k, v, _zero_offs(), causal=causal, scale=scale,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret, need_lse=False)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, residuals = _forward_impl(q, k, v, _zero_offs(), causal=causal,
                                   scale=scale, block_q=block_q,
                                   block_k=block_k, interpret=interpret,
                                   need_lse=True)
    return out, residuals


def _flash_bwd(causal, scale, block_q, block_k, interpret, residuals, g):
    qr, kr, vr, outr, lse = residuals
    return _backward_impl(qr, kr, vr, outr, lse, _zero_offs(), g, None,
                          causal=causal, scale=scale, block_q=block_q,
                          block_k=block_k, interpret=interpret)


_flash_with_vjp.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
):
    """Softmax attention over (batch, seq, heads, head_dim) inputs.

    Numerically equal to parallel.ring_attention.full_attention (the
    oracle in tests); O(block) VMEM instead of the (T, T) score matrix.
    Sequence length must divide by the block sizes (pad upstream — the
    model keeps T a multiple of 128). Differentiable: custom VJP whose
    backward is two blockwise Pallas kernels (dQ pass, dK/dV pass)
    recomputing P from the forward's saved logsumexp — O(block) VMEM in
    both directions.
    """
    return _flash_with_vjp(q, k, v, causal, scale, block_q, block_k, interpret)


# ----------------------------------------------------------------- block


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8)
)
def _flash_block_with_vjp(q, k, v, offs_i, causal, scale, block_q, block_k,
                          interpret):
    offs = offs_i.reshape(1, 2)
    out, (_, _, _, _, lse) = _forward_impl(
        q, k, v, offs, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret, need_lse=True,
    )
    B, Tq, H, _ = q.shape
    lse_user = jnp.einsum("bht->bth", lse.reshape(B, H, Tq))
    return out, lse_user


def _flash_block_fwd(q, k, v, offs_i, causal, scale, block_q, block_k,
                     interpret):
    offs = offs_i.reshape(1, 2)
    out, residuals = _forward_impl(
        q, k, v, offs, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret, need_lse=True,
    )
    B, Tq, H, _ = q.shape
    lse = residuals[4]
    lse_user = jnp.einsum("bht->bth", lse.reshape(B, H, Tq))
    return (out, lse_user), (*residuals, offs)


def _flash_block_bwd(causal, scale, block_q, block_k, interpret,
                     residuals, g):
    qr, kr, vr, outr, lse, offs = residuals
    g_out, g_lse = g
    dq, dk, dv = _backward_impl(
        qr, kr, vr, outr, lse, offs, g_out, g_lse, causal=causal,
        scale=scale, block_q=block_q, block_k=block_k, interpret=interpret,
    )
    # offsets are integer positions: their cotangent is the symbolic
    # float0 zero (also exempt from shard_map's varying-axes check)
    return dq, dk, dv, np.zeros((2,), jax.dtypes.float0)


_flash_block_with_vjp.defvjp(_flash_block_fwd, _flash_block_bwd)


def flash_attention_block(
    q,
    k,
    v,
    q_offset,
    k_offset,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
):
    """One *partial* attention: local queries ``q`` (global position
    ``q_offset``) against one visiting K/V block (global position
    ``k_offset``); Tq and Tk may differ. Returns ``(out, lse)`` —
    the softmax attention restricted to this block, normalized within
    it, plus the per-row logsumexp (B, Tq, H) f32 — so partials over
    disjoint K/V blocks merge exactly:

        m = max(lse_a, lse_b); e_x = exp(lse_x - m)
        out = (e_a·out_a + e_b·out_b) / (e_a + e_b);  lse = m + log(e_a+e_b)

    This is the per-step compute of ring attention (the reference's
    ring exchange-accumulate, allreduce-mpi-sycl.cpp:173-182, with
    attention as the combine). Offsets may be traced (e.g. derived from
    ``axis_index`` inside shard_map). A fully-future block (causal,
    k_offset > all query positions) runs zero kernel iterations and
    returns out=0, lse≈-1e30, which the merge weights to zero.
    Differentiable in q, k, v, including gradient flow through lse.
    """
    offs_i = jnp.stack([
        jnp.asarray(q_offset, jnp.int32), jnp.asarray(k_offset, jnp.int32)
    ])
    return _flash_block_with_vjp(q, k, v, offs_i, causal, scale, block_q,
                                 block_k, interpret)
