"""Single-query (decode-step) flash attention streaming the KV cache.

The serving-side analog of ops/flash_attention.py (the framework rule:
hot loops are Pallas — docs/ARCHITECTURE.md; reference analog: the
own-the-hot-loop principle of concurency/sycl_con.cpp:26-33). A decode
step is cache-read-bound — the framework's own measurement proved GQA's
full n_heads/kv_heads bandwidth saving shows up end-to-end
(benchmarks/RESULTS.md "KV-cache decoding") — so the kernel's job is to
make exactly one streamed pass over the *live* prefix of the cache:

- grid = (batch·kv_heads, S_max/BLOCK_S): each step loads one
  (BLOCK_S, head_dim) cache block into VMEM while the previous block
  computes (Pallas double-buffers the stream); the online-softmax state
  (m, l, acc) for the g = n_heads/kv_heads grouped queries carries in
  f32 scratch across the S axis.
- the current fill position arrives via scalar prefetch, and the cache
  index map CLAMPS blocks past it to the last live block — consecutive
  clamped steps revisit that block, Pallas elides the fetch, and
  ``pl.when`` skips the compute. Per-step HBM traffic is proportional
  to the POSITION, not the allocated cache length (the XLA gather path
  always reads all of max_len and masks).
- GQA is native: the q block is the (g, head_dim) group sharing this
  kv head; the cache is streamed kv_heads-narrow. MHA is g = 1.

The cache must be kernel-layout: (batch·kv_heads, S_max, head_dim) with
S contiguous — models/decode.py stores it that way from prefill on
(a per-step transpose would itself read the whole cache and defeat the
point).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _softmax_block(q_ref, k_ref, v_ref, ks_ref, vs_ref, m_ref, l_ref,
                   acc_ref, block_start, pos, scale: float,
                   quantized: bool):
    """One online-softmax update over the cache block at logical rows
    [block_start, block_start + block_s): THE streamed-attention math,
    shared by the linear kernel (one block per grid step) and the paged
    kernel (``pages_per_step`` page blocks per grid step).

    f32 score/value math (unlike the training kernel's native-dtype
    matmuls): a decode step is cache-READ-bound — the f32 compute is
    free next to the bf16 stream, and it reproduces the gather path's
    f32 einsum numerics so greedy tokens match. ``quantized``: per-row
    dequant folded into the LANE axis of the score and probability
    blocks — s_ij = (q·k8_j)·kscale_j and out = (p∘vscaleᵀ)·v8; the
    (1, block_s) scale rows ride lane-major, and the (block_s, D)
    tiles are never rescaled elementwise (a sublane-oriented
    (block_s, 1) scale multiply measured ~3x slower than bf16)."""
    q = q_ref[:].astype(jnp.float32)
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST) * scale
    if quantized:
        s = s * ks_ref[:].astype(jnp.float32)
    k_pos = block_start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(k_pos <= pos, s, _NEG_INF)
    m = m_ref[:]
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    rescale = jnp.exp(m - m_new)
    m_ref[:] = m_new
    l_ref[:] = l_ref[:] * rescale + p.sum(axis=-1, keepdims=True)
    if quantized:
        p = p * vs_ref[:].astype(jnp.float32)
    acc_ref[:] = acc_ref[:] * rescale + jnp.dot(
        p, v, preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST,
    )


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, *rest, scale: float,
                   quantized: bool, hkv_per_row: int = 0):
    # grid (B·Hkv, n_s): one kv-cache block per step, grouped-query
    # online softmax carried in scratch over the S axis. ``quantized``:
    # the cache blocks are int8 with per-row scales (two extra refs) —
    # dequantized in VMEM, so HBM streams HALF the bytes of bf16 (the
    # whole cost of a decode step on a read-bound path). ``hkv_per_row``
    # > 0: RAGGED positions — pos_ref holds one fill position per
    # sequence and grid row r belongs to sequence r // hkv_per_row.
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_ref, l_ref, acc_ref = rest
    g, d = q_ref.shape
    block_s = k_ref.shape[0]
    si = pl.program_id(1)
    n_s = pl.num_programs(1)
    pos = (pos_ref[pl.program_id(0) // hkv_per_row] if hkv_per_row
           else pos_ref[0])

    @pl.when(si == 0)
    def _():
        m_ref[:] = jnp.full((g, 1), _NEG_INF, jnp.float32)
        l_ref[:] = jnp.zeros((g, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((g, d), jnp.float32)

    # a block fully past the fill position contributes nothing: its
    # fetch was elided by the clamped index map, its compute is skipped
    @pl.when(si * block_s <= pos)
    def _():
        _softmax_block(q_ref, k_ref, v_ref, ks_ref, vs_ref, m_ref,
                       l_ref, acc_ref, si * block_s, pos, scale,
                       quantized)

    @pl.when(si == n_s - 1)
    def _():
        o_ref[:] = acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)


def flash_decode_attention(
    q,
    k_cache,
    v_cache,
    pos,
    *,
    k_scale=None,
    v_scale=None,
    scale: float | None = None,
    block_s: int = 2048,
    interpret: bool | None = None,
):
    """Attention of one new token per sequence against the KV cache.

    ``q``: (B, n_heads, head_dim) — the current token's queries;
    ``k_cache``/``v_cache``: (B, kv_heads, S_max, head_dim), the live
    prefix being rows [0, pos]; ``pos``: traced int32 scalar, the
    position being decoded (== number of already-cached tokens; the
    row at ``pos`` must already hold this token's K/V). Returns
    (B, n_heads, head_dim) f32. Numerically the gather-path softmax
    (models/decode.py) evaluated blockwise in f32.

    ``k_scale``/``v_scale``: (B, kv_heads, S_max) per-row dequant
    scales for an int8 cache (kv_cache_dtype="int8"): the kernel
    streams the int8 blocks — half the HBM bytes — and dequantizes in
    VMEM.
    """
    B, H, D = q.shape
    Hkv, S = k_cache.shape[1], k_cache.shape[2]
    if H % Hkv or v_cache.shape[1] != Hkv:
        raise ValueError(
            f"kv heads {Hkv}/{v_cache.shape[1]} must match and divide "
            f"n_heads {H}"
        )
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    block_s = min(block_s, S)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    g = H // Hkv

    quantized = k_scale is not None
    qr = q.reshape(B * Hkv, g, D)          # q head k·g+j -> row b·Hkv+k
    kr = k_cache.reshape(B * Hkv, S, D)
    vr = v_cache.reshape(B * Hkv, S, D)
    pos_arr = jnp.asarray(pos, jnp.int32).reshape(1)

    # ceil-div grid: a ragged last block reads the padded tile and the
    # k_pos <= pos mask (pos < S always) zeroes whatever it holds
    n_s = -(-S // block_s)

    def kv_idx(r, si, pos_ref):
        # clamp past-the-fill blocks to the last live one: consecutive
        # clamped steps revisit it and Pallas skips the fetch
        return r, jnp.minimum(si, pos_ref[0] // block_s), 0

    row = functools.partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    in_specs = [
        row((None, g, D), lambda r, si, pos: (r, 0, 0)),
        row((None, block_s, D), kv_idx),
        row((None, block_s, D), kv_idx),
    ]
    operands = [pos_arr, qr, kr, vr]
    if quantized:
        # scales enter as LANE-major (1, block_s) rows (see kernel note)
        scale_idx = lambda r, si, pos: (
            kv_idx(r, si, pos)[0], 0, kv_idx(r, si, pos)[1]
        )
        in_specs += [row((None, 1, block_s), scale_idx),
                     row((None, 1, block_s), scale_idx)]
        operands += [k_scale.reshape(B * Hkv, 1, S),
                     v_scale.reshape(B * Hkv, 1, S)]
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=float(scale),
                          quantized=quantized),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B * Hkv, n_s),
            in_specs=in_specs,
            out_specs=row((None, g, D), lambda r, si, pos: (r, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((g, 1), jnp.float32),   # running max
                pltpu.VMEM((g, 1), jnp.float32),   # running sumexp
                pltpu.VMEM((g, D), jnp.float32),   # output accumulator
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * Hkv, g, D), jnp.float32),
        interpret=interpret,
    )(*operands)
    return out.reshape(B, H, D)


def _decode_kernel_paged(pos_ref, table_ref, q_ref, *rest, scale: float,
                         page_size: int, unroll: int,
                         quantized: bool = False, hkv_per_row: int = 0):
    # grid (B·Hkv, ceil(pages/unroll)): ``unroll`` page blocks arrive
    # per grid step as separate refs (k_0..k_{U-1}, v_0..v_{U-1}
    # [, ks_.., vs_..]) and the online softmax walks them in order —
    # the round-4 page-hopping residue was one grid step (and one
    # shallow DMA) per page; batching U pages per step restores the
    # linear kernel's block depth (U·page ≈ its 2048-row block) while
    # keeping page-granular allocation. The table ref is consumed by
    # the index maps only.
    del table_ref
    U = unroll
    k_refs, rest = rest[:U], rest[U:]
    v_refs, rest = rest[:U], rest[U:]
    if quantized:
        ks_refs, rest = rest[:U], rest[U:]
        vs_refs, rest = rest[:U], rest[U:]
    else:
        ks_refs = vs_refs = (None,) * U
    o_ref, m_ref, l_ref, acc_ref = rest
    g, d = q_ref.shape
    si = pl.program_id(1)
    n_s = pl.num_programs(1)
    pos = (pos_ref[pl.program_id(0) // hkv_per_row] if hkv_per_row
           else pos_ref[0])

    @pl.when(si == 0)
    def _():
        m_ref[:] = jnp.full((g, 1), _NEG_INF, jnp.float32)
        l_ref[:] = jnp.zeros((g, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((g, d), jnp.float32)

    for j in range(U):
        start = (si * U + j) * page_size

        @pl.when(start <= pos)
        def _(j=j, start=start):
            _softmax_block(q_ref, k_refs[j], v_refs[j], ks_refs[j],
                           vs_refs[j], m_ref, l_ref, acc_ref, start,
                           pos, scale, quantized)

    @pl.when(si == n_s - 1)
    def _():
        o_ref[:] = acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)


def flash_decode_paged(
    q,
    k_pool,
    v_pool,
    table,
    pos,
    *,
    k_scale_pool=None,
    v_scale_pool=None,
    scale: float | None = None,
    pages_per_step: int | None = None,
    interpret: bool | None = None,
):
    """Single-query attention against a PAGED KV cache.

    The block-table serving layout (vLLM-style, TPU-shaped): K/V live
    in a shared pool of fixed-size pages and each sequence owns an
    ordered page list — allocation follows ACTUAL generation length,
    not the declared maximum (the linear cache's
    allocate-for-the-longest waste is the round-3 capacity ceiling).
    The kernel is the linear ``flash_decode_attention`` body unchanged;
    only the index map differs — the page id for grid step ``si`` is
    read from the scalar-prefetched table, so the indirection costs
    nothing per block and pages can live ANYWHERE in the pool.

    ``q``: (B, n_heads, head_dim); ``k_pool``/``v_pool``:
    (pool_pages, kv_heads, page_size, head_dim) in the compute dtype;
    ``table``: (B, pages_per_seq) int32 page ids (entries past the live
    prefix may be any valid id — the clamped index map never fetches
    them); ``pos``: traced int32 — a scalar (batch-uniform position)
    or a (B,) vector of PER-SEQUENCE positions (ragged serving: every
    sequence at its own length; each grid row masks and clamps by its
    own sequence's fill position, so per-row HBM traffic follows
    per-row length). Returns (B, n_heads, head_dim) f32, numerically
    identical to the linear kernel on the equivalent cache.

    ``k_scale_pool``/``v_scale_pool``: (pool_pages, kv_heads, 1,
    page_size) f32 per-row dequant scales for int8 pools — the linear
    kernel's half-the-HBM-bytes lever composed with the block table
    (the CAPACITY levers stack: int8 halves page bytes, paging frees
    the allocate-for-longest waste).

    ``pages_per_step``: page blocks fetched per grid step (separate
    refs walked by one online-softmax pass). Default: enough pages to
    match the linear kernel's 2048-row streaming block — the round-4
    measurement showed the paged kernel's 1.7x/token residue was the
    per-page grid/DMA granularity, not the table indirection. Tradeoff:
    a row whose live prefix is SHORTER than one step's U pages pays up
    to U-1 one-time fetches of its clamped last page (each ref is a
    distinct operand; cross-step elision still applies, cross-ref
    doesn't) — negligible next to the long-row streaming this buys,
    and ``pages_per_step=1`` restores the exact old behavior.
    """
    B, H, D = q.shape
    n_pool, Hkv, P, Dp = k_pool.shape
    pages = table.shape[1]
    if H % Hkv or v_pool.shape != k_pool.shape or Dp != D:
        raise ValueError(
            f"shape mismatch: q {q.shape}, pools {k_pool.shape}/"
            f"{v_pool.shape}"
        )
    if table.shape[0] != B:
        raise ValueError(f"table rows {table.shape[0]} != batch {B}")
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    g = H // Hkv

    quantized = k_scale_pool is not None
    qr = q.reshape(B * Hkv, g, D)
    ragged = jnp.ndim(pos) == 1
    if ragged and jnp.shape(pos)[0] != B:
        raise ValueError(
            f"ragged pos has {jnp.shape(pos)[0]} entries for batch {B}"
        )
    pos_arr = jnp.asarray(pos, jnp.int32).reshape(B if ragged else 1)
    table_flat = table.reshape(-1).astype(jnp.int32)

    if pages_per_step is None:
        # match the linear kernel's streaming block (block_s = 2048)
        pages_per_step = max(1, 2048 // P)
    U = max(1, min(int(pages_per_step), pages))
    n_steps = -(-pages // U)

    def page_idx(j):
        # clamp to the last live page (same fetch-elision as the linear
        # kernel), then indirect through this sequence's page list
        def f(r, si, pos_ref, table_ref):
            b = r // Hkv
            live = jnp.minimum(si * U + j,
                               pos_ref[b if ragged else 0] // P)
            return table_ref[b * pages + live], r % Hkv, 0, 0

        return f

    row = functools.partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    in_specs = [row((None, g, D), lambda r, si, pos, tab: (r, 0, 0))]
    in_specs += [row((None, None, P, D), page_idx(j)) for j in range(U)]
    in_specs += [row((None, None, P, D), page_idx(j)) for j in range(U)]
    operands = [pos_arr, table_flat, qr]
    operands += [k_pool] * U + [v_pool] * U
    if quantized:
        # scales ride lane-major (1, page) rows, page-indirected like
        # the value blocks (see the linear kernel's layout note)
        in_specs += [row((None, None, 1, P), page_idx(j))
                     for j in range(U)]
        in_specs += [row((None, None, 1, P), page_idx(j))
                     for j in range(U)]
        operands += [k_scale_pool] * U + [v_scale_pool] * U
    out = pl.pallas_call(
        functools.partial(_decode_kernel_paged, scale=float(scale),
                          page_size=P, unroll=U, quantized=quantized,
                          hkv_per_row=Hkv if ragged else 0),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * Hkv, n_steps),
            in_specs=in_specs,
            out_specs=row((None, g, D), lambda r, si, pos, tab: (r, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * Hkv, g, D), jnp.float32),
        interpret=interpret,
    )(*operands)
    return out.reshape(B, H, D)
