"""Sanitizer + multi-host init tests."""

import pytest

from hpc_patterns_tpu import topology
from hpc_patterns_tpu.comm.ring import _ring_perm, check_permutation


class TestPermutationSanitizer:
    def test_valid_rings_pass(self):
        for size in (1, 2, 8):
            for shift in (1, -1, 3):
                check_permutation(_ring_perm(size, shift), size)

    def test_duplicate_destination_rejected(self):
        with pytest.raises(ValueError, match="duplicate destinations"):
            check_permutation([(0, 1), (1, 1)], 4)

    def test_duplicate_source_rejected(self):
        with pytest.raises(ValueError, match="duplicate sources"):
            check_permutation([(0, 1), (0, 2)], 4)

    def test_out_of_range_rejected(self):
        with pytest.raises(ValueError, match="out of range"):
            check_permutation([(0, 7)], 4, allow_partial=True)

    def test_partial_permutation_rejected(self):
        # the silent-drop case: ranks with no incoming pair get zeros
        with pytest.raises(ValueError, match="partial permutation"):
            check_permutation([(0, 1), (1, 2), (2, 3)], 4)

    def test_partial_allowed_when_opted_in(self):
        check_permutation([(0, 1), (1, 2), (2, 3)], 4, allow_partial=True)


class TestInitDistributed:
    def test_single_process_is_noop(self):
        # CPU test env is single-process; init must not raise and must
        # report that no multi-host initialization happened
        assert topology.init_distributed() is False
        assert topology.is_multihost() is False
