"""Probe: do AOT ``compiler_options`` reach the remote TPU compiler?

Round 3 established that ``XLA_FLAGS`` is environment-bound through the
axon tunnel: the client process's CPU XLA aborts on TPU flag names, and
the remote compile service pins its own flags (RESULTS.md round 3).
This probe tests the other route the verdict prescribed:
``jit(step).lower(args).compile(compiler_options={...})`` ships options
inside the compile *request*, bypassing the client env entirely.

Protocol (per leg, headline train step — d=1024 L=8 ff=4096 GQA kv=2,
flash, remat split, B=8 T=2048):
  1. ``sentinel`` leg: a nonexistent option name. If compile raises, the
     option string is being parsed by whoever compiles; if it is
     silently accepted, options are dropped and timings below prove
     nothing.
  2. ``base`` leg: AOT compile with no options (same-session baseline).
  3. flag legs: each candidate option, timed adjacent to base.

All timings use the amortized differencing protocol (two compiles per
leg: n-iter scan and n/2-iter scan).

Usage: python benchmarks/probe_aot_flags.py [--iters=16]
"""

import sys
from functools import partial

import jax
import optax
from jax import lax

from hpc_patterns_tpu.harness.timing import measure_forced
from hpc_patterns_tpu.models import TransformerConfig
from hpc_patterns_tpu.models.train import (
    init_train_state,
    make_batch,
    make_optimizer,
)
from hpc_patterns_tpu.models.transformer import loss_fn


def arg(name, default, cast):
    for a in sys.argv[1:]:
        if a.startswith(f"--{name}="):
            return cast(a.split("=", 1)[1])
    return default


# candidate options: the in-situ diagnosis is matmul fusions at ~50%
# MXU (fusion-context overhead), so the levers are vmem headroom for
# bigger fusion tiles and the fusion/scheduling cost models. Unknown
# names are harmless — the remote compiler rejects them and the leg is
# reported as FAILED. First sweep (2026-07-31, this file's first run):
# sentinel REJECTED remotely => options reach the compiler;
# vmem 65536: 0.982x; vmem 98304: 1.063x; scheduler_rerun=2: 1.000x.
CANDIDATES = [
    {"xla_tpu_scoped_vmem_limit_kib": "49152"},
    {"xla_tpu_scoped_vmem_limit_kib": "57344"},
    {"xla_tpu_scoped_vmem_limit_kib": "65536"},
    {"xla_tpu_scoped_vmem_limit_kib": "73728"},
    {"xla_tpu_enable_experimental_fusion_cost_model": "true"},
    {"xla_tpu_licm_size_inflation_ratio": "10"},
    {"xla_tpu_rwb_fusion": "false"},
    {"xla_tpu_enable_dot_strength_reduction": "false"},
    {"xla_tpu_scoped_vmem_limit_kib": "65536",
     "xla_tpu_enable_experimental_fusion_cost_model": "true"},
]

# --confirm=1: the second-pass list — sweep survivors re-measured with
# the base re-timed before EVERY leg (chip drift over a long sweep is
# comparable to the effects being measured)
CONFIRM = [
    {"xla_tpu_rwb_fusion": "false"},
    {"xla_tpu_enable_dot_strength_reduction": "false"},
    {"xla_tpu_rwb_fusion": "false",
     "xla_tpu_enable_dot_strength_reduction": "false"},
]


def main():
    on_tpu = jax.default_backend() == "tpu"
    cfg = TransformerConfig(
        vocab=32768 if on_tpu else 256,
        d_model=1024 if on_tpu else 64,
        n_heads=8 if on_tpu else 4,
        n_layers=8 if on_tpu else 2,
        d_ff=4096 if on_tpu else 128,
        max_seq=2048 if on_tpu else 64,
        dtype="bfloat16",
        attention="flash" if on_tpu else "full",
        remat=True,
        remat_policy="split",
        n_kv_heads=2 if on_tpu else 0,
    )
    batch = 8 if on_tpu else 2
    iters = arg("iters", 16 if on_tpu else 4, int)
    optimizer = make_optimizer()
    params, opt_state = init_train_state(
        jax.random.PRNGKey(0), cfg, optimizer=optimizer
    )
    tokens = make_batch(jax.random.PRNGKey(1), cfg, batch, cfg.max_seq)

    def run_t(carry, tokens, n):
        def one_step(carry, _):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(partial(loss_fn, cfg=cfg))(
                params, tokens
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        _, losses = lax.scan(one_step, carry, None, length=n)
        return losses[-1]

    def compile_leg(options):
        jitted = jax.jit(run_t, static_argnums=(2,))
        out = []
        for n in (iters, iters // 2):
            lowered = jitted.lower((params, opt_state), tokens, n)
            out.append(lowered.compile(compiler_options=options))
        return out  # [compiled_many, compiled_base]

    def time_leg(compiled_pair):
        t_many = measure_forced(
            lambda: compiled_pair[0]((params, opt_state), tokens),
            repetitions=3,
        ).min_s
        t_base = measure_forced(
            lambda: compiled_pair[1]((params, opt_state), tokens),
            repetitions=3,
        ).min_s
        return max(t_many - t_base, 0.0) / (iters - iters // 2)

    confirm = bool(arg("confirm", 0, int))
    candidates = CONFIRM if confirm else CANDIDATES
    # confirm mode re-times the base before EVERY leg: chip drift over a
    # long sweep is comparable to the effects being measured
    retime_every = 1 if confirm else 3

    # --- leg 1: sentinel (first pass only) ---
    sentinel_parsed = None
    if not confirm:
        try:
            jax.jit(run_t, static_argnums=(2,)).lower(
                (params, opt_state), tokens, 2
            ).compile(
                compiler_options={"xla_probe_nonexistent_option_xyz": "1"}
            )
            print("sentinel: ACCEPTED silently -> options are likely "
                  "DROPPED before any compiler parses them")
            sentinel_parsed = False
        except Exception as e:
            print(f"sentinel: REJECTED ({type(e).__name__}: "
                  f"{str(e)[:200]}) -> options are parsed; flag legs are "
                  "meaningful")
            sentinel_parsed = True

    # --- leg 2: base (kept compiled; re-timed periodically so chip
    # drift within the session is visible, per the adjacency protocol) ---
    base_pair = compile_leg(None)
    base = time_leg(base_pair)
    print(f"base (AOT, no options): {base * 1e3:.2f} ms/step", flush=True)

    # --- flag legs ---
    for idx, options in enumerate(candidates):
        if idx and idx % retime_every == 0:
            base = time_leg(base_pair)
            print(f"base (re-timed): {base * 1e3:.2f} ms/step", flush=True)
        name = ", ".join(f"{k}={v}" for k, v in options.items())
        try:
            pair = compile_leg(options)
        except Exception as e:
            print(f"{name}: compile FAILED "
                  f"({type(e).__name__}: {str(e)[:200]})", flush=True)
            continue
        t = time_leg(pair)
        print(f"{name}: {t * 1e3:.2f} ms/step ({t / base:.3f}x of base)",
              flush=True)

    print(f"sentinel_parsed={sentinel_parsed}")


if __name__ == "__main__":
    main()
