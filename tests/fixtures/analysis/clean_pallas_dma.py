"""Known-clean: the same ring shapes with the discipline
``comm/fused.py`` actually ships — tail-only drain after the
slot-reuse wait chain, dedicated per-phase recv buffers, send waits
before slot rewrites, registry collective ids, and the explicit
``.astype(o_ref.dtype)`` on widened stores."""

import jax
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hpc_patterns_tpu.ops.tiling import collective_id


def _remote(src, dst, send, recv, dev):
    return pltpu.make_async_remote_copy(
        src_ref=src, dst_ref=dst, send_sem=send, recv_sem=recv,
        device_id=dev, device_id_type=pltpu.DeviceIdType.LOGICAL)


def ring_with_tail_drain(x, axis, size, cn):
    """The fixed drain: the in-loop slot-reuse waits consumed
    dmas[0..size-3]'s sends; only the LAST send is still outstanding
    at exit, and only it is waited."""

    def kernel(x_ref, o_ref, rs_recv, sendbuf, send_sem, recv_sem):
        me = lax.axis_index(axis)
        dst = lax.rem(me + 1, size)
        sendbuf[0] = x_ref[:, pl.ds(0, cn)]
        dmas = []
        d = _remote(sendbuf.at[0], rs_recv.at[0], send_sem.at[0],
                    recv_sem.at[0], dst)
        d.start()
        dmas.append(d)
        for s in range(1, size):
            dmas[s - 1].wait_recv()
            slot = s % 2
            if s >= 2:
                dmas[s - 2].wait_send()
            sendbuf[slot] = x_ref[:, pl.ds(s * cn, cn)] + rs_recv[s - 1]
            if s < size - 1:
                d = _remote(sendbuf.at[slot], rs_recv.at[s],
                            send_sem.at[slot], recv_sem.at[s], dst)
                d.start()
                dmas.append(d)
        o_ref[...] = sendbuf[(size - 1) % 2]
        dmas[-1].wait_send()

    return pl.pallas_call(kernel, out_shape=x)(x)


def dedicated_phase_buffers(x, axis, size):
    """Each phase lands its DMAs in its OWN recv scratch under its own
    semaphore family — the race-free split comm/fused.py documents."""

    def kernel(x_ref, o_ref, rs_recv, ag_recv, sendbuf, rs_send,
               rs_sem, ag_send, ag_sem):
        me = lax.axis_index(axis)
        dst = lax.rem(me + 1, size)
        d = _remote(sendbuf.at[0], rs_recv.at[0], rs_send.at[0],
                    rs_sem.at[0], dst)
        d.start()
        d.wait()
        g = _remote(sendbuf.at[0], ag_recv.at[0], ag_send.at[0],
                    ag_sem.at[0], dst)
        g.start()
        g.wait()

    return pl.pallas_call(kernel, out_shape=x)(x)


def send_wait_before_rewrite(x, axis, size):
    """The alternating send slot is rewritten only after the DMA that
    read it two steps ago has drained."""

    def kernel(x_ref, o_ref, recvb, sendbuf, send_sem, recv_sem):
        me = lax.axis_index(axis)
        dst = lax.rem(me + 1, size)
        dmas = []
        for s in range(size - 1):
            slot = s % 2
            if s >= 2:
                dmas[s - 2].wait_send()
            sendbuf[slot] = x_ref[...] * s
            d = _remote(sendbuf.at[slot], recvb.at[s],
                        send_sem.at[slot], recv_sem.at[s], dst)
            d.start()
            dmas.append(d)
        for s in range(size - 1):
            dmas[s].wait_recv()
        for d in dmas[max(0, len(dmas) - 2):]:
            d.wait_send()

    return pl.pallas_call(kernel, out_shape=x)(x)


def _double_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def registry_collective_ids(x, w):
    """Concurrent kernels with REGISTERED ids: distinct by
    construction, greppable by name."""
    a = pl.pallas_call(
        _double_kernel,
        out_shape=x,
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=collective_id("fixture.clean.a")),
    )(x)
    b = pl.pallas_call(
        _double_kernel,
        out_shape=w,
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=collective_id("fixture.clean.b")),
    )(w)
    return a, b


def _cast_store_kernel(x_ref, w_ref, o_ref):
    # the widened matmul lands through an explicit narrowing cast —
    # the contract interpret and Mosaic both honor
    o_ref[...] = jax.numpy.dot(
        x_ref[...], w_ref[...],
        preferred_element_type=jax.numpy.float32,
    ).astype(o_ref.dtype)


def cast_store(x, w):
    return pl.pallas_call(_cast_store_kernel, out_shape=x)(x, w)
