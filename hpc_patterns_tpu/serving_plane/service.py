"""Cross-process serving plane: socket replicas + router client.

The in-process plane (``serving_plane/router.py``) is the oracle
tier; this module is the LAUNCHED tier — one replica per OS process
under ``apps/launch.py`` (the mpirun analog), a router process
driving them over localhost TCP (newline-delimited JSON). The socket
hop is the DCN analog of the in-process ``device_put`` path: KV
bundles cross it bit-identically (``migration.bundle_to_wire``), and
both sides fingerprint every handoff into their collective-schedule
chains, so the cross-rank trace merge proves the router and replicas
agreed on the migration schedule (verdict ``consistent``) and threads
KV-handoff flow arrows between the replica lanes.

Import-light ON PURPOSE (stdlib + numpy-free): launcher children in
the tier-1 replica-chaos tests run STUB engines — a deterministic
jax-free token generator behind the same protocol — so the router's
failure handling (death detection, resume-on-survivor, shed
accounting) is exercised in milliseconds. Real engines enter through
:class:`EngineAdapter` subclasses that import jax lazily.

Protocol (one JSON object per line, request/response):

- ``hello``   -> replica identity + geometry + load
- ``submit``  -> enqueue a request (``resume_prefix`` for re-queued
  work from a dead replica)
- ``round``   -> run ONE service round (the chaos ``replica_round``
  site fires here); reply carries finished rows, per-row progress
  (the router's resume checkpoint), exported KV bundles, and load
- ``migrate`` -> queue a KV bundle for install behind the next round's
  decode chunk
- ``stop``    -> drain the connection; the server loop returns

Replica death: a ``die`` chaos fault (or any crash) severs the socket
mid-call; the router marks the replica dead and RE-QUEUES its
in-flight requests as resumes on survivors — prompt = original +
tokens observed so far, ``resume_prefix`` carrying them — or counts
them SHED in the SLO table when no survivor can take them. Nothing is
dropped silently (the round-10 acceptance bar).
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import time
from collections import deque
from pathlib import Path

from hpc_patterns_tpu.analysis import runtime as analysis_runtime
from hpc_patterns_tpu.harness import chaos as chaoslib
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import budget as budgetlib
from hpc_patterns_tpu.harness import reqtrace as reqtracelib
from hpc_patterns_tpu.harness import slo as slolib
from hpc_patterns_tpu.harness import trace as tracelib


class ReplicaDead(Exception):
    """The socket to a replica broke mid-protocol."""


#: device-subtrack layout for ``plane.kv_migration`` windows, shared
#: by EVERY party to a handoff (the in-process plane in router.py;
#: the socket plane's donor and receiver here): the cross-rank merge
#: matches windows by (name, seq), and concurrent migrations must not
#: share a subtrack (Chrome sync slices on one track must nest). The
#: band itself lives in ``harness/trace.py``'s TRACK_BANDS registry
#: (clear of the decode chunk's track 0 and the per-slot admission
#: subtracks); this import-light module unpacks it — trace.py is
#: stdlib-only, so the jax-free stub tier still never pays for the
#: jax-side migration codec.
MIG_TRACK_BASE, MIG_TRACKS = tracelib.track_band("migration")


def migration_track(seq: int) -> int:
    """The device subtrack a migration's windows land on — ONE
    formula for donor, receiver, and the in-process plane, or the
    merged timeline's flow arrows silently stop threading."""
    return MIG_TRACK_BASE + int(seq) % MIG_TRACKS


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def send_msg(sock: socket.socket, obj: dict) -> None:
    try:
        sock.sendall((json.dumps(obj) + "\n").encode())
    except OSError as e:
        raise ReplicaDead(str(e)) from e


def recv_msg(rfile) -> dict | None:
    try:
        line = rfile.readline()
    except OSError as e:
        raise ReplicaDead(str(e)) from e
    if not line:
        return None
    return json.loads(line)


def send_migration(sock, rfile, wire_bundle: dict) -> dict:
    """The router's half of one KV handoff: ship the bundle and wait
    for the ack. Fingerprinting happens on the REPLICA sides (donor at
    export, receiver at install) — the router is the carrier, not a
    party to the schedule."""
    send_msg(sock, {"op": "migrate", "bundle": wire_bundle})
    reply = recv_msg(rfile)
    if reply is None:
        raise ReplicaDead("EOF during migrate")
    return reply


def _record_handoff(wire: dict, rec) -> float:
    """Fingerprint one side of a handoff into the schedule chain and
    open its device-track window; returns the window stamp (0.0
    without a recorder). Both sides derive identical fingerprints from
    the bundle itself — the donor at export, the receiver at arrival —
    which is what makes the merge-time verdict meaningful: with one
    prefill and one decode replica the two chains must be EQUAL, so a
    bundle lost, duplicated, or reordered in the router reads as a
    schedule divergence naming the first bad (op, seq)."""
    analysis_runtime.record_collective(
        "kv_migration", int(wire["seq"]),
        shape=(int(wire["n_pages"]), int(wire["page_size"])),
        dtype=wire.get("payload_dtype") or "uint8",
        axis="plane", algorithm="socket")
    if rec is None:
        return 0.0
    return rec.mark_dispatch(
        "plane.kv_migration",
        {"seq": int(wire["seq"]), "pages": int(wire["n_pages"]),
         "seq_id": int(wire["seq_id"])},
        track=migration_track(wire["seq"]))


def record_export(wire: dict, rec) -> None:
    """Donor-side handoff record: fingerprint + a closed device-track
    window at the export instant. The donor assigns ``seq`` (its
    export counter); the router carries it verbatim, so the receiver
    fingerprints the identical value."""
    t_disp = _record_handoff(wire, rec)
    if rec is not None and t_disp:
        rec.mark_complete(
            "plane.kv_migration", t_disp,
            {"seq": int(wire["seq"]), "side": "export"},
            track=migration_track(wire["seq"]))


def recv_migration(wire: dict, adapter: "EngineAdapter", rec) -> None:
    """The receiver's half: fingerprint + window open on arrival, then
    queue the bundle so the install runs BEHIND the next round's
    decode chunk (the overlap discipline; the window closes when the
    install completes inside the round)."""
    t_disp = _record_handoff(wire, rec)
    adapter.queue_install(wire, t_disp)


# ---------------------------------------------------------------------------
# engine adapters
# ---------------------------------------------------------------------------


class EngineAdapter:
    """What the replica server needs from an engine; implemented by
    :class:`StubAdapter` (jax-free, deterministic) and
    :class:`RealAdapter` (an EngineCore)."""

    role = "both"

    def describe(self) -> dict:
        raise NotImplementedError

    def submit(self, req: dict) -> None:
        raise NotImplementedError

    def queue_install(self, wire: dict, t_disp: float) -> None:
        raise NotImplementedError

    def round(self, rec) -> dict:
        """One service round; returns the ``round`` reply body."""
        raise NotImplementedError


def stub_token(orig_prompt, k: int) -> int:
    """Token ``k`` of the stub generator: a pure function of the
    ORIGINAL prompt, so a resume (prompt = original + emitted) and a
    migrated continuation reproduce the uninterrupted stream exactly —
    the stub plane keeps the same byte-exactness contract the real
    engines get from causality."""
    key = (",".join(str(int(t)) for t in orig_prompt)).encode()
    h = hashlib.sha256(key + int(k).to_bytes(4, "little")).digest()
    return int.from_bytes(h[:4], "little") % 251


# -- the stub's SAMPLED mode (the key-stream checkpoint drill) -------------
#
# Real sampled engines carry per-row PRNG key STATE that evolves with
# every emitted token; a resume is only byte-exact when it seeds from
# the state where the stream stopped (models/serving._preempt's
# contract). The stub mirrors that shape jax-free with a hash CHAIN:
# key_0 = H(prompt), key_{k+1} = H(key_k), token_k = f(key_k) — so a
# death-resume that does NOT carry the checkpointed key restarts the
# chain at key_0 and diverges at the first resumed position, which is
# exactly the teeth the tier-1 launch test needs. The router treats
# the key as OPAQUE (hex here, a uint32 pair for real engines): it
# checkpoints whatever the round reply reports and hands it back
# verbatim on resume.


def stub_key0(orig_prompt) -> bytes:
    key = (",".join(str(int(t)) for t in orig_prompt)).encode()
    return hashlib.sha256(key + b"|k0").digest()[:8]


def stub_next_key(key: bytes) -> bytes:
    return hashlib.sha256(key).digest()[:8]


def stub_token_keyed(key: bytes) -> int:
    return int.from_bytes(hashlib.sha256(key).digest()[4:8],
                          "little") % 251


def stub_sampled_stream(orig_prompt, n: int) -> list[int]:
    """The full n-token sampled stub stream — the oracle's spelling
    (walk the chain from key_0; a correct resume lands on the same
    tokens because it continued the chain from the carried state)."""
    key, out = stub_key0(orig_prompt), []
    for _ in range(n):
        out.append(stub_token_keyed(key))
        key = stub_next_key(key)
    return out


class StubAdapter(EngineAdapter):
    """A deterministic jax-free engine behind the replica protocol:
    page-pool accounting, slot admission, ``chunk`` tokens per round
    per active row, prefill-role export, migration install. Exists so
    the launched plane's ROUTER mechanics (placement, death recovery,
    shed accounting, handoff fingerprints) are tier-1-testable in
    milliseconds."""

    def __init__(self, *, slots: int = 2, pool_pages: int = 16,
                 pages_per_seq: int = 8, page_size: int = 16,
                 chunk: int = 4, role: str = "both",
                 sampled: bool = False):
        self.slots = slots
        self.pool_pages = pool_pages
        self.pages_per_seq = pages_per_seq
        self.page_size = page_size
        self.chunk = chunk
        self.role = role
        #: sampled mode: tokens come from an evolving per-row key
        #: CHAIN (stub_key0/stub_next_key) instead of the position-
        #: indexed pure function — the jax-free mirror of a real
        #: engine's PRNG key state, so the router's key checkpoint is
        #: exercised with teeth (a resume that drops the key restarts
        #: the chain and fails the oracle)
        self.sampled = bool(sampled)
        self.free_pages = pool_pages
        self._queue: deque = deque()
        self._rows: list[dict] = []
        self._installs: deque = deque()
        self._round = 0
        self._mig_seq = 0
        self.finished: dict[int, list[int]] = {}
        self.outcomes: dict[int, str] = {}

    def _pages_for(self, prompt_len: int, budget: int) -> int:
        return -(-(prompt_len + budget) // self.page_size)

    def describe(self) -> dict:
        return {"role": self.role, "slots": self.slots,
                "pages_per_seq": self.pages_per_seq,
                "page_size": self.page_size, "stub": True,
                "free_pages": self.free_pages,
                "queue_depth": len(self._queue)}

    def submit(self, req: dict) -> None:
        prompt = [int(t) for t in req["prompt"]]
        prefix = [int(t) for t in req.get("resume_prefix") or []]
        need = self._pages_for(len(prompt), int(req["max_new"]))
        if need > min(self.pages_per_seq, self.pool_pages):
            raise ValueError(
                f"request needs {need} pages > capacity")
        self._queue.append({
            "rid": int(req["rid"]), "prompt": prompt,
            "orig": prompt[:len(prompt) - len(prefix)]
            if prefix else prompt,
            "prefix": prefix, "out": list(prefix),
            "budget": int(req["max_new"]), "need": need,
            "priority": int(req.get("priority") or 0),
            # sampled resumes seed the chain from the ROUTER's
            # checkpointed key; a fresh row starts its own at admit
            "key": (bytes.fromhex(req["key"])
                    if self.sampled and req.get("key") else None),
        })

    def queue_install(self, wire: dict, t_disp: float) -> None:
        self._installs.append((wire, t_disp))

    def _emit_token(self, row: dict) -> int:
        """One emitted token. Greedy mode: the position-indexed pure
        function of the original prompt. Sampled mode: consume the
        row's key CHAIN — a fresh row opens it at ``stub_key0``, a
        resume continues from the carried checkpoint state (and a
        resume that LOST the key restarts at key_0, diverging at its
        first token — the oracle's teeth)."""
        if not self.sampled:
            return stub_token(row["orig"], len(row["out"]))
        if row.get("key") is None:
            row["key"] = stub_key0(row["orig"])
        tok = stub_token_keyed(row["key"])
        row["key"] = stub_next_key(row["key"])
        return tok

    def _admit(self) -> None:
        q = sorted(self._queue, key=lambda r: r["priority"])
        for req in q:
            if len(self._rows) >= self.slots:
                break
            if req["need"] > self.free_pages:
                continue
            self._queue.remove(req)
            self.free_pages -= req["need"]
            # admission emits the first token (the prefill pick);
            # token k is indexed from the ORIGINAL prompt's end, so a
            # resume (out pre-seeded with its prefix) continues the
            # exact stream
            req["out"].append(self._emit_token(req))
            self._rows.append(req)

    def _install_pending(self, rec) -> None:
        while self._installs:
            wire, t_disp = self._installs[0]
            need = int(wire["n_pages"])
            if len(self._rows) >= self.slots or need > self.free_pages:
                break
            self._installs.popleft()
            self.free_pages -= need
            self._rows.append({
                "rid": int(wire["seq_id"]),
                "prompt": [int(t) for t in wire["prompt"]],
                "orig": [int(t) for t in wire["orig"]],
                "prefix": list(wire["prefix"]),
                "out": list(wire["out"]),
                "budget": int(wire["budget"]), "need": need,
                "priority": int(wire.get("priority") or 0),
                # the migrated key state continues the donor's chain
                "key": (bytes.fromhex(wire["key"])
                        if self.sampled and wire.get("key") else None),
            })
            if rec is not None and t_disp:
                rec.mark_complete(
                    "plane.kv_migration", t_disp,
                    {"seq": int(wire["seq"])},
                    track=migration_track(wire["seq"]))

    def round(self, rec) -> dict:
        chaoslib.maybe_inject("replica_round", self._round)
        self._round += 1
        self._admit()
        self._install_pending(rec)
        exports = []
        if self.role == "prefill":
            # every admitted row leaves via migration once its first
            # token exists (it does: admission emitted it)
            for row in list(self._rows):
                if len(row["out"]) - len(row["prefix"]) \
                        >= row["budget"]:
                    continue  # finishes below instead
                self._rows.remove(row)
                self.free_pages += row["need"]
                wire = {
                    "seq_id": row["rid"], "prompt": row["prompt"],
                    "orig": row["orig"], "prefix": row["prefix"],
                    "out": row["out"], "budget": row["budget"],
                    "n_pages": row["need"],
                    "page_size": self.page_size,
                    "payload_dtype": "uint8",
                    "priority": row["priority"],
                    "key": (row["key"].hex()
                            if self.sampled and row.get("key")
                            else None),
                    # the DONOR assigns seq (its export counter) and
                    # fingerprints it; the router carries it verbatim
                    "seq": self._mig_seq,
                }
                self._mig_seq += 1
                record_export(wire, rec)
                exports.append(wire)
        else:
            for row in list(self._rows):
                emitted = len(row["out"]) - len(row["prefix"])
                take = min(self.chunk, row["budget"] - emitted)
                row["out"].extend(self._emit_token(row)
                                  for _ in range(take))
        for row in list(self._rows):
            if len(row["out"]) - len(row["prefix"]) >= row["budget"]:
                self._rows.remove(row)
                self.free_pages += row["need"]
                self.finished[row["rid"]] = row["out"]
                self.outcomes[row["rid"]] = "ok"
        fin = {str(r): t for r, t in self.finished.items()}
        self.finished = {}
        reply = {
            "ok": 1, "round": self._round, "finished": fin,
            "outcomes": {str(r): self.outcomes.pop(r)
                         for r in list(self.outcomes)},
            "progress": {str(r["rid"]): r["out"] for r in self._rows},
            "exports": exports,
            "free_pages": self.free_pages,
            "queue_depth": len(self._queue),
            "active": len(self._rows),
        }
        if self.sampled:
            # the router's RESUME CHECKPOINT, key half: each active
            # row's chain state next to the tokens the progress field
            # already carries — what makes a death-resume byte-exact
            # in sampled mode (opaque to the router; handed back
            # verbatim on resume)
            reply["keys"] = {str(r["rid"]): r["key"].hex()
                             for r in self._rows if r.get("key")}
        return reply


class RealAdapter(EngineAdapter):
    """An :class:`~hpc_patterns_tpu.models.serving.EngineCore` behind
    the replica protocol (imports jax lazily — only replicas that
    actually serve a model pay for it). The donor export runs after a
    prefill-only round; installs queue and run behind the next round's
    decode chunk through ``service_round``'s ``pre_collect`` hook."""

    def __init__(self, engine, *, role: str = "both"):
        self.engine = engine
        self.role = role
        self._installs: deque = deque()
        self._round = 0
        self._mig_seq = 0

    def describe(self) -> dict:
        e = self.engine
        return {"role": self.role, "slots": e.slots,
                "pages_per_seq": e.pages_per_seq,
                "page_size": e.page_size, "stub": False,
                "free_pages": e.free_page_count,
                "queue_depth": e.queue_depth}

    def submit(self, req: dict) -> None:
        import numpy as np

        kw = {}
        if req.get("key") is not None and not self.engine.greedy:
            # the router's checkpointed key state (a uint32 pair from
            # a prior round reply): the resumed row's sampling stream
            # continues exactly where the dead replica's stopped —
            # the _preempt/_admit_row split/pick contract
            import jax.numpy as jnp

            kw["key"] = jnp.asarray(np.asarray(req["key"], np.uint32))
        self.engine.submit(
            np.asarray(req["prompt"], np.int32), int(req["max_new"]),
            seq_id=int(req["rid"]),
            priority=int(req.get("priority") or 0),
            deadline_s=req.get("deadline_s"),
            resume_prefix=(np.asarray(req["resume_prefix"], np.int32)
                           if req.get("resume_prefix") else None),
            **kw)

    def queue_install(self, wire: dict, t_disp: float) -> None:
        self._installs.append((wire, t_disp))

    def _install_pending(self, rec, overlapped: bool) -> None:
        from hpc_patterns_tpu.serving_plane.migration import (
            bundle_from_wire,
        )

        while self._installs:
            wire, t_disp = self._installs[0]
            if not self.engine.migration_admissible(
                    int(wire["n_pages"])):
                break
            self._installs.popleft()
            self.engine.install_migration(bundle_from_wire(wire))
            if rec is not None and t_disp:
                rec.mark_complete(
                    "plane.kv_migration", t_disp,
                    {"seq": int(wire["seq"]),
                     "overlapped": overlapped},
                    track=migration_track(wire["seq"]))

    def round(self, rec) -> dict:
        from hpc_patterns_tpu.serving_plane.migration import (
            bundle_to_wire,
        )

        chaoslib.maybe_inject("replica_round", self._round)
        self._round += 1
        e = self.engine
        keys: dict[str, list[int]] = {}
        if self.role == "prefill":
            e.service_round(decode=False)
            exports = []
            for slot in e.exportable_slots():
                b = e.export_migration(slot)
                b.seq = self._mig_seq
                self._mig_seq += 1
                if not e.greedy:
                    # the exported key state also seeds the router's
                    # checkpoint: a receiver dying between delivery
                    # and its first round reply must not cost the
                    # sampled stream its continuation point
                    import numpy as np

                    keys[str(b.seq_id)] = [
                        int(v) for v in np.asarray(b.key, np.uint32)]
                wire = bundle_to_wire(b)
                # the socket IS the transport on this plane: stamp it
                # at export so the receiver's install (and any replayed
                # artifact) records how the payload traveled
                wire["transport"] = "wire"
                wire["payload_dtype"] = str(
                    b.pages_payload["k"][0].dtype)
                record_export(wire, rec)
                exports.append(wire)
        else:
            pre = None
            if self._installs:
                def pre(overlapped):
                    self._install_pending(rec, overlapped)
            e.service_round(pre_collect=pre)
            exports = []
        fin = {}
        outcomes = {}
        for sid in list(e.finished):
            fin[str(sid)] = [int(t) for t in e.finished.pop(sid)]
            outcomes[str(sid)] = (e.stats.get(sid, {}).get("outcome")
                                  or "ok")
        progress = {str(s.seq_id): [int(t) for t in s.out]
                    for s in e._slots if s.active}
        if not e.greedy and e.active_count:
            # the key half of the router's resume checkpoint: the
            # post-round per-row PRNG state, consistent with the
            # progress tokens the same reply carries (the chunk was
            # collected before this round returned)
            import numpy as np

            import jax

            # jaxlint: disable=host-sync-in-dispatch — round-boundary
            # snapshot (the chunk readback already synced); np.array
            # COPIES the view a later donated _chunk_step would mutate
            arr = np.array(jax.device_get(e.keys))
            for i, s in enumerate(e._slots):
                if s.active:
                    keys[str(s.seq_id)] = [int(v) for v in arr[i]]
        reply = {
            "ok": 1, "round": self._round, "finished": fin,
            "outcomes": outcomes, "progress": progress,
            "exports": exports,
            "free_pages": e.free_page_count,
            "queue_depth": e.queue_depth,
            "active": e.active_count,
        }
        if keys:
            reply["keys"] = keys
        return reply


# ---------------------------------------------------------------------------
# replica server
# ---------------------------------------------------------------------------


def addr_path(rdv_dir: str | Path, rank: int) -> Path:
    return Path(rdv_dir) / f"replica{rank:05d}.addr"


def serve_replica(adapter: EngineAdapter, *, rank: int,
                  rdv_dir: str | Path, timeout_s: float = 120.0,
                  rec=None) -> int:
    """One replica process: bind an ephemeral localhost port, publish
    it under ``rdv_dir`` (the launcher gives every child the same
    directory — the mpirun-hostfile analog), then serve the router's
    protocol until ``stop`` or an idle timeout (an orphaned replica
    must not outlive a dead router; the launcher's own timeout is the
    backstop)."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    srv.settimeout(timeout_s)
    host, port = srv.getsockname()
    p = addr_path(rdv_dir, rank)
    tmp = p.with_suffix(".tmp")
    tmp.write_text(f"{host}:{port}")
    os.replace(tmp, p)
    print(f"replica {rank} ({adapter.role}) listening on {host}:{port}",
          flush=True)
    try:
        conn, _ = srv.accept()
    except socket.timeout:
        print(f"replica {rank}: no router within {timeout_s}s",
              flush=True)
        return 1
    conn.settimeout(timeout_s)
    rfile = conn.makefile("r")
    served_rounds = 0
    try:
        while True:
            msg = recv_msg(rfile)
            if msg is None:
                print(f"replica {rank}: router hung up", flush=True)
                return 0
            op = msg.get("op")
            if op == "hello":
                send_msg(conn, {"ok": 1, "rank": rank,
                                **adapter.describe()})
            elif op == "submit":
                try:
                    adapter.submit(msg)
                    send_msg(conn, {"ok": 1})
                except Exception as e:  # noqa: BLE001 — protocol reply
                    send_msg(conn, {"ok": 0, "error": str(e)})
            elif op == "migrate":
                recv_migration(msg["bundle"], adapter, rec)
                send_msg(conn, {"ok": 1})
            elif op == "round":
                reply = adapter.round(rec)
                served_rounds += 1
                send_msg(conn, reply)
            elif op == "stop":
                send_msg(conn, {"ok": 1, "rounds": served_rounds})
                print(f"replica {rank}: served {served_rounds} "
                      "round(s)", flush=True)
                return 0
            else:
                send_msg(conn, {"ok": 0, "error": f"bad op {op!r}"})
    except (ReplicaDead, socket.timeout) as e:
        print(f"replica {rank}: connection lost ({e})", flush=True)
        return 0


# ---------------------------------------------------------------------------
# router client
# ---------------------------------------------------------------------------


class ReplicaHandle:
    def __init__(self, rank: int, addr: str, *,
                 timeout_s: float = 120.0):
        # the recv timeout doubles as the death detector: it must
        # track the operator's --plane-timeout, or a slow replica
        # round (first-round jit compiles on the real-engine leg) is
        # misread as a death and its work double-served on survivors
        self.rank = rank
        host, port = addr.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)),
                                             timeout=timeout_s)
        self.rfile = self.sock.makefile("r")
        self.alive = True
        self.info: dict = {}
        self.load: dict = {"free_pages": 0, "queue_depth": 0,
                           "active": 0}
        self.assigned: set[int] = set()

    def call(self, msg: dict) -> dict:
        send_msg(self.sock, msg)
        reply = recv_msg(self.rfile)
        if reply is None:
            raise ReplicaDead(f"EOF from replica {self.rank}")
        return reply

    @property
    def role(self) -> str:
        return self.info.get("role", "both")

    @property
    def can_prefill(self) -> bool:
        return self.role in ("both", "prefill")

    @property
    def can_decode(self) -> bool:
        return self.role in ("both", "decode")


def connect_replicas(rdv_dir: str | Path, ranks, *,
                     wait_s: float = 60.0,
                     timeout_s: float = 120.0) -> list[ReplicaHandle]:
    """Wait for every replica's address file, then connect and
    handshake. Order = rank order. ``timeout_s`` becomes each
    handle's recv timeout (the death detector)."""
    deadline = time.monotonic() + wait_s
    handles = []
    for rank in ranks:
        p = addr_path(rdv_dir, rank)
        while not p.exists():
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replica {rank} never published {p}")
            time.sleep(0.02)
        h = ReplicaHandle(rank, p.read_text().strip(),
                          timeout_s=timeout_s)
        h.info = h.call({"op": "hello"})
        h.load = {k: h.info.get(k, 0)
                  for k in ("free_pages", "queue_depth", "active")}
        handles.append(h)
    return handles


class PlaneRouter:
    """The router process of the launched plane: admits the open-loop
    stream across the replica handles, forwards KV handoffs from
    prefill to decode replicas, detects replica death, re-queues the
    dead replica's in-flight requests as resumes on survivors (or
    counts them shed), and rolls the SLO table up at the end. All
    timing is stamped at the ROUTER (one clock): TTFT is when the
    router first observes tokens — the latency the front end actually
    served."""

    def __init__(self, handles: list[ReplicaHandle], *,
                 policy: str = "least_loaded", slo_targets=None,
                 emit=None, placement_weights: dict | None = None):
        if not handles:
            raise ValueError("no replicas")
        self.handles = handles
        self.policy = policy
        #: fitted per-replica capacity shares keyed by str(rank) —
        #: read by the "weighted" policy (harness/autofit.py); empty =
        #: neutral (every replica weight 1.0)
        self.placement_weights = {
            str(k): float(v)
            for k, v in (placement_weights or {}).items()}
        self.slo_targets = slo_targets or {}
        self._emit = emit or (lambda **kw: None)
        #: the sliding-window SLO-attainment signal (the in-process
        #: plane's satellite, mirrored here so the LAUNCHED plane feeds
        #: the same ``kind=plane_attainment`` trajectory to autofit and
        #: any future launched autoscaler): judged at resolution,
        #: emitted once per router round
        self.attain_window = slolib.AttainmentWindow()
        self._plane_rounds = 0
        self._attain_emitted = (0, 0)  # (judged, attained) last emit
        self.stats: dict[int, dict] = {}
        self.finished: dict[int, list[int]] = {}
        self.requests: dict[int, dict] = {}
        self.progress: dict[int, list[int]] = {}
        #: the key half of the resume checkpoint (PR 9 remainder):
        #: per-row sampling key state from the replicas' round
        #: replies, OPAQUE to the router (a uint32 pair for real
        #: engines, a hex chain state for the sampled stub) — handed
        #: back verbatim on a death-resume so sampled streams
        #: continue byte-exact, not just greedy ones
        self.key_ckpt: dict[int, object] = {}
        self.pending_bundles: deque = deque()
        self._next_rid = 0
        self._rr = 0
        self.migrations = 0
        self.deaths: list[int] = []
        self.resumed: list[int] = []
        self.shed: list[int] = []
        self.last_slo: dict | None = None

    @classmethod
    def from_fitted(cls, handles, fitted, *, slo_targets=None,
                    emit=None, **kw):
        """A router from an autofit ``FittedConfig``: the fitted
        ``placement`` section picks the policy and the per-replica
        weights (keyed by rank in the launched plane) — defaults when
        the config carries no placement signal. An explicit ``policy=``
        kwarg wins over the fit."""
        from hpc_patterns_tpu.harness import autofit as autofitlib

        fitted = autofitlib.validate_fitted(fitted)
        section = fitted.get("placement") or {}
        if "policy" not in kw and section.get("policy"):
            kw["policy"] = section["policy"]
        if "placement_weights" not in kw and section.get("weights"):
            kw["placement_weights"] = section["weights"]
        return cls(handles, slo_targets=slo_targets, emit=emit, **kw)

    # -- placement ---------------------------------------------------------

    def _alive(self, pred=None):
        return [h for h in self.handles
                if h.alive and (pred is None or pred(h))]

    def _pick(self, cand: list[ReplicaHandle]) -> ReplicaHandle | None:
        if not cand:
            return None
        if self.policy == "round_robin":
            h = cand[self._rr % len(cand)]
            self._rr += 1
            return h
        if self.policy == "weighted":
            # the fitted capacity share per unit of present pressure —
            # the launched twin of router.py's _weighted (a replica
            # the fit never saw is neutral at 1.0)
            return max(cand, key=lambda h: (
                self.placement_weights.get(str(h.rank), 1.0)
                / (1.0 + h.load["queue_depth"]),
                h.load["free_pages"]))
        return max(cand, key=lambda h: (h.load["free_pages"],
                                        -h.load["queue_depth"]))

    # -- submission --------------------------------------------------------

    def submit(self, prompt, max_new: int, *, priority: int = 0,
               deadline_s=None, t_submit: float | None = None) -> int:
        rid = self._next_rid
        self._next_rid += 1
        now = time.perf_counter()
        self.requests[rid] = {
            "prompt": [int(t) for t in prompt],
            "max_new": int(max_new), "priority": int(priority),
            "deadline_s": deadline_s,
        }
        self.stats[rid] = {
            "priority": int(priority),
            "t_submit": t_submit if t_submit is not None else now,
            "t_first": None, "t_finish": None, "tokens": 0,
            "outcome": None, "preemptions": 0,
        }
        rtr = reqtracelib.active()
        if rtr is not None:
            # launched-plane segments are ROUTER-stamped (one clock,
            # the class contract above): replica-side detail is not
            # visible here, so the buckets are the router's own
            # transitions — queued until assigned, prefill until the
            # first observed token, decode after
            rtr.begin_request(rid, self.stats[rid]["t_submit"])
        if not self._assign(rid, resume_prefix=None):
            self._shed(rid)
        return rid

    def _assign(self, rid: int, *, resume_prefix) -> bool:
        """Place one (possibly resumed) request: try candidates in
        policy-preference order until one accepts. Fresh work goes to
        prefill-capable replicas; a RESUME re-enters through any
        survivor's ordinary admission path (a decode-role engine still
        admits — its role only means it never receives fresh routing)."""
        req = self.requests[rid]
        prompt = list(req["prompt"])
        if resume_prefix:
            prompt = prompt + list(resume_prefix)
        tried: set[int] = set()
        while True:
            cand = self._alive(
                lambda h: h.rank not in tried
                and (h.can_prefill or resume_prefix is not None))
            h = self._pick(cand)
            if h is None:
                return False
            tried.add(h.rank)
            try:
                reply = h.call({
                    "op": "submit", "rid": rid, "prompt": prompt,
                    "max_new": req["max_new"] - len(resume_prefix or []),
                    "priority": req["priority"],
                    "deadline_s": req["deadline_s"],
                    "resume_prefix": list(resume_prefix or []) or None,
                    # a resume carries the checkpointed key state so a
                    # sampled stream continues where the dead replica
                    # stopped; fresh work derives its own request key
                    "key": (self.key_ckpt.get(rid)
                            if resume_prefix is not None else None),
                })
            except ReplicaDead:
                self._on_death(h)
                continue
            if not reply.get("ok"):
                continue  # this replica cannot fit it; try the next
            h.assigned.add(rid)
            rtr = reqtracelib.active()
            if rtr is not None:
                # in a replica's hands: the service attempt (remote
                # queue + prefill) runs until the router observes the
                # first token in _merge_round
                rtr.stamp_transition(rid, "prefill")
            # bump the local load estimate NOW: a burst of submits
            # between rounds must spread instead of piling onto the
            # replica whose snapshot happened to look emptiest
            h.load["queue_depth"] += 1
            self._emit(kind="plane_route", seq_id=rid,
                       replica=h.rank, resumed=bool(resume_prefix))
            return True

    def _shed(self, rid: int) -> None:
        rec = self.stats[rid]
        rec["outcome"] = "shed"
        rec["t_finish"] = time.perf_counter()
        rtr = reqtracelib.active()
        if rtr is not None:
            rtr.finish_request(rid, rec["t_finish"], final="shed")
        self._judge_window(rec)  # a shed never attains — it counts
        self.finished[rid] = []
        self.shed.append(rid)
        self._emit(kind="plane_shed", seq_id=rid)

    def _judge_window(self, rec: dict) -> None:
        """Fold one resolved stats row into the sliding attainment
        window (a rank with no declared target judges trivially when
        served — the signal still tracks sheds and queue health)."""
        target = self.slo_targets.get(int(rec.get("priority") or 0),
                                      slolib.SLOTarget())
        self.attain_window.judge(rec, target)

    def _emit_attainment(self) -> None:
        """The per-round sliding-window SLO-attainment gauge of the
        LAUNCHED plane — same window, same three mediums (metrics
        gauge / trace counter / ``kind=plane_attainment`` record) as
        the in-process plane, so autofit's threshold fitter replays
        one trajectory format regardless of which plane recorded it."""
        self._plane_rounds += 1
        snap = self.attain_window.snapshot()
        judged, attained = (self.attain_window.judged,
                            self.attain_window.attained)
        judged_round = judged - self._attain_emitted[0]
        attained_round = attained - self._attain_emitted[1]
        self._attain_emitted = (judged, attained)
        alive = self._alive()
        queued = sum(int(h.load.get("queue_depth") or 0)
                     for h in alive)
        active = sum(int(h.load.get("active") or 0) for h in alive)
        m = metricslib.get_metrics()
        if m.enabled and snap["overall"] is not None:
            m.gauge("plane.attainment").set(snap["overall"])
            for prio, frac in snap["per_class"].items():
                m.gauge(f"plane.attainment.p{prio}").set(frac)
        rec = tracelib.active()
        if rec is not None and snap["overall"] is not None:
            rec.counter("plane.attainment", {
                "overall": snap["overall"],
                **{f"p{prio}": frac
                   for prio, frac in snap["per_class"].items()}})
        self._emit(kind="plane_attainment", round=self._plane_rounds,
                   overall=snap["overall"],
                   per_class={str(p): f
                              for p, f in snap["per_class"].items()},
                   window_n=snap["n"], judged_round=judged_round,
                   attained_round=attained_round, queued=queued,
                   active=active, replicas=len(alive))

    # -- failure handling --------------------------------------------------

    def _on_death(self, h: ReplicaHandle) -> None:
        """A replica died mid-protocol: every in-flight request it
        held is re-queued as a RESUME on a survivor — prompt =
        original + the tokens the router already observed (its last
        ``progress`` report) — or counted shed. Bundles queued toward
        it are re-routed the same way."""
        if not h.alive:
            return
        h.alive = False
        self.deaths.append(h.rank)
        print(f"router: replica {h.rank} died; re-queueing "
              f"{len(h.assigned)} in-flight request(s)", flush=True)
        orphans = sorted(h.assigned)
        h.assigned.clear()
        for rid in orphans:
            if self.stats[rid].get("outcome") is not None:
                continue
            emitted = list(self.progress.get(rid, []))
            if len(emitted) >= self.requests[rid]["max_new"]:
                # everything was emitted; the finish report died with
                # the replica — the observed tokens ARE the output
                self._finish(rid, emitted, "ok")
                continue
            rtr = reqtracelib.active()
            if rtr is not None:
                # the replica died with the row: the span from death
                # to re-admission is a preemption, same bucket as an
                # engine-level eviction (a successful _assign then
                # transitions it back to prefill)
                rtr.stamp_transition(rid, "preempted")
            if self._assign(rid, resume_prefix=emitted):
                self.stats[rid]["preemptions"] += 1
                self.resumed.append(rid)
                self._emit(kind="plane_resume", seq_id=rid,
                           from_rank=h.rank, tokens=len(emitted))
            else:
                self._shed(rid)

    # -- result plumbing ---------------------------------------------------

    def _finish(self, rid: int, tokens: list[int],
                outcome: str) -> None:
        rec = self.stats[rid]
        if rec.get("outcome") is not None:
            return
        rec["outcome"] = outcome
        rec["t_finish"] = time.perf_counter()
        rec["tokens"] = len(tokens)
        if rec["t_first"] is None and tokens:
            rec["t_first"] = rec["t_finish"]
        rtr = reqtracelib.active()
        if rtr is not None:
            rtr.finish_request(rid, rec["t_finish"])
        self._judge_window(rec)
        self.finished[rid] = tokens
        self.progress.pop(rid, None)
        # the key checkpoint resolves with the request, like the
        # progress half above — a long-lived router must not grow one
        # dead key entry per served request
        self.key_ckpt.pop(rid, None)

    def _merge_round(self, h: ReplicaHandle, reply: dict) -> None:
        now = time.perf_counter()
        h.load = {k: reply.get(k, 0)
                  for k in ("free_pages", "queue_depth", "active")}
        rtr = reqtracelib.active()
        for rid_s, toks in reply.get("progress", {}).items():
            rid = int(rid_s)
            self.progress[rid] = list(toks)
            rec = self.stats.get(rid)
            if rec is not None and rec["t_first"] is None and toks:
                rec["t_first"] = now
                if rtr is not None:
                    rtr.stamp_transition(rid, "decode", now)
        for rid_s, key in reply.get("keys", {}).items():
            self.key_ckpt[int(rid_s)] = key
        outcomes = reply.get("outcomes", {})
        for rid_s, toks in reply.get("finished", {}).items():
            rid = int(rid_s)
            h.assigned.discard(rid)
            self._finish(rid, list(toks),
                         outcomes.get(rid_s, "ok"))
            if outcomes.get(rid_s) == "shed":
                self.shed.append(rid)
        for wire in reply.get("exports", []):
            # seq was assigned (and fingerprinted) by the donor; the
            # router carries it verbatim so the receiver's fingerprint
            # matches — renumbering here would fake a desync
            h.assigned.discard(int(wire["seq_id"]))
            # the wire carries the prefill-side tokens: seed the
            # resume checkpoint NOW, so a receiver that dies between
            # delivery and its next round reply does not cost the
            # router the tokens it was already holding
            rid = int(wire["seq_id"])
            if len(wire.get("out", [])) > len(self.progress.get(rid,
                                                                ())):
                self.progress[rid] = list(wire["out"])
                rec = self.stats.get(rid)
                if rec is not None and rec["t_first"] is None:
                    rec["t_first"] = now
            if rtr is not None:
                # the row left its donor: in plane transit until a
                # decode replica accepts the forwarded bundle
                rtr.stamp_transition(rid, "migrating", now)
                if isinstance(wire.get("seq"), int):
                    rtr.annotate_open(rid, seq=wire["seq"])
            self.pending_bundles.append(wire)

    def _forward_bundles(self) -> None:
        still: deque = deque()
        while self.pending_bundles:
            wire = self.pending_bundles.popleft()
            need = int(wire["n_pages"])
            cand = self._alive(
                lambda h: h.can_decode
                and h.load["free_pages"] >= need
                # table width too (hello carries it): an oversized
                # bundle delivered to a replica that can NEVER install
                # it would wedge that replica's whole install queue
                # behind the head-of-line break
                and need <= int(h.info.get("pages_per_seq", need)))
            h = self._pick(cand)
            if h is None:
                still.append(wire)
                continue
            try:
                h.call({"op": "migrate", "bundle": wire})
            except ReplicaDead:
                self._on_death(h)
                still.append(wire)
                continue
            h.assigned.add(int(wire["seq_id"]))
            h.load["free_pages"] -= int(wire["n_pages"])
            rtr = reqtracelib.active()
            if rtr is not None:
                # handoff delivered: the row decodes on the receiver
                # (its tokens reappear in that replica's progress)
                rtr.stamp_transition(int(wire["seq_id"]), "decode")
            self.migrations += 1
        self.pending_bundles = still

    # -- the loop ----------------------------------------------------------

    def _unresolved(self) -> list[int]:
        return [rid for rid, rec in self.stats.items()
                if rec.get("outcome") is None]

    def run(self, arrivals, *, timeout_s: float = 300.0) -> dict:
        """Admit the open-loop schedule, drive replica rounds until
        every request resolves (finished, resumed-and-finished, or
        shed), and return the report."""
        t0 = time.perf_counter()
        pending = deque(sorted(arrivals, key=lambda a: a[0]))
        deadline = t0 + timeout_s
        while True:
            now_rel = time.perf_counter() - t0
            while pending and pending[0][0] <= now_rel:
                t_arr, kw = pending.popleft()
                self.submit(t_submit=t0 + t_arr, **kw)
            if not pending and not self._unresolved():
                break
            if time.perf_counter() > deadline:
                for rid in self._unresolved():
                    self._shed(rid)
                print("router: timeout — remaining in-flight "
                      "requests counted shed", flush=True)
                break
            if not self._alive():
                for rid in self._unresolved():
                    self._shed(rid)
                print("router: no replicas left alive", flush=True)
                break
            if pending and not self._unresolved():
                # nothing in flight, next arrival in the future: wait
                # on the schedule's clock, boundedly
                wait = pending[0][0] - (time.perf_counter() - t0)
                time.sleep(min(max(wait, 0.0), 0.005))
                continue
            for h in list(self._alive()):
                try:
                    reply = h.call({"op": "round"})
                except ReplicaDead:
                    self._on_death(h)
                    continue
                self._merge_round(h, reply)
            self._forward_bundles()
            self._emit_attainment()
        for h in self._alive():
            try:
                h.call({"op": "stop"})
            except ReplicaDead:
                h.alive = False
        wall = time.perf_counter() - t0
        self.last_slo = slolib.attainment(
            self.stats, self.slo_targets, wall)
        # segment SLO budgets (harness/budget.py): when the run was
        # request-traced AND judged against targets, say WHICH
        # lifecycle segment blew them — breach records ride the same
        # RunLog as the attainment rollup, next to the reqtrace record
        self.last_budget: list = []
        rtr = reqtracelib.active()
        if rtr is not None and self.slo_targets:
            self.last_budget = budgetlib.evaluate(
                rtr.snapshot(self.stats), self.slo_targets)
            budgetlib.publish(self.last_budget, emit=self._emit)
        return {
            "wall_s": wall,
            "n": len(self.stats),
            "served": sum(1 for r in self.stats.values()
                          if r.get("outcome") == "ok"),
            "shed": sorted(set(self.shed)),
            "deaths": list(self.deaths),
            "resumed": sorted(set(self.resumed)),
            "migrations": self.migrations,
            "slo": self.last_slo,
            "budget_breaches": len(self.last_budget),
        }
