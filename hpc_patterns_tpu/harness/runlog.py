"""Structured run log — the native-harness upgrade of ``run.log``.

The reference's only artifact is a tee'd text log grepped for
SUCCESS/FAILURE (concurency/run.sh:15-18). This keeps that grep-able
stdout contract and *additionally* writes one JSON object per record, so
sweeps are machine-readable (SURVEY.md section 5 "metrics/observability"
upgrade). The native sweep driver (native/sweep.cpp) consumes the same
format.

Closing-record convention: instrumented runs append their snapshots as
the log's final records — one ``kind=metrics`` (the registry tables,
harness/metrics.py; aggregated by harness.report) and, under
``--trace``, one ``kind=trace`` (the flight-recorder ring,
harness/trace.py; exported to a Chrome-trace timeline by
``python -m hpc_patterns_tpu.harness.trace``). Both append (never
truncate), so the app's own records survive — the structured analog of
run.sh's trailing grep summary.

Forensic vs dispatched kinds: ``FORENSIC_KINDS`` below lists the
record kinds nothing string-dispatches on. Kinds a consumer DOES
dispatch on stay off that list — e.g. ``kind=slo_budget``
(harness/budget.py breach records), which ``harness.report`` collects
into the per-class breach table; declaring it forensic would hide the
producer/consumer edge contractlint verifies.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path
from typing import IO, Any

#: Record kinds written for the FORENSIC stream — the raw per-event
#: log a human (or replay tooling) greps after a bad run — and
#: deliberately not string-dispatched by report/collect/autofit/
#: explain. contractlint's ``record-kind-drift`` treats membership
#: here as consumption-by-declaration: a kind belongs on this list
#: only if nothing *should* dispatch on it; adding one to silence a
#: finding while a consumer exists is the drift the rule hunts.
FORENSIC_KINDS = (
    # serving-engine lifecycle events (models/serving.py): the
    # per-seq swap/migration audit trail behind the aggregated
    # serve_admit/serve_swap_out windows autofit DOES dispatch on
    "serve_migrate_out",
    "serve_migrate_in",
    "serve_swap_in",
    # serving-plane round events (serving_plane/router.py,
    # service.py, autoscaler.py): the elastic plane's decision journal
    "plane_migrate",
    "plane_route",
    "plane_shed",
    "plane_resume",
    "plane_transport_fallback",
    "plane_replica_death",
    "plane_spinup",
    "plane_drain",
    "plane_retire",
    # per-step training journal (apps/train_app.py): loss/dt per
    # step for post-mortem grep; the aggregated numbers ride the
    # metrics snapshot
    "step",
    # the versioned --rollup-out artifact envelope (harness/
    # collect.py): consumers take the whole document, nothing
    # string-dispatches on its kind field
    "trace_rollup",
    # kernel autotune outcomes (benchmarks): cache-warm evidence
    "autotune",
)


class RunLog:
    def __init__(
        self,
        path: str | Path | None = None,
        stream: IO[str] | None = None,
        *,
        truncate: bool = True,
    ):
        self.path = Path(path) if path else None
        self.stream = stream if stream is not None else sys.stdout
        self.records: list[dict[str, Any]] = []
        if self.path:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            if truncate:
                # one log per run, like run.sh's tee; apps invoked *by* a
                # harness pass truncate=False (--log-append) to share the
                # harness's log instead
                self.path.write_text("")

    def emit(self, **record: Any) -> dict[str, Any]:
        record.setdefault("ts", time.time())
        self.records.append(record)
        line = json.dumps(record, default=str)
        if self.path:
            with self.path.open("a") as f:
                f.write(line + "\n")
        return record

    def print(self, text: str) -> None:
        """Human/grep-able line to stdout (run.sh:17-18 contract)."""
        print(text, file=self.stream)

    def result(self, name: str, verdict, **extra: Any) -> None:
        self.emit(
            kind="result",
            name=name,
            success=verdict.success,
            speedup=verdict.speedup,
            max_theoretical_speedup=verdict.max_theoretical_speedup,
            **extra,
        )
        for m in verdict.messages:
            self.print(f"[{name}] {m}")

    def summary(self) -> tuple[int, int]:
        """(n_success, n_failure) over result records; prints the grep
        summary exactly once, like run.sh:17-18."""
        results = [r for r in self.records if r.get("kind") == "result"]
        ok = sum(1 for r in results if r.get("success"))
        bad = len(results) - ok
        self.print(f"SUCCESS count: {ok}")
        self.print(f"FAILURE count: {bad}")
        return ok, bad
