"""autofit — profile-driven configuration: observability becomes control.

The observability ladder (metrics registry → flight recorder →
regression gate → distributed merge + rollups) stops at diagnosis: a
human reads the Perfetto fan and hand-tunes the prompt ladder, the
residency knobs, the placement policy, and the autoscaler thresholds.
This module closes the loop: it consumes the RunLog records a prior run
already writes (``kind=serve_admit`` / ``kind=trace`` /
``kind=trace_merged`` / ``kind=metrics``, plus ``collect.py``'s
``--rollup-out`` JSON) and emits a versioned ``FittedConfig`` — the
config *derived from* the run, the first-touch idea of automatic
data-movement tuning applied to our serving tiers.

Five independent fitters, each deterministic and pure (no RNG, no
timestamps, no device dispatch — same records in, bit-identical JSON
out):

- **ladder** — prompt-length bucket ladder via the exact-DP
  :func:`~hpc_patterns_tpu.models.serving.fit_bucket_ladder`, fed from
  the observed ``serve_admit`` prompt/padded lengths (one more rung than
  the shape-blind default ladder, so the fit can only remove padding);
- **residency** — eviction policy, anti-thrash floor and prefetch depth
  from the ``mem.prefetch`` overlap fractions in the trace and the
  ``mem.hbm_pages`` / ``mem.host_pages`` pressure gauges;
- **placement** — per-replica weights from the merged busy/bubble
  rollups and the ``plane.<name>.queue_depth`` gauges;
- **autoscaler** — hysteresis bands picked by replaying the observed
  attainment/queue trajectory (``kind=plane_attainment`` records, the
  sliding-window gauge both planes emit) through the pure
  :class:`~hpc_patterns_tpu.serving_plane.autoscaler.Autoscaler`
  offline and keeping the candidate that never flaps;
- **blame** — acts on *why* the tail happened, not just on raw
  signals: the pooled attribution digest (``kind=reqtrace`` records
  through :func:`harness.explain.digest`) names the dominant p99-band
  segment, and the fitter maps blame to a knob — ``prefetch_wait``
  refits the prefetch depth from the wait-overlap structure
  (stacked waits cap at one in-flight pull; serialized waits deepen
  to the parked-row peak), ``queued`` widens the autoscaler band
  (scale up at a shallower backlog), ``admit_wait`` recommends a
  higher admission high-water. Blame overrides the signal fit where
  both speak: the digest sees the REQUEST's wait, the trace only
  sees the transfer.

A section whose signals are absent from the input is emitted as
``null`` — consumers fall back to their defaults, so a config fitted
from a trace that never paged still applies its ladder.

Consumers: ``EngineCore.from_fitted`` / ``ContinuousBatcher``,
``ResidencyManager.from_fitted``, ``ServingPlane.from_fitted`` (and the
launched ``PlaneRouter``), ``AutoscalerPolicy.from_fitted``; the apps
and benches take ``--autofit config.json``.

Usage::

    python -m hpc_patterns_tpu.harness.autofit run.jsonl --emit config.json
    python -m hpc_patterns_tpu.harness.autofit run.jsonl --rollups rollups.json

Exit 0: config emitted (even if every section is null — that is a
statement about the input, not an error). 2: unreadable input.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any

FITTED_VERSION = 1
FITTED_KIND = "fitted_config"

# deterministic fitter constants (documented, not tunable per-call: two
# people fitting the same trace must get the same config)
EXTRA_RUNGS = 1           # fitted ladder may use default rungs + this
THRASH_PULLS_PER_SEQ = 1.5  # pulls/seq above this = re-eviction churn
MIN_OVERLAP_FOR_DEPTH = 0.2  # exposed pulls => depth 1, don't stack
ROUND_ROBIN_MAX_SKEW = 1.25  # weight skew below this: uniform is fine
MIN_TRAJECTORY_ROUNDS = 4   # fewer observed rounds fit nothing
MIN_BLAME_SHARE = 0.25      # a band share below this blames nobody
MAX_BLAME_DEPTH = 8         # deepened prefetch depth is still bounded
BLAME_RESIDENT_ROUNDS = 8   # blamed churn escalates the anti-thrash
                            # floor to this: long enough that a
                            # bench-scale decode finishes its stint
                            # instead of paying an exposed pull mid-way


# ---------------------------------------------------------------------------
# record ingestion


def read_records(paths) -> list[dict[str, Any]]:
    """All JSON records from the given RunLog JSONL files, in file then
    line order. Non-JSON lines are skipped (RunLog files share stdout
    real estate with grep-able text in some harnesses)."""
    records: list[dict[str, Any]] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    return records


def _iter_trace_events(records):
    """Yield ``(ph, cat, name, ts, tid, dur, args)`` tuples from every
    ``kind=trace`` record's event list (JSON round-trips the recorder's
    tuples as lists)."""
    for rec in records:
        if rec.get("kind") != "trace":
            continue
        for ev in rec.get("events") or ():
            if isinstance(ev, (list, tuple)) and len(ev) == 7:
                yield tuple(ev)


def _windows(records, name: str) -> list[tuple[float, float]]:
    """Completed ``(start, end)`` device windows with the given name
    (``ph == "X"`` events carry a duration)."""
    out = []
    for ph, _cat, ev_name, ts, _tid, dur, _args in _iter_trace_events(
            records):
        if ph == "X" and ev_name == name and dur is not None:
            out.append((float(ts), float(ts) + float(dur)))
    return sorted(out)


def _gauges(records) -> dict[str, dict[str, Any]]:
    """The union of gauge tables from every ``kind=metrics`` record
    (later records win a key collision — they snapshot later state)."""
    gauges: dict[str, dict[str, Any]] = {}
    for rec in records:
        if rec.get("kind") == "metrics" and isinstance(
                rec.get("gauges"), dict):
            gauges.update(rec["gauges"])
    return gauges


def _merged_rollup(records) -> dict[str, Any] | None:
    """The last ``kind=trace_merged`` record (collect.py's cross-rank
    rollup appended to the shared log), if any."""
    rollup = None
    for rec in records:
        if rec.get("kind") == "trace_merged":
            rollup = rec
    return rollup


def _union_len(intervals) -> float:
    total, cur_lo, cur_hi = 0.0, None, None
    for lo, hi in sorted(intervals):
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    if cur_hi is not None:
        total += cur_hi - cur_lo
    return total


def _overlap_frac(pulls, chunks) -> float | None:
    """Mean fraction of each pull window hidden under the union of
    decode-chunk windows — the same quantity the live engine folds into
    ``prefetch_overlap_frac``, recomputed from the recorded timeline."""
    if not pulls:
        return None
    merged = []
    for lo, hi in sorted(chunks):
        if merged and lo <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], hi))
        else:
            merged.append((lo, hi))
    fracs = []
    for lo, hi in pulls:
        dur = hi - lo
        if dur <= 0:
            continue
        covered = _union_len(
            [(max(lo, a), min(hi, b)) for a, b in merged
             if b > lo and a < hi])
        fracs.append(covered / dur)
    if not fracs:
        return None
    return sum(fracs) / len(fracs)


def _max_concurrency(intervals) -> int:
    events = sorted([(lo, 1) for lo, _ in intervals]
                    + [(hi, -1) for _, hi in intervals],
                    key=lambda e: (e[0], e[1]))
    cur = peak = 0
    for _, d in events:
        cur += d
        peak = max(peak, cur)
    return peak


# ---------------------------------------------------------------------------
# section fitters


def fit_ladder(records) -> dict[str, Any] | None:
    """Prompt-length bucket ladder from the observed ``serve_admit``
    stream: the exact DP gets ONE more rung than the shape-blind
    default ladder over the same range, so the fitted ladder can only
    pad less (the default is in the DP's feasible set)."""
    from hpc_patterns_tpu.models.serving import (
        bucket_ladder,
        expected_padding,
        fit_bucket_ladder,
    )

    admits = [r for r in records if r.get("kind") == "serve_admit"
              and isinstance(r.get("prompt_len"), int)]
    if not admits:
        return None
    lengths = sorted(r["prompt_len"] for r in admits)
    max_len = max(max(lengths),
                  max((r.get("padded_len") or 0 for r in admits)))
    default = bucket_ladder(max_len)
    max_rungs = max(2, len(default) + EXTRA_RUNGS)
    buckets = fit_bucket_ladder(lengths, max_rungs, max_len=max_len)
    counts: dict[int, int] = {}
    for t in lengths:
        counts[t] = counts.get(t, 0) + 1
    return {
        "buckets": [int(b) for b in buckets],
        "max_rungs": max_rungs,
        "max_len": int(max_len),
        "n_admits": len(admits),
        "observed_lengths": [[int(t), counts[t]] for t in sorted(counts)],
        "expected_padding": round(expected_padding(buckets, lengths), 6),
        "default_ladder": [int(b) for b in default],
        "default_expected_padding": round(
            expected_padding(default, lengths), 6),
    }


def fit_residency(records) -> dict[str, Any] | None:
    """Eviction policy + anti-thrash floor + prefetch depth from the
    paging signals. Rules (deterministic, in order):

    - two or more priority classes among the admitted rows → the
      ``priority`` policy (evict batch before interactive); else LRU;
    - pulls-per-swapped-sequence above ``THRASH_PULLS_PER_SEQ`` means
      rows are being re-evicted before they finish → raise
      ``min_resident_rounds`` to 2 (the anti-thrash floor);
    - the recorded ``mem.prefetch`` windows' overlap against the
      ``serve.chunk`` windows picks the prefetch depth: well-hidden
      pulls (≥ ``MIN_OVERLAP_FOR_DEPTH``) keep the observed peak
      concurrency; exposed pulls cap the engine at one in-flight pull
      so transfers never stack in the open.
    """
    swaps = sum(1 for r in records if r.get("kind") == "serve_swap_out")
    pulls = [r for r in records if r.get("kind") == "serve_prefetch"]
    gauges = _gauges(records)
    hbm = gauges.get("mem.hbm_pages")
    host = gauges.get("mem.host_pages")
    if not swaps and not pulls and host is None:
        return None  # this run never paged — nothing to fit
    prios = sorted({r["priority"] for r in records
                    if r.get("kind") == "serve_admit"
                    and r.get("priority") is not None})
    policy = "priority" if len(prios) >= 2 else "lru"
    seqs = {r.get("seq_id") for r in pulls}
    pulls_per_seq = (len(pulls) / len(seqs)) if seqs else 0.0
    min_resident_rounds = 2 if pulls_per_seq > THRASH_PULLS_PER_SEQ else 1
    pull_windows = _windows(records, "mem.prefetch")
    chunk_windows = _windows(records, "serve.chunk")
    overlap = _overlap_frac(pull_windows, chunk_windows)
    if overlap is None:
        prefetch_depth = None  # no timeline — leave the engine's default
    elif overlap >= MIN_OVERLAP_FOR_DEPTH:
        prefetch_depth = max(1, _max_concurrency(pull_windows))
    else:
        prefetch_depth = 1
    hbm_peak = float(hbm["max"]) if hbm else None
    host_peak = float(host["max"]) if host else None
    pressure = None
    if hbm_peak is not None and host_peak is not None \
            and hbm_peak + host_peak > 0:
        pressure = round(host_peak / (hbm_peak + host_peak), 6)
    return {
        "policy": policy,
        "min_resident_rounds": min_resident_rounds,
        "prefetch_depth": prefetch_depth,
        "observed": {
            "swap_outs": swaps,
            "pulls": len(pulls),
            "pulls_per_seq": round(pulls_per_seq, 6),
            "priority_classes": [int(p) for p in prios],
            "prefetch_overlap_frac": (None if overlap is None
                                      else round(overlap, 6)),
            "hbm_pages_peak": hbm_peak,
            "host_pages_peak": host_peak,
            "host_pressure": pressure,
        },
    }


def fit_placement(records, rollups=None) -> dict[str, Any] | None:
    """Per-replica placement weights from the queue-depth gauges
    (preferred — they name replicas) or, cross-rank, from the merged
    busy/bubble rollups (idle share = capacity share). Near-uniform
    weights pick ``round_robin`` (no information to act on); skewed
    weights pick the ``weighted`` policy so the router sends work where
    the capacity is."""
    gauges = _gauges(records)
    raw: dict[str, float] = {}
    source = None
    qd = {k[len("plane."):-len(".queue_depth")]: v
          for k, v in gauges.items()
          if k.startswith("plane.") and k.endswith(".queue_depth")}
    if qd:
        source = "queue_depth_gauges"
        for name, g in sorted(qd.items()):
            # mean queue depth over the run ≈ how backed-up the
            # replica stayed; weight is inverse pressure
            n = max(1, int(g.get("n") or 1))
            mean_q = (float(g.get("last") or 0.0)
                      if n == 1 else
                      (float(g.get("min") or 0.0)
                       + float(g.get("max") or 0.0)) / 2.0)
            raw[name] = 1.0 / (1.0 + max(0.0, mean_q))
    else:
        rollup = rollups if isinstance(rollups, dict) else None
        rollup = rollup or _merged_rollup(records)
        busy = (rollup or {}).get("busy")
        if not isinstance(busy, dict) or not busy:
            return None
        source = "busy_rollup"
        for pid, row in sorted(busy.items()):
            busy_frac = float(row.get("busy_frac") or 0.0)
            raw[str(pid)] = max(0.0, 1.0 - busy_frac)
    if not raw:
        return None
    total = sum(raw.values())
    if total <= 0.0:
        weights = {k: round(1.0 / len(raw), 6) for k in sorted(raw)}
    else:
        weights = {k: round(v / total, 6) for k, v in sorted(raw.items())}
    lo, hi = min(weights.values()), max(weights.values())
    skew = (hi / lo) if lo > 0 else float("inf")
    policy = ("round_robin" if skew <= ROUND_ROBIN_MAX_SKEW
              else "weighted")
    return {
        "policy": policy,
        "weights": weights,
        "skew": (None if skew == float("inf") else round(skew, 6)),
        "source": source,
    }


def _trajectory(records) -> list[dict[str, Any]]:
    """The per-round attainment/queue trajectory: the sliding-window
    ``kind=plane_attainment`` records both planes emit (satellite of
    the same PR), sorted by round."""
    rows = [r for r in records if r.get("kind") == "plane_attainment"
            and isinstance(r.get("round"), int)]
    return sorted(rows, key=lambda r: r["round"])


def replay(trajectory, policy) -> list:
    """Replay an observed trajectory through a fresh pure controller —
    the offline harness the threshold fitter (and its tests) use. Each
    trajectory row carries the per-round signal fields the planes
    record: ``round``, ``replicas``, ``queued``, ``active``,
    ``attained_round``, ``judged_round``."""
    from hpc_patterns_tpu.serving_plane.autoscaler import (
        Autoscaler,
        Signals,
    )

    scaler = Autoscaler(policy)
    decisions = []
    for row in trajectory:
        sig = Signals(
            round=int(row["round"]),
            replicas=int(row.get("replicas") or 1),
            queued=int(row.get("queued") or 0),
            active=int(row.get("active") or 0),
            attained=int(row.get("attained_round") or 0),
            judged=int(row.get("judged_round") or 0),
        )
        decisions.append(scaler.observe(sig))
    return decisions


def flap_count(decisions) -> int:
    """Direction reversals among the non-hold decisions: an ``up``
    followed (next non-hold) by a ``down`` or vice versa. The quantity
    the threshold fit minimizes — hysteresis bands exist so a steady
    boundary trajectory never oscillates."""
    acts = [d.action for d in decisions if d.action != "hold"]
    return sum(1 for a, b in zip(acts, acts[1:]) if a != b)


def fit_autoscaler(records) -> dict[str, Any] | None:
    """Hysteresis bands from the observed attainment/queue trajectory:
    a small deterministic candidate grid, each candidate replayed
    through the pure controller offline, keeping the lexicographically
    best ``(flaps, changes, thresholds…)`` — i.e. never-flapping first,
    least-twitchy second, tightest bands as the tie-break."""
    from hpc_patterns_tpu.serving_plane.autoscaler import AutoscalerPolicy

    trajectory = _trajectory(records)
    if len(trajectory) < MIN_TRAJECTORY_ROUNDS:
        return None
    max_seen = max(int(r.get("replicas") or 1) for r in trajectory)
    max_replicas = max(2, max_seen)
    candidates = []
    for up_queue in (1.5, 2.0, 3.0, 4.0):
        for margin in (0.02, 0.05, 0.10):
            for cooldown in (2, 3, 4, 6):
                for window in (4, 8):
                    candidates.append(AutoscalerPolicy(
                        min_replicas=1,
                        max_replicas=max_replicas,
                        up_queue=up_queue,
                        down_queue=round(up_queue / 4.0, 6),
                        up_attainment=round(0.98 - margin, 6),
                        down_attainment=0.98,
                        cooldown_rounds=cooldown,
                        window=window,
                    ))
    best = None
    for pol in candidates:
        decisions = replay(trajectory, pol)
        flaps = flap_count(decisions)
        changes = sum(1 for d in decisions if d.action != "hold")
        key = (flaps, changes, pol.up_queue, pol.down_attainment
               - pol.up_attainment, pol.cooldown_rounds, pol.window)
        if best is None or key < best[0]:
            best = (key, pol, flaps, changes)
    _key, pol, flaps, changes = best
    return {
        "min_replicas": pol.min_replicas,
        "max_replicas": pol.max_replicas,
        "up_queue": pol.up_queue,
        "down_queue": pol.down_queue,
        "up_attainment": pol.up_attainment,
        "down_attainment": pol.down_attainment,
        "cooldown_rounds": pol.cooldown_rounds,
        "window": pol.window,
        "replay": {
            "rounds": len(trajectory),
            "flaps": flaps,
            "changes": changes,
            "candidates": len(candidates),
        },
    }


# ---------------------------------------------------------------------------
# blame: the attribution digest becomes a knob


def _segment_intervals(snaps, kinds) -> list[tuple[float, float]]:
    """Canonically-tiled ``(start, end)`` intervals of the given
    segment kinds across every request in the reqtrace snapshots —
    the overlap structure :func:`fit_blame` reads depth from."""
    from hpc_patterns_tpu.harness import reqtrace as reqtracelib

    out: list[tuple[float, float]] = []
    for snap in snaps:
        for entry in (snap.get("requests") or {}).values():
            t_submit = entry.get("t_submit")
            t_finish = entry.get("t_finish")
            if t_submit is None or t_finish is None:
                continue
            tiled, _ = reqtracelib.finalize(
                entry.get("segments") or (), t_submit, t_finish)
            out.extend((float(s0), float(s1))
                       for kind, s0, s1, _meta in tiled
                       if kind in kinds and s1 > s0)
    return sorted(out)


def fit_blame(records) -> dict[str, Any] | None:
    """Blame-driven fitting: digest the run's ``kind=reqtrace``
    records (harness/explain.py) and map the dominant p99-band
    segment to a config action. Candidates and rules (deterministic):

    - ``prefetch_wait`` dominating the pooled p99 *inter-token gap*
      band → the decode tail is paying for mid-decode churn: escalate
      the anti-thrash floor to ``BLAME_RESIDENT_ROUNDS`` (a resident
      row finishes its stint instead of paging out and paying an
      exposed pull to come back) and refit the prefetch depth from
      the wait overlap — waits that STACK (peak concurrency ≥ 2)
      mean exposed transfers piled onto one host, cap at one
      in-flight pull; waits that never overlap while rows sit parked
      mean the serializing depth IS the stall, deepen to the
      parked-row peak (bounded by ``MAX_BLAME_DEPTH``);
    - ``queued`` dominating the pooled p99 *TTFT* band → widen the
      autoscaler band: scale up at a backlog of 1 (the tail already
      proved the queue is where the time goes);
    - ``admit_wait`` dominating the pooled p99 TTFT band → recommend
      the full admission high-water (stop holding arena back from a
      tail that is waiting on it).

    Precedence is fixed, not max-share: a decode-phase stall
    mechanism outranks the TTFT candidates, because ``queued``
    dominating the TTFT band is the DEFAULT look of any saturated
    open-loop stream while a stall-dominated inter-token band is the
    rarer, sharper finding. A share below ``MIN_BLAME_SHARE`` blames
    nobody (empty actions). Returns None when the input has no
    reqtrace records at all.
    """
    from hpc_patterns_tpu.harness import explain as explainlib

    snaps = [r for r in records if r.get("kind") == "reqtrace"]
    if not snaps:
        return None
    dig = explainlib.digest(snaps, worst_n=0)
    ttft_band = dig.get("ttft_p99_band_shares") or {}
    tpot_band = dig.get("tpot_p99_band_shares") or {}
    candidates = {
        "tpot.prefetch_wait": float(tpot_band.get("prefetch_wait",
                                                  0.0)),
        "ttft.queued": float(ttft_band.get("queued", 0.0)),
        "ttft.admit_wait": float(ttft_band.get("admit_wait", 0.0)),
    }
    axis = dominant = None
    share = 0.0
    for key in ("tpot.prefetch_wait", "ttft.queued",
                "ttft.admit_wait"):
        if candidates[key] >= MIN_BLAME_SHARE:
            axis, dominant = key.split(".", 1)
            share = candidates[key]
            break
    actions: dict[str, Any] = {}
    waits: dict[str, Any] = {}
    if dominant == "prefetch_wait":
        wait_iv = _segment_intervals(snaps, ("prefetch_wait",))
        parked_iv = _segment_intervals(
            snaps, ("prefetch_wait", "swapped_out"))
        stacked = _max_concurrency(wait_iv)
        parked = _max_concurrency(parked_iv)
        actions["min_resident_rounds"] = BLAME_RESIDENT_ROUNDS
        actions["prefetch_depth"] = (
            1 if stacked >= 2
            else max(2, min(MAX_BLAME_DEPTH, parked)))
        waits = {"stacked_waits_peak": stacked,
                 "parked_rows_peak": parked}
    elif dominant == "queued":
        actions["up_queue"] = 1
    elif dominant == "admit_wait":
        actions["admit_highwater"] = 1.0
    return {
        "axis": axis,
        "dominant": dominant,
        "share": round(float(share), 6),
        "candidates": {k: round(v, 6)
                       for k, v in sorted(candidates.items())},
        "actions": actions,
        "observed": {"n_requests": int(dig.get("n") or 0),
                     "tpot_p99_stall_share": round(float(
                         dig.get("tpot_p99_stall_share") or 0.0), 6),
                     **waits},
    }


# ---------------------------------------------------------------------------
# the FittedConfig


def fit(records, *, rollups=None) -> dict[str, Any]:
    """The full FittedConfig from a run's records (+ optional rollups
    JSON from ``collect.py --rollup-out``). Pure and deterministic."""
    ladder = fit_ladder(records)
    residency = fit_residency(records)
    placement = fit_placement(records, rollups)
    autoscaler = fit_autoscaler(records)
    blame = fit_blame(records)
    # blame overrides the signal fit where both speak: the trace only
    # proves the transfer was exposed; the digest proves a request's
    # p99 PAID for it — act on the latter
    if blame and residency is not None \
            and blame["actions"].get("prefetch_depth") is not None:
        residency = dict(residency,
                         prefetch_depth=blame["actions"][
                             "prefetch_depth"])
    if blame and residency is not None \
            and blame["actions"].get("min_resident_rounds") is not None:
        residency = dict(residency,
                         min_resident_rounds=max(
                             int(residency.get(
                                 "min_resident_rounds") or 1),
                             int(blame["actions"][
                                 "min_resident_rounds"])))
    if blame and autoscaler is not None \
            and blame["actions"].get("up_queue") is not None:
        autoscaler = dict(autoscaler,
                          up_queue=min(int(autoscaler["up_queue"]),
                                       int(blame["actions"][
                                           "up_queue"])))
    return {
        "version": FITTED_VERSION,
        "kind": FITTED_KIND,
        "source": {
            "n_records": len(records),
            "n_serve_admit": sum(
                1 for r in records if r.get("kind") == "serve_admit"),
            "n_trace": sum(
                1 for r in records if r.get("kind") == "trace"),
            "n_metrics": sum(
                1 for r in records if r.get("kind") == "metrics"),
            "n_trace_merged": sum(
                1 for r in records if r.get("kind") == "trace_merged"),
            "n_plane_attainment": sum(
                1 for r in records
                if r.get("kind") == "plane_attainment"),
            "n_reqtrace": sum(
                1 for r in records if r.get("kind") == "reqtrace"),
            "rollups": bool(rollups),
        },
        "ladder": ladder,
        "residency": residency,
        "placement": placement,
        "autoscaler": autoscaler,
        "blame": blame,
    }


def fit_paths(paths, rollups_path=None) -> dict[str, Any]:
    records = read_records(paths)
    rollups = None
    if rollups_path:
        with open(rollups_path) as f:
            rollups = json.load(f)
    return fit(records, rollups=rollups)


def dumps_config(fitted: dict[str, Any]) -> str:
    """The canonical serialization: sorted keys, fixed indent, trailing
    newline — byte-identical for equal configs (the determinism pin in
    tests/test_autofit.py diffs these bytes)."""
    return json.dumps(fitted, sort_keys=True, indent=2) + "\n"


def load_fitted(path) -> dict[str, Any]:
    """Read and validate a FittedConfig emitted by this module — the
    one ingestion point every ``from_fitted`` / ``--autofit`` consumer
    routes through."""
    with open(path) as f:
        fitted = json.load(f)
    return validate_fitted(fitted)


def validate_fitted(fitted) -> dict[str, Any]:
    if not isinstance(fitted, dict):
        raise ValueError(f"fitted config must be a JSON object, got "
                         f"{type(fitted).__name__}")
    if fitted.get("kind") != FITTED_KIND:
        raise ValueError(
            f"not a fitted config (kind={fitted.get('kind')!r}, "
            f"expected {FITTED_KIND!r})")
    if fitted.get("version") != FITTED_VERSION:
        raise ValueError(
            f"fitted config version {fitted.get('version')!r} not "
            f"supported (this build reads version {FITTED_VERSION})")
    return fitted


def ladder_from(fitted, *, max_seq: int | None = None):
    """The fitted prompt ladder as engine-ready ``prompt_buckets``
    (or None when the config has no ladder section). Rungs above the
    consumer's ``max_seq`` are clamped — a ladder fitted on a bigger
    model must not make a smaller engine refuse to boot."""
    section = (fitted or {}).get("ladder")
    if not section:
        return None
    rungs = [int(b) for b in section["buckets"]]
    if max_seq is not None:
        rungs = [min(b, int(max_seq)) for b in rungs]
    rungs = sorted(set(b for b in rungs if b >= 1))
    return tuple(rungs) or None


# ---------------------------------------------------------------------------
# CLI


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m hpc_patterns_tpu.harness.autofit",
        description=__doc__.splitlines()[0])
    p.add_argument("logs", nargs="+",
                   help="RunLog JSONL files from the run to fit "
                        "(serve_admit/trace/metrics/trace_merged "
                        "records)")
    p.add_argument("--rollups", default=None,
                   help="rollups JSON from `collect.py --rollup-out` "
                        "(the cross-rank busy/bubble input)")
    p.add_argument("--emit", default=None,
                   help="write the FittedConfig JSON here (default: "
                        "print to stdout)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        fitted = fit_paths(args.logs, args.rollups)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 2
    text = dumps_config(fitted)
    if args.emit:
        Path(args.emit).write_text(text)
        sections = [k for k in ("ladder", "residency", "placement",
                                "autoscaler", "blame")
                    if fitted.get(k)]
        print(f"fitted config -> {args.emit} "
              f"(sections: {', '.join(sections) or 'none'})")
    else:
        print(text, end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
