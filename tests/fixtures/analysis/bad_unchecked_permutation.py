"""Known-bad: ppermute pair lists that never flowed through
``comm.ring.check_permutation``. A malformed permutation does not
deadlock — XLA silently zero-fills destinations with no incoming pair
and drops duplicated sources — so the job completes with wrong data."""

from jax import lax


def rotate_unchecked(x, size):
    pairs = [(i, (i + 2) % size) for i in range(size)]
    return lax.ppermute(x, "x", pairs)  # EXPECT: unchecked-permutation


def inline_pairs(x, size):
    return lax.ppermute(x, "x", [(i, i ^ 1) for i in range(size)])  # EXPECT: unchecked-permutation
