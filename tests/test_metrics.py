"""Tests for the metrics/span registry (harness/metrics.py).

The observability contract: percentiles survive JSON round-trips
through RunLog (the fixed-bucket guarantee), spans nest and attribute
wall time per phase, and the disabled registry is a true no-op —
zero records, identical timing code path (the tier-1 protection).
"""

import json
import math
import time

import pytest

from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness.metrics import (
    Histogram,
    Metrics,
    bucket_index,
    bucket_value,
)
from hpc_patterns_tpu.harness.runlog import RunLog


@pytest.fixture(autouse=True)
def _fresh_registry():
    # never leak enablement into other tests: the suite's default is
    # the disabled registry (the production default)
    yield
    metricslib.configure(enabled=False)


class TestHistogram:
    def test_bucket_layout_roundtrip(self):
        # every bucket's representative value maps back to its bucket
        for i in range(0, metricslib.N_BUCKETS):
            assert bucket_index(bucket_value(i)) == i

    def test_observe_and_percentiles(self):
        h = Histogram()
        for v in [0.001] * 50 + [0.01] * 45 + [0.1] * 5:
            h.observe(v)
        assert h.count == 100
        assert h.min == 0.001 and h.max == 0.1
        # p50 in the 1ms bucket, p95 in the 10ms bucket, p100 == max
        assert h.percentile(50) == bucket_value(bucket_index(0.001))
        assert h.percentile(95) == bucket_value(bucket_index(0.01))
        assert h.percentile(100) == 0.1

    def test_percentile_clamps_to_observed_range(self):
        # single sample: every percentile is that sample exactly (the
        # clamp to [min, max]), not the bucket midpoint
        h = Histogram()
        h.observe(0.005)
        for q in (0, 50, 100):
            assert h.percentile(q) == 0.005

    def test_empty_percentile_nan(self):
        assert math.isnan(Histogram().percentile(50))

    def test_out_of_range_values_clamp_to_end_buckets(self):
        h = Histogram()
        h.observe(1e-12)  # below the lowest decade
        h.observe(1e9)    # above the highest
        h.observe(0.0)    # nonpositive
        assert h.count == 3
        assert set(h.counts) == {0, metricslib.N_BUCKETS - 1}
        assert h.min == 0.0 and h.max == 1e9

    def test_snapshot_roundtrip_preserves_percentiles(self):
        h = Histogram()
        for v in (1e-6, 3e-6, 2e-3, 0.5, 0.5, 7.0):
            h.observe(v)
        # through actual JSON, as RunLog would write it
        back = Histogram.from_snapshot(json.loads(json.dumps(h.snapshot())))
        for q in (0, 25, 50, 75, 90, 95, 99, 100):
            assert back.percentile(q) == h.percentile(q)
        assert (back.count, back.sum, back.min, back.max) == (
            h.count, h.sum, h.min, h.max)

    def test_merge(self):
        a, b = Histogram(), Histogram()
        for v in (0.001, 0.002):
            a.observe(v)
        for v in (0.1, 0.2):
            b.observe(v)
        a.merge(b)
        assert a.count == 4
        assert a.min == 0.001 and a.max == 0.2
        assert a.sum == pytest.approx(0.303)


class TestRegistry:
    def test_counter_gauge(self):
        m = Metrics(enabled=True)
        m.counter("c").inc()
        m.counter("c").inc(4)
        m.gauge("g").set(2.0)
        m.gauge("g").set(0.5)
        snap = m.snapshot()
        assert snap["counters"]["c"] == 5
        assert snap["gauges"]["g"] == {
            "last": 0.5, "min": 0.5, "max": 2.0, "n": 2}

    def test_span_records_and_nests(self):
        m = Metrics(enabled=True)
        with m.span("outer"):
            with m.span("inner"):
                time.sleep(0.001)
        snap = m.snapshot()
        assert set(snap["histograms"]) == {"span.outer",
                                           "span.outer/inner"}
        inner = snap["histograms"]["span.outer/inner"]
        outer = snap["histograms"]["span.outer"]
        assert inner["count"] == outer["count"] == 1
        assert outer["max"] >= inner["max"] >= 0.001

    def test_span_stack_survives_exceptions(self):
        m = Metrics(enabled=True)
        with pytest.raises(RuntimeError):
            with m.span("a"):
                raise RuntimeError("boom")
        with m.span("b"):
            pass
        # "a" popped despite the exception: "b" is NOT nested under it
        assert set(m.snapshot()["histograms"]) == {"span.a", "span.b"}

    def test_disabled_registry_is_noop(self):
        m = Metrics(enabled=False)
        m.counter("c").inc()
        m.gauge("g").set(1.0)
        m.histogram("h").observe(1.0)
        with m.span("s"):
            pass
        snap = m.snapshot()
        assert snap["counters"] == {}
        assert snap["gauges"] == {}
        assert snap["histograms"] == {}

    def test_disabled_span_is_shared_nullcontext(self):
        m = Metrics(enabled=False)
        # the no-op fast path allocates nothing per call
        assert m.span("x") is m.span("y")

    def test_nonfinite_values_stay_strict_json(self):
        # a diverged loss is NaN; bare NaN tokens are invalid strict
        # JSON, so the snapshot nulls them and histograms drop them
        m = Metrics(enabled=True)
        m.gauge("loss").set(math.nan)
        m.histogram("h").observe(math.nan)
        m.histogram("h").observe(0.5)
        snap = m.snapshot()
        json.dumps(snap, allow_nan=False)  # raises on NaN/Infinity
        assert snap["gauges"]["loss"]["last"] is None
        assert snap["histograms"]["h"]["count"] == 1

    def test_configure_installs_fresh_registry(self):
        m1 = metricslib.configure(enabled=True)
        m1.counter("c").inc()
        m2 = metricslib.configure(enabled=True)
        assert metricslib.get_metrics() is m2
        assert m2.snapshot()["counters"] == {}


class TestTimingIntegration:
    def test_measure_disabled_records_nothing(self):
        from hpc_patterns_tpu.harness.timing import measure

        m = metricslib.configure(enabled=False)
        r = measure(lambda: None, repetitions=3, warmup=1)
        assert len(r.times_s) == 3
        assert m.snapshot()["histograms"] == {}

    def test_measure_enabled_reports_phases(self):
        from hpc_patterns_tpu.harness.timing import measure

        m = metricslib.configure(enabled=True)
        r = measure(lambda: time.sleep(0.0005), repetitions=4, warmup=2,
                    label="unit")
        assert len(r.times_s) == 4
        snap = m.snapshot()
        # warmup-vs-timed phase attribution + the per-rep histogram
        assert snap["histograms"]["span.unit.warmup"]["count"] == 1
        assert snap["histograms"]["span.unit.timed"]["count"] == 1
        assert snap["histograms"]["unit.rep_s"]["count"] == 4
        # the rep histogram's p100 is the slowest rep, exactly
        back = Histogram.from_snapshot(snap["histograms"]["unit.rep_s"])
        assert back.percentile(100) == max(r.times_s)

    def test_train_step_metrics_phases(self):
        from hpc_patterns_tpu.models.train import record_step_metrics

        m = metricslib.configure(enabled=True)
        record_step_metrics(0, 6.9, 2.0, 1024)   # compile step
        record_step_metrics(1, 5.0, 0.01, 1024)  # steady
        record_step_metrics(2, 4.0, 0.01, 1024)
        snap = m.snapshot()
        assert snap["counters"]["train.steps"] == 3
        assert snap["gauges"]["train.compile_s"]["last"] == 2.0
        # compile excluded from the steady-state histogram
        assert snap["histograms"]["train.step_s"]["count"] == 2
        assert snap["gauges"]["train.loss"]["last"] == 4.0

    def test_record_collective_bandwidth(self):
        from hpc_patterns_tpu.comm.communicator import (
            record_collective_bandwidth,
        )

        m = metricslib.configure(enabled=True)
        record_collective_bandwidth("allreduce.ring", 10**9, 0.5,
                                    busbw_gbps=1.75)
        snap = m.snapshot()
        assert snap["gauges"]["comm.allreduce.ring.bandwidth_gbps"][
            "last"] == pytest.approx(2.0)
        assert snap["gauges"]["comm.allreduce.ring.busbw_gbps"][
            "last"] == 1.75
        assert snap["histograms"]["comm.allreduce.ring.s"]["count"] == 1
        # disabled (and degenerate-time) calls record nothing
        m = metricslib.configure(enabled=False)
        record_collective_bandwidth("pingpong", 10**9, 0.5)
        assert m.snapshot()["gauges"] == {}


class TestRunLogIntegration:
    def test_snapshot_roundtrips_through_runlog(self, tmp_path, capsys):
        from hpc_patterns_tpu.harness import report

        m = metricslib.configure(enabled=True)
        hist = m.histogram("lat_s")
        for v in (0.001, 0.002, 0.004, 0.008, 0.5):
            hist.observe(v)
        m.counter("reqs").inc(5)
        log = RunLog(tmp_path / "run.jsonl")
        log.emit(kind="metrics", **m.snapshot())
        agg = report.aggregate(
            report.load_records([tmp_path / "run.jsonl"]))
        merged = agg["histograms"]["lat_s"]
        for q in (50, 95, 100):
            assert merged.percentile(q) == hist.percentile(q)
        assert agg["counters"]["reqs"] == 5
        capsys.readouterr()

    def test_runlog_append_mode_preserves_prior_records(self, tmp_path):
        # the harness-owns-the-log protocol: an app invoked with
        # --log-append (truncate=False) must not clobber the harness's
        # earlier records; the default truncates (one log per run)
        path = tmp_path / "shared.jsonl"
        RunLog(path).emit(kind="result", name="harness", success=True)
        RunLog(path, truncate=False).emit(kind="result", name="app",
                                          success=True)
        names = [json.loads(l)["name"]
                 for l in path.read_text().splitlines()]
        assert names == ["harness", "app"]
        RunLog(path).emit(kind="result", name="fresh", success=True)
        names = [json.loads(l)["name"]
                 for l in path.read_text().splitlines()]
        assert names == ["fresh"]
