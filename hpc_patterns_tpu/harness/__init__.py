"""Benchmark harness: timing protocol, verdict engine, config, run log.

The reference has no shared harness library — each C++ app hand-rolls its
own timing (std::chrono min-of-reps), verdict (SUCCESS/FAILURE exit codes)
and CLI (argv loops / getopt). SURVEY.md section 7 step 1 calls for
unifying them; this package is that unification.
"""

from hpc_patterns_tpu.harness.timing import TimingResult, measure, bandwidth_gbps  # noqa: F401
from hpc_patterns_tpu.harness.verdict import (  # noqa: F401
    Verdict,
    concurrency_verdict,
    correctness_verdict,
)
from hpc_patterns_tpu.harness.runlog import RunLog  # noqa: F401
from hpc_patterns_tpu.harness.metrics import (  # noqa: F401
    Metrics,
    configure as configure_metrics,
    get_metrics,
    span,
)
# harness.trace (the flight recorder) and harness.regress (the bench
# gate) are deliberately NOT re-exported here: both are `python -m`
# CLIs, and importing them in the package __init__ would make runpy
# warn about double import. Use `from hpc_patterns_tpu.harness import
# trace` directly, as report.py and the apps do.
