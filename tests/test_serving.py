"""Continuous batching (models/serving.py): every sequence admitted
through the shared-pool engine must emit exactly the tokens its
standalone paged_generate emits — regardless of what was scheduled
around it, what chunk size amortized the dispatch, or how often its
pages were recycled."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.models import TransformerConfig, init_params
from hpc_patterns_tpu.models.decode import paged_generate
from hpc_patterns_tpu.models.serving import ContinuousBatcher

BASE = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=64, dtype="float32")


def _setup(**over):
    cfg = TransformerConfig(**{**BASE, **over})
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _standalone(params, cfg, prompt, max_new):
    return np.asarray(paged_generate(
        params, jnp.asarray(prompt, jnp.int32)[None, :], cfg, max_new,
        page_size=8))[0]


def _requests(cfg, n, seed=1):
    """n requests with varied prompt lengths and budgets."""
    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(n):
        t = int(rng.choice([5, 8, 11]))
        prompt = rng.randint(0, cfg.vocab, size=t).astype(np.int32)
        reqs.append((prompt, int(rng.choice([3, 6, 9]))))
    return reqs


class TestContinuousBatching:
    @pytest.mark.parametrize("chunk", [1, 4])
    def test_every_sequence_matches_standalone(self, chunk):
        # 6 requests through 2 slots and a pool with room for ~2 rows:
        # admission waits on freed pages, rows complete at their own
        # budgets, and each output must equal standalone paged decode
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8,
                                chunk=chunk)
        reqs = _requests(cfg, 6)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        assert sorted(got) == sorted(ids)
        for sid, (prompt, max_new) in zip(ids, reqs):
            want = _standalone(params, cfg, prompt, max_new)
            np.testing.assert_array_equal(got[sid], want,
                                          err_msg=f"seq {sid}")
        # the arena drained back to empty
        assert sorted(eng.free_pages) == list(range(6))

    def test_single_slot_serializes_exactly(self):
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=1, pool_pages=3,
                                pages_per_seq=3, page_size=8, chunk=2)
        reqs = _requests(cfg, 4, seed=3)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new))

    def test_int8_pages_compose(self):
        cfg, params = _setup(kv_cache_dtype="int8")
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8, chunk=4)
        reqs = _requests(cfg, 4, seed=5)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new))

    def test_eos_truncates_like_standalone_prefix(self):
        # pick the eos id from a standalone run's interior so it WILL
        # fire mid-generation; the engine must emit exactly the prefix
        # through that first occurrence
        cfg, params = _setup()
        prompt = np.arange(5, dtype=np.int32)
        full = _standalone(params, cfg, prompt, 9)
        eos = int(full[3])
        first = int(np.argmax(full == eos))
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8, chunk=2,
                                eos_id=eos)
        sid = eng.submit(prompt, 9)
        got = eng.run()[sid]
        np.testing.assert_array_equal(got, full[:first + 1])

    def test_engine_reuse_across_runs(self):
        # a drained engine accepts a second wave: pages/slots/cursors
        # reset cleanly and the second run's outputs are exact too.
        # (True mid-run admission — new requests entering while rows
        # are generating — is covered by the 6-requests/2-slots test,
        # where 4 requests queue behind active rows.)
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8, chunk=4)
        r1 = _requests(cfg, 2, seed=7)
        ids1 = [eng.submit(p, m) for p, m in r1]
        eng.run()
        r2 = _requests(cfg, 2, seed=9)
        ids2 = [eng.submit(p, m) for p, m in r2]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids1 + ids2, r1 + r2):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new))

    @pytest.mark.parametrize("gamma", [2, 4])
    def test_draft_assisted_matches_standalone(self, gamma):
        # speculative decoding INSIDE the engine: the draft proposes,
        # the target verifies per round, rows advance 1..gamma+1 tokens
        # per dispatch at their own acceptance — and every sequence is
        # STILL token-exact vs its standalone paged decode (greedy
        # speculative == greedy target, the serving oracle)
        from hpc_patterns_tpu.models.transformer import init_params as ip

        cfg, params = _setup()
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        dparams = ip(jax.random.PRNGKey(42), dcfg)
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=8,
                                pages_per_seq=4, page_size=8,
                                draft_params=dparams, draft_cfg=dcfg,
                                gamma=gamma)
        reqs = _requests(cfg, 5, seed=11)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new),
                err_msg=f"seq {sid} gamma={gamma}")
        assert sorted(eng.free_pages) == list(range(8))

    def test_draft_assisted_self_draft_accepts_everything(self):
        # target drafting for itself: every proposal accepted, rows
        # advance gamma+1 per round, output still exact
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=8,
                                pages_per_seq=4, page_size=8,
                                draft_params=params, draft_cfg=cfg,
                                gamma=3)
        prompt = np.arange(5, dtype=np.int32)
        sid = eng.submit(prompt, 9)
        got = eng.run()[sid]
        np.testing.assert_array_equal(
            got, _standalone(params, cfg, prompt, 9))

    def test_draft_assisted_eos(self):
        cfg, params = _setup()
        prompt = np.arange(5, dtype=np.int32)
        full = _standalone(params, cfg, prompt, 9)
        eos = int(full[3])
        first = int(np.argmax(full == eos))
        eng = ContinuousBatcher(params, cfg, slots=1, pool_pages=4,
                                pages_per_seq=4, page_size=8,
                                draft_params=params, draft_cfg=cfg,
                                gamma=2, eos_id=eos)
        sid = eng.submit(prompt, 9)
        got = eng.run()[sid]
        np.testing.assert_array_equal(got, full[:first + 1])

    def test_draft_assisted_int8_matches_standalone(self):
        # all three serving levers at once: draft-assisted rounds over
        # int8 page pools — still token-exact vs standalone int8 paged
        from hpc_patterns_tpu.models.transformer import init_params as ip

        cfg, params = _setup(kv_cache_dtype="int8")
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2,
                                    "kv_cache_dtype": "int8"})
        dparams = ip(jax.random.PRNGKey(42), dcfg)
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=8,
                                pages_per_seq=4, page_size=8,
                                draft_params=dparams, draft_cfg=dcfg,
                                gamma=2)
        reqs = _requests(cfg, 4, seed=13)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new))

    def test_draft_assisted_tp_matches_standalone(self, mesh_dp_sp_tp):
        # draft-assisted rounds under tp: the engine's pools shard on
        # kv heads, draft kernel steps shard_map, the extend rides
        # GSPMD — still token-exact vs unsharded standalone
        from hpc_patterns_tpu.models.sharding import shard_params
        from hpc_patterns_tpu.models.transformer import init_params as ip

        cfg, params = _setup(n_heads=4)  # kv_heads 4, tp=2 divides
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        dparams = ip(jax.random.PRNGKey(42), dcfg)
        p_sh = shard_params(params, mesh_dp_sp_tp, cfg)
        d_sh = shard_params(dparams, mesh_dp_sp_tp, dcfg)
        eng = ContinuousBatcher(p_sh, cfg, slots=2, pool_pages=8,
                                pages_per_seq=4, page_size=8,
                                draft_params=d_sh, draft_cfg=dcfg,
                                gamma=2, mesh=mesh_dp_sp_tp)
        reqs = _requests(cfg, 3, seed=17)
        ids = [eng.submit(p, m) for p, m in reqs]
        got = eng.run()
        for sid, (prompt, max_new) in zip(ids, reqs):
            np.testing.assert_array_equal(
                got[sid], _standalone(params, cfg, prompt, max_new))

    def test_draft_guards(self):
        cfg, params = _setup()
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        from hpc_patterns_tpu.models.transformer import init_params as ip

        dparams = ip(jax.random.PRNGKey(42), dcfg)
        with pytest.raises(ValueError, match="draft_cfg"):
            ContinuousBatcher(params, cfg, slots=1, pool_pages=3,
                              pages_per_seq=3, page_size=8,
                              draft_params=dparams)

    def test_telemetry_events(self):
        # the observability hook records every admission and
        # completion with page accounting (the metrics/logging
        # subsystem applied to serving)
        cfg, params = _setup()
        events = []
        eng = ContinuousBatcher(params, cfg, slots=2, pool_pages=6,
                                pages_per_seq=3, page_size=8, chunk=2,
                                emit=lambda **kw: events.append(kw))
        reqs = _requests(cfg, 3, seed=21)
        ids = [eng.submit(p, m) for p, m in reqs]
        eng.run()
        admits = [e for e in events if e["kind"] == "serve_admit"]
        finishes = [e for e in events if e["kind"] == "serve_finish"]
        assert sorted(e["seq_id"] for e in admits) == sorted(ids)
        assert sorted(e["seq_id"] for e in finishes) == sorted(ids)
        for e, (prompt, max_new) in zip(sorted(admits,
                                               key=lambda e: e["seq_id"]),
                                        reqs):
            assert e["prompt_len"] == len(prompt)
            assert e["budget"] == max_new
        for e in finishes:
            assert e["tokens"] >= 1 and e["pages_freed"] >= 1

    def test_guards(self):
        cfg, params = _setup()
        eng = ContinuousBatcher(params, cfg, slots=1, pool_pages=2,
                                pages_per_seq=3, page_size=8)
        with pytest.raises(ValueError, match="pages_per_seq"):
            eng.submit(np.arange(20, dtype=np.int32), 20)
        with pytest.raises(ValueError, match="max_new"):
            eng.submit(np.arange(4, dtype=np.int32), 0)
        # needs 3 pages but the pool only has 2: deadlock, loudly
        eng.submit(np.arange(10, dtype=np.int32), 8)
        with pytest.raises(RuntimeError, match="deadlock"):
            eng.run()
