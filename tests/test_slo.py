"""SLO accounting (harness/slo.py): attainment math over synthetic
stats tables with hand-computable answers — goodput counts ONLY
attained requests' tokens, shed requests count against attainment with
zero tokens, percentiles are exact, and the format renders goodput
next to raw tok/s."""

import pytest

from hpc_patterns_tpu.harness import loadgen, slo


def _rec(prio, t_submit, t_first, t_finish, tokens, outcome="ok",
         preemptions=0):
    return {"priority": prio, "t_submit": t_submit, "t_first": t_first,
            "t_finish": t_finish, "tokens": tokens, "outcome": outcome,
            "preemptions": preemptions}


class TestLatencies:
    def test_ttft_and_tpot(self):
        ttft, tpot = slo.request_latencies(_rec(0, 10.0, 10.5, 14.5, 5))
        assert ttft == pytest.approx(0.5)
        assert tpot == pytest.approx(1.0)  # 4s over 4 inter-token gaps

    def test_single_token_has_no_tpot(self):
        ttft, tpot = slo.request_latencies(_rec(0, 0.0, 0.2, 0.2, 1))
        assert ttft == pytest.approx(0.2) and tpot is None

    def test_attained_rules(self):
        tight = slo.SLOTarget(ttft_s=0.1, tpot_s=0.1)
        loose = slo.SLOTarget()
        ok = _rec(0, 0.0, 0.05, 0.2, 3)  # ttft .05, tpot .075
        assert slo.attained(ok, tight)
        assert not slo.attained(_rec(0, 0.0, 0.5, 0.6, 3), tight)  # ttft
        assert not slo.attained(_rec(0, 0.0, 0.05, 1.0, 3), tight)  # tpot
        assert slo.attained(_rec(0, 0.0, 0.5, 9.0, 3), loose)
        assert not slo.attained(
            _rec(0, 0.0, None, None, 0, outcome="shed"), loose)


class TestAttainment:
    def test_goodput_counts_only_attained_tokens(self):
        targets = {0: slo.SLOTarget(ttft_s=0.1), 1: slo.SLOTarget()}
        stats = {
            1: _rec(0, 0.0, 0.05, 1.0, 10),            # attains
            2: _rec(0, 0.0, 0.50, 1.0, 10),            # blows TTFT
            3: _rec(1, 0.0, 0.30, 1.0, 20),            # no target: attains
            4: _rec(0, 0.0, None, 0.4, 0, "shed"),     # shed
        }
        rep = slo.attainment(stats, targets, wall_s=2.0)
        c0, c1 = rep["classes"][0], rep["classes"][1]
        assert c0["n"] == 3 and c0["served"] == 2 and c0["shed"] == 1
        assert c0["attained"] == 1
        assert c0["tok_s"] == pytest.approx(10.0)        # 20 tokens / 2s
        assert c0["goodput_tok_s"] == pytest.approx(5.0)  # attained only
        assert c1["attained"] == 1
        tot = rep["total"]
        assert tot["n"] == 4 and tot["shed"] == 1
        assert tot["tok_s"] == pytest.approx(20.0)
        assert tot["goodput_tok_s"] == pytest.approx(15.0)
        assert tot["attained_frac"] == pytest.approx(2 / 4)

    def test_percentiles_are_exact_not_bucketed(self):
        targets = {0: slo.SLOTarget()}
        stats = {i: _rec(0, 0.0, 0.01 * (i + 1), 1.0, 2)
                 for i in range(100)}
        rep = slo.attainment(stats, targets, wall_s=1.0)
        p = rep["classes"][0]["ttft_s"]
        assert p["p50"] == pytest.approx(0.505, abs=0.02)
        assert p["p99"] == pytest.approx(0.99 + 0.01 * 0.01, abs=0.02)

    def test_in_flight_requests_are_not_judged(self):
        rep = slo.attainment(
            {1: _rec(0, 0.0, 0.1, None, 0, outcome=None)},
            {0: slo.SLOTarget()}, wall_s=1.0)
        assert rep["classes"][0]["served"] == 0
        assert rep["total"]["tokens"] == 0

    def test_preemptions_rollup(self):
        rep = slo.attainment(
            {1: _rec(1, 0.0, 0.1, 0.5, 4, preemptions=2),
             2: _rec(1, 0.0, 0.1, 0.5, 4, preemptions=1)},
            {}, wall_s=1.0)
        assert rep["classes"][1]["preemptions"] == 3
        assert rep["total"]["preemptions"] == 3

    def test_targets_from_classes(self):
        targets = slo.targets_from_classes((
            loadgen.PriorityClass("i", 0, ttft_slo_s=0.5, tpot_slo_s=0.1),
            loadgen.PriorityClass("b", 1),
        ))
        assert targets[0] == slo.SLOTarget(ttft_s=0.5, tpot_s=0.1)
        assert targets[1] == slo.SLOTarget()


class TestFormat:
    def test_goodput_renders_next_to_raw(self):
        rep = slo.attainment(
            {1: _rec(0, 0.0, 0.05, 1.0, 10),
             2: _rec(0, 0.0, None, 0.4, 0, "shed")},
            {0: slo.SLOTarget(ttft_s=0.1)}, wall_s=2.0)
        text = slo.format_slo(rep)
        assert "goodput" in text and "tok/s raw" in text
        assert "1 shed" in text
        assert "p0" in text
