"""KV-cache decoding vs the re-run-forward oracle (§4 style: the
incremental path must reproduce the batched one exactly)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.models import TransformerConfig, forward, init_params
from hpc_patterns_tpu.models.decode import (
    decode_step,
    greedy_generate,
    init_cache,
    prefill,
)

BASE = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=32, dtype="float32")


def _setup(batch=2, seed=0, **over):
    cfg = TransformerConfig(**{**BASE, **over})
    params = init_params(jax.random.PRNGKey(seed), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(seed + 1), (batch, 8), 0,
                                cfg.vocab, jnp.int32)
    return cfg, params, prompt


def _oracle_generate(params, prompt, cfg, new_tokens):
    """Greedy decode by re-running the full forward on the growing
    sequence — O(T^2) but trivially correct."""
    seq = prompt
    out = []
    for _ in range(new_tokens):
        logits = forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


class TestPrefill:
    def test_last_logits_match_forward(self):
        cfg, params, prompt = _setup()
        logits, cache = prefill(params, prompt, cfg, max_len=16)
        want = forward(params, prompt, cfg)[:, -1]
        np.testing.assert_allclose(np.asarray(logits), np.asarray(want),
                                   atol=1e-5)
        assert len(cache["k"]) == 2  # per-layer buffers (in-place DUS)
        assert cache["k"][0].shape == (2, 4, 16, 8)

    def test_bad_lengths_rejected(self):
        cfg, params, prompt = _setup()
        with pytest.raises(ValueError, match="max_len"):
            prefill(params, prompt, cfg, max_len=4)  # < prompt
        with pytest.raises(ValueError, match="max_len"):
            prefill(params, prompt, cfg, max_len=cfg.max_seq + 1)


class TestDecodeStep:
    def test_incremental_logits_match_forward(self):
        # feed the prompt token-by-token through the cache; every step's
        # logits must equal the batched forward's logits at that position
        cfg, params, prompt = _setup()
        B, T = prompt.shape
        want = forward(params, prompt, cfg)  # (B, T, V)
        cache = init_cache(cfg, B, max_len=T)
        for t in range(T):
            logits, cache = decode_step(params, cache, jnp.int32(t),
                                        prompt[:, t], cfg)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(want[:, t]), atol=1e-4,
                err_msg=f"position {t}",
            )


class TestGenerate:
    # fast tier keeps the baseline, GQA, and rope+GQA variants; the
    # rest (MoE routing, bf16 ties, plain rope — subsumed by rope+GQA)
    # are slow-tier
    @pytest.mark.parametrize("over", [
        {},
        {"n_kv_heads": 2},                  # GQA: grouped cache attention
        # MoE: decode routes drop-free, so the oracle forward must be
        # drop-free too (capacity_factor = n_experts => capacity =
        # token count); batch 4 actually exercises same-step routing
        # contention, which a capacity-limited decode would fail
        pytest.param({"n_experts": 2, "capacity_factor": 2.0},
                     marks=pytest.mark.slow),
        # top-2 routing must serve with top-2 too (a top-1 decode of a
        # top-k-trained model silently diverges from forward)
        {"n_experts": 2, "n_experts_top_k": 2, "capacity_factor": 2.0},
        pytest.param({"dtype": "bfloat16"}, marks=pytest.mark.slow),
        # post-rope keys in the cache
        pytest.param({"pos_embed": "rope"}, marks=pytest.mark.slow),
        {"pos_embed": "rope", "n_kv_heads": 2},
    ])
    @pytest.mark.parametrize(
        "seed", [0, pytest.param(7, marks=pytest.mark.slow)]
    )
    def test_matches_oracle(self, over, seed):
        cfg, params, prompt = _setup(batch=4, seed=seed, **over)
        got = greedy_generate(params, prompt, cfg, new_tokens=6)
        want = _oracle_generate(params, prompt, cfg, 6)
        assert got.shape == (4, 6)
        if over.get("dtype") == "bfloat16":
            # bf16: tiny logit diffs between the two association orders
            # may flip an argmax tie; demand near-total agreement
            agree = float(np.mean(np.asarray(got) == np.asarray(want)))
            assert agree >= 0.9, f"agreement {agree}"
        else:
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_single_token(self):
        cfg, params, prompt = _setup()
        got = greedy_generate(params, prompt, cfg, new_tokens=1)
        want = _oracle_generate(params, prompt, cfg, 1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_length_guards(self):
        cfg, params, prompt = _setup()
        with pytest.raises(ValueError, match="new_tokens"):
            greedy_generate(params, prompt, cfg, 0)
        with pytest.raises(ValueError, match="max_seq"):
            greedy_generate(params, prompt, cfg, cfg.max_seq)


class TestShardedServing:
    def test_tp_sharded_params_decode_exactly(self, mesh_dp_sp_tp):
        # serving-side tensor parallelism is pure GSPMD: Megatron-sharded
        # params flow through the decode einsums with XLA inserting the
        # tp collectives; tokens must be bit-identical to local decode
        from hpc_patterns_tpu.models.sharding import shard_params

        # decode_attn="gather": sharded serving rides GSPMD-partitioned
        # einsums (a pallas_call does not auto-partition); tokens must
        # still match the (default, flash-kernel) local decode exactly
        cfg, params, prompt = _setup()
        want = np.asarray(greedy_generate(params, prompt, cfg, 6))
        gcfg = TransformerConfig(**{**BASE, "decode_attn": "gather"})
        p_sh = shard_params(params, mesh_dp_sp_tp, gcfg)
        got = np.asarray(jax.device_get(
            greedy_generate(p_sh, prompt, gcfg, 6)
        ))
        np.testing.assert_array_equal(got, want)

    def test_tp_flash_kernel_decodes_exactly(self, mesh_dp_sp_tp):
        # the flash decode/prefill kernels under tp: shard_map manual
        # partition over whole kv-head blocks (round-4 route) — tokens
        # must match the unsharded flash decode exactly, so tp serving
        # keeps the kernel's position-proportional cache traffic
        from hpc_patterns_tpu.models.sharding import shard_params

        cfg, params, prompt = _setup(n_heads=4, n_kv_heads=2)
        want = np.asarray(greedy_generate(params, prompt, cfg, 6))
        p_sh = shard_params(params, mesh_dp_sp_tp, cfg)
        got = np.asarray(jax.device_get(
            greedy_generate(p_sh, prompt, cfg, 6, mesh=mesh_dp_sp_tp)
        ))
        np.testing.assert_array_equal(got, want)

    def test_tp_flash_int8_cache_decodes_exactly(self, mesh_dp_sp_tp):
        # int8 KV cache composes with the tp shard_map route (the
        # per-row scales shard with their kv heads)
        from hpc_patterns_tpu.models.sharding import shard_params

        cfg, params, prompt = _setup(n_heads=4, n_kv_heads=2,
                                     kv_cache_dtype="int8")
        want = np.asarray(greedy_generate(params, prompt, cfg, 6))
        p_sh = shard_params(params, mesh_dp_sp_tp, cfg)
        got = np.asarray(jax.device_get(
            greedy_generate(p_sh, prompt, cfg, 6, mesh=mesh_dp_sp_tp)
        ))
        np.testing.assert_array_equal(got, want)

    def test_tp_paged_generate_token_exact(self, mesh_dp_sp_tp):
        # the round-4 serving wins compose: PAGED cache x tp shard_map
        # — pools kv-head-sharded, the paged kernel manual-partitioned,
        # tokens identical to the unsharded paged decode (= generate)
        from hpc_patterns_tpu.models.decode import paged_generate
        from hpc_patterns_tpu.models.sharding import shard_params

        cfg, params, prompt = _setup(n_heads=4, n_kv_heads=2)
        want = np.asarray(paged_generate(params, prompt, cfg, 6,
                                         page_size=8))
        np.testing.assert_array_equal(
            want, np.asarray(greedy_generate(params, prompt, cfg, 6)))
        p_sh = shard_params(params, mesh_dp_sp_tp, cfg)
        got = np.asarray(jax.device_get(paged_generate(
            p_sh, prompt, cfg, 6, page_size=8, mesh=mesh_dp_sp_tp)))
        np.testing.assert_array_equal(got, want)

    def test_tp_paged_int8_token_exact(self, mesh_dp_sp_tp):
        # all three serving levers at once: paged pools + int8 pages +
        # tp (scale pools shard with their kv heads)
        from hpc_patterns_tpu.models.decode import paged_generate
        from hpc_patterns_tpu.models.sharding import shard_params

        cfg, params, prompt = _setup(n_heads=4, n_kv_heads=2,
                                     kv_cache_dtype="int8")
        want = np.asarray(paged_generate(params, prompt, cfg, 6,
                                         page_size=8))
        p_sh = shard_params(params, mesh_dp_sp_tp, cfg)
        got = np.asarray(jax.device_get(paged_generate(
            p_sh, prompt, cfg, 6, page_size=8, mesh=mesh_dp_sp_tp)))
        np.testing.assert_array_equal(got, want)

    def test_tp_paged_ragged_step_token_exact(self, mesh_dp_sp_tp):
        # ragged per-sequence positions through the SHARDED paged step:
        # logits must match the unsharded ragged step exactly
        from hpc_patterns_tpu.models.decode import (
            init_paged_cache,
            paged_decode_step,
            paged_prefill,
        )
        from hpc_patterns_tpu.models.sharding import shard_params

        cfg, params, prompt = _setup(n_heads=4, n_kv_heads=2)
        cache = init_paged_cache(cfg, 2, pages_per_seq=3, page_size=8)
        _, cache = paged_prefill(params, prompt, cfg, cache, 8)
        pos = jnp.array([8, 9], jnp.int32)
        tok = jnp.array([1, 2], jnp.int32)
        want, want_cache = paged_decode_step(params, cache, pos, tok, cfg)
        p_sh = shard_params(params, mesh_dp_sp_tp, cfg)
        sc = init_paged_cache(cfg, 2, pages_per_seq=3, page_size=8)
        _, sc = paged_prefill(p_sh, prompt, cfg, sc, 8,
                              mesh=mesh_dp_sp_tp)
        got, got_cache = paged_decode_step(p_sh, sc, pos, tok, cfg,
                                           mesh=mesh_dp_sp_tp)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)
        for a, b in zip(jax.tree.leaves(got_cache),
                        jax.tree.leaves(want_cache)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)

    def test_tp_paged_rejects_indivisible_kv_heads(self, mesh_dp_sp_tp):
        from hpc_patterns_tpu.models.decode import (
            init_paged_cache,
            paged_decode_step,
        )

        cfg, params, _ = _setup(n_heads=4, n_kv_heads=1)
        cache = init_paged_cache(cfg, 2, pages_per_seq=2, page_size=8)
        with pytest.raises(ValueError, match="kv_heads"):
            paged_decode_step(params, cache, jnp.int32(0),
                              jnp.array([1, 2], jnp.int32), cfg,
                              mesh=mesh_dp_sp_tp)

    def test_tp_not_dividing_kv_heads_warns_and_falls_back(
            self, mesh_dp_sp_tp):
        # tp=2 cannot split kv_heads=1: the flash request must warn and
        # serve on the gather path, still token-exact
        from hpc_patterns_tpu.models.sharding import shard_params

        cfg, params, prompt = _setup(n_heads=4, n_kv_heads=1)
        want = np.asarray(greedy_generate(params, prompt, cfg, 6))
        p_sh = shard_params(params, mesh_dp_sp_tp, cfg)
        with pytest.warns(UserWarning, match="falls back to the gather"):
            got = np.asarray(jax.device_get(
                greedy_generate(p_sh, prompt, cfg, 6, mesh=mesh_dp_sp_tp)
            ))
        np.testing.assert_array_equal(got, want)


class TestSampling:
    def test_top_k_1_is_greedy(self):
        from hpc_patterns_tpu.models.decode import generate

        cfg, params, prompt = _setup()
        greedy = greedy_generate(params, prompt, cfg, 5)
        sampled = generate(params, prompt, cfg, 5,
                           key=jax.random.PRNGKey(3), temperature=1.0,
                           top_k=1)
        np.testing.assert_array_equal(np.asarray(greedy),
                                      np.asarray(sampled))

    def test_sampling_valid_and_key_dependent(self):
        from hpc_patterns_tpu.models.decode import generate

        cfg, params, prompt = _setup()
        a = generate(params, prompt, cfg, 8, key=jax.random.PRNGKey(0),
                     temperature=1.0)
        b = generate(params, prompt, cfg, 8, key=jax.random.PRNGKey(1),
                     temperature=1.0)
        for t in (a, b):
            arr = np.asarray(t)
            assert arr.shape == (2, 8)
            assert arr.min() >= 0 and arr.max() < cfg.vocab
        assert not np.array_equal(np.asarray(a), np.asarray(b))

    def test_sampling_needs_key(self):
        from hpc_patterns_tpu.models.decode import generate

        cfg, params, prompt = _setup()
        with pytest.raises(ValueError, match="PRNG key"):
            generate(params, prompt, cfg, 2, temperature=1.0)


class TestInt8KVCache:
    def test_flash_matches_gather_on_int8(self):
        # implementation equality to f32 rounding: the kernel folds the
        # scales AFTER its dots (lane-major), the gather path before —
        # same math, different f32 association, so compare step LOGITS
        # within tight tolerance (bitwise token equality would be a
        # latent argmax-tie flake)
        cfg, params, prompt = _setup(kv_cache_dtype="int8")
        gcfg = TransformerConfig(**{**BASE, "kv_cache_dtype": "int8",
                                    "decode_attn": "gather"})
        _, cache = prefill(params, prompt, cfg, 16)
        tok = jnp.array([1, 2], jnp.int32)
        lf, _ = decode_step(params, cache, jnp.int32(8), tok, cfg)
        lg, _ = decode_step(params, cache, jnp.int32(8), tok, gcfg)
        np.testing.assert_allclose(np.asarray(lf), np.asarray(lg),
                                   atol=1e-4)

    def test_int8_close_to_full_precision(self):
        # per-row int8 quantization: the step logits stay close to the
        # full-precision cache's (the quantization error bound), and
        # the cache is half the bytes
        cfg, params, prompt = _setup()
        qcfg = TransformerConfig(**{**BASE, "kv_cache_dtype": "int8"})
        _, cache_f = prefill(params, prompt, cfg, 16)
        _, cache_q = prefill(params, prompt, qcfg, 16)
        assert cache_q["k"][0].dtype == jnp.int8
        assert cache_f["k"][0].dtype == jnp.dtype(cfg.dtype)
        tok = jnp.array([1, 2], jnp.int32)
        lf, _ = decode_step(params, cache_f, jnp.int32(8), tok, cfg)
        lq, _ = decode_step(params, cache_q, jnp.int32(8), tok, qcfg)
        scale = np.abs(np.asarray(lf)).max()
        err = np.abs(np.asarray(lf) - np.asarray(lq)).max() / scale
        assert err < 0.05, err

    def test_int8_generate_agrees(self):
        cfg, params, prompt = _setup()
        qcfg = TransformerConfig(**{**BASE, "kv_cache_dtype": "int8"})
        want = np.asarray(greedy_generate(params, prompt, cfg, 8))
        got = np.asarray(greedy_generate(params, prompt, qcfg, 8))
        agree = float((want == got).mean())
        assert agree >= 0.75, agree  # argmax flips only near ties

    def test_bad_cache_dtype_rejected(self):
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            TransformerConfig(**{**BASE, "kv_cache_dtype": "int4"})


class TestSpeculative:
    """Greedy speculative decoding must emit EXACTLY the target's own
    greedy tokens — whatever the draft is (the acceptance rule only
    short-circuits agreement; disagreements are replaced by the
    target's token)."""

    @pytest.mark.parametrize("over", [
        {},
        {"pos_embed": "rope", "n_kv_heads": 2},  # the flagship serving
        # config: vectorized rope over chunk positions + the grouped
        # 5-axis extend einsum must stay oracle-exact too
    ])
    @pytest.mark.parametrize("gamma", [1, 3, 5])
    def test_token_identical_to_greedy(self, gamma, over):
        from hpc_patterns_tpu.models.speculative import speculative_generate

        cfg, params, prompt = _setup(batch=1, **over)
        # a DIFFERENT (smaller, differently-seeded) model drafts
        dcfg = TransformerConfig(**{**BASE, **over, "d_model": 16,
                                    "d_ff": 32, "n_layers": 1,
                                    "n_heads": 2,
                                    "n_kv_heads": min(
                                        2, over.get("n_kv_heads", 0))})
        dparams = init_params(jax.random.PRNGKey(42), dcfg)
        want = np.asarray(greedy_generate(params, prompt, cfg, 10))
        got = np.asarray(speculative_generate(
            params, cfg, dparams, dcfg, prompt, 10, gamma=gamma
        ))
        np.testing.assert_array_equal(got, want)

    def test_self_draft_is_still_exact(self):
        # target drafting for itself: maximal acceptance, same tokens
        from hpc_patterns_tpu.models.speculative import speculative_generate

        cfg, params, prompt = _setup(batch=1)
        want = np.asarray(greedy_generate(params, prompt, cfg, 8))
        got = np.asarray(speculative_generate(
            params, cfg, params, cfg, prompt, 8, gamma=4
        ))
        np.testing.assert_array_equal(got, want)

    def test_guards(self):
        from hpc_patterns_tpu.models.speculative import speculative_generate

        cfg, params, prompt = _setup(batch=2)
        with pytest.raises(ValueError, match="batch 1"):
            speculative_generate(params, cfg, params, cfg, prompt, 4)
        cfg1, params1, prompt1 = _setup(batch=1)
        bad = TransformerConfig(**{**BASE, "vocab": 32})
        with pytest.raises(ValueError, match="vocab"):
            speculative_generate(params1, cfg1, init_params(
                jax.random.PRNGKey(1), bad), bad, prompt1, 4)
        with pytest.raises(ValueError, match="PRNG key"):
            speculative_generate(params1, cfg1, params1, cfg1, prompt1, 4,
                                 temperature=0.8)


class TestSpeculativeSampling:
    """Rejection-sampling speculative decoding: the emitted tokens must
    be distributed EXACTLY as target-only sampling at the same
    temperature/top_k (Leviathan-style accept/resample). The primitive
    is pinned against the analytic law; the end-to-end path against its
    deterministic (top_k=1) limit."""

    def test_accept_resample_marginal_is_target(self):
        # fixed synthetic q (draft) and p (target) rows: over many
        # rounds, the FIRST emitted token (props[0] if accepted, else
        # the residual draw) must have marginal law exactly p_0 — the
        # defining property of the accept/resample rule
        from hpc_patterns_tpu.models.speculative import _accept_resample

        V, gamma, M = 6, 2, 20000
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.dirichlet(np.ones(V), size=gamma),
                        jnp.float32)
        p = jnp.asarray(rng.dirichlet(np.ones(V), size=gamma + 1),
                        jnp.float32)

        def draw(key):
            kq, kr = jax.random.split(key)
            props = jax.vmap(
                lambda k, row: jax.random.categorical(k, jnp.log(row))
            )(jax.random.split(kq, gamma), q).astype(jnp.int32)
            a, nxt = _accept_resample(kr, props, q, p)
            return jnp.where(a >= 1, props[0], nxt)

        keys = jax.random.split(jax.random.PRNGKey(1), M)
        firsts = np.asarray(jax.jit(jax.vmap(draw))(keys))
        emp = np.bincount(firsts, minlength=V) / M
        tv = 0.5 * np.abs(emp - np.asarray(p[0])).sum()
        assert tv < 0.02, (tv, emp, np.asarray(p[0]))

    def test_accept_resample_bonus_row_when_draft_matches(self):
        # q == p rows: every proposal accepts (ratio 1), the residual is
        # empty, and the closing token must fall back to a draw from the
        # bonus row p_gamma
        from hpc_patterns_tpu.models.speculative import _accept_resample

        V, gamma, M = 6, 2, 20000
        rng = np.random.default_rng(2)
        p = jnp.asarray(rng.dirichlet(np.ones(V), size=gamma + 1),
                        jnp.float32)
        q = p[:gamma]

        def draw(key):
            kq, kr = jax.random.split(key)
            props = jax.vmap(
                lambda k, row: jax.random.categorical(k, jnp.log(row))
            )(jax.random.split(kq, gamma), q).astype(jnp.int32)
            a, nxt = _accept_resample(kr, props, q, p)
            return a, nxt

        keys = jax.random.split(jax.random.PRNGKey(3), M)
        a, nxt = jax.jit(jax.vmap(draw))(keys)
        assert int(np.asarray(a).min()) == gamma  # all accepted, always
        emp = np.bincount(np.asarray(nxt), minlength=V) / M
        tv = 0.5 * np.abs(emp - np.asarray(p[gamma])).sum()
        assert tv < 0.02, tv

    def test_top_k_1_sampling_equals_greedy(self):
        # top_k=1 collapses both warped distributions to the argmax
        # point mass: the sampling path must emit exactly the greedy
        # speculative (= greedy target) tokens, end to end
        from hpc_patterns_tpu.models.speculative import speculative_generate

        cfg, params, prompt = _setup(batch=1)
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        dparams = init_params(jax.random.PRNGKey(42), dcfg)
        want = np.asarray(greedy_generate(params, prompt, cfg, 10))
        got = np.asarray(speculative_generate(
            params, cfg, dparams, dcfg, prompt, 10, gamma=3,
            key=jax.random.PRNGKey(7), temperature=0.9, top_k=1,
        ))
        np.testing.assert_array_equal(got, want)

    def test_batched_sampling_rows_run_independently(self):
        # B=2 sampled rows: finite tokens in range, and each row equals
        # its per-sequence call with the same per-row key fold
        from hpc_patterns_tpu.models.speculative import (
            speculative_generate_batched,
        )

        cfg, params, prompt = _setup(batch=2)
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        dparams = init_params(jax.random.PRNGKey(42), dcfg)
        got = np.asarray(speculative_generate_batched(
            params, cfg, dparams, dcfg, prompt, 8, gamma=2,
            key=jax.random.PRNGKey(5), temperature=0.8, top_k=4,
        ))
        assert got.shape == (2, 8)
        assert got.min() >= 0 and got.max() < cfg.vocab


class TestExtendStep:
    @pytest.mark.parametrize("over", [
        {},
        {"pos_embed": "rope"},
        {"n_kv_heads": 2},
    ])
    def test_extend_matches_sequential_steps(self, over):
        # one c-token extend == c single-token decode_steps: same
        # logits at every position, same cache contents
        cfg, params, prompt = _setup(**over)
        B, T = prompt.shape
        _, cache_a = prefill(params, prompt, cfg, 16)
        _, cache_b = prefill(params, prompt, cfg, 16)
        chunk = jnp.array([[1, 2, 3], [4, 5, 6]], jnp.int32)
        from hpc_patterns_tpu.models.decode import extend_step

        le, cache_a = extend_step(params, cache_a, jnp.int32(T), chunk, cfg)
        for j in range(3):
            lj, cache_b = decode_step(params, cache_b, jnp.int32(T + j),
                                      chunk[:, j], cfg)
            np.testing.assert_allclose(np.asarray(le[:, j]),
                                       np.asarray(lj), atol=2e-4,
                                       err_msg=f"chunk position {j}")
        for a, b in zip(jax.tree.leaves(cache_a), jax.tree.leaves(cache_b)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=2e-5)


class TestSpeculativeBatched:
    def test_batched_rows_match_greedy(self):
        from hpc_patterns_tpu.models.speculative import (
            speculative_generate_batched,
        )

        cfg, params, _ = _setup(batch=1)
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        dparams = init_params(jax.random.PRNGKey(42), dcfg)
        prompts = jax.random.randint(jax.random.PRNGKey(9), (3, 8), 0,
                                     cfg.vocab, jnp.int32)
        want = np.asarray(greedy_generate(params, prompts, cfg, 10))
        got = np.asarray(speculative_generate_batched(
            params, cfg, dparams, dcfg, prompts, 10, gamma=3
        ))
        np.testing.assert_array_equal(got, want)

    def test_batched_impls_agree_greedy(self):
        # the per-row-progress ragged impl and the round-3 vmap impl
        # must emit identical greedy tokens (both == target greedy) on
        # heterogeneous rows whose acceptance rates differ — rows
        # advancing at different per-round strides is the point
        from hpc_patterns_tpu.models.speculative import (
            speculative_generate_batched,
        )

        cfg, params, _ = _setup(batch=1)
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        dparams = init_params(jax.random.PRNGKey(42), dcfg)
        # one row is the target's own prompt style, one is constant,
        # one adversarial — acceptance will differ row to row
        prompts = jnp.stack([
            jax.random.randint(jax.random.PRNGKey(9), (8,), 0,
                               cfg.vocab, jnp.int32),
            jnp.full((8,), 3, jnp.int32),
            jnp.arange(8, dtype=jnp.int32) * 7 % cfg.vocab,
        ])
        want = np.asarray(greedy_generate(params, prompts, cfg, 12))
        for impl in ("ragged", "vmap"):
            got = np.asarray(speculative_generate_batched(
                params, cfg, dparams, dcfg, prompts, 12, gamma=4,
                impl=impl))
            np.testing.assert_array_equal(got, want, err_msg=impl)

    def test_batched_ragged_sampling_in_range(self):
        from hpc_patterns_tpu.models.speculative import (
            speculative_generate_batched,
        )

        cfg, params, prompt = _setup(batch=2)
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        dparams = init_params(jax.random.PRNGKey(42), dcfg)
        got = np.asarray(speculative_generate_batched(
            params, cfg, dparams, dcfg, prompt, 8, gamma=2,
            key=jax.random.PRNGKey(5), temperature=0.8, top_k=4,
            impl="ragged"))
        assert got.shape == (2, 8)
        assert got.min() >= 0 and got.max() < cfg.vocab

    def test_batched_ragged_tp_matches_greedy(self, mesh_dp_sp_tp):
        # the ragged impl under tp: draft steps ride the shard_map
        # paged-kernel route, the ragged extend partitions via GSPMD —
        # tokens must equal unsharded target greedy exactly
        from hpc_patterns_tpu.models.sharding import shard_params
        from hpc_patterns_tpu.models.speculative import (
            speculative_generate_batched,
        )

        cfg, params, prompt = _setup(batch=2, n_heads=4, n_kv_heads=2)
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2})
        dparams = init_params(jax.random.PRNGKey(42), dcfg)
        want = np.asarray(greedy_generate(params, prompt, cfg, 8))
        p_sh = shard_params(params, mesh_dp_sp_tp, cfg)
        d_sh = shard_params(dparams, mesh_dp_sp_tp, dcfg)
        got = np.asarray(jax.device_get(speculative_generate_batched(
            p_sh, cfg, d_sh, dcfg, prompt, 8, gamma=2,
            mesh=mesh_dp_sp_tp)))
        np.testing.assert_array_equal(got, want)

    def test_batched_ragged_int8_matches_greedy(self):
        # int8 pools through the ragged impl: the paged extend
        # quantizes chunk writes and dequantizes the gather, so the
        # output must equal the target's own int8 greedy decode
        from hpc_patterns_tpu.models.speculative import (
            speculative_generate_batched,
        )

        cfg, params, prompt = _setup(batch=2, kv_cache_dtype="int8")
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2,
                                    "kv_cache_dtype": "int8"})
        dparams = init_params(jax.random.PRNGKey(42), dcfg)
        want = np.asarray(greedy_generate(params, prompt, cfg, 8))
        got = np.asarray(speculative_generate_batched(
            params, cfg, dparams, dcfg, prompt, 8, gamma=2))
        np.testing.assert_array_equal(got, want)


class TestPagedExtend:
    @pytest.mark.parametrize("over", [
        {},
        {"pos_embed": "rope"},
        {"n_kv_heads": 2},
        {"kv_cache_dtype": "int8"},
    ])
    def test_ragged_extend_matches_sequential_ragged_steps(self, over):
        # one c-token RAGGED extend == c sequential ragged paged
        # decode_steps: same logits at every chunk position, same pool
        # contents — with every row at a DIFFERENT starting length
        from hpc_patterns_tpu.models.decode import (
            init_paged_cache,
            paged_decode_step,
            paged_extend_step,
            paged_prefill,
        )

        cfg, params, prompt = _setup(**over)
        pos = jnp.array([8, 9], jnp.int32)  # row 1 one past row 0
        chunk = jnp.array([[1, 2, 3], [4, 5, 6]], jnp.int32)
        ca = init_paged_cache(cfg, 2, pages_per_seq=3, page_size=8)
        cb = init_paged_cache(cfg, 2, pages_per_seq=3, page_size=8)
        _, ca = paged_prefill(params, prompt, cfg, ca, 8)
        _, cb = paged_prefill(params, prompt, cfg, cb, 8)
        # row 1 needs its position-8 row filled before starting at 9
        _, cb = paged_decode_step(params, cb, jnp.array([12, 8],
                                                       jnp.int32),
                                  jnp.array([0, 9], jnp.int32), cfg)
        _, ca = paged_decode_step(params, ca, jnp.array([12, 8],
                                                       jnp.int32),
                                  jnp.array([0, 9], jnp.int32), cfg)
        le, ca = paged_extend_step(params, ca, pos, chunk, cfg)
        for j in range(3):
            lj, cb = paged_decode_step(params, cb, pos + j,
                                       chunk[:, j], cfg)
            np.testing.assert_allclose(np.asarray(le[:, j]),
                                       np.asarray(lj), atol=2e-5,
                                       err_msg=f"chunk position {j}")
        for a, b in zip(jax.tree.leaves(ca), jax.tree.leaves(cb)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_guards(self):
        from hpc_patterns_tpu.models.decode import (
            init_paged_cache,
            paged_extend_step,
        )

        cfg, params, _ = _setup()
        cache = init_paged_cache(cfg, 2, pages_per_seq=2, page_size=8)
        with pytest.raises(ValueError, match="capacity"):
            paged_extend_step(params, cache, jnp.array([14, 3],
                                                       jnp.int32),
                              jnp.zeros((2, 3), jnp.int32), cfg)
        with pytest.raises(ValueError, match="per-row"):
            paged_extend_step(params, cache, jnp.int32(3),
                              jnp.zeros((2, 3), jnp.int32), cfg)


class TestPagedCache:
    """Block-table (paged) KV serving: the paged kernel must reproduce
    the linear kernel exactly through ANY page permutation, and
    paged_generate must be token-identical to generate — the capacity
    lever changes allocation, never tokens."""

    def test_paged_kernel_matches_linear_permuted_table(self):
        from hpc_patterns_tpu.ops.flash_decode import (
            flash_decode_attention,
            flash_decode_paged,
        )

        B, H, Hkv, D, P, pages = 2, 4, 2, 8, 16, 4
        S = P * pages
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (B, H, D), jnp.float32)
        kc = jax.random.normal(ks[1], (B, Hkv, S, D), jnp.float32)
        vc = jax.random.normal(ks[2], (B, Hkv, S, D), jnp.float32)
        pos = jnp.int32(37)  # mid-page, pages beyond never fetched
        want = flash_decode_attention(q, kc, vc, pos)

        perm = np.random.default_rng(0).permutation(B * pages)
        table = jnp.asarray(perm.reshape(B, pages), jnp.int32)
        pool_k = jnp.zeros((B * pages, Hkv, P, D), jnp.float32)
        pool_v = jnp.zeros_like(pool_k)
        for b in range(B):
            for j in range(pages):
                pool_k = pool_k.at[perm[b * pages + j]].set(
                    kc[b, :, j * P:(j + 1) * P])
                pool_v = pool_v.at[perm[b * pages + j]].set(
                    vc[b, :, j * P:(j + 1) * P])
        got = flash_decode_paged(q, pool_k, pool_v, table, pos)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)
        # every pages_per_step unroll (1 = the round-4 one-page-per-
        # grid-step form; 3 = ragged last group; auto > pages clamps)
        # walks the same permuted table to the same numbers — scalar
        # and ragged positions both
        rpos = jnp.array([37, 52], jnp.int32)
        want_r = flash_decode_attention(q, kc, vc, jnp.int32(52))
        for u in (1, 2, 3, None):
            got_u = flash_decode_paged(q, pool_k, pool_v, table, pos,
                                       pages_per_step=u)
            np.testing.assert_allclose(np.asarray(got_u),
                                       np.asarray(want), atol=1e-6,
                                       err_msg=f"unroll={u}")
            got_ur = flash_decode_paged(q, pool_k, pool_v, table, rpos,
                                        pages_per_step=u)
            np.testing.assert_allclose(np.asarray(got_ur[0]),
                                       np.asarray(want[0]), atol=1e-6,
                                       err_msg=f"ragged row0 unroll={u}")
            np.testing.assert_allclose(np.asarray(got_ur[1]),
                                       np.asarray(want_r[1]), atol=1e-6,
                                       err_msg=f"ragged row1 unroll={u}")

    @pytest.mark.parametrize("over", [
        {},
        {"pos_embed": "rope", "n_kv_heads": 2},  # flagship serving
    ])
    def test_paged_generate_token_exact(self, over):
        from hpc_patterns_tpu.models.decode import paged_generate

        cfg, params, prompt = _setup(**over)
        want = np.asarray(greedy_generate(params, prompt, cfg, 8))
        got = np.asarray(paged_generate(params, prompt, cfg, 8,
                                        page_size=8))
        np.testing.assert_array_equal(got, want)

    def test_paged_sampling_same_draws(self):
        # same key, same warp, bitwise-identical attention: the paged
        # path must emit the SAME sampled tokens as the linear path
        from hpc_patterns_tpu.models.decode import generate, paged_generate

        cfg, params, prompt = _setup()
        key = jax.random.PRNGKey(11)
        want = np.asarray(generate(params, prompt, cfg, 8, key=key,
                                   temperature=0.9, top_k=8))
        got = np.asarray(paged_generate(params, prompt, cfg, 8,
                                        page_size=8, key=key,
                                        temperature=0.9, top_k=8))
        np.testing.assert_array_equal(got, want)

    def test_allocation_tracks_need_not_max(self):
        # the capacity contract: pages allocate for prompt+new_tokens,
        # not cfg.max_seq — at max_seq=32 and 16 needed tokens the pool
        # is half the linear cache
        from hpc_patterns_tpu.models.decode import init_paged_cache

        cfg, params, prompt = _setup()  # max_seq 32
        cache = init_paged_cache(cfg, 2, pages_per_seq=2, page_size=8)
        pool_tokens = cache["k"][0].shape[0] * cache["k"][0].shape[2]
        assert pool_tokens == 2 * 2 * 8  # B * pages * page_size
        assert pool_tokens < 2 * cfg.max_seq

    def test_guards(self):
        from hpc_patterns_tpu.models.decode import (
            init_paged_cache,
            paged_generate,
        )

        cfg, params, prompt = _setup()
        with pytest.raises(ValueError, match="pages"):
            paged_generate(params, prompt, cfg, 8, page_size=8,
                           pages_per_seq=1)
        with pytest.raises(ValueError, match="entries"):
            from hpc_patterns_tpu.ops.flash_decode import (
                flash_decode_paged,
            )

            flash_decode_paged(
                jnp.zeros((2, 4, 8)), jnp.zeros((4, 4, 8, 8)),
                jnp.zeros((4, 4, 8, 8)),
                jnp.zeros((2, 2), jnp.int32),
                jnp.zeros((3,), jnp.int32),  # ragged pos != batch
            )

    @pytest.mark.parametrize("over", [{}, {"kv_cache_dtype": "int8"}])
    def test_identity_write_path_matches_scatter(self, over):
        # the in-place DUS fast path (identity table) must produce the
        # same logits/cache as the general scatter write — for bf16 AND
        # int8 pools (the scale-pool writes have both branches too)
        from hpc_patterns_tpu.models.decode import (
            init_paged_cache,
            paged_decode_step,
            paged_prefill,
        )

        cfg, params, prompt = _setup(**over)
        cache = init_paged_cache(cfg, 2, pages_per_seq=3, page_size=8)
        _, cache = paged_prefill(params, prompt, cfg, cache, 8)
        tok = jnp.array([1, 2], jnp.int32)
        l_scatter, c_scatter = paged_decode_step(
            params, cache, jnp.int32(8), tok, cfg)
        l_dus, c_dus = paged_decode_step(
            params, cache, jnp.int32(8), tok, cfg, identity_layout=True)
        np.testing.assert_allclose(np.asarray(l_scatter),
                                   np.asarray(l_dus), atol=1e-6)
        for a, b in zip(jax.tree.leaves(c_scatter),
                        jax.tree.leaves(c_dus)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_int8_pages_match_int8_linear(self):
        # int8 pools + scale pools: the paged path must reproduce the
        # int8 LINEAR flash path exactly (same per-row quantization,
        # same lane-folded dequant math, page indirection on both the
        # values and the scales)
        from hpc_patterns_tpu.models.decode import generate, paged_generate

        cfg, params, prompt = _setup(kv_cache_dtype="int8")
        want = np.asarray(generate(params, prompt, cfg, 8))
        got = np.asarray(paged_generate(params, prompt, cfg, 8,
                                        page_size=8))
        np.testing.assert_array_equal(got, want)

    def test_undersized_pool_default_table_rejected(self):
        # a default table over an undersized pool would alias pages
        # across sequences (silent K/V clobbering): must raise
        from hpc_patterns_tpu.models.decode import init_paged_cache

        cfg, _, _ = _setup()
        with pytest.raises(ValueError, match="pool_pages"):
            init_paged_cache(cfg, 2, pages_per_seq=2, page_size=8,
                             pool_pages=2)

    def test_prompt_within_a_page_of_max_seq(self):
        # page padding must not trip prefill's max_len <= max_seq guard:
        # prompt 17 + 3 new at max_seq 20 fits, though t_pad = 32 > 20
        from hpc_patterns_tpu.models.decode import paged_generate

        cfg = TransformerConfig(**{**BASE, "max_seq": 20})
        params = init_params(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0,
                                    cfg.vocab, jnp.int32)
        want = np.asarray(greedy_generate(params, prompt, cfg, 3))
        got = np.asarray(paged_generate(params, prompt, cfg, 3,
                                        page_size=16))
        np.testing.assert_array_equal(got, want)

    def test_oversized_pool_identity_falls_back_to_scatter(self):
        # pool_pages > batch*pages_per_seq with an explicit identity
        # table: the DUS view layout would disagree with the table's
        # row numbering, so the fast path must fall through to the
        # scatter and stay token-exact
        from hpc_patterns_tpu.models.decode import (
            init_paged_cache,
            paged_decode_step,
            paged_prefill,
        )

        cfg, params, prompt = _setup()
        ident = jnp.arange(4, dtype=jnp.int32).reshape(2, 2)
        big = init_paged_cache(cfg, 2, pages_per_seq=2, page_size=8,
                               pool_pages=6, table=ident)
        exact = init_paged_cache(cfg, 2, pages_per_seq=2, page_size=8)
        _, big = paged_prefill(params, prompt, cfg, big, 8)
        _, exact = paged_prefill(params, prompt, cfg, exact, 8)
        tok = jnp.array([1, 2], jnp.int32)
        l_big, _ = paged_decode_step(params, big, jnp.int32(8), tok, cfg,
                                     identity_layout=True)
        l_exact, _ = paged_decode_step(params, exact, jnp.int32(8), tok,
                                       cfg, identity_layout=True)
        np.testing.assert_allclose(np.asarray(l_big), np.asarray(l_exact),
                                   atol=1e-6)

    def test_identity_promise_verified_for_concrete_table(self):
        # identity_layout=True with a PERMUTED concrete table over an
        # exact-size pool must raise — taking the DUS path there would
        # write to the wrong pool rows and corrupt other sequences' K/V
        from hpc_patterns_tpu.models.decode import (
            init_paged_cache,
            paged_decode_step,
        )

        cfg, params, _ = _setup()
        perm = jnp.array([[1, 0], [3, 2]], jnp.int32)
        cache = init_paged_cache(cfg, 2, pages_per_seq=2, page_size=8,
                                 table=perm)
        tok = jnp.array([1, 2], jnp.int32)
        with pytest.raises(ValueError, match="identity"):
            paged_decode_step(params, cache, jnp.int32(0), tok, cfg,
                              identity_layout=True)

    def test_past_capacity_concrete_pos_rejected(self):
        # direct (eager) callers with a concrete position past
        # pages_per_seq*page_size get the capacity guard paged_generate
        # provides — scalar and ragged forms both
        from hpc_patterns_tpu.models.decode import (
            init_paged_cache,
            paged_decode_step,
        )

        cfg, params, _ = _setup()
        cache = init_paged_cache(cfg, 2, pages_per_seq=2, page_size=8)
        tok = jnp.array([1, 2], jnp.int32)
        with pytest.raises(ValueError, match="capacity"):
            paged_decode_step(params, cache, jnp.int32(16), tok, cfg)
        with pytest.raises(ValueError, match="capacity"):
            paged_decode_step(params, cache,
                              jnp.array([3, 16], jnp.int32), tok, cfg)


class TestSpeculativeSharded:
    def test_tp_speculative_greedy_token_exact(self, mesh_dp_sp_tp):
        # speculative decoding under tp: prefills and draft steps ride
        # the shard_map flash route, the verify extend rides GSPMD —
        # tokens must equal the unsharded speculative (= plain greedy)
        from hpc_patterns_tpu.models.sharding import shard_params
        from hpc_patterns_tpu.models.speculative import speculative_generate

        cfg, params, prompt = _setup(batch=1, n_heads=4, n_kv_heads=2)
        dcfg = TransformerConfig(**{**BASE, "d_model": 16, "d_ff": 32,
                                    "n_layers": 1, "n_heads": 2,
                                    "n_kv_heads": 2})
        dparams = init_params(jax.random.PRNGKey(42), dcfg)
        want = np.asarray(greedy_generate(params, prompt, cfg, 8))
        p_sh = shard_params(params, mesh_dp_sp_tp, cfg)
        dp_sh = shard_params(dparams, mesh_dp_sp_tp, dcfg)
        got = np.asarray(jax.device_get(speculative_generate(
            p_sh, cfg, dp_sh, dcfg, prompt, 8, gamma=3,
            mesh=mesh_dp_sp_tp,
        )))
        np.testing.assert_array_equal(got, want)


class TestRaggedPaged:
    @pytest.mark.parametrize("over", [
        {},
        {"pos_embed": "rope", "n_kv_heads": 2},  # flagship serving:
        # per-row rope rotation + the GQA grid-row mapping
        # (r // hkv_per_row) both ride the ragged path
        {"kv_cache_dtype": "int8"},  # quantized + ragged: per-row
        # positions through the scale-indirected kernel path
    ])
    def test_ragged_positions_per_row_oracle(self, over):
        # RAGGED serving: two sequences at different live lengths decode
        # in ONE paged step with a (B,) position vector; each row's
        # logits must equal its own single-sequence linear-flash decode
        from hpc_patterns_tpu.models.decode import (
            init_paged_cache,
            paged_decode_step,
        )

        cfg, params, _ = _setup(**over)
        P, pages = 8, 3
        Hkv, Dh = cfg.kv_heads, cfg.head_dim
        lens = (6, 11)
        prompts = [
            jax.random.randint(jax.random.PRNGKey(10 + i), (1, n), 0,
                               cfg.vocab, jnp.int32)
            for i, n in enumerate(lens)
        ]
        tok = jnp.array([3, 5], jnp.int32)

        want = []
        lins = []
        for i, p in enumerate(prompts):
            _, lin = prefill(params, p, cfg, pages * P)
            lins.append(lin)
            logits, _ = decode_step(params, lin, jnp.int32(lens[i]),
                                    tok[i:i + 1], cfg)
            want.append(np.asarray(logits[0]))

        # shared pool: each row's prefix pages placed at the identity
        # rows (b * pages + j)
        cache = init_paged_cache(cfg, 2, pages, P)
        pools = {n: list(cache[n]) for n in cache if n != "table"}
        for l in range(cfg.n_layers):
            for b in range(2):
                for name, pool in pools.items():
                    lin_l = lins[b][name][l]
                    if lin_l.ndim == 4:  # values (1, Hkv, S, D)
                        chunks = lin_l.reshape(
                            Hkv, pages, P, Dh).transpose(1, 0, 2, 3)
                    else:  # int8 scales (1, Hkv, S) -> (pages, Hkv, 1, P)
                        chunks = lin_l.reshape(
                            Hkv, pages, P).transpose(1, 0, 2)[:, :, None, :]
                    pool[l] = pool[l].at[
                        b * pages:(b + 1) * pages].set(chunks)
        cache = {**{n: tuple(p) for n, p in pools.items()},
                 "table": cache["table"]}

        pos = jnp.asarray(lens, jnp.int32)
        got, _ = paged_decode_step(params, cache, pos, tok, cfg)
        for b in range(2):
            np.testing.assert_allclose(np.asarray(got[b]), want[b],
                                       atol=1e-5, err_msg=f"row {b}")
