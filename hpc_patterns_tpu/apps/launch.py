"""Process launcher: the ``mpirun -np N`` analog (SURVEY.md §4, C11).

The reference registers every miniapp as ``mpirun -np 4 ./app`` under
CTest (aurora.mpich.miniapps/src/CMakeLists.txt:39-50). Here the same
role is played by N local processes joined through JAX's distributed
runtime: each child gets a shared coordinator address plus its process
id via the ``HPCPAT_*`` env protocol (topology.init_distributed_from_env
— the MPI_Init analog), and ``--cpu-devices-per-proc`` K virtual CPU
devices, so an ``-np 2`` launch of the allreduce miniapp is a real
4-rank SPMD run across two OS processes with zero TPU hardware — the
multi-host communication path (cross-process collectives, cross-process
MAX timing) exercised for real, which the reference cannot do without a
GPU cluster (SURVEY.md §4's gap).

On an actual TPU pod this launcher is not needed: one process per host
is started by the pod runtime and ``jax.distributed.initialize`` reads
everything from the environment (topology.init_distributed with no
args).

Usage:
    python -m hpc_patterns_tpu.apps.launch -np 2 -- \
        python -m hpc_patterns_tpu.apps.allreduce_app -p 10

Exit 0 iff every rank exits 0 (the ctest contract); per-rank output is
echoed with a ``[r]`` prefix and a grep-able summary line closes the
run (run.sh:17-18 style). On timeout the hung ranks are named with
each one's last output line (what a deadlocked-collective debug needs
first: which rank never arrived).

Distributed flight recorder (``--trace-out merged.json``): the
launcher exports ``HPCPAT_TRACE_DIR``, every child run with
``--trace`` hands off its per-rank recorder snapshot there, and at
exit — clean, failed, or timed out — the launcher merges whatever rank
files exist into one clock-aligned Perfetto timeline with cross-rank
skew/straggler rollups (harness/collect.py, rung 4 of the
observability ladder; docs/observability.md).

Chaos runs (round 8): ``--chaos SPEC`` exports ``HPCPAT_CHAOS`` so
every child runs under the seeded fault injectors (harness/chaos.py —
straggler rank, stalled host, mid-stream worker death). A rank that
exits nonzero — killed included — lands in the rank report with its
FAULT KIND, last output line, and last collective fingerprint, and the
surviving ranks' trace files still merge (the ``trace_merged`` record
carries ``faults``). ``--retry N --retry-backoff S`` relaunches a
failed run with doubling backoff — bounded retry for transient and
injected faults.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

from hpc_patterns_tpu import topology


def build_parser():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-np", "--num-processes", type=int, default=2,
                   help="processes to launch (mpirun -np)")
    p.add_argument("--cpu-devices-per-proc", type=int, default=2,
                   help="virtual CPU devices per process "
                        "(xla_force_host_platform_device_count)")
    p.add_argument("--slices", type=int, default=0,
                   help="treat the processes as this many equal TPU "
                        "slices (sets HPCPAT_SLICE_GROUPING so "
                        "group_by_slice/--dcn-dp see an N-slice system "
                        "whose DCN axis crosses real process "
                        "boundaries); 0 = no slice override")
    p.add_argument("--port", type=int, default=0,
                   help="coordinator port (0 = pick a free one)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="per-run timeout in seconds")
    p.add_argument("--retry", type=int, default=0,
                   help="relaunch a failed run (nonzero/killed rank or "
                        "timeout) up to N more times with backoff — "
                        "bounded retry for chaos runs where a worker "
                        "death is an injected or transient fault")
    p.add_argument("--retry-backoff", type=float, default=1.0,
                   help="seconds to wait before the first retry "
                        "(doubles per attempt)")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="export HPCPAT_CHAOS=SPEC to every child — the "
                        "seeded fault injectors of harness/chaos.py "
                        "(e.g. 'straggler:rank=1,delay_ms=40' or "
                        "'die:rank=1,at=5'); the rank report records "
                        "the fault kind and partial trace sets still "
                        "merge")
    p.add_argument("--trace-out", default=None, metavar="MERGED.json",
                   help="distributed flight recorder: export the "
                        "launcher env (HPCPAT_TRACE_DIR) so every "
                        "child run with --trace hands off its per-rank "
                        "snapshot, then collect, clock-align, and "
                        "merge them into this Perfetto JSON (one pid "
                        "lane per rank, flow arrows per collective) "
                        "and print the skew/straggler rollup "
                        "(harness/collect.py)")
    p.add_argument("--trace-dir", default=None,
                   help="keep the per-rank trace files here instead of "
                        "a temporary directory (implies they survive "
                        "the run; default: tmpdir, removed on success)")
    p.add_argument("--log", default=None,
                   help="append launcher records (kind=trace_merged "
                        "under --trace-out) to this runlog JSONL; "
                        "default: <trace-out>.rollup.jsonl")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="command to launch, after --")
    return p


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env(base: dict, coord: str, nprocs: int, pid: int,
               cpu_devices: int, slices: int = 0) -> dict:
    env = topology.cpu_worker_env(base, cpu_devices)
    env[topology.ENV_COORDINATOR] = coord
    env[topology.ENV_NUM_PROCESSES] = str(nprocs)
    env[topology.ENV_PROCESS_ID] = str(pid)
    if slices:
        # contiguous equal groups of processes per slice; the SAME value
        # goes to every child so each computes the identical grouping
        mapping = ",".join(str(q * slices // nprocs) for q in range(nprocs))
        env[topology.ENV_SLICE_GROUPING] = "process:" + mapping
    # children must resolve `-m hpc_patterns_tpu...` regardless of cwd
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    paths = env.get("PYTHONPATH", "")
    if pkg_root not in paths.split(os.pathsep):
        env["PYTHONPATH"] = (
            f"{pkg_root}{os.pathsep}{paths}" if paths else pkg_root
        )
    return env


_pump = topology.pump_lines


class _LastLineTee:
    """Stdout sink that remembers the most recent non-empty line per
    rank, so the timeout path can say WHAT each hung rank last printed
    (a rank stuck compiling vs. stuck in a collective read very
    differently) without re-parsing the interleaved launcher output."""

    def __init__(self, sink, store: dict, pid: int):
        self._sink, self._store, self._pid = sink, store, pid

    def write(self, text: str) -> None:
        self._sink.write(text)
        stripped = text.strip()
        if stripped:
            self._store[self._pid] = stripped

    def flush(self) -> None:
        self._sink.flush()


def _read_sched_progress(trace_dir: str) -> dict[int, dict]:
    """Per-rank collective-fingerprint progress files
    (``rank<id>.sched.json``, written by
    ``analysis.runtime.record_collective`` on EVERY collective): the
    hang forensics. A rank stuck inside a collective never reaches its
    trace-snapshot handoff, but the fingerprint of the collective it
    entered is already on disk — so a timeout report can say which
    collective each rank is at instead of just that it hung."""
    out: dict[int, dict] = {}
    for f in sorted(Path(trace_dir).glob("rank*.sched.json")):
        try:
            rec = json.loads(f.read_text())
            out[int(rec["process_id"])] = rec
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def _fault_kind(code: int | None) -> str:
    """One rank's exit classified for the rank report: ``clean``,
    ``exit N`` (error), ``killed (SIGNAME)`` (a negative returncode —
    the mid-stream worker-death shape: SIGKILLed, OOMed, preempted),
    or ``timeout`` (never exited)."""
    if code is None:
        return "timeout"
    if code == 0:
        return "clean"
    if code < 0:
        import signal

        try:
            return f"killed ({signal.Signals(-code).name})"
        except ValueError:
            return f"killed (signal {-code})"
    return f"exit {code}"


def _harvest_traces(trace_dir: str, out: str, log: str | None,
                    nprocs: int, faults: dict | None = None) -> None:
    """Collect whatever per-rank trace files exist under ``trace_dir``
    (ALL of them after a clean run; any partial set after a timeout or
    a killed worker — the surviving ranks are still debuggable), merge
    them clock-aligned into ``out``, print the skew/straggler rollup,
    and append the ``kind=trace_merged`` record to ``log``.
    ``faults``: the per-rank fault kinds of a failed run — recorded on
    the rollup so the merged record says WHY a lane is missing."""
    from hpc_patterns_tpu.harness import collect as collectlib
    from hpc_patterns_tpu.harness.runlog import RunLog

    files = sorted(Path(trace_dir).glob("rank*.trace.json"))
    if not files:
        print(f"trace: no per-rank snapshots under {trace_dir} — did "
              "the launched command include --trace?")
        return
    if len(files) < nprocs:
        have = ", ".join(f.name for f in files)
        print(f"trace: only {len(files)}/{nprocs} rank snapshot(s) "
              f"harvested ({have}) — merging what exists")
    rollup = collectlib.collect_to_file(files, out)
    if rollup is None:
        print(f"trace: rank files under {trace_dir} held no snapshots")
        return
    if faults:
        rollup["faults"] = {str(r): k for r, k in sorted(faults.items())}
    print(collectlib.format_rollup(rollup))
    print(f"merged trace: {out} (open in Perfetto / chrome://tracing)")
    log = log or f"{out}.rollup.jsonl"
    RunLog(log, truncate=False).emit(kind="trace_merged", **rollup)


def _attempt(cmd, base_env, nprocs, args, trace_dir) -> tuple[
        list, bool, dict]:
    """One launch attempt: spawn the ranks, wait them out, print the
    timeout forensics when they hang. Returns ``(codes, timed_out,
    last_lines)`` where ``codes[pid]`` is None for a rank that never
    exited (killed after the timeout)."""
    coord = f"127.0.0.1:{args.port or _free_port()}"
    procs, pumps = [], []
    last_lines: dict[int, str] = {}
    for pid in range(nprocs):
        proc = subprocess.Popen(
            cmd,
            env=_child_env(base_env, coord, nprocs, pid,
                           args.cpu_devices_per_proc, args.slices),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        t = threading.Thread(
            target=_pump,
            args=(f"[{pid}] ", proc.stdout,
                  _LastLineTee(sys.stdout, last_lines, pid)),
            daemon=True,
        )
        t.start()
        procs.append(proc)
        pumps.append(t)

    timed_out = False
    stuck: list[int] = []
    deadline = time.monotonic() + args.timeout
    try:
        for proc in procs:
            proc.wait(timeout=max(0.0, deadline - time.monotonic()))
    except subprocess.TimeoutExpired:
        timed_out = True
        # name the hung ranks BEFORE killing them: rank id + the last
        # line each printed is the first thing a debugger wants from a
        # deadlocked collective (which rank never arrived?)
        stuck = [pid for pid, proc in enumerate(procs)
                 if proc.poll() is None]
        for proc in procs:
            proc.kill()
        for proc in procs:
            # reap the kills: un-waited children stay zombies for the
            # launcher's lifetime, and --retry would stack nprocs more
            # per timed-out attempt
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        print(f"FAILURE: timeout after {args.timeout}s — "
              f"{len(stuck)}/{nprocs} rank(s) had not exited:")
        fps = _read_sched_progress(trace_dir) if trace_dir else {}
        for pid in stuck:
            last = last_lines.get(pid, "<no output>")
            print(f"  rank {pid}: last output: {last}")
            e = fps.get(pid)
            if e:
                # the collective-schedule fingerprint: a hang now reads
                # as "rank 2 is at allreduce#17, rank 0 at
                # sendrecv_ring#17" instead of a dead tunnel
                print(f"  rank {pid}: is at {e['last']['op']}"
                      f"#{e['last']['seq']} ({e['n']} collective(s) "
                      f"issued, digest {e['digest']})")
        for pid, e in sorted(fps.items()):
            if pid not in stuck:
                print(f"  rank {pid} (exited): was at "
                      f"{e['last']['op']}#{e['last']['seq']} "
                      f"({e['n']} issued)")
    finally:
        for t in pumps:
            t.join(timeout=5)
    codes = [proc.poll() for proc in procs]
    if timed_out:
        # a killed-on-timeout rank reports None ("timeout"), not the
        # SIGKILL code of the launcher's OWN kill — by the time poll()
        # runs, the kill has been reaped and returncode reads -9, the
        # chaos worker-death signature; membership in the pre-kill
        # stuck list is what distinguishes a hang from a death
        codes = [None if pid in stuck else c
                 for pid, c in enumerate(codes)]
    return codes, timed_out, last_lines


def run(args) -> int:
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("ERROR: no command given (put it after --)")
        return 2
    nprocs = args.num_processes
    if nprocs < 1:
        print("ERROR: -np must be >= 1")
        return 2
    if args.slices and nprocs % args.slices:
        print(f"ERROR: -np {nprocs} must divide by --slices {args.slices}")
        return 2
    if args.chaos:
        # validate NOW: a typo'd chaos spec injecting nothing would
        # fake a healthy run out of a chaos scenario
        from hpc_patterns_tpu.harness import chaos as chaoslib

        try:
            chaoslib.parse(args.chaos)
        except ValueError as e:
            print(f"ERROR: bad --chaos spec: {e}")
            return 2
    # distributed-trace handoff: children see HPCPAT_TRACE_DIR and (if
    # run with --trace) write rank<id>.trace.json there at exit; the
    # path is absolute because children may chdir. Without --trace-out
    # nothing is exported and the launch is byte-identical to before.
    trace_dir = made_trace_dir = None
    if args.trace_out:
        if args.trace_dir:
            trace_dir = os.path.abspath(args.trace_dir)
            os.makedirs(trace_dir, exist_ok=True)
        else:
            trace_dir = made_trace_dir = tempfile.mkdtemp(
                prefix="hpcpat_trace_")
    elif args.trace_dir or args.log:
        print("note: --trace-dir/--log do nothing without --trace-out "
              "(the distributed-trace pipeline is off)")
    base_env = dict(os.environ)
    if trace_dir:
        base_env[topology.ENV_TRACE_DIR] = trace_dir
    if args.chaos:
        from hpc_patterns_tpu.harness import chaos as chaoslib

        base_env[chaoslib.ENV_CHAOS] = args.chaos
    attempts = max(0, args.retry) + 1
    backoff = max(0.0, args.retry_backoff)
    ok = False
    faults: dict[int, str] = {}
    try:
        for attempt in range(attempts):
            if attempt:
                print(f"retrying launch (attempt {attempt + 1}/"
                      f"{attempts}) after {backoff:.1f}s backoff")
                time.sleep(backoff)
                backoff *= 2
            if trace_dir:
                # each attempt starts clean: a prior run's (or failed
                # attempt's) rank files must not stand in for ranks
                # that crashed before writing, nor its collective
                # fingerprints leak into this attempt's hang report
                for pattern in ("rank*.trace.json", "rank*.sched.json"):
                    for stale in Path(trace_dir).glob(pattern):
                        stale.unlink()
            codes, timed_out, last_lines = _attempt(
                cmd, base_env, nprocs, args, trace_dir)
            faults = {pid: _fault_kind(c) for pid, c in enumerate(codes)}
            ok = not timed_out and all(c == 0 for c in codes)
            if timed_out:
                continue
            print(f"launch -np {nprocs}: exit codes {codes}")
            if not ok:
                # the rank report, fault-kind edition: a worker that
                # DIED mid-stream (negative returncode — SIGKILLed,
                # OOMed, chaos-injected death) is named with what
                # killed it, its last output, and the collective it
                # was at (the same forensics the timeout path prints)
                fps = (_read_sched_progress(trace_dir)
                       if trace_dir else {})
                for pid, c in enumerate(codes):
                    if c == 0:
                        continue
                    last = last_lines.get(pid, "<no output>")
                    print(f"  rank {pid}: fault: {faults[pid]} — "
                          f"last output: {last}")
                    e = fps.get(pid)
                    if e:
                        print(f"  rank {pid}: was at {e['last']['op']}"
                              f"#{e['last']['seq']} ({e['n']} "
                              f"collective(s) issued)")
            print("SUCCESS" if ok else "FAILURE")
            if ok:
                break
    finally:
        if trace_dir:
            # harvest even after a timeout or a killed worker: ranks
            # that finished (or crashed cleanly) already wrote their
            # snapshots — the partial set is the surviving evidence
            try:
                _harvest_traces(trace_dir, args.trace_out, args.log,
                                nprocs,
                                faults=None if ok else faults)
            finally:
                if made_trace_dir and ok:
                    shutil.rmtree(made_trace_dir, ignore_errors=True)
                elif made_trace_dir:
                    print(f"per-rank trace files kept: {made_trace_dir}")
    return 0 if ok else 1


def main(argv=None) -> int:
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
