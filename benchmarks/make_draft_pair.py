"""Build an ALIGNED draft/target pair for honest speculative numbers.

Round 4's speculative envelope was measured on independent random
weights — greedy acceptance inflated by degenerate repetition loops,
sampling acceptance deflated by model independence (the builder's own
caveat). This script produces the real thing:

1. generate a LEARNABLE corpus (order-1 Markov chain with a sparse,
   seeded transition table — uniform-random tokens would leave nothing
   for either model to agree about);
2. train the target on it briefly (models/train.make_train_step);
3. make the draft by LAYER TRUNCATION of the trained target (first
   draft_layers layers + the target's own embed/norm/head — the
   classic self-draft recipe) and DISTILL it: KL(target || draft) on
   corpus windows, target frozen;
4. save both checkpoints (+ META.json) for bench_speculative --pair=;
5. report the analytic acceptance diagnostics on held-out windows —
   greedy top-1 agreement and E[sum min(p_draft, p_target)] (the
   Leviathan expected acceptance under sampling) — for the aligned
   pair AND the round-4 random-draft baseline, so the table shows
   exactly what alignment buys.

Usage:
  python benchmarks/make_draft_pair.py --out=pair_dir
      [--steps=400] [--distill-steps=400] [--draft-layers=2]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp
import optax

from hpc_patterns_tpu.models import TransformerConfig, forward
from hpc_patterns_tpu.models.train import (
    init_train_state,
    make_optimizer,
    make_train_step,
)
from hpc_patterns_tpu.models.transformer import init_params
from hpc_patterns_tpu.utils.checkpoint import save_checkpoint


def arg(name, default, cast=int):
    for a in sys.argv[1:]:
        if a.startswith(f"--{name}="):
            return cast(a.split("=", 1)[1])
    return default


def markov_corpus(vocab: int, n_tokens: int, seed: int = 0,
                  branching: int = 8, draw_seed: int | None = None):
    """Order-1 Markov stream: every token has ``branching`` plausible
    successors (Zipf-ish weights). Learnable structure with entropy low
    enough that a small draft can agree with a bigger target.

    ``seed`` fixes the TRANSITION TABLE (the process); ``draw_seed``
    (default: seed) fixes the sample path — held-out data and
    benchmark prompts must come from the SAME process as training
    (same seed) but a DISJOINT path (different draw_seed), or the
    acceptance numbers are train-set figures / off-distribution."""
    rng = np.random.RandomState(seed)
    succ = rng.randint(0, vocab, size=(vocab, branching))
    w = 1.0 / np.arange(1, branching + 1)
    w /= w.sum()
    draw_rng = np.random.RandomState(
        seed if draw_seed is None else draw_seed)
    out = np.empty(n_tokens, np.int32)
    tok = draw_rng.randint(vocab)
    draws = draw_rng.choice(branching, size=n_tokens, p=w)
    for i in range(n_tokens):
        tok = succ[tok, draws[i]]
        out[i] = tok
    return out


def windows(corpus, batch, seq, rng):
    starts = rng.randint(0, len(corpus) - seq - 1, size=batch)
    return jnp.asarray(
        np.stack([corpus[s:s + seq] for s in starts]), jnp.int32)


def truncate_draft(params, cfg: TransformerConfig,
                   dcfg: TransformerConfig):
    """Draft = the target's first dcfg.n_layers layers + its embed/
    final-norm/head, verbatim (same widths — only depth shrinks)."""
    sliced = jax.tree.map(lambda a: a[:dcfg.n_layers], params["layers"])
    draft = dict(params)
    draft["layers"] = sliced
    return jax.tree.map(jnp.array, draft)


def acceptance_stats(params, cfg, dparams, dcfg, corpus, rng, *,
                     batch=8, seq=128, temp=0.8):
    """Held-out diagnostics: greedy top-1 agreement rate and the
    Leviathan expected sampling acceptance E[sum_v min(p, q)] (both
    models' next-token distributions on the same real-context rows)."""
    toks = windows(corpus, batch, seq, rng)
    lt = forward(params, toks, cfg)[:, :-1].astype(jnp.float32)
    ld = forward(dparams, toks, dcfg)[:, :-1].astype(jnp.float32)
    greedy = float(jnp.mean(jnp.argmax(lt, -1) == jnp.argmax(ld, -1)))
    p = jax.nn.softmax(lt / temp, -1)
    q = jax.nn.softmax(ld / temp, -1)
    accept = float(jnp.mean(jnp.sum(jnp.minimum(p, q), -1)))
    return greedy, accept


def main():
    on_tpu = jax.default_backend() == "tpu"
    out = arg("out", "draft_pair", str)
    steps = arg("steps", 400 if on_tpu else 30)
    dsteps = arg("distill-steps", 400 if on_tpu else 30)
    batch = arg("batch", 16 if on_tpu else 4)
    seq = arg("seq", 256 if on_tpu else 32)
    n_corpus = arg("corpus", 2_000_000 if on_tpu else 60_000)
    base = dict(
        vocab=arg("vocab", 32768 if on_tpu else 256),
        d_model=arg("d", 1024 if on_tpu else 64),
        n_heads=8 if on_tpu else 4,
        d_ff=arg("ff", 4096 if on_tpu else 128),
        dtype="bfloat16" if on_tpu else "float32",
        n_kv_heads=2 if on_tpu else 0,
        pos_embed="rope",
        max_seq=arg("max-seq", 2048 if on_tpu else 256),
    )
    cfg = TransformerConfig(**base, n_layers=arg("layers", 8 if on_tpu
                                                 else 2))
    dcfg = TransformerConfig(**base, n_layers=arg(
        "draft-layers", 2 if on_tpu else 1))

    print(f"corpus: order-1 markov, {n_corpus} tokens", flush=True)
    corpus = markov_corpus(cfg.vocab, n_corpus)
    rng = np.random.RandomState(1)

    # --- 1. train the target
    params, opt_state = init_train_state(jax.random.PRNGKey(0), cfg)
    step = make_train_step(cfg)
    t0 = time.time()
    for i in range(steps):
        loss, params, opt_state = step(params, opt_state,
                                       windows(corpus, batch, seq, rng))
        if i % max(1, steps // 5) == 0 or i == steps - 1:
            print(f"target step {i}: loss {float(loss):.4f}", flush=True)
    print(f"target trained: {time.time() - t0:.1f}s", flush=True)

    # --- 2. draft by truncation + distillation (target frozen)
    draft = truncate_draft(params, cfg, dcfg)
    opt = make_optimizer(1e-3)
    dopt = opt.init(draft)

    @jax.jit
    def distill_step(draft, dopt, toks):
        tlog = forward(params, toks, cfg).astype(jnp.float32)
        tprob = jax.nn.softmax(tlog, -1)

        def loss_fn(dp):
            dlog = forward(dp, toks, dcfg).astype(jnp.float32)
            return -jnp.mean(
                jnp.sum(tprob * jax.nn.log_softmax(dlog, -1), -1))

        loss, g = jax.value_and_grad(loss_fn)(draft)
        upd, dopt = opt.update(g, dopt, draft)
        return loss, optax.apply_updates(draft, upd), dopt

    t0 = time.time()
    for i in range(dsteps):
        dl, draft, dopt = distill_step(draft, dopt,
                                       windows(corpus, batch, seq, rng))
        if i % max(1, dsteps // 5) == 0 or i == dsteps - 1:
            print(f"distill step {i}: CE {float(dl):.4f}", flush=True)
    print(f"draft distilled: {time.time() - t0:.1f}s", flush=True)

    # --- 3. diagnostics: aligned pair vs the round-4 random baseline,
    # on a genuinely held-out path — SAME transition table (the
    # process both models learned), DISJOINT sample path (draw_seed)
    held_corpus = markov_corpus(cfg.vocab, 50_000, draw_seed=31337)
    held = np.random.RandomState(99)
    g_a, a_a = acceptance_stats(params, cfg, draft, dcfg, held_corpus,
                                held)
    rand_draft = init_params(jax.random.PRNGKey(7), dcfg)
    g_r, a_r = acceptance_stats(params, cfg, rand_draft, dcfg,
                                held_corpus, held)
    print(f"acceptance (held-out): aligned greedy-agree {g_a:.3f} "
          f"E[min(p,q)] {a_a:.3f} | random-draft greedy-agree "
          f"{g_r:.3f} E[min(p,q)] {a_r:.3f}", flush=True)

    # --- 4. save the pair
    os.makedirs(out, exist_ok=True)
    save_checkpoint(os.path.join(out, "target"), params, opt_state)
    save_checkpoint(os.path.join(out, "draft"), draft, dopt)
    meta = {
        "target_cfg": {**base, "n_layers": cfg.n_layers},
        "draft_cfg": {**base, "n_layers": dcfg.n_layers},
        "steps": steps, "distill_steps": dsteps,
        "acceptance": {"aligned_greedy": g_a, "aligned_minpq": a_a,
                       "random_greedy": g_r, "random_minpq": a_r},
        "corpus": {"kind": "markov1", "tokens": n_corpus},
    }
    with open(os.path.join(out, "META.json"), "w") as f:
        json.dump(meta, f, indent=1)
    print(f"pair saved to {out}/ (META.json has the diagnostics)")


if __name__ == "__main__":
    main()
