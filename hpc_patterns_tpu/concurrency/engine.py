"""The concurrency bench engine: run N commands under a dispatch mode,
min-of-repetitions (sycl_con.cpp:84-115 / omp_con.cpp:62-125).

Modes (reference → here):

- ``serial``       — submit+wait each command in turn, recording
  per-command times (the baseline, sycl_con.cpp:101-106)
- ``async``        — submit all, then wait all: JAX async dispatch plays
  the out-of-order queue / OpenMP ``nowait`` role
  (sycl_con.cpp:108-114, omp_con.cpp:76-99). Aliases: ``out_of_order``,
  ``in_order`` (a pool of in-order queues is still concurrent *across*
  queues), ``nowait``.
- ``threads``      — one host thread per command, each submit+wait:
  the OpenMP ``host_threads`` strategy (omp_con.cpp:67-73).

Returns per-mode totals and, for serial, per-command
:class:`~hpc_patterns_tpu.harness.timing.TimingResult`\\ s — exactly the
inputs the verdict engine needs (harness.verdict.concurrency_verdict).
"""

from __future__ import annotations

import dataclasses
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Sequence

from hpc_patterns_tpu.concurrency.commands import Command
from hpc_patterns_tpu.harness.timing import TimingResult

ALIASES = {
    "out_of_order": "async",
    "in_order": "async",
    "nowait": "async",
    "host_threads": "threads",
}
MODES = ("serial", "async", "threads")


def canonical_mode(mode: str) -> str:
    mode = ALIASES.get(mode, mode)
    if mode not in MODES:
        raise ValueError(
            f"unknown mode {mode!r}; expected {MODES} or aliases {sorted(ALIASES)}"
        )
    return mode


@dataclasses.dataclass(frozen=True)
class BenchResult:
    mode: str
    total: TimingResult
    per_command: tuple[TimingResult, ...] | None  # serial mode only

    @property
    def best_serial_total_s(self) -> float:
        """Sum of per-command minima — the reference's "best theoretical
        serial" baseline (sycl_con.cpp:117-119)."""
        if self.per_command is None:
            raise ValueError("per-command times only exist in serial mode")
        return sum(t.min_s for t in self.per_command)


def _run_serial(commands: Sequence[Command]) -> tuple[float, list[float]]:
    per = []
    t_all = time.perf_counter()
    for cmd in commands:
        t0 = time.perf_counter()
        cmd.run_blocking()
        per.append(time.perf_counter() - t0)
    return time.perf_counter() - t_all, per


def _run_async(commands: Sequence[Command]) -> float:
    t0 = time.perf_counter()
    for cmd in commands:
        cmd.submit()
    for cmd in commands:
        cmd.block()
    return time.perf_counter() - t0


def _run_threads(commands: Sequence[Command], pool: ThreadPoolExecutor) -> float:
    t0 = time.perf_counter()
    futures = [pool.submit(cmd.run_blocking) for cmd in commands]
    for f in futures:
        f.result()
    return time.perf_counter() - t0


def bench(
    mode: str,
    commands: Sequence[Command],
    *,
    repetitions: int = 10,
    warmup: int = 2,
) -> BenchResult:
    """Time ``commands`` under ``mode``: ``warmup`` untimed runs (absorbing
    XLA compiles — SURVEY.md §7 hard part (d)), then min over
    ``repetitions`` (sycl_con.cpp:114, default 10 at :182)."""
    mode = canonical_mode(mode)
    if not commands:
        raise ValueError("need at least one command")
    if repetitions < 1:
        raise ValueError("repetitions must be >= 1")
    pool = ThreadPoolExecutor(max_workers=len(commands)) if mode == "threads" else None
    try:
        totals: list[float] = []
        per: list[list[float]] = [[] for _ in commands]
        for rep in range(warmup + repetitions):
            if mode == "serial":
                total, per_cmd = _run_serial(commands)
            elif mode == "async":
                total, per_cmd = _run_async(commands), None
            else:
                total, per_cmd = _run_threads(commands, pool), None
            if rep < warmup:
                continue
            totals.append(total)
            if per_cmd is not None:
                for i, t in enumerate(per_cmd):
                    per[i].append(t)
        return BenchResult(
            mode=mode,
            total=TimingResult(tuple(totals)),
            per_command=(
                tuple(TimingResult(tuple(ts)) for ts in per)
                if mode == "serial"
                else None
            ),
        )
    finally:
        if pool is not None:
            pool.shutdown()
