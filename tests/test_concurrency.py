"""Concurrency suite tests (C1-C4, C12).

The reference's own test is performance-property-based (overlap speedup,
SURVEY.md §4.3) — inherently timing-dependent, so on the CPU test mesh we
assert *mechanics and correctness* (kernel math, command lifecycle, mode
dispatch, autotuner behavior, verdict wiring) and leave the overlap PASS
claim to real-TPU runs (bench.py / the driver).
"""

import json
import numpy as np
import pytest

import jax.numpy as jnp

from hpc_patterns_tpu.concurrency import autotune, commands, engine, kernels


class TestBusyWaitKernel:
    def test_matches_reference_recurrence(self):
        x = jnp.full((8, 128), 2.0, jnp.float32)
        got = kernels.busy_wait(x, 3)
        want = kernels.busy_wait_reference(x, 3)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)

    def test_tripcount_is_runtime_scalar_no_recompile(self):
        x = jnp.full((8, 128), 2.0, jnp.float32)
        a = kernels.busy_wait(x, 1)
        b = kernels.busy_wait(x, 5)
        # different trips must give different results (the autotuner's
        # core assumption: duration/result depend on the runtime scalar)
        assert not np.array_equal(np.asarray(a), np.asarray(b))
        assert kernels._busy_wait_call._cache_size() <= 2

    def test_compute_buffer_tileable(self):
        for n in (1, 100, 8 * 128, 10_000):
            buf = kernels.compute_buffer(n)
            assert buf.shape[1] == 128 and buf.shape[0] % 8 == 0
            assert buf.size >= n


class TestCommands:
    @pytest.mark.parametrize("kind", ["C", "M2D", "D2M"])
    def test_lifecycle(self, kind):
        cmd = commands.make_command(kind, copy_elements=1 << 10, tripcount=2)
        assert cmd.name == kind
        for _ in range(3):  # repeat submissions must do fresh work
            cmd.submit()
            cmd.block()
        assert cmd.nbytes > 0

    def test_block_before_submit_is_noop(self):
        cmd = commands.make_command("M2D", copy_elements=1 << 8)
        cmd.block()

    def test_unknown_kind(self):
        with pytest.raises(ValueError, match="unknown command"):
            commands.make_command("H2H")


class TestEngine:
    def _cmds(self):
        return [
            commands.make_command("C", tripcount=2),
            commands.make_command("M2D", copy_elements=1 << 10),
            commands.make_command("D2M", copy_elements=1 << 10),
        ]

    def test_serial_records_per_command(self):
        res = engine.bench("serial", self._cmds(), repetitions=2, warmup=1)
        assert res.mode == "serial"
        assert len(res.per_command) == 3
        assert res.best_serial_total_s > 0
        assert len(res.total.times_s) == 2

    @pytest.mark.parametrize("mode", ["async", "threads"])
    def test_concurrent_modes(self, mode):
        res = engine.bench(mode, self._cmds(), repetitions=2, warmup=1)
        assert res.per_command is None
        assert res.total.min_s > 0
        with pytest.raises(ValueError):
            res.best_serial_total_s

    @pytest.mark.parametrize(
        "alias,canonical",
        [("out_of_order", "async"), ("in_order", "async"),
         ("nowait", "async"), ("host_threads", "threads")],
    )
    def test_reference_mode_aliases(self, alias, canonical):
        assert engine.canonical_mode(alias) == canonical

    def test_unknown_mode(self):
        with pytest.raises(ValueError, match="unknown mode"):
            engine.canonical_mode("warp_speed")

    def test_empty_commands(self):
        with pytest.raises(ValueError):
            engine.bench("async", [])


class TestAutotune:
    def test_balance_shrinks_slower_direction(self):
        m2d, d2m, info = autotune.balance_copy_sizes(1 << 12, 1 << 12)
        assert m2d <= 1 << 12 and d2m <= 1 << 12
        assert min(m2d, d2m) >= 1 << 10  # floor respected
        assert info["t_m2d_s"] > 0 and info["t_d2m_s"] > 0

    def test_tune_tripcount_scales_toward_target(self):
        trip, info = autotune.tune_tripcount(
            5e-3, probe_tripcount=8, compute_elements=8 * 128
        )
        assert trip >= 1
        assert info["tripcount"] == trip
        # longer targets must not yield smaller tripcounts
        trip_big, _ = autotune.tune_tripcount(
            5e-2, probe_tripcount=8, compute_elements=8 * 128
        )
        assert trip_big >= trip / 4  # generous: timing noise on shared CI

    def test_rejects_nonpositive_target(self):
        with pytest.raises(ValueError):
            autotune.tune_tripcount(0.0)


class TestApps:
    def test_concurrency_app_serial(self, capsys):
        from hpc_patterns_tpu.apps import concurrency_app

        code = concurrency_app.main(
            ["serial", "C", "M2D", "--tripcount", "2",
             "--copy-elements", "1024", "--repetitions", "2", "--warmup", "1"]
        )
        out = capsys.readouterr().out
        assert code == 0
        assert "SUCCESS" in out

    def test_concurrency_app_async_runs_to_verdict(self, capsys):
        from hpc_patterns_tpu.apps import concurrency_app

        code = concurrency_app.main(
            ["async", "C", "M2D", "--tripcount", "2",
             "--copy-elements", "1024", "--repetitions", "2", "--warmup", "1"]
        )
        out = capsys.readouterr().out
        assert code in (0, 1)  # overlap not guaranteed on CPU interpret path
        assert ("SUCCESS" in out) or ("FAILURE" in out)
        assert "speedup=" in out

    def test_sweep_emits_summary(self, capsys, tmp_path):
        from hpc_patterns_tpu.apps import sweep

        log = tmp_path / "run.jsonl"
        sweep.main(
            ["--modes", "async", "--tripcount", "2", "--copy-elements", "1024",
             "--repetitions", "1", "--warmup", "1", "--log", str(log)]
        )
        out = capsys.readouterr().out
        assert "SUCCESS count:" in out and "FAILURE count:" in out
        assert log.exists() and log.read_text().strip()

    def test_profiling_flag_produces_trace(self, tmp_path, capsys):
        from hpc_patterns_tpu.apps import concurrency_app

        tdir = tmp_path / "trace"
        code = concurrency_app.main(
            ["async", "C", "--tripcount", "2", "--repetitions", "1",
             "--warmup", "1", "--enable_profiling", "--trace-dir", str(tdir)]
        )
        assert code in (0, 1)
        out = capsys.readouterr().out
        assert "profiler trace:" in out
        assert any(tdir.rglob("*")), "trace dir should contain artifacts"


class TestOnchipEngine:
    """run_onchip's flow, CPU-testable via stubbed measurements (the real
    kernels only time meaningfully on hardware — bench/app runs cover
    that); attribution, verdicts, and autotune wiring are logic."""

    def _drive(self, monkeypatch, tmp_path, argv, times):
        import jax.numpy as jnp

        from hpc_patterns_tpu.apps import concurrency_app
        from hpc_patterns_tpu.concurrency import pipeline
        from hpc_patterns_tpu.harness import RunLog

        monkeypatch.setattr(
            pipeline, "per_pass_seconds",
            lambda x, m, t, **kw: times[m],
        )
        monkeypatch.setattr(
            pipeline, "make_hbm_array",
            lambda *a, **kw: jnp.zeros((2, 8, 128), jnp.float32),
        )
        log_path = tmp_path / "run.jsonl"
        args = concurrency_app.build_parser().parse_args(
            [*argv, "--log", str(log_path)]
        )
        log = RunLog(str(log_path))
        mode = "serial" if argv[0] == "serial" else "async"
        code = concurrency_app.run_onchip(args, log, mode)
        records = [json.loads(line) for line in
                   log_path.read_text().splitlines()]
        return code, records

    def test_attribution_not_swapped(self, monkeypatch, tmp_path):
        # distinct baseline times: the copy must land on M2D, not C
        code, records = self._drive(
            monkeypatch, tmp_path, ["async", "C", "M2D"],
            {"dma": 10e-6, "compute": 14e-6, "serial": 24e-6,
             "overlap": 15e-6},
        )
        assert code == 0
        result = [r for r in records if r.get("kind") == "result"][-1]
        assert result["commands"] == ["M2D", "C"]
        assert result["per_command_us"] == [10.0, 14.0]
        assert result["resources"] == ["hbm", "core"]

    def test_shared_resource_pair_passes_at_unity(self, monkeypatch, tmp_path):
        # two DMA streams share HBM bandwidth: ~sum-of-times concurrent
        # time passes (floor = sum), the naive 2x bar is never applied
        code, records = self._drive(
            monkeypatch, tmp_path, ["async", "M2D", "D2M"],
            {"dma": 10e-6, "dma_out": 10e-6, "pair_serial": 21e-6,
             "pair_overlap": 19e-6},
        )
        assert code == 0

    def test_distinct_resources_demand_overlap(self, monkeypatch, tmp_path):
        # C vs copy on separate hardware: no overlap -> FAILURE
        code, _ = self._drive(
            monkeypatch, tmp_path, ["async", "C", "M2D"],
            {"dma": 10e-6, "compute": 10e-6, "serial": 20e-6,
             "overlap": 20e-6},
        )
        assert code == 1

    def test_serial_mode_skips_concurrent_measurement(self, monkeypatch,
                                                      tmp_path):
        # the overlap mode must never be measured in serial mode
        code, records = self._drive(
            monkeypatch, tmp_path, ["serial", "C", "M2D"],
            {"dma": 10e-6, "compute": 10e-6},  # no serial/overlap entries
        )
        assert code == 0

    def test_cc_pair_passes_without_overlap(self, monkeypatch, tmp_path):
        # two chains serialize on the one core: the two-chain kernel
        # takes ~2x a single chain, speedup ~1.0 vs the resource floor
        code, _ = self._drive(
            monkeypatch, tmp_path, ["async", "C", "C"],
            {"compute": 10e-6, "compute2": 21e-6},
        )
        assert code == 0


def test_balance_tripcount_clamps_runaway():
    from hpc_patterns_tpu.concurrency import pipeline

    # absurdly fast compute probe: trips must clamp, not explode
    trips, t = pipeline.balance_tripcount(
        lambda m, t: 1e-9, 1.0, "compute", 64, max_trips=4096
    )
    assert trips <= 4096
