"""Blockwise (flash) causal attention as a Pallas TPU kernel.

Standard flash-attention dataflow, TPU-shaped:

- grid = (batch·heads, Tq/BLOCK_Q, Tk/BLOCK_K): K/V stream through VMEM
  one block per grid step while the online-softmax state (m, l, acc)
  carries across the kv axis in f32 scratch — sequence length is
  HBM-bounded, not VMEM-bounded (same accumulator as
  parallel/ring_attention, which runs this dataflow *across chips*).
  Pallas auto-pipelines each step's HBM→VMEM block loads against the
  previous step's compute (the same DMA/compute overlap the concurrency
  suite measures, here for free from the grid).
- big blocks by default (512×1024): grid-step overhead amortizes over
  the MXU-shaped block matmuls (``jnp.dot(...,
  preferred_element_type=f32)``; bf16 inputs stay bf16 into the MXU).
- causal masking is in GLOBAL positions: the kernels take (q_offset,
  k_offset) scalars via scalar prefetch, so the same kernel serves the
  single-device case (offsets 0) and one ring-attention step (q at
  rank·T, the visiting K/V block at src·S). Masked entries get a finite
  -1e30 (inf-free, like ring_attention); blocks outside the causal
  triangle skip their compute via ``pl.when`` AND their HBM fetch — the
  index map clamps to the last visible block, and Pallas elides the
  repeated fetch.
- backward (Dao 2023 §B): Δ = rowsum(dO ⊙ O), then two blockwise passes
  — dQ streaming K blocks, dK/dV streaming Q blocks — recomputing P
  from the forward's saved per-row logsumexp. O(block) VMEM in both
  directions.

Two public entry points:

- :func:`flash_attention` — full softmax attention, square (Tq == Tk),
  offsets 0. Drop-in equal to parallel.ring_attention.full_attention.
- :func:`flash_attention_block` — one *partial* attention over a K/V
  block at a global offset, returning (out, lse) so partial results
  merge by logsumexp (parallel/ring_attention's flash path does this
  per ring step). Differentiable in q, k, v AND through lse: the lse
  cotangent folds into Δ (d lse/d s = P, so ds = P∘(dP − Δ + ḡ_lse)).
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# NOTE on dimension_semantics: marking grid axes 0/1 "parallel" measured
# ~10% SLOWER at T=8192 (fwd+bwd 3.13 ms vs 2.83 ms) — Mosaic's
# reordering breaks the causal index-map fetch-elision, which needs
# consecutive grid steps to revisit the same clamped K/V block. The
# default sequential walk is the fast path; do not "optimize" this.


def _causal_mask(s, q_start, k_start):
    """Mask score block ``s`` so position (i, j) survives iff the global
    key index k_start+j is at or before the global query index q_start+i.
    Shared by the forward and both backward kernels — the mask must be
    identical or the recomputed P diverges from the forward's. Offsets
    may be traced (dynamic) values."""
    q_pos = q_start + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = k_start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(k_pos <= q_pos, s, _NEG_INF)


def _kv_row(H, Hkv):
    """bh (0..B·H) → row of the kv-heads-narrow (B·Hkv, T, D) array: the
    GQA group map, head h reads kv head h // (H/Hkv). Identity-shaped
    when Hkv == H (the div/mod folds away)."""
    if Hkv == H:
        return lambda bh: bh
    group = H // Hkv
    return lambda bh: (bh // H) * Hkv + (bh % H) // group


def _kv_index_map(block_q, block_k, causal, H, Hkv):
    """kv-block index map for grid (bh, qi, ki): causal clamps ki to the
    last block visible from this query block, so every fully-future grid
    step revisits the previous block and Pallas skips its HBM fetch.
    The row map sends each q head to its (possibly shared) kv head — GQA
    streams the NARROW cache, no expanded copy in HBM."""
    row = _kv_row(H, Hkv)
    if not causal:
        return lambda bh, qi, ki, offs: (row(bh), ki, 0)

    def idx(bh, qi, ki, offs):
        q_end_g = offs[0] + (qi + 1) * block_q - 1
        last = jnp.maximum((q_end_g - offs[1]) // block_k, 0)
        return row(bh), jnp.minimum(ki, last), 0

    return idx


def _q_index_map(block_q, block_k, causal, n_q, H, Hkv):
    """q-side index map for the dK/dV grid (bkv, ki, j) where
    j = g_idx·n_q + qi enumerates every (query head of the group, query
    block) pair: row = the g_idx-th q head served by kv row bkv; causal
    clamps qi UP to the first block that can see this K block (earlier
    steps revisit it, skipping the fetch)."""
    group = H // Hkv

    def row(bkv, j):
        if group == 1:
            return bkv
        return (bkv // Hkv) * H + (bkv % Hkv) * group + j // n_q

    if not causal:
        return lambda bkv, ki, j, offs: (row(bkv, j), j % n_q, 0)

    def idx(bkv, ki, j, offs):
        k_start_g = offs[1] + ki * block_k
        first = jnp.clip((k_start_g - offs[0]) // block_q, 0, n_q - 1)
        return row(bkv, j), jnp.maximum(j % n_q, first), 0

    return idx


def _kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, *rest, scale: float,
            causal: bool, with_lse: bool):
    # grid (B·H, n_q, n_kv): K/V stream through VMEM one block per grid
    # step (no whole-sequence residency — T is bounded by HBM, not VMEM);
    # the online-softmax state (m, l, acc) carries across the kv axis in
    # scratch. offs_ref: (2,) int32 scalar-prefetch [q_offset, k_offset].
    if with_lse:
        lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        m_ref, l_ref, acc_ref = rest
    block_q, d = q_ref.shape
    block_k = k_ref.shape[0]
    ki = pl.program_id(2)
    n_kv = pl.num_programs(2)
    q_start_g = offs_ref[0] + pl.program_id(1) * block_q
    k_start_g = offs_ref[1] + ki * block_k

    @pl.when(ki == 0)
    def _():
        m_ref[:] = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
        l_ref[:] = jnp.zeros((block_q, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((block_q, d), jnp.float32)

    # causal: a K/V block fully in the future contributes nothing — its
    # compute is skipped here and its fetch was already elided by the
    # clamped index map (the streamed analog of the loop-bound skip)
    visible = (k_start_g <= q_start_g + block_q - 1) if causal else True

    @pl.when(visible)
    def _():
        # matmuls in the inputs' native dtype (bf16 stays bf16 into the
        # MXU — f32xf32 runs at a fraction of MXU rate), f32 accumulate
        # via preferred_element_type; softmax state stays f32 throughout
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_start_g, k_start_g)
        m = m_ref[:]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        rescale = jnp.exp(m - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * rescale + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * rescale + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )

    @pl.when(ki == n_kv - 1)
    def _():
        m = m_ref[:]
        l = jnp.maximum(l_ref[:], 1e-30)
        out = acc_ref[:] / l
        if causal:
            # rows with nothing visible (m never rose): out 0,
            # lse -> -1e30, matching _dense_forward
            out = jnp.where(m <= _NEG_INF * 0.5, 0.0, out)
        o_ref[:] = out.astype(o_ref.dtype)
        if with_lse:
            lse_ref[:] = m + jnp.log(l)


def _dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_acc_ref, *, scale: float, causal: bool):
    # grid (B·H, n_q, n_kv), dQ carried in scratch across the kv axis.
    # dS = P * (dO·Vᵀ − Δ); dQ = scale · dS·K, with P recomputed from the
    # saved per-row logsumexp (no (T,T) matrix ever materialized).
    block_q, d = q_ref.shape
    block_k = k_ref.shape[0]
    ki = pl.program_id(2)
    n_kv = pl.num_programs(2)
    q_start_g = offs_ref[0] + pl.program_id(1) * block_q
    k_start_g = offs_ref[1] + ki * block_k

    @pl.when(ki == 0)
    def _():
        dq_acc_ref[:] = jnp.zeros((block_q, d), jnp.float32)

    visible = (k_start_g <= q_start_g + block_q - 1) if causal else True

    @pl.when(visible)
    def _():
        # native-dtype matmul operands (see _kernel); s must be computed
        # exactly as the forward computed it or P diverges from lse
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        do = do_ref[:]
        lse = lse_ref[:]      # (BLOCK_Q, 1)
        delta = delta_ref[:]  # (BLOCK_Q, 1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_start_g, k_start_g)
        p = jnp.exp(s - lse)
        if causal:
            # dead rows have lse=-1e30, where exp(s - lse) = 1 on masked
            # entries; match _dense_backward's explicit zero
            p = jnp.where(s > _NEG_INF * 0.5, p, 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_acc_ref[:] = dq_acc_ref[:] + jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        )

    @pl.when(ki == n_kv - 1)
    def _():
        dq_ref[:] = (dq_acc_ref[:] * scale).astype(dq_ref.dtype)


def _fused_bwd_kernel(offs_ref, q_ref, do_ref, lse_ref, delta_ref, k_ref,
                      v_ref, dk_ref, dv_ref, dqp_ref, dk_acc_ref, dv_acc_ref,
                      *, scale: float, causal: bool, n_q: int):
    # Fused backward: the _dkv_kernel walk — grid (B·Hkv, n_kv,
    # group·n_q) — with ONE extra matmul per visible pair (dS·K), whose
    # result is this pair's dQ contribution, written to its own slot of
    # a (n_kv, B·H, Tq, D) partial slab and summed outside. This
    # replaces the whole separate dQ pass: the two-pass backward runs 7
    # block matmuls per visible pair (S and dP are recomputed in BOTH
    # passes), the fused one runs 5 — the theoretical-minimum FLOP count
    # (Dao 2023 §B) — at the cost of the slab's HBM round-trip (written
    # in the inputs' dtype to halve it). Causality: invisible (fully
    # future-q) steps skip compute AND the slab write; their slots are
    # never targeted (the clamped q index map points them at the first
    # visible block, whose own later step overwrites before flush), and
    # the outside sum masks never-written slots analytically.
    block_k, d = k_ref.shape
    block_q = q_ref.shape[0]
    j = pl.program_id(2)
    qi = lax.rem(j, n_q)
    q_start_g = offs_ref[0] + qi * block_q
    k_start_g = offs_ref[1] + pl.program_id(1) * block_k

    @pl.when(j == 0)
    def _():
        dk_acc_ref[:] = jnp.zeros((block_k, d), jnp.float32)
        dv_acc_ref[:] = jnp.zeros((block_k, d), jnp.float32)

    visible = (q_start_g + block_q - 1 >= k_start_g) if causal else True

    @pl.when(visible)
    def _():
        q = q_ref[:]
        do = do_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        lse = lse_ref[:]
        delta = delta_ref[:]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_start_g, k_start_g)
        p = jnp.exp(s - lse)
        if causal:
            p = jnp.where(s > _NEG_INF * 0.5, p, 0.0)
        dv_acc_ref[:] = dv_acc_ref[:] + jnp.dot(
            p.astype(do.dtype).T, do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc_ref[:] = dk_acc_ref[:] + jnp.dot(
            ds.astype(q.dtype).T, q, preferred_element_type=jnp.float32
        )
        dqp_ref[:] = (jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        ) * scale).astype(dqp_ref.dtype)

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        dk_ref[:] = (dk_acc_ref[:] * scale).astype(dk_ref.dtype)
        dv_ref[:] = dv_acc_ref[:].astype(dv_ref.dtype)


def _dkv_kernel(offs_ref, q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *, scale: float,
                causal: bool, n_q: int):
    # grid (B·Hkv, n_kv, group·n_q): axis 2 walks every (q head of this
    # kv head's group, q block) pair — j = g_idx·n_q + qi — with dK/dV
    # carried in scratch across the WHOLE axis, so GQA's cross-head
    # gradient sum happens in the same accumulator as the q-block walk.
    # dV = Pᵀ·dO; dK = scale · dSᵀ·Q. Causal: query blocks strictly
    # before this K block see none of it — skipped via pl.when.
    block_k, d = k_ref.shape
    block_q = q_ref.shape[0]
    j = pl.program_id(2)
    qi = lax.rem(j, n_q)
    q_start_g = offs_ref[0] + qi * block_q
    k_start_g = offs_ref[1] + pl.program_id(1) * block_k

    @pl.when(j == 0)
    def _():
        dk_acc_ref[:] = jnp.zeros((block_k, d), jnp.float32)
        dv_acc_ref[:] = jnp.zeros((block_k, d), jnp.float32)

    visible = (q_start_g + block_q - 1 >= k_start_g) if causal else True

    @pl.when(visible)
    def _():
        # native-dtype matmul operands (see _kernel)
        q = q_ref[:]
        do = do_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        lse = lse_ref[:]
        delta = delta_ref[:]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_start_g, k_start_g)
        p = jnp.exp(s - lse)
        if causal:
            p = jnp.where(s > _NEG_INF * 0.5, p, 0.0)
        dv_acc_ref[:] = dv_acc_ref[:] + jnp.dot(
            p.astype(do.dtype).T, do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc_ref[:] = dk_acc_ref[:] + jnp.dot(
            ds.astype(q.dtype).T, q, preferred_element_type=jnp.float32
        )

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        dk_ref[:] = (dk_acc_ref[:] * scale).astype(dk_ref.dtype)
        dv_ref[:] = dv_acc_ref[:].astype(dv_ref.dtype)


def _fit_block(block, t):
    """Pow2 block fitting, floored at the 128 lane width — shared rule
    in :mod:`hpc_patterns_tpu.ops.tiling` (streamed kernels want big
    blocks; lengths that no 128-multiple divides still fail validation
    — pad upstream)."""
    from hpc_patterns_tpu.ops.tiling import fit_block_pow2

    return fit_block_pow2(block, t)


def _resolve(Tq, Tk, D, scale, block_q, block_k, interpret, *,
             validate=True):
    """Resolve the shared per-call parameters (scale default, block
    fitting, interpret default). ``validate=False`` for the backward,
    whose shapes the forward already validated — the resolution logic
    must stay common so fwd and bwd never disagree on block sizes.

    ``block_q``/``block_k`` of None pick the defaults (512, 1024).
    These were swept on chip at training shapes (benchmarks/RESULTS.md):
    a standalone kernel microbench prefers (512, 512) at T=2048 by 26%,
    but IN SITU — inside the full train step, competing with the
    surrounding matmuls for VMEM and scheduling — (512, 1024) wins at
    every measured shape. Round 3 re-confirmed at long T: standalone
    fwd prefers (512, 2048) at T=8192 by 16% (133 vs 115 TF/s) and
    LOSES in situ (175.9 vs 172.1 ms/step). Trust the end-to-end
    number, not the microbench.
    """
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if block_q is None:
        block_q = 512
    if block_k is None:
        block_k = 1024
    block_q = _fit_block(block_q, Tq)
    block_k = _fit_block(block_k, Tk)
    if validate and (Tq % block_q or Tk % block_k):
        raise ValueError(
            f"seq ({Tq}, {Tk}) must divide by blocks ({block_q}, {block_k})"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return float(scale), block_q, block_k, interpret


def _to_kernel_layout(x):
    B, T, H, D = x.shape
    return jnp.einsum("bthd->bhtd", x).reshape(B * H, T, D)


def _expand_rows(xr, B, Hkv, group):
    """Expand kernel-layout (B·Hkv, T, D) rows to (B·H, T, D) by group
    repetition — ONLY for the dense interpret-mode mirrors; the kernels
    themselves read the narrow array through their index maps."""
    if group == 1:
        return xr
    _, T, D = xr.shape
    return jnp.repeat(
        xr.reshape(B, Hkv, T, D), group, axis=1
    ).reshape(B * Hkv * group, T, D)


def _align_vma(*arrays):
    """Bring every array to the union of their varying-mesh-axes sets
    (``lax.pvary``), so the kernels work inside ``shard_map``
    (check_vma=True) even when some inputs — e.g. the constant zero
    offsets — are replicated. Returns (arrays, union_vma). On jax
    builds without the varying-axes type machinery (no ``jax.typeof``
    — e.g. 0.4.x, where shard_map's check is ``check_rep``) there is
    nothing to align: arrays pass through with an empty vma."""
    if not hasattr(jax, "typeof"):
        return arrays, frozenset()
    vma = frozenset().union(*(jax.typeof(x).vma for x in arrays))
    out = tuple(
        lax.pcast(x, tuple(vma - jax.typeof(x).vma), to='varying') if vma - jax.typeof(x).vma
        else x
        for x in arrays
    )
    return out, vma


def _sds(shape, dtype, vma):
    """``jax.ShapeDtypeStruct`` with the varying-axes set — omitted on
    jax builds whose ShapeDtypeStruct predates the ``vma`` kwarg."""
    if hasattr(jax, "typeof"):
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _masked_scores(qr, kr, offs, scale, causal):
    """(N, Tq, Tk) scaled scores with the global causal mask — the dense
    mirror of the kernels' per-block ``_causal_mask`` walk."""
    s = jnp.einsum(
        "ntd,nsd->nts", qr.astype(jnp.float32), kr.astype(jnp.float32)
    ) * scale
    if causal:
        q_pos = offs[0] + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        k_pos = offs[1] + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
    return s


def _dense_forward(qr, kr, vr, offs, *, causal, scale, need_lse, out_dtype):
    """jnp mirror of ``_kernel`` (same clamps and dead-row semantics),
    used where Pallas interpret mode can't run — inside ``shard_map`` on
    CPU (its vma tracking rejects kernel-internal constants). Real-TPU
    execution always takes the kernel path. Numerics match the kernel
    exactly for f32 inputs; for bf16 inputs the kernel's native-dtype
    matmuls round p to bf16 where this mirror keeps f32 — equal only to
    bf16 precision."""
    s = _masked_scores(qr, kr, offs, scale, causal)
    m = s.max(-1, keepdims=True)
    p = jnp.exp(s - m) * (s > _NEG_INF / 2)  # fully-masked rows stay 0
    l = jnp.maximum(p.sum(-1, keepdims=True), 1e-30)
    outr = (
        jnp.einsum("nts,nsd->ntd", p, vr.astype(jnp.float32)) / l
    ).astype(out_dtype)
    lse = (m + jnp.log(l)) if need_lse else None
    return outr, lse


def _dense_backward(qr, kr, vr, dor, lse, delta, offs, *, causal, scale):
    """jnp mirror of ``_dq_kernel``/``_dkv_kernel`` (same P recompute from
    lse and the same Δ shift); see ``_dense_forward`` for when it runs
    and the bf16-input precision caveat."""
    s = _masked_scores(qr, kr, offs, scale, causal)
    p = jnp.exp(s - lse) * (s > _NEG_INF / 2)
    dp = jnp.einsum(
        "ntd,nsd->nts", dor.astype(jnp.float32), vr.astype(jnp.float32)
    )
    ds = p * (dp - delta)
    dq = jnp.einsum("nts,nsd->ntd", ds, kr.astype(jnp.float32)) * scale
    dk = jnp.einsum("nts,ntd->nsd", ds, qr.astype(jnp.float32)) * scale
    dv = jnp.einsum("nts,ntd->nsd", p, dor.astype(jnp.float32))
    return dq.astype(qr.dtype), dk.astype(kr.dtype), dv.astype(vr.dtype)


def _forward_impl(q, k, v, offs, *, causal, scale, block_q, block_k,
                  interpret, need_lse):
    """Shared forward. ``offs``: (1, 2) int32 [q_offset, k_offset].
    Returns (out, residuals) — residuals in kernel layout (B·H, T, D),
    lse (B·H, Tq, 1) f32; both None-lse when ``need_lse`` is False (the
    inference path skips the lse work entirely)."""
    if q.ndim != 4:
        raise ValueError(f"want (batch, seq, heads, head_dim), got {q.shape}")
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    Hkv = k.shape[2]
    if H % max(Hkv, 1) or v.shape[2] != Hkv:
        raise ValueError(
            f"kv heads {Hkv}/{v.shape[2]} must match and divide "
            f"n_heads {H} (GQA streams the narrow K/V)"
        )
    group = H // Hkv
    scale, block_q, block_k, interpret = _resolve(
        Tq, Tk, D, scale, block_q, block_k, interpret
    )

    qr, kr, vr = map(_to_kernel_layout, (q, k, v))

    kernel = functools.partial(
        _kernel, scale=scale, causal=causal, with_lse=need_lse,
    )
    # index maps see the prefetched offsets: for causal, clamp the kv
    # block index to the last visible block — consecutive clamped steps
    # revisit the same block, so Pallas elides the HBM fetch entirely
    kv_idx = _kv_index_map(block_q, block_k, causal, H, Hkv)
    blk_q = pl.BlockSpec((None, block_q, D),
                         lambda bh, qi, ki, offs: (bh, qi, 0),
                         memory_space=pltpu.VMEM)
    blk_k = pl.BlockSpec((None, block_k, D), kv_idx,
                         memory_space=pltpu.VMEM)
    (offs, qr, kr, vr), vma = _align_vma(offs, qr, kr, vr)
    if interpret and vma:
        kr_e = _expand_rows(kr, B, Hkv, group)
        vr_e = _expand_rows(vr, B, Hkv, group)
        outr, lse = _dense_forward(qr, kr_e, vr_e, offs, causal=causal,
                                   scale=scale, need_lse=need_lse,
                                   out_dtype=q.dtype)
        out = outr.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
        return out, (qr, kr, vr, outr, lse)
    out_specs = [blk_q]
    out_shape = [_sds((B * H, Tq, D), q.dtype, vma)]
    if need_lse:
        out_specs.append(
            pl.BlockSpec((None, block_q, 1),
                         lambda bh, qi, ki, offs: (bh, qi, 0),
                         memory_space=pltpu.VMEM)
        )
        out_shape.append(
            _sds((B * H, Tq, 1), jnp.float32, vma)
        )

    results = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B * H, Tq // block_q, Tk // block_k),
            in_specs=[blk_q, blk_k, blk_k],
            out_specs=tuple(out_specs),
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),   # running max m
                pltpu.VMEM((block_q, 1), jnp.float32),   # running sum l
                pltpu.VMEM((block_q, D), jnp.float32),   # output accumulator
            ],
        ),
        out_shape=tuple(out_shape),
        interpret=interpret,
    )(offs, qr, kr, vr)
    outr = results[0]
    out = outr.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)  # -> (B, Tq, H, D)
    lse = results[1] if need_lse else None
    return out, (qr, kr, vr, outr, lse)


# The fused backward materializes a (n_kv, B·H, Tq, D) partial-dQ slab;
# above this byte budget the two-pass backward (no slab, more FLOPs) is
# the memory-safe automatic choice. 1.5 GiB measured against a 16 GiB
# chip: the T=32k flagship step fits with the (512, 2048) ladder rung's
# 1.07 GiB slab but OOMs by ~270 MiB with the 2.15 GiB (1024, 1024)
# slab. Overridable per call via ``bwd``, or globally via
# HPCPAT_FLASH_BWD_SLAB_LIMIT (bytes; 0 forces two-pass).
_FUSED_SLAB_LIMIT = int(
    os.environ.get("HPCPAT_FLASH_BWD_SLAB_LIMIT", 3 << 29)
)


def _backward_impl(qr, kr, vr, outr, lse, offs, g, g_lse, *, causal, scale,
                   block_q, block_k, interpret, bwd=None,
                   block_q_bwd=None, block_k_bwd=None):
    """Shared backward. ``g``: (B, Tq, H, D) out-cotangent; ``g_lse``:
    (B, Tq, H) lse-cotangent or None. Returns (dq, dk, dv) user-layout
    (dk/dv with the narrow kv head count — the group sum happens in the
    dkv kernel's accumulator). ``bwd``: "fused" (single pass, 5 block
    matmuls + partial-dQ slab), "split" (dQ pass + dK/dV pass, 7 block
    matmuls, O(T·D) extra memory only), or None/"auto" (fused when the
    slab fits _FUSED_SLAB_LIMIT)."""
    B, Tq, H, D = g.shape
    Tk = kr.shape[1]
    Hkv = kr.shape[0] // B
    group = H // Hkv
    # the backward has its own block-size optimum: the fused kernel's
    # 5-matmul body amortizes best at (1024, 1024) (measured on chip at
    # T=8192: 135 TF/s vs 125 at the forward's (512, 1024)); callers may
    # still pin both passes via block_q_bwd/block_k_bwd. When the
    # partial-dQ slab at that shape would bust the memory budget, the
    # auto ladder steps to (512, 2048) — doubling block_k halves the
    # slab (fewer kv chunks), and block_q must drop to keep the kernel
    # inside VMEM — before giving up and going two-pass.
    # the heuristic only fires when the caller pinned NOTHING: an
    # explicit forward block_q or block_k carries into the backward
    # (the resolve below falls back to them), and block_q_bwd/block_k_bwd
    # pin the backward outright
    if block_q_bwd is None and block_q is None and block_k is None:
        block_q_bwd = 1024
        if bwd in (None, "auto", "fused") and Tk >= 4096:
            slab_at = lambda bk: (Tk // bk) * B * H * Tq * D *                 jnp.dtype(qr.dtype).itemsize
            if slab_at(1024) > _FUSED_SLAB_LIMIT:
                # take the rung whenever the (1024,1024) slab busts the
                # budget — halving the slab either fits directly or
                # halves the q-chunk count (each chunk re-streams K/V,
                # so fewer chunks beats smaller ones); (512,2048) also
                # measured FASTER standalone at long T (137 vs 133 TF/s
                # at T=16k)
                block_q_bwd, block_k_bwd = 512, 2048
    scale, block_q, block_k, interpret = _resolve(
        Tq, Tk, D, scale,
        block_q if block_q_bwd is None else block_q_bwd,
        block_k if block_k_bwd is None else block_k_bwd,
        interpret, validate=False,
    )

    dor = _to_kernel_layout(g)
    delta = jnp.sum(
        dor.astype(jnp.float32) * outr.astype(jnp.float32),
        axis=-1, keepdims=True,
    )  # (B·H, Tq, 1) — trailing unit dim keeps TPU block shapes legal
    if g_lse is not None:
        # d lse/d s = P, so the lse cotangent enters ds = P∘(dP − Δ + ḡ)
        # — i.e. it just shifts Δ.
        delta = delta - jnp.einsum("bth->bht", g_lse).reshape(B * H, Tq, 1)

    (offs, qr, kr, vr, dor, lse, delta), vma = _align_vma(
        offs, qr, kr, vr, dor, lse, delta
    )
    if interpret and vma:
        kr_e = _expand_rows(kr, B, Hkv, group)
        vr_e = _expand_rows(vr, B, Hkv, group)
        dq, dk, dv = _dense_backward(qr, kr_e, vr_e, dor, lse, delta, offs,
                                     causal=causal, scale=scale)
        if group > 1:  # fold the per-q-head contributions into kv heads
            dk = dk.reshape(B, Hkv, group, Tk, D).sum(2).reshape(-1, Tk, D)
            dv = dv.reshape(B, Hkv, group, Tk, D).sum(2).reshape(-1, Tk, D)
        back = lambda x, h, t: x.reshape(B, h, t, D).transpose(0, 2, 1, 3)
        return back(dq, H, Tq), back(dk, Hkv, Tk), back(dv, Hkv, Tk)
    if bwd not in (None, "auto", "fused", "split"):
        raise ValueError(f"bwd {bwd!r} not in (None, 'auto', 'fused', 'split')")
    n_q = Tq // block_q
    n_kv = Tk // block_k
    slab_bytes = n_kv * B * H * Tq * D * jnp.dtype(qr.dtype).itemsize
    # q-chunking: when the whole-Tq slab busts the budget, run the fused
    # kernel over static query-range chunks — each call's slab is
    # slab/nc, dK/dV accumulate across calls, and causal fetch-elision
    # means early chunks never touch their future K/V blocks (the extra
    # cost is re-streaming K/V once per chunk). This keeps the 5-matmul
    # backward available at 65k+ context where one slab cannot fit.
    n_chunks = 1
    if bwd in (None, "auto", "fused") and slab_bytes > _FUSED_SLAB_LIMIT:
        while (slab_bytes // n_chunks > _FUSED_SLAB_LIMIT
               and n_chunks < 16
               and Tq % (2 * n_chunks) == 0
               and (Tq // (2 * n_chunks)) % block_q == 0):
            n_chunks *= 2
    use_fused = bwd == "fused" or (
        bwd in (None, "auto")
        and slab_bytes // n_chunks <= _FUSED_SLAB_LIMIT
    )
    row = functools.partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    kv_idx = _kv_index_map(block_q, block_k, causal, H, Hkv)
    q_idx = _q_index_map(block_q, block_k, causal, n_q, H, Hkv)
    # grid (B·H, n_q, n_kv): q-indexed blocks follow axis 1, kv axis 2
    q_on1 = row((None, block_q, D), lambda bh, qi, ki, offs: (bh, qi, 0))
    k_on2 = row((None, block_k, D), kv_idx)
    vec_on1 = row((None, block_q, 1), lambda bh, qi, ki, offs: (bh, qi, 0))
    # grid (B·Hkv, n_kv, group·n_q): kv-indexed blocks follow axis 1,
    # the (q head of the group, q block) walk axis 2
    k_on1 = row((None, block_k, D), lambda bkv, ki, j, offs: (bkv, ki, 0))
    q_on2 = row((None, block_q, D), q_idx)
    vec_on2 = row((None, block_q, 1),
                  lambda bkv, ki, j, offs: q_idx(bkv, ki, j, offs))

    if use_fused:
        Tq_c = Tq // n_chunks
        n_q_c = Tq_c // block_q
        q_idx_c = _q_index_map(block_q, block_k, causal, n_q_c, H, Hkv)
        q_on2c = row((None, block_q, D), q_idx_c)
        vec_on2c = row((None, block_q, 1),
                       lambda bkv, ki, j, offs: q_idx_c(bkv, ki, j, offs))

        def dqp_idx(bkv, ki, j, offs):
            r, qi, _ = q_idx_c(bkv, ki, j, offs)
            return ki, r, qi, 0

        fused_call = pl.pallas_call(
            functools.partial(_fused_bwd_kernel, scale=scale, causal=causal,
                              n_q=n_q_c),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(B * Hkv, n_kv, group * n_q_c),
                in_specs=[q_on2c, q_on2c, vec_on2c, vec_on2c, k_on1, k_on1],
                out_specs=(k_on1, k_on1,
                           row((None, None, block_q, D), dqp_idx)),
                scratch_shapes=[
                    pltpu.VMEM((block_k, D), jnp.float32),
                    pltpu.VMEM((block_k, D), jnp.float32),
                ],
            ),
            out_shape=(
                _sds((B * Hkv, Tk, D), kr.dtype, vma),
                _sds((B * Hkv, Tk, D), vr.dtype, vma),
                _sds((n_kv, B * H, Tq_c, D), qr.dtype, vma),
            ),
            interpret=interpret,
        )

        dq_parts = []
        dk_acc = dv_acc = None
        for i in range(n_chunks):
            lo = i * Tq_c
            offs_i = offs + jnp.array([lo, 0], jnp.int32)
            dk_i, dv_i, dqp = fused_call(
                offs_i, qr[:, lo:lo + Tq_c], dor[:, lo:lo + Tq_c],
                lse[:, lo:lo + Tq_c], delta[:, lo:lo + Tq_c], kr, vr,
            )
            if causal:
                # a slab slot (ki, ·, t, ·) was written iff the q block
                # holding row t can see kv block ki; never-written slots
                # hold whatever HBM held (possibly NaN) — select, not
                # multiply
                q_end_g = offs_i[0] + (
                    lax.iota(jnp.int32, Tq_c) // block_q + 1
                ) * block_q - 1
                k_start_g = offs[1] + lax.iota(jnp.int32, n_kv) * block_k
                written = q_end_g[None, :] >= k_start_g[:, None]
                dqp = jnp.where(written[:, None, :, None], dqp, 0)
            dq_parts.append(dqp.astype(jnp.float32).sum(0).astype(qr.dtype))
            if dk_acc is None:
                dk_acc, dv_acc = (dk_i.astype(jnp.float32),
                                  dv_i.astype(jnp.float32))
            else:
                dk_acc = dk_acc + dk_i.astype(jnp.float32)
                dv_acc = dv_acc + dv_i.astype(jnp.float32)
        dq = (dq_parts[0] if n_chunks == 1
              else jnp.concatenate(dq_parts, axis=1))
        dk = dk_acc.astype(kr.dtype)
        dv = dv_acc.astype(vr.dtype)
        back = lambda x, h, t: x.reshape(B, h, t, D).transpose(0, 2, 1, 3)
        return back(dq, H, Tq), back(dk, Hkv, Tk), back(dv, Hkv, Tk)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B * H, Tq // block_q, Tk // block_k),
            in_specs=[q_on1, k_on2, k_on2, q_on1, vec_on1, vec_on1],
            out_specs=q_on1,
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        ),
        out_shape=_sds((B * H, Tq, D), qr.dtype, vma),
        interpret=interpret,
    )(offs, qr, kr, vr, dor, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, n_q=n_q),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B * Hkv, Tk // block_k, group * n_q),
            in_specs=[q_on2, q_on2, vec_on2, vec_on2, k_on1, k_on1],
            out_specs=(k_on1, k_on1),
            scratch_shapes=[
                pltpu.VMEM((block_k, D), jnp.float32),
                pltpu.VMEM((block_k, D), jnp.float32),
            ],
        ),
        out_shape=(
            _sds((B * Hkv, Tk, D), kr.dtype, vma),
            _sds((B * Hkv, Tk, D), vr.dtype, vma),
        ),
        interpret=interpret,
    )(offs, qr, dor, lse, delta, kr, vr)

    back = lambda x, h, t: x.reshape(B, h, t, D).transpose(0, 2, 1, 3)
    return back(dq, H, Tq), back(dk, Hkv, Tk), back(dv, Hkv, Tk)


def _zero_offs():
    return jnp.zeros((2,), jnp.int32)


# ---------------------------------------------------------------- square


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10)
)
def _flash_with_vjp(q, k, v, causal, scale, block_q, block_k, interpret, bwd,
                    block_q_bwd, block_k_bwd):
    out, _ = _forward_impl(q, k, v, _zero_offs(), causal=causal, scale=scale,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret, need_lse=False)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret, bwd,
               block_q_bwd, block_k_bwd):
    out, residuals = _forward_impl(q, k, v, _zero_offs(), causal=causal,
                                   scale=scale, block_q=block_q,
                                   block_k=block_k, interpret=interpret,
                                   need_lse=True)
    return out, residuals


def _flash_bwd(causal, scale, block_q, block_k, interpret, bwd,
               block_q_bwd, block_k_bwd, residuals, g):
    qr, kr, vr, outr, lse = residuals
    return _backward_impl(qr, kr, vr, outr, lse, _zero_offs(), g, None,
                          causal=causal, scale=scale, block_q=block_q,
                          block_k=block_k, interpret=interpret, bwd=bwd,
                          block_q_bwd=block_q_bwd, block_k_bwd=block_k_bwd)


_flash_with_vjp.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    bwd: str | None = None,
    block_q_bwd: int | None = None,
    block_k_bwd: int | None = None,
):
    """Softmax attention over (batch, seq, heads, head_dim) inputs.

    Numerically equal to parallel.ring_attention.full_attention (the
    oracle in tests); O(block) VMEM instead of the (T, T) score matrix.
    Sequence length must divide by the block sizes (pad upstream — the
    model keeps T a multiple of 128). Differentiable: custom VJP whose
    backward is two blockwise Pallas kernels (dQ pass, dK/dV pass)
    recomputing P from the forward's saved logsumexp — O(block) VMEM in
    both directions.
    """
    return _flash_with_vjp(q, k, v, causal, scale, block_q, block_k,
                           interpret, bwd, block_q_bwd, block_k_bwd)


# ----------------------------------------------------------------- block


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11)
)
def _flash_block_with_vjp(q, k, v, offs_i, causal, scale, block_q, block_k,
                          interpret, bwd, block_q_bwd, block_k_bwd):
    offs = offs_i.reshape(2)
    out, (_, _, _, _, lse) = _forward_impl(
        q, k, v, offs, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret, need_lse=True,
    )
    B, Tq, H, _ = q.shape
    lse_user = jnp.einsum("bht->bth", lse.reshape(B, H, Tq))
    return out, lse_user


def _flash_block_fwd(q, k, v, offs_i, causal, scale, block_q, block_k,
                     interpret, bwd, block_q_bwd, block_k_bwd):
    offs = offs_i.reshape(2)
    out, residuals = _forward_impl(
        q, k, v, offs, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret, need_lse=True,
    )
    B, Tq, H, _ = q.shape
    lse = residuals[4]
    lse_user = jnp.einsum("bht->bth", lse.reshape(B, H, Tq))
    return (out, lse_user), (*residuals, offs)


def _flash_block_bwd(causal, scale, block_q, block_k, interpret, bwd,
                     block_q_bwd, block_k_bwd, residuals, g):
    qr, kr, vr, outr, lse, offs = residuals
    g_out, g_lse = g
    dq, dk, dv = _backward_impl(
        qr, kr, vr, outr, lse, offs, g_out, g_lse, causal=causal,
        scale=scale, block_q=block_q, block_k=block_k, interpret=interpret,
        bwd=bwd, block_q_bwd=block_q_bwd, block_k_bwd=block_k_bwd,
    )
    # offsets are integer positions: their cotangent is the symbolic
    # float0 zero (also exempt from shard_map's varying-axes check)
    return dq, dk, dv, np.zeros((2,), jax.dtypes.float0)


_flash_block_with_vjp.defvjp(_flash_block_fwd, _flash_block_bwd)


def flash_attention_block(
    q,
    k,
    v,
    q_offset,
    k_offset,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    bwd: str | None = None,
    block_q_bwd: int | None = None,
    block_k_bwd: int | None = None,
):
    """One *partial* attention: local queries ``q`` (global position
    ``q_offset``) against one visiting K/V block (global position
    ``k_offset``); Tq and Tk may differ. Returns ``(out, lse)`` —
    the softmax attention restricted to this block, normalized within
    it, plus the per-row logsumexp (B, Tq, H) f32 — so partials over
    disjoint K/V blocks merge exactly:

        m = max(lse_a, lse_b); e_x = exp(lse_x - m)
        out = (e_a·out_a + e_b·out_b) / (e_a + e_b);  lse = m + log(e_a+e_b)

    This is the per-step compute of ring attention (the reference's
    ring exchange-accumulate, allreduce-mpi-sycl.cpp:173-182, with
    attention as the combine). Offsets may be traced (e.g. derived from
    ``axis_index`` inside shard_map). A fully-future block (causal,
    k_offset > all query positions) skips all fetches/matmuls and
    returns out=0, lse≈-1e30, which the merge weights to zero.
    Differentiable in q, k, v, including gradient flow through lse.
    (A fully-future block's fetches and matmuls are skipped, not just
    masked.)
    """
    offs_i = jnp.stack([
        jnp.asarray(q_offset, jnp.int32), jnp.asarray(k_offset, jnp.int32)
    ])
    return _flash_block_with_vjp(q, k, v, offs_i, causal, scale, block_q,
                                 block_k, interpret, bwd,
                                 block_q_bwd, block_k_bwd)
