"""contractlint rules: judging the whole-tree producer/consumer tables.

Second pass over :mod:`hpc_patterns_tpu.analysis.contracts`'s tables.
Every rule here anchors its findings INSIDE the module currently
under analysis (output stays stable per-file, like every other rule
family), but judges that module's sites against the tables merged
over the tree the module belongs to — so deleting a gated key's
emitter in ``bench.py`` surfaces at the surviving ``SPECS`` row in
``harness/regress.py``, at review time, instead of as the PR 5
runtime coverage-loss warning after a bench run already happened.

The five rules and the seams they pin (each drifted at least once in
review before this existed):

- ``gate-key-orphan`` — ``harness/regress.py`` gate keys vs. bench
  ``detail`` emitters; metric/span names consumed by string in
  report/explain/autofit vs. ``metrics.gauge(...)`` producers.
- ``record-kind-drift`` — RunLog ``kind=`` literals written vs. the
  kinds report/collect/autofit/explain dispatch on, both directions;
  ``FORENSIC_KINDS`` in ``harness/runlog.py`` declares the kinds
  written for the record stream / replay tooling on purpose.
- ``wire-field-compat`` — the migration wire codec field-by-field:
  reads absent-tolerant unless in ``REQUIRED_WIRE_FIELDS``;
  write/read sets must match.
- ``track-band-collision`` — Perfetto device-subtrack bands come
  from the ``harness/trace.py`` ``TRACK_BANDS`` registry; overlaps
  and hand-picked integers are findings (pallaslint's collective-id
  registry discipline, applied to trace tracks).
- ``chaos-site-drift`` — chaos site/kind names claimed at injection
  sites and spelled in specs vs. ``harness/chaos.py``'s declarations.
"""

from __future__ import annotations

import ast
from types import SimpleNamespace
from typing import Iterable

from hpc_patterns_tpu.analysis import contracts
from hpc_patterns_tpu.analysis.contracts import Site
from hpc_patterns_tpu.analysis.core import (AnalysisConfig, Finding,
                                            ModuleInfo, Rule, register)


def _at(site: Site) -> SimpleNamespace:
    """A Finding anchor for a table Site (duck-types an AST node)."""
    return SimpleNamespace(lineno=site.line, col_offset=site.col)


@register
class GateKeyOrphanRule(Rule):
    """Every consumer-by-string of a bench/telemetry name must have a
    live producer. Three contracts share the shape: (a) a
    ``MetricSpec("detail.<key>", ...)`` row in the regression gate
    with no ``<key>`` emitted by any bench-tree dict; (b) a metric
    name read by string (``gauges.get("mem.hbm_pages")``) with no
    ``.gauge/.counter/.histogram`` producer; (c) a device-window span
    name (``_windows(records, "serve.chunk")``) nothing
    ``mark_dispatch``\\ es. All three are the "emitter deleted, gate
    silently stops gating" failure the PR 5 runtime coverage-loss
    warning patches over — this is the review-time version."""

    name = "gate-key-orphan"
    family = "contractlint"
    summary = ("gate key / string-consumed metric name has no live "
               "emitter anywhere in the tree")
    hint = ("restore the emitter (bench detail dict key, "
            "metrics.gauge(...) call, or mark_dispatch span), or "
            "delete the consumer row if the metric is gone for good")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        t = contracts.tables_for(mod)
        for s in t.gate_specs:
            if s.path != mod.path or not s.name.startswith("detail."):
                continue
            key = s.name.split(".", 1)[1]
            if key not in t.detail_keys:
                yield self.finding(mod, _at(s), (
                    f"{s.detail} gate key {s.name!r} has no emitter: "
                    f"no bench-tree dict ever writes {key!r}"))
        for s in t.gauges_consumed:
            if s.path != mod.path:
                continue
            if not t.gauge_has_producer(s.name):
                yield self.finding(mod, _at(s), (
                    f"metric {s.name!r} is consumed by string here "
                    f"but no gauge/counter/histogram call produces "
                    f"it"))
        for s in t.spans_consumed:
            if s.path != mod.path:
                continue
            if s.name not in t.spans_produced:
                yield self.finding(mod, _at(s), (
                    f"device-window span {s.name!r} is consumed here "
                    f"but nothing mark_dispatch()es it"))


@register
class RecordKindDriftRule(Rule):
    """RunLog record kinds, both directions. A kind DISPATCHED on
    (``rec["kind"] == "trace"`` and friends) that nothing writes is a
    dead consumer branch — usually a renamed producer. A kind WRITTEN
    (``kind="..."`` keyword, ``{"kind": "..."}`` literal,
    ``rec["kind"] = "..."``) that nothing dispatches on is telemetry
    nobody reads — unless it is declared in ``harness/runlog.py``'s
    ``FORENSIC_KINDS``, the explicit list of kinds written for the
    raw record stream / replay tooling rather than for a dispatcher."""

    name = "record-kind-drift"
    family = "contractlint"
    summary = ("record kind written but never dispatched on (or "
               "dispatched but never written)")
    hint = ("rename the drifted side, or — if the kind is write-only "
            "by design — add it to FORENSIC_KINDS in "
            "harness/runlog.py")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        t = contracts.tables_for(mod)
        for kind, sites in t.kinds_consumed.items():
            if kind in t.kinds_produced:
                continue
            for s in sites:
                if s.path == mod.path:
                    yield self.finding(mod, _at(s), (
                        f"record kind {kind!r} is dispatched on here "
                        f"but nothing in the tree ever writes it"))
        for kind, sites in t.kinds_produced.items():
            if kind in t.kinds_consumed or kind in t.forensic_kinds:
                continue
            for s in sites:
                if s.path == mod.path:
                    yield self.finding(mod, _at(s), (
                        f"record kind {kind!r} is written here but "
                        f"nothing dispatches on it (declare it in "
                        f"FORENSIC_KINDS if write-only by design)"))


def _function_defs(mod: ModuleInfo) -> Iterable[ast.FunctionDef]:
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _required_wire_fields(mod: ModuleInfo) -> tuple[set[str], bool]:
    """(fields, declared) from a module-level REQUIRED_WIRE_FIELDS
    tuple/set/list literal."""
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "REQUIRED_WIRE_FIELDS":
            elems = contracts._str_tuple_elems(node.value) or []
            return {e.value for e in elems}, True
    return set(), False


@register
class WireFieldCompatRule(Rule):
    """The migration wire codec, field by field. Inside any
    ``*to_wire`` function the written field set is every string key
    stored into the wire dict; inside any ``*from_wire`` function a
    read is ``wire["k"]`` (absent-INTOLERANT), ``wire.get("k", ...)``
    (tolerant), or a ``"k" in wire`` guarded access (tolerant — the
    PR 17 ``transport`` / PR 18 ``segments`` discipline). Findings:
    an intolerant read of a field not listed in the module's
    ``REQUIRED_WIRE_FIELDS`` literal (an old producer's wire kills
    the new consumer), a field written but never read (dead bytes on
    the wire), and a field read but never written (guaranteed
    KeyError or silently-dead fallback)."""

    name = "wire-field-compat"
    family = "contractlint"
    summary = ("wire codec field sets drifted, or a read is "
               "absent-intolerant without being REQUIRED")
    hint = ("read optional fields with .get()/an `in` guard, list "
            "genuinely mandatory ones in REQUIRED_WIRE_FIELDS, and "
            "keep to_wire/from_wire field sets in lockstep")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        required, declared = _required_wire_fields(mod)
        writes: dict[str, ast.AST] = {}
        reads: dict[str, ast.AST] = {}
        intolerant: dict[str, ast.AST] = {}
        have_to = have_from = False
        for fn in _function_defs(mod):
            if fn.name.endswith("to_wire"):
                have_to = True
                for node in ast.walk(fn):
                    if isinstance(node, ast.Dict):
                        for k in node.keys:
                            key = contracts._str_const(k) \
                                if k is not None else None
                            if key is not None:
                                writes.setdefault(key, k)
                    elif isinstance(node, ast.Assign) \
                            and len(node.targets) == 1 \
                            and isinstance(node.targets[0],
                                           ast.Subscript):
                        key = contracts._str_const(
                            node.targets[0].slice)
                        if key is not None:
                            writes.setdefault(key, node.targets[0])
            elif fn.name.endswith("from_wire"):
                have_from = True
                params = {a.arg for a in (
                    fn.args.posonlyargs + fn.args.args
                    + fn.args.kwonlyargs)}
                guarded: set[str] = set()
                subs: list[tuple[str, ast.AST]] = []
                for node in ast.walk(fn):
                    if isinstance(node, ast.Compare) \
                            and len(node.ops) == 1 \
                            and isinstance(node.ops[0],
                                           (ast.In, ast.NotIn)) \
                            and isinstance(node.comparators[0],
                                           ast.Name) \
                            and node.comparators[0].id in params:
                        key = contracts._str_const(node.left)
                        if key is not None:
                            guarded.add(key)
                            reads.setdefault(key, node.left)
                    elif isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "get" \
                            and isinstance(node.func.value, ast.Name) \
                            and node.func.value.id in params \
                            and node.args:
                        key = contracts._str_const(node.args[0])
                        if key is not None:
                            reads.setdefault(key, node.args[0])
                    elif isinstance(node, ast.Subscript) \
                            and isinstance(node.value, ast.Name) \
                            and node.value.id in params \
                            and isinstance(node.ctx, ast.Load):
                        key = contracts._str_const(node.slice)
                        if key is not None:
                            reads.setdefault(key, node)
                            subs.append((key, node))
                # judge subscripts only after the whole walk — the
                # `"k" in wire` guard may sit after the read in a
                # conditional expression
                for key, node in subs:
                    if key not in guarded:
                        intolerant.setdefault(key, node)
        if not (have_to or have_from):
            return
        for key, node in sorted(intolerant.items()):
            if key in required:
                continue
            yield self.finding(mod, node, (
                f"absent-intolerant read wire[{key!r}] of a field "
                f"not in REQUIRED_WIRE_FIELDS"
                + ("" if declared else " (no REQUIRED_WIRE_FIELDS "
                   "literal declared in this module)")))
        if have_to and have_from:
            for key, node in sorted(writes.items()):
                if key not in reads:
                    yield self.finding(mod, node, (
                        f"wire field {key!r} is written by to_wire "
                        f"but from_wire never reads it"))
            for key, node in sorted(reads.items()):
                if key not in writes:
                    yield self.finding(mod, node, (
                        f"wire field {key!r} is read by from_wire "
                        f"but to_wire never writes it"))


@register
class TrackBandCollisionRule(Rule):
    """Perfetto device-subtrack allocation. ``harness/trace.py``'s
    ``TRACK_BANDS`` literal is the single declared source of subtrack
    bands (decode, admit, migration, spinup, residency); modules
    unpack their base/width via ``track_band("<name>")``. Findings:
    two declared bands overlapping, a ``FOO_TRACK_BASE = <int>``
    hand-picked outside the registry (the pre-registry idiom that
    produced the 64/72/80 near-misses), a ``track_band()`` reference
    to an undeclared band name, and a literal ``track=<int>``
    argument landing outside every declared band."""

    name = "track-band-collision"
    family = "contractlint"
    summary = ("trace track bands overlap, or a track id bypasses "
               "the TRACK_BANDS registry")
    hint = ("declare the band in harness/trace.py TRACK_BANDS and "
            "unpack it with track_band('<name>') instead of "
            "hand-picking integers")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        t = contracts.tables_for(mod)
        for band in t.declared_bands.values():
            if band.site.path != mod.path:
                continue
            for other in t.declared_bands.values():
                if other.name != band.name and band.overlaps(other):
                    yield self.finding(mod, _at(band.site), (
                        f"track band {band.name!r} "
                        f"({band.base}..{band.hi}) overlaps "
                        f"{other.name!r} ({other.base}..{other.hi})"))
        for s in t.band_literals:
            if s.path == mod.path:
                yield self.finding(mod, _at(s), (
                    f"hand-picked track base {s.name} = {s.detail} "
                    f"bypasses the TRACK_BANDS registry"))
        if not t.declared_bands:
            return
        for s in t.band_refs:
            if s.path == mod.path and s.name not in t.declared_bands:
                yield self.finding(mod, _at(s), (
                    f"track_band({s.name!r}) names a band "
                    f"TRACK_BANDS does not declare"))
        for s in t.track_literals:
            if s.path != mod.path:
                continue
            track = int(s.detail)
            if t.band_covering(track) is None:
                yield self.finding(mod, _at(s), (
                    f"literal track={track} falls outside every "
                    f"declared TRACK_BANDS band"))


@register
class ChaosSiteDriftRule(Rule):
    """Chaos site/kind names. ``harness/chaos.py`` declares the
    legal injection sites (``SITES``) and fault kinds (``KINDS``);
    every ``chaos.maybe_inject("<site>", ...)`` claim, ``site=``
    keyword, recorded injection kind, and ``"kind:key=val"`` spec
    string must spell a declared name — a typo'd site silently
    injects nothing and a typo'd kind dies at parse time in the one
    run (the chaos soak) least equipped to debug it."""

    name = "chaos-site-drift"
    family = "contractlint"
    summary = ("chaos site/kind name not declared in "
               "harness/chaos.py SITES/KINDS")
    hint = ("match the literal to chaos.SITES/chaos.KINDS, or add "
            "the new site/kind to the declaration first")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        t = contracts.tables_for(mod)
        if t.chaos_sites:
            for s in t.chaos_site_claims:
                if s.path == mod.path and s.name not in t.chaos_sites:
                    yield self.finding(mod, _at(s), (
                        f"chaos site {s.name!r} is claimed here but "
                        f"SITES declares only: "
                        + ", ".join(sorted(t.chaos_sites))))
        if t.chaos_kinds:
            for s in t.chaos_kind_claims:
                if s.path == mod.path and s.name not in t.chaos_kinds:
                    yield self.finding(mod, _at(s), (
                        f"chaos kind {s.name!r} is claimed here but "
                        f"KINDS declares only: "
                        + ", ".join(sorted(t.chaos_kinds))))
