"""jaxlint: static hazard analysis for the JAX patterns this repo has
been burned by — donation aliasing, dispatch-path host syncs, per-call
re-jits, PRNG key reuse, tracer leaks; (the shardlint family) the
SPMD collective-divergence class: rank-branched collective schedules,
reordered collective paths, unchecked ppermute pair lists, and
PartitionSpec/mesh inconsistencies; and (the pallaslint family) the
in-kernel DMA/semaphore/VMEM contract: semaphore-ledger imbalance,
scratch-slot reuse across live DMAs, collective-id collisions, dtype
holes, and VMEM budget overflows — the chip-only bug class interpret
mode cannot see (``pallas_rules.py`` / ``vmem.py``; runtime half:
``runtime.strict_semaphores``); and (the contractlint family) the
stringly-typed producer/consumer seams: orphaned regression-gate
keys, RunLog record-kind drift, wire-codec field incompatibility,
Perfetto track-band collisions, and chaos site/kind typos — checked
whole-tree against merged extraction tables (``contracts.py`` /
``contract_rules.py``; ``--contract-report`` prints the tables).

Run it over the package (CI mode exits nonzero on any unsuppressed
finding)::

    python -m hpc_patterns_tpu.analysis --ci

The motivating incidents: PR 2's "poisoned cache" — a zero-copy
``np.asarray`` host view of a buffer that a donated jit arg later
mutated in place (``serving._dispatch_chunk``), caught at review time
by ``donation-alias`` — and the reference suite's silent MPI-ring
deadlock, where SPMD ranks disagree on which collective comes next,
caught by ``collective-divergence``. The recorder shows you the
bubble; jaxlint stops the next one.

Public surface:

- :func:`run_paths` / :class:`Report` / :class:`Finding` — the engine
  (hpc_patterns_tpu.analysis.core; rules in .rules self-register);
- :func:`dispatch_critical` — no-op marker decorator: the
  ``host-sync-in-dispatch`` rule treats any function carrying it as
  dispatch-critical, in addition to the configured name list;
- hpc_patterns_tpu.analysis.runtime — the RUNTIME complements:
  :func:`~hpc_patterns_tpu.analysis.runtime.poison_donated` clobbers
  donated inputs after each call so an aliasing bug the analyzer
  missed fails loudly in tests, and
  :class:`~hpc_patterns_tpu.analysis.runtime.CollectiveSchedule`
  fingerprints every eager collective into a per-rank hash chain that
  the cross-rank trace merge (harness/collect.py) verifies — and that
  names which collective a hung rank is stuck in on a launch timeout.
"""

from __future__ import annotations

from hpc_patterns_tpu.analysis.core import (  # noqa: F401
    AnalysisConfig,
    DEFAULT_DISPATCH_CRITICAL,
    Finding,
    Report,
    analyze_file,
    registered_rules,
    run_paths,
)


def dispatch_critical(fn):
    """Marker decorator: this function is on a dispatch-critical path
    (its job is to ENQUEUE device work, never to wait for it). Purely
    declarative — the wrapped function is returned unchanged — but the
    ``host-sync-in-dispatch`` rule audits every function carrying it,
    so the marker turns a design intention into a checked invariant."""
    return fn
