"""Known-bad: kernels whose literal-resolvable VMEM working set
already exceeds their budget — the PR 8 overflow shape, which passes
interpret mode (no VMEM exists there) and fails at Mosaic lowering on
the chip, after the tunnel queue. The vmem-budget rule judges ONLY the
literal lower bound (blocks + scratch it can resolve from constants);
symbolic shapes are ``--vmem-report``'s territory."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _accum_kernel(x_ref, o_ref, acc_ref):
    o_ref[...] = x_ref[...] + acc_ref[...]


def scratch_over_default_limit(x):
    """A 64 MiB f32 scratch against Mosaic's 16 MiB default scoped
    limit: 4096·4096·4 bytes of accumulator nobody sized."""
    return pl.pallas_call(  # EXPECT: vmem-budget
        _accum_kernel,
        out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
        in_specs=[pl.BlockSpec((128, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((128, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        grid=(1,),
        scratch_shapes=[pltpu.VMEM((4096, 4096), jnp.float32)],
    )(x)


def scratch_over_declared_limit(x):
    """An explicit (small) vmem_limit_bytes the literal scratch still
    blows through: the declared budget is the contract, and 8 MiB of
    f32 double-buffer does not fit 4 MiB of it."""
    return pl.pallas_call(  # EXPECT: vmem-budget
        _accum_kernel,
        out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
        in_specs=[pl.BlockSpec((128, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((128, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        grid=(1,),
        scratch_shapes=[pltpu.VMEM((2, 1024, 1024), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=4 * 1024 * 1024),
    )(x)
