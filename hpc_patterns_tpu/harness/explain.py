"""Tail attribution: render WHERE every p99 went, per class.

The read side of harness/reqtrace.py. Input is one or more
``kind=reqtrace`` RunLog records (each request's segment history
zipped with its stats endpoints); output is the question the
device-centric ladder could never answer: *for the requests that blew
the tail, which lifecycle state ate the time?* —

    class 0 (interactive)  n=24  ttft p99 812ms
      p99-TTFT band: 61% queued, 22% prefill, 9% admit_wait, ...

Attribution is over the **TTFT window** ``[t_submit, t_first]`` (the
window the SLO judges; a request that was shed before serving is
attributed over its whole ``[t_submit, t_finish]`` life instead), on
the canonical tiling :func:`reqtrace.finalize` produces — so shares
per request sum to exactly 1.0 and unclaimed time shows up as an
explicit ``untracked`` share, never as a silently shrunk denominator.
The tail band is the class's requests with TTFT at or above the exact
p99 (numpy over raw values, the harness/slo.py discipline — at bench
scale that is "the worst few requests", which is the point).

Attribution does NOT stop at the first token: the **inter-token
digest** tiles the same canonical segments over every gap between
consecutive token-availability stamps (``token_ts`` in the stats
table, stamped at chunk readback by models/serving.py) inside
``[t_first, t_finish]`` — so a decode-phase stall (a swap, a pull, a
preemption, a migration) is blamed on the mechanism that caused it
instead of vanishing into a fat TPOT mean. The gap band is the gaps
at/above the exact pooled p99 of gap width.

Three numbers feed the bench gate (harness/regress.py):

- ``coverage_frac`` — 1 - untracked share over all finished requests
  (gated HIGHER with tight slack: attribution that quietly loses
  coverage is worse than no attribution);
- ``ttft_p99_queue_share`` — queued share of the pooled p99 band's
  TTFT windows (captured per round; the single scalar that says
  whether the tail is a scheduling problem or a compute problem);
- ``tpot_p99_stall_share`` — the :data:`TPOT_STALL_KINDS` share of
  the pooled p99 inter-token gap band (the single scalar that says
  whether the decode tail is the model or the memory/control plane).

Usage::

    python -m hpc_patterns_tpu.harness.explain run.jsonl [more ...]
           [--worst N] [-o digest.json]

Exit 0 when at least one reqtrace record was found; 2 otherwise.
The same :func:`digest`/:func:`format_explain` pair backs the
``--explain`` flag in serve_app / plane_app / bench_serving
(harness/cli.add_explain_args). docs/observability.md#request-forensics.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Iterable, Mapping

import numpy as np

from hpc_patterns_tpu.harness import reqtrace
from hpc_patterns_tpu.harness.report import load_records

#: how many worst-TTFT requests the digest itemizes by default
WORST_N = 5

#: decode-phase segment kinds the inter-token digest counts as STALL
#: time — everything that is not the row making forward progress (or
#: the explicit unclaimed remainder). ``decode``/``prefill`` in a gap
#: is compute; these are the mechanisms a fitter can act on.
TPOT_STALL_KINDS = ("preempted", "swapped_out", "prefetch_wait",
                    "migrating", "untracked")


def _decode_gaps(entry: Mapping[str, Any]) -> list[tuple[float, float]]:
    """Inter-token windows of one request: consecutive pairs of token
    availability stamps, clamped to ``[t_first, t_finish]``. Empty for
    shed rows (no tokens), single-token responses (no gap), and legacy
    snapshots without ``token_ts``."""
    ts = entry.get("token_ts") or ()
    t_first, t_finish = entry.get("t_first"), entry.get("t_finish")
    if t_first is None or t_finish is None or len(ts) < 2:
        return []
    lo, hi = float(t_first), float(t_finish)
    pts = sorted(min(max(float(t), lo), hi) for t in ts)
    return [(a, b) for a, b in zip(pts, pts[1:]) if b - a > 0]


def _gap_rows(entry: Mapping[str, Any]
              ) -> list[tuple[dict[str, float], float]]:
    """``(shares, width_s)`` per inter-token gap of one request —
    the same canonical :func:`reqtrace.finalize` tiling the TTFT
    window uses, intersected with each gap, so shares per gap sum to
    exactly 1.0 (a gap fully inside one stamped ``decode`` span is
    100% decode — honest: the chunk was simply slow)."""
    gaps = _decode_gaps(entry)
    if not gaps:
        return []
    tiled, _ = reqtrace.finalize(entry.get("segments") or (),
                                 entry["t_submit"], entry["t_finish"])
    rows: list[tuple[dict[str, float], float]] = []
    for g0, g1 in gaps:
        width = g1 - g0
        shares: dict[str, float] = {}
        for kind, s0, s1, _meta in tiled:
            ov = min(s1, g1) - max(s0, g0)
            if ov > 0:
                shares[kind] = shares.get(kind, 0.0) + ov / width
        rows.append((shares, width))
    return rows


def _gap_band(rows: list[tuple[dict[str, float], float]]) -> tuple[
        list[tuple[dict[str, float], float]], float | None]:
    """Gaps at/above the exact p99 of gap width (the slo.py numpy
    discipline, same as the TTFT band)."""
    if not rows:
        return [], None
    widths = np.asarray([w for _, w in rows], np.float64)
    p99 = float(np.percentile(widths, 99.0))
    return [r for r in rows if r[1] >= p99], p99


def _stall_share(shares: Mapping[str, float]) -> float:
    return float(sum(shares.get(k, 0.0) for k in TPOT_STALL_KINDS))


def _window_shares(entry: Mapping[str, Any]) -> tuple[
        dict[str, float], float, float] | None:
    """Per-kind share of one request's attribution window. Returns
    ``(shares, window_s, untracked_in_window_s)`` or None when the
    request never resolved (no window to attribute)."""
    t_submit, t_finish = entry.get("t_submit"), entry.get("t_finish")
    if t_submit is None or t_finish is None:
        return None
    t_end = entry.get("t_first")
    if t_end is None:
        t_end = t_finish  # shed / zero-token life: attribute it all
    tiled, _ = reqtrace.finalize(entry.get("segments") or (),
                                 t_submit, t_finish)
    window = max(0.0, float(t_end) - float(t_submit))
    shares: dict[str, float] = {}
    for kind, s0, s1, _meta in tiled:
        ov = min(s1, float(t_end)) - max(s0, float(t_submit))
        if ov > 0:
            shares[kind] = shares.get(kind, 0.0) + ov
    if window > 0:
        shares = {k: v / window for k, v in shares.items()}
    return shares, window, shares.get("untracked", 0.0) * window


def _merge_shares(rows: list[tuple[dict[str, float], float]]
                  ) -> dict[str, float]:
    """Window-weighted mean of per-request shares (a 2s wait counts
    double a 1s wait — the band total is what the table explains)."""
    total = sum(w for _, w in rows)
    if total <= 0:
        return {}
    out: dict[str, float] = {}
    for shares, w in rows:
        for k, v in shares.items():
            out[k] = out.get(k, 0.0) + v * w
    return {k: v / total for k, v in sorted(
        out.items(), key=lambda kv: -kv[1])}


def digest(snapshots: Iterable[Mapping[str, Any]],
           worst_n: int = WORST_N) -> dict[str, Any]:
    """Fold ``kind=reqtrace`` record payloads into the attribution
    digest: per-class tail bands, run coverage, the two gate scalars,
    and the worst-N request itemization."""
    requests: dict[str, dict[str, Any]] = {}
    for snap in snapshots:
        requests.update(snap.get("requests") or {})

    per_req: list[dict[str, Any]] = []
    gap_rows_by_prio: dict[int, list[tuple[dict[str, float], float]]] \
        = {}
    untracked_s = span_s = 0.0
    for sid, entry in requests.items():
        ws = _window_shares(entry)
        if ws is None:
            continue
        shares, window, _ = ws
        prio_key = int(entry.get("priority") or 0)
        gap_rows_by_prio.setdefault(prio_key, []).extend(
            _gap_rows(entry))
        ttft = (float(entry["t_first"]) - float(entry["t_submit"])
                if entry.get("t_first") is not None else None)
        span = float(entry["t_finish"]) - float(entry["t_submit"])
        _, u = reqtrace.finalize(entry.get("segments") or (),
                                 entry["t_submit"], entry["t_finish"])
        untracked_s += u
        span_s += max(0.0, span)
        per_req.append({
            "seq_id": int(sid),
            "priority": int(entry.get("priority") or 0),
            "outcome": entry.get("outcome"),
            "preemptions": int(entry.get("preemptions") or 0),
            "ttft_s": ttft,
            "span_s": span,
            "window_s": window,
            "shares": shares,
        })

    def _band(rows: list[dict[str, Any]]) -> tuple[
            list[dict[str, Any]], float | None]:
        """Rows at/above the exact p99 of TTFT (served rows only)."""
        ttfts = [r["ttft_s"] for r in rows if r["ttft_s"] is not None]
        if not ttfts:
            return [], None
        p99 = float(np.percentile(np.asarray(ttfts, np.float64), 99.0))
        return [r for r in rows
                if r["ttft_s"] is not None and r["ttft_s"] >= p99], p99

    def _tpot(rows: list[tuple[dict[str, float], float]]
              ) -> dict[str, Any]:
        """The inter-token-tail table for one pool of gaps."""
        widths = [w for _, w in rows]
        band, p99 = _gap_band(rows)
        band_shares = _merge_shares(band)
        span_shares = _merge_shares(rows)
        return {
            "n_gaps": len(rows),
            "n_band": len(band),
            "gap": ({"p50": float(np.percentile(widths, 50.0)),
                     "p95": float(np.percentile(widths, 95.0)),
                     "p99": p99} if widths else
                    {"p50": None, "p95": None, "p99": None}),
            "band_shares": band_shares,
            "band_stall_share": _stall_share(band_shares),
            "span_shares": span_shares,
            "span_stall_share": _stall_share(span_shares),
        }

    classes: dict[int, dict[str, Any]] = {}
    for prio in sorted({r["priority"] for r in per_req}):
        rows = [r for r in per_req if r["priority"] == prio]
        ttfts = [r["ttft_s"] for r in rows if r["ttft_s"] is not None]
        band, p99 = _band(rows)
        classes[prio] = {
            "n": len(rows),
            "n_band": len(band),
            "ttft": ({"p50": float(np.percentile(ttfts, 50.0)),
                      "p95": float(np.percentile(ttfts, 95.0)),
                      "p99": p99} if ttfts else
                     {"p50": None, "p95": None, "p99": None}),
            "band_shares": _merge_shares(
                [(r["shares"], r["window_s"]) for r in band]),
            "span_shares": _merge_shares(
                [(r["shares"], r["window_s"]) for r in rows]),
            "tpot": _tpot(gap_rows_by_prio.get(prio, [])),
        }

    pooled_band, _ = _band(per_req)
    pooled = _merge_shares([(r["shares"], r["window_s"])
                            for r in pooled_band])
    pooled_tpot = _tpot([g for rows in gap_rows_by_prio.values()
                         for g in rows])
    worst = sorted(per_req,
                   key=lambda r: -(r["ttft_s"] if r["ttft_s"]
                                   is not None else r["span_s"]))
    return {
        "n": len(per_req),
        "coverage_frac": (1.0 - untracked_s / span_s
                          if span_s > 0 else 1.0),
        "ttft_p99_queue_share": pooled.get("queued", 0.0),
        "ttft_p99_band_shares": pooled,
        "tpot_p99_stall_share": pooled_tpot["band_stall_share"],
        "tpot_p99_band_shares": pooled_tpot["band_shares"],
        "tpot": pooled_tpot,
        "classes": classes,
        "worst": worst[:max(0, int(worst_n))],
    }


def _fmt_shares(shares: Mapping[str, float]) -> str:
    parts = [f"{frac:.0%} {kind}" for kind, frac in shares.items()
             if frac >= 0.005]
    return ", ".join(parts) if parts else "(no attributed time)"


def _ms(v: float | None) -> str:
    return "-" if v is None else f"{v * 1e3:.0f}ms"


def _dominant(shares: Mapping[str, float]) -> str:
    """``"61% queued"`` for the band's biggest segment — whatever kind
    it is (a prefetch_wait-dominated band must not be summarized as
    "queue share 0%"); ``_merge_shares`` already sorted descending."""
    for kind, frac in shares.items():
        return f"{frac:.0%} {kind}"
    return "none"


def format_explain(dig: Mapping[str, Any]) -> str:
    """The human table the ``--explain`` surfaces print after the
    goodput row (same fixed-layout style as slo.format_slo)."""
    lines = [
        f"request forensics  n={dig['n']}  "
        f"coverage {dig['coverage_frac']:.1%}  "
        f"p99-band dominant "
        f"{_dominant(dig.get('ttft_p99_band_shares') or {})}  "
        f"tpot-p99 stall share "
        f"{dig.get('tpot_p99_stall_share', 0.0):.0%}"]
    for prio, cls in sorted(dig["classes"].items()):
        t = cls["ttft"]
        lines.append(
            f"  class {prio}  n={cls['n']}  ttft p50/p95/p99 "
            f"{_ms(t['p50'])}/{_ms(t['p95'])}/{_ms(t['p99'])}")
        lines.append(
            f"    p99-TTFT band (n={cls['n_band']}): "
            f"{_fmt_shares(cls['band_shares'])}")
        lines.append(f"    all requests:  "
                     f"{_fmt_shares(cls['span_shares'])}")
        tp = cls.get("tpot") or {}
        if tp.get("n_gaps"):
            g = tp["gap"]
            lines.append(
                f"    inter-token gaps n={tp['n_gaps']}  p50/p95/p99 "
                f"{_ms(g['p50'])}/{_ms(g['p95'])}/{_ms(g['p99'])}")
            lines.append(
                f"    p99-gap band (n={tp['n_band']}, stall "
                f"{tp['band_stall_share']:.0%}): "
                f"{_fmt_shares(tp['band_shares'])}")
    if dig["worst"]:
        lines.append("  worst requests by TTFT:")
        for r in dig["worst"]:
            tag = (f"ttft {_ms(r['ttft_s'])}" if r["ttft_s"] is not None
                   else f"{r['outcome'] or 'unserved'}")
            pre = (f"  preempt x{r['preemptions']}"
                   if r["preemptions"] else "")
            lines.append(
                f"    seq {r['seq_id']}  prio {r['priority']}  {tag}"
                f"  span {r['span_s'] * 1e3:.0f}ms{pre}: "
                f"{_fmt_shares(r['shares'])}")
    return "\n".join(lines)


def digest_from_stats(stats: Mapping[int, Mapping[str, Any]],
                      tracer: reqtrace.ReqTrace,
                      worst_n: int = WORST_N) -> dict[str, Any]:
    """One-step digest for in-process surfaces (serve_app/plane_app/
    bench_serving): snapshot the live recorder against the run's
    stats table and fold it."""
    return digest([tracer.snapshot(stats)], worst_n=worst_n)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hpc_patterns_tpu.harness.explain",
        description="per-class tail attribution from kind=reqtrace "
                    "records in run logs")
    ap.add_argument("logs", nargs="+", help="JSONL run logs")
    ap.add_argument("--worst", type=int, default=WORST_N,
                    help="worst-N requests to itemize "
                         f"(default {WORST_N})")
    ap.add_argument("-o", "--out", default=None,
                    help="also write the digest as JSON")
    args = ap.parse_args(argv)

    records = load_records(args.logs)
    snaps = [r for r in records if r.get("kind") == "reqtrace"]
    if not snaps:
        print("no kind=reqtrace records (run apps with --explain "
              "--log PATH)", file=sys.stderr)
        return 2
    dig = digest(snaps, worst_n=args.worst)
    print(format_explain(dig))
    if args.out:
        Path(args.out).write_text(json.dumps(dig) + "\n")
        print(f"digest -> {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
