"""Cross-cutting utilities: checkpoint/resume, misc helpers.

SURVEY.md §5 records the reference has **no** checkpoint/resume ("None
anywhere — no serialization of any state"). For a framework with a
training loop that gap is load-bearing, so it is closed here rather
than reproduced: orbax-backed save/restore of the full sharded train
state (checkpoint.py).
"""

from hpc_patterns_tpu.utils.checkpoint import save_checkpoint, restore_checkpoint  # noqa: F401
from hpc_patterns_tpu.utils.data import PrefetchLoader, synthetic_tokens  # noqa: F401
