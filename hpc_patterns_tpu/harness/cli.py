"""Unified CLI/config layer (C13 in SURVEY.md).

The reference uses three ad-hoc mechanisms — hand-rolled argv loops with a
``-1 = auto`` sentinel (sycl_con.cpp:179-232), getopt short options
``-haHDSp:`` (allreduce-mpi-sycl.cpp:106-131), and env vars
(allreduce-usm-mpi-omp-offload.cpp:121-124). SURVEY.md section 5 calls for
one layer with a ``--backend`` flag; this is it. All apps under
``hpc_patterns_tpu.apps`` build on :func:`base_parser`.

Kept semantics:
- ``-1`` means auto/autotune wherever a size is accepted
- ``-p N`` selects 2**N elements (allreduce-mpi-sycl.cpp:99,125-128),
  default 25 (~128 MiB of float32)
- memory-kind axis ``-H/-D`` maps host/device USM to JAX memory kinds
  ``pinned_host`` / ``device`` (``-S`` shared has no TPU analog and maps
  to device with a note)
- ``--repetitions`` (default 10, sycl_con.cpp:182; the reference also
  accepts a typo'd ``--repetitionss``, sycl_con.cpp:205 — not reproduced)
"""

from __future__ import annotations

import argparse

AUTO = -1


def base_parser(description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument(
        "--backend",
        default=None,
        choices=["tpu", "cpu", "gpu"],
        help="platform filter for device discovery (default: whatever JAX has)",
    )
    p.add_argument(
        "--repetitions",
        type=int,
        default=10,
        help="timing repetitions; result is the min (sycl_con.cpp protocol)",
    )
    p.add_argument("--warmup", type=int, default=2, help="untimed warm-up calls (absorbs XLA compile)")
    p.add_argument("--log", default=None, help="write JSONL run log here (run.log analog)")
    p.add_argument(
        "--log-append",
        action="store_true",
        help="append to --log instead of truncating (for harness-invoked runs)",
    )
    p.add_argument(
        "--metrics",
        action="store_true",
        help="enable the metrics/span registry (harness/metrics.py); with "
             "--log, one final kind=metrics snapshot record is appended — "
             "aggregate with `python -m hpc_patterns_tpu.harness.report`",
    )
    p.add_argument(
        "--trace",
        action="store_true",
        help="enable the flight recorder (harness/trace.py): spans, "
             "device dispatch/completion markers, compile events, and "
             "memory samples land in a bounded ring buffer; with --log, "
             "one kind=trace snapshot record is appended — export to "
             "Chrome-trace JSON with "
             "`python -m hpc_patterns_tpu.harness.trace <log>`",
    )
    p.add_argument(
        "--trace-capacity",
        type=int,
        default=None,
        metavar="N",
        help="flight-recorder ring size in events (default 16384; "
             "oldest events evicted beyond it)",
    )
    return p


def add_serving_args(p: argparse.ArgumentParser) -> None:
    """The serving-engine knobs shared by the serving surfaces
    (serve_app; benchmarks/bench_serving.py mirrors them through its
    own flag parser): the prompt-length bucket ladder, the sampling
    mode, and the admission-overlap toggle."""
    p.add_argument(
        "--prompt-buckets",
        default="auto",
        help="prompt-length bucket ladder bounding admission-prefill "
             "compiles: 'auto' (power-of-two-ish ladder over the max "
             "prompt length, serving.bucket_ladder), 'none' (exact "
             "lengths — one compile per distinct length), or "
             "comma-separated rungs, e.g. '16,32,64'",
    )
    p.add_argument(
        "--temperature",
        type=float,
        default=0.0,
        help="sampling temperature (0 = greedy, the token-exact "
             "serving oracle; > 0 samples per-row key streams that "
             "stay standalone-exact)",
    )
    p.add_argument("--top-k", type=int, default=0,
                   help="top-k sampling truncation (0 = off)")
    p.add_argument("--seed", type=int, default=0,
                   help="base PRNG seed for per-request sampling keys")
    p.add_argument(
        "--no-overlap",
        action="store_true",
        help="disable overlapped admission (prefills serialize with "
             "decode chunks — the measurable baseline for the "
             "admission-bubble fraction)",
    )


#: the serving precision knob's legal values — ONE definition shared
#: by serve_app and benchmarks/bench_serving.py (the two surfaces must
#: not drift on what "--kv-dtype fp8" means)
KV_DTYPE_CHOICES = ("f32", "bf16", "int8", "fp8")

#: --kv-dtype value -> (compute dtype override or None, kv_cache_dtype)
_KV_DTYPE_MAP = {
    "f32": ("float32", "compute"),
    "bf16": ("bfloat16", "compute"),
    "int8": (None, "int8"),
    "fp8": (None, "fp8"),
}


def add_kv_dtype_arg(p: argparse.ArgumentParser,
                     default: str = "f32") -> None:
    """The shared ``--kv-dtype`` serving-precision flag (serve_app;
    bench_serving mirrors it through its own flag parser but resolves
    through the SAME :func:`resolve_kv_cache_dtype`)."""
    p.add_argument(
        "--kv-dtype",
        default=default,
        choices=list(KV_DTYPE_CHOICES),
        help="KV-cache precision: f32/bf16 store the compute dtype "
             "(scale-free); int8/fp8 store one byte per element with "
             "per-row dequant scales — half the pool bytes of bf16, a "
             "quarter of f32, dequantized in the kernel/einsum stream "
             "(docs/quantization.md). fp8 degrades to int8 with a "
             "note on backends without float8_e4m3fn support "
             "(dtypes.supports_fp8)",
    )


def resolve_kv_cache_dtype(spec: str, *, note=print):
    """Resolve a ``--kv-dtype`` value into ``(compute_dtype_override,
    kv_cache_dtype)`` — compute override None means "keep the config's
    dtype". The ONE degrade point: ``fp8`` on a backend that cannot
    execute the fp8 pipeline becomes ``int8`` with a LOUD note (the
    alternative is a deep XLA lowering error mid-serve), so every
    surface that accepts the knob degrades identically."""
    spec = (spec or "f32").strip().lower()
    if spec not in _KV_DTYPE_MAP:
        raise argparse.ArgumentTypeError(
            f"--kv-dtype must be one of {KV_DTYPE_CHOICES}, got "
            f"{spec!r}")
    compute, kv = _KV_DTYPE_MAP[spec]
    if kv == "fp8":
        from hpc_patterns_tpu import dtypes

        if not dtypes.supports_fp8():
            note("NOTE: backend cannot execute float8_e4m3fn "
                 "(dtypes.supports_fp8 probe failed) — degrading "
                 "--kv-dtype fp8 to int8 (same pool bytes, integer "
                 "grid instead of a floating one)")
            kv = "int8"
    return compute, kv


def parse_buckets(spec: str, max_prompt_len: int):
    """Resolve an ``--prompt-buckets`` value into a ladder tuple or
    None: 'none' disables bucketing, 'auto' builds the default ladder
    over ``max_prompt_len``, anything else is comma-separated rungs."""
    spec = (spec or "none").strip().lower()
    if spec == "none":
        return None
    if spec == "auto":
        from hpc_patterns_tpu.models.serving import bucket_ladder

        return bucket_ladder(max_prompt_len)
    try:
        return tuple(int(tok) for tok in spec.split(",") if tok.strip())
    except ValueError as e:
        raise argparse.ArgumentTypeError(
            f"--prompt-buckets must be 'auto', 'none', or "
            f"comma-separated ints, got {spec!r}") from e


def add_autofit_arg(p: argparse.ArgumentParser) -> None:
    """The shared ``--autofit`` flag: every serving surface that can
    consume a FittedConfig (serve_app, plane_app; bench_serving mirrors
    it through its own flag parser) ingests through the SAME
    :func:`load_autofit`, so a config fitted once applies identically
    everywhere."""
    p.add_argument(
        "--autofit",
        default=None,
        metavar="CONFIG",
        help="apply a FittedConfig JSON emitted by `python -m "
             "hpc_patterns_tpu.harness.autofit run.jsonl --emit "
             "CONFIG`: the fitted prompt ladder (and, where the "
             "surface has them, residency / placement / autoscaler "
             "knobs) replace the defaults; explicit flags still win",
    )


def add_explain_args(p: argparse.ArgumentParser) -> None:
    """The shared ``--explain``/``--explain-out`` pair: every serving
    surface (serve_app, plane_app; bench_serving mirrors them through
    its own flag parser) enables request-scoped lifecycle tracing
    (harness/reqtrace.py) the same way and renders the SAME
    per-class tail-attribution table (harness/explain.py) after its
    goodput row — where every p99 went, by lifecycle segment."""
    p.add_argument(
        "--explain",
        action="store_true",
        help="trace request lifecycle segments (queued/prefill/decode/"
             "admit_wait/preempted/swapped_out/prefetch_wait/"
             "migrating/shed) and print the per-class tail-"
             "attribution table; with --log, a kind=reqtrace record "
             "is appended for `python -m hpc_patterns_tpu.harness."
             "explain run.jsonl`",
    )
    p.add_argument(
        "--explain-out",
        default=None,
        metavar="PATH",
        help="also write the attribution digest as JSON "
             "(implies --explain)",
    )


def explain_enabled(args) -> bool:
    """Did this invocation ask for request tracing? (``--explain-out``
    implies ``--explain`` — writing the digest requires recording.)"""
    return bool(getattr(args, "explain", False)
                or getattr(args, "explain_out", None))


def load_autofit(path):
    """Load-and-validate a ``--autofit`` value (None passes through) —
    the one CLI ingestion point over ``autofit.load_fitted``."""
    if not path:
        return None
    from hpc_patterns_tpu.harness import autofit

    return autofit.load_fitted(path)


def add_msg_size_args(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "-p",
        "--log2-elements",
        type=int,
        default=25,
        help="message size = 2**p elements (default 25, ~128 MiB float32)",
    )
    p.add_argument("--dtype", default="float32", help="element dtype (dtypes.REGISTRY key)")


def _nonneg_int(s: str) -> int:
    v = int(s)
    if v < 0:
        raise argparse.ArgumentTypeError(f"must be >= 0, got {v}")
    return v


def add_sweep_args(p: argparse.ArgumentParser, default_min_p: int = 3) -> None:
    """The size-sweep start flag shared by the sweeping apps (pingpong,
    allreduce --sweep): sizes run 2**min_p .. 2**p."""
    p.add_argument(
        "--min-p",
        type=_nonneg_int,
        default=default_min_p,
        help=f"sweep start: 2**min_p elements (default {default_min_p})",
    )


def add_memory_kind_args(p: argparse.ArgumentParser) -> None:
    g = p.add_mutually_exclusive_group()
    g.add_argument(
        "-H",
        "--host",
        dest="memory_kind",
        action="store_const",
        const="pinned_host",
        help="buffers in host memory kind (reference -H, host USM)",
    )
    g.add_argument(
        "-D",
        "--device",
        dest="memory_kind",
        action="store_const",
        const="device",
        help="buffers in device HBM (reference -D, device USM; default)",
    )
    g.add_argument(
        "-S",
        "--shared",
        dest="memory_kind",
        action="store_const",
        const="device",
        help="reference -S shared USM; no TPU analog, treated as device",
    )
    p.set_defaults(memory_kind="device")
