"""Known-clean: the same kernel shapes with scratch sized to the
budget — a lane-aligned f32 accumulator well under the default scoped
limit, and a declared limit that actually covers its double-buffer
(the comm/fused.py pattern: the override is deliberate, justified,
and sufficient)."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _accum_kernel(x_ref, o_ref, acc_ref):
    o_ref[...] = x_ref[...] + acc_ref[...]


def scratch_inside_default_limit(x):
    return pl.pallas_call(
        _accum_kernel,
        out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
        in_specs=[pl.BlockSpec((128, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((128, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        grid=(1,),
        scratch_shapes=[pltpu.VMEM((512, 512), jnp.float32)],
    )(x)


def scratch_inside_declared_limit(x):
    return pl.pallas_call(
        _accum_kernel,
        out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
        in_specs=[pl.BlockSpec((128, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((128, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        grid=(1,),
        scratch_shapes=[pltpu.VMEM((2, 1024, 1024), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=16 * 1024 * 1024),
    )(x)
