"""Multi-process launches: the mpirun -np analog end to end.

The reference's distributed tests are `mpirun -np 4 ./app` CTest cases
(src/CMakeLists.txt:39-50). Here apps/launch.py spawns real OS
processes joined via jax.distributed over a local coordinator, CPU
devices standing in for chips — cross-process collectives,
cross-process MAX timing, and per-rank validation all run for real
(SURVEY.md §4's hardware-free-testing gap, closed at the process
level too).

Tiering: the broad app matrix stays in the slow tier (each case boots
2 jax processes); the distributed-flight-recorder acceptance (ONE
2-process launch) and the jax-free launcher-mechanics cases run tier-1
— the rung-4 contract must hold without `--slow`."""

import json
import sys

import pytest

from hpc_patterns_tpu.apps import launch

slow = pytest.mark.slow  # per-class: this module is no longer all-slow


def _launch(app_args, np_=2, devices=2, slices=0):
    return launch.main([
        "-np", str(np_), "--cpu-devices-per-proc", str(devices),
        *(["--slices", str(slices)] if slices else []), "--",
        sys.executable, "-m", *app_args,
    ])


@slow
class TestLaunch:
    def test_allreduce_ring_4_ranks_2_processes(self, capsys):
        code = _launch(["hpc_patterns_tpu.apps.allreduce_app", "-p", "8",
                        "--repetitions", "2", "--warmup", "1"])
        out = capsys.readouterr().out
        assert code == 0, out
        # every global rank validated, split across the two processes
        for r in range(4):
            assert f"Passed {r}" in out
        assert "world=4" in out

    def test_pingpong_across_processes(self, capsys):
        code = _launch(["hpc_patterns_tpu.apps.pingpong_app", "-p", "6",
                        "--min-p", "6", "--repetitions", "2",
                        "--warmup", "1"])
        out = capsys.readouterr().out
        assert code == 0, out
        assert "ok" in out

    def test_train_dp_across_processes(self, capsys):
        # the flagship train step as true multi-process SPMD: dp=4 over
        # 2 OS processes, gradient all-reduce crossing the process
        # boundary
        code = _launch(["hpc_patterns_tpu.apps.train_app", "--dp", "4",
                        "--steps", "2", "--batch", "8", "--seq", "32",
                        "--d-model", "32", "--n-layers", "1",
                        "--vocab", "128"])
        out = capsys.readouterr().out
        assert code == 0, out

    def test_train_pp_stages_in_separate_processes(self, capsys):
        # 1F1B pipeline with each stage living in a different OS process
        code = _launch(["hpc_patterns_tpu.apps.train_app", "--pp", "2",
                        "--steps", "2", "--batch", "4",
                        "--microbatches", "2", "--seq", "32",
                        "--d-model", "32", "--n-layers", "2",
                        "--vocab", "128"], devices=1)
        out = capsys.readouterr().out
        assert code == 0, out

    def test_train_dcn_dp_slices_across_processes(self, capsys):
        # the multi-slice hybrid-mesh path with REAL process boundaries:
        # --slices 2 makes each OS process one "slice" (the production
        # HPCPAT_SLICE_GROUPING protocol, not a monkeypatch), so the
        # --dcn-dp gradient psum is a genuine DCN-analog collective
        # crossing processes while the tp collectives stay
        # slice-internal (each process's own 4 devices)
        code = _launch(["hpc_patterns_tpu.apps.train_app", "--dcn-dp",
                        "--dp", "-1", "--tp", "2", "--steps", "2",
                        "--batch", "4", "--seq", "32",
                        "--d-model", "32", "--n-layers", "1",
                        "--vocab", "128"], devices=4, slices=2)
        out = capsys.readouterr().out
        assert code == 0, out
        assert "SUCCESS" in out

    def test_train_pp_dcn_dp_slices_across_processes(self, capsys):
        # pp x dcn-dp: the 1F1B stage ppermutes stay slice-internal
        # (each process's own devices) while the once-per-step dp
        # gradient pmean crosses the OS process boundary
        code = _launch(["hpc_patterns_tpu.apps.train_app", "--dcn-dp",
                        "--dp", "-1", "--pp", "2", "--steps", "2",
                        "--batch", "4", "--microbatches", "2",
                        "--seq", "32", "--d-model", "32",
                        "--n-layers", "2", "--vocab", "128"],
                       devices=4, slices=2)
        out = capsys.readouterr().out
        assert code == 0, out
        assert "SUCCESS" in out and "dcn-dp=2" in out

    def test_train_pp_tp_across_processes(self, capsys):
        # Megatron tp inside pipeline stages with the mesh spanning two
        # OS processes: the per-layer tp psums (f/g) and the sharded
        # loss head's reductions run as true cross-process collectives
        code = _launch(["hpc_patterns_tpu.apps.train_app", "--pp", "2",
                        "--tp", "2", "--steps", "2", "--batch", "4",
                        "--microbatches", "2", "--seq", "32",
                        "--d-model", "32", "--n-heads", "4",
                        "--n-layers", "2", "--vocab", "128"])
        out = capsys.readouterr().out
        assert code == 0, out
        assert "SUCCESS" in out and "tp=2" in out

    def test_train_sp_ring_attention_across_processes(self, capsys):
        # ring attention with the sp axis spanning both OS processes:
        # the per-step K/V ppermute crosses the process boundary
        code = _launch(["hpc_patterns_tpu.apps.train_app", "--sp", "4",
                        "--attention", "ring_flash", "--steps", "2",
                        "--batch", "2", "--seq", "32",
                        "--d-model", "32", "--n-layers", "1",
                        "--vocab", "128"])
        out = capsys.readouterr().out
        assert code == 0, out

class TestLauncherMechanics:
    # jax-free children: tier-1 (no backend boot, sub-second cases)

    def test_failure_propagates(self, capsys):
        # a child that exits nonzero must fail the launch (ctest contract)
        code = launch.main([
            "-np", "2", "--",
            sys.executable, "-c", "import sys; sys.exit(3)",
        ])
        out = capsys.readouterr().out
        assert code == 1
        assert "FAILURE" in out

    def test_no_command_is_an_error(self, capsys):
        assert launch.main(["-np", "2"]) == 2
        capsys.readouterr()

    def test_timeout_names_hung_ranks_with_last_output(self, capsys):
        # rank 1 exits immediately; rank 0's pid makes it hang — the
        # timeout report must name ONLY the hung rank and quote its
        # last printed line (what a deadlocked collective debug needs)
        code = launch.main([
            "-np", "2", "--timeout", "2", "--",
            sys.executable, "-c",
            "import os, sys, time\n"
            "pid = int(os.environ['HPCPAT_PROCESS_ID'])\n"
            "print(f'entering collective {pid}', flush=True)\n"
            "time.sleep(0 if pid == 1 else 60)\n",
        ])
        out = capsys.readouterr().out
        assert code == 1
        assert "1/2 rank(s) had not exited" in out
        assert "rank 0: last output: [0] entering collective 0" in out
        assert "rank 1: last" not in out

    def test_timeout_still_harvests_written_traces(self, tmp_path,
                                                   capsys):
        # a hung run is still debuggable: ranks that already handed off
        # their snapshot merge; the hung rank is reported as missing
        snap = {
            "kind": "trace",
            "clock": {"mono0": 0.0, "wall0": 0.0,
                      "mono1": 1.0, "wall1": 1.0},
            "process": {"process_id": 1, "num_processes": 2,
                        "slice_id": 0},
            "sync": [], "capacity": 8, "n_events": 0, "n_dropped": 0,
            "by_cat": {}, "compile": {"count": 0, "total_s": 0.0},
            "mem": {"peak_live_bytes": 0}, "events": [],
        }
        out = tmp_path / "merged.json"
        code = launch.main([
            "-np", "2", "--timeout", "3",
            "--trace-out", str(out),
            "--trace-dir", str(tmp_path / "ranks"),
            "--log", str(tmp_path / "run.jsonl"), "--",
            sys.executable, "-c",
            "import json, os, sys, time\n"
            "pid = int(os.environ['HPCPAT_PROCESS_ID'])\n"
            "d = os.environ['HPCPAT_TRACE_DIR']\n"
            f"snap = {snap!r}\n"
            "if pid == 1:\n"
            "    with open(os.path.join(d, 'rank00001.trace.json'), 'w') as f:\n"
            "        json.dump(snap, f)\n"
            "    sys.exit(0)\n"
            "time.sleep(60)\n",
        ])
        printed = capsys.readouterr().out
        assert code == 1
        assert "timeout" in printed
        assert "only 1/2 rank snapshot(s) harvested" in printed
        assert out.exists()  # the partial merge still landed
        recs = [json.loads(l)
                for l in (tmp_path / "run.jsonl").read_text().splitlines()]
        assert recs[-1]["kind"] == "trace_merged"
        assert recs[-1]["n_ranks"] == 1
        # the hung rank is a TIMEOUT in the fault record, not a
        # worker death — the launcher's own kill must not read as the
        # chaos 'die' signature
        assert recs[-1]["faults"] == {"0": "timeout", "1": "clean"}


class TestCollectiveScheduleLaunch:
    """The desync check, divergent side (tier-1): a deliberately
    divergent worker pair must be named with the exact first-divergent
    (rank, op, seq) at merge time, and a hung worker's last fingerprint
    must surface in the timeout report. The workers drive the REAL
    per-rank recording path (analysis/runtime.py + the trace handoff)
    without booting a jax mesh, so both cases stay tier-1 fast."""

    def test_divergent_worker_named_with_first_divergent_op_seq(
            self, tmp_path, capsys):
        out, log = tmp_path / "merged.json", tmp_path / "run.jsonl"
        worker = (
            "import os\n"
            "from hpc_patterns_tpu.analysis import runtime as rt\n"
            "from hpc_patterns_tpu.harness import trace\n"
            "pid = int(os.environ['HPCPAT_PROCESS_ID'])\n"
            "rec = trace.TraceRecorder(enabled=True)\n"
            "rt.reset_collective_schedule()\n"
            "kw = dict(shape=(2, 8), dtype='float32', axis='x')\n"
            "rt.record_collective('allreduce.collective', 0, **kw)\n"
            "if pid == 0:\n"
            "    rt.record_collective('allreduce.collective', 1, **kw)\n"
            "else:\n"
            "    rt.record_collective('sendrecv_ring', 1, **kw)\n"
            "trace.write_rank_snapshot(rec, os.environ['HPCPAT_TRACE_DIR'])\n"
        )
        code = launch.main([
            "-np", "2", "--trace-out", str(out), "--log", str(log),
            "--", sys.executable, "-c", worker,
        ])
        printed = capsys.readouterr().out
        assert code == 0, printed
        assert "COLLECTIVE SCHEDULE DIVERGENCE at #1" in printed
        assert "rank 0 is at allreduce.collective#1" in printed
        assert "rank 1 is at sendrecv_ring#1" in printed
        recs = [json.loads(l) for l in log.read_text().splitlines()]
        sched = [r for r in recs
                 if r["kind"] == "trace_merged"][0]["schedule"]
        assert sched["verdict"] == "divergent"
        fd = sched["first_divergence"]
        assert fd["index"] == 1
        assert fd["ranks"]["0"] == {"op": "allreduce.collective",
                                    "seq": 1}
        assert fd["ranks"]["1"] == {"op": "sendrecv_ring", "seq": 1}

    def test_timeout_prints_each_ranks_last_fingerprint(
            self, tmp_path, capsys):
        # rank 0 hangs INSIDE its second collective (never reaches the
        # trace handoff); the per-record progress file is what lets the
        # timeout report say WHICH collective it is stuck at — the
        # "rank 0 is at allreduce#17" read of a deadlocked run
        out = tmp_path / "merged.json"
        worker = (
            "import os, sys, time\n"
            "from hpc_patterns_tpu.analysis import runtime as rt\n"
            "pid = int(os.environ['HPCPAT_PROCESS_ID'])\n"
            "rt.record_collective('allreduce.collective', 16)\n"
            "if pid == 1:\n"
            "    rt.record_collective('sendrecv_ring', 17)\n"
            "    sys.exit(0)\n"
            "rt.record_collective('allreduce.collective', 17)\n"
            "time.sleep(60)\n"
        )
        code = launch.main([
            "-np", "2", "--timeout", "8",
            "--trace-out", str(out),
            "--trace-dir", str(tmp_path / "ranks"),
            "--", sys.executable, "-c", worker,
        ])
        printed = capsys.readouterr().out
        assert code == 1
        assert "rank 0: is at allreduce.collective#17" in printed
        assert "2 collective(s) issued" in printed
        assert "rank 1 (exited): was at sendrecv_ring#17" in printed


class TestChaosLaunch:
    """Chaos scenarios verified THROUGH the rollups (tier-1, jax-free
    workers driving the real recording paths): the injected straggler
    is the rank the straggler table names, a chaos-killed worker's
    fault kind lands in the rank report while the survivors' traces
    still merge, and a transient failure recovers under the launcher's
    bounded retry."""

    def test_straggler_rank_named_by_merged_rollup(self, tmp_path,
                                                   capsys):
        # HPCPAT_CHAOS (via --chaos) delays every collective on rank 1
        # by 150ms in the same pre-dispatch position the Communicator
        # hot path injects at; the merged rollup must NAME rank 1 from
        # the windows — straggler table, skew fan — and the schedule
        # verifier must stay consistent (a straggler is late, not
        # divergent). A file barrier kills process-spawn skew so the
        # injected delay dominates the timeline.
        out, log = tmp_path / "merged.json", tmp_path / "run.jsonl"
        worker = (
            "import os, time\n"
            "from hpc_patterns_tpu.harness import chaos, trace\n"
            "from hpc_patterns_tpu.analysis import runtime as rt\n"
            "pid = int(os.environ['HPCPAT_PROCESS_ID'])\n"
            "d = os.environ['HPCPAT_TRACE_DIR']\n"
            "rec = trace.TraceRecorder(enabled=True)\n"
            "rt.reset_collective_schedule()\n"
            "open(os.path.join(d, f'ready{pid}'), 'w').close()\n"
            "while not all(os.path.exists(os.path.join(d, f'ready{q}'))\n"
            "              for q in (0, 1)):\n"
            "    time.sleep(0.005)\n"
            "for seq in range(3):\n"
            "    chaos.maybe_inject('collective', seq)\n"
            "    t = rec.mark_dispatch('comm.allreduce', {'seq': seq})\n"
            "    rt.record_collective('allreduce.collective', seq,\n"
            "                         shape=(2, 8), dtype='float32',\n"
            "                         axis='x')\n"
            "    time.sleep(0.01)\n"
            "    rec.mark_complete('comm.allreduce', t, {'seq': seq})\n"
            "trace.write_rank_snapshot(rec, d)\n"
        )
        code = launch.main([
            "-np", "2", "--trace-out", str(out), "--log", str(log),
            "--trace-dir", str(tmp_path / "ranks"),
            "--chaos", "straggler:rank=1,delay_ms=150",
            "--", sys.executable, "-c", worker,
        ])
        printed = capsys.readouterr().out
        assert code == 0, printed
        assert ("straggler: rank 1 finished last in 3/3 matched "
                "collective(s)") in printed
        assert "collective schedules consistent across 2 rank(s)" in printed
        recs = [json.loads(l) for l in log.read_text().splitlines()]
        rollup = [r for r in recs if r["kind"] == "trace_merged"][0]
        assert rollup["stragglers"]["1"]["last"] == 3
        assert rollup["stragglers"]["0"]["last"] == 0
        # the skew fan carries the injected delay, not just its sign
        skew = rollup["skew"]["comm.allreduce"]
        assert skew["max_start_skew_s"] > 0.1
        assert rollup["schedule"]["verdict"] == "consistent"

    def test_worker_death_fault_kind_and_partial_merge(self, tmp_path,
                                                       capsys):
        # a chaos-killed worker (SIGKILL at collective 1 — no exit
        # handler, exactly an OOM-killed rank) must land in the rank
        # report WITH its fault kind and last collective fingerprint,
        # and the surviving rank's trace must still merge
        out, log = tmp_path / "merged.json", tmp_path / "run.jsonl"
        worker = (
            "import os, time\n"
            "from hpc_patterns_tpu.harness import chaos, trace\n"
            "from hpc_patterns_tpu.analysis import runtime as rt\n"
            "pid = int(os.environ['HPCPAT_PROCESS_ID'])\n"
            "rec = trace.TraceRecorder(enabled=True)\n"
            "rt.reset_collective_schedule()\n"
            "for seq in range(3):\n"
            "    rt.record_collective('allreduce.collective', seq)\n"
            "    chaos.maybe_inject('collective', seq)\n"
            "trace.write_rank_snapshot(rec,\n"
            "                          os.environ['HPCPAT_TRACE_DIR'])\n"
        )
        code = launch.main([
            "-np", "2", "--trace-out", str(out), "--log", str(log),
            "--trace-dir", str(tmp_path / "ranks"),
            "--chaos", "die:rank=1,at=1",
            "--", sys.executable, "-c", worker,
        ])
        printed = capsys.readouterr().out
        assert code == 1
        assert "rank 1: fault: killed (SIGKILL)" in printed
        # the progress file names the collective it died inside
        assert "rank 1: was at allreduce.collective#1" in printed
        assert "only 1/2 rank snapshot(s) harvested" in printed
        assert "FAILURE" in printed
        recs = [json.loads(l) for l in log.read_text().splitlines()]
        rollup = [r for r in recs if r["kind"] == "trace_merged"][0]
        assert rollup["n_ranks"] == 1  # the survivor merged anyway
        assert rollup["faults"] == {"0": "clean",
                                    "1": "killed (SIGKILL)"}

    def test_bad_chaos_spec_is_an_error(self, capsys):
        assert launch.main([
            "-np", "1", "--chaos", "stragler:delay_ms=1", "--",
            sys.executable, "-c", "pass",
        ]) == 2
        assert "bad --chaos spec" in capsys.readouterr().out

    def test_bounded_retry_recovers_transient_failure(self, tmp_path,
                                                      capsys):
        # each rank fails its FIRST attempt (marker file protocol) and
        # succeeds the second: --retry 1 must relaunch after backoff
        # and exit 0; without retries the same launch fails
        marker = tmp_path / "attempt"
        worker = (
            "import os, sys\n"
            f"m = {str(marker)!r} + os.environ['HPCPAT_PROCESS_ID']\n"
            "if not os.path.exists(m):\n"
            "    open(m, 'w').close()\n"
            "    sys.exit(3)\n"
            "sys.exit(0)\n"
        )
        code = launch.main([
            "-np", "2", "--retry", "1", "--retry-backoff", "0.1",
            "--", sys.executable, "-c", worker,
        ])
        printed = capsys.readouterr().out
        assert code == 0, printed
        assert "rank 0: fault: exit 3" in printed
        assert "retrying launch (attempt 2/2)" in printed
        assert "FAILURE" in printed and "SUCCESS" in printed


class TestDistributedTraceMerge:
    """The rung-4 acceptance, tier-1: ONE 2-process launch of the
    allreduce miniapp under --trace must produce a Perfetto-valid
    merged timeline with one pid lane per rank, flow events linking the
    two ranks' windows of each timed collective, a skew/straggler
    rollup on stdout, and a kind=trace_merged record harness.report
    renders."""

    @pytest.fixture(scope="class")
    def merged_run(self, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("dtrace")
        out, log = tmp / "merged.json", tmp / "run.jsonl"
        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            code = launch.main([
                "-np", "2", "--cpu-devices-per-proc", "1",
                "--trace-out", str(out), "--log", str(log), "--",
                sys.executable, "-m",
                "hpc_patterns_tpu.apps.allreduce_app", "-p", "8",
                "--repetitions", "3", "--warmup", "1", "--trace",
            ])
        return code, out, log, buf.getvalue()

    def test_exit_0_and_rollup_printed(self, merged_run):
        code, _out, _log, printed = merged_run
        assert code == 0, printed
        assert "max start skew" in printed
        assert "clock align: sync" in printed  # barrier anchor taken

    def test_collective_schedules_verified_consistent(self, merged_run):
        # the desync check, clean side: both ranks' fingerprint chains
        # (analysis/runtime.py) carry the same digest, so the merge
        # PROVES the rank schedules matched rather than assuming SPMD
        code, _out, log, printed = merged_run
        assert code == 0, printed
        assert "collective schedules consistent across 2 rank(s)" in printed
        recs = [json.loads(l) for l in log.read_text().splitlines()]
        sched = [r for r in recs
                 if r["kind"] == "trace_merged"][0]["schedule"]
        assert sched["verdict"] == "consistent"
        assert sched["n_ranks_recorded"] == 2
        assert sched["n_collectives"] >= 3  # the timed reps at least
        assert sched["digest"]

    def test_merged_json_is_perfetto_valid_with_2_lanes(self, merged_run):
        code, out, _log, printed = merged_run
        assert code == 0, printed
        chrome = json.loads(out.read_text())  # strict JSON
        evs = chrome["traceEvents"]
        assert {e["pid"] for e in evs if e["ph"] != "M"} == {0, 1}
        names = {e["args"]["name"] for e in evs
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert names == {"rank 0/2", "rank 1/2"}
        # B/E pairs stay balanced per (pid, tid) lane after the merge
        stacks = {}
        for e in evs:
            if e["ph"] == "B":
                stacks.setdefault((e["pid"], e["tid"]), []).append(e["name"])
            elif e["ph"] == "E":
                assert stacks[(e["pid"], e["tid"])].pop() == e["name"]
        assert all(not s for s in stacks.values())

    def test_flow_events_link_collective_pairs(self, merged_run):
        code, out, _log, printed = merged_run
        assert code == 0, printed
        evs = json.loads(out.read_text())["traceEvents"]
        flows = [e for e in evs if e["ph"] in ("s", "t", "f")]
        assert flows, "no flow events in merged trace"
        by_id = {}
        for e in flows:
            by_id.setdefault(e["id"], []).append(e)
        crossing = [c for c in by_id.values()
                    if len({e["pid"] for e in c}) >= 2]
        assert crossing, "no flow chain crosses rank lanes"

    def test_report_renders_the_desync_verdict(self, merged_run, capsys):
        code, _out, log, printed = merged_run
        assert code == 0, printed
        from hpc_patterns_tpu.harness import report

        assert report.main([str(log)]) == 0
        out = capsys.readouterr().out
        assert "schedules consistent" in out

    def test_trace_merged_record_and_report(self, merged_run, capsys):
        code, _out, log, printed = merged_run
        assert code == 0, printed
        recs = [json.loads(l) for l in log.read_text().splitlines()]
        merged = [r for r in recs if r["kind"] == "trace_merged"]
        assert len(merged) == 1
        rec = merged[0]
        assert rec["n_ranks"] == 2 and rec["n_matched"] >= 1
        assert rec["align"]["method"] == "sync"
        assert "allreduce" in " ".join(rec["skew"])
        from hpc_patterns_tpu.harness import report

        assert report.main([str(log)]) == 0
        out = capsys.readouterr().out
        assert "trace_merged: 2 rank(s)" in out


class TestServingPlaneLaunch:
    """The launched serving plane (round 10), stub tier: real launcher
    processes, real sockets, real trace/schedule recording — stub
    token generators, so the router's mechanics (placement, KV-handoff
    forwarding, replica death recovery, shed accounting) run tier-1 in
    seconds. The real-engine shape of the same path is the reground
    step-7d leg."""

    def test_disaggregated_stub_plane_traced_merge(self, tmp_path,
                                                   capsys):
        # router + 1 prefill + 1 decode replica: the launch must exit
        # 0 with the stub oracle green, the merged trace must carry
        # the verdict "consistent" (donor and receiver fingerprinted
        # the identical kv_migration schedule), and the KV-handoff
        # flow arrows must thread the two replica LANES
        out, log = tmp_path / "merged.json", tmp_path / "run.jsonl"
        code = launch.main([
            "-np", "3", "--timeout", "60",
            "--trace-out", str(out), "--log", str(log), "--",
            sys.executable, "-m", "hpc_patterns_tpu.apps.plane_app",
            "--stub", "--roles", "prefill,decode",
            "--rdv", str(tmp_path / "rdv"), "--requests", "6",
            "--rate", "10000", "--trace",
        ])
        printed = capsys.readouterr().out
        assert code == 0, printed
        assert "PLANE SUCCESS" in printed
        assert "migrations=6" in printed
        assert "collective schedules consistent across 2 rank(s)" \
            in printed
        merged = json.loads(out.read_text())
        flows = [e for e in merged["traceEvents"]
                 if e.get("cat") == "collective"
                 and e.get("name") == "plane.kv_migration"]
        assert {e["ph"] for e in flows} == {"s", "f"}
        assert len({e["pid"] for e in flows}) == 2  # two replica lanes
        windows = [e for e in merged["traceEvents"]
                   if e.get("name") == "plane.kv_migration"
                   and e.get("ph") == "X"]
        assert len({e["pid"] for e in windows}) == 2
        recs = [json.loads(line)
                for line in log.read_text().splitlines()]
        sched = [r for r in recs
                 if r["kind"] == "trace_merged"][0]["schedule"]
        assert sched["verdict"] == "consistent"
        assert sched["n_collectives"] == 6

    def test_replica_death_resumes_on_survivors(self, tmp_path,
                                                capsys):
        # die chaos targets ONE replica of three (site=replica_round);
        # the router must re-queue its in-flight requests as resumes
        # on survivors — byte-checked by the stub oracle — with the
        # lost replica named in the rank report and on the
        # trace_merged record, and nothing shed silently. The stream
        # is SAMPLED (round 14, the PR 9 remainder): stub tokens come
        # from an evolving per-row key CHAIN, the round replies
        # checkpoint the chain state, and the router hands it back on
        # the death-resume — the oracle walks the chain from key_0,
        # so a resume that LOST the key restarts the chain and
        # diverges at its first resumed token (teeth; the greedy stub
        # oracle stays covered by the disaggregated test above)
        out, log = tmp_path / "merged.json", tmp_path / "run.jsonl"
        code = launch.main([
            "-np", "4", "--timeout", "60",
            "--chaos", "die:replica=2,at=3,site=replica_round",
            "--trace-out", str(out), "--log", str(log), "--",
            sys.executable, "-m", "hpc_patterns_tpu.apps.plane_app",
            "--stub", "--roles", "both,both,both",
            "--rdv", str(tmp_path / "rdv"), "--requests", "9",
            "--rate", "10000", "--budget", "16",
            "--temperature", "0.7", "--trace",
        ])
        printed = capsys.readouterr().out
        assert code == 1  # a rank died: the launch fails loudly...
        assert "PLANE SUCCESS" in printed  # ...but the PLANE recovered
        assert "replica 2 died" in printed
        assert "deaths=[2]" in printed
        # every re-queued stream finished byte-exact (the stub oracle
        # inside PLANE SUCCESS) and nothing was dropped silently:
        # served + shed must account for all 9
        assert "served 9/9" in printed
        assert "resumed=[" in printed and "resumed=[]" not in printed
        # the rank report names the lost replica with its fault kind
        assert "rank 2: fault: killed (SIGKILL)" in printed
        recs = [json.loads(line)
                for line in log.read_text().splitlines()]
        tm = [r for r in recs if r["kind"] == "trace_merged"][0]
        assert tm["faults"]["2"] == "killed (SIGKILL)"
        assert tm["faults"]["0"] == "clean"
