"""Guard the slow-tier selection logic against pytest private-API drift.

conftest._markexpr_selects_slow leans on pytest's private
``_pytest.mark.expression.Expression``; if a pytest upgrade changes that
API, the function silently falls back to a substring check that gives
DIFFERENT answers for several expressions CI actually uses. The cases
below include discriminators ("not slow" → False, "slowish" → False)
where the fallback would answer True — so an API drift fails here
loudly instead of silently flipping which tier runs.
"""

from conftest import _markexpr_selects_slow


def test_expressions_that_select_slow():
    assert _markexpr_selects_slow("slow")
    assert _markexpr_selects_slow("slow and tpu")
    assert _markexpr_selects_slow("slow or tpu")
    assert _markexpr_selects_slow("(slow)")


def test_expressions_that_do_not_select_slow():
    # discriminators: the substring fallback would return True for
    # every one of these — if any fails, the private API drifted
    assert not _markexpr_selects_slow("not slow")
    assert not _markexpr_selects_slow("not (slow)")
    assert not _markexpr_selects_slow("not  slow")
    assert not _markexpr_selects_slow("slowish")


def test_empty_and_unrelated():
    assert not _markexpr_selects_slow("")
    assert not _markexpr_selects_slow("tpu")
