"""Dtype traits — TPU-native analog of the reference's
``aurora.mpich.miniapps/src/include/mpi_datatype.hpp`` (C9 in SURVEY.md).

The reference maps C++ scalar types to MPI datatypes via a trait template
with 10 specializations and an ``MPI_BYTE`` default (mpi_datatype.hpp:24-51).
XLA collectives are dtype-generic already, so the TPU equivalent is a
registry of *supported, tested* dtypes with their collective/compute
properties (bf16 is the MXU-native type; integer allreduce must be exact),
plus the same "default = bytes" escape hatch: any unlisted dtype is handled
by bitcasting to uint8 words, like the reference's MPI_BYTE default.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DtypeTraits:
    dtype: jnp.dtype
    itemsize: int
    exact_sum: bool  # integer/exact accumulate: validation uses ==, not isclose
    mxu_native: bool  # preferred MXU input type
    tolerance: float  # allreduce validation tolerance (reference: 1e-6,
    # allreduce-mpi-sycl.cpp:197)


def _t(dt, exact, mxu, tol) -> DtypeTraits:
    dt = jnp.dtype(dt)
    return DtypeTraits(dt, dt.itemsize, exact, mxu, tol)


# The 10 scalar specializations of mpi_datatype.hpp:28-51 map onto these.
REGISTRY: dict[str, DtypeTraits] = {
    "float32": _t(jnp.float32, False, False, 1e-6),
    "float16": _t(jnp.float16, False, False, 1e-2),
    "bfloat16": _t(jnp.bfloat16, False, True, 1e-2),
    "float64": _t(jnp.float64, False, False, 1e-12),  # x64 mode only
    "int8": _t(jnp.int8, True, False, 0.0),
    "int16": _t(jnp.int16, True, False, 0.0),
    "int32": _t(jnp.int32, True, False, 0.0),
    "int64": _t(jnp.int64, True, False, 0.0),  # x64 mode only
    "uint8": _t(jnp.uint8, True, False, 0.0),
    "uint32": _t(jnp.uint32, True, False, 0.0),
}


def get_traits(dtype) -> DtypeTraits:
    """Traits for ``dtype``; unlisted dtypes get the byte-default treatment
    (exact, bytewise), mirroring the reference's MPI_BYTE fallback
    (mpi_datatype.hpp:24-26)."""
    name = jnp.dtype(dtype).name
    if name in REGISTRY:
        return REGISTRY[name]
    dt = jnp.dtype(dtype)
    return DtypeTraits(dt, dt.itemsize, True, False, 0.0)


_FP8_SUPPORT: bool | None = None


def supports_fp8() -> bool:
    """Does this backend execute the fp8 KV pipeline — store
    ``float8_e4m3fn``, convert to f32, and matmul the dequantized
    values? Probed ONCE per process by running the exact op sequence
    the quantized decode path uses (quantize-cast, dequant-cast, a
    tiny f32 matmul over the result) on the default backend; any
    lowering/execution error reads as "no". Callers
    (``harness.cli.resolve_kv_cache_dtype`` — the serving CLIs'
    ``--kv-dtype`` resolver) degrade fp8 to int8 WITH A NOTE instead
    of letting the user hit a deep XLA error mid-serve."""
    global _FP8_SUPPORT
    if _FP8_SUPPORT is None:
        import jax
        import jax.numpy as jnp

        try:
            x = jnp.linspace(-1.0, 1.0, 16, dtype=jnp.float32)
            q = (x * 448.0).astype(jnp.float8_e4m3fn)
            # jaxlint: disable=recompile-hazard — one-shot probe: the
            # result is memoized in _FP8_SUPPORT for the process
            # lifetime, so this jit builds exactly once
            y = jax.jit(lambda a: jnp.dot(
                a.astype(jnp.float32).reshape(4, 4),
                a.astype(jnp.float32).reshape(4, 4)))(q)
            _FP8_SUPPORT = bool(np.isfinite(np.asarray(y)).all())
        except Exception:  # noqa: BLE001 — any failure means "no fp8"
            _FP8_SUPPORT = False
    return _FP8_SUPPORT


def validate_allreduce(result: np.ndarray, expected_scalar, dtype) -> bool:
    """The analytic-oracle check: every element equals the closed-form
    expected value (allreduce-mpi-sycl.cpp:192-204)."""
    traits = get_traits(dtype)
    if traits.exact_sum:
        # Compare in the original (integer) dtype — a float64 cast would
        # lose precision past 2**53 and false-PASS wrong int64 results.
        arr = np.asarray(result)
        return bool(np.all(arr == arr.dtype.type(expected_scalar)))
    arr = np.asarray(result, dtype=np.float64)
    expected = float(expected_scalar)
    bound = traits.tolerance + 1e-6 * abs(expected)  # atol + rtol form
    return bool(np.all(np.abs(arr - expected) <= bound))
