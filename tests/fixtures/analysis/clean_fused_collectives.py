"""Known-clean: fused collectives issued unconditionally on every rank
(rank branches stay data-only), and every ``fused_permute`` pair list
sanitized by ``check_permutation`` first — the ``comm.fused`` module's
own discipline."""

import jax.numpy as jnp
from jax import lax

from hpc_patterns_tpu.comm import fused
from hpc_patterns_tpu.comm.ring import check_permutation


def data_only_rank_branch(x, axis):
    me = lax.axis_index(axis)
    contribution = jnp.where(me == 0, x, -x)
    return fused.fused_allreduce(contribution, axis)


def same_sequence_both_arms(x, w, axis, use_bias):
    if use_bias:
        y = fused.allgather_matmul(x, w, axis)
    else:
        y = fused.allgather_matmul(x, w, axis)
    return fused.allreduce_into(y, axis)


def checked_pairs_fused(x, size):
    pairs = [(i, (i + 3) % size) for i in range(size)]
    check_permutation(pairs, size)
    return fused.fused_permute(x, "x", pairs)
