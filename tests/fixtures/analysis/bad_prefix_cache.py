"""Known-bad: prefix-sharing admission hazards, minimized.

The round-12 sharing arena's admission path (radix match -> map shared
pages -> tail prefill -> decref releases) is HOST trie/list work that
runs inside the admission window, with or behind an in-flight decode
chunk — so the hazard class is a device readback smuggled into those
paths (``DEFAULT_DISPATCH_CRITICAL`` names them): a sync there stalls
exactly the prefill the cache exists to skip, and the bubble rollup
then blames admission for latency the match caused.

Lines carrying ``EXPECT: <rule>`` markers are the golden findings
tests/test_analysis.py asserts, line-exact.
"""

import numpy as np

import jax


def _prefix_match(engine, prompt):
    # "verifying" the cached chain against live cursors forces a
    # readback of state the in-flight chunk is still writing — the
    # match is a HOST trie walk over tokens, never a device question
    pos_now = np.asarray(engine.pos)  # EXPECT: host-sync-in-dispatch
    chain = engine._prefix.match(prompt, engine._bucket_len(prompt.size))
    return chain if pos_now[0] >= 0 else []


def _insert_prefix(engine, prompt, rung, pages):
    # blocking on the tail prefill before publishing the chain stalls
    # the chunk the prefill was dispatched behind; insertion needs only
    # the PAGE IDS, which are host bookkeeping — the bytes can land
    # whenever the device gets there
    jax.block_until_ready(engine.cache["k"])  # EXPECT: host-sync-in-dispatch
    engine._prefix.insert(prompt, rung, pages)


def _decref_pages(engine, pages):
    # the release funnel is pure refcount arithmetic; reading the pool
    # back to "check the page is quiescent" serializes every
    # completion behind the device queue
    _ = np.array(jax.device_get(engine.cache["k"][0]))  # EXPECT: host-sync-in-dispatch
    for p in pages:
        r = engine._page_refs[p] - 1
        if r:
            engine._page_refs[p] = r
        else:
            del engine._page_refs[p]
            engine.free_pages.append(p)
