"""Speculative decoding: a small draft model proposes, the target
model verifies in one batched pass. Greedy AND sampling modes.

The serving-latency play the KV-cache machinery enables: plain decode
is one big-model forward per token (cache-read-bound,
benchmarks/RESULTS.md); here a cheap draft model runs ``gamma``
sequential steps and the target scores the whole proposed chunk with
ONE ``decode.extend_step`` — large-matmul shapes instead of gamma
sequential single-token reads. With greedy acceptance the output is
PROVABLY identical to the target's own greedy decode, whatever the
draft proposes (the oracle the tests pin): accepted proposals are
exactly the tokens the target would have picked, and the first
disagreement is replaced by the target's token.

With ``temperature > 0`` the verify step is the standard
rejection-sampling acceptance (speculative sampling): proposal j drawn
from the draft's warped distribution q_j is accepted with probability
min(1, p_j(x_j)/q_j(x_j)) against the target's warped p_j; the first
rejection is replaced by a draw from the residual norm(max(p_j − q_j,
0)), and a fully-accepted round appends a bonus draw from p_gamma. The
emitted sequence is distributed EXACTLY as target-only sampling at the
same temperature/top_k (the warped distributions are what
decode._pick samples) — the distribution-exactness oracle in
tests/test_decode.py pins the accept/resample primitive against the
analytic law. Both modes share the distributions through one
``_accept_resample``: greedy is the temperature→0 limit evaluated
exactly (argmax + first-mismatch), not a separate bookkeeping path.

Bookkeeping invariant (both caches, one shared position cursor): at the
top of each iteration the caches hold K/V for the prompt and every
emitted token EXCEPT the last, which is ``cur`` (pending). The draft
runs gamma+1 steps (the +1 writes the last proposal's K/V so a fully
accepted round leaves no hole), the target extend writes
[cur, proposals...]; rejected rows go stale and are simply overwritten
when the cursor re-crosses them — position masking makes stale rows
invisible (the same static-shape trick as the cache itself).

Batch is 1 per call: acceptance lengths diverge per sequence, and a
per-row position cursor cannot drive a single dynamic_update_slice
(vmap over sequences instead if needed).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from hpc_patterns_tpu.models.decode import (
    _pick,
    _topk_mask,
    decode_step,
    extend_step,
    init_paged_cache,
    paged_decode_step,
    paged_extend_step,
    paged_prefill,
    prefill,
)
from hpc_patterns_tpu.models.transformer import TransformerConfig


def _warp(logits, temperature, top_k: int):
    """The warped next-token distribution ``decode._pick`` samples:
    the SHARED ``_topk_mask`` support then temperature softmax —
    _pick's categorical over masked-logits/temperature IS this softmax,
    by construction (one mask definition, no drift). (..., V) f32."""
    masked = _topk_mask(logits.astype(jnp.float32), top_k)
    return jax.nn.softmax(masked / temperature, axis=-1)


def _accept_resample(key, props, q_probs, p_probs):
    """The speculative-sampling verify primitive (one round).

    ``props``: (gamma,) proposal tokens drawn from the draft rows;
    ``q_probs``: (gamma, V) the draft's warped distributions;
    ``p_probs``: (gamma+1, V) the target's warped distributions at the
    same positions (+1 = the bonus row). Returns ``(a, nxt)``: the
    accepted-prefix length (proposal j accepted with probability
    min(1, p_j(x_j)/q_j(x_j)), stopping at the first rejection) and
    the round's closing token — a draw from the residual
    norm(max(p_a − q_a, 0)) on rejection, or from p_gamma when all
    gamma proposals were accepted (padding q with a zeros row makes
    those the same expression). The emitted law [props[:a], nxt] is
    exactly target-only ancestral sampling — the oracle test draws this
    many times and checks the first-token marginal equals p analytically.
    """
    gamma = props.shape[0]
    k_acc, k_nxt = jax.random.split(key)
    sel = jnp.arange(gamma)
    p_at = p_probs[sel, props]
    q_at = q_probs[sel, props]
    u = jax.random.uniform(k_acc, (gamma,))
    accept = u * q_at < jnp.minimum(q_at, p_at)  # u < min(1, p/q), q>0
    a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))
    q_padded = jnp.concatenate(
        [q_probs, jnp.zeros_like(q_probs[:1])], axis=0
    )
    res = jnp.maximum(p_probs[a] - q_padded[a], 0.0)
    res_sum = jnp.sum(res)
    # p == q exactly leaves an empty residual; the limit law is p itself
    dist = jnp.where(res_sum > 1e-12, res / res_sum, p_probs[a])
    nxt = jax.random.categorical(k_nxt, jnp.log(dist + 1e-30))
    return a, nxt.astype(jnp.int32)


@partial(jax.jit, static_argnums=(1, 3, 5, 6, 8, 9, 11))
def _speculative_jit(params, cfg, draft_params, draft_cfg, prompt,
                     new_tokens, gamma, key=None, greedy=True, top_k=0,
                     temperature=1.0, mesh=None):
    B, T = prompt.shape
    max_len = T + new_tokens + gamma + 1  # slack for the final round
    logits, cache = prefill(params, prompt, cfg, max_len, mesh=mesh)
    _, dcache = prefill(draft_params, prompt, draft_cfg, max_len,
                        mesh=mesh)
    if key is None:
        key = jax.random.PRNGKey(0)  # unused in greedy mode
    key, sub = jax.random.split(key)
    first = _pick(logits, sub, temperature, greedy, top_k)  # (1,)

    out = jnp.zeros((new_tokens + gamma + 1,), jnp.int32)
    out = out.at[0].set(first[0])

    def cond(state):
        _, _, _, _, n_out, _ = state
        return n_out < new_tokens

    def iteration(state):
        cache, dcache, pos, cur, n_out, key = state
        # --- draft proposes gamma tokens (gamma+1 steps: the extra one
        # writes the last proposal's K/V — see module docstring)
        props = []
        qs = []
        tok = cur
        dc = dcache
        for j in range(gamma + 1):
            dlogits, dc = decode_step(draft_params, dc, pos + j, tok,
                                      draft_cfg, mesh=mesh)
            key, sub = jax.random.split(key)
            tok = _pick(dlogits, sub, temperature, greedy, top_k)
            if j < gamma:
                props.append(tok[0])
                if not greedy:
                    qs.append(_warp(dlogits[0], temperature, top_k))
        props = jnp.stack(props)  # (gamma,)

        # --- target verifies [cur, props] in ONE extend
        chunk = jnp.concatenate([cur, props])[None, :]  # (1, gamma+1)
        vlogits, cache = extend_step(params, cache, pos, chunk, cfg)

        if greedy:
            # exact temperature->0 limit: accept while the proposal IS
            # the target argmax; replace the first mismatch with it
            t_all = jnp.argmax(vlogits[0], axis=-1).astype(jnp.int32)
            matches = (props == t_all[:gamma]).astype(jnp.int32)
            a = jnp.sum(jnp.cumprod(matches))
            nxt = t_all[a]
        else:
            key, sub = jax.random.split(key)
            a, nxt = _accept_resample(
                sub, props, jnp.stack(qs),
                _warp(vlogits[0], temperature, top_k),
            )
        # emitted this round: props[:a] then nxt (positions > a are
        # filler, overwritten by the next round's slice)
        props_padded = jnp.concatenate([props, props[-1:]])
        emit = jnp.where(jnp.arange(gamma + 1) < a, props_padded, nxt)
        return cache, dc, pos + a + 1, nxt[None], n_out + a + 1, key, emit

    def body(state_out):
        state, out = state_out
        n_out = state[4]
        cache, dc, pos2, cur2, n_out2, key2, emit = iteration(state)
        out = lax.dynamic_update_slice(out, emit, (n_out,))
        return (cache, dc, pos2, cur2, n_out2, key2), out

    state = (cache, dcache, jnp.int32(T), first, jnp.int32(1), key)
    (state, out) = lax.while_loop(
        lambda so: cond(so[0]),
        body,
        (state, out),
    )
    return out[:new_tokens][None, :]


def _validate(cfg, draft_cfg, prompt_len, new_tokens, gamma):
    """The shared argument guards of both entry points."""
    if cfg.vocab != draft_cfg.vocab:
        raise ValueError(
            f"draft vocab {draft_cfg.vocab} != target vocab {cfg.vocab}"
        )
    if new_tokens < 1:
        raise ValueError(f"new_tokens must be >= 1, got {new_tokens}")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if prompt_len + new_tokens + gamma + 1 > min(cfg.max_seq,
                                                 draft_cfg.max_seq):
        raise ValueError(
            f"prompt {prompt_len} + new {new_tokens} + gamma slack "
            f"{gamma + 1} exceeds max_seq "
            f"{min(cfg.max_seq, draft_cfg.max_seq)}"
        )


def _sampling_args(cfg, temperature, top_k, key):
    """Shared sampling-argument guards (mirrors decode.generate)."""
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if not 0 <= top_k <= cfg.vocab:
        raise ValueError(f"top_k {top_k} outside [0, vocab]")
    greedy = temperature <= 0.0
    return (key, greedy, int(top_k),
            jnp.float32(max(temperature, 1e-6)))


def speculative_generate(params, cfg: TransformerConfig, draft_params,
                         draft_cfg: TransformerConfig, prompt,
                         new_tokens: int, *, gamma: int = 4, key=None,
                         temperature: float = 0.0, top_k: int = 0,
                         mesh=None):
    """Continuation (1, new_tokens) int32. Greedy by default —
    token-identical to ``greedy_generate(params, prompt, cfg,
    new_tokens)``: the draft only changes HOW FAST tokens come, never
    which tokens. With ``temperature > 0`` (``key`` required), the
    rejection-sampling verify makes the output distributed exactly as
    ``generate(..., temperature, top_k)`` — same law, not same draws
    (the two consume randomness differently).

    ``prompt``: (1, T); ``gamma``: proposals per round (the draft/target
    cost ratio picks it — more acceptance, longer verified chunks).
    Both configs must share the vocabulary; compute-dtype caches.
    ``mesh``: tp-sharded serving — the prefills and the draft's decode
    steps take the shard_map flash route (decode.generate's contract);
    the verification extend is GSPMD-partitioned einsum math already.
    """
    if prompt.shape[0] != 1:
        raise ValueError(
            "speculative decoding is per-sequence (batch 1): acceptance "
            "lengths diverge per row; vmap over sequences instead"
        )
    _validate(cfg, draft_cfg, prompt.shape[1], new_tokens, gamma)
    key, greedy, top_k, temperature = _sampling_args(
        cfg, temperature, top_k, key
    )
    return _speculative_jit(params, cfg, draft_params, draft_cfg, prompt,
                            new_tokens, gamma, key, greedy, top_k,
                            temperature, mesh)


def paged_round(params, cfg, draft_params, draft_cfg, cache, dcache,
                pos_eff, cur, gamma: int, key, greedy: bool,
                top_k: int, temperature, mesh=None):
    """ONE batched draft/verify round on the ragged paged caches — THE
    shared speculative round body (``_speculative_batched_ragged_jit``
    and the serving engine's draft-assisted rounds both call it; an
    acceptance/emit fix lands in both or neither).

    The draft runs gamma+1 ragged steps from each row's own cursor
    (the extra one writes the last proposal's K/V, the cache
    invariant); the target verifies ``[cur, props]`` in one ragged
    paged extend; acceptance is greedy-exact or rejection-sampling per
    row. Returns ``(cache, dcache, a, emit, key)``: per-row
    accepted-prefix lengths (B,) and the round's tokens
    (B, gamma+1) — positions > a are filler the caller masks.

    ``mesh``: tp-sharded rounds — the draft's ragged steps take the
    shard_map paged-kernel route (kv-head blocks), while the ragged
    extend is pure XLA scatter/gather/einsum math and partitions via
    GSPMD from the sharded params/pools alone.

    ``temperature``: a scalar, or PER-ROW ``(B,)`` temperatures — the
    serving engine's per-request sampling knob; each row's draft picks
    and warped accept/resample distributions use its own value."""
    B = pos_eff.shape[0]
    temperature = jnp.asarray(temperature, jnp.float32)
    per_row = temperature.ndim == 1
    t_draft = temperature[:, None] if per_row else temperature
    t_verify = temperature[:, None, None] if per_row else temperature
    props = []
    qs = []
    tok = cur
    dc = dcache
    for j in range(gamma + 1):
        dlogits, dc = paged_decode_step(draft_params, dc, pos_eff + j,
                                        tok, draft_cfg, mesh=mesh)
        key, sub = jax.random.split(key)
        tok = _pick(dlogits, sub, t_draft, greedy, top_k)
        if j < gamma:
            props.append(tok)
            if not greedy:
                qs.append(_warp(dlogits, t_draft, top_k))
    props = jnp.stack(props, axis=1)  # (B, gamma)

    chunk = jnp.concatenate([cur[:, None], props], axis=1)
    vlogits, cache = paged_extend_step(params, cache, pos_eff, chunk,
                                       cfg)
    if greedy:
        t_all = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)
        matches = (props == t_all[:, :gamma]).astype(jnp.int32)
        a = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)  # (B,)
        nxt = t_all[jnp.arange(B), a]
    else:
        key, sub = jax.random.split(key)
        a, nxt = jax.vmap(_accept_resample)(
            jax.random.split(sub, B), props,
            jnp.stack(qs, axis=1),
            _warp(vlogits, t_verify, top_k),
        )
    props_padded = jnp.concatenate([props, props[:, -1:]], axis=1)
    emit = jnp.where(jnp.arange(gamma + 1)[None, :] < a[:, None],
                     props_padded, nxt[:, None])
    return cache, dc, a, emit, key


@partial(jax.jit, static_argnums=(1, 3, 5, 6, 8, 9, 11))
def _speculative_batched_ragged_jit(params, cfg, draft_params, draft_cfg,
                                    prompts, new_tokens, gamma, key,
                                    greedy, top_k, temperature,
                                    mesh=None):
    """Per-row-progress batched speculative decoding on the ragged
    paged machinery: ONE batched draft/verify round per iteration,
    every row advancing at its OWN acceptance rate through per-row
    position cursors (the serving building block), instead of vmap
    lifting B independent single-row loops (whose per-row cache DUS
    becomes a full-cache scatter per lane per step). Rows that reach
    ``new_tokens`` freeze: their cursors stop, their (masked) writes
    land inside pages they still own, and their emit slots re-write
    the existing values."""
    B, T = prompts.shape
    # slack: the final active round can run gamma+1 past new_tokens
    max_len = T + new_tokens + gamma + 1
    page = 128 if max_len > 128 else 16
    pages = -(-max_len // page)

    cache = init_paged_cache(cfg, B, pages, page)
    dcache = init_paged_cache(draft_cfg, B, pages, page)
    logits, cache = paged_prefill(params, prompts, cfg, cache, page,
                                  mesh=mesh)
    _, dcache = paged_prefill(draft_params, prompts, draft_cfg, dcache,
                              page, mesh=mesh)
    if key is None:
        key = jax.random.PRNGKey(0)  # unused in greedy mode
    key, sub = jax.random.split(key)
    first = _pick(logits, sub, temperature, greedy, top_k)  # (B,)

    out = jnp.zeros((B, new_tokens + gamma + 1), jnp.int32)
    out = out.at[:, 0].set(first)
    rows = jnp.arange(B)

    def cond(state):
        _, _, _, _, n_out, _, _ = state
        return jnp.any(n_out < new_tokens)

    def body(state):
        cache, dcache, pos, cur, n_out, key, out = state
        active = n_out < new_tokens
        # frozen rows keep stepping (one batched kernel serves all
        # rows) but at a CLAMPED position so they can never run past
        # their page allocation; their garbage lands in pages they own
        pos_eff = jnp.where(active, pos, 0)

        cache, dc, a, emit, key = paged_round(
            params, cfg, draft_params, draft_cfg, cache, dcache,
            pos_eff, cur, gamma, key, greedy, top_k, temperature,
            mesh=mesh)
        nxt = emit[rows, a]
        # emitted this round per row: props[:a], then nxt; frozen rows
        # re-write their existing slots (gather-old / where / scatter)
        idx = jnp.minimum(n_out[:, None] + jnp.arange(gamma + 1),
                          out.shape[1] - 1)
        old = out[rows[:, None], idx]
        out = out.at[rows[:, None], idx].set(
            jnp.where(active[:, None], emit, old))
        adv = jnp.where(active, a + 1, 0)
        return (cache, dc, pos + adv, jnp.where(active, nxt, cur),
                n_out + adv, key, out)

    state = (cache, dcache, jnp.full((B,), T, jnp.int32), first,
             jnp.ones((B,), jnp.int32), key, out)
    state = lax.while_loop(cond, body, state)
    return state[6][:, :new_tokens]


def speculative_generate_batched(params, cfg: TransformerConfig,
                                 draft_params,
                                 draft_cfg: TransformerConfig, prompts,
                                 new_tokens: int, *, gamma: int = 4,
                                 key=None, temperature: float = 0.0,
                                 top_k: int = 0, impl: str = "ragged",
                                 mesh=None):
    """Batched speculative decoding, (B, new_tokens) int32.

    ``impl="ragged"`` (default): per-row-progress on the ragged paged
    machinery — one batched draft/verify round per iteration with
    per-row position cursors, each row advancing at its own acceptance
    rate (greedy output row-wise token-identical to
    :func:`speculative_generate`; sampling rows draw from the same law
    but consume randomness differently than the vmap form). ``mesh``:
    tp-sharded serving — draft steps ride the shard_map paged-kernel
    route, the ragged extend partitions via GSPMD.

    ``impl="vmap"``: the round-3 form — ``jax.vmap`` over per-row
    loops (each lane's cache update lifts to a full-cache scatter;
    kept for comparison and for exact per-row key-fold reproducibility
    with per-sequence sampling calls). Single-device (vmap over the
    shard_map route is not supported).

    Wall-clock note (both impls): the CALL returns when the slowest
    row finishes — that is batch semantics, not an impl property; for
    throughput past it, serve via models/serving.py's continuous
    batching."""
    if prompts.ndim != 2:
        raise ValueError(f"prompts must be (B, T), got {prompts.shape}")
    _validate(cfg, draft_cfg, prompts.shape[1], new_tokens, gamma)
    key, greedy, top_k, temperature = _sampling_args(
        cfg, temperature, top_k, key
    )
    if impl == "ragged":
        return _speculative_batched_ragged_jit(
            params, cfg, draft_params, draft_cfg, prompts, new_tokens,
            gamma, key, greedy, top_k, temperature, mesh)
    if impl != "vmap":
        raise ValueError(f"impl must be 'ragged' or 'vmap', got {impl!r}")
    if mesh is not None:
        raise ValueError(
            "impl='vmap' is single-device (vmap over the shard_map "
            "route is unsupported); use impl='ragged' with a mesh")
    # greedy mode still threads per-row keys through vmap (unused by the
    # accept path); split a fixed root so the dummies share the REAL
    # keys' dtype/format — raw uint32 zeros relied on the deprecated
    # legacy-key acceptance and break under typed keys
    keys = jax.random.split(
        key if key is not None else jax.random.PRNGKey(0),
        prompts.shape[0])

    def one(row, k):
        return _speculative_jit(params, cfg, draft_params, draft_cfg,
                                row[None, :], new_tokens, gamma, k,
                                greedy, top_k, temperature)[0]

    return jax.vmap(one)(prompts, keys)
