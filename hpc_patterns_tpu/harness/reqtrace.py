"""Request-scoped lifecycle tracing: where every request's time went.

The observability ladder so far (metrics -> flight recorder -> cross-
rank merge -> autofit) is device- and phase-centric: the rollups can
say the admission bubble was 12% of the run, but nothing can answer
"why was THIS request's TTFT 3x the p50?" — the per-request stats
table carries only endpoint stamps (``t_submit``/``t_first``/
``t_finish``), so queueing, preemption, swap-out, and cross-replica
migration time are indistinguishable inside the interval. This module
is the next rung: the unit is the **segment**, one per lifecycle state
the engine already owns a transition for:

``queued`` (submitted, not yet admitted), ``admit_wait`` (inside the
admission pass that seats it — the per-request share of the admission
bubble), ``prefill`` (admission dispatch -> first-token readback),
``decode`` (first token -> completion), ``preempted`` (evicted back to
the queue, awaiting re-admission), ``swapped_out`` (paged to the host
tier), ``prefetch_wait`` (host->HBM pull in flight), ``migrating``
(exported from one engine, not yet installed in another), ``shed``
(terminal zero-length marker), and ``untracked`` — the explicit filler
for any span no stamp claimed.

The load-bearing contract is the **coverage invariant**: a finished
request's finalized segments tile ``[t_submit, t_finish]`` exactly
(:func:`finalize`), with gaps surfacing as ``untracked`` segments so
unattributed time is a measured number, not silence. Cross-replica,
the history rides the :class:`~hpc_patterns_tpu.models.serving.
MigrationBundle` and the wire codec as a backward-compatible field
(the PR 17 ``transport``-field pattern: new writers always write it,
a reader of a legacy artifact decodes the absent key to ONE
``untracked`` segment — :data:`LEGACY_SEGMENTS`).

Zero-cost when disabled, same discipline as harness/trace.py and
harness/chaos.py: every engine/router stamp site does ONE module-
global read (:func:`active`) and nothing else. The stamp helpers
themselves are dispatch-critical (jaxlint names them): they run inside
the serving loop with chunks in flight, so they must stay pure host
list work — a device readback to "timestamp precisely" would stall
exactly the pipeline the attribution exists to explain.

Import-light (stdlib only — no numpy, no jax): the launched plane's
jax-free stub tier stamps through the same module.

Consumers: ``harness/explain.py`` renders per-class tail attribution
and the worst-N digest from the ``kind=reqtrace`` RunLog record this
module snapshots; ``harness/collect.py`` threads each request as a
Perfetto lane (the segments are mirrored into the flight recorder at
finish when one is active) with flow arrows into the matched
migration windows. docs/observability.md#request-forensics.
"""

from __future__ import annotations

import time
from typing import Any, Iterable, Mapping

#: every segment kind a stamp may open (``untracked`` is synthesized
#: by :func:`finalize`, never stamped)
SEGMENT_KINDS = (
    "queued", "admit_wait", "prefill", "decode", "preempted",
    "swapped_out", "prefetch_wait", "migrating", "shed", "untracked",
)

#: what an ABSENT ``segments`` field on a legacy wire artifact decodes
#: to (serving_plane/migration.bundle_from_wire): one open untracked
#: segment — :meth:`ReqTrace.install_history` resolves its start to
#: the bundle's ``t_submit`` and its end to the install instant, so a
#: pre-round-18 bundle's whole donor-side life is one measured
#: untracked span, not a silent gap
LEGACY_SEGMENTS = (("untracked", None, None),)

#: tiling tolerance (seconds): gaps below it are clock-stamp noise and
#: are absorbed, not reported as untracked
EPS_S = 1e-7


def _now() -> float:
    return time.perf_counter()


class ReqTrace:
    """Per-request segment recorder.

    Segments are compact JSON-able lists ``[kind, t0, t1, meta]``
    (``t1`` is None while the segment is open; ``meta`` an optional
    dict — e.g. the plane migration sequence number, for the merge's
    flow arrows). Histories are keyed by ``seq_id`` — the engine's
    and the plane's request ids share one space per recorder, exactly
    like the stats tables they annotate. All stamps are
    ``time.perf_counter`` instants: one recorder = one clock (the
    launched plane stamps ONLY at its router for this reason).
    """

    def __init__(self, *, enabled: bool = True):
        self.enabled = enabled
        self._segs: dict[int, list[list]] = {}

    # -- stamping (dispatch-critical: pure host list work) ---------------

    def _open(self, segs: list[list]) -> list | None:
        if segs and segs[-1][2] is None:
            return segs[-1]
        return None

    def _close(self, segs: list[list], t: float) -> None:
        seg = self._open(segs)
        if seg is not None:
            t0 = seg[1]
            seg[2] = t if t0 is None else max(t, t0)

    def begin_request(self, seq_id: int, t: float | None = None) -> None:
        """Open the ``queued`` segment at submit time. A re-begin of a
        known id CONTINUES its history (the plane's death-resume path
        resubmits the same plane-global id to a surviving replica —
        one user-visible life, one tiling)."""
        t = _now() if t is None else t
        segs = self._segs.get(seq_id)
        if segs:
            self.stamp_transition(seq_id, "queued", t)
            return
        self._segs[seq_id] = [["queued", t, None, None]]

    def restamp_submit(self, seq_id: int, t: float) -> None:
        """Move the FIRST segment's start back to the open-loop
        arrival instant — the same restamp the engines apply to
        ``stats[sid]["t_submit"]`` when a scheduled arrival is drained
        late (the request queued on the USER's clock, and the tiling
        is against the restamped ``t_submit``)."""
        segs = self._segs.get(seq_id)
        if segs:
            segs[0][1] = min(t, segs[0][1]) if segs[0][1] is not None \
                else t

    def stamp_transition(self, seq_id: int, kind: str,
                         t: float | None = None) -> None:
        """Close the open segment and open ``kind`` at the same
        instant — THE transition stamp every engine/router site calls.
        An unknown ``seq_id`` starts a fresh history at ``kind`` (the
        leading gap back to ``t_submit`` finalizes as untracked
        rather than losing the request)."""
        t = _now() if t is None else t
        segs = self._segs.get(seq_id)
        if segs is None:
            segs = self._segs[seq_id] = []
        self._close(segs, t)
        segs.append([kind, t, None, None])

    def annotate_open(self, seq_id: int, **meta: Any) -> None:
        """Attach metadata to the currently open segment (e.g. the
        router's migration ``seq`` — the handle harness/collect.py
        matches flow arrows on)."""
        segs = self._segs.get(seq_id)
        seg = self._open(segs) if segs else None
        if seg is not None:
            seg[3] = {**(seg[3] or {}), **meta}

    def finish_request(self, seq_id: int, t: float | None = None,
                       final: str | None = None) -> None:
        """Close the open segment at the request's resolution instant;
        ``final`` appends a zero-length terminal marker (``shed``).
        When a flight recorder is active the finished history is
        mirrored onto the request's Perfetto lane."""
        t = _now() if t is None else t
        segs = self._segs.get(seq_id)
        if segs is None:
            return
        self._close(segs, t)
        if final is not None:
            segs.append([final, t, t, None])
        self._emit_lane(seq_id, segs)

    # -- cross-engine history transport ----------------------------------

    def export_history(self, seq_id: int,
                       t: float | None = None) -> tuple:
        """Transition to ``migrating`` and return a JSON-able copy of
        the history — the donor half: what
        :class:`~hpc_patterns_tpu.models.serving.MigrationBundle`
        carries (and the wire codec serializes) so a migrated
        request's destination-side record does NOT start fresh."""
        t = _now() if t is None else t
        self.stamp_transition(seq_id, "migrating", t)
        return tuple(tuple(s) for s in self._segs[seq_id])

    def install_history(self, seq_id: int, segments, *,
                        t: float | None = None,
                        t_submit: float | None = None) -> None:
        """Adopt a bundle's carried history on the installing engine
        and open ``decode`` — the receiver half. A LOCAL history wins
        when one exists (the in-process plane shares one recorder, and
        the live history carries annotations — the migration ``seq``
        tag — the bundle's exported copy predates); the carried
        ``segments`` seed a fresh recorder (the cross-process install).
        Both absent — donor traced nothing, or a legacy artifact
        decoded to :data:`LEGACY_SEGMENTS` — resolves to one
        ``untracked`` span from ``t_submit``."""
        t = _now() if t is None else t
        segs = self._segs.get(seq_id)
        if segs is None:
            if segments is not None:
                segs = [list(s) + [None] * (4 - len(s))
                        for s in segments]
            else:
                segs = [["untracked", t_submit, None, None]]
            self._segs[seq_id] = segs
        self._close(segs, t)
        segs.append(["decode", t, None, None])

    # -- read side -------------------------------------------------------

    def segments(self, seq_id: int) -> list[list] | None:
        segs = self._segs.get(seq_id)
        return [list(s) for s in segs] if segs is not None else None

    def snapshot(self, stats: Mapping[int, Mapping[str, Any]]
                 ) -> dict[str, Any]:
        """The ``kind=reqtrace`` record payload: every request's raw
        segment history zipped with its stats endpoints, plus the
        run-level coverage number the bench gate captures. ``stats``
        is the engine's/plane's per-request table (the same input
        harness/slo.py consumes)."""
        requests: dict[str, dict[str, Any]] = {}
        untracked_s = total_s = 0.0
        for sid, rec in stats.items():
            segs = self._segs.get(sid)
            entry = {
                "priority": rec.get("priority", 0),
                "t_submit": rec.get("t_submit"),
                "t_first": rec.get("t_first"),
                "t_finish": rec.get("t_finish"),
                "tokens": rec.get("tokens", 0),
                "outcome": rec.get("outcome"),
                "preemptions": rec.get("preemptions", 0),
                "segments": ([list(s) for s in segs]
                             if segs is not None else None),
                # per-token availability instants (models/serving.py
                # collect readbacks) — None on a legacy stats table;
                # harness/explain.py tiles decode-phase stalls over
                # the gaps between consecutive stamps
                "token_ts": (list(rec["token_ts"])
                             if rec.get("token_ts") else None),
            }
            if rec.get("replica") is not None:
                entry["replica"] = rec["replica"]
            requests[str(sid)] = entry
            if rec.get("t_submit") is not None \
                    and rec.get("t_finish") is not None:
                tiled, u = finalize(segs or (), rec["t_submit"],
                                    rec["t_finish"])
                untracked_s += u
                total_s += max(0.0, rec["t_finish"] - rec["t_submit"])
        return {
            "n": len(requests),
            "coverage_frac": (1.0 - untracked_s / total_s
                              if total_s > 0 else 1.0),
            "requests": requests,
        }

    # -- the Perfetto lane mirror ----------------------------------------

    def _emit_lane(self, seq_id: int, segs: Iterable) -> None:
        from hpc_patterns_tpu.harness import trace as tracelib

        rec = tracelib.active()
        if rec is None:
            return
        for kind, t0, t1, meta in segs:
            if t0 is None or t1 is None or t1 < t0:
                continue  # unresolved legacy spans have no lane form
            rec.mark_request_segment(seq_id, kind, t0, t1,
                                     args=meta)


def finalize(segments: Iterable, t_submit: float, t_finish: float
             ) -> tuple[list[list], float]:
    """Canonicalize a raw history into the tiling the coverage
    invariant is stated over: clamp every segment into
    ``[t_submit, t_finish]``, resolve open/unknown ends, and fill
    every gap wider than :data:`EPS_S` with an explicit ``untracked``
    segment. Returns ``(tiled, untracked_seconds)`` — the tiled list's
    spans sum to exactly ``t_finish - t_submit``, always."""
    span = max(0.0, t_finish - t_submit)
    out: list[list] = []
    cursor = t_submit
    untracked = 0.0
    for seg in segments:
        kind, t0, t1 = seg[0], seg[1], seg[2]
        meta = seg[3] if len(seg) > 3 else None
        s0 = cursor if t0 is None else max(float(t0), cursor)
        s1 = t_finish if t1 is None else float(t1)
        s0 = min(s0, t_finish)
        s1 = min(max(s1, s0), t_finish)
        if s0 - cursor > EPS_S:
            out.append(["untracked", cursor, s0, None])
            untracked += s0 - cursor
        if s1 > s0 or (kind == "shed" and s1 == s0):
            out.append([kind, s0, s1, meta])
            if kind == "untracked":
                # a literal untracked segment (the legacy-artifact
                # decode) counts against coverage like the synthesized
                # gap filler does
                untracked += s1 - s0
        cursor = max(cursor, s1)
    if t_finish - cursor > EPS_S:
        out.append(["untracked", cursor, t_finish, None])
        untracked += t_finish - cursor
    if not out and span > 0:
        out.append(["untracked", t_submit, t_finish, None])
        untracked = span
    return out, untracked


def coverage_frac(segments: Iterable, t_submit: float,
                  t_finish: float) -> float:
    """1 - untracked share of ``[t_submit, t_finish]`` (1.0 for a
    zero-length life) — the per-request form of the gated run-level
    ``attribution_coverage_frac``."""
    span = max(0.0, t_finish - t_submit)
    if span <= 0:
        return 1.0
    _, untracked = finalize(segments, t_submit, t_finish)
    return 1.0 - untracked / span


# ---------------------------------------------------------------------------
# process-wide recorder (the chaos/trace module-global discipline)
# ---------------------------------------------------------------------------

_tracer: ReqTrace | None = None


def active() -> ReqTrace | None:
    """The enabled recorder, or None — THE fast-path check every stamp
    site makes (one module-global read; the disabled path never
    allocates, never stamps, never touches a clock)."""
    rt = _tracer
    if rt is not None and rt.enabled:
        return rt
    return None


def configure(*, enabled: bool = False) -> ReqTrace:
    """Install a FRESH process-wide recorder (``--explain`` surfaces
    call this once per run; each bench leg reconfigures so seq-id
    spaces never bleed across legs)."""
    global _tracer
    _tracer = ReqTrace(enabled=enabled)
    return _tracer


def reset() -> None:
    """Drop the recorder entirely (tests; mirrors chaos.reset)."""
    global _tracer
    _tracer = None
