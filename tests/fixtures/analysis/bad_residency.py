"""Known-bad: tiered-memory prefetch/evict hazards, minimized.

The round-11 residency manager's whole point is that the host<->HBM
transfer hides under the in-flight decode chunk — so the hazard class
is a host readback INSIDE the prefetch/evict dispatch paths
(``DEFAULT_DISPATCH_CRITICAL`` names them): a sync there serializes
exactly the latency the tier exists to hide, turning every swap into
a stall the bubble rollup then blames on admission.

Lines carrying ``EXPECT: <rule>`` markers are the golden findings
tests/test_analysis.py asserts, line-exact.
"""

import numpy as np

import jax


def _dispatch_prefetch(engine, bundle):
    # peeking at the cursors before the pull forces a readback while
    # the decode chunk is (or should be) in flight
    pos_now = np.asarray(engine.pos)  # EXPECT: host-sync-in-dispatch
    payload, handle = engine.residency.pull_payload(
        bundle.pages_payload, attrs={"pos": int(pos_now[0])})
    return payload, handle


def _install_prefetched(engine, bundle, payload):
    slot = engine._attach_row(bundle)
    # "confirming" the install mid-round stalls the chunk it was
    # supposed to hide behind — completion belongs to the round
    # boundary (_complete_prefetches)
    jax.block_until_ready(engine.temps)  # EXPECT: host-sync-in-dispatch
    return slot


def _swap_out(engine, slot):
    bundle = engine._detach_row(slot)
    # the gathered payload is device-side by design; forcing it to
    # host HERE is the all-or-nothing synchronous offload the manager
    # replaced (the pinned-host tier moves it asynchronously)
    raw = {k: tuple(np.array(jax.device_get(a)) for a in v)  # EXPECT: host-sync-in-dispatch
           for k, v in bundle.pages_payload.items()}
    return raw
