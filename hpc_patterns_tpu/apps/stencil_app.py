"""Stencil miniapp: distributed 1-D diffusion with halo exchange.

The BASELINE.json config "SYCL+OMP shared-USM stencil with halo
exchange" as a self-validating benchmark: a periodic 3-point Jacobi
diffusion, domain sharded over the mesh, ghost cells exchanged per step
via ``ppermute`` (comm/halo.py), the whole step loop inside ONE jitted
``lax.fori_loop`` so the halo transfers pipeline against the stencil
compute (no host round-trip per step — the XLA-semantics ground rule).

Validation oracles (SURVEY.md §4.2 style):
1. conservation — periodic diffusion preserves the domain sum exactly
   (up to fp tolerance);
2. single-device replay — the sharded result must equal the unsharded
   loop bit-for-fp-bit-close.

Reports per-step time and halo bandwidth.
"""

from __future__ import annotations

import sys

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from hpc_patterns_tpu.apps import common
from hpc_patterns_tpu.comm import halo
from hpc_patterns_tpu.comm.communicator import record_collective_bandwidth
from hpc_patterns_tpu.harness import RunLog, Verdict, measure
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness.cli import add_msg_size_args, base_parser
from hpc_patterns_tpu.topology import shard_map
from hpc_patterns_tpu.harness.timing import blocking, max_across_processes


def build_parser():
    p = base_parser(__doc__.splitlines()[0])
    add_msg_size_args(p)
    p.set_defaults(log2_elements=22)  # stencil default: 4M cells
    p.add_argument("--steps", type=int, default=64, help="Jacobi steps per run")
    p.add_argument("--world", type=int, default=-1, help="ranks; -1 = all devices")
    p.add_argument("--alpha", type=float, default=0.25)
    return p


def run(args) -> int:
    log = RunLog(args.log, truncate=not args.log_append)
    comm = common.make_communicator(args.backend, args.world)
    mesh, axis = comm.mesh, comm.axis
    world = comm.size
    n = 1 << args.log2_elements  # global domain size (2**p, like -p)
    n += (-n) % world
    steps = args.steps
    alpha = args.alpha

    key = jax.random.PRNGKey(0)
    u0 = jax.random.uniform(key, (n,), jnp.float32)
    u0_sharded = jax.device_put(u0, NamedSharding(mesh, P(axis)))

    def local_loop(u):
        return lax.fori_loop(
            0, steps, lambda _, v: halo.jacobi_step(v, axis, alpha=alpha), u
        )

    stepper = jax.jit(
        shard_map(local_loop, mesh=mesh, in_specs=P(axis), out_specs=P(axis))
    )

    result = measure(
        blocking(stepper, u0_sharded),
        repetitions=args.repetitions, warmup=args.warmup, label="stencil",
    )
    out = stepper(u0_sharded)

    # oracles over addressable shards only, so multi-process launches
    # (apps/launch.py) validate per rank like the reference's per-rank
    # asserts; u0 and the dense replay are identical on every process.
    # oracle 2: single-device replay
    def dense_step(v):
        return (1 - 2 * alpha) * v + alpha * (jnp.roll(v, 1) + jnp.roll(v, -1))

    want = np.asarray(
        # jaxlint: disable=recompile-hazard — one-shot dense oracle per
        # run(); closes over the run's steps/alpha args
        jax.jit(lambda v: lax.fori_loop(0, steps, lambda _, w: dense_step(w), v))(u0)
    )
    shards = out.addressable_shards
    matches = all(
        bool(np.allclose(np.asarray(s.data), want[s.index], atol=1e-5))
        for s in shards
    )
    # oracle 1: conservation (periodic diffusion preserves the sum) —
    # local shard sums, summed across processes
    local_sum = sum(float(np.asarray(s.data).sum()) for s in shards)
    total = common.reduce_across_processes(local_sum, np.sum)
    conserved = bool(np.isclose(total, float(np.asarray(u0).sum()), rtol=1e-4))

    ok = common.all_processes_agree(conserved and matches)
    per_step = max_across_processes(result.min_s) / steps
    halo_bytes = 2 * 4 * world  # 2 directions × f32 per rank, per step
    record_collective_bandwidth("halo", halo_bytes, per_step)
    metricslib.get_metrics().gauge("stencil.step_us").set(per_step * 1e6)
    log.emit(
        kind="result", name="stencil", success=ok, world=world,
        elements=n, steps=steps, per_step_us=per_step * 1e6,
        conserved=conserved, matches_dense=matches,
    )
    log.print(
        f"stencil world={world} n={n} steps={steps}: "
        f"{per_step * 1e6:.2f} us/step "
        f"(halo {halo_bytes}B/step) conserved={conserved} dense-match={matches}"
    )
    if ok:
        rows_per_rank = n // world
        for s in shards:
            log.print(f"Passed {(s.index[0].start or 0) // rows_per_rank}")
    verdict = Verdict(success=ok, messages=("SUCCESS" if ok else "FAILURE",))
    log.print(verdict.summary_line())
    return verdict.exit_code


def main(argv=None) -> int:
    return common.run_instrumented(run, build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
