"""Timing protocol: warm-up + min-over-repetitions wall clock.

Reproduces the reference's measurement protocol (SURVEY.md section 6):
- min over N repetitions (sycl_con.cpp:114, default 10 at :182;
  NUM_REPETION 2 in omp_con.cpp:22) as the noise-control estimator;
- "best theoretical serial" = sum of per-command minima
  (sycl_con.cpp:117-119);
- per-rank wall clock, MAX-reduced across ranks for distributed runs
  (allreduce-mpi-sycl.cpp:188-190) — here :func:`max_across_processes`.

TPU-specific addition the reference didn't need: the first call under jit
pays XLA compilation (~seconds), so measurement *must* warm up first and
block on dispatch (`jax.block_until_ready`) — SURVEY.md section 7 "hard
parts" (d).

When the native extension is built (native/hpcpat.cpp), the min/mean/std
reduction runs in C++; the pure-Python fallback is numerically identical.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Sequence

import jax

from hpc_patterns_tpu.analysis import runtime as _runtimelib
from hpc_patterns_tpu.harness import chaos as chaoslib
from hpc_patterns_tpu.harness import metrics as metricslib


@dataclasses.dataclass(frozen=True)
class TimingResult:
    times_s: tuple[float, ...]

    @property
    def min_s(self) -> float:
        return min(self.times_s)

    @property
    def mean_s(self) -> float:
        return sum(self.times_s) / len(self.times_s)

    @property
    def max_s(self) -> float:
        return max(self.times_s)

    def bandwidth_gbps(self, nbytes: int) -> float:
        return bandwidth_gbps(nbytes, self.min_s)


def bandwidth_gbps(nbytes: int, seconds: float) -> float:
    if seconds <= 0:
        return float("inf")
    return nbytes / seconds / 1e9


def measure(
    fn: Callable[[], object],
    *,
    repetitions: int = 10,
    warmup: int = 1,
    label: str = "measure",
) -> TimingResult:
    """Time ``fn`` with the reference's protocol: ``warmup`` untimed calls
    (absorbing XLA compilation), then ``repetitions`` timed calls; the
    caller consumes :attr:`TimingResult.min_s`.

    ``fn`` must block until its device work completes; wrap JAX work so it
    ends in ``jax.block_until_ready``. Use :func:`blocking` for that.

    With the metrics registry enabled (``--metrics``), the warmup and
    timed phases become ``<label>.warmup`` / ``<label>.timed`` spans and
    every repetition lands in the ``<label>.rep_s`` histogram — the
    per-phase attribution that separates compile-absorbing warmup from
    the numbers a verdict consumes. With a flight recorder installed
    (``--trace``), each timed repetition additionally lands as a
    ``<label>`` dispatch→completion window on the device track carrying
    its ``seq`` index: in a multi-process launch every rank times the
    same repetitions, so the cross-rank merge (harness/collect.py) can
    match rank A's rep k against rank B's rep k and draw the skew fan.
    Disabled (the default), this is the identical code path as always:
    no spans, no records, no extra work.

    Chaos (harness/chaos.py): each timed repetition probes the
    ``collective`` injection site at its ``seq`` index — the timed rep
    IS the collective loop of the launched benchmarks (the same
    identification PR 5 made for the skew fan), so a seeded straggler
    rank is late in exactly the windows the cross-rank merge measures.
    One cached-config read per rep when no chaos is active.
    """
    if repetitions < 1:
        raise ValueError("repetitions must be >= 1")
    chaos_on = chaoslib.active() is not None
    m = metricslib.get_metrics()
    # the instrumented path also engages when a flight recorder is
    # installed (--trace): the warmup/timed spans then land on the
    # timeline even without --metrics (the histogram writes stay no-ops)
    if not (m.enabled or m.mirror_traces
            or metricslib._trace_sink is not None):
        for _ in range(warmup):
            fn()
        times = []
        for seq in range(repetitions):
            if chaos_on:
                chaoslib.maybe_inject("collective", seq)
                with chaoslib.suppress("collective"):
                    t0 = time.perf_counter()
                    fn()
                    times.append(time.perf_counter() - t0)
            else:
                t0 = time.perf_counter()
                fn()
                times.append(time.perf_counter() - t0)
        return TimingResult(tuple(_native_identity(times)))
    from hpc_patterns_tpu.harness import trace as tracelib

    rec = tracelib.active()
    with m.span(f"{label}.warmup", repetitions=warmup):
        for _ in range(warmup):
            fn()
    hist = m.histogram(f"{label}.rep_s")
    times = []
    with m.span(f"{label}.timed", repetitions=repetitions):
        for seq in range(repetitions):
            if chaos_on:
                # the straggler site: inject BEFORE the dispatch marker
                # so the delayed rank's window STARTS late — the shape
                # a genuinely slow rank has in the skew fan
                chaoslib.maybe_inject("collective", seq)
            if rec is not None:
                # fingerprint the rep into the per-rank schedule hash
                # chain (analysis/runtime.py) BEFORE dispatching: every
                # rank times the same repetitions, so the chains match
                # iff the rank schedules did — and a rank that hangs
                # inside rep k has already persisted k to the launcher
                # (the recorder-gated path keeps untraced timing loops
                # byte-identical)
                _runtimelib.record_collective(label, seq)
                t_disp = rec.mark_dispatch(label, args={"seq": seq})
            t0 = time.perf_counter()
            if chaos_on:
                # the rep owns the collective site: an eager collective
                # inside fn() must not re-inject the same fault
                with chaoslib.suppress("collective"):
                    fn()
            else:
                fn()  # blocking by contract: completion, not dispatch
            dt = time.perf_counter() - t0
            if rec is not None:
                rec.mark_complete(label, t_disp, args={"seq": seq})
            hist.observe(dt)
            times.append(dt)
    return TimingResult(tuple(_native_identity(times)))


def _native_identity(times: Sequence[float]) -> Sequence[float]:
    """Round-trip the samples through the native stats engine when it is
    available, so the C++ path is exercised everywhere timing is used."""
    try:
        from hpc_patterns_tpu.interop import native

        if native.available():
            return native.stats_roundtrip(times)
    except Exception:
        pass
    return times


def blocking(fn: Callable[..., object], *args, **kwargs) -> Callable[[], object]:
    """Wrap a JAX computation into a zero-arg blocking thunk for measure()."""

    def thunk():
        return jax.block_until_ready(fn(*args, **kwargs))

    return thunk


def measure_forced(
    fn: Callable[[], object],
    *,
    repetitions: int = 5,
    warmup: int = 1,
    label: str = "measure_forced",
) -> TimingResult:
    """Like :func:`measure`, but forces completion by reading the result
    back to the host (``np.asarray``).

    Needed on dispatch paths where ``block_until_ready`` resolves before
    device work truly finishes (observed through tunneled PJRT backends):
    a host readback is the only airtight completion barrier. ``fn`` must
    return the array whose value depends on all timed work.
    """
    import numpy as np

    def forced():
        np.asarray(fn())

    return measure(forced, repetitions=repetitions, warmup=warmup,
                   label=label)


def amortized_seconds(
    run_with_iters: Callable[[int], object],
    *,
    iters: int = 64,
    repetitions: int = 5,
    warmup: int = 1,
    base_iters: int = 1,
    label: str = "amortized",
) -> float:
    """Per-iteration device time via differencing: run the workload with
    ``iters`` internal repetitions and with ``base_iters``, both
    completion-forced, and return ``(t_iters - t_base) / (iters - base)``.

    This cancels dispatch/readback latency (~100 ms through tunneled
    backends) and any per-call constant, leaving pure steady-state device
    time — the TPU-honest version of the reference's min-of-reps protocol
    for environments where wall-clocking a single dispatch is meaningless.
    ``run_with_iters(n)`` must return an array depending on all n
    iterations (e.g. a Pallas kernel looping n passes internally).

    The default ``base_iters=1`` suits fast per-iteration work; when
    dispatch-latency *variance* (tens of ms through a tunnel) rivals the
    difference being measured, pick a large base (e.g. ``iters // 2``) so
    both timed calls are device-time-dominated and the noise divides by a
    large (iters - base).
    """
    if iters < 2:
        raise ValueError("iters must be >= 2")
    if not 1 <= base_iters < iters:
        raise ValueError(f"need 1 <= base_iters < iters, got {base_iters}")
    t_many = measure_forced(
        lambda: run_with_iters(iters), repetitions=repetitions, warmup=warmup,
        label=f"{label}.many",
    ).min_s
    t_base = measure_forced(
        lambda: run_with_iters(base_iters), repetitions=repetitions,
        warmup=warmup, label=f"{label}.base",
    ).min_s
    return max(t_many - t_base, 0.0) / (iters - base_iters)


def max_across_processes(seconds: float) -> float:
    """Cross-process MAX of a local elapsed time, the distributed timing
    convention of allreduce-mpi-sycl.cpp:188-190 (MPI_Allreduce(MAX)).

    Single-process (the common JAX SPMD case: one process drives all local
    devices) returns the input unchanged.
    """
    if jax.process_count() == 1:
        return seconds
    import numpy as np
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.float64(seconds))
    return float(np.max(gathered))
