"""Known-clean: the prefix-sharing admission discipline.

The radix match is a host trie walk over the request's OWN numpy
tokens, shared-page mapping is refcount arithmetic, the tail prefill
is dispatch-only (its first-token readback defers to the loop's next
sync point), and releases decref host lists — no device value is ever
consulted on the admission path.
"""


def _prefix_match(engine, prompt):
    # host trie walk over host tokens: the longest cached chain at
    # this prompt's rung, no device op anywhere near it
    return engine._prefix.match(
        prompt, engine._bucket_len(prompt.size),
        max_pages=(prompt.size - 1) // engine.page_size)


def _insert_prefix(engine, prompt, rung, pages):
    # publish the page IDS; the prefill's device writes land behind
    # the in-flight chunk on their own schedule
    n_full = prompt.size // engine.page_size
    if n_full:
        engine._incref_pages(
            engine._prefix.insert(prompt, rung, pages[:n_full]))


def _decref_pages(engine, pages):
    # the one release rule: refcount arithmetic on host lists, a page
    # returns to the free list only at zero
    for p in pages:
        r = engine._page_refs[p] - 1
        if r:
            engine._page_refs[p] = r
        else:
            del engine._page_refs[p]
            engine.free_pages.append(p)
