"""Tests for topology (C8 parity: devices.hpp rank->device policies,
fission fallback, mesh construction)."""

import jax

from hpc_patterns_tpu.topology import shard_map
import pytest

from hpc_patterns_tpu import topology


def test_get_devices_platform_filter():
    ds = topology.get_devices("cpu")
    assert len(ds) == 8
    with pytest.raises(topology.TopologyError):
        topology.get_devices("nonexistent-platform")


def test_fission_never_fails():
    # reference semantics: finest partition, whole-device fallback
    # (devices.hpp:28-38)
    assert len(topology.fission()) == 8
    assert topology.fission([]) == []


def test_core_topology_introspection():
    infos = topology.core_topology()
    assert len(infos) == 8
    for info in infos:
        assert info.num_cores >= 1
        assert isinstance(info.kind, str)
        # CPU devices are plain single cores, never megacore
        assert not info.megacore

    # synthetic megacore (v4/v5p-style: one device, two fused cores)
    class _Mega:
        platform = "tpu"
        device_kind = "TPU v4"
        coords = (0, 0, 0)
        core_on_chip = 0
        num_cores = 2
        process_index = 0
        id = 0

    (mega,) = topology.core_topology([_Mega()])
    assert mega.megacore and mega.num_cores == 2


def test_group_by_chip():
    # CPU devices expose no coords: every device is its own "chip"
    groups = topology.group_by_chip()
    assert len(groups) == 8
    assert all(len(v) == 1 for v in groups.values())

    # synthetic v2/v3-style chip: two per-core devices sharing coords
    class _Core:
        platform = "tpu"
        process_index = 0

        def __init__(self, i, core):
            self.id = i
            self.coords = (0, 0, 0)
            self.core_on_chip = core

    groups = topology.group_by_chip([_Core(0, 0), _Core(1, 1)])
    assert len(groups) == 1
    (devs,) = groups.values()
    assert len(devs) == 2


def test_assign_device_modulo_when_oversubscribed():
    # ranks > devices -> rank % n (devices.hpp:47)
    ds = topology.get_devices()
    n = len(ds)
    for rank in range(2 * n):
        assert topology.assign_device(rank, 2 * n, ds) == ds[rank % n]


def test_assign_device_block_when_undersubscribed():
    # devices >= ranks -> contiguous blocks (devices.hpp:49-53)
    ds = topology.get_devices()  # 8
    assert topology.assign_device(0, 2, ds) == ds[0]
    assert topology.assign_device(1, 2, ds) == ds[4]
    assert topology.devices_for_rank(1, 2, ds) == list(ds[4:8])
    assert topology.devices_for_rank(0, 4, ds) == list(ds[0:2])


def test_assign_device_bad_args():
    ds = topology.get_devices()
    with pytest.raises(ValueError):
        topology.assign_device(3, 2, ds)
    with pytest.raises(topology.TopologyError):
        topology.assign_device(0, 1, [])


def test_make_mesh_explicit_and_auto():
    m = topology.make_mesh({"dp": 2, "tp": 4})
    assert m.shape == {"dp": 2, "tp": 4}
    # -1 auto sentinel (sycl_con.cpp CLI convention)
    m = topology.make_mesh({"dp": -1, "tp": 2})
    assert m.shape == {"dp": 4, "tp": 2}
    m = topology.make_mesh({"a": -1, "b": -1, "c": 2})
    assert m.shape == {"a": 4, "b": 1, "c": 2}


def test_make_mesh_rejects_nondividing():
    with pytest.raises(topology.TopologyError):
        topology.make_mesh({"dp": 3})
    with pytest.raises(topology.TopologyError):
        topology.make_mesh({"dp": 2})  # uses 2 of 8 with no auto axis


def test_single_device_mesh_and_info():
    m = topology.single_device_mesh(("dp", "tp"))
    assert m.shape == {"dp": 1, "tp": 1}
    info = topology.TopologyInfo.detect()
    assert info.n_devices == 8
    assert info.platform == "cpu"
    assert info.n_hosts == 1


def test_group_by_host():
    groups = topology.group_by_host()
    assert sum(len(v) for v in groups.values()) == 8
    assert set(groups) == {jax.devices()[0].process_index}


class _FakeDev:
    """Synthetic device carrying a slice_index (CPU devices are all
    slice 0, so multi-slice layouts are tested with these)."""

    def __init__(self, i, slice_index):
        self.id = i
        self.slice_index = slice_index

    def __repr__(self):
        return f"d{self.id}@s{self.slice_index}"


class TestHybridMesh:
    def test_layout_dcn_across_slices(self):
        # 2 slices x 4 devices: dp must span slices, tp/sp stay inside
        devs = [_FakeDev(i, i // 4) for i in range(8)]
        arr, names = topology.hybrid_device_layout(
            {"dp": -1}, {"sp": 2, "tp": 2}, devs
        )
        assert names == ("dp", "sp", "tp")
        assert arr.shape == (2, 2, 2)
        # every (sp, tp) plane = one slice; dp index = slice index
        for d in range(2):
            slices = {dev.slice_index for dev in arr[d].ravel()}
            assert slices == {d}

    def test_layout_guards(self):
        devs = [_FakeDev(i, i // 4) for i in range(8)]
        with pytest.raises(topology.TopologyError, match="both"):
            topology.hybrid_device_layout({"dp": 2}, {"dp": 4}, devs)
        with pytest.raises(topology.TopologyError):
            # dcn product != slice count
            topology.hybrid_device_layout({"dp": 4}, {"tp": 4}, devs)
        uneven = [_FakeDev(i, 0 if i < 5 else 1) for i in range(8)]
        with pytest.raises(topology.TopologyError, match="unequal"):
            topology.hybrid_device_layout({"dp": 2}, {"tp": -1}, uneven)

    def test_mesh_runs_collectives_per_domain(self, monkeypatch):
        # real Mesh over the CPU devices with two SYNTHETIC slices:
        # psum over the ici axis must stay inside one fake slice
        # (device rows 0-3 / 4-7), psum over dcn crosses them
        import numpy as np
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        ds = topology.get_devices()
        fake_groups = {0: ds[:4], 1: ds[4:]}
        # the production slice-override path (no monkeypatching): the
        # same env protocol apps/launch.py --slices uses cross-process
        monkeypatch.setenv(topology.ENV_SLICE_GROUPING, "devices:4")
        mesh = topology.make_hybrid_mesh({"dp": -1}, {"tp": -1}, ds)
        assert mesh.shape == {"dp": 2, "tp": 4}
        # row d of the mesh = fake slice d
        for d in range(2):
            assert list(mesh.devices[d]) == list(fake_groups[d])

        x = jnp.arange(8.0)
        got = jax.jit(shard_map(
            lambda v: jax.lax.psum(v, "tp"),
            mesh=mesh, in_specs=P(("dp", "tp")), out_specs=P(("dp", "tp")),
        ))(x)
        # tp-psum folds within each slice: rows 0-3 sum to 6, 4-7 to 22
        want = np.repeat([6.0, 22.0], 4)
        np.testing.assert_allclose(np.asarray(got), want)

    def test_slice_grouping_env(self, monkeypatch):
        ds = topology.get_devices()
        monkeypatch.setenv(topology.ENV_SLICE_GROUPING, "devices:2")
        assert sorted(topology.group_by_slice(ds)) == [0, 1, 2, 3]
        # process mapping: all CPU devices are process 0 -> slice 0
        monkeypatch.setenv(topology.ENV_SLICE_GROUPING, "process:0,1")
        assert set(topology.group_by_slice(ds)) == {0}
        monkeypatch.setenv(topology.ENV_SLICE_GROUPING, "process")
        assert set(topology.group_by_slice(ds)) == {0}
        monkeypatch.setenv(topology.ENV_SLICE_GROUPING, "banana")
        with pytest.raises(topology.TopologyError, match="SLICE_GROUPING"):
            topology.group_by_slice(ds)

    def test_single_slice_degenerates(self):
        mesh = topology.make_hybrid_mesh({"dp": -1}, {"tp": 8})
        assert mesh.shape == {"dp": 1, "tp": 8}


class TestProcessEnvInfo:
    # the flight-recorder snapshot stamp: env protocol first (right
    # even before jax.distributed initializes), jax runtime fallback

    def test_launcher_env_wins(self):
        env = {topology.ENV_PROCESS_ID: "2",
               topology.ENV_NUM_PROCESSES: "4"}
        assert topology.process_env_info(env) == (2, 4, 0)

    def test_slice_id_from_process_mapping(self):
        env = {topology.ENV_PROCESS_ID: "3",
               topology.ENV_NUM_PROCESSES: "4",
               topology.ENV_SLICE_GROUPING: "process:0,0,1,1"}
        assert topology.process_env_info(env) == (3, 4, 1)

    def test_slice_id_process_identity(self):
        env = {topology.ENV_PROCESS_ID: "1",
               topology.ENV_NUM_PROCESSES: "2",
               topology.ENV_SLICE_GROUPING: "process"}
        assert topology.process_env_info(env) == (1, 2, 1)

    def test_device_keyed_grouping_does_not_apply(self):
        env = {topology.ENV_PROCESS_ID: "1",
               topology.ENV_NUM_PROCESSES: "2",
               topology.ENV_SLICE_GROUPING: "devices:4"}
        assert topology.process_env_info(env) == (1, 2, 0)

    def test_jax_fallback_single_process(self):
        assert topology.process_env_info({}) == (0, 1, 0)


def test_cpu_worker_env_requests_gloo_collectives():
    # a CPU worker exists to be one rank of many: without a collectives
    # backend the CPU client rejects every multi-process computation
    env = topology.cpu_worker_env({}, 2)
    assert env["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] == "gloo"
    # an operator's explicit choice survives
    env = topology.cpu_worker_env(
        {"JAX_CPU_COLLECTIVES_IMPLEMENTATION": "mpi"}, 2)
    assert env["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] == "mpi"
