"""Pallas op tests: flash attention vs the dense oracle (interpret mode
on the CPU mesh; real-TPU correctness/perf are exercised by bench/driver
runs)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.ops import flash_attention, flash_attention_block
from hpc_patterns_tpu.parallel.ring_attention import full_attention


def _qkv(key, B=2, T=128, H=4, D=32, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (B, T, H, D), dtype) for k in ks)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_oracle(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(0))
        got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5
        )

    def test_uneven_blocks_rejected(self):
        q, k, v = _qkv(jax.random.PRNGKey(1), T=96)
        with pytest.raises(ValueError, match="divide"):
            flash_attention(q, k, v, block_q=64, block_k=64)

    def test_bad_rank(self):
        with pytest.raises(ValueError, match="head_dim"):
            flash_attention(jnp.zeros((2, 2)), jnp.zeros((2, 2)), jnp.zeros((2, 2)))

    def test_block_larger_than_seq_clamps(self):
        q, k, v = _qkv(jax.random.PRNGKey(2), T=64)
        got = flash_attention(q, k, v, causal=True)  # default blocks 128 > 64
        want = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    @pytest.mark.parametrize(
        "causal", [True, pytest.param(False, marks=pytest.mark.slow)]
    )
    def test_block_partials_merge_to_full(self, causal):
        # two half-sequence K/V blocks at their global offsets, merged by
        # logsumexp, must equal attention over the whole sequence
        T = 64
        q, k, v = _qkv(jax.random.PRNGKey(4), B=1, T=T, H=2, D=16)
        half = T // 2

        def merged(q, k, v):
            out = jnp.zeros(q.shape, jnp.float32)
            lse = jnp.full(q.shape[:3], -1e30, jnp.float32)
            for i in (0, 1):
                o_b, lse_b = flash_attention_block(
                    q, k[:, i * half:(i + 1) * half],
                    v[:, i * half:(i + 1) * half],
                    0, i * half, causal=causal, block_q=32, block_k=32,
                )
                m = jnp.maximum(lse, lse_b)
                e_run, e_b = jnp.exp(lse - m), jnp.exp(lse_b - m)
                denom = e_run + e_b
                out = (out * e_run[..., None]
                       + o_b.astype(jnp.float32) * e_b[..., None]) \
                    / denom[..., None]
                lse = m + jnp.log(denom)
            return out.astype(q.dtype)

        np.testing.assert_allclose(
            np.asarray(merged(q, k, v)),
            np.asarray(full_attention(q, k, v, causal=causal)),
            atol=2e-5,
        )

        # gradient flows through BOTH out and lse of each partial
        g_got = jax.grad(lambda *a: merged(*a).sum(), argnums=(0, 1, 2))(q, k, v)
        g_want = jax.grad(
            lambda *a: full_attention(*a, causal=causal).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g_got, g_want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    def test_block_dead_rows_inside_iterating_block_are_zero(self):
        # rows 32-47 see nothing of a K block at offset 48, but share a
        # query block with rows 48-63 which do — the kernel must zero
        # them, not average the visited V rows
        q, k, v = _qkv(jax.random.PRNGKey(6), B=1, T=64, H=2, D=16)
        o_b, lse_b = flash_attention_block(q, k[:, :32], v[:, :32], 0, 48,
                                           causal=True, block_q=32,
                                           block_k=32)
        dead = np.asarray(o_b)[:, 32:48]
        assert np.all(dead == 0), np.abs(dead).max()
        assert np.all(np.asarray(lse_b)[:, 32:48] < -1e29)

    def test_block_fully_future_is_masked(self):
        # causal block entirely in the future: zero kernel iterations,
        # zero weight in the merge
        q, k, v = _qkv(jax.random.PRNGKey(5), B=1, T=32, H=2, D=16)
        o_b, lse_b = flash_attention_block(q, k, v, 0, 1000, causal=True,
                                           block_q=32, block_k=32)
        assert np.all(np.asarray(o_b) == 0)
        assert np.all(np.asarray(lse_b) < -1e29)

    @pytest.mark.parametrize("bwd", ["fused", "split"])
    @pytest.mark.parametrize("causal", [True, False])
    def test_gqa_narrow_kv_matches_expanded(self, causal, bwd):
        # K/V with fewer heads stream through the kernel index maps;
        # result and grads must equal the expanded-K/V oracle, with
        # dk/dv returned narrow (the group sum in the kernel
        # accumulator)
        H, Hkv = 4, 2
        q, _, _ = _qkv(jax.random.PRNGKey(7), B=1, T=64, H=H, D=16)
        _, k, v = _qkv(jax.random.PRNGKey(8), B=1, T=64, H=Hkv, D=16)
        expand = lambda x: jnp.repeat(x, H // Hkv, axis=2)
        got = flash_attention(q, k, v, causal=causal, block_q=32,
                              block_k=32)
        want = full_attention(q, expand(k), expand(v), causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5)
        g1 = jax.grad(
            lambda q, k, v: flash_attention(q, k, v, causal=causal,
                                            block_q=32, block_k=32,
                                            bwd=bwd).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        g2 = jax.grad(
            lambda q, k, v: full_attention(q, expand(k), expand(v),
                                           causal=causal).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        assert g1[1].shape == (1, 64, Hkv, 16)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5)

    def test_fused_bwd_q_chunked_matches(self, monkeypatch):
        # long-context shape analog: shrink the slab budget until the
        # fused backward must split the query range into 2 chunks; the
        # chunked grads must equal the one-call fused grads exactly
        import importlib

        fa = importlib.import_module("hpc_patterns_tpu.ops.flash_attention")

        q, k, v = _qkv(jax.random.PRNGKey(12), B=1, T=128, H=2, D=16)
        grad = lambda: jax.grad(
            lambda q, k, v: flash_attention(q, k, v, causal=True,
                                            block_q=32, block_k=32,
                                            bwd="fused").sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        want = grad()
        # slab = 4 kv chunks * 1 * 2 * 128 * 16 * 4 B = 64 KiB; half of
        # it forces n_chunks = 2 (Tq/4 = 32 still divides block_q)
        monkeypatch.setattr(fa, "_FUSED_SLAB_LIMIT", 32768)
        got = grad()
        for a, b in zip(got, want):
            # chunked dK/dV accumulate call-by-call in f32 and the dQ
            # slab-sum association changes: equal to f32 rounding
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)

    def test_bad_bwd_rejected(self):
        q, k, v = _qkv(jax.random.PRNGKey(9), B=1, T=32, H=2, D=16)
        with pytest.raises(ValueError, match="bwd"):
            jax.grad(lambda q: flash_attention(q, k, v, bwd="fuse").sum())(q)

    def test_mismatched_kv_heads_rejected(self):
        q, k, v = _qkv(jax.random.PRNGKey(9), B=1, T=32, H=4, D=16)
        with pytest.raises(ValueError, match="kv heads"):
            flash_attention(q, k[:, :, :3], v[:, :, :3])

    @pytest.mark.parametrize("bwd", ["fused", "split"])
    @pytest.mark.parametrize("causal", [True, False])
    def test_grad_matches_oracle(self, causal, bwd):
        # both backward impls stay oracle-exact: the auto heuristic picks
        # fused at every test-scale shape, so "split" (the memory-safe
        # big-model fallback) must be pinned here or it loses coverage
        q, k, v = _qkv(jax.random.PRNGKey(3), B=1, T=64, H=2, D=16)

        def loss_flash(q, k, v):
            return flash_attention(q, k, v, causal=causal,
                                   block_q=32, block_k=32, bwd=bwd).sum()

        def loss_dense(q, k, v):
            return full_attention(q, k, v, causal=causal).sum()

        got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=2e-5)

    def test_model_flash_matches_full(self):
        from hpc_patterns_tpu.models import TransformerConfig, forward, init_params

        base = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                    max_seq=32, dtype="float32")
        params = init_params(jax.random.PRNGKey(0), TransformerConfig(**base))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64, "int32")
        a = forward(params, tokens, TransformerConfig(**base))
        b = forward(params, tokens, TransformerConfig(**base, attention="flash"))
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    def test_flash_on_mesh_sp1_allowed(self, mesh8):
        from hpc_patterns_tpu.models import TransformerConfig, forward, init_params

        # mesh8 has one axis "x"; treat it as dp (sequence unsharded)
        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=1,
                                d_ff=64, max_seq=32, dtype="float32",
                                attention="flash", axis_dp="x", axis_sp="sp",
                                axis_tp="tp", axis_ep="ep")
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 64,
                                    "int32")
        got = forward(params, tokens, cfg, mesh8)
        want = forward(params, tokens,
                       TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                         n_layers=1, d_ff=64, max_seq=32,
                                         dtype="float32"))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4)

    def test_flash_on_mesh_rejected(self, mesh_dp_sp_tp):
        from hpc_patterns_tpu.models import TransformerConfig, forward, init_params

        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=8, n_layers=1,
                                d_ff=64, max_seq=32, attention="flash")
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64, "int32")
        with pytest.raises(ValueError, match="ring_flash"):
            forward(params, tokens, cfg, mesh_dp_sp_tp)


class TestFusedMLP:
    """The fused MLP kernel (ops/fused_mlp.py) vs the dense einsum
    oracle: forward values and ALL THREE gradients, multi-block grids,
    f32 (exact-ish) and bf16 paths."""

    @staticmethod
    def _dense(x, w1, w2):
        return jnp.dot(jax.nn.gelu(jnp.dot(x, w1)), w2)

    @staticmethod
    def _setup(dtype, N=16, D=8, F=32, seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        x = jax.random.normal(ks[0], (N, D), dtype)
        w1 = jax.random.normal(ks[1], (D, F), dtype) * 0.3
        w2 = jax.random.normal(ks[2], (F, D), dtype) * 0.3
        return x, w1, w2

    def test_forward_matches_dense(self):
        from hpc_patterns_tpu.ops.fused_mlp import fused_mlp

        x, w1, w2 = self._setup(jnp.float32)
        got = fused_mlp(x, w1, w2, block_t=4, block_f=8)  # 4x4 grid
        want = self._dense(x, w1, w2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5)

    def test_forward_leading_dims(self):
        from hpc_patterns_tpu.ops.fused_mlp import fused_mlp

        x, w1, w2 = self._setup(jnp.float32)
        x3 = x.reshape(2, 8, -1)
        got = fused_mlp(x3, w1, w2, block_t=4, block_f=8)
        want = self._dense(x3, w1, w2)
        assert got.shape == x3.shape
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5)

    def test_grads_match_dense(self):
        from hpc_patterns_tpu.ops.fused_mlp import fused_mlp

        x, w1, w2 = self._setup(jnp.float32)

        def loss_fused(x, w1, w2):
            return jnp.sum(fused_mlp(x, w1, w2, block_t=4, block_f=8) ** 2)

        def loss_dense(x, w1, w2):
            return jnp.sum(self._dense(x, w1, w2) ** 2)

        got = jax.grad(loss_fused, argnums=(0, 1, 2))(x, w1, w2)
        want = jax.grad(loss_dense, argnums=(0, 1, 2))(x, w1, w2)
        for g, w, name in zip(got, want, ("dx", "dw1", "dw2")):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-4,
                err_msg=name,
            )

    def test_bf16_close_to_f32(self):
        from hpc_patterns_tpu.ops.fused_mlp import fused_mlp

        x, w1, w2 = self._setup(jnp.float32)
        want = self._dense(x, w1, w2)
        got = fused_mlp(x.astype(jnp.bfloat16), w1.astype(jnp.bfloat16),
                        w2.astype(jnp.bfloat16), block_t=8, block_f=16)
        scale = np.abs(np.asarray(want)).max()
        err = np.abs(np.asarray(got, np.float32)
                     - np.asarray(want)).max() / scale
        assert err < 0.05, err

    def test_off_size_blocks_auto_fit(self):
        # token counts / d_ff that don't divide the requested blocks
        # fall back to the largest fitting divisor (never a mid-trace
        # ValueError): N=6 with block_t=4 runs at block_t=3
        from hpc_patterns_tpu.ops.fused_mlp import fused_mlp

        x, w1, w2 = self._setup(jnp.float32, N=6, F=12)
        got = fused_mlp(x, w1, w2, block_t=4, block_f=8)
        want = self._dense(x, w1, w2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5)
