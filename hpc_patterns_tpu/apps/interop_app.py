"""Interop proof app — the rebuild of ``interop_omp_ze_sycl`` (C10).

The reference's main() proves zero-copy both directions between two
runtimes sharing one device context: an OMP-allocated buffer filled by
an OMP kernel is read by a SYCL memcpy, and a SYCL-allocated buffer is
read by an OMP kernel, each validated by asserts
(interop_omp_ze_sycl.cpp:70-104).

Here the runtime pair is {native C++ allocator, numpy} ↔ {JAX} ↔
{torch}, over the dlpack protocol:

1. native → JAX: C++ ``hp_iota`` fills an aligned allocation; JAX reads
   it through dlpack; **zero-copy asserted by pointer identity** (the
   airtight form of the reference's value asserts) + value oracle.
2. JAX → torch → JAX: a JAX computation's output crosses to torch and
   back, pointer-identical, value-validated in C (``hp_validate``).
3. foreign memory → accelerator: the native buffer staged to the
   default (TPU) device and back, value-validated — the boundary that
   is a DMA by physics (the reference's analog stops at one GPU's
   context; crossing memory spaces is the concurrency suite's M2D).
4. device-side in-place (interop/device.py): jit donation and a Pallas
   ``input_output_aliases`` kernel writing the output INTO the input's
   device buffer — pointer identity where the backend exposes raw
   pointers, else the compiled executable's aliasing contract — the
   device-context leg the reference proves with OMP/SYCL kernels in
   one Level-Zero context (interop_omp_ze_sycl.cpp:81-101).
5. ``--native-driver``: the C++ XLA driver (native/interop_driver.cpp)
   — native main() allocating buffers, XLA reading them zero-copy and
   writing donated outputs in place, every assert on the C side.

Prints per-direction "Passed <n>" lines and a SUCCESS/FAILURE verdict.
"""

from __future__ import annotations

import sys

import numpy as np

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.apps import common
from hpc_patterns_tpu.harness import RunLog, Verdict
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness.cli import base_parser
from hpc_patterns_tpu.interop import native, zero_copy

# module-level jits: run() is re-entrant (tests, sweeps), and a
# jax.jit built inside it would re-trace on every invocation
# (jaxlint: recompile-hazard)
_double = jax.jit(lambda x: x * 2.0)
_triple = jax.jit(lambda x: x * 3.0)


def build_parser():
    p = base_parser(__doc__.splitlines()[0])
    p.add_argument("-n", "--elements", type=int, default=1 << 16)
    p.add_argument("--alignment", type=int, default=128,
                   help="native allocation alignment (reference ALIGNMENT=128)")
    p.add_argument("--native-driver", action="store_true",
                   help="also run the C++ XLA driver leg (builds "
                        "native/interop-driver; asserts on the C side)")
    return p


def _native_driver_leg(log, n: int) -> bool:
    """Build and run native/interop-driver: C++ owning main(), the
    allocator, and the asserts while XLA executes on its buffers."""
    import os
    import subprocess
    from pathlib import Path

    native_dir = Path(native.__file__).resolve().parents[2] / "native"
    try:
        r = subprocess.run(["make", "-C", str(native_dir), "interop-driver"],
                           capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            log.print(f"native driver build failed: {r.stderr[:200]}")
            return False
        pythonpath = ":".join(p for p in sys.path if p)
        env = dict(os.environ)
        r = subprocess.run(
            [str(native_dir / "interop-driver"), "--elements", str(n),
             "--pythonpath", pythonpath],
            capture_output=True, text=True, timeout=300, env=env,
        )
    except (OSError, subprocess.SubprocessError) as e:
        # a missing toolchain or a hung build is a FAILED leg, not an
        # app crash — the other legs' results must still be reported
        log.print(f"native driver leg error: {type(e).__name__}: {e}")
        return False
    for line in r.stdout.splitlines():
        log.print(f"  [driver] {line}")
    if r.returncode != 0:
        log.print(f"native driver failed rc={r.returncode}: "
                  f"{r.stderr[-300:]}")
    return r.returncode == 0


def run(args) -> int:
    log = RunLog(args.log, truncate=not args.log_append)
    checks: list[tuple[str, bool]] = []

    if not native.available() and not native.build():
        log.print("SKIP: native library unavailable (make -C native failed)")
        log.print("FAILURE")
        return 1

    n = args.elements

    # 1. native C++ -> numpy -> JAX, zero-copy (≙ OMP fill, SYCL read)
    buf = native.AlignedBuffer(n, alignment=args.alignment)
    buf.iota(0.0, 1.0)
    arr, zc = zero_copy.native_to_jax(buf)
    values_ok = bool(
        jnp.all(arr == jnp.arange(n, dtype=jnp.float32)).item()
    )
    checks.append(("native->jax zero-copy", zc))
    checks.append(("native->jax values", values_ok))

    # 2. JAX compute -> torch -> JAX, zero-copy both hops (≙ SYCL alloc,
    #    OMP kernel read). Result validated by the C oracle.
    doubled = _double(
        jax.device_put(jnp.ones((n,), jnp.float32), jax.devices("cpu")[0])
    )
    doubled = jax.block_until_ready(doubled)
    try:
        t, zc_jt = zero_copy.jax_to_torch(doubled)
        back, zc_tj = zero_copy.torch_to_jax(t)
        out = native.AlignedBuffer(n, alignment=args.alignment)
        out.as_numpy()[:] = np.from_dlpack(back)
        checks.append(("jax->torch zero-copy", zc_jt))
        checks.append(("torch->jax zero-copy", zc_tj))
        checks.append(("C-oracle validation", out.validate(2.0) == -1))
    except ImportError:
        # torch is the stand-in second runtime; without it the leg is
        # unprovable, not failed (mirrors the reference's per-runtime
        # precondition guards)
        log.print("SKIP: torch unavailable, torch bridge legs skipped")

    # 3. native memory -> accelerator and back (staged: DMA by physics)
    dev = jax.devices(args.backend)[0] if args.backend else jax.devices()[0]
    staged = jax.device_put(buf.as_numpy(), dev)
    tripled = np.asarray(_triple(staged))
    # compare in f32 with tolerance: exact f64 equality would fail for
    # n past 2^24 purely from float32 rounding
    expect_last = np.float32(3.0) * np.float32(n - 1)
    checks.append(
        (f"native->{dev.platform} roundtrip",
         bool(np.isclose(tripled[-1], expect_last, rtol=1e-6)))
    )

    # 4. device-side in-place: donation + Pallas input_output_aliases
    from hpc_patterns_tpu.interop import device as device_proofs

    def kind(ev):
        return "pointer" if ev["pointer_ok"] is not None else "compiled contract"

    ok_don, ev_don = device_proofs.donation_alias_proof(n)
    checks.append((f"device donation in-place ({kind(ev_don)})", ok_don))
    ok_pal, ev_pal = device_proofs.pallas_alias_proof()
    checks.append(
        (f"pallas input_output_alias ({kind(ev_pal)}"
         f"{', interpret' if ev_pal['interpret'] else ''})", ok_pal)
    )

    # 5. the C++ XLA driver (opt-in: builds a binary, embeds CPython)
    if args.native_driver:
        checks.append(("native C++ XLA driver", _native_driver_leg(log, n)))

    all_ok = all(ok for _, ok in checks)
    m = metricslib.get_metrics()
    m.gauge("interop.checks_total").set(len(checks))
    m.gauge("interop.checks_ok").set(sum(ok for _, ok in checks))
    for i, (name, ok) in enumerate(checks):
        log.print(f"{'Passed' if ok else 'FAILED'} {i} ({name})")
    log.emit(kind="result", name="interop", success=all_ok,
             checks={name: ok for name, ok in checks}, elements=n)
    verdict = Verdict(success=all_ok, messages=("SUCCESS" if all_ok else "FAILURE",))
    log.print(verdict.summary_line())
    return verdict.exit_code


def main(argv=None) -> int:
    return common.run_instrumented(run, build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
