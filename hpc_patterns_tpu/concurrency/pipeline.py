"""On-chip DMA/compute overlap benchmark (the Pallas heart of C1).

The reference's concurrency suite asks: do independent copy and compute
commands *actually overlap* on one device (sycl_con.cpp:84-115)? On TPU
the equivalent boundary is HBM↔VMEM DMA vs VPU compute inside a kernel
(SURVEY.md §2.2 "intra-device stream parallelism": Pallas double-buffered
DMA/compute overlap stands in for H2D/D2H-vs-kernel overlap), and —
unlike host wall-clock games — it is measurable honestly even through a
high-latency dispatch path, because the whole experiment is ONE kernel.

Four variants of the same chunk-walk over an HBM-resident array, all
computing the identical checksum (the correctness oracle):

- ``overlap``  — double-buffered: DMA of chunk i+1 in flight while the
  busy-wait chain runs on chunk i (the out-of-order-queue analog)
- ``serial``   — single-buffered: DMA chunk i, wait, compute chunk i
  (the reference's serial baseline, sycl_con.cpp:101-106)
- ``dma``      — DMAs only (per-command baseline for M2D/D2M)
- ``compute``  — busy-wait only (per-command baseline for C)

``tripcount`` (compute per chunk) and ``passes`` (repetitions over the
whole array, amortizing fixed overheads inside the kernel) are runtime
SMEM scalars, so the C12 autotuner balances DMA vs compute without
recompiles. Speedup/verdict math reuses the shared rules
(harness.verdict.concurrency_verdict).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hpc_patterns_tpu.concurrency.kernels import FMA_UNROLL

MODES = ("overlap", "serial", "dma", "compute")


def _chain(acc, trips, salt):
    # ``salt`` (pass-index-derived) keeps every pass's chain distinct so
    # the compiler cannot hoist the loop body out of the pass loop.
    add = jnp.float32(0.5) + salt

    def body(_, a):
        for _ in range(FMA_UNROLL):
            a = a * jnp.float32(0.9999999) + add
        return a

    return lax.fori_loop(0, trips, body, acc)


def _make_kernel(mode: str, num_chunks: int):
    do_dma = mode in ("overlap", "serial", "dma")
    do_compute = mode in ("overlap", "serial", "compute")

    def kernel(scalar_ref, hbm_ref, out_ref):
        trips = scalar_ref[0]
        passes = scalar_ref[1]

        def body(scratch, sem):
            def get_dma(slot, chunk):
                return pltpu.make_async_copy(
                    hbm_ref.at[chunk], scratch.at[slot], sem.at[slot]
                )

            def one_pass(p, checksum):
                if mode == "overlap":
                    # warm-up DMA for this pass's first chunk
                    get_dma(0, 0).start()

                def chunk_step(i, csum):
                    slot = lax.rem(i, 2)
                    if mode == "overlap":

                        @pl.when(i + 1 < num_chunks)
                        def _():
                            get_dma(1 - slot, i + 1).start()

                        get_dma(slot, i).wait()
                    elif do_dma:
                        dma = get_dma(slot, i)
                        dma.start()
                        dma.wait()
                    if do_compute:
                        salt = (p * num_chunks + i).astype(jnp.float32) * jnp.float32(1e-7)
                        acc = _chain(scratch[slot], trips, salt)
                        # fold EVERY chunk into the checksum so the oracle
                        # (overlap == serial) covers every DMA'd block, not
                        # just the last one
                        csum = csum + acc[:8]
                    return csum

                return lax.fori_loop(0, num_chunks, chunk_step, checksum)

            out_ref[:] = lax.fori_loop(
                0, passes, one_pass, jnp.zeros((8, 128), jnp.float32)
            )

        chunk_shape = hbm_ref.shape[1:]
        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((2, *chunk_shape), jnp.float32),
            sem=pltpu.SemaphoreType.DMA((2,)),
        )

    return kernel


@functools.partial(jax.jit, static_argnames=("mode", "interpret"))
def _run(hbm_array, tripcount, passes, *, mode: str, interpret: bool):
    num_chunks = hbm_array.shape[0]
    scalars = jnp.asarray([tripcount, passes], jnp.int32)
    return pl.pallas_call(
        _make_kernel(mode, num_chunks),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),  # stays in HBM; DMA'd manually
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(scalars, hbm_array)


def overlap_run(
    hbm_array,
    *,
    mode: str,
    tripcount: int = 64,
    passes: int = 1,
    interpret: bool | None = None,
):
    """Run one variant over ``hbm_array`` of shape (num_chunks, rows, 128)
    float32; returns the (8, 128) checksum tile (identical across modes
    that compute — the oracle for tests)."""
    if mode not in MODES:
        raise ValueError(f"mode {mode!r} not in {MODES}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if hbm_array.ndim != 3 or hbm_array.shape[2] != 128 or hbm_array.shape[1] % 8:
        raise ValueError(
            f"want (num_chunks, 8k rows, 128) float32, got {hbm_array.shape}"
        )
    return _run(
        hbm_array, jnp.int32(tripcount), jnp.int32(passes),
        mode=mode, interpret=interpret,
    )


def make_hbm_array(num_chunks: int = 64, chunk_rows: int = 512, seed: int = 0):
    """The HBM working set: (num_chunks, chunk_rows, 128) float32. Values
    in [0, 1) so the busy-wait chain stays bounded."""
    key = jax.random.PRNGKey(seed)
    return jax.random.uniform(
        key, (num_chunks, chunk_rows, 128), jnp.float32
    )
