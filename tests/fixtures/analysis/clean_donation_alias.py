"""Known-clean: the blessed snapshot patterns around a donating call.

``np.array`` is a REAL copy (the shipped ``_dispatch_chunk`` fix), and
a view of a buffer the call does NOT donate is fine.
"""

from functools import partial

import jax
import numpy as np


@partial(jax.jit, donate_argnums=(1,))
def _step(params, cache):
    return cache * params


def snapshot_with_copy(engine):
    # real copy: safe to hold across the donating call
    pos_start = np.array(engine.pos)
    engine.cache = _step(engine.params, engine.cache)
    return pos_start


def view_of_undonated(engine):
    # zero-copy view of params — which _step does NOT donate
    p = np.asarray(engine.params)
    engine.cache = _step(engine.params, engine.cache)
    return p


def view_not_used_after(engine):
    # view dies before the donating call's result can alias into it
    # being observed: nothing reads it afterwards
    peek = np.asarray(engine.cache)
    total = float(peek.sum())
    engine.cache = _step(engine.params, engine.cache)
    return total
