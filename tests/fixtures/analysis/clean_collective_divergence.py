"""Known-clean: the blessed rank-dependent shapes — branch on rank for
DATA or host I/O, never for which collective comes next; uniform
config flags may pick the algorithm because every rank sees the same
flag (the SPMD same-command-line invariant)."""

import jax
import jax.numpy as jnp
from jax import lax


def uniform_algorithm_switch(comm, x, use_ring):
    # the flag is per-RUN config, identical on every rank: whichever
    # arm is taken, all ranks take it together
    if use_ring:
        return comm.allreduce(x, algorithm="ring")
    return comm.allreduce(x)


def rank_dependent_data_not_schedule(comm, x):
    me = lax.axis_index("x")
    y = jnp.where(me == 0, x, -x)  # data diverges; the schedule doesn't
    return comm.allreduce(y)


def same_sequence_both_arms(comm, x):
    if jax.process_index() == 0:
        y = comm.allreduce(x)  # both arms issue the identical op
    else:
        y = comm.allreduce(-x)  # sequence: every rank is at allreduce#k
    return y


def rank_guarded_host_io(comm, x):
    y = comm.allreduce(x)
    if jax.process_index() == 0:
        print("sum ready")  # host-side logging under a rank guard is
    return y  # the sanctioned pattern — no collectives in the arm
