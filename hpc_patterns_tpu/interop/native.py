"""ctypes bindings for the native support library (native/hpcpat.cpp).

No pybind11 in this image, so the binding is plain ctypes over an
``extern "C"`` surface — the same spirit as the reference's C MPI API
use (mpi_datatype.hpp). The library is built by ``make -C native`` (or
:func:`build` — loading never compiles as a side effect); when the .so
is absent the module degrades gracefully (``available()`` → False,
Python fallbacks take over), the reference's whole-GPU-fallback
philosophy (devices.hpp:33-38).
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_SO = _NATIVE_DIR / "libhpcpat.so"

_lib = None
_load_failed = False


def build() -> bool:
    """Explicitly build the native library (``make -C native``). The
    only place a compiler run happens — loading never builds as a side
    effect, so a fresh checkout's first timing call stays cheap."""
    global _load_failed
    try:
        subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            check=True, capture_output=True, timeout=120,
        )
        _load_failed = False
        return _load() is not None
    except Exception:
        return False


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        if not _SO.exists():
            raise FileNotFoundError(f"{_SO} not built (run native.build())")
        lib = ctypes.CDLL(str(_SO))
        lib.hp_stats.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.hp_roundtrip.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
        ]
        lib.hp_aligned_alloc.restype = ctypes.c_void_p
        lib.hp_aligned_alloc.argtypes = [ctypes.c_size_t, ctypes.c_size_t]
        lib.hp_free.argtypes = [ctypes.c_void_p]
        lib.hp_fill.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_float,
        ]
        lib.hp_iota.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.c_float, ctypes.c_float,
        ]
        lib.hp_validate.restype = ctypes.c_int64
        lib.hp_validate.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.c_float, ctypes.c_float,
        ]
        lib.hp_ring_plan.argtypes = [
            ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.hp_ring_phase.restype = ctypes.c_int32
        lib.hp_ring_phase.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ]
        _lib = lib
    except Exception:
        _load_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


def _require_lib():
    lib = _load()
    if lib is None:
        raise RuntimeError(
            "native library unavailable (run hpc_patterns_tpu.interop."
            "native.build() or `make -C native`)"
        )
    return lib


class _OwnedView(np.ndarray):
    """ndarray subclass that holds a strong reference to the owning
    AlignedBuffer, so views (and dlpack consumers of them, which keep
    the exporting array alive) can never outlive the C allocation."""

    _owner = None


def stats(samples) -> dict:
    """min/max/mean/std computed in C (≙ the per-app chrono reductions)."""
    lib = _require_lib()
    xs = np.ascontiguousarray(samples, np.float64)
    out = np.zeros(4, np.float64)
    lib.hp_stats(
        xs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), xs.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    return {"min": out[0], "max": out[1], "mean": out[2], "std": out[3]}


def stats_roundtrip(samples):
    """Samples through native memory and back (binding health check used
    by harness.timing)."""
    lib = _require_lib()
    xs = np.ascontiguousarray(samples, np.float64)
    out = np.empty_like(xs)
    lib.hp_roundtrip(
        xs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), xs.size,
    )
    return out.tolist()


class AlignedBuffer:
    """float32 buffer from the native aligned allocator, exposed to
    numpy zero-copy (≙ the reference's USM allocations crossing
    runtimes). Frees the C memory when garbage collected."""

    def __init__(self, n_elements: int, alignment: int = 128):
        self._lib = _require_lib()
        self.n_elements = int(n_elements)
        self.alignment = int(alignment)
        self._ptr = self._lib.hp_aligned_alloc(self.n_elements * 4, self.alignment)
        if not self._ptr:
            raise MemoryError(
                f"hp_aligned_alloc({n_elements * 4}, {alignment}) failed"
            )

    @property
    def address(self) -> int:
        return int(self._ptr)

    def as_numpy(self) -> np.ndarray:
        """Zero-copy numpy view of the native memory. The view keeps this
        buffer alive (no use-after-free when the AlignedBuffer goes out
        of scope while views — or dlpack importers of them — remain)."""
        buf = (ctypes.c_float * self.n_elements).from_address(self._ptr)
        view = np.ctypeslib.as_array(buf).view(_OwnedView)
        view._owner = self
        return view

    def fill(self, value: float) -> None:
        self._lib.hp_fill(
            ctypes.cast(self._ptr, ctypes.POINTER(ctypes.c_float)),
            self.n_elements, ctypes.c_float(value),
        )

    def iota(self, base: float = 0.0, step: float = 1.0) -> None:
        self._lib.hp_iota(
            ctypes.cast(self._ptr, ctypes.POINTER(ctypes.c_float)),
            self.n_elements, ctypes.c_float(base), ctypes.c_float(step),
        )

    def validate(self, expected: float, tol: float = 1e-6) -> int:
        """Index of first mismatching element, or -1 (all good) — the C
        version of the analytic-oracle check (allreduce-mpi-sycl.cpp:
        192-204)."""
        return int(
            self._lib.hp_validate(
                ctypes.cast(self._ptr, ctypes.POINTER(ctypes.c_float)),
                self.n_elements, ctypes.c_float(expected), ctypes.c_float(tol),
            )
        )

    def __del__(self):
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr:
            self._lib.hp_free(ptr)


def ring_plan(size: int, shift: int = 1) -> list[tuple[int, int]]:
    """(src, dst) pairs for one ring step, computed natively — must match
    comm.ring._ring_perm exactly (cross-language cross-check)."""
    lib = _require_lib()
    src = np.zeros(size, np.int32)
    dst = np.zeros(size, np.int32)
    lib.hp_ring_plan(
        size, shift,
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return list(zip(src.tolist(), dst.tolist()))


def ring_phase_senders(size: int, phase: int) -> list[int]:
    """The even/odd deadlock-freedom ordering (allreduce-mpi-sycl.cpp:
    50-58): phase 0 = even ranks send, phase 1 = odd."""
    lib = _require_lib()
    out = np.zeros(size, np.int32)
    n = lib.hp_ring_phase(size, phase,
                          out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out[:n].tolist()
