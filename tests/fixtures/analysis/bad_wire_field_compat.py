"""Known-bad: wire-codec field drift across the to_wire/from_wire
pair. The reader indexes ``deadline_s`` without a guard even though
the field is not in REQUIRED_WIRE_FIELDS (an old-format peer kills
the resume), the writer ships a ``scratch`` field the reader never
looks at, and the reader still probes ``resume_from`` — a field the
writer stopped emitting."""

REQUIRED_WIRE_FIELDS = ("seq_id", "pos")


def bundle_to_wire(seq):
    return {
        "seq_id": seq.seq_id,
        "pos": seq.pos,
        "deadline_s": seq.deadline_s,
        "scratch": list(seq.scratch),  # EXPECT: wire-field-compat
    }


def bundle_from_wire(wire):
    seq_id = wire["seq_id"]
    pos = wire["pos"]
    # optional field read as if mandatory: raises KeyError on wires
    # sent by a peer from before the field existed
    deadline_s = wire["deadline_s"]  # EXPECT: wire-field-compat
    resume_from = wire.get("resume_from")  # EXPECT: wire-field-compat
    return seq_id, pos, deadline_s, resume_from
