"""Verdict engine: SUCCESS/FAILURE acceptance thresholds.

The reference's benchmarks are their own tests (SURVEY.md section 4): each
binary computes a theoretical bound from its serial baseline and exits
0/1 on whether the measured result is within tolerance of it. This module
centralizes those rules:

- SYCL rule (sycl_con.cpp:279-296): theoretical max speedup =
  serial_total / max_single_command; PASS iff achieved speedup >
  theoretical / 1.3; WARN (unbalanced commands) if theoretical <= 1.5.
- OMP rule (omp_con.cpp:223-244): PASS iff concurrent_total <=
  1.3 * max_single_command; WARN if theoretical <= 1.3.
- correctness rule (allreduce-mpi-sycl.cpp:192-204): every element equals
  the analytic oracle within tolerance; prints "Passed <rank>".
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

TOLERANCE = 1.3  # the reference's universal slack factor


@dataclasses.dataclass(frozen=True)
class Verdict:
    success: bool
    messages: tuple[str, ...]
    speedup: float | None = None
    max_theoretical_speedup: float | None = None
    warned_unbalanced: bool = False

    @property
    def exit_code(self) -> int:
        return 0 if self.success else 1

    def summary_line(self) -> str:
        # grep-able, like the lines run.sh:17-18 filters for
        return "SUCCESS" if self.success else "FAILURE"


def concurrency_verdict(
    serial_command_times_s: Sequence[float],
    concurrent_total_s: float,
    *,
    tolerance: float = TOLERANCE,
    rule: str = "sycl",
    resources: Sequence[str] | None = None,
) -> Verdict:
    """Overlap acceptance for the concurrency suite.

    ``rule="sycl"``: speedup-based (sycl_con.cpp:279-296).
    ``rule="omp"``: absolute-time-based (omp_con.cpp:238-244).

    ``resources`` (optional, aligned with the serial times): hardware
    resource label per command. Commands sharing a resource cannot
    overlap — two busy-wait chains on one sequential TensorCore, or two
    DMA streams sharing HBM bandwidth — so the concurrent floor is
    ``max over resources of (sum of that resource's command times)``
    rather than the reference's max-single-command. With one command per
    resource the two are identical; the reference's GPU assumption
    (every command class has its own engine) is exactly ``resources =
    all distinct``. This keeps the PASS bar honest on hardware where the
    assumption doesn't hold, instead of demanding physically impossible
    overlap.
    """
    serial_times = [float(t) for t in serial_command_times_s]
    if not serial_times or concurrent_total_s <= 0 or min(serial_times) <= 0:
        raise ValueError(
            "need positive serial per-command times and a positive concurrent total"
        )
    if resources is not None and len(resources) != len(serial_times):
        raise ValueError("resources must align with serial_command_times_s")
    serial_total = sum(serial_times)
    if resources is None:
        floor = max(serial_times)
    else:
        by_resource: dict[str, float] = {}
        for r, t in zip(resources, serial_times):
            by_resource[r] = by_resource.get(r, 0.0) + t
        floor = max(by_resource.values())
    max_single = floor
    max_theoretical = serial_total / max_single
    speedup = serial_total / concurrent_total_s
    msgs = [
        f"serial_total={serial_total:.6f}s max_single={max_single:.6f}s",
        f"speedup={speedup:.3f} max_theoretical={max_theoretical:.3f}",
    ]
    warn_threshold = 1.5 if rule == "sycl" else tolerance
    warned = max_theoretical <= warn_threshold
    if warned:
        msgs.append(
            "WARNING: commands are unbalanced; overlap barely measurable "
            f"(max theoretical speedup {max_theoretical:.3f} <= {warn_threshold})"
        )
    if rule == "sycl":
        ok = speedup > max_theoretical / tolerance
    elif rule == "omp":
        ok = concurrent_total_s <= tolerance * max_single
    else:
        raise ValueError(f"unknown rule {rule!r}")
    msgs.append("SUCCESS" if ok else "FAILURE")
    return Verdict(
        success=ok,
        messages=tuple(msgs),
        speedup=speedup,
        max_theoretical_speedup=max_theoretical,
        warned_unbalanced=warned,
    )


def correctness_verdict(
    result,
    expected_scalar: float,
    *,
    dtype=None,
    rank: int = 0,
) -> Verdict:
    """Analytic-oracle elementwise validation (allreduce-mpi-sycl.cpp:192-204)."""
    from hpc_patterns_tpu.dtypes import get_traits, validate_allreduce

    arr = np.asarray(result)
    dt = dtype if dtype is not None else arr.dtype
    ok = validate_allreduce(arr, expected_scalar, dt)
    if ok:
        msgs = (f"Passed {rank}", "SUCCESS")
    else:
        traits = get_traits(dt)
        atol = traits.tolerance if not traits.exact_sum else 0.0
        bad = np.flatnonzero(
            ~np.isclose(arr.astype(np.float64), float(expected_scalar), atol=atol, rtol=1e-6)
        )
        first = int(bad[0]) if bad.size else -1
        msgs = (
            f"rank {rank}: {bad.size}/{arr.size} elements wrong, "
            f"first at [{first}] = {arr.flat[first] if first >= 0 else '?'} "
            f"expected {expected_scalar}",
            "FAILURE",
        )
    return Verdict(success=ok, messages=msgs)
