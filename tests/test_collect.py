"""Unit tests for the cross-rank trace merge (harness/collect.py).

Pure-host tests over synthetic snapshots with KNOWN clock geometry:
offset estimation must recover constructed per-rank offsets within
tolerance (wall anchors alone, then the sync-anchor refinement undoing
a deliberately lying wall clock), the merge must produce one pid lane
per rank with flow events threading matched collectives, and the skew/
straggler/busy rollups must equal the numbers the events were built
from. No jax, no subprocesses — the multi-process end-to-end lives in
tests/test_launch.py.
"""

import json

import pytest

from hpc_patterns_tpu.harness import collect


def make_snap(rank, *, nprocs=2, boot=0.0, wall_skew=0.0, events=(),
              sync_mono=None, source=None):
    """A recorder snapshot for a rank whose monotonic clock started at
    true time ``boot`` (so ``mono = true − boot``) and whose wall clock
    is off by ``wall_skew`` seconds. Events carry LOCAL mono stamps."""
    snap = {
        "kind": "trace",
        "clock": {"mono0": 0.0, "wall0": boot + wall_skew,
                  "mono1": 100.0, "wall1": boot + 100.0 + wall_skew},
        "process": {"process_id": rank, "num_processes": nprocs,
                    "slice_id": 0},
        "sync": ([] if sync_mono is None else
                 [{"name": "make_communicator", "mono": sync_mono}]),
        "capacity": 1024, "n_events": len(events), "n_dropped": 0,
        "by_cat": {}, "compile": {"count": 0, "total_s": 0.0},
        "mem": {"peak_live_bytes": 0},
        "events": [list(e) for e in events],
    }
    if source is not None:
        snap["_source"] = source
    return snap


def window(name, true_start, dur, *, boot, seq, tid=1 << 20):
    """A device X slice in local mono time for a rank booted at ``boot``."""
    return ("X", "device", name, true_start - boot, tid, dur,
            {"seq": seq})


class TestClockAlignment:
    def test_wall_anchors_recover_known_offsets(self):
        # rank 0 booted at true t=100, rank 1 at t=200: their offsets
        # (mono -> true time) are exactly the boot instants
        snaps = [make_snap(0, boot=100.0), make_snap(1, boot=200.0)]
        align = collect.estimate_alignment(snaps)
        assert align["method"] == "wall"
        assert align["offsets"][0] == pytest.approx(100.0, abs=1e-9)
        assert align["offsets"][1] == pytest.approx(200.0, abs=1e-9)

    def test_drift_bound_from_anchor_disagreement(self):
        snap = make_snap(0, boot=100.0)
        snap["clock"]["wall1"] += 0.002  # clock drifted 2 ms over the run
        _, drift = collect.wall_offset(snap)
        assert drift == pytest.approx(0.001, abs=1e-9)

    def test_sync_anchors_correct_lying_wall_clock(self):
        # rank 1's wall clock is 0.5 s fast (NTP-scale skew). The sync
        # anchors were taken at the SAME true instant t=250 on both
        # ranks; refinement must pull rank 1's offset back to truth.
        snaps = [
            make_snap(0, boot=100.0, sync_mono=150.0),
            make_snap(1, boot=200.0, wall_skew=0.5, sync_mono=50.0),
        ]
        align = collect.estimate_alignment(snaps)
        assert align["method"] == "sync"
        assert align["offsets"][0] == pytest.approx(100.0, abs=1e-9)
        assert align["offsets"][1] == pytest.approx(200.0, abs=1e-9)
        # the refinement also reports how wrong wall-only would have been
        assert align["wall_disagreement_s"] == pytest.approx(0.5, abs=1e-9)

    def test_sync_skipped_without_common_anchors(self):
        snaps = [make_snap(0, boot=0.0, sync_mono=10.0),
                 make_snap(1, boot=0.0)]  # rank 1 has none
        align = collect.estimate_alignment(snaps)
        assert align["method"] == "wall"


class TestMerge:
    def _two_rank_snaps(self):
        # collective seq 0: rank 1 starts 2 ms late (start skew), and
        # with equal durations rank 1 finishes last (the straggler);
        # collective seq 1: aligned starts, rank 0 runs 3 ms longer
        # (dur skew) and is the straggler.
        name = "comm.allreduce.ring"
        r0 = [window(name, 300.000, 0.010, boot=100.0, seq=0),
              window(name, 301.000, 0.013, boot=100.0, seq=1)]
        r1 = [window(name, 300.002, 0.010, boot=200.0, seq=0),
              window(name, 301.000, 0.010, boot=200.0, seq=1)]
        return [make_snap(0, boot=100.0, sync_mono=150.0, events=r0),
                make_snap(1, boot=200.0, sync_mono=50.0, events=r1)]

    def test_one_pid_lane_per_rank_with_names(self):
        merged = collect.merge(self._two_rank_snaps())
        evs = merged["chrome"]["traceEvents"]
        pids = {e["pid"] for e in evs if e["ph"] not in ("M",)}
        assert pids == {0, 1}
        lanes = {e["args"]["name"] for e in evs
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert lanes == {"rank 0/2", "rank 1/2"}

    def test_flow_events_thread_matched_collectives(self):
        merged = collect.merge(self._two_rank_snaps())
        evs = merged["chrome"]["traceEvents"]
        flows = [e for e in evs if e["ph"] in ("s", "t", "f")]
        # 2 matched collectives x 2 ranks = 2 chains of (s, f)
        assert len(flows) == 4
        by_id = {}
        for e in flows:
            by_id.setdefault(e["id"], []).append(e)
        for chain in by_id.values():
            assert [e["ph"] for e in chain] == ["s", "f"]
            assert chain[0]["pid"] != chain[1]["pid"]  # crosses ranks
            assert chain[0]["ts"] <= chain[1]["ts"]  # time-ordered
            assert chain[-1]["bp"] == "e"

    def test_skew_rollup_matches_construction(self):
        rollup = collect.merge(self._two_rank_snaps())["rollup"]
        s = rollup["skew"]["comm.allreduce.ring"]
        assert s["n"] == 2
        assert s["max_start_skew_s"] == pytest.approx(0.002, abs=1e-6)
        assert s["mean_start_skew_s"] == pytest.approx(0.001, abs=1e-6)
        assert s["max_dur_skew_s"] == pytest.approx(0.003, abs=1e-6)

    def test_straggler_table(self):
        rollup = collect.merge(self._two_rank_snaps())["rollup"]
        # seq 0: rank 1 ends last (started late); seq 1: rank 0 (ran long)
        assert rollup["stragglers"]["0"] == {"last": 1, "of": 2}
        assert rollup["stragglers"]["1"] == {"last": 1, "of": 2}
        assert rollup["n_matched"] == 2

    def test_busy_bubble_fractions(self):
        rollup = collect.merge(self._two_rank_snaps())["rollup"]
        for r in ("0", "1"):
            b = rollup["busy"][r]
            assert 0.0 < b["busy_frac"] < 1.0
            assert b["busy_frac"] + b["bubble_frac"] == pytest.approx(1.0)

    def test_unmatched_single_rank_collective_counted_not_flowed(self):
        snaps = self._two_rank_snaps()
        snaps[0]["events"].append(list(window(
            "comm.pingpong", 302.0, 0.001, boot=100.0, seq=0)))
        merged = collect.merge(snaps)
        assert merged["rollup"]["n_unmatched"] == 1
        assert "comm.pingpong" not in merged["rollup"]["skew"]

    def test_colliding_rank_ids_get_distinct_lanes(self):
        # two unrelated single-process logs both claim rank 0: the
        # multi-file export fix — they must not share a pid lane
        snaps = [
            make_snap(0, nprocs=1, boot=0.0, source="a.jsonl"),
            make_snap(0, nprocs=1, boot=0.0, source="b.jsonl"),
        ]
        merged = collect.merge(snaps)
        meta = [e for e in merged["chrome"]["traceEvents"]
                if e["ph"] == "M" and e["name"] == "process_name"]
        assert {e["pid"] for e in meta} == {0, 1}
        assert {e["args"]["name"] for e in meta} == {"a.jsonl", "b.jsonl"}

    def test_same_source_same_rank_share_a_lane(self):
        snaps = [
            make_snap(0, nprocs=1, boot=0.0, source="a.jsonl"),
            make_snap(0, nprocs=1, boot=0.0, source="a.jsonl"),
        ]
        merged = collect.merge(snaps)
        pids = {e["pid"] for e in merged["chrome"]["traceEvents"]}
        assert pids == {0}

    def test_format_rollup_names_the_straggler(self):
        text = collect.format_rollup(
            collect.merge(self._two_rank_snaps())["rollup"])
        assert "allreduce.ring" in text
        assert "straggler: rank" in text
        assert "clock align: sync" in text


class TestCLI:
    def _rank_dir(self, tmp_path):
        d = tmp_path / "ranks"
        d.mkdir()
        snaps = TestMerge()._two_rank_snaps()
        for snap in snaps:
            r = snap["process"]["process_id"]
            (d / f"rank{r:05d}.trace.json").write_text(json.dumps(snap))
        return d

    def test_merges_rank_dir(self, tmp_path, capsys):
        d = self._rank_dir(tmp_path)
        out = tmp_path / "merged.json"
        log = tmp_path / "run.jsonl"
        assert collect.main([str(d), "-o", str(out),
                             "--log", str(log)]) == 0
        printed = capsys.readouterr().out
        assert "max start skew" in printed
        chrome = json.loads(out.read_text())  # strict JSON
        assert {e["pid"] for e in chrome["traceEvents"]} == {0, 1}
        recs = [json.loads(l) for l in log.read_text().splitlines()]
        assert [r["kind"] for r in recs] == ["trace_merged"]
        assert recs[0]["n_ranks"] == 2

    def test_reads_runlog_jsonl_inputs(self, tmp_path, capsys):
        snaps = TestMerge()._two_rank_snaps()
        files = []
        for snap in snaps:
            r = snap["process"]["process_id"]
            p = tmp_path / f"r{r}.jsonl"
            p.write_text(json.dumps({"kind": "result"}) + "\n"
                         + json.dumps(snap) + "\n")
            files.append(str(p))
        out = tmp_path / "m.json"
        assert collect.main([*files, "-o", str(out)]) == 0
        capsys.readouterr()
        chrome = json.loads(out.read_text())
        assert {e["pid"] for e in chrome["traceEvents"]} == {0, 1}

    def test_no_snapshots_exits_2(self, tmp_path, capsys):
        d = tmp_path / "empty"
        d.mkdir()
        assert collect.main([str(d)]) == 2
        assert "no trace snapshots" in capsys.readouterr().err


class TestUnionSeconds:
    def test_overlapping_intervals_not_double_counted(self):
        assert collect._union_seconds(
            [(0.0, 1.0), (0.5, 1.5), (3.0, 4.0)]) == pytest.approx(2.5)

    def test_contained_interval(self):
        assert collect._union_seconds(
            [(0.0, 2.0), (0.5, 1.0)]) == pytest.approx(2.0)

    def test_empty(self):
        assert collect._union_seconds([]) == 0.0


def make_chain(ops):
    """``collectives`` snapshot field built by the REAL runtime chain
    (analysis/runtime.py), so these tests pin the same hashing the
    launched ranks use."""
    from hpc_patterns_tpu.analysis.runtime import CollectiveSchedule

    s = CollectiveSchedule()
    for op, seq in ops:
        s.record(op, seq, shape=(2, 8), dtype="float32", axis="x")
    return s.snapshot()


class TestScheduleCheck:
    """Merge-time collective schedule verification: equal chains prove
    the SPMD schedules matched; a mismatch names the first divergent
    (rank, op, seq) — the deadlock-debug headline."""

    def _snaps(self, ops0, ops1):
        s0 = make_snap(0, boot=100.0)
        s0["collectives"] = make_chain(ops0)
        s1 = make_snap(1, boot=200.0)
        s1["collectives"] = make_chain(ops1)
        return [s0, s1]

    def test_equal_chains_verdict_consistent(self):
        ops = [("allreduce.ring", i) for i in range(5)]
        rollup = collect.merge(self._snaps(ops, ops))["rollup"]
        sched = rollup["schedule"]
        assert sched["verdict"] == "consistent"
        assert sched["n_collectives"] == 5
        assert sched["n_ranks_recorded"] == 2
        assert sched["digest"]
        text = collect.format_rollup(rollup)
        assert "collective schedules consistent across 2 rank(s)" in text
        assert sched["digest"] in text

    def test_divergence_names_first_divergent_op_seq(self):
        shared = ("allreduce.ring", 0)
        ops0 = [shared, ("allreduce.ring", 1), ("allreduce.ring", 2)]
        ops1 = [shared, ("sendrecv_ring", 1), ("allreduce.ring", 2)]
        rollup = collect.merge(self._snaps(ops0, ops1))["rollup"]
        sched = rollup["schedule"]
        assert sched["verdict"] == "divergent"
        fd = sched["first_divergence"]
        assert fd["index"] == 1
        assert fd["ranks"]["0"] == {"op": "allreduce.ring", "seq": 1}
        assert fd["ranks"]["1"] == {"op": "sendrecv_ring", "seq": 1}
        text = collect.format_rollup(rollup)
        assert "COLLECTIVE SCHEDULE DIVERGENCE at #1" in text
        assert "rank 0 is at allreduce.ring#1" in text
        assert "rank 1 is at sendrecv_ring#1" in text

    def test_short_chain_reported_as_ended(self):
        # rank 1 stopped issuing collectives one step early (the hang /
        # early-exit shape): the divergence point is the first
        # collective it never issued
        ops0 = [("allreduce.ring", 0), ("allreduce.ring", 1)]
        ops1 = [("allreduce.ring", 0)]
        sched = collect.merge(
            self._snaps(ops0, ops1))["rollup"]["schedule"]
        assert sched["verdict"] == "divergent"
        fd = sched["first_divergence"]
        assert fd["index"] == 1
        assert fd["ranks"]["0"] == {"op": "allreduce.ring", "seq": 1}
        assert fd["ranks"]["1"] == {"ended_at": 1}

    def test_shape_divergence_caught_by_fingerprint(self):
        # same op/seq stream, different SHAPE on rank 1 — invisible to
        # op-name matching, caught because shape feeds the hash
        from hpc_patterns_tpu.analysis.runtime import CollectiveSchedule

        s0, s1 = make_snap(0, boot=0.0), make_snap(1, boot=0.0)
        a = CollectiveSchedule()
        a.record("allreduce.ring", 0, shape=(2, 8), dtype="f32", axis="x")
        b = CollectiveSchedule()
        b.record("allreduce.ring", 0, shape=(2, 16), dtype="f32", axis="x")
        s0["collectives"], s1["collectives"] = a.snapshot(), b.snapshot()
        sched = collect.merge([s0, s1])["rollup"]["schedule"]
        assert sched["verdict"] == "divergent"
        assert sched["first_divergence"]["index"] == 0

    def test_no_chains_reads_not_recorded(self):
        rollup = collect.merge(
            [make_snap(0, boot=0.0), make_snap(1, boot=0.0)])["rollup"]
        assert rollup["schedule"]["verdict"] == "not_recorded"
        assert "SCHEDULE" not in collect.format_rollup(rollup)

    def test_one_chain_reads_single_rank(self):
        s0, s1 = make_snap(0, boot=0.0), make_snap(1, boot=0.0)
        s0["collectives"] = make_chain([("allreduce.ring", 0)])
        sched = collect.merge([s0, s1])["rollup"]["schedule"]
        assert sched["verdict"] == "single_rank"
        assert sched["n_ranks_recorded"] == 1
