"""Known-bad: jit wrappers built per call / per iteration, and a fresh
container as a static arg."""

from functools import partial

import jax


def per_call(x):
    return jax.jit(lambda v: v + 1)(x)  # EXPECT: recompile-hazard


def per_iteration(xs):
    out = []
    for x in xs:
        f = jax.jit(lambda v: v * 2)  # EXPECT: recompile-hazard
        out.append(f(x))
    return out


@partial(jax.jit, static_argnames=("sizes",))
def bucketed(x, *, sizes):
    return x


def fresh_static_container(x):
    return bucketed(x, sizes=[16, 32])  # EXPECT: recompile-hazard
