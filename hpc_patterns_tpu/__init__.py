"""tpu-hpc-patterns: a TPU-native framework with the capabilities of
illuhad/HPC-Patterns, rebuilt idiomatically on JAX/XLA/Pallas/pjit.

The reference (mounted at /root/reference) is a C++ suite of three
self-validating GPU-parallelism pattern benchmarks:

1. ``concurency/``              -> :mod:`hpc_patterns_tpu.concurrency`
   (concurrent kernel/copy overlap; SYCL/OMP queues -> JAX async dispatch)
2. ``aurora.mpich.miniapps/``   -> :mod:`hpc_patterns_tpu.comm` + ``apps/``
   (GPU-aware MPI ring + collective allreduce -> ppermute/psum over a Mesh)
3. ``sycl_omp_ze_interopt/``    -> :mod:`hpc_patterns_tpu.interop`
   (Level-Zero zero-copy interop -> dlpack + native C++ shared buffers)

Plus the layers the reference implies (SURVEY.md section 1):
- device discovery/topology (``devices.hpp``) -> :mod:`hpc_patterns_tpu.topology`
- dtype traits (``mpi_datatype.hpp``)         -> :mod:`hpc_patterns_tpu.dtypes`
- harness/verdict/timing (per-app main()s)    -> :mod:`hpc_patterns_tpu.harness`

And the TPU-first extensions the ring/pt2pt primitives are shaped for:
- :mod:`hpc_patterns_tpu.parallel` — ring attention / sequence parallelism,
  tensor parallelism helpers built on the same ring engine.
- :mod:`hpc_patterns_tpu.models` — a flagship transformer exercising
  dp/tp/sp shardings end to end.
"""

__version__ = "0.1.0"

from hpc_patterns_tpu import topology, dtypes  # noqa: F401
