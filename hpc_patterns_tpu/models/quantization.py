"""The precision-law oracle: what "correct" means across precisions.

Token identity is the serving oracle WITHIN a precision (an int8-KV
engine is token-identical to int8-KV standalone decode — same math
both sides, tests/test_quantization.py pins it). ACROSS precisions it
cannot hold: a quantized cache or weight set perturbs every logit, so
the contract is a LAW bound instead — the same oracle shape PR 2 used
for draft-assisted sampling, applied to precision:

- **greedy top-1 agreement**: the fraction of TEACHER-FORCED steps
  whose argmax token matches the reference precision's. Teacher-forced
  (both variants walk the REFERENCE's token stream) because
  free-running agreement compounds: one near-tie flip early makes
  every later token trivially different, which measures drift, not
  quantization error;
- **total-variation distance**: ``0.5 * sum |softmax_a - softmax_b|``
  per teacher-forced step — the distributional distance sampling
  inherits, reported as mean and max over the walk.

``bench_serving --kv-dtype`` runs this oracle BEFORE reporting any
quantized number (bounds in :data:`DEFAULT_BOUNDS`), and the tier-1
tests pin the same bounds per precision (int8/fp8 KV, int8 weights,
and the composed forms). docs/quantization.md has the full matrix.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from hpc_patterns_tpu.models.decode import decode_step, prefill
from hpc_patterns_tpu.models.transformer import (  # noqa: F401  (re-export)
    QUANT_SCALE_SUFFIX,
    TransformerConfig,
    matmul_weight,
    quantize_weights_int8,
)

#: the law bounds the serving benches gate on (comfortably above the
#: measured smoke-scale values — agreement ~0.95+, mean TV ~0.01 —
#: tight enough that a broken dequant path, which sends TV toward 1,
#: cannot pass)
DEFAULT_BOUNDS = {
    "greedy_agreement_min": 0.85,
    "tv_mean_max": 0.05,
    "tv_max_max": 0.15,
}


@dataclasses.dataclass(frozen=True)
class PrecisionLaw:
    """One oracle run's verdict (:func:`precision_law`)."""
    greedy_agreement: float
    tv_mean: float
    tv_max: float
    steps: int

    def check(self, bounds: dict | None = None) -> None:
        """Raise AssertionError naming the violated bound (the
        benches call this before believing any quantized number)."""
        b = {**DEFAULT_BOUNDS, **(bounds or {})}
        assert self.greedy_agreement >= b["greedy_agreement_min"], (
            f"precision law: greedy top-1 agreement "
            f"{self.greedy_agreement:.3f} < "
            f"{b['greedy_agreement_min']} over {self.steps} "
            "teacher-forced steps")
        assert self.tv_mean <= b["tv_mean_max"], (
            f"precision law: mean TV distance {self.tv_mean:.4f} > "
            f"{b['tv_mean_max']}")
        assert self.tv_max <= b["tv_max_max"], (
            f"precision law: max TV distance {self.tv_max:.4f} > "
            f"{b['tv_max_max']}")


def precision_law(params_ref, cfg_ref: TransformerConfig, params_q,
                  cfg_q: TransformerConfig, prompts, steps: int = 8,
                  ) -> PrecisionLaw:
    """Teacher-forced precision-law measurement between a REFERENCE
    precision (``params_ref``/``cfg_ref``) and a QUANTIZED variant
    (``params_q``/``cfg_q`` — quantized KV config, int8 weights from
    :func:`quantize_weights_int8`, or both). ``prompts``: (B, T) int32.

    Both variants prefill the same prompts and then walk ``steps``
    decode steps along the REFERENCE's greedy continuation, comparing
    the step logits' argmax and softmax TV at every position — each
    step an independent judgment of the quantization error at that
    state, no compounding. The linear cache route is used (one
    prefill + unrolled steps); KV-precision effects show up from the
    first decode step because prefill quantizes the stored K/V."""
    prompts = jnp.asarray(prompts, jnp.int32)
    B, T = prompts.shape
    need = T + steps
    if need > min(cfg_ref.max_seq, cfg_q.max_seq):
        raise ValueError(
            f"prompt {T} + steps {steps} exceeds max_seq "
            f"{min(cfg_ref.max_seq, cfg_q.max_seq)}")
    la, cache_a = prefill(params_ref, prompts, cfg_ref, need)
    lb, cache_b = prefill(params_q, prompts, cfg_q, need)
    agree, tvs = [], []
    pos = T
    for step in range(steps):
        pa = jax.nn.softmax(la, axis=-1)
        pb = jax.nn.softmax(lb, axis=-1)
        tvs.append(0.5 * np.abs(np.asarray(pa) - np.asarray(pb))
                   .sum(axis=-1))
        ref_tok = jnp.argmax(la, axis=-1).astype(jnp.int32)
        agree.append(np.asarray(
            ref_tok == jnp.argmax(lb, axis=-1).astype(jnp.int32)))
        if step == steps - 1:
            break  # the last judged logits need no successor state
        # BOTH variants consume the reference's token (teacher forcing)
        la, cache_a = decode_step(params_ref, cache_a, jnp.int32(pos),
                                  ref_tok, cfg_ref)
        lb, cache_b = decode_step(params_q, cache_b, jnp.int32(pos),
                                  ref_tok, cfg_q)
        pos += 1
    return PrecisionLaw(
        greedy_agreement=float(np.mean(agree)),
        tv_mean=float(np.mean(tvs)),
        tv_max=float(np.max(tvs)),
        steps=steps,
    )
