"""Ring engine: neighbor-exchange collectives built on ``lax.ppermute``.

TPU-native rebuild of the reference's hand-rolled ring
(``SendRecvRing`` + step-wise accumulate + buffer swap,
allreduce-mpi-sycl.cpp:43-59,173-182). The reference's even/odd blocking
send/recv ordering exists only to avoid MPI deadlock; ``ppermute`` is a
deadlock-free collective permute, so the *schedule* (who talks to whom,
what is combined per step) is what is reproduced, not the ordering trick.

Everything here is a **rank-local** function meant to run inside
``shard_map``: it takes the local shard and a mesh axis name, the way the
reference's per-rank functions take a device buffer and a communicator.
On TPU the permutes ride ICI between mesh neighbors; XLA lowers them to
collective-permute with no host staging ("GPU-aware" semantics, §2.3).

This ring engine is deliberately API-shaped as a reusable primitive
(SURVEY.md §5 "long-context"): per-step neighbor shift + local combine +
buffer rotation is exactly the ring-attention / context-parallel
dataflow, and :mod:`hpc_patterns_tpu.parallel.ring_attention` builds on
:func:`ring_schedule` directly.
"""

from __future__ import annotations

from collections import Counter
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax


def axis_size(axis: str) -> int:
    """World size of a mesh axis, inside shard_map (MPI_Comm_size analog).
    ``lax.psum(1, axis)`` on builds without ``lax.axis_size`` (0.4.x) —
    a concrete reduction of a concrete 1, so it stays a Python int
    (usable in loop bounds/shapes) on both routes."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def axis_index(axis: str):
    """This shard's rank on ``axis`` (MPI_Comm_rank analog); traced value."""
    return lax.axis_index(axis)


def _ring_perm(size: int, shift: int) -> list[tuple[int, int]]:
    """Static source->dest pairs sending each rank's data ``shift`` to the
    right (shift may be negative)."""
    return [(i, (i + shift) % size) for i in range(size)]


def check_permutation(pairs, size: int, *, allow_partial: bool = False) -> None:
    """Deadlock/race sanitizer for ppermute schedules.

    The reference avoids deadlock *by construction* (even/odd blocking
    ordering, allreduce-mpi-sycl.cpp:50-58) and has no checker
    (SURVEY.md §5 "race detection: None"). ppermute is deadlock-free by
    design, but a malformed permutation silently drops or duplicates
    data (XLA zero-fills destinations with no incoming pair); this
    closes that gap: indices in range, no rank twice as source or
    destination, and — unless ``allow_partial`` — every rank exactly
    once as both (a true permutation). Raises ValueError. O(n).
    """
    srcs, dsts = [], []
    for s, d in pairs:
        if not (0 <= s < size and 0 <= d < size):
            raise ValueError(f"pair ({s}, {d}) out of range for size {size}")
        srcs.append(s)
        dsts.append(d)
    by_name = (("sources", srcs), ("destinations", dsts))
    for name, idxs in by_name:
        counts = Counter(idxs)
        dups = sorted(x for x, c in counts.items() if c > 1)
        if dups:
            raise ValueError(
                f"malformed permutation: duplicate {name} {dups} — data "
                "would be dropped/duplicated"
            )
    if not allow_partial:
        for name, idxs in by_name:
            missing = sorted(set(range(size)) - set(idxs))
            if missing:
                raise ValueError(
                    f"partial permutation: ranks {missing} missing from "
                    f"{name} — ppermute would zero-fill their buffers "
                    "(pass allow_partial=True if intended)"
                )


def ring_shift(x, axis: str, shift: int = 1):
    """Shift local data ``shift`` ranks around the ring.

    The TPU analog of one ``SendRecvRing(src, dest, rank, right, left, n)``
    step (allreduce-mpi-sycl.cpp:43-59): rank r's buffer lands on rank
    ``(r + shift) % size``. Deadlock-free by construction (collective
    permute), unlike the reference which needs even/odd send/recv
    ordering (:50-58).
    """
    size = axis_size(axis)
    perm = _ring_perm(size, shift)
    check_permutation(perm, size)
    return lax.ppermute(x, axis, perm)


def pairwise_exchange(x, axis: str):
    """Even/odd partner swap: rank r exchanges with rank ``r ^ 1``.

    The ping-pong pattern (BASELINE.json pt2pt config; the reference's
    paired blocking Send/Recv, allreduce-mpi-sycl.cpp:50-58). Requires an
    even axis size, matching the miniapps' even-rank-count precondition
    (allreduce-mpi-sycl.cpp:95-97).
    """
    size = axis_size(axis)
    if size % 2:
        raise ValueError(f"pairwise_exchange needs an even axis size, got {size}")
    perm = [(i, i ^ 1) for i in range(size)]
    check_permutation(perm, size)
    return lax.ppermute(x, axis, perm)


def ring_schedule(
    x,
    axis: str,
    step_fn: Callable,
    *,
    steps: int | None = None,
    shift: int = 1,
    carry=None,
):
    """The generic ring dataflow: ``steps`` rounds of (shift buffer one
    neighbor over, combine locally).

    Reproduces the reference's ring loop shape (allreduce-mpi-sycl.cpp:
    177-181): ``for s in 1..size-1: SendRecvRing; swap(VA,VB); Accumulate``
    — here the "swap" is functional (the shifted value *is* the next
    buffer) and "Accumulate" is ``step_fn``.

    ``step_fn(carry, incoming, step)`` -> new carry. ``incoming`` at step
    ``s`` is the shard originally held by rank ``(r - s*shift) % size``.
    The loop is a static Python loop over a static ``steps`` (size-1 by
    default) so XLA can pipeline permutes against the combines — a
    ``fori_loop`` would also work but hides the unrolled overlap from the
    scheduler at small world sizes.
    """
    size = axis_size(axis)
    if steps is None:
        steps = size - 1
    buf = x
    if carry is None:
        carry = x
    for s in range(1, steps + 1):
        buf = ring_shift(buf, axis, shift)
        carry = step_fn(carry, buf, s)
    return carry


def ring_allreduce(x, axis: str):
    """Allreduce(SUM) as a (size-1)-step ring of neighbor exchanges —
    the reference's hand-rolled algorithm (allreduce-mpi-sycl.cpp:173-182)
    rebuilt on ``ppermute``.

    Every rank ends with the elementwise sum over all ranks, same as
    ``MPI_Allreduce``; the analytic oracle ``size*(size-1)/2`` for
    rank-valued inputs holds (:192-204). Moves the *full* buffer each
    step: (size-1) * n elements on the wire per rank — the bandwidth cost
    the reference's ring pays. See :func:`ring_allreduce_chunked` for the
    bandwidth-optimal two-phase version.
    """
    return ring_schedule(x, axis, lambda acc, incoming, _s: acc + incoming)


def ring_reduce_scatter(x, axis: str, *, scatter_axis: int = 0):
    """Reduce-scatter as a (size-1)-step chunked ring.

    Phase 1 of the bandwidth-optimal allreduce: the local buffer is split
    into ``size`` chunks along ``scatter_axis``; each step sends the
    partially-reduced chunk one neighbor right and accumulates the chunk
    arriving from the left. Rank r ends holding chunk r fully reduced.
    Wire cost: n * (size-1)/size per rank — the reason rings win at large
    message sizes (the ring-vs-collective comparison of BASELINE.json).
    """
    size = axis_size(axis)
    me = lax.axis_index(axis)
    if x.shape[scatter_axis] % size:
        raise ValueError(
            f"scatter axis length {x.shape[scatter_axis]} not divisible by {size}"
        )
    chunks = jnp.split(x, size, axis=scatter_axis)
    # Walk the ring: at step s, rank r sends the chunk destined for rank
    # (r - s) and receives+accumulates the one destined for (r - s - 1)...
    # equivalently: send chunk index (me - s + 1), recv (me - s). Static
    # loop with a dynamic chunk select keeps shapes static under jit.
    stacked = jnp.stack(chunks)  # (size, chunk...)
    send = lax.dynamic_index_in_dim(stacked, (me + size - 1) % size, keepdims=False)
    for s in range(1, size):
        incoming = ring_shift(send, axis, 1)
        idx = (me + size - 1 - s) % size
        mine = lax.dynamic_index_in_dim(stacked, idx, keepdims=False)
        send = mine + incoming
    # send now holds chunk ``me`` fully reduced.
    return send


def ring_all_gather(x, axis: str, *, gather_axis: int = 0, tiled: bool = False):
    """All-gather as a (size-1)-step ring (phase 2 of two-phase allreduce).

    Each step forwards the chunk received last step; after size-1 steps
    every rank holds every chunk. ``tiled=False`` stacks a new leading
    axis; ``tiled=True`` concatenates along ``gather_axis`` (XLA
    ``all_gather`` convention, kept so this is a drop-in for
    ``lax.all_gather``).
    """
    size = axis_size(axis)
    me = lax.axis_index(axis)
    pieces = [x]
    buf = x
    for _ in range(size - 1):
        buf = ring_shift(buf, axis, 1)
        pieces.append(buf)
    # pieces[s] came from rank (me - s); roll into global rank order so
    # position j holds rank j's chunk on every rank.
    stacked = jnp.stack(pieces)  # (size, ...), index s = rank (me - s)
    ranks = (me - jnp.arange(size)) % size  # position->source rank
    inv = jnp.zeros((size,), dtype=ranks.dtype).at[ranks].set(jnp.arange(size))
    ordered = jnp.take(stacked, inv, axis=0)
    if not tiled:
        return ordered
    parts = [lax.index_in_dim(ordered, i, keepdims=False) for i in range(size)]
    return jnp.concatenate(parts, axis=gather_axis)


def ring_allreduce_chunked(x, axis: str, *, scatter_axis: int = 0):
    """Bandwidth-optimal allreduce: ring reduce-scatter + ring all-gather.

    2·n·(size-1)/size wire bytes per rank vs the naive ring's n·(size-1)
    — the textbook ring allreduce the reference's miniapp is a teaching
    version of. This is the variant raced against ``lax.psum`` in the
    miniapp's ring-vs-collective benchmark (§2.3 requirement (b)).
    """
    reduced = ring_reduce_scatter(x, axis, scatter_axis=scatter_axis)
    return ring_all_gather(reduced, axis, gather_axis=scatter_axis, tiled=True)


def ring_pipeline(xs: Sequence, axis: str, stage_fn: Callable, *, shift: int = 1):
    """Neighbor handoff skeleton for pipeline-parallel stage boundaries:
    apply ``stage_fn`` locally, then pass activations one rank over (the
    pt2pt pattern of SURVEY.md §2.2 "Pairwise pt2pt (the core of PP)").
    """
    ys = stage_fn(*xs) if isinstance(xs, (tuple, list)) else stage_fn(xs)
    return jax.tree.map(lambda t: ring_shift(t, axis, shift), ys)
