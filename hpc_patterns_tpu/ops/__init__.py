"""Pallas TPU kernels — the hand-written hot ops.

The reference's only device kernels are a busy-wait FMA chain and a
vector accumulate (sycl_con.cpp:26-33, allreduce-mpi-sycl.cpp:26-31);
the TPU framework's hot ops live here instead, written as Pallas kernels
where XLA's automatic fusion isn't enough (SURVEY.md preamble: "pallas
kernels for the hot ops"):

- :mod:`~.flash_attention` — blockwise causal attention in VMEM with an
  online-softmax accumulator: O(T) memory, MXU-shaped block matmuls,
  grid-pipelined HBM→VMEM streaming. The single-chip fast path of the
  model (the ring/Ulysses paths in :mod:`hpc_patterns_tpu.parallel`
  distribute *across* chips; this kernel is what each chip should run
  locally).

The concurrency suite's kernels (busy-wait, DMA/compute pipeline) stay
in :mod:`hpc_patterns_tpu.concurrency` next to their benchmarks.
"""

from hpc_patterns_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_block,
)
from hpc_patterns_tpu.ops.paged_attention import (  # noqa: F401
    paged_attention_decode,
)
