"""Serving-plane app: a router + N engine replicas under the launcher.

The launched tier of the disaggregated serving plane
(``hpc_patterns_tpu/serving_plane/``): ``apps/launch.py -np K`` starts
K processes of this app; rank 0 becomes the ROUTER, ranks 1..K-1
become REPLICAS (roles from ``--roles``, e.g. ``prefill,decode`` for
the disaggregated 1p/1d shape). Replicas publish ephemeral localhost
ports under ``--rdv`` (the hostfile analog), the router connects,
admits a seeded open-loop loadgen stream across them, forwards KV
handoffs from prefill- to decode-role replicas, and prints the SLO
table with GOODPUT next to raw tok/s plus a grep-able summary line.

Two engine tiers behind one protocol:

- ``--stub``: deterministic jax-free token generators — the plane's
  ROUTER mechanics (placement, migration forwarding, replica-death
  recovery, shed accounting) exercised in milliseconds; the router
  byte-checks every served stream against the stub's pure function,
  so even the failure drills are oracle-checked (tier-1,
  tests/test_launch.py).
- real engines (default): each replica boots a small model
  (identically seeded, so ``request_key`` agrees across replicas) and
  serves through :class:`~hpc_patterns_tpu.models.serving.EngineCore`
  — the reground leg's shape.

Chaos composes through the launcher: ``--chaos
'die:replica=2,at=5,site=replica_round'`` kills ONE replica of many
mid-stream; the router re-queues its in-flight requests as resumes on
survivors (or counts them shed — never a silent drop), the rank
report names the lost replica with its fault kind, and the surviving
ranks' traces still merge. Under ``--trace`` + ``--trace-out``, both
sides of every KV handoff record matched ``plane.kv_migration``
windows and ``kv_migration`` schedule fingerprints: the merged
timeline threads flow arrows between the replica lanes and the
schedule verifier proves router and replicas agreed on the handoff
order (docs/serving_plane.md).

Usage (the tier-1 test shape)::

    python -m hpc_patterns_tpu.apps.launch -np 3 --trace-out m.json -- \\
        python -m hpc_patterns_tpu.apps.plane_app --stub \\
        --roles prefill,decode --rdv /tmp/rdv --requests 6 --trace
"""

from __future__ import annotations

import os
import sys
import time

from hpc_patterns_tpu.harness.cli import (
    add_autofit_arg,
    add_explain_args,
    base_parser,
    explain_enabled,
)


def build_parser():
    p = base_parser(__doc__.splitlines()[0])
    add_autofit_arg(p)
    add_explain_args(p)
    p.add_argument("--rdv", required=True,
                   help="rendezvous directory replicas publish their "
                        "listen addresses under (shared by all ranks)")
    p.add_argument("--roles", default="both",
                   help="comma-separated replica roles for ranks 1..N "
                        "(both|prefill|decode; short lists repeat "
                        "their last entry): 'prefill,decode' is the "
                        "disaggregated 1p/1d shape")
    p.add_argument("--stub", action="store_true",
                   help="jax-free deterministic stub engines (router-"
                        "mechanics tier; tokens byte-checked against "
                        "the stub's pure function)")
    p.add_argument("--policy", default="least_loaded",
                   choices=["least_loaded", "round_robin"],
                   help="router placement policy")
    p.add_argument("--requests", type=int, default=8)
    p.add_argument("--rate", type=float, default=200.0,
                   help="open-loop Poisson arrival rate (req/s)")
    p.add_argument("--prompt-len", type=int, default=12)
    p.add_argument("--budget", type=int, default=12,
                   help="max new tokens per request")
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--pool-pages", type=int, default=0,
                   help="per-replica arena (0 = slots * pages/seq)")
    p.add_argument("--chunk", type=int, default=4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="sampled serving (> 0): real replicas sample "
                        "per-row key streams; stub replicas run the "
                        "keyed hash chain — either way the round "
                        "replies carry per-row key state, so a "
                        "replica death resumes SAMPLED streams "
                        "byte-exact on survivors (the router's "
                        "resume checkpoint, docs/serving_plane.md)")
    p.add_argument("--plane-timeout", type=float, default=120.0,
                   help="router drain deadline / replica idle timeout")
    return p


def _roles_for(nreplicas: int, spec: str) -> list[str]:
    roles = [r.strip() for r in spec.split(",") if r.strip()]
    if not roles:
        roles = ["both"]
    for r in roles:
        if r not in ("both", "prefill", "decode"):
            raise ValueError(f"bad role {r!r}")
    while len(roles) < nreplicas:
        roles.append(roles[-1])
    return roles[:nreplicas]


def _schedule(args):
    """The seeded open-loop stream: Poisson arrivals over two priority
    classes (harness/loadgen.py), prompt CONTENT from a separate
    seeded rng — deterministic end to end, so the stub oracle and a
    chaos replay both see the exact same traffic."""
    import numpy as np

    from hpc_patterns_tpu.harness import loadgen

    classes = (
        loadgen.PriorityClass("interactive", 0, weight=0.5,
                              ttft_slo_s=30.0),
        loadgen.PriorityClass("batch", 1, weight=0.5),
    )
    sched = loadgen.make_schedule(
        args.requests, rate_rps=args.rate, classes=classes,
        prompt_lens=(max(1, args.prompt_len // 2), args.prompt_len),
        budgets=(max(1, args.budget // 2), args.budget),
        process="poisson", seed=args.seed)
    rng = np.random.RandomState(args.seed + 13)
    prompts = {r.index: [int(t) for t in rng.randint(0, 64,
                                                     size=r.prompt_len)]
               for r in sched.requests}
    arrivals = [
        (r.t_arrival_s, dict(prompt=prompts[r.index],
                             max_new=r.max_new,
                             priority=r.priority,
                             deadline_s=r.deadline_s))
        for r in sched.requests
    ]
    return sched, prompts, arrivals, classes


def _run_router(args, nprocs: int) -> int:
    from hpc_patterns_tpu.harness import slo as slolib
    from hpc_patterns_tpu.harness.runlog import RunLog
    from hpc_patterns_tpu.serving_plane import service

    sched, prompts, arrivals, classes = _schedule(args)
    handles = service.connect_replicas(
        args.rdv, range(1, nprocs), wait_s=args.plane_timeout,
        timeout_s=args.plane_timeout)
    print(f"router: {len(handles)} replica(s) connected "
          f"(roles {[h.role for h in handles]}, "
          f"policy {args.policy})", flush=True)
    emit = (RunLog(args.log, truncate=False).emit
            if args.log else None)
    if args.fitted is not None:
        # fitted placement (policy + per-replica weights) applies
        # unless the user picked a non-default --policy explicitly
        kw = ({"policy": args.policy}
              if args.policy != "least_loaded" else {})
        router = service.PlaneRouter.from_fitted(
            handles, args.fitted,
            slo_targets=slolib.targets_from_classes(classes),
            emit=emit, **kw)
        print(f"router: autofit placement from {args.autofit} "
              f"(policy {router.policy})", flush=True)
    else:
        router = service.PlaneRouter(
            handles, policy=args.policy,
            slo_targets=slolib.targets_from_classes(classes),
            emit=emit)
    if explain_enabled(args):
        # router-stamped request tracing: one recorder, one clock —
        # the PlaneRouter class contract (serving_plane/service.py)
        from hpc_patterns_tpu.harness import reqtrace as reqtracelib

        reqtracelib.configure(enabled=True)
    report = router.run(arrivals, timeout_s=args.plane_timeout)

    ok = True
    if args.stub:
        # the stub oracle: every served stream must equal the pure
        # token function of its ORIGINAL prompt — resumed-on-survivor
        # rows included (that is the point of the drill). Sampled
        # mode walks the key CHAIN from key_0: a resume is only
        # byte-equal to it when the router's checkpoint carried the
        # chain state across the death
        for rid, toks in sorted(router.finished.items()):
            if router.stats[rid].get("outcome") != "ok":
                continue
            if args.temperature > 0:
                want = service.stub_sampled_stream(prompts[rid],
                                                   len(toks))
            else:
                want = [service.stub_token(prompts[rid], k)
                        for k in range(len(toks))]
            if list(toks) != want:
                print(f"ORACLE FAIL: rid {rid} tokens diverge "
                      f"(got {list(toks)[:6]}.., want {want[:6]}..)",
                      flush=True)
                ok = False
    for rid, rec in sorted(router.stats.items()):
        if rec.get("outcome") == "ok" \
                and rec["tokens"] != sched.requests[rid].max_new:
            print(f"ORACLE FAIL: rid {rid} served {rec['tokens']} "
                  f"!= budget {sched.requests[rid].max_new}",
                  flush=True)
            ok = False
    unresolved = [rid for rid, rec in router.stats.items()
                  if rec.get("outcome") is None]
    if unresolved:
        print(f"ORACLE FAIL: unresolved requests {unresolved}",
              flush=True)
        ok = False

    tot = report["slo"]["total"]
    print(slolib.format_slo(report["slo"]), flush=True)
    if explain_enabled(args):
        from hpc_patterns_tpu.harness import explain as explainlib
        from hpc_patterns_tpu.harness import reqtrace as reqtracelib

        rtr = reqtracelib.active()
        if rtr is not None:
            snap = rtr.snapshot(router.stats)
            if emit is not None:
                emit(kind="reqtrace", **snap)
            dig = explainlib.digest([snap])
            print(explainlib.format_explain(dig), flush=True)
            if args.explain_out:
                import json

                from pathlib import Path

                Path(args.explain_out).write_text(
                    json.dumps(dig) + "\n")
                print(f"explain digest -> {args.explain_out}",
                      flush=True)
    print(f"plane: served {report['served']}/{report['n']} "
          f"shed={report['shed']} deaths={report['deaths']} "
          f"resumed={report['resumed']} "
          f"migrations={report['migrations']} "
          f"goodput_tok_s={tot['goodput_tok_s']:.1f}", flush=True)
    print("PLANE SUCCESS" if ok else "PLANE FAILURE", flush=True)
    return 0 if ok else 1


def _run_replica(args, rank: int, role: str) -> int:
    from hpc_patterns_tpu.harness import trace as tracelib
    from hpc_patterns_tpu.serving_plane import service

    pages_per_seq = -(-(args.prompt_len + args.budget)
                      // args.page_size)
    pool = args.pool_pages or args.slots * pages_per_seq
    if args.stub:
        adapter = service.StubAdapter(
            slots=args.slots, pool_pages=pool,
            pages_per_seq=pages_per_seq, page_size=args.page_size,
            chunk=args.chunk, role=role,
            sampled=args.temperature > 0)
    else:
        import jax

        from hpc_patterns_tpu.models import (
            TransformerConfig,
            init_params,
        )
        from hpc_patterns_tpu.models.serving import (
            EngineCore,
            bucket_ladder,
        )

        cfg = TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq=max(64, args.prompt_len + args.budget),
            dtype="float32", decode_attn="gather")
        # identical seed on every replica: request_key(sid) must not
        # depend on placement (the plane's routing-invariance contract)
        params = init_params(jax.random.PRNGKey(0), cfg)
        kw = dict(
            slots=args.slots, pool_pages=pool,
            pages_per_seq=pages_per_seq, page_size=args.page_size,
            chunk=args.chunk, temperature=args.temperature,
            top_k=8 if args.temperature > 0 else 0, seed=0)
        if args.fitted is not None:
            # fitted ladder when present; default ladder otherwise
            engine = EngineCore.from_fitted(
                params, cfg, args.fitted, **kw)
            if engine.prompt_buckets is None:
                engine = EngineCore(
                    params, cfg,
                    prompt_buckets=bucket_ladder(args.prompt_len),
                    **kw)
        else:
            engine = EngineCore(
                params, cfg,
                prompt_buckets=bucket_ladder(args.prompt_len), **kw)
        adapter = service.RealAdapter(engine, role=role)
    return service.serve_replica(
        adapter, rank=rank, rdv_dir=args.rdv,
        timeout_s=args.plane_timeout, rec=tracelib.active())


def run(args) -> int:
    pid = int(os.environ.get("HPCPAT_PROCESS_ID") or 0)
    nprocs = int(os.environ.get("HPCPAT_NUM_PROCESSES") or 1)
    if nprocs < 2:
        print("ERROR: plane_app needs a launcher (-np >= 2: one "
              "router + at least one replica); see docs/serving_plane.md")
        return 2
    # one load point for every rank: the router applies fitted
    # placement, real replicas the fitted ladder (cli.load_autofit)
    args.fitted = None
    if args.autofit:
        from hpc_patterns_tpu.harness.cli import load_autofit

        try:
            args.fitted = load_autofit(args.autofit)
        except (OSError, ValueError) as e:
            print(f"ERROR: bad --autofit {args.autofit}: {e}")
            return 2
    os.makedirs(args.rdv, exist_ok=True)
    roles = _roles_for(nprocs - 1, args.roles)
    t0 = time.perf_counter()
    if pid == 0:
        # replica roles are discovered via the hello handshake; the
        # router only needs to know how many replicas to expect
        rc = _run_router(args, nprocs)
    else:
        rc = _run_replica(args, pid, roles[pid - 1])
    print(f"rank {pid} done in {time.perf_counter() - t0:.2f}s rc={rc}",
          flush=True)
    return rc


def main(argv=None) -> int:
    from hpc_patterns_tpu.apps import common

    args = build_parser().parse_args(argv)
    return common.run_instrumented(run, args)


if __name__ == "__main__":
    sys.exit(main())
