"""Flagship transformer tests.

The §4 strategy applied to the model: the distributed configuration
(dp×sp×tp mesh, ring/ulysses attention, Megatron shardings) must produce
the same numbers as the single-device oracle — the analytic-validation
idea, with the oracle being the unsharded model itself.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.models import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
)
from hpc_patterns_tpu.models.train import init_train_state, make_batch

TINY = dict(vocab=64, d_model=32, n_heads=8, n_layers=2, d_ff=64, max_seq=64,
            dtype="float32")


def _tokens(key, b=4, t=16):
    return jax.random.randint(key, (b, t), 0, 64, jnp.int32)


class TestForward:
    def test_shapes_and_dtype(self):
        cfg = TransformerConfig(**TINY)
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = _tokens(jax.random.PRNGKey(1))
        logits = forward(params, tokens, cfg)
        assert logits.shape == (4, 16, cfg.vocab)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        """Changing a future token must not change past logits."""
        cfg = TransformerConfig(**TINY)
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = _tokens(jax.random.PRNGKey(1))
        logits_a = forward(params, tokens, cfg)
        tokens_b = tokens.at[:, 10].set((tokens[:, 10] + 1) % cfg.vocab)
        logits_b = forward(params, tokens_b, cfg)
        np.testing.assert_allclose(
            np.asarray(logits_a[:, :10]), np.asarray(logits_b[:, :10]), atol=1e-5
        )
        assert not np.allclose(np.asarray(logits_a[:, 10:]),
                               np.asarray(logits_b[:, 10:]))

    def test_bad_attention_impl(self):
        with pytest.raises(ValueError, match="attention"):
            TransformerConfig(attention="telepathy")

    # every remat policy must be a pure FLOPs/HBM trade: loss AND grads
    # identical to the no-remat computation
    @pytest.mark.parametrize(
        "policy", ["nothing", "attn", "dots", "dots_attn", "split"]
    )
    def test_remat_matches_no_remat(self, policy):
        cfg = TransformerConfig(**TINY)
        cfg_r = TransformerConfig(**{**TINY, "remat": True,
                                     "remat_policy": policy})
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = _tokens(jax.random.PRNGKey(1))
        a, ga = jax.value_and_grad(lambda p: loss_fn(p, tokens, cfg))(params)
        b, gb = jax.value_and_grad(lambda p: loss_fn(p, tokens, cfg_r))(params)
        np.testing.assert_allclose(float(a), float(b), atol=1e-6)
        for x, y in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       atol=1e-5)

    def test_bad_remat_policy(self):
        with pytest.raises(ValueError, match="remat_policy"):
            TransformerConfig(remat_policy="yolo")

    # the logits-free loss must be numerically identical to the dense
    # path (same f32 logit values through an online logsumexp), grads
    # included — it is a memory transform, not an approximation
    @pytest.mark.parametrize("chunk", [8, 16, 64])
    def test_chunked_loss_matches_dense(self, chunk):
        cfg_d = TransformerConfig(**TINY)
        cfg_c = TransformerConfig(**TINY, loss_chunk=chunk)
        params = init_params(jax.random.PRNGKey(0), cfg_d)
        tokens = _tokens(jax.random.PRNGKey(1))
        want, gw = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg_d)
        )(params)
        got, gc = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg_c)
        )(params)
        np.testing.assert_allclose(float(got), float(want), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(gc), jax.tree.leaves(gw)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_chunked_loss_sharded_matches_local(self, mesh_dp_sp_tp):
        tiny = dict(TINY)
        cfg_local = TransformerConfig(**tiny)
        cfg_mesh = TransformerConfig(**{**tiny, "attention": "ring",
                                        "loss_chunk": 16})
        params = init_params(jax.random.PRNGKey(0), cfg_local)
        tokens = _tokens(jax.random.PRNGKey(1), b=4)
        want = loss_fn(params, tokens, cfg_local)

        from hpc_patterns_tpu.models.sharding import shard_params

        p_sharded = shard_params(params, mesh_dp_sp_tp, cfg_mesh)
        got = jax.jit(
            lambda p, tk: loss_fn(p, tk, cfg_mesh, mesh_dp_sp_tp)
        )(p_sharded, tokens)
        np.testing.assert_allclose(float(got), float(want), rtol=2e-5)

    def test_bad_loss_chunk_rejected(self):
        with pytest.raises(ValueError, match="loss_chunk"):
            TransformerConfig(**TINY, loss_chunk=7)

    def test_unrolled_layers_match_scan(self):
        cfg = TransformerConfig(**TINY)
        cfg_u = TransformerConfig(**{**TINY, "scan_layers": False})
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = _tokens(jax.random.PRNGKey(1))
        # atol 1e-5: unrolling changes XLA's fusion order, which moves
        # f32 logits by ~2e-6
        np.testing.assert_allclose(
            np.asarray(forward(params, tokens, cfg)),
            np.asarray(forward(params, tokens, cfg_u)),
            atol=1e-5,
        )


class TestShardedOracle:
    @pytest.mark.parametrize(
        "attention", ["ring", "ring_flash", "ulysses", "ulysses_flash"]
    )
    def test_sharded_loss_matches_single_device(self, mesh_dp_sp_tp, attention):
        cfg_local = TransformerConfig(**TINY)
        cfg_mesh = TransformerConfig(**{**TINY, "attention": attention})
        params = init_params(jax.random.PRNGKey(0), cfg_local)
        tokens = _tokens(jax.random.PRNGKey(1), b=4, t=16)

        want = loss_fn(params, tokens, cfg_local)

        from hpc_patterns_tpu.models.sharding import shard_params

        p_sharded = shard_params(params, mesh_dp_sp_tp, cfg_mesh)
        # tokens (b, t): full length feeds forward, divisible by sp=2
        got = jax.jit(
            lambda p, tk: loss_fn(p, tk, cfg_mesh, mesh_dp_sp_tp)
        )(p_sharded, tokens)
        np.testing.assert_allclose(float(got), float(want), rtol=2e-5)


class TestTrainStep:
    def test_loss_decreases_single_device(self):
        cfg = TransformerConfig(**TINY)
        params, opt_state = init_train_state(jax.random.PRNGKey(0), cfg)
        step = make_train_step(cfg)
        tokens = _tokens(jax.random.PRNGKey(1), b=8, t=16)
        losses = []
        for _ in range(5):
            loss, params, opt_state = step(params, opt_state, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0], f"no learning: {losses}"

    def test_sharded_step_matches_single_device(self, mesh_dp_sp_tp):
        tiny = {**TINY}
        cfg_local = TransformerConfig(**tiny)
        cfg_mesh = TransformerConfig(**{**tiny, "attention": "ring"})
        tokens = _tokens(jax.random.PRNGKey(1), b=4, t=16)

        p0, s0 = init_train_state(jax.random.PRNGKey(0), cfg_local)
        loss_l, p_l, _ = make_train_step(cfg_local)(p0, s0, tokens)

        p1, s1 = init_train_state(jax.random.PRNGKey(0), cfg_mesh, mesh_dp_sp_tp)
        loss_m, p_m, _ = make_train_step(cfg_mesh, mesh_dp_sp_tp)(p1, s1, tokens)

        np.testing.assert_allclose(float(loss_m), float(loss_l), rtol=2e-5)
        # updated params must agree too (grad + optimizer path)
        la = np.asarray(p_l["layers"]["wqkv"])
        lm = np.asarray(jax.device_get(p_m["layers"]["wqkv"]))
        np.testing.assert_allclose(lm, la, atol=1e-5)

    def test_offload_opt_state_residency(self):
        # placement is backend-agnostic (the compute annotation is
        # TPU-only — full-step equivalence is covered by on-chip runs):
        # every opt leaf must land in pinned_host and keep its structure
        from hpc_patterns_tpu.apps import common
        from hpc_patterns_tpu.models.train import (
            memory_kind_shardings,
            offload_opt_state,
        )

        if not common.supports_memory_kind("pinned_host"):
            pytest.skip("backend has no pinned_host memory kind "
                        "(older XLA:CPU exposes unpinned_host only)")
        cfg = TransformerConfig(**TINY)
        _, opt = init_train_state(jax.random.PRNGKey(0), cfg)
        hosted = offload_opt_state(opt)
        kinds = {x.sharding.memory_kind for x in jax.tree.leaves(hosted)}
        assert kinds == {"pinned_host"}
        assert jax.tree.structure(hosted) == jax.tree.structure(opt)
        back = memory_kind_shardings(hosted, "device")
        assert all(
            s.memory_kind == "device" for s in jax.tree.leaves(back)
        )

    def test_batch_helper_sharded(self, mesh_dp_sp_tp):
        cfg = TransformerConfig(**TINY)
        tokens = make_batch(jax.random.PRNGKey(2), cfg, 4, 16, mesh_dp_sp_tp)
        assert tokens.shape == (4, 16)
        assert tokens.sharding.spec == jax.sharding.PartitionSpec("dp", "sp")


class TestRoPE:
    def test_no_pos_table_and_causal(self):
        cfg = TransformerConfig(**{**TINY, "pos_embed": "rope"})
        params = init_params(jax.random.PRNGKey(0), cfg)
        assert "pos_embed" not in params
        tokens = _tokens(jax.random.PRNGKey(1))
        a = forward(params, tokens, cfg)
        tokens_b = tokens.at[:, 10].set((tokens[:, 10] + 1) % cfg.vocab)
        b = forward(params, tokens_b, cfg)
        np.testing.assert_allclose(np.asarray(a[:, :10]),
                                   np.asarray(b[:, :10]), atol=1e-5)

    def test_relative_shift_invariance(self):
        # rope scores depend on relative distance only: running the same
        # content through apply_rope at positions p and p+s must give
        # identical q.k dot products
        from hpc_patterns_tpu.models.transformer import apply_rope

        cfg = TransformerConfig(**{**TINY, "pos_embed": "rope"})
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 4, 2, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 4, 2, 32))
        pos = jnp.arange(4, dtype=jnp.int32)[None]

        def scores(shift):
            qr = apply_rope(q, pos + shift, cfg)
            kr = apply_rope(k, pos + shift, cfg)
            return jnp.einsum("bthd,bshd->bhts", qr, kr)

        np.testing.assert_allclose(np.asarray(scores(0)),
                                   np.asarray(scores(37)), atol=1e-4)

    @pytest.mark.parametrize("attention", ["ring", "ring_flash", "ulysses"])
    def test_sharded_rope_matches_local(self, mesh_dp_sp_tp, attention):
        # the critical offset property: rope applied on GLOBAL positions
        # must make the sp-sharded model equal the unsharded oracle
        rope = {**TINY, "pos_embed": "rope"}
        cfg_local = TransformerConfig(**rope)
        cfg_mesh = TransformerConfig(**{**rope, "attention": attention})
        params = init_params(jax.random.PRNGKey(0), cfg_local)
        tokens = _tokens(jax.random.PRNGKey(1), b=4, t=16)
        want = loss_fn(params, tokens, cfg_local)

        from hpc_patterns_tpu.models.sharding import shard_params

        p_sharded = shard_params(params, mesh_dp_sp_tp, cfg_mesh)
        got = jax.jit(
            lambda p, tk: loss_fn(p, tk, cfg_mesh, mesh_dp_sp_tp)
        )(p_sharded, tokens)
        np.testing.assert_allclose(float(got), float(want), rtol=2e-5)


class TestGQA:
    def test_kv_heads_equal_heads_is_mha(self):
        base = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                    max_seq=16, dtype="float32")
        cfg_a = TransformerConfig(**base)
        cfg_b = TransformerConfig(**base, n_kv_heads=4)
        params = init_params(jax.random.PRNGKey(0), cfg_a)
        tokens = _tokens(jax.random.PRNGKey(1), b=2, t=16)
        np.testing.assert_allclose(
            np.asarray(forward(params, tokens, cfg_a)),
            np.asarray(forward(params, tokens, cfg_b)),
        )

    @pytest.mark.parametrize("attention", ["full", "flash"])
    def test_gqa_matches_mha_with_expanded_kv(self, attention):
        # oracle: an MHA model whose K/V projection columns are the GQA
        # weights repeated per head group — GQA must equal it exactly
        base = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                    max_seq=16, dtype="float32")
        cfg = TransformerConfig(**base, n_kv_heads=2, attention=attention)
        cfg_mha = TransformerConfig(**base)
        params = init_params(jax.random.PRNGKey(0), cfg)
        L, D, Dh, H, Hkv = 2, 32, 8, 4, 2
        wqkv = np.asarray(params["layers"]["wqkv"])
        assert wqkv.shape == (L, D, D + 2 * Hkv * Dh)
        qw = wqkv[..., :D]
        kw, vw = (
            wqkv[..., D + i * Hkv * Dh:D + (i + 1) * Hkv * Dh]
            .reshape(L, D, Hkv, Dh).repeat(H // Hkv, axis=2)
            .reshape(L, D, H * Dh)
            for i in (0, 1)
        )
        params_mha = {
            **params,
            "layers": {
                **params["layers"],
                "wqkv": jnp.asarray(np.concatenate([qw, kw, vw], axis=-1)),
            },
        }
        tokens = _tokens(jax.random.PRNGKey(1), b=2, t=16)
        np.testing.assert_allclose(
            np.asarray(forward(params, tokens, cfg)),
            np.asarray(forward(params_mha, tokens, cfg_mha)),
            atol=1e-4,
        )

    # n_kv_heads=2 on the dp2/sp2/tp2 mesh: ring/ring_flash run the
    # NARROW path (tp 2 | kv 2); ulysses falls back to expansion
    # ((2/2) % sp 2 != 0). n_kv_heads=4 sends ulysses down the narrow
    # head-scatter path too. Every combination must equal the local
    # unsharded oracle.
    @pytest.mark.parametrize("attention,n_kv", [
        ("ring", 2), ("ring_flash", 2), ("ulysses", 2), ("ulysses", 4),
        ("ulysses_flash", 4),
    ])
    def test_gqa_sharded_matches_local(self, mesh_dp_sp_tp, attention, n_kv):
        tiny = dict(vocab=64, d_model=32, n_heads=8, n_layers=1, d_ff=64,
                    max_seq=16, dtype="float32", n_kv_heads=n_kv)
        cfg_local = TransformerConfig(**tiny)
        cfg_mesh = TransformerConfig(**{**tiny, "attention": attention})
        params = init_params(jax.random.PRNGKey(0), cfg_local)
        tokens = _tokens(jax.random.PRNGKey(1), b=4, t=16)
        want = loss_fn(params, tokens, cfg_local)

        from hpc_patterns_tpu.models.sharding import shard_params

        p_sharded = shard_params(params, mesh_dp_sp_tp, cfg_mesh)
        got = jax.jit(
            lambda p, tk: loss_fn(p, tk, cfg_mesh, mesh_dp_sp_tp)
        )(p_sharded, tokens)
        np.testing.assert_allclose(float(got), float(want), rtol=2e-5)

    def test_bad_kv_heads_rejected(self):
        with pytest.raises(ValueError, match="n_kv_heads"):
            TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=1,
                              d_ff=64, max_seq=16, n_kv_heads=3)

    @pytest.mark.slow  # multi-step train loop; learning also covered by
    def test_gqa_train_learns(self):  # TestTrainStep + sharded oracles
        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                                d_ff=64, max_seq=16, n_kv_heads=2)
        params, opt = init_train_state(jax.random.PRNGKey(0), cfg)
        step = make_train_step(cfg)
        tokens = _tokens(jax.random.PRNGKey(1), b=8, t=16)
        losses = []
        for _ in range(5):
            loss, params, opt = step(params, opt, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses


class TestFusedMLPModel:
    """mlp_impl="fused" (the Pallas fused MLP kernel) must reproduce the
    dense einsum model: forward, loss+grads (incl. under split remat,
    where the kernel sits outside the remat region), and the sharded
    path (shard_map over tp with the row-parallel psum)."""

    def test_forward_matches_dense(self):
        cfg_d = TransformerConfig(**TINY)
        cfg_f = TransformerConfig(**{**TINY, "mlp_impl": "fused"})
        params = init_params(jax.random.PRNGKey(0), cfg_d)
        tokens = _tokens(jax.random.PRNGKey(1))
        want = forward(params, tokens, cfg_d)
        got = forward(params, tokens, cfg_f)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4)

    @pytest.mark.parametrize("remat_over", [
        {},
        {"remat": True, "remat_policy": "split"},
        {"remat": True, "remat_policy": "nothing"},
    ])
    def test_loss_grads_match_dense(self, remat_over):
        cfg_d = TransformerConfig(**TINY)
        cfg_f = TransformerConfig(**{**TINY, "mlp_impl": "fused",
                                     **remat_over})
        params = init_params(jax.random.PRNGKey(0), cfg_d)
        tokens = _tokens(jax.random.PRNGKey(1))
        want_l, want_g = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg_d)
        )(params)
        got_l, got_g = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, cfg_f)
        )(params)
        np.testing.assert_allclose(float(got_l), float(want_l), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(got_g), jax.tree.leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4)

    def test_tp_mesh_matches_dense(self, mesh_dp_sp_tp):
        # the shard_map route: w1/w2 column/row-sharded over tp, psum
        # closing the block — must equal the single-device dense oracle
        cfg_f = TransformerConfig(**{**TINY, "mlp_impl": "fused",
                                     "attention": "ring"})
        cfg_d = TransformerConfig(**TINY)
        params = init_params(jax.random.PRNGKey(0), cfg_d)
        tokens = _tokens(jax.random.PRNGKey(1))
        want = float(loss_fn(params, tokens, cfg_d))
        from hpc_patterns_tpu.models.sharding import shard_params

        p_sh = shard_params(params, mesh_dp_sp_tp, cfg_f)
        got = float(jax.jit(
            lambda p, t: loss_fn(p, t, cfg_f, mesh_dp_sp_tp)
        )(p_sh, tokens))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_fsdp_mesh_matches_dense(self):
        # fused MLP under ZeRO-3: w1/w2 stored fsdp-sharded, gathered by
        # GSPMD at the shard_map boundary — loss equals the dense oracle
        from hpc_patterns_tpu import topology
        from hpc_patterns_tpu.models.sharding import shard_params

        cfg_f = TransformerConfig(**{**TINY, "mlp_impl": "fused",
                                     "fsdp": True})
        cfg_d = TransformerConfig(**TINY)
        mesh = topology.make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
        params = init_params(jax.random.PRNGKey(0), cfg_d)
        tokens = _tokens(jax.random.PRNGKey(1))
        want = float(loss_fn(params, tokens, cfg_d))
        p_sh = shard_params(params, mesh, cfg_f)
        got = float(jax.jit(
            lambda p, t: loss_fn(p, t, cfg_f, mesh)
        )(p_sh, tokens))
        np.testing.assert_allclose(got, want, rtol=1e-5)
