"""Device-side zero-copy proofs: buffer aliasing ON the accelerator.

The host-side bridges (zero_copy.py) prove same-address-space sharing
between runtimes; this module proves the *device-context* leg the
reference demonstrates with OMP and SYCL kernels sharing one Level-Zero
context (interop_omp_ze_sycl.cpp:81-101): XLA writing a computation's
output INTO an existing device buffer with no copy —

- :func:`donation_alias_proof` — plain ``jit`` with ``donate_argnums``:
  the output reuses the input's HBM buffer;
- :func:`pallas_alias_proof` — a Pallas kernel with
  ``input_output_aliases={0: 0}``: the kernel's output ref IS the
  input's buffer (the in-place kernel form the reference's
  ``is_device_ptr`` OMP kernel takes, :95-99).

Proof forms, strongest available per backend:

1. **pointer identity** (``unsafe_buffer_pointer``) where the PJRT
   backend exposes raw device pointers (CPU backend; most GPU/TPU
   runtimes);
2. **the compiled executable's aliasing contract** otherwise (e.g. the
   axon TPU transport, which refuses raw pointers):
   ``memory_analysis().alias_size_in_bytes`` covering the entire
   output, the ``input_output_alias`` entry in the compiled HLO, and
   the donated input being invalidated by the run. This is the
   contract XLA *enforces* when it executes — a compiler guarantee,
   not a runtime sample.

Every proof also validates values (the reference's assert style).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def buffer_pointer(arr) -> int | None:
    """Raw device-buffer address, or None where the backend refuses
    (axon TPU raises; other backends may too)."""
    try:
        return int(arr.addressable_shards[0].data.unsafe_buffer_pointer())
    except Exception:  # noqa: BLE001 — backend-specific refusal
        return None


def _run_aliased(f, x):
    """Compile, extract the aliasing contract, run with donation, and
    collect every form of evidence available on this backend."""
    compiled = f.lower(x).compile()
    ma = compiled.memory_analysis()
    contract = dict(
        alias_bytes=int(ma.alias_size_in_bytes),
        output_bytes=int(ma.output_size_in_bytes),
        hlo_alias="input_output_alias={" in compiled.as_text(),
    )
    ptr_in = buffer_pointer(x)
    out = jax.block_until_ready(f(x))
    ptr_out = buffer_pointer(out)
    evidence = dict(
        contract,
        contract_ok=(
            contract["hlo_alias"]
            and contract["alias_bytes"] == contract["output_bytes"] > 0
        ),
        pointer_ok=(
            None if ptr_in is None or ptr_out is None else ptr_in == ptr_out
        ),
        input_invalidated=bool(x.is_deleted()),
    )
    return out, evidence


def donation_alias_proof(n: int = 1 << 14):
    """jit + donation writing in place: returns (ok, evidence dict).

    ok = values correct AND the donated input was consumed AND the
    strongest available aliasing evidence holds (pointer identity when
    readable, else the compiled aliasing contract).
    """
    x = jax.block_until_ready(jnp.full((n,), 2.0, jnp.float32))
    f = jax.jit(lambda v: v * 3 + 1, donate_argnums=0)
    out, ev = _run_aliased(f, x)
    values_ok = bool(jnp.all(out == 7.0).item())
    alias_ok = ev["pointer_ok"] if ev["pointer_ok"] is not None else ev["contract_ok"]
    ev["values_ok"] = values_ok
    return bool(values_ok and alias_ok and ev["input_invalidated"]), ev


def pallas_alias_proof(rows: int = 8, cols: int = 128):
    """Pallas ``input_output_aliases`` + donation: the kernel's output
    lands in the input's HBM buffer. Returns (ok, evidence dict).

    On backends without native Pallas (CPU tests) the kernel runs in
    interpret mode; the jit-level donation and the compiled aliasing
    contract are still real, which is what is being proven.
    """
    from jax.experimental import pallas as pl

    interpret = jax.default_backend() not in ("tpu", "gpu")

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0 + 1.0

    x = jax.block_until_ready(
        jnp.arange(rows * cols, dtype=jnp.float32).reshape(rows, cols)
    )
    want = np.asarray(x) * 2.0 + 1.0
    f = jax.jit(
        lambda v: pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(v.shape, v.dtype),
            input_output_aliases={0: 0},
            interpret=interpret,
        )(v),
        donate_argnums=0,
    )
    out, ev = _run_aliased(f, x)
    values_ok = bool(np.allclose(np.asarray(out), want))
    alias_ok = ev["pointer_ok"] if ev["pointer_ok"] is not None else ev["contract_ok"]
    ev["values_ok"] = values_ok
    ev["interpret"] = interpret
    return bool(values_ok and alias_ok and ev["input_invalidated"]), ev
