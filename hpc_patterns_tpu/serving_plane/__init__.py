"""Disaggregated multi-replica serving plane (round 10).

The ContinuousBatcher is a single-process engine; this package is the
serving *system* above it — the scale jump from one engine to N:

- ``router.py``   — the in-process plane: a front-end router admitting
  an open-loop stream across N :class:`~hpc_patterns_tpu.models.
  serving.EngineCore` replicas with a pluggable placement policy
  (least-loaded by free pages / round-robin / prefill-decode
  role-aware), per-replica queue-depth + goodput accounting through
  the metrics/SLO layer, and prefill/decode DISAGGREGATION: KV pages
  migrate from prefill-role to decode-role replicas with the transfer
  dispatched BEFORE the decode chunk, so it hides behind compute
  exactly like round-6 overlapped admission (the serving analog of
  the reference's hide-traffic-behind-compute discipline).
- ``migration.py`` — the KV-handoff transfer: device-to-device
  dispatch for in-process replicas on distinct devices (the ICI
  analog), plus the wire codec the cross-process path shares.
- ``service.py``  — the cross-process plane (import-light, jax-free):
  a socket replica server + router client driven by
  ``apps/launch.py`` (one replica per launched process — the DCN
  analog), with replica-death detection and resume-on-survivor
  (observed tokens AND, in sampled mode, the per-row key state the
  round replies checkpoint).
- ``autoscaler.py`` — the ELASTIC plane (round 14): a pure
  SLO-feedback controller (hysteresis, cooldown, min/max clamps)
  driving warm replica spin-up (params paged from the residency
  manager's host tier, measured as ``plane.spinup`` windows),
  drain-by-migration scale-down, and checkpoint-resume death
  recovery over the router (docs/serving_plane.md "Elastic plane").

Import discipline: this ``__init__`` stays lazy so launcher children
can ``import hpc_patterns_tpu.serving_plane.service`` without paying
(or even having) jax. See docs/serving_plane.md.
"""

from __future__ import annotations

_LAZY = {
    "Replica": "hpc_patterns_tpu.serving_plane.router",
    "ServingPlane": "hpc_patterns_tpu.serving_plane.router",
    "PLACEMENT_POLICIES": "hpc_patterns_tpu.serving_plane.router",
    "migrate_pages": "hpc_patterns_tpu.serving_plane.migration",
    "Autoscaler": "hpc_patterns_tpu.serving_plane.autoscaler",
    "AutoscalerPolicy": "hpc_patterns_tpu.serving_plane.autoscaler",
    "ElasticServingPlane": "hpc_patterns_tpu.serving_plane.autoscaler",
    "WarmParamPool": "hpc_patterns_tpu.serving_plane.autoscaler",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(mod), name)
