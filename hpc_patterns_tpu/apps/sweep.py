"""Concurrency sweep harness — the rebuild of ``concurency/run.sh`` (C4).

The reference's harness (run.sh:4-15) sweeps the command matrix
{C C, C M2D, C D2M, M2D D2M} × {out_of_order, in_order}, re-runs each
passing configuration with ``--enable_profiling``, tees everything to
``run.log``, and greps a SUCCESS/FAILURE summary (:17-18).

Same behavior here, with the structured upgrades of SURVEY.md §5: the log
is JSONL (machine-readable) *and* the grep-able stdout contract is kept;
modes default to the TPU-meaningful pair (``async``, ``threads``).
"""

from __future__ import annotations

import sys

from hpc_patterns_tpu.apps import common, concurrency_app
from hpc_patterns_tpu.harness import RunLog
from hpc_patterns_tpu.harness.cli import base_parser

# run.sh:4's command matrix
DEFAULT_MATRIX = [["C", "C"], ["C", "M2D"], ["C", "D2M"], ["M2D", "D2M"]]


def build_parser():
    p = base_parser(__doc__.splitlines()[0])
    p.add_argument("--modes", nargs="*", default=None,
                   help="dispatch modes to sweep (run.sh sweeps out_of_order, "
                        "in_order); default: async+threads on a multi-device "
                        "backend, async alone on a single TPU (threads-style "
                        "dispatch cannot overlap on one sequential core)")
    p.add_argument("--engine", default="auto",
                   choices=["auto", "dispatch", "onchip"],
                   help="passed through to the concurrency app")
    p.add_argument("--copy-elements", type=int, default=-1)
    p.add_argument("--tripcount", type=int, default=-1)
    p.add_argument("--rule", default="sycl", choices=["sycl", "omp"])
    p.add_argument("--profile-on-success", action="store_true",
                   help="re-run passing configs under the profiler (run.sh:10-12)")
    return p


def run(args) -> int:
    log = RunLog(args.log, truncate=not args.log_append)  # harness owns the log
    app_parser = concurrency_app.build_parser()
    modes = args.modes
    if modes is None:
        import jax

        single_tpu = (jax.default_backend() == "tpu"
                      and len(jax.devices()) == 1)
        modes = ["async"] if single_tpu else ["async", "threads"]
    for commands in DEFAULT_MATRIX:
        for mode in modes:
            argv = [mode, *commands,
                    "--engine", args.engine,
                    "--copy-elements", str(args.copy_elements),
                    "--tripcount", str(args.tripcount),
                    "--rule", args.rule,
                    "--repetitions", str(args.repetitions),
                    "--warmup", str(args.warmup)]
            if args.backend:
                argv += ["--backend", args.backend]
            if args.log:
                argv += ["--log", args.log, "--log-append"]  # share our log
            log.print(f"=== {mode} {' '.join(commands)} ===")
            code = concurrency_app.run(app_parser.parse_args(argv))
            log.emit(kind="result", name=f"sweep[{mode}:{'+'.join(commands)}]",
                     success=code == 0, mode=mode, commands=commands)
            if code == 0 and args.profile_on_success:
                log.print(f"=== {mode} {' '.join(commands)} (profiling) ===")
                concurrency_app.run(app_parser.parse_args(argv + ["--enable_profiling"]))
    ok, bad = log.summary()
    return 0 if bad == 0 else 1


def main(argv=None) -> int:
    # one shared registry for the whole sweep: sub-apps run in-process
    # via concurrency_app.run, so their spans/gauges accumulate into
    # the harness's single closing kind=metrics snapshot
    return common.run_instrumented(run, build_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
