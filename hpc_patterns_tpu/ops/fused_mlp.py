"""Fused transformer MLP (matmul → gelu → matmul) as Pallas TPU kernels.

The round-3 step profile put 57.4% of the headline training step in
matmul fusions running at ~50% MXU utilization while the same shapes
hit 82-97% isolated (benchmarks/RESULTS.md) — the MLP block is most of
that time. This kernel applies the framework's own-the-hot-loop rule
(docs/ARCHITECTURE.md; reference analog concurency/sycl_con.cpp:26-33)
to the d_ff block:

- **forward**: grid (N/bt, F/bf), token-block outer. For one token
  block, the F axis streams through VMEM: a = x·W1[:, f] (f32),
  g = gelu(a), acc += g·W2[f, :] — the (N, F) activation NEVER exists
  in HBM (XLA materializes it between its two matmul fusions: a 128 MB
  write + read per layer at the headline shape). HBM traffic per token
  block is x once + both weight panels once.
- **backward**: one fused pass, grid (F/bf, N/bt), f outer. Per step
  (5 block matmuls): recompute a = x·W1f and g, dh = dy·W2fᵀ,
  da = dh ⊙ gelu'(a), dW2f += gᵀ·dy, dW1f += xᵀ·da, and the partial
  dx contribution da·W1fᵀ goes to an (F/bf, N, D) slab summed outside
  (the flash fused backward's partial-dQ pattern,
  ops/flash_attention.py). dW accumulators live in f32 VMEM scratch
  and write once per f panel.
- custom_vjp residuals: (x, w1, w2) only — the g recompute is 1 of the
  5 backward matmuls, the price of never storing (N, F).

gelu is the tanh approximation (jax.nn.gelu's default) with an
analytic derivative, so the kernel matches the einsum path's math.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hpc_patterns_tpu.ops.tiling import (
    default_interpret,
    fit_block_divisor as _fit_block,
    tpu_compiler_params as _compiler_params,
)

_SQRT_2_OVER_PI = 0.7978845608028654
_GELU_C = 0.044715


def _gelu(a):
    """tanh-approx gelu in f32 (== jax.nn.gelu(approximate=True))."""
    u = _SQRT_2_OVER_PI * (a + _GELU_C * a * a * a)
    return 0.5 * a * (1.0 + jnp.tanh(u))


def _dgelu(a):
    """d/da of the tanh-approx gelu, analytic."""
    u = _SQRT_2_OVER_PI * (a + _GELU_C * a * a * a)
    t = jnp.tanh(u)
    du = _SQRT_2_OVER_PI * (1.0 + 3.0 * _GELU_C * a * a)
    return 0.5 * (1.0 + t) + 0.5 * a * (1.0 - t * t) * du


def _fwd_kernel(x_ref, w1_ref, w2_ref, o_ref, acc_ref, *, a_ref=None):
    # grid (n_t, n_f), f inner: x block constant across f (fetch
    # elided); acc carries the growing y in f32 scratch. ``a_ref``:
    # optionally also emit the pre-gelu activation (the residual the
    # save-a backward consumes — matmul-count parity with XLA's
    # dots-saved remat backward)
    fi = pl.program_id(1)
    n_f = pl.num_programs(1)
    a = jnp.dot(x_ref[...], w1_ref[...],
                preferred_element_type=jnp.float32)
    if a_ref is not None:
        a_ref[...] = a.astype(a_ref.dtype)
    g = _gelu(a).astype(x_ref.dtype)
    part = jnp.dot(g, w2_ref[...], preferred_element_type=jnp.float32)

    @pl.when(fi == 0)
    def _():
        acc_ref[...] = part

    @pl.when(fi > 0)
    def _():
        acc_ref[...] += part

    @pl.when(fi == n_f - 1)
    def _():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _bwd_kernel(x_ref, dy_ref, w1_ref, w2_ref, dxs_ref, dw1_ref, dw2_ref,
                dw1_acc, dw2_acc):
    # grid (n_f, n_t), t inner: weight panels constant across t; dW
    # accumulates across the token stream in f32 scratch and writes
    # once per f panel
    ti = pl.program_id(1)
    n_t = pl.num_programs(1)
    x = x_ref[...]
    dy = dy_ref[...]
    w1 = w1_ref[...]
    a = jnp.dot(x, w1, preferred_element_type=jnp.float32)
    g = _gelu(a).astype(x.dtype)
    # dh = dy · W2ᵀ  (contract the model dim)
    dh = lax.dot_general(dy, w2_ref[...], (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    da = (dh * _dgelu(a)).astype(x.dtype)

    # dW2f += gᵀ · dy ; dW1f += xᵀ · da  (contract the token dim)
    dw2_part = lax.dot_general(g, dy, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    dw1_part = lax.dot_general(x, da, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)

    @pl.when(ti == 0)
    def _():
        dw2_acc[...] = dw2_part
        dw1_acc[...] = dw1_part

    @pl.when(ti > 0)
    def _():
        dw2_acc[...] += dw2_part
        dw1_acc[...] += dw1_part

    # partial dx for this f panel: da · W1fᵀ (contract the d_ff dim)
    dxs_ref[...] = lax.dot_general(
        da, w1, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dxs_ref.dtype)

    @pl.when(ti == n_t - 1)
    def _():
        dw1_ref[...] = dw1_acc[...]
        dw2_ref[...] = dw2_acc[...]


def _resolve(N, D, F, block_t, block_f, interpret):
    # block fitting + interpret default live in ops.tiling, shared with
    # the flash and fused-collective kernels
    block_t = _fit_block(N, block_t)
    block_f = _fit_block(F, block_f)
    if interpret is None:
        interpret = default_interpret()
    return block_t, block_f, interpret


def _fwd_kernel_save_a(x_ref, w1_ref, w2_ref, o_ref, a_ref, acc_ref):
    _fwd_kernel(x_ref, w1_ref, w2_ref, o_ref, acc_ref, a_ref=a_ref)


def _forward(x2, w1, w2, block_t, block_f, interpret, save_a=False):
    N, D = x2.shape
    F = w1.shape[1]
    bt, bf, interpret = _resolve(N, D, F, block_t, block_f, interpret)
    out_specs = pl.BlockSpec((bt, D), lambda t, f: (t, 0),
                             memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((N, D), x2.dtype)
    if save_a:
        out_specs = [out_specs,
                     pl.BlockSpec((bt, bf), lambda t, f: (t, f),
                                  memory_space=pltpu.VMEM)]
        out_shape = [out_shape,
                     jax.ShapeDtypeStruct((N, F), x2.dtype)]
    return pl.pallas_call(
        _fwd_kernel_save_a if save_a else _fwd_kernel,
        grid=(N // bt, F // bf),
        in_specs=[
            pl.BlockSpec((bt, D), lambda t, f: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((D, bf), lambda t, f: (0, f),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bf, D), lambda t, f: (f, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((bt, D), jnp.float32)],
        # big token blocks (f32 acc + double-buffered panels) can pass
        # Mosaic's 16 MB default scoped limit; physical VMEM is larger
        compiler_params=_compiler_params(
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=interpret,
    )(x2, w1, w2)


def _backward(x2, w1, w2, dy2, block_t, block_f, interpret):
    N, D = x2.shape
    F = w1.shape[1]
    bt, bf, interpret = _resolve(N, D, F, block_t, block_f, interpret)
    n_f = F // bf
    dx_slab, dw1, dw2 = pl.pallas_call(
        _bwd_kernel,
        grid=(n_f, N // bt),
        in_specs=[
            pl.BlockSpec((bt, D), lambda f, t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bt, D), lambda f, t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((D, bf), lambda f, t: (0, f),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bf, D), lambda f, t: (f, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((None, bt, D), lambda f, t: (f, t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((D, bf), lambda f, t: (0, f),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bf, D), lambda f, t: (f, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_f, N, D), x2.dtype),
            jax.ShapeDtypeStruct((D, F), jnp.float32),
            jax.ShapeDtypeStruct((F, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((D, bf), jnp.float32),
            pltpu.VMEM((bf, D), jnp.float32),
        ],
        # block set + f32 dW accumulators legitimately need ~18-24 MB
        # of VMEM at the flagship shape — above Mosaic's 16 MB default
        # scoped limit, well under the physical budget
        compiler_params=_compiler_params(
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=interpret,
    )(x2, dy2, w1, w2)
    # the partial-dx slab sums outside the kernel (flash's dQ pattern);
    # f32 accumulation of the bf16 partials
    dx2 = jnp.sum(dx_slab.astype(jnp.float32), axis=0).astype(x2.dtype)
    return dx2, dw1.astype(w1.dtype), dw2.astype(w2.dtype)


def _backward_xla(x2, w1, w2, dy2):
    """Reference backward in plain XLA ops (recompute a and g, then the
    same 5 matmuls the kernel fuses). Diagnostic path — selected with
    HPCPAT_FUSED_MLP_BWD=xla — to separate the forward kernel's in-situ
    effect from the backward kernel's."""
    a = jnp.dot(x2, w1, preferred_element_type=jnp.float32)
    g = _gelu(a).astype(x2.dtype)
    dh = lax.dot_general(dy2, w2, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    da = (dh * _dgelu(a)).astype(x2.dtype)
    dw2 = lax.dot_general(g, dy2, (((0,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    dw1 = lax.dot_general(x2, da, (((0,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    dx2 = lax.dot_general(da, w1, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    return (dx2.astype(x2.dtype), dw1.astype(w1.dtype),
            dw2.astype(w2.dtype))


def _bwd_mode() -> str:
    """Backward strategy (env knob, measured in benchmarks/RESULTS.md):

    - "kernel": the one-pass fused backward kernel (5 matmuls,
      partial-dx slab) — residuals (x, w1, w2) only, lowest memory;
    - "xla": XLA ops recomputing a from x — same residuals, and XLA
      fuses/schedules the 5 matmuls itself;
    - "xla_a": the forward kernel ALSO writes the pre-gelu activation
      and the backward starts from it (4 matmuls — parity with the
      dots-saved dense remat backward) at (N, F) extra residual memory.
    """
    return os.environ.get("HPCPAT_FUSED_MLP_BWD", "kernel")


def _backward_xla_from_a(x2, a, w1, w2, dy2):
    """Save-a backward: gelu recomputed elementwise from the saved
    pre-activation; 4 matmuls, no recompute matmul."""
    a = a.astype(jnp.float32)
    g = _gelu(a).astype(x2.dtype)
    dh = lax.dot_general(dy2, w2, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    da = (dh * _dgelu(a)).astype(x2.dtype)
    dw2 = lax.dot_general(g, dy2, (((0,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    dw1 = lax.dot_general(x2, da, (((0,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    dx2 = lax.dot_general(da, w1, (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)
    return (dx2.astype(x2.dtype), dw1.astype(w1.dtype),
            dw2.astype(w2.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_mlp(x2, w1, w2, block_t, block_f, interpret):
    return _forward(x2, w1, w2, block_t, block_f, interpret)


def _fused_mlp_fwd(x2, w1, w2, block_t, block_f, interpret):
    if _bwd_mode() == "xla_a":
        y, a = _forward(x2, w1, w2, block_t, block_f, interpret,
                        save_a=True)
        return y, (x2, a, w1, w2)
    return (_forward(x2, w1, w2, block_t, block_f, interpret),
            (x2, None, w1, w2))


def _fused_mlp_bwd(block_t, block_f, interpret, res, dy2):
    x2, a, w1, w2 = res
    mode = _bwd_mode()
    if mode == "xla_a":
        return _backward_xla_from_a(x2, a, w1, w2, dy2.astype(x2.dtype))
    if mode == "xla":
        return _backward_xla(x2, w1, w2, dy2.astype(x2.dtype))
    return _backward(x2, w1, w2, dy2.astype(x2.dtype), block_t, block_f,
                     interpret)


_fused_mlp.defvjp(_fused_mlp_fwd, _fused_mlp_bwd)


def fused_mlp(x, w1, w2, *, block_t: int = 512, block_f: int = 512,
              interpret: bool | None = None):
    """gelu MLP ``x @ w1 -> gelu -> @ w2`` with the (tokens, d_ff)
    activation never materialized in HBM.

    ``x``: (..., D) in the compute dtype (leading dims flatten to the
    token axis); ``w1``: (D, F); ``w2``: (F, D), both already cast to
    the compute dtype. Block sizes auto-fit to the largest divisor of
    the token count / F at or below the request (off-size shapes run
    at a smaller tile, never error). Differentiable (one fused
    backward pass, see module docstring); numerically the einsum
    path's math with the gelu evaluated in f32.
    """
    lead = x.shape[:-1]
    D = x.shape[-1]
    if w1.shape[0] != D or w2.shape[1] != D or w1.shape[1] != w2.shape[0]:
        raise ValueError(
            f"shape mismatch: x (..., {D}), w1 {w1.shape}, w2 {w2.shape}"
        )
    x2 = x.reshape(-1, D)
    y2 = _fused_mlp(x2, w1, w2, block_t, block_f, interpret)
    return y2.reshape(*lead, D)
