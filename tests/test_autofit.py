"""Tier-1 pins for autofit (harness/autofit.py) — observability
becomes control.

Four claims, each on hand-built fixtures with KNOWN optima (the fitters
are pure functions of the records, so the tests need no device):

- determinism: the same records fit to bit-identical config bytes, and
  the CLI round-trips them through ``--emit`` / ``load_fitted``;
- each section fitter lands on the fixture's known optimum (ladder
  rungs at the observed lengths, priority policy when two classes
  paged, inverse-pressure placement weights, hysteresis bands that
  never flap on the recorded trajectory);
- the offline threshold replay holds steady on a boundary trajectory
  (the flap the hysteresis band exists to prevent);
- the A/B smoke: ``bench_serving.run_fitted`` fits a config from its
  own recording leg and the fitted engine must not lose to the default
  (the strict expected-padding win is asserted inside run_fitted
  itself, before any wall clock).
"""

import json
import sys
from pathlib import Path
from types import SimpleNamespace

import pytest

from hpc_patterns_tpu.harness import autofit

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


# ---------------------------------------------------------------------------
# fixtures: hand-built record streams with known optima


def admit(prompt_len, padded_len=None, priority=0, seq_id=0):
    return {"kind": "serve_admit", "seq_id": seq_id, "slot": 0,
            "prompt_len": prompt_len,
            "padded_len": padded_len or prompt_len,
            "priority": priority}


def trace_rec(events):
    """One ``kind=trace`` record; events as the recorder's 7-tuples
    (ph, cat, name, ts, tid, dur, args) — JSON round-trips them as
    lists, which is what read_records hands the fitters."""
    return {"kind": "trace", "events": [list(e) for e in events]}


def metrics_rec(gauges):
    return {"kind": "metrics",
            "gauges": {k: {"last": v, "min": v, "max": v, "n": 2}
                       for k, v in gauges.items()}}


def attain(round_, replicas, queued, attained, judged, active=0):
    return {"kind": "plane_attainment", "round": round_,
            "replicas": replicas, "queued": queued, "active": active,
            "attained_round": attained, "judged_round": judged}


def ladder_records():
    # 60% of the mass at 40, which the shape-blind default ladder
    # (16, 32, 64) pads to 64: the known optimum puts a rung AT 40
    lengths = [16] * 4 + [40] * 12 + [64] * 4
    return [admit(t, seq_id=i) for i, t in enumerate(lengths)]


def paging_records(*, overlap=True):
    # two priority classes paged; 8 pulls across 4 seqs (2.0/seq, past
    # the 1.5 thrash bar); pull windows either fully hidden under the
    # chunk union (overlap=True) or fully exposed after it
    recs = [admit(16, priority=p % 2, seq_id=p) for p in range(4)]
    recs += [{"kind": "serve_swap_out", "seq_id": s} for s in range(4)]
    recs += [{"kind": "serve_prefetch", "seq_id": s % 4}
             for s in range(8)]
    chunks = [("X", "serve", "serve.chunk", 10.0 * i, 0, 10.0, None)
              for i in range(4)]
    t0 = 5.0 if overlap else 100.0
    pulls = [("X", "mem", "mem.prefetch", t0 + 2.0 * i, 0, 4.0, None)
             for i in range(3)]  # peak concurrency 2
    recs.append(trace_rec(chunks + pulls))
    recs.append(metrics_rec({"mem.hbm_pages": 6.0,
                             "mem.host_pages": 2.0}))
    return recs


# ---------------------------------------------------------------------------


class TestDeterminism:
    def test_same_records_fit_to_identical_bytes(self):
        recs = (ladder_records() + paging_records()
                + [attain(i, 2, 2, 4, 4) for i in range(8)])
        a = autofit.dumps_config(autofit.fit(recs))
        b = autofit.dumps_config(autofit.fit(recs))
        assert a == b
        assert json.loads(a)["kind"] == autofit.FITTED_KIND

    def test_cli_emit_is_deterministic_and_loadable(self, tmp_path,
                                                    capsys):
        log = tmp_path / "run.jsonl"
        log.write_text("".join(json.dumps(r) + "\n"
                               for r in ladder_records()))
        out1, out2 = tmp_path / "a.json", tmp_path / "b.json"
        assert autofit.main([str(log), "--emit", str(out1)]) == 0
        assert autofit.main([str(log), "--emit", str(out2)]) == 0
        capsys.readouterr()
        assert out1.read_bytes() == out2.read_bytes()
        fitted = autofit.load_fitted(out1)
        assert fitted["version"] == autofit.FITTED_VERSION
        assert fitted["ladder"] is not None

    def test_unreadable_input_exits_2(self, tmp_path, capsys):
        assert autofit.main([str(tmp_path / "nope.jsonl")]) == 2
        capsys.readouterr()

    def test_load_rejects_wrong_kind_and_version(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"kind": "not_a_config", "version": 1}))
        with pytest.raises(ValueError, match="kind"):
            autofit.load_fitted(p)
        p.write_text(json.dumps({"kind": autofit.FITTED_KIND,
                                 "version": 999}))
        with pytest.raises(ValueError, match="version"):
            autofit.load_fitted(p)

    def test_empty_input_fits_all_null_sections(self):
        fitted = autofit.fit([])
        for section in ("ladder", "residency", "placement",
                        "autoscaler"):
            assert fitted[section] is None
        # an all-null config is still a valid, loadable config
        autofit.validate_fitted(json.loads(autofit.dumps_config(fitted)))


class TestLadderFit:
    def test_rung_lands_on_the_dominant_length(self):
        section = autofit.fit_ladder(ladder_records())
        assert 40 in section["buckets"]
        # the fit can only remove padding: the default is feasible
        assert (section["expected_padding"]
                <= section["default_expected_padding"])
        # and on THIS mixture it strictly wins (60% of mass padded
        # 40 -> 64 by the default)
        assert (section["expected_padding"]
                < section["default_expected_padding"])

    def test_no_admits_fits_nothing(self):
        assert autofit.fit_ladder(paging_records()[4:]) is None

    def test_ladder_from_clamps_to_max_seq(self):
        fitted = autofit.fit(ladder_records())
        full = autofit.ladder_from(fitted)
        assert full is not None and max(full) == 64
        clamped = autofit.ladder_from(fitted, max_seq=40)
        assert max(clamped) == 40
        assert autofit.ladder_from({"ladder": None}) is None


class TestResidencyFit:
    def test_never_paged_fits_nothing(self):
        assert autofit.fit_residency(ladder_records()) is None

    def test_two_classes_and_thrash_raise_the_floor(self):
        section = autofit.fit_residency(paging_records())
        assert section["policy"] == "priority"
        # 8 pulls / 4 seqs = 2.0 > 1.5: the anti-thrash floor
        assert section["min_resident_rounds"] == 2
        assert section["observed"]["pulls_per_seq"] == 2.0

    def test_hidden_pulls_keep_observed_depth(self):
        section = autofit.fit_residency(paging_records(overlap=True))
        # the three staggered 4s pulls peak at 2 in flight, all hidden
        # under the chunk union
        assert section["prefetch_depth"] == 2
        assert section["observed"]["prefetch_overlap_frac"] == 1.0

    def test_exposed_pulls_cap_depth_at_one(self):
        section = autofit.fit_residency(paging_records(overlap=False))
        assert section["prefetch_depth"] == 1
        assert section["observed"]["prefetch_overlap_frac"] == 0.0


class TestPlacementFit:
    def test_uniform_queues_pick_round_robin(self):
        recs = [metrics_rec({"plane.a.queue_depth": 2.0,
                             "plane.b.queue_depth": 2.0})]
        section = autofit.fit_placement(recs)
        assert section["policy"] == "round_robin"
        assert section["weights"]["a"] == section["weights"]["b"]

    def test_skewed_queues_weight_the_idle_replica(self):
        recs = [metrics_rec({"plane.a.queue_depth": 0.0,
                             "plane.b.queue_depth": 8.0})]
        section = autofit.fit_placement(recs)
        assert section["policy"] == "weighted"
        assert section["weights"]["a"] > section["weights"]["b"]
        assert abs(sum(section["weights"].values()) - 1.0) < 1e-6
        assert section["source"] == "queue_depth_gauges"

    def test_busy_rollup_fallback_weights_the_idle_rank(self):
        recs = [{"kind": "trace_merged",
                 "busy": {"0": {"busy_frac": 0.9},
                          "1": {"busy_frac": 0.3}}}]
        section = autofit.fit_placement(recs)
        assert section["source"] == "busy_rollup"
        assert section["weights"]["1"] > section["weights"]["0"]

    def test_no_signal_fits_nothing(self):
        assert autofit.fit_placement(ladder_records()) is None


class TestAutoscalerFit:
    def test_short_trajectory_fits_nothing(self):
        recs = [attain(i, 2, 2, 4, 4) for i in range(3)]
        assert autofit.fit_autoscaler(recs) is None

    def test_fitted_bands_never_flap_on_the_recorded_trajectory(self):
        # a steady boundary load: queued-per-replica sits at 2.0 every
        # round with attainment at 1.0 — the trajectory the hysteresis
        # band exists for. The fitted candidate must replay with zero
        # flaps, and re-replaying it must reproduce the fit's verdict.
        recs = [attain(i, 2, 4, 4, 4) for i in range(12)]
        section = autofit.fit_autoscaler(recs)
        assert section["replay"]["flaps"] == 0
        from hpc_patterns_tpu.serving_plane.autoscaler import (
            AutoscalerPolicy,
        )
        pol = AutoscalerPolicy(
            min_replicas=section["min_replicas"],
            max_replicas=section["max_replicas"],
            up_queue=section["up_queue"],
            down_queue=section["down_queue"],
            up_attainment=section["up_attainment"],
            down_attainment=section["down_attainment"],
            cooldown_rounds=section["cooldown_rounds"],
            window=section["window"])
        decisions = autofit.replay(autofit._trajectory(recs), pol)
        assert autofit.flap_count(decisions) == 0
        assert len(decisions) == 12

    def test_flap_count_counts_direction_reversals(self):
        def d(*actions):
            return [SimpleNamespace(action=a) for a in actions]

        assert autofit.flap_count(d("hold", "hold")) == 0
        assert autofit.flap_count(d("up", "hold", "up")) == 0
        assert autofit.flap_count(d("up", "down", "up")) == 2
        assert autofit.flap_count(d("up", "hold", "down")) == 1


class TestConsumers:
    def test_autoscaler_policy_from_fitted_applies_bands(self):
        from hpc_patterns_tpu.serving_plane.autoscaler import (
            AutoscalerPolicy,
        )

        recs = [attain(i, 2, 4, 4, 4) for i in range(12)]
        fitted = autofit.fit(recs)
        pol = AutoscalerPolicy.from_fitted(fitted, max_replicas=8)
        section = fitted["autoscaler"]
        assert pol.up_queue == section["up_queue"]
        assert pol.window == section["window"]
        # operator overrides win over the fit
        assert pol.max_replicas == 8

    def test_residency_manager_from_fitted_applies_depth(self):
        from hpc_patterns_tpu.memory import ResidencyManager

        fitted = autofit.fit(paging_records(overlap=True))
        mgr = ResidencyManager.from_fitted(fitted, host_blocks=4)
        assert mgr.prefetch_depth == 2


def stall_entry(*, queued_to=1.0, wait=(1.1, 2.9), t_finish=3.0,
                token_ts=(1.0, 1.1, 3.0), priority=0):
    """One finished request whose inter-token tail is dominated by a
    ``prefetch_wait`` span: token stamps at 1.0/1.1/then the finish,
    with the wait segment filling (most of) the long gap."""
    segs = [["queued", 0.0, queued_to, None],
            ["decode", queued_to, wait[0], None],
            ["prefetch_wait", wait[0], wait[1], None],
            ["decode", wait[1], t_finish, None]]
    return {"priority": priority, "t_submit": 0.0,
            "t_first": float(token_ts[0]), "t_finish": t_finish,
            "tokens": len(token_ts), "outcome": "ok",
            "preemptions": 0, "segments": segs,
            "token_ts": list(token_ts)}


def reqtrace_rec(entries):
    return {"kind": "reqtrace", "n": len(entries),
            "coverage_frac": 1.0,
            "requests": {str(i): e for i, e in enumerate(entries)}}


class TestBlameFit:
    def test_decode_stall_outranks_the_queued_ttft_shape(self):
        # queued fills the ENTIRE TTFT window (share 1.0, the default
        # look of any saturated open-loop stream) yet the decode-phase
        # stall still wins: precedence, not max-share
        blame = autofit.fit_blame([reqtrace_rec([stall_entry()])])
        assert blame["axis"] == "tpot"
        assert blame["dominant"] == "prefetch_wait"
        assert blame["candidates"]["ttft.queued"] == pytest.approx(
            1.0, abs=1e-6)
        assert blame["share"] >= autofit.MIN_BLAME_SHARE

    def test_stall_actions_raise_the_antithrash_floor(self):
        blame = autofit.fit_blame([reqtrace_rec([stall_entry()])])
        assert blame["actions"]["min_resident_rounds"] \
            == autofit.BLAME_RESIDENT_ROUNDS
        # one parked row, no stacked waits -> deepen (floor 2)
        assert blame["actions"]["prefetch_depth"] == 2
        assert blame["observed"]["stacked_waits_peak"] == 1

    def test_stacked_waits_cap_depth_at_one(self):
        # two requests whose wait spans overlap in wall time: exposed
        # transfers piled onto one host, the fit serializes them
        entries = [stall_entry(), stall_entry(wait=(1.2, 2.8))]
        blame = autofit.fit_blame([reqtrace_rec(entries)])
        assert blame["dominant"] == "prefetch_wait"
        assert blame["observed"]["stacked_waits_peak"] == 2
        assert blame["actions"]["prefetch_depth"] == 1

    def test_no_decode_stall_blames_the_queue(self):
        # same request with the stall segment replaced by decode and
        # an even token cadence: only the TTFT queued share is left
        e = stall_entry()
        e["segments"] = [["queued", 0.0, 1.0, None],
                         ["decode", 1.0, 3.0, None]]
        e["token_ts"] = [1.0, 2.0, 3.0]
        blame = autofit.fit_blame([reqtrace_rec([e])])
        assert (blame["axis"], blame["dominant"]) == ("ttft", "queued")
        assert blame["actions"] == {"up_queue": 1}

    def test_admit_wait_blamed_when_queued_is_quiet(self):
        e = stall_entry()
        e["segments"] = [["queued", 0.0, 0.1, None],
                         ["admit_wait", 0.1, 1.0, None],
                         ["decode", 1.0, 3.0, None]]
        e["token_ts"] = [1.0, 2.0, 3.0]
        blame = autofit.fit_blame([reqtrace_rec([e])])
        assert blame["dominant"] == "admit_wait"
        assert blame["actions"] == {"admit_highwater": 1.0}

    def test_below_threshold_blames_nobody(self):
        # every candidate under MIN_BLAME_SHARE: an untracked-heavy
        # history with an even cadence leaves no segment dominant
        e = stall_entry()
        e["segments"] = [["queued", 0.0, 0.2, None],
                         ["admit_wait", 0.2, 0.4, None]]
        e["token_ts"] = [1.0, 2.0, 3.0]
        blame = autofit.fit_blame([reqtrace_rec([e])])
        assert blame["dominant"] is None and blame["axis"] is None
        assert blame["actions"] == {}

    def test_no_reqtrace_records_means_no_blame(self):
        assert autofit.fit_blame(ladder_records()) is None
        assert autofit.fit(ladder_records())["blame"] is None

    def test_fit_threads_blame_into_the_residency_section(self):
        # paging signals alone fit depth from the trace overlap; the
        # digest proves a request's p99 PAID for the exposed pull, so
        # the blame actions override the signal fit
        recs = paging_records(overlap=True) \
            + [reqtrace_rec([stall_entry()])]
        fitted = autofit.fit(recs)
        res = fitted["residency"]
        assert res["min_resident_rounds"] \
            == autofit.BLAME_RESIDENT_ROUNDS
        assert res["prefetch_depth"] \
            == fitted["blame"]["actions"]["prefetch_depth"]
        assert fitted["source"]["n_reqtrace"] == 1
        # the blamed fit is still deterministic, byte for byte
        assert autofit.dumps_config(autofit.fit(recs)) \
            == autofit.dumps_config(autofit.fit(recs))


class TestABSmoke:
    def test_fitted_engine_does_not_lose_to_default(self):
        # the tier-1 A/B: run_fitted records an untimed leg under the
        # default ladder, fits a config from that trace, and asserts
        # the STRICT expected-padding win in-run (deterministic,
        # before any wall clock) plus byte-exactness of both legs.
        # Here we re-pin the deterministic claim and bound the wall
        # clock with slack for shared-host noise (~+5% measured).
        from benchmarks.bench_serving import fit_smoke_config, run_fitted

        r = run_fitted(**fit_smoke_config(), quiet=True)
        assert (r["expected_padding_fitted"]
                < r["expected_padding_default"])
        assert r["fitted_goodput_tok_s"] > 0
        assert (r["fitted_goodput_tok_s"]
                >= r["default_goodput_tok_s"] * 0.85)
        assert "ladder" in r["config_sections"]
        # the blame A/B rode along: the seeded decode stall was
        # blamed (prefetch_wait, not the queued TTFT shape) and the
        # blamed segment's p99-gap-band share strictly shrank under
        # the blame-fitted residency (also asserted in-run)
        assert r["blame_segment"] == "prefetch_wait"
        assert r["blame_share_fitted"] < r["blame_share_default"]
