"""Known-bad: traced intermediates smuggled out of the trace through
``self.*`` and module globals."""

from functools import partial

import jax

_LAST = None


@partial(jax.jit, static_argnames=("scale",))
def leaky_method(self, x, *, scale):
    self.cache = x * scale  # EXPECT: tracer-leak
    return x


@jax.jit
def leaky_global(x):
    global _LAST  # EXPECT: tracer-leak
    _LAST = x + 1
    return x


@jax.jit
def leaky_nested(x):
    def inner(v):
        # nested defs trace under the same jit
        inner.owner.state = v  # not self/global: allowed by the rule
        return v

    return inner(x)
