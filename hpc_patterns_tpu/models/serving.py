"""Continuous batching: a serving loop over the ragged paged cache.

The round-4 machinery (per-sequence positions, per-row pool writes,
page-table indirection — models/decode.py) provided the building
blocks; this module is the loop that makes them a serving system, the
vLLM-style capacity story:

- a **page free-list**: the pool is a shared arena; each admitted
  sequence takes exactly the pages its prompt + budget needs and
  returns them on completion;
- **admission**: new sequences enter as soon as pages free up —
  batch slots don't wait for the whole batch to finish (the static-
  batching waste: every row pays the longest row's wall clock);
- **per-row completion**: on-device ``pos``/``limit`` cursors let every
  row advance at its own length; budget exhaustion and (optional) EOS
  end a row independently of its neighbors.

TPU shape of the loop: the inner stepper is ONE jit containing a
``lax.scan`` over ``chunk`` tokens (iteration-level scheduling
quantized to ``chunk``) — host work and dispatch latency amortize over
the chunk, exactly the reference's amortize-the-submit-path discipline
(SURVEY.md §3.1's repetition loop). Finished rows stop advancing
INSIDE the chunk (their ``pos`` freezes at ``limit``; the frozen write
re-targets the row's own last slot, which the row still owns), so a
chunk never writes past a row's allocation. Idle slots point their
table row at a dedicated TRASH page and their writes land there —
garbage in, never read, discarded.

Production shape (round 6), three coupled levers:

- **prompt-length bucketing**: prompts pad to a small ladder of
  lengths (:func:`bucket_ladder`), so admission prefill compiles are
  bounded by the LADDER size, not the number of distinct prompt
  lengths in the stream (causality keeps the true-prefix K/V and the
  last-real-token logits exact — decode.prefill's ``last_pos`` route);
- **overlapped admission**: the decode chunk is DISPATCHED first and
  admissions (table upload, prefill, first-token pick) are enqueued
  behind it — JAX async dispatch keeps the device queue fed while the
  host does admission work, and the first-token readback is deferred
  to the next sync point instead of stalling the loop per admission.
  The admission-bubble fraction (host admission time exposed with no
  decode work in flight) is measured per ``run()`` and emitted through
  the metrics registry;
- **sampling in the engine**: per-row temperature and per-row PRNG key
  streams (``temperature``/``top_k``/``seed``; per-request overrides
  via :meth:`ContinuousBatcher.submit`). Each row consumes its key
  exactly as a standalone ``paged_generate(..., key=request_key(sid))``
  would, so SAMPLED serving is token-identical to standalone sampling
  — the same oracle discipline as greedy mode, not a weaker
  distributional claim. Draft-assisted serving samples through the
  shared speculative accept/resample (models/speculative.paged_round),
  which preserves the law but not the draws — its oracle is
  distributional.

Robustness shape (round 8) — the scenario layer for traffic that does
not cooperate:

- **priority classes + admission control**: requests carry a
  ``priority`` (lower number = more important); admission serves
  classes in priority order, a ``admit_highwater`` mark makes fresh
  admissions back off before the pool is exhausted (headroom reserved
  for resumes), and requests with a queue ``deadline_s`` are SHED once
  it expires instead of silently aging;
- **preemption-and-resume under memory pressure** (``preempt=True``):
  when a higher-priority request cannot get pages, the lowest-priority
  victim is EVICTED at a chunk boundary — its generated tokens and
  (sampled mode) its per-row key state snapshot to host, its pages
  return to the arena — and later RESUMED through the ordinary prefill
  path with prompt = original prompt + generated-so-far. Causality
  makes the resumed cache exactly the uninterrupted one, and the
  split/pick order of ``_admit_row`` matches ``_chunk_step``'s, so a
  preempted-and-resumed sequence's tokens are BYTE-IDENTICAL to an
  uninterrupted run with the same request key (oracle-tested);
- **open-loop serving** (``run(arrivals=...)``): requests enter on the
  schedule's clock (harness/loadgen.py), not on completion — overload
  builds queues and blows deadlines where a closed loop would just
  slow down;
- **SLO accounting** (``slo={priority: harness.slo.SLOTarget}``):
  per-class TTFT/TPOT tracking against declared targets; after each
  run ``last_slo`` carries the attainment rollup and goodput
  (SLO-attained tok/s) lands next to raw tok/s in the metrics
  registry;
- **chaos hook**: each scheduler round probes
  ``harness.chaos.maybe_inject("engine_round", ...)`` so a seeded
  stalled-host fault perturbs the real loop (and shows up as bubble in
  the trace rollups).

Serving-plane shape (round 10) — the engine core / transport split:

- :class:`EngineCore` is the engine CORE — batching, paging, sampling,
  preemption, and the per-round scheduler (:meth:`EngineCore.
  service_round`) — with no opinion about where requests come from;
- :class:`ContinuousBatcher` is the single-process SUBMISSION
  TRANSPORT over it: the classic ``submit()``/``run()`` loop
  (open-loop arrivals, bounded runs, the SLO rollup tail). Its
  behavior is byte-identical to the pre-split engine;
- the multi-replica serving plane (``hpc_patterns_tpu/serving_plane/``)
  drives the SAME core through its router: N replicas each own an
  :class:`EngineCore` and the router is just another transport. KV
  MIGRATION (prefill/decode disaggregation) lives here as the core
  primitives :meth:`EngineCore.export_migration` /
  :meth:`EngineCore.install_migration`: a migrated request is
  structurally a RESUME on another replica — the exported row state
  (cursors, sampling key, KV pages) re-enters a peer engine exactly
  where the donor left off, so the resume oracle extends to the
  disaggregated path byte-for-byte (docs/serving_plane.md).

Tiered-memory shape (round 11) — the HBM arena as a cache:

- ``EngineCore(residency=...)`` (a :class:`hpc_patterns_tpu.memory.
  ResidencyManager`) fronts a larger HOST-resident pool with the HBM
  page arena: under page pressure, policy-chosen victim rows PAGE OUT
  to the host tier at a chunk boundary (the :meth:`EngineCore.
  _detach_row` snapshot — KV bytes move, nothing is recomputed) and
  swapped rows prefetch back with the pull dispatched BEFORE the
  decode chunk and the install landing behind it (the overlapped-
  admission discipline, measured as ``mem.prefetch`` windows). So
  admission consults the manager instead of failing at
  ``free_pages == 0`` — context length and batch become a policy
  knob (docs/memory.md).

Prefix-sharing shape (round 12) — the sharing-aware arena:

- ``EngineCore(prefix_cache=True)`` puts a radix prefix index
  (:class:`hpc_patterns_tpu.memory.RadixPrefixCache`) over the paged
  pool with REFCOUNTED page ownership: admission longest-prefix-
  matches the prompt against every chain already resident at its
  bucket rung, maps the matched pages read-only into the new row's
  table, and prefills ONLY the tail — the hottest KV bytes (shared
  system prompts, few-shot templates, conversation trees) live ONCE
  in the arena instead of N times, and TTFT skips the matched span's
  compute (``serve.prefill_skip_frac``). Copy-on-write is resolved AT
  ADMISSION: the boundary page (the first the row may write) is
  always private by construction, and interior shared pages are never
  rewritten — decode writes start at the prompt's own tail
  (docs/prefix_cache.md has the full COW rule and the rung-keyed
  bitwise-parity story).

Correctness contract (oracle-tested): every admitted sequence's
emitted tokens are exactly ``paged_generate``'s for the same prompt,
budget, and (when sampling) per-request key, regardless of what was
scheduled around it — including sequences preempted and resumed along
the way, sequences prefilled on one engine and decoded on another
(the serving-plane migration oracle, tests/test_serving_plane.py),
sequences paged through the host tier and back
(tests/test_residency_serving.py), and sequences served through
shared prefix pages (tests/test_prefix_cache.py — greedy AND
sampled, under preemption and migration).

Reference lineage: the benchmark-IS-the-test discipline
(aurora.mpich.miniapps/src/CMakeLists.txt:39-50) — the engine's
throughput benchmark (benchmarks/bench_serving.py) validates the
oracle on every run.
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field, replace
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from hpc_patterns_tpu.harness import chaos as chaoslib
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import reqtrace as reqtracelib
from hpc_patterns_tpu.harness import slo as slolib
from hpc_patterns_tpu.harness import trace as tracelib
from hpc_patterns_tpu.memory.prefix_cache import RadixPrefixCache
from hpc_patterns_tpu.models.decode import (
    PREFIX_ALIGN,
    _pick,
    _topk_mask,
    init_paged_cache,
    paged_decode_step,
    paged_prefill,
    paged_tail_prefill,
)
from hpc_patterns_tpu.models.transformer import TransformerConfig


def bucket_ladder(max_len: int, *, lo: int = 16,
                  growth: float = 2.0) -> tuple[int, ...]:
    """A power-of-two-ish prompt-length ladder covering 1..``max_len``:
    rungs ``lo, lo*growth, ...`` with the top rung clamped to
    ``max_len`` (so no rung pads past the longest legal prompt). The
    ladder size — not the stream's distinct-length count — bounds the
    engine's admission-prefill compiles."""
    if max_len < 1:
        raise ValueError(f"max_len must be >= 1, got {max_len}")
    if lo < 1 or growth <= 1.0:
        raise ValueError(f"need lo >= 1 and growth > 1, got {lo}/{growth}")
    rungs = []
    r = lo
    while r < max_len:
        rungs.append(r)
        r = max(int(r * growth), r + 1)
    rungs.append(max_len)
    return tuple(rungs)


def fit_bucket_ladder(lengths, max_rungs: int, *,
                      max_len: int | None = None) -> tuple[int, ...]:
    """Fit a prompt-length ladder to an OBSERVED length sample: up to
    ``max_rungs`` rungs minimizing the expected padding waste
    ``E[rung(len) - len]`` over the sample — the data-driven
    counterpart of :func:`bucket_ladder`'s shape-blind powers of two
    (open since round 6; the serving plane's router and the plane
    benchmark fit their ladder from a loadgen sample before building
    replicas). Exact DP over the distinct observed lengths (optimal
    rungs always sit ON sample points: lowering a rung to the largest
    length it covers only removes padding), O(U^2 * R) for U distinct
    lengths. ``max_len``: extend the top rung to cover prompts up to
    this length even if the sample never reached it. Also reachable as
    ``bucket_ladder.fit`` (the constructor spelling)."""
    lengths = [int(t) for t in lengths]
    if not lengths or min(lengths) < 1:
        raise ValueError("fit_bucket_ladder needs a nonempty sample of "
                         "positive lengths")
    if max_rungs < 1:
        raise ValueError(f"max_rungs must be >= 1, got {max_rungs}")
    counts: dict[int, int] = {}
    for t in lengths:
        counts[t] = counts.get(t, 0) + 1
    if max_len is not None and max_len > max(counts):
        counts[int(max_len)] = counts.get(int(max_len), 0)
    cand = sorted(counts)
    n_cand = len(cand)
    cnt = np.asarray([counts[c] for c in cand], np.int64)
    val = np.asarray(cand, np.int64)
    pc = np.concatenate([[0], np.cumsum(cnt)])
    pv = np.concatenate([[0], np.cumsum(cnt * val)])

    def seg_waste(i: int, j: int) -> int:
        # lengths cand[i..j] all pad up to cand[j]
        return int(val[j] * (pc[j + 1] - pc[i]) - (pv[j + 1] - pv[i]))

    r_max = min(max_rungs, n_cand)
    inf = float("inf")
    # dp[r][j]: min waste covering cand[0..j] with r rungs, top = cand[j]
    dp = [[inf] * n_cand for _ in range(r_max + 1)]
    back = [[-1] * n_cand for _ in range(r_max + 1)]
    for j in range(n_cand):
        dp[1][j] = seg_waste(0, j)
    for r in range(2, r_max + 1):
        for j in range(r - 1, n_cand):
            best, bi = inf, -1
            for i in range(r - 2, j):
                w = dp[r - 1][i] + seg_waste(i + 1, j)
                if w < best:
                    best, bi = w, i
            dp[r][j], back[r][j] = best, bi
    # the ladder must cover the sample max: chains end at the top cand
    r_best = min(range(1, r_max + 1), key=lambda r: dp[r][n_cand - 1])
    rungs, j, r = [], n_cand - 1, r_best
    while r >= 1 and j >= 0:
        rungs.append(int(val[j]))
        j = back[r][j]
        r -= 1
    return tuple(sorted(rungs))


bucket_ladder.fit = fit_bucket_ladder


def expected_padding(buckets, lengths) -> float:
    """Mean padded-minus-true tokens per prompt for ``lengths`` under
    ``buckets`` (None = exact lengths, zero padding) — the objective
    :func:`fit_bucket_ladder` minimizes, exposed so ladders can be
    compared (the fit-beats-default pin in tests/test_serving_plane.py
    and the plane benchmark's ladder report)."""
    lengths = [int(t) for t in lengths]
    if not lengths:
        return 0.0
    return float(sum(pad_to_bucket(buckets, t) - t
                     for t in lengths)) / len(lengths)


def pad_to_bucket(buckets, prompt_len: int) -> int:
    """The padded prefill length: the smallest ladder rung that fits
    (the exact length when ``buckets`` is None). THE single pad rule —
    the engine pads admissions with it and pool-sizing callers
    (serve_app, bench_serving) must size with the same function, or
    ``pages_needed`` desynchronizes from what admission writes."""
    if buckets is None:
        return prompt_len
    for rung in sorted(buckets):
        if rung >= prompt_len:
            return int(rung)
    raise ValueError(
        f"prompt length {prompt_len} above the bucket-ladder top "
        f"{max(buckets)}; extend prompt_buckets"
    )


@dataclass
class Request:
    """One sequence to serve: ``prompt`` (T,) int32, up to ``max_new``
    generated tokens (fewer if ``eos_id`` fires). ``t_submit`` stamps
    queue entry so admission can attribute time-to-first-token.
    ``temperature``/``key``: per-request sampling overrides (None =
    the engine's defaults; the default key is
    ``ContinuousBatcher.request_key(seq_id)``). ``priority``: lower
    number = more important (admission order; preemption eligibility).
    ``deadline_s``: queue-time shedding deadline relative to submit
    (None = never shed). ``resume_prefix``: internal — tokens this
    request already emitted before being preempted; its prompt then
    already carries them, and the engine prepends them to the output."""
    prompt: np.ndarray
    max_new: int
    seq_id: int = -1
    t_submit: float = 0.0
    temperature: float | None = None
    key: jax.Array | None = None
    priority: int = 0
    deadline_s: float | None = None
    resume_prefix: np.ndarray | None = None


@dataclass
class MigrationBundle:
    """One row's complete serving state, detached from its engine —
    what a prefill-role replica hands a decode-role replica (the
    serving plane's KV handoff, docs/serving_plane.md). Contains
    everything :meth:`EngineCore.install_migration` needs to continue
    the row EXACTLY where the donor stopped: the per-row cursors
    (``pos``/``limit``), the current token, the post-admission sampling
    key state, the per-row temperature, and the row's KV pages gathered
    from the donor's pool (``pages_payload``: {cache key: per-layer
    arrays with leading dim ``n_pages``} — device arrays on the
    in-process path, numpy on the wire). A migrated request is
    structurally a RESUME on another replica, so the round-8 resume
    oracle extends to it byte-for-byte. ``seq`` is the plane-assigned
    migration sequence number: both sides fingerprint it into the
    collective schedule chain, which is how a router/replica desync is
    caught at merge time."""
    seq_id: int
    prompt: np.ndarray       # THIS admission's (possibly resume) prompt
    out: list                # tokens emitted so far (prefix included)
    prefix: list             # tokens emitted before THIS admission
    budget: int
    pos: int
    limit: int
    token: int               # current device token (== out[-1])
    key: np.ndarray          # (2,) uint32 post-admission key state
    temp: float              # effective per-row temperature
    temp_override: float | None
    priority: int
    deadline_s: float | None
    t_submit: float
    t_first: float | None
    preemptions: int
    n_pages: int
    page_size: int
    pages_payload: dict
    seq: int = -1            # plane-assigned migration sequence number
    #: the admission rung (bucket-padded length) the row prefilled at —
    #: the KEY a prefix-sharing destination resolves against: prefix
    #: K/V bytes are rung-stamped (docs/prefix_cache.md), so only a
    #: same-rung cached chain is bit-identical to this payload. 0 =
    #: unknown (pre-round-12 bundles; destinations then materialize)
    rung: int = 0
    #: leading tokens whose pages hold PURE-PROMPT K/V (page-aligned,
    #: = (prompt_len // page_size) * page_size): the span a destination
    #: with a warm prefix cache may resolve to its own shared pages
    #: instead of installing the payload — byte-exact either way
    prefix_len: int = 0
    #: how the payload reached (or will reach) the installing replica:
    #: "local" (never left the exporting engine), "device_put" (host
    #: -staged cross-device copy), "dma" (the fused remote-DMA pair,
    #: comm/migration_dma.py), "wire" (the socket codec). The router
    #: fingerprints this into the collective schedule's
    #: ``kv_migration`` entries as the ``algorithm`` field
    transport: str = "local"
    #: request-lifecycle segment history (harness/reqtrace.py) carried
    #: across the handoff so the destination's attribution does not
    #: start fresh — the same backward-compatible pattern as
    #: ``transport``: None when the donor traced nothing; an ABSENT
    #: key on a legacy wire artifact decodes to one ``untracked``
    #: segment (reqtrace.LEGACY_SEGMENTS)
    segments: tuple | None = None


@dataclass
class _Slot:
    seq_id: int = -1
    pages: list = field(default_factory=list)
    prompt_len: int = 0
    budget: int = 0
    out: list = field(default_factory=list)
    active: bool = False
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_dispatch: float = 0.0  # admission-dispatch trace stamp
    first_dev: jax.Array | None = None  # pending first-token readback
    prompt: np.ndarray | None = None  # THIS admission's unpadded prompt
    priority: int = 0
    deadline_s: float | None = None
    temp_override: float | None = None
    prefix: list = field(default_factory=list)  # pre-preemption tokens
    padded_len: int = 0      # the admission rung this row prefilled at
    shared_pages: int = 0    # leading table entries mapped SHARED


@partial(jax.jit,
         static_argnames=("cfg", "chunk", "eos_id", "greedy", "top_k",
                          "mesh"),
         donate_argnums=(1, 2, 3, 4, 5))
def _chunk_step(params, cache, pos, limit, tokens, keys, temps, *, cfg,
                chunk, eos_id, greedy, top_k, mesh):
    """``chunk`` ragged decode steps in one trace: rows advance while
    ``pos < limit``; an emitted ``eos_id`` pulls the row's limit down
    to its current end. Emits the picked token per step (valid where
    the step was active). eos_id < 0 disables EOS. Module-level jit
    (static config) so every engine instance with the same config
    shares one compilation.

    ``greedy`` (static) picks argmax; otherwise each row samples from
    its OWN key stream (``keys`` (B, 2) uint32) at its OWN temperature
    (``temps`` (B,)), advancing the key only on active steps — the
    exact split/pick sequence of decode._generation_scan per row, which
    is what makes sampled serving token-identical to standalone
    ``paged_generate`` with the same per-request key."""

    def step(carry, _):
        cache, pos, limit, tok, keys = carry
        active = pos < limit
        logits, cache = paged_decode_step(params, cache, pos, tok, cfg,
                                          mesh=mesh)
        if greedy:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            split2 = jax.vmap(jax.random.split)(keys)  # (B, 2, 2)
            masked = _topk_mask(logits, top_k) / temps[:, None]
            nxt = jax.vmap(
                lambda l, k: jax.random.categorical(k, l[None, :],
                                                    axis=-1)[0]
            )(masked, split2[:, 1]).astype(jnp.int32)
            keys = jnp.where(active[:, None], split2[:, 0], keys)
        nxt = jnp.where(active, nxt, tok)
        if eos_id >= 0:
            limit = jnp.where(active & (nxt == eos_id),
                              jnp.minimum(limit, pos + 1), limit)
        pos = jnp.where(active, pos + 1, pos)
        return (cache, pos, limit, nxt, keys), nxt

    (cache, pos, limit, tokens, keys), out = lax.scan(
        step, (cache, pos, limit, tokens, keys), None, length=chunk
    )
    return cache, pos, limit, tokens, keys, out


@partial(jax.jit,
         static_argnames=("cfg", "dcfg", "gamma", "rounds", "eos_id",
                          "greedy", "top_k", "mesh"),
         donate_argnums=(2, 3, 4, 5, 6, 7))
def _spec_chunk(params, dparams, cache, dcache, pos, limit, cur, key,
                temps, *, cfg, dcfg, gamma, rounds, eos_id, greedy,
                top_k, mesh=None):
    """``rounds`` draft-assisted serving rounds in ONE dispatch: each
    round is THE shared speculative round body
    (models/speculative.paged_round — one acceptance/emit definition
    for the engine and speculative_generate_batched) at each row's own
    cursor, advancing 1..gamma+1 tokens per round. Budget and EOS
    truncation happen ON DEVICE between rounds (``adv`` clamps at the
    row's limit; an emitted eos pulls the limit to the row's end), so
    the host pays one round trip per ``rounds`` — the draft-mode
    counterpart of _chunk_step's dispatch amortization. Rows at their
    limit run at a clamped cursor (garbage lands in pages they own or
    the trash page).

    ``greedy`` (static) keeps the provably-token-exact acceptance;
    otherwise the rounds run paged_round's LIVE rejection-sampling path
    (speculative._accept_resample) from ``key``, one split per round,
    at per-row ``temps`` — same emitted law as target-only sampling,
    different draws (the distribution oracle's territory). Returns
    (cache, dcache, pos, limit, cur, key, emits, advs): per-round
    tokens (rounds, B, gamma+1) and valid counts (rounds, B) for the
    host to append."""
    from hpc_patterns_tpu.models.speculative import paged_round

    B = pos.shape[0]
    rows = jnp.arange(B)

    def one_round(carry, _):
        cache, dcache, pos, limit, cur, key = carry
        active = pos < limit
        pos_eff = jnp.where(active, pos, 0)
        key, sub = jax.random.split(key)  # greedy: unused, DCE'd
        cache, dcache, a, emit, _ = paged_round(
            params, cfg, dparams, dcfg, cache, dcache, pos_eff, cur,
            gamma, sub, greedy, top_k, temps, mesh=mesh)
        adv = jnp.where(active,
                        jnp.minimum(a + 1, limit - pos), 0)
        if eos_id >= 0:
            k = jnp.arange(gamma + 1)[None, :]
            is_eos = (emit == eos_id) & (k < adv[:, None])
            has = jnp.any(is_eos, axis=1)
            first = jnp.argmax(is_eos, axis=1)
            adv = jnp.where(has, first + 1, adv)
        new_cur = emit[rows, jnp.clip(adv - 1, 0, gamma)]
        cur = jnp.where(adv > 0, new_cur, cur)
        pos = pos + adv
        if eos_id >= 0:
            limit = jnp.where(has, pos, limit)
        return (cache, dcache, pos, limit, cur, key), (emit, adv)

    (cache, dcache, pos, limit, cur, key), (emits, advs) = lax.scan(
        one_round, (cache, dcache, pos, limit, cur, key), None,
        length=rounds)
    return cache, dcache, pos, limit, cur, key, emits, advs


@partial(jax.jit, static_argnames=("cfg", "page_size", "mesh"),
         donate_argnums=(3,))
def _prefill_one(params, prompt, last_pos, cache_one, *, cfg, page_size,
                 mesh):
    """One-row prefill through the shared pool (jitted; compiles per
    distinct PADDED prompt length — the engine's bucket ladder bounds
    that count, see ``prompt_buckets``). ``last_pos`` (traced) redirects
    the returned logits to the last REAL token of a padded prompt.
    ``cache_one`` is donated: the pool IS the capacity lever, so
    admissions must not double it."""
    return paged_prefill(params, prompt, cfg, cache_one, page_size,
                         mesh=mesh, last_pos=last_pos)


@partial(jax.jit,
         static_argnames=("cfg", "page_size", "n_prefix_pages", "mesh"),
         donate_argnums=(3,))
def _tail_prefill_one(params, tail, last_rel, cache_one, *, cfg,
                      page_size, n_prefix_pages, mesh):
    """One-row TAIL prefill through the shared pool — the sharing-aware
    admission's compute half (:func:`~hpc_patterns_tpu.models.decode.
    paged_tail_prefill`): the row's first ``n_prefix_pages`` table
    entries point at SHARED pages whose K/V a same-rung admission
    already wrote, so only the tail positions are computed and only
    the tail pages written. ``last_rel`` (traced) is the true last
    token's offset into the tail. ``cache_one`` is donated like
    :func:`_prefill_one`'s — the pool IS the capacity lever. Compiles
    per (matched page count, padded tail length) — bounded by
    pages_per_seq × the ladder size (see ``tail_prefill_cache_size``)."""
    return paged_tail_prefill(params, tail, cfg, cache_one, page_size,
                              n_prefix_pages, mesh=mesh,
                              last_pos=last_rel)


def prefill_cache_size() -> int:
    """Compiled admission-prefill variants in this process (the jit
    cache of :func:`_prefill_one`) — THE compile-count observable the
    bucket-ladder claim is asserted against (tests) and reported by
    (benchmarks/bench_serving.py). One entry per distinct (padded
    length, config) pair across every engine in the process. A
    consumer of the flight recorder's shared probe
    (harness.trace.jit_cache_size), which compile_watch diffs to stamp
    per-compile events on the trace timeline — strict mode, because
    the ladder-bound assertions gate on this number and a silently
    missing probe would read as the passing value 0."""
    return tracelib.jit_cache_size(_prefill_one, strict=True)


def tail_prefill_cache_size() -> int:
    """Compiled TAIL-prefill variants (:func:`_tail_prefill_one`) in
    this process — the sharing engine's compile-count observable: one
    entry per distinct (matched page count, padded tail length,
    config), bounded by pages_per_seq × ladder size. Strict for the
    same reason as :func:`prefill_cache_size`."""
    return tracelib.jit_cache_size(_tail_prefill_one, strict=True)


@partial(jax.jit, static_argnames=("eos_id", "greedy", "top_k"),
         donate_argnums=(0, 1, 2, 3, 4))
def _admit_row(pos, limit, tokens, keys, temps, logits, key, temp, slot,
               true_len, budget, *, eos_id, greedy, top_k):
    """All device-side admission bookkeeping in ONE dispatch: pick the
    first token from the prefill logits (the same split/pick sequence
    decode._generation_scan opens with, so sampled rows stay
    standalone-exact), seed the row's cursors, and pull the limit to
    ``true_len`` when the row is already done (budget 1, or the first
    token IS eos) — all decided on device, so admission never forces a
    host readback. ``slot``/``true_len``/``budget`` ride as traced
    scalars: one compilation serves every admission."""
    newk, sub = jax.random.split(key)
    first = _pick(logits, sub, temp, greedy, top_k)[0]
    # budget b emits 1 token at admit + (lim - true_len) from chunks
    lim = true_len + budget - 1
    if eos_id >= 0:
        lim = jnp.where(first == eos_id, true_len, lim)
    pos = pos.at[slot].set(true_len)
    limit = limit.at[slot].set(lim)
    tokens = tokens.at[slot].set(first)
    keys = keys.at[slot].set(newk)
    temps = temps.at[slot].set(temp)
    return pos, limit, tokens, keys, temps, first


@partial(jax.jit, donate_argnums=(0,))
def _install_pages(pool, idx, payload):
    """Scatter a migrated row's gathered pages into this engine's pool
    at its newly allocated page ids — the device half of
    :meth:`EngineCore.install_migration`. ``pool`` is donated (the pool
    IS the capacity lever; an install must not double it), and the
    scatter enqueues behind an in-flight decode chunk exactly like an
    overlapped admission's table upload. Compiles per (pool shape,
    payload page-count) — bounded by the engines' page geometries."""
    return pool.at[idx].set(payload)


class EngineCore:
    """Serve a stream of :class:`Request`s through ``slots`` concurrent
    rows of one paged pool — the engine CORE (batching, paging,
    sampling, preemption, migration), shared by the single-process
    :class:`ContinuousBatcher` transport and the multi-replica serving
    plane (``hpc_patterns_tpu/serving_plane/``).

    ``pool_pages``: the shared arena size (pages; one extra trash page
    is appended internally). ``pages_per_seq``: table width = the max
    pages any single sequence may hold (size requests with
    :meth:`pages_needed`). ``chunk``: decode steps per jitted dispatch
    — admission/eviction happen at chunk boundaries (larger amortizes
    host+dispatch; 1 = immediate). ``eos_id`` optionally ends rows
    early. ``mesh``: tp-sharded serving — pools/kernel shard exactly
    like ``paged_generate(..., mesh=...)``.

    ``prompt_buckets``: the prompt-length ladder (sorted ints; see
    :func:`bucket_ladder`). Prompts right-pad to the smallest rung
    that fits, so admission-prefill compiles are bounded by the ladder
    size instead of the stream's distinct lengths (the padding K/V is
    causally invisible and overwritten as the row generates). None =
    exact lengths (one compile per distinct length).

    ``overlap``: dispatch the decode chunk BEFORE doing admissions, so
    table uploads + prefills + first-token picks enqueue behind the
    in-flight chunk instead of stalling it (JAX async dispatch); the
    first-token host readback defers to the next sync point. The
    exposed (un-overlapped) admission time is reported as
    ``last_bubble_frac`` and the ``serve.admit_bubble_frac`` gauge.

    ``temperature``/``top_k``/``seed``: sampling in the engine.
    temperature <= 0 (default) is greedy — the token-exact serving
    oracle. temperature > 0 samples per row from per-request key
    streams (default ``request_key(seq_id)``); a row's emitted tokens
    are then EXACTLY ``paged_generate(prompt, budget,
    key=request_key(sid), temperature=..., top_k=...)``'s — same
    oracle, sampled mode. Per-request ``temperature``/``key`` override
    at :meth:`submit` (sampling engines only).

    ``draft_params``/``draft_cfg``/``gamma``: draft-assisted serving —
    speculative ROUNDS (draft proposes gamma, target verifies in one
    ragged extend; rows advance 1..gamma+1 tokens at their own
    acceptance). ``chunk`` here means ROUNDS per jitted dispatch
    (budget/EOS truncation runs on device between rounds), so
    admission/eviction happen every chunk·(1..gamma+1) tokens.
    Composes with ``mesh``: draft steps ride the shard_map
    paged-kernel route, the ragged extend partitions via GSPMD (tp
    must divide BOTH models' kv_heads). With ``temperature > 0`` the
    rounds run the live rejection-sampling acceptance — emitted law
    exactly target-only sampling, draws not reproducible row-wise
    (the distribution oracle covers it).

    ``preempt``: allow eviction of a lower-priority active row when a
    higher-priority (numerically smaller) request cannot get pages —
    the victim's tokens and key state snapshot to host at a chunk
    boundary, its pages return to the arena, and it re-enters through
    the ordinary prefill path with prompt = original + generated, so
    its final output is byte-identical to an uninterrupted run.
    ``admit_highwater``: fraction of pool pages FRESH admissions may
    fill (1.0 = off); the remainder is headroom reserved for resumes
    (fresh admissions back off, resumes bypass the mark). ``slo``:
    ``{priority: harness.slo.SLOTarget}`` — enables per-class
    TTFT/TPOT tracking; after each :meth:`run`, ``last_slo`` holds the
    attainment rollup (goodput next to raw tok/s) and the
    ``serve.goodput_tok_s``/``serve.tok_s`` gauges are set. Per-request
    outcomes accumulate in ``stats`` either way.

    ``residency``: a :class:`hpc_patterns_tpu.memory.ResidencyManager`
    — tiered HBM<->host paging: the pool becomes a CACHE over the
    manager's host tier, cold/demanded rows page out at chunk
    boundaries and prefetch back under the decode chunk, and the
    constrained engine stays token-identical to an all-HBM one
    (docs/memory.md; draft-assisted engines refuse it — the draft
    cache's row state would have to tier too).

    ``prefix_cache``: the SHARING-AWARE arena (round 12,
    docs/prefix_cache.md) — a radix prefix index over admitted
    prompts plus refcounted page ownership. Admission longest-prefix-
    matches the prompt at its bucket rung, maps the matched pages
    READ-ONLY into the row's table, and prefills ONLY the tail
    (:func:`_tail_prefill_one`); every release path decrefs instead
    of freeing. Token-identical to a private-pages engine, greedy AND
    sampled — the match is RUNG-KEYED because prefix K/V bytes depend
    on the prefill's row count, and the tail prefill mirrors the
    monolithic einsum prefill bit for bit (the parity contract in
    :func:`~hpc_patterns_tpu.models.decode.paged_tail_prefill`).
    Requires an aligned bucket ladder; refuses quantized KV and draft
    engines. Composes with preemption/shed (decref, re-match on
    resume), migration (bundles carry prefix refs a warm destination
    resolves — or it materializes), and residency (shared pages are
    pinned while a second reader is resident).
    """

    def __init__(self, params, cfg: TransformerConfig, *, slots: int,
                 pool_pages: int, pages_per_seq: int, page_size: int,
                 chunk: int = 8, eos_id: int | None = None, mesh=None,
                 draft_params=None, draft_cfg: TransformerConfig | None
                 = None, gamma: int = 4, emit=None,
                 prompt_buckets=None, overlap: bool = True,
                 temperature: float = 0.0, top_k: int = 0,
                 seed: int = 0, preempt: bool = False,
                 admit_highwater: float = 1.0,
                 slo: dict[int, slolib.SLOTarget] | None = None,
                 residency=None, prefix_cache: bool = False):
        if cfg.n_experts:
            # paged serving is dense-model territory so far
            raise ValueError("continuous batching: dense models only")
        if draft_params is not None:
            if draft_cfg is None:
                raise ValueError("draft_params needs draft_cfg")
            if draft_cfg.vocab != cfg.vocab:
                raise ValueError("draft/target vocab mismatch")
            if gamma < 1:
                raise ValueError(f"gamma must be >= 1, got {gamma}")
        if not 0 <= top_k <= cfg.vocab:
            raise ValueError(f"top_k {top_k} outside [0, vocab]")
        if prompt_buckets is not None:
            rungs = tuple(sorted({int(b) for b in prompt_buckets}))
            if not rungs or rungs[0] < 1:
                raise ValueError(
                    f"prompt_buckets must be positive ints, {rungs}")
            if rungs[-1] > cfg.max_seq:
                raise ValueError(
                    f"bucket rung {rungs[-1]} exceeds max_seq "
                    f"{cfg.max_seq} (padded prompts must still fit)")
            prompt_buckets = rungs
        if not 0.0 < admit_highwater <= 1.0:
            raise ValueError(
                f"admit_highwater must be in (0, 1], got {admit_highwater}")
        if prefix_cache:
            # the sharing-aware arena's byte-exactness preconditions
            # (docs/prefix_cache.md): rung-keyed chains need a ladder;
            # SIMD-stable GEMM row counts need aligned rungs and pages;
            # the tail prefill mirrors the EINSUM attention route and
            # attends to exact (not re-quantized) prefix K/V
            if draft_params is not None:
                raise ValueError(
                    "prefix sharing does not compose with draft-"
                    "assisted serving: the draft cache's pages would "
                    "need their own refcounted sharing tier")
            if cfg.kv_cache_dtype != "compute":
                raise ValueError(
                    f"prefix sharing needs exact KV pages but "
                    f"kv_cache_dtype={cfg.kv_cache_dtype!r}: the "
                    "monolithic prefill attends to unquantized K/V "
                    "and quantizes only for storage, so a tail "
                    "computed from dequantized shared pages could not "
                    "be bit-identical to it — serve quantized KV with "
                    "prefix_cache=False, or keep sharing on a "
                    "compute-dtype pool (docs/quantization.md)")
            if prompt_buckets is None:
                raise ValueError(
                    "prefix sharing is RUNG-KEYED (prefix K/V bytes "
                    "depend on the prefill row count): pass "
                    "prompt_buckets so admissions land on shared rungs")
            if page_size % PREFIX_ALIGN or any(
                    r % PREFIX_ALIGN for r in prompt_buckets):
                raise ValueError(
                    f"prefix sharing needs page_size {page_size} and "
                    f"every rung {prompt_buckets} aligned to "
                    f"{PREFIX_ALIGN} (bitwise GEMM row stability — "
                    "models/decode.PREFIX_ALIGN)")
            if cfg.decode_attn == "flash" and any(
                    r % 128 == 0 for r in prompt_buckets):
                raise ValueError(
                    "prefix sharing mirrors the einsum prefill route; "
                    "a flash-attn config with 128-multiple rungs would "
                    "send monolithic prefills through the Pallas "
                    "kernel instead — use off-multiple rungs or "
                    "decode_attn='gather'")
        self.prompt_buckets = prompt_buckets
        self.overlap = bool(overlap)
        self.preempt = bool(preempt)
        self.admit_highwater = float(admit_highwater)
        self.slo = slo
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.greedy = self.temperature <= 0.0
        base, spec = jax.random.split(jax.random.PRNGKey(seed))
        self._req_key_base = base
        self._spec_key = spec
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.gamma = gamma
        # speculative rounds touch positions up to pos+gamma; the page
        # allocation (NOT max_seq) must cover the overshoot
        self.spec_slack = gamma + 1 if draft_params is not None else 0
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.page_size = page_size
        self.pages_per_seq = pages_per_seq
        self.chunk = chunk
        self.eos_id = -1 if eos_id is None else int(eos_id)
        self.mesh = mesh
        self.trash = pool_pages  # the appended trash page's id
        table = np.full((slots, pages_per_seq), self.trash, np.int32)
        self.cache = init_paged_cache(
            cfg, slots, pages_per_seq, page_size,
            pool_pages=pool_pages + 1, table=jnp.asarray(table),
        )
        if draft_params is not None:
            # the draft pool mirrors the target's page geometry and
            # SHARES the page table (one allocation decision serves
            # both caches)
            self.dcache = init_paged_cache(
                draft_cfg, slots, pages_per_seq, page_size,
                pool_pages=pool_pages + 1, table=jnp.asarray(table),
            )
        self.free_pages = list(range(pool_pages))
        self.pool_pages = pool_pages  # arena size (trash page excluded)
        # the sharing-aware arena (round 12): a radix prefix index over
        # admitted prompts plus per-page refcounts — a page is owned by
        # every row whose table maps it AND by the cache chain that
        # indexes it; release paths DECREF (never free) and the page
        # returns to free_pages only at refcount 0 (docs/prefix_cache.md)
        self._prefix = RadixPrefixCache(page_size) if prefix_cache \
            else None
        self._page_refs: dict[int, int] = {}
        self._match_memo: tuple | None = None
        self._prefill_skip_tokens = 0
        self._prefill_total_tokens = 0
        self._table = table  # host mirror
        self.pos = jnp.zeros((slots,), jnp.int32)
        self.limit = jnp.zeros((slots,), jnp.int32)
        self.tokens = jnp.zeros((slots,), jnp.int32)
        self.keys = jnp.zeros((slots, 2), jnp.uint32)
        self.temps = jnp.ones((slots,), jnp.float32)
        self._slots = [_Slot() for _ in range(slots)]
        self._pending: list[int] = []  # admitted, first token unread
        self._queue: list[Request] = []
        self.finished: dict[int, np.ndarray] = {}
        self._next_id = 0
        self.last_bubble_frac = 0.0  # of the most recent run()
        # per-request outcome table (harness/slo.py's input): t_submit /
        # t_first / t_finish / tokens / priority / outcome ("ok"|"shed")
        # / preemptions, keyed by seq_id; survives across runs
        self.stats: dict[int, dict] = {}
        self.last_slo: dict | None = None  # attainment of the last run
        self._serve_s = 0.0  # cumulative run() wall time (goodput base)
        # chunk-window host stamps for the serving plane's migration-
        # overlap accounting; off on the single-process path (the
        # plane flips it on for decode-role replicas)
        self.track_chunk_windows = False
        self.chunk_windows: deque = deque(maxlen=8192)
        # tiered residency (hpc_patterns_tpu/memory/): the HBM pool as
        # a cache over a larger host pool — admission consults the
        # manager instead of failing at free_pages == 0; cold rows
        # page out at chunk boundaries and page back in with the pull
        # dispatched BEFORE the decode chunk (docs/memory.md)
        self.residency = residency
        self._swapped: dict[int, MigrationBundle] = {}
        #: pulls in flight: (host bundle, device payload, window handle)
        self._prefetching: list[tuple] = []
        #: installed this round, window completion pending
        self._installed_prefetch: list[tuple] = []
        self._external_demand = 0  # router-signaled install pressure
        if residency is not None:
            if draft_params is not None:
                raise ValueError(
                    "draft-assisted engines do not page: the draft "
                    "cache's row state would have to tier too")
            # the overlap proof needs the chunk windows to intersect
            self.track_chunk_windows = True
            # per-page payload bytes (every non-table pool, all
            # layers): the manager's block accounting unit
            self._page_nbytes = sum(
                int(arr.nbytes) // (pool_pages + 1)
                for name, pools in self.cache.items() if name != "table"
                for arr in pools)
        else:
            self._page_nbytes = 0
        # observability hook (the framework's metrics/logging
        # subsystem, SURVEY.md §5): a callable taking keyword fields —
        # pass harness.RunLog.emit for JSONL records of admissions,
        # completions, and queue waits; None = silent
        self._emit = emit or (lambda **kw: None)

    @classmethod
    def from_fitted(cls, params, cfg: TransformerConfig, fitted, **kw):
        """Build an engine from a :mod:`hpc_patterns_tpu.harness.autofit`
        ``FittedConfig`` (the dict, as ``autofit.load_fitted`` returns
        it): the fitted prompt ladder becomes ``prompt_buckets``
        (clamped to this model's ``max_seq``), everything else passes
        through unchanged. An explicit ``prompt_buckets=`` kwarg wins —
        the caller's hand-tuned ladder outranks the fit."""
        from hpc_patterns_tpu.harness import autofit as autofitlib

        fitted = autofitlib.validate_fitted(fitted)
        if kw.get("prompt_buckets") is None:
            buckets = autofitlib.ladder_from(fitted, max_seq=cfg.max_seq)
            if buckets is not None:
                kw["prompt_buckets"] = buckets
        return cls(params, cfg, **kw)

    # -- admission ---------------------------------------------------------

    @staticmethod
    def pages_needed(prompt_len: int, max_new: int, page_size: int, *,
                     gamma: int | None = None,
                     padded_len: int | None = None) -> int:
        """Pages one request holds in this engine: prompt + budget,
        plus the speculative overshoot slack (gamma+1) when a draft
        serves, OR the bucket-padded prefill length if that reaches
        further — THE sizing rule; callers building their own pools
        (serve_app) must use it rather than re-deriving the slack."""
        slack = (gamma + 1) if gamma is not None else 0
        span = max(prompt_len + max_new + slack, padded_len or 0)
        return -(-span // page_size)

    def _bucket_len(self, prompt_len: int) -> int:
        return pad_to_bucket(self.prompt_buckets, prompt_len)

    def _pages_for(self, prompt_len: int, max_new: int) -> int:
        return self.pages_needed(
            prompt_len, max_new, self.page_size,
            gamma=self.gamma if self.draft_params is not None else None,
            padded_len=self._bucket_len(prompt_len))

    # -- the sharing-aware arena (refcounted pages + radix index) ----------

    def _alloc_pages(self, n: int) -> list[int]:
        """Take ``n`` pages from the free list at refcount 1 (host-list
        bookkeeping only). The caller checked capacity."""
        pages = [self.free_pages.pop() for _ in range(n)]
        if self._prefix is not None:
            for p in pages:
                self._page_refs[p] = 1
        return pages

    def _incref_pages(self, pages) -> None:
        for p in pages:
            self._page_refs[p] += 1

    def _decref_pages(self, pages) -> None:
        """THE release path: drop one reference per page, freeing only
        at zero — completion, preemption, shed, migration-out, swap-out
        and cache eviction all funnel here, so a page another row (or
        the prefix index) still maps can never be handed out twice.
        Plain engines (no cache) keep the original free-list append."""
        if self._prefix is None:
            self.free_pages.extend(pages)
            return
        for p in pages:
            r = self._page_refs[p] - 1
            if r:
                self._page_refs[p] = r
            else:
                del self._page_refs[p]
                self.free_pages.append(p)

    def _prefix_match(self, prompt) -> list[int]:
        """Longest-cached-prefix page ids for ``prompt`` at ITS rung —
        the admission-match decision (a host trie walk; no device op
        anywhere near it). Capped at ``(T-1) // page_size`` pages so
        the tail always keeps the last true token: the first-token
        logits must be COMPUTED over the tail, never looked up. PURE
        peek: no LRU touch (a queued request that never admits must
        not keep its chain hot — an admission stamps its chain via
        ``_insert_prefix``) and no hit/miss accounting (that moves
        only when a match becomes an admission, ``count_match`` in
        :meth:`_admit`)."""
        if self._prefix is None:
            return []
        T = int(prompt.size)
        return self._prefix.match(
            prompt, self._bucket_len(T),
            max_pages=(T - 1) // self.page_size, touch=False)

    def _memo_match(self, req: Request) -> list[int]:
        """``_prefix_match`` with a ONE-round, one-entry memo: the
        queue head is sized up to three times per round (the
        preemption policy, the residency balance, and the admission
        pass) — each a full-prompt tobytes + trie walk on the
        dispatch-critical path. The memo is keyed by request identity
        and cleared at ``service_round`` entry; within a round the
        head's chain cannot be invalidated between uses (every
        reclaim in the round keeps the head's own chain, preemption
        and swap-out decref without touching the trie, and inserts
        only add nodes)."""
        memo = self._match_memo
        if memo is not None and memo[0] is req:
            return memo[1]
        chain = self._prefix_match(req.prompt)
        self._match_memo = (req, chain)
        return chain

    def _request_need(self, req: Request) -> int:
        """PRIVATE pages this request needs right now: the full sizing
        rule minus whatever a prefix match would map shared — the
        number admissibility, preemption, and residency demand all
        charge (the capacity win is exactly this subtraction)."""
        need = self._pages_for(req.prompt.size, req.max_new)
        if self._prefix is not None:
            need -= len(self._memo_match(req))
        return need

    def _insert_prefix(self, prompt, rung: int, pages) -> None:
        """Publish an admission's full-prompt pages into the radix
        index (host trie insert): pages ``[0, T // page_size)`` hold
        pure-prompt K/V computed at ``rung``, bitwise what any
        same-rung admission would prefill, so future prompts sharing
        the prefix map them instead of re-prefilling. Newly indexed
        pages take the cache's own arena reference."""
        if self._prefix is None:
            return
        n_full = int(prompt.size) // self.page_size
        if n_full:
            self._incref_pages(
                self._prefix.insert(prompt, rung, pages[:n_full]))

    def _reclaim_cache_pages(self, need: int, fresh: bool,
                             keep=()) -> int:
        """Free LRU cache-only pages (refcount 1 — no row maps them)
        until a ``need``-page request could admit: the raw free count
        and, for fresh admissions, the high-water mark (cached pages
        count as used until reclaimed). ``keep``: the requesting
        prompt's OWN matched chain — evicting it would free pages only
        to grow the same request's private need by exactly as many
        (the ``need`` the caller computed assumed the match), a
        self-defeating reclaim. Partial progress kept — the victims()
        philosophy. Host bookkeeping only."""
        if self._prefix is None:
            return 0
        reserved = self._reserved_prefetch_pages()
        shortfall = need - (len(self.free_pages) - reserved)
        if fresh:
            used = self.pool_pages - len(self.free_pages) + reserved
            hw_cap = self.admit_highwater * self.pool_pages
            shortfall = max(shortfall, math.ceil(used + need - hw_cap))
        if shortfall <= 0:
            return 0
        kept = set(keep)
        freed = self._prefix.evict(
            shortfall,
            lambda p: p not in kept
            and self._page_refs.get(p, 0) == 1)
        self._decref_pages(freed)
        return len(freed)

    def _row_swappable(self, slot: int) -> bool:
        """May the residency manager page this row out? NOT while
        another row maps any of its pages (pin-while-shared: net of
        the cache's own reference, refcount >= 2 means a second reader
        would be left pointing at pages whose bytes are mid-flight).
        Cache-only references don't block — those pages simply STAY
        resident and shareable while the row's private pages move.
        Runs once per active slot per round (the pin loop), so
        membership goes through the O(1) ``has_page`` probe rather
        than materializing the cache's page set."""
        if self._prefix is None:
            return True
        for p in self._slots[slot].pages:
            if (self._page_refs.get(p, 0)
                    - (1 if self._prefix.has_page(p) else 0)) >= 2:
                return False
        return True

    def _row_freeable_pages(self, slot: int) -> int:
        """Pages an eviction of this row would ACTUALLY free (refcount
        1) — the preemption feasibility math must not count shared
        pages it cannot reclaim."""
        if self._prefix is None:
            return len(self._slots[slot].pages)
        return sum(1 for p in self._slots[slot].pages
                   if self._page_refs.get(p, 0) == 1)

    @property
    def prefill_skip_frac(self) -> float:
        """Fraction of submitted prompt tokens whose prefill was
        SKIPPED via a prefix match — the headline capacity/TTFT
        observable (``serve.prefill_skip_frac``; measured and gated by
        ``bench_serving --shared`` / ``harness/regress.py``)."""
        if not self._prefill_total_tokens:
            return 0.0
        return self._prefill_skip_tokens / self._prefill_total_tokens

    def release_prefix_cache(self) -> None:
        """Drop every cached chain and return cache-only pages to the
        arena (rows keep their own references) — engine teardown and
        the tests' arena-drain helper."""
        if self._prefix is not None:
            self._decref_pages(self._prefix.clear())

    def request_key(self, seq_id: int) -> jax.Array:
        """The per-request PRNG key a default (key=None) submit gets:
        the standalone-reproduction handle. A sampled row's served
        tokens equal ``paged_generate(prompt, budget,
        key=request_key(sid), temperature=engine.temperature,
        top_k=engine.top_k)`` exactly (non-draft engines)."""
        return jax.random.fold_in(self._req_key_base, seq_id)

    def submit(self, prompt, max_new: int, seq_id: int | None = None, *,
               temperature: float | None = None, key=None,
               priority: int = 0, deadline_s: float | None = None,
               resume_prefix=None) -> int:
        """Enqueue a sequence; returns its id. Tokens appear in
        ``finished[id]`` once served. ``temperature``/``key``: per-row
        sampling overrides (sampling engines only; key defaults to
        :meth:`request_key`). ``priority``: lower = more important
        (admission order; with ``preempt=True``, may evict
        numerically-higher classes under page pressure).
        ``deadline_s``: shed the request (empty output, outcome
        ``"shed"``) if still queued this long after submit.
        ``resume_prefix``: tokens this request already emitted
        elsewhere — ``prompt`` must then be the original prompt plus
        those tokens, and the engine prepends them to the output
        instead of re-emitting (the cross-replica resume path: the
        serving-plane router re-queues a dead replica's in-flight
        requests on survivors through this; within one engine,
        preemption builds its resume Requests directly)."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError(f"prompt must be 1-D nonempty, {prompt.shape}")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        if key is not None and self.greedy:
            raise ValueError(
                "per-request key needs a sampling engine (construct "
                "with temperature > 0); a greedy engine never consumes "
                "key streams and would silently ignore it"
            )
        if temperature is not None:
            if self.greedy:
                raise ValueError(
                    "per-request temperature needs a sampling engine "
                    "(construct with temperature > 0); greedy engines "
                    "compile the argmax path only"
                )
            if temperature <= 0.0:
                raise ValueError(
                    f"per-request temperature must be > 0, got "
                    f"{temperature}")
        padded = self._bucket_len(int(prompt.size))  # raises off-ladder
        need = self._pages_for(prompt.size, max_new)
        if need > self.pages_per_seq:
            raise ValueError(
                f"prompt {prompt.size} + budget {max_new} (+ spec "
                f"slack {self.spec_slack}; bucket pad {padded}) needs "
                f"{need} pages > pages_per_seq {self.pages_per_seq}"
            )
        if max(prompt.size + max_new, padded) > self.cfg.max_seq:
            raise ValueError(
                f"prompt {prompt.size} + budget {max_new} (bucket pad "
                f"{padded}) exceeds max_seq {self.cfg.max_seq}"
            )
        sid = self._next_id if seq_id is None else seq_id
        if (sid in self.finished
                or any(r.seq_id == sid for r in self._queue)
                or sid in self._swapped
                or any(b.seq_id == sid for b, _, _ in self._prefetching)
                or any(s.active and s.seq_id == sid
                       for s in self._slots)):
            raise ValueError(
                f"seq_id {sid} already queued/active/finished — outputs "
                "would silently merge under one key"
            )
        self._next_id = max(self._next_id, sid) + 1
        if resume_prefix is not None:
            resume_prefix = np.asarray(resume_prefix, np.int32)
            if resume_prefix.size > prompt.size:
                raise ValueError(
                    f"resume_prefix ({resume_prefix.size} tokens) longer "
                    f"than the prompt ({prompt.size}) that must carry it")
        now = time.perf_counter()
        self._queue.append(Request(prompt, max_new, sid, t_submit=now,
                                   temperature=temperature, key=key,
                                   priority=int(priority),
                                   deadline_s=deadline_s,
                                   resume_prefix=resume_prefix))
        self.stats[sid] = {
            "priority": int(priority), "t_submit": now, "t_first": None,
            "t_finish": None, "tokens": 0, "outcome": None,
            "preemptions": 0, "token_ts": [],
        }
        rtr = reqtracelib.active()
        if rtr is not None:
            rtr.begin_request(sid, now)
        metricslib.get_metrics().gauge("serve.queue_depth").set(
            len(self._queue))
        return sid

    def _queue_order(self) -> list[int]:
        """Queue indices in admission order: priority class first
        (lower number = more important), resumes before fresh arrivals
        within a class (a preempted row's pages were taken FROM it; it
        re-enters ahead of new same-class work), FCFS within that."""
        return sorted(
            range(len(self._queue)),
            key=lambda qi: (self._queue[qi].priority,
                            self._queue[qi].resume_prefix is None, qi))

    def _shed_expired(self) -> None:
        """Admission control, shed side: queued FRESH requests whose
        ``deadline_s`` expired are dropped with an empty output and
        outcome ``"shed"`` (resumes are exempt — their tokens are
        already paid for and preemption guarantees re-admission).
        Host-list bookkeeping only: no device op, nothing dispatched."""
        if not any(req.deadline_s is not None
                   and req.resume_prefix is None
                   for req in self._queue):
            return  # deadline-free traffic: the common fast path
        now = time.perf_counter()
        kept = []
        for req in self._queue:
            if (req.deadline_s is None or req.resume_prefix is not None
                    or now - req.t_submit <= req.deadline_s):
                kept.append(req)
                continue
            self.finished[req.seq_id] = np.zeros((0,), np.int32)
            rec = self.stats.get(req.seq_id)
            if rec is not None:
                rec["outcome"] = "shed"
                rec["t_finish"] = now
            rtr = reqtracelib.active()
            if rtr is not None:
                rtr.finish_request(req.seq_id, now, final="shed")
            self._emit(kind="serve_shed", seq_id=req.seq_id,
                       priority=req.priority,
                       waited_s=now - req.t_submit,
                       deadline_s=req.deadline_s)
            m = metricslib.get_metrics()
            if m.enabled:
                m.counter("serve.shed").inc()
        self._queue = kept
        metricslib.get_metrics().gauge("serve.queue_depth").set(
            len(self._queue))

    def _try_admit(self, overlapped: bool = False) -> int:
        """ONE admission pass per scheduler round: shed, then walk the
        queue in admission order — priority classes first, resumes
        before fresh arrivals within a class, FCFS with skip inside
        that (a large request does not block a small one behind it —
        the documented head-of-line tradeoff) — admitting every
        request that fits. Fresh admissions respect
        ``admit_highwater``: past the mark they back off and stay
        queued (headroom for resumes); resumes bypass it. One shed
        scan and one order sort per ROUND (the admission window is the
        measured bubble; bookkeeping must not inflate it). In a
        private-pages engine admissions only consume slots/pages, so
        a request skipped earlier in the pass cannot become
        admissible later in it and the single sorted walk decides
        exactly what a per-admission re-sort would. With the sharing
        arena that is one-round approximate: a later candidate's
        cache reclaim frees pages, and each admission publishes
        chains that can shrink an earlier-skipped request's private
        need — such a request waits for the next round's pass (it
        keeps its place in the admission order, so nothing starves;
        re-walking the queue per admission would put the trie work
        back in the admission window). Returns the number
        admitted."""
        self._shed_expired()
        # one pass-start stamp: every request seated THIS round closes
        # its queued segment here — the span from pass start to its
        # own dispatch-complete is its share of the admission bubble
        t_pass = (time.perf_counter()
                  if reqtracelib.active() is not None else None)
        order = [self._queue[qi] for qi in self._queue_order()]
        admitted = 0
        for req in order:
            free_slot = next(
                (i for i, s in enumerate(self._slots) if not s.active),
                None)
            if free_slot is None:
                break
            fresh = req.resume_prefix is None
            # PRIVATE pages only: a prefix match maps the rest shared
            # (the sharing arena's capacity win); cache-only pages are
            # reclaimed LRU first when the request would not fit —
            # never the request's own matched chain
            chain = self._memo_match(req)
            need = (self._pages_for(req.prompt.size, req.max_new)
                    - len(chain))
            if self._prefix is not None:
                self._reclaim_cache_pages(need, fresh, keep=chain)
            # ONE admissibility definition (_admissible): the policy
            # _maybe_preempt predicts with must be the one applied here
            if not self._admissible(need, fresh=fresh):
                continue
            # identity-keyed removal BEFORE _admit (whose telemetry
            # reads the queue depth): Request is a value dataclass
            # holding ndarrays, so list.remove/__eq__ would be both
            # ambiguous and wrong here
            self._queue = [r for r in self._queue if r is not req]
            self._admit(free_slot, req, overlapped, chain=chain,
                        t_pass=t_pass)
            admitted += 1
        return admitted

    def _admit(self, slot: int, req: Request, overlapped: bool,
               chain: list[int] | None = None,
               t_pass: float | None = None):
        """Dispatch-only admission: every device op (table upload,
        prefill, first-token pick, cursor seeding) enqueues without a
        host readback, so an in-flight decode chunk is never stalled.
        The first token's readback is deferred to
        :meth:`_resolve_pending` at the loop's next sync point.

        Sharing-aware (``prefix_cache=True``): the longest cached
        prefix chain at this prompt's rung maps READ-ONLY into the
        row's leading table entries (incref, no bytes move, no
        compute), private pages are allocated only for the rest, and
        the prefill computes ONLY the tail (:func:`_tail_prefill_one`
        — bit-identical to the monolithic prefill by the rung-keyed
        parity contract). ``chain`` is the matched chain the caller's
        admissibility math already walked (``_try_admit`` sized
        ``need`` and ran the reclaim against it — re-matching here
        would both repeat the trie walk in the admission window and
        let the two walks drift); the hit/miss observables are folded
        in once, here, where the match actually becomes an admission.
        The match/map decisions are host trie walks; nothing here
        reads a device value."""
        if chain is None:
            chain = self._prefix_match(req.prompt)
        m = len(chain)
        if self._prefix is not None:
            self._prefix.count_match(m)
        need = self._pages_for(req.prompt.size, req.max_new)
        self._incref_pages(chain)
        pages = chain + self._alloc_pages(need - m)
        if self.residency is not None:
            self.residency.register_group(
                req.seq_id, need, need * self._page_nbytes,
                tier="hbm", priority=req.priority)
        row = np.full((self.pages_per_seq,), self.trash, np.int32)
        row[:need] = pages
        self._table[slot] = row
        self.cache["table"] = jnp.asarray(self._table)
        T = int(req.prompt.size)
        padded = self._bucket_len(T)
        prompt = req.prompt
        if padded > T:
            # right-pad to the bucket rung: causality keeps the true
            # prefix exact; the pad K/V is cursor-masked garbage inside
            # pages the row owns, overwritten as the row generates
            prompt = np.concatenate(
                [prompt, np.zeros(padded - T, np.int32)])
        # one-row prefill THROUGH the shared pool: the scatter touches
        # only this row's pages (compiles once per bucket rung)
        one = dict(self.cache)
        # fresh upload from the host mirror, NOT a slice of the device
        # table: a full-range slice can alias the same buffer, and
        # _prefill_one donates its table — an alias would delete the
        # engine's live table with it
        one["table"] = jnp.asarray(self._table[slot:slot + 1])
        M = m * self.page_size
        if m:
            # tail-only prefill: positions [M, padded) computed against
            # the mapped prefix pages; the matched span's compute AND
            # page writes are skipped — the TTFT lever the skip-frac
            # gauge measures
            tail = prompt[M:]
            with metricslib.span("serve.prefill", prompt_len=T,
                                 padded_len=padded, matched=M), \
                    tracelib.compile_watch("serving._tail_prefill_one",
                                           _tail_prefill_one,
                                           padded_len=padded, matched=M):
                logits, out = _tail_prefill_one(
                    self.params, jnp.asarray(tail)[None, :],
                    jnp.int32(T - 1 - M), one,
                    cfg=self.cfg, page_size=self.page_size,
                    n_prefix_pages=m, mesh=self.mesh,
                )
        else:
            with metricslib.span("serve.prefill", prompt_len=T,
                                 padded_len=padded), \
                    tracelib.compile_watch("serving._prefill_one",
                                           _prefill_one,
                                           padded_len=padded):
                logits, out = _prefill_one(
                    self.params, jnp.asarray(prompt)[None, :],
                    jnp.int32(T - 1), one,
                    cfg=self.cfg, page_size=self.page_size,
                    mesh=self.mesh,
                )
        for k, v in out.items():
            if k != "table":
                self.cache[k] = v
        # publish this admission's full-prompt pages (matched chain +
        # newly prefilled) so the NEXT same-rung prompt shares them
        self._insert_prefix(req.prompt, padded, pages)
        self._prefill_total_tokens += T
        self._prefill_skip_tokens += M
        if self.draft_params is not None:
            self.dcache["table"] = jnp.asarray(self._table)
            done = dict(self.dcache)
            done["table"] = jnp.asarray(self._table[slot:slot + 1])
            with tracelib.compile_watch("serving._prefill_one[draft]",
                                        _prefill_one,
                                        padded_len=padded):
                _, dout = _prefill_one(
                    self.draft_params, jnp.asarray(prompt)[None, :],
                    jnp.int32(T - 1), done, cfg=self.draft_cfg,
                    page_size=self.page_size, mesh=self.mesh,
                )
            for k, v in dout.items():
                if k != "table":
                    self.dcache[k] = v
        key = req.key if req.key is not None else self.request_key(
            req.seq_id)
        temp = (req.temperature if req.temperature is not None
                else self.temperature)
        (self.pos, self.limit, self.tokens, self.keys, self.temps,
         first_dev) = _admit_row(
            self.pos, self.limit, self.tokens, self.keys, self.temps,
            logits, key, jnp.float32(max(temp, 1e-6)), slot, T,
            req.max_new, eos_id=self.eos_id, greedy=self.greedy,
            top_k=self.top_k)
        st = self._slots[slot]
        st.seq_id, st.pages, st.prompt_len = req.seq_id, pages, T
        st.budget = req.max_new
        st.out, st.active = [], True
        st.first_dev = first_dev
        st.t_submit = req.t_submit
        st.t_admit = time.perf_counter()
        st.prompt = req.prompt
        st.priority = req.priority
        st.deadline_s = req.deadline_s
        st.temp_override = req.temperature
        st.prefix = ([] if req.resume_prefix is None
                     else [int(t) for t in req.resume_prefix])
        st.padded_len = padded
        st.shared_pages = m
        rec = tracelib.active()
        if rec is not None:
            # all admission device work (table upload, prefill, first-
            # token pick) is now enqueued; the first-token readback in
            # _resolve_pending closes this window. Per-slot SUBTRACK
            # (track=slot+1): overlapped admissions run concurrently
            # with the decode chunk (track 0) by design, and Chrome
            # sync slices on one track must nest
            st.t_dispatch = rec.mark_dispatch(
                "serve.admit", {"seq_id": req.seq_id, "slot": slot,
                                "padded_len": padded,
                                "overlapped": overlapped},
                track=slot + 1)
        self._pending.append(slot)
        self._emit(kind="serve_admit", seq_id=req.seq_id, slot=slot,
                   pages=need, prompt_len=T, padded_len=padded,
                   budget=req.max_new, overlapped=overlapped,
                   free_pages=len(self.free_pages),
                   queued=len(self._queue), priority=req.priority,
                   resumed=req.resume_prefix is not None,
                   matched_tokens=M, shared_pages=m)
        mx = metricslib.get_metrics()
        if mx.enabled:
            mx.gauge("serve.queue_depth").set(len(self._queue))
            mx.gauge("serve.free_pages").set(len(self.free_pages))
            mx.counter("serve.admitted").inc()
            if overlapped:
                mx.counter("serve.admit_overlapped").inc()
            if m:
                mx.counter("serve.prefix_matched_pages").inc(m)
                mx.counter("serve.prefill_skip_tokens").inc(M)
        rtr = reqtracelib.active()
        if rtr is not None:
            # queued (or preempted, for a resume) closed at the pass
            # start; admit_wait covers the host admission work up to
            # dispatch-complete; prefill runs until the first-token
            # readback in _resolve_pending
            rtr.stamp_transition(
                req.seq_id, "admit_wait",
                st.t_admit if t_pass is None else t_pass)
            rtr.stamp_transition(req.seq_id, "prefill")

    def _resolve_pending(self):
        """Host bookkeeping deferred from :meth:`_admit`: read back the
        first tokens (by now computed behind — or overlapped with — the
        decode chunk), stamp TTFT, and finish rows that were done at
        admission (budget 1, or eos as the first token; the device-side
        limit already froze them out of the chunks)."""
        for slot in self._pending:
            st = self._slots[slot]
            first = int(jax.device_get(st.first_dev))
            st.first_dev = None
            # a resumed row's output re-opens with everything it had
            # already emitted before preemption (its prompt carries
            # those tokens, so the device never re-emits them)
            st.out = list(st.prefix) + [first]
            rec = tracelib.active()
            if rec is not None and st.t_dispatch:
                # the readback IS completion: the admission's device
                # work (prefill + first-token pick) is done by now
                rec.mark_complete("serve.admit", st.t_dispatch,
                                  {"seq_id": st.seq_id, "slot": slot},
                                  track=slot + 1)
                st.t_dispatch = 0.0
            now = time.perf_counter()
            rec_s = self.stats.get(st.seq_id)
            resumed = bool(st.prefix)
            if rec_s is not None and rec_s["t_first"] is None:
                rec_s["t_first"] = now
            if rec_s is not None:
                # first-token availability instant (the inter-token
                # digest's window endpoints; a resume stamps only its
                # NEW token — prefix stamps rode the earlier life)
                rec_s.setdefault("token_ts", []).append(now)
            rtr = reqtracelib.active()
            if rtr is not None:
                rtr.stamp_transition(st.seq_id, "decode", now)
            m = metricslib.get_metrics()
            if m.enabled and not resumed:
                # prefill emitted the first token: its readback IS
                # first-token availability (TTFT counted from submit;
                # a resume keeps its ORIGINAL first-token time — the
                # user saw it before the preemption)
                ttft = now - (st.t_submit or now)
                m.histogram("serve.ttft_s").observe(ttft)
                if self.slo is not None:
                    m.histogram(
                        f"serve.ttft_s.p{st.priority}").observe(ttft)
            if (self.eos_id >= 0 and first == self.eos_id) \
                    or st.budget == 1:
                self._finish(slot)
        self._pending.clear()

    # -- completion --------------------------------------------------------

    def _release_slot(self, slot: int):
        """Return a row's pages to the arena and reset its cursors —
        the shared tail of completion AND eviction. The table upload is
        dispatch-only; pos/limit zeroing freezes the row out of future
        chunks (stale keys/temps in an inactive row are never
        consumed). Pages DECREF, never free: a page the prefix index
        or another row still maps stays allocated (the sharing arena's
        one release rule)."""
        st = self._slots[slot]
        self._decref_pages(st.pages)
        self._table[slot] = self.trash
        self.cache["table"] = jnp.asarray(self._table)
        if self.draft_params is not None:
            self.dcache["table"] = jnp.asarray(self._table)
        self._slots[slot] = _Slot()
        self.pos = self.pos.at[slot].set(0)
        self.limit = self.limit.at[slot].set(0)

    def _residency_release(self, seq_id: int) -> None:
        """Drop a row's blocks from the residency accounting (it
        finished, was preempted back to the queue, or migrated away).
        No-op without a manager."""
        if self.residency is not None:
            self.residency.release_group(seq_id)

    def _finish(self, slot: int):
        st = self._slots[slot]
        self._residency_release(st.seq_id)
        self.finished[st.seq_id] = np.asarray(st.out, np.int32)
        self._emit(kind="serve_finish", seq_id=st.seq_id, slot=slot,
                   tokens=len(st.out), pages_freed=len(st.pages))
        now = time.perf_counter()
        rec_s = self.stats.get(st.seq_id)
        if rec_s is not None:
            rec_s["t_finish"] = now
            rec_s["tokens"] = len(st.out)
            rec_s["outcome"] = "ok"
        rtr = reqtracelib.active()
        if rtr is not None:
            rtr.finish_request(st.seq_id, now)
        m = metricslib.get_metrics()
        if m.enabled:
            dt = now - st.t_admit
            m.histogram("serve.per_token_s").observe(
                dt / max(1, len(st.out)))
            if self.slo is not None and rec_s is not None \
                    and rec_s["t_first"] is not None and len(st.out) > 1:
                m.histogram(f"serve.tpot_s.p{st.priority}").observe(
                    (now - rec_s["t_first"]) / (len(st.out) - 1))
            m.counter("serve.finished").inc()
            m.counter("serve.tokens").inc(len(st.out))
            # shared pages don't free with the row — count only what
            # the release will actually return to the arena
            m.gauge("serve.free_pages").set(
                len(self.free_pages) + self._row_freeable_pages(slot))
        self._release_slot(slot)

    # -- preemption --------------------------------------------------------

    def _reserved_prefetch_pages(self) -> int:
        """Pages spoken for by pulls in flight (dispatched host->HBM
        prefetches whose install has not happened yet): admissions and
        preemption must not hand them to someone else, or the staged
        swap-in starves behind the very traffic it yielded to."""
        return sum(b.n_pages for b, _, _ in self._prefetching)

    def _admissible(self, need: int, fresh: bool) -> bool:
        """Would a request needing ``need`` pages admit right now?
        (free slot + free pages + the fresh-admission high-water mark
        — the same three checks :meth:`_try_admit` applies). Pages and
        slots reserved for in-flight prefetch installs are not free —
        and for the high-water math they count as USED: the staged
        swap-in will occupy them at install, and a fresh admission
        that squeaked under the mark meanwhile would breach the
        headroom the mark reserves."""
        free_slots = sum(1 for s in self._slots if not s.active)
        if free_slots <= len(self._prefetching):
            return False
        reserved = self._reserved_prefetch_pages()
        if need > len(self.free_pages) - reserved:
            return False
        if fresh:
            used = self.pool_pages - len(self.free_pages) + reserved
            if used + need > self.admit_highwater * self.pool_pages:
                return False
        return True

    def _can_resume(self, slot: int) -> bool:
        """Is this active row safely evictable? Its resume request
        (prompt = this admission's prompt + tokens generated since)
        must fit the bucket ladder, the per-sequence table width, and
        the arena — a victim whose resume could never re-admit must
        not be evicted. Host bookkeeping only; no device op."""
        st = self._slots[slot]
        if not st.active or slot in self._pending or st.prompt is None:
            return False
        emitted = len(st.out) - len(st.prefix)
        remaining = st.budget - emitted
        if remaining < 1:
            return False  # about to finish; nothing left to resume
        resumed_len = int(st.prompt.size) + emitted
        if self.prompt_buckets is not None \
                and resumed_len > max(self.prompt_buckets):
            return False
        pages = self._pages_for(resumed_len, remaining)
        return pages <= min(self.pages_per_seq, self.pool_pages)

    def _maybe_preempt(self):
        """Preemption policy, decision half (runs at a chunk boundary,
        nothing in flight): when the most urgent waiting request cannot
        be admitted for lack of pages, evict strictly-lower-priority
        victims — lowest class first, most recently admitted first
        within a class (least sunk latency) — until it fits or no
        eligible victim remains. Only the head of the admission order
        is served per round (starvation-free: it stays the head until
        admitted)."""
        # shed first: an already-expired request must not evict a
        # victim only to be dropped by the admission pass right after
        self._shed_expired()
        if not self._queue:
            return
        order = self._queue_order()
        req = self._queue[order[0]]
        # private pages only — the head's match maps the rest shared
        chain = self._memo_match(req)
        need = (self._pages_for(req.prompt.size, req.max_new)
                - len(chain))
        fresh = req.resume_prefix is None
        if self._prefix is not None:
            # cache-only pages are strictly cheaper to free than a
            # victim's eviction-and-resume round trip: reclaim first
            self._reclaim_cache_pages(need, fresh, keep=chain)
        if self._admissible(need, fresh):
            return  # ordinary admission will take it this round
        victims = [
            v for v in sorted(
                (i for i, s in enumerate(self._slots)
                 if s.active and s.priority > req.priority),
                key=lambda i: (-self._slots[i].priority,
                               -self._slots[i].t_admit))
            if self._can_resume(v)
        ]
        # feasibility BEFORE the first eviction: would evicting EVERY
        # eligible victim actually admit the head? Pages held by
        # non-victim rows (same-or-higher priority) still count toward
        # the fresh high-water cap, so a head they keep over the mark
        # must not trigger evictions — the victim's resume bypasses the
        # mark and re-admits the same round, and the next round evicts
        # it again: an evict/re-prefill thrash loop that collapses
        # goodput while the head stays stuck regardless
        # (refcount-aware: a victim's SHARED pages don't free with it)
        freeable = sum(self._row_freeable_pages(v) for v in victims)
        if need > len(self.free_pages) + freeable:
            return
        if fresh:
            used_after = (self.pool_pages - len(self.free_pages)
                          - freeable)
            if used_after + need > self.admit_highwater * self.pool_pages:
                return
        for v in victims:
            if self._admissible(need, fresh):
                break
            self._preempt(v, for_sid=req.seq_id)

    def _preempt(self, slot: int, for_sid: int | None = None):
        """Evict one active row: snapshot its generated tokens and (in
        sampled mode) its per-row key state to host, return its pages
        to the arena, and re-queue it as a RESUME request whose prompt
        is this admission's prompt + the tokens generated since.
        Causality makes the resumed prefill's cache exactly the
        uninterrupted one, and ``_admit_row`` consumes the snapshot key
        with the same split/pick order ``_chunk_step`` would have — so
        the resumed row's remaining tokens are byte-identical to never
        having been preempted (the oracle in tests/test_serving.py)."""
        st = self._slots[slot]
        new = st.out[len(st.prefix):]
        remaining = st.budget - len(new)
        key = None
        if not self.greedy:
            # jaxlint: disable=host-sync-in-dispatch — eviction IS a
            # deliberate sync point: it runs at a chunk boundary with
            # the victim's last chunk already collected, and the key
            # snapshot is the resume contract (np.array COPIES — the
            # device_get view aliases a buffer _chunk_step donates)
            key = jnp.asarray(np.array(jax.device_get(self.keys))[slot])
        # jaxlint: disable=host-sync-in-dispatch — host-list packing,
        # not a device readback: st.out/new are plain Python ints the
        # collected chunks already materialized
        new_arr = np.asarray(new, np.int32)
        prompt = (np.concatenate([st.prompt, new_arr])
                  if new else st.prompt)
        req = Request(prompt, remaining, st.seq_id,
                      t_submit=st.t_submit,
                      temperature=st.temp_override, key=key,
                      priority=st.priority, deadline_s=st.deadline_s,
                      # jaxlint: disable=host-sync-in-dispatch — same
                      # host-list packing as the prompt above
                      resume_prefix=np.asarray(st.out, np.int32))
        rec_s = self.stats.get(st.seq_id)
        if rec_s is not None:
            rec_s["preemptions"] += 1
        rtr = reqtracelib.active()
        if rtr is not None:
            # decode closes; preempted spans the wait for re-admission
            # (the resume's _admit transitions it to admit_wait)
            rtr.stamp_transition(st.seq_id, "preempted")
        self._emit(kind="serve_preempt", seq_id=st.seq_id, slot=slot,
                   tokens_done=len(st.out), remaining=remaining,
                   pages_freed=len(st.pages), priority=st.priority,
                   for_seq_id=for_sid)
        m = metricslib.get_metrics()
        if m.enabled:
            m.counter("serve.preempted").inc()
            m.gauge("serve.free_pages").set(
                len(self.free_pages) + self._row_freeable_pages(slot))
        self._residency_release(st.seq_id)
        self._release_slot(slot)
        self._queue.append(req)
        if m.enabled:
            m.gauge("serve.queue_depth").set(len(self._queue))

    # -- the loop ----------------------------------------------------------

    def _dispatch_chunk(self):
        """Enqueue one ``chunk`` dispatch for the currently active rows
        and return the in-flight handle (participants, their start
        cursors, the un-read token block) — no readback here."""
        # a true COPY, not np.asarray: on CPU that returns a zero-copy
        # view of the device buffer, and _chunk_step DONATES it — an
        # executable that honors the donation (cache-loaded ones do)
        # overwrites the "snapshot" in place with the post-chunk cursors
        # jaxlint: disable=host-sync-in-dispatch — the copy is the PR 2
        # donation-alias fix; it syncs only on the PREVIOUS chunk's
        # cursors, which _collect_chunk already resolved
        pos_start = np.array(self.pos)
        parts = [i for i, s in enumerate(self._slots) if s.active]
        with metricslib.span("serve.decode_dispatch", chunk=self.chunk), \
                tracelib.compile_watch("serving._chunk_step",
                                       _chunk_step, chunk=self.chunk):
            (self.cache, self.pos, self.limit, self.tokens, self.keys,
             out) = _chunk_step(
                self.params, self.cache, self.pos, self.limit,
                self.tokens, self.keys, self.temps,
                cfg=self.cfg, chunk=self.chunk, eos_id=self.eos_id,
                greedy=self.greedy, top_k=self.top_k, mesh=self.mesh,
            )
        rec = tracelib.active()
        t_disp = (rec.mark_dispatch(
            "serve.chunk", {"chunk": self.chunk, "rows": len(parts)})
            if rec is not None else 0.0)
        return parts, pos_start, out, t_disp

    def _collect_chunk(self, inflight):
        parts, pos_start, out, t_disp = inflight
        with metricslib.span("serve.decode_round", chunk=self.chunk):
            out = np.asarray(out)  # (chunk, slots); readback = sync
        rec = tracelib.active()
        if rec is not None and t_disp:
            # readback resolved: the dispatch→completion window is the
            # chunk's device time + queueing, a slice on the device
            # track; host gaps between slices are admission bubbles
            rec.mark_complete("serve.chunk", t_disp,
                              {"chunk": self.chunk, "rows": len(parts)})
        limit_new = np.asarray(self.limit)
        # the chunk's tokens all became host-visible at THIS readback —
        # one shared availability instant (honest: intra-chunk device
        # timing is invisible; the inter-token digest tiles stall
        # segments over the gaps BETWEEN these instants)
        now = time.perf_counter()
        for i in parts:
            st = self._slots[i]
            if not st.active:
                continue
            valid = int(np.clip(limit_new[i] - pos_start[i], 0,
                                self.chunk))
            st.out.extend(int(t) for t in out[:valid, i])
            rec_s = self.stats.get(st.seq_id)
            if rec_s is not None and valid:
                rec_s.setdefault("token_ts", []).extend([now] * valid)
            if pos_start[i] + valid >= limit_new[i]:
                self._finish(i)

    def _dispatch_spec(self):
        """``chunk`` draft-assisted rounds per dispatch: budget/EOS
        truncation happens on device between rounds (_spec_chunk), so
        over-acceptance beyond a limit is discarded there and the
        caches' stale rows get overwritten when the cursor re-crosses
        them (the speculative invariant)."""
        parts = [i for i, s in enumerate(self._slots) if s.active]
        with metricslib.span("serve.spec_dispatch", rounds=self.chunk,
                             gamma=self.gamma), \
                tracelib.compile_watch("serving._spec_chunk",
                                       _spec_chunk, rounds=self.chunk,
                                       gamma=self.gamma):
            (self.cache, self.dcache, self.pos, self.limit, self.tokens,
             self._spec_key, emits, advs) = _spec_chunk(
                self.params, self.draft_params, self.cache, self.dcache,
                self.pos, self.limit, self.tokens, self._spec_key,
                self.temps,
                cfg=self.cfg, dcfg=self.draft_cfg, gamma=self.gamma,
                rounds=self.chunk, eos_id=self.eos_id,
                greedy=self.greedy, top_k=self.top_k, mesh=self.mesh,
            )
        rec = tracelib.active()
        t_disp = (rec.mark_dispatch(
            "serve.spec_chunk",
            {"rounds": self.chunk, "gamma": self.gamma,
             "rows": len(parts)}) if rec is not None else 0.0)
        return parts, None, (emits, advs), t_disp

    def _collect_spec(self, inflight):
        parts, _, (emits, advs), t_disp = inflight
        with metricslib.span("serve.spec_round", rounds=self.chunk,
                             gamma=self.gamma):
            emits = np.asarray(emits)  # (rounds, slots, gamma+1)
            advs = np.asarray(advs)    # (rounds, slots)
        rec = tracelib.active()
        if rec is not None and t_disp:
            rec.mark_complete("serve.spec_chunk", t_disp,
                              {"rounds": self.chunk,
                               "rows": len(parts)})
        pos_np = np.asarray(self.pos)
        limit_np = np.asarray(self.limit)
        now = time.perf_counter()
        for i in parts:
            st = self._slots[i]
            if not st.active:
                continue
            accepted = 0
            for k in range(advs.shape[0]):
                v = int(advs[k, i])
                if v:
                    st.out.extend(int(t) for t in emits[k, i, :v])
                    accepted += v
            rec_s = self.stats.get(st.seq_id)
            if rec_s is not None and accepted:
                rec_s.setdefault("token_ts", []).extend([now] * accepted)
            if pos_np[i] >= limit_np[i]:
                self._finish(i)

    def service_round(self, *, decode: bool = True, chaos_index=None,
                      pre_collect=None) -> dict:
        """ONE scheduler round — the core's unit of work, shared by
        :meth:`ContinuousBatcher.run` and the serving plane's router
        (which interleaves rounds across replicas): chaos probe,
        preemption policy, decode-chunk dispatch (overlap mode:
        FIRST, so admissions enqueue behind it), one admission pass,
        deferred first-token readbacks, collect.

        ``decode=False`` is the PREFILL-ROLE round: admissions run
        (table upload, bucket-padded prefill, first-token pick) but no
        decode chunk is ever dispatched — admitted rows park at their
        first token awaiting :meth:`export_migration`. ``pre_collect``:
        called with ``overlapped`` (True iff a decode chunk is in
        flight) AFTER admissions and BEFORE the chunk readback — the
        plane installs arrived KV migrations here, so the install's
        device work enqueues behind the in-flight chunk exactly like an
        overlapped admission. Returns ``{"admitted", "exposed_s"
        (admission host time with nothing in flight), "stalled" (queue
        waits but nothing admitted and nothing runs — the transport
        decides whether that is a deadlock), "active"}``."""
        if chaos_index is not None and chaoslib.active() is not None:
            chaoslib.maybe_inject("engine_round", chaos_index)
        # fresh round, fresh head-match memo (_memo_match): the memo's
        # validity argument is scoped to one round's mutations
        self._match_memo = None
        if self.preempt:
            self._maybe_preempt()
        if self.residency is not None:
            self.residency.begin_round()
            for si, s in enumerate(self._slots):
                if s.active:
                    self.residency.touch_group(s.seq_id)
                    if self._prefix is not None:
                        # pin-while-shared: a row whose pages another
                        # row maps (refcount >= 2 net of the cache's
                        # own reference) must not page to host while
                        # the reader is resident — the manager's
                        # victim selection skips pinned groups
                        self.residency.pin_group(
                            s.seq_id, not self._row_swappable(si))
            # pulls for swapped rows dispatch BEFORE the decode chunk:
            # the host->HBM copies fly while the chunk computes, and
            # the install lands behind it at the pre_collect position
            self._dispatch_prefetch()
        spec = self.draft_params is not None
        dispatch = self._dispatch_spec if spec else self._dispatch_chunk
        collect = self._collect_spec if spec else self._collect_chunk
        inflight = None
        t_chunk0 = 0.0
        if decode and self.overlap and any(s.active for s in self._slots):
            inflight = dispatch()
            t_chunk0 = time.perf_counter()
        t0 = time.perf_counter()
        admitted = self._try_admit(overlapped=inflight is not None)
        self._resolve_pending()
        exposed_s = 0.0
        stalled = False
        if inflight is None:
            exposed_s = time.perf_counter() - t0
            if decode and any(s.active for s in self._slots):
                inflight = dispatch()
                t_chunk0 = time.perf_counter()
            elif not any(s.active for s in self._slots):
                stalled = (bool(self._queue) and not admitted
                           and not self._swapped
                           and not self._prefetching)
        if self.residency is not None:
            self._install_prefetched(inflight is not None)
        if pre_collect is not None:
            pre_collect(inflight is not None)
        if inflight is not None:
            collect(inflight)
            if self.track_chunk_windows:
                # host-clock (dispatch, readback-resolved) stamps of
                # this chunk — the serving plane intersects migration
                # windows with these to PROVE the KV handoff hid
                # behind decode compute (kv_migration_overlap_frac)
                self.chunk_windows.append(
                    (t_chunk0, time.perf_counter()))
        if self.residency is not None:
            # round boundary: the chunk is collected, nothing in
            # flight — observe this round's prefetch completions, then
            # run the eviction policy (cold + demanded rows page out)
            self._complete_prefetches()
            self._residency_balance()
        return {"admitted": admitted, "exposed_s": exposed_s,
                "stalled": stalled,
                "active": any(s.active for s in self._slots)}

    # -- router-facing load observables ------------------------------------

    @property
    def free_page_count(self) -> int:
        return len(self.free_pages)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def active_count(self) -> int:
        return sum(1 for s in self._slots if s.active)

    def has_work(self) -> bool:
        return (bool(self._queue) or bool(self._swapped)
                or bool(self._prefetching)
                or any(s.active for s in self._slots))

    def request_pages(self, n_pages: int) -> None:
        """External install pressure (the serving-plane router waiting
        to land a migration bundle): ask the residency manager to free
        ``n_pages`` at this round's balance point. No-op without a
        manager — the caller then waits for ordinary completions."""
        self._external_demand = max(self._external_demand, int(n_pages))

    def would_fit(self, prompt_len: int, max_new: int) -> bool:
        """Could this engine EVER serve the request (table width, pool
        size, ladder, max_seq) — the router's placement feasibility
        check, distinct from :meth:`_admissible`'s right-now check."""
        try:
            padded = self._bucket_len(int(prompt_len))
        except ValueError:
            return False
        need = self._pages_for(prompt_len, max_new)
        return (need <= min(self.pages_per_seq, self.pool_pages)
                and max(prompt_len + max_new, padded) <= self.cfg.max_seq)

    # -- migration (the serving plane's KV handoff) ------------------------

    def migration_admissible(self, n_pages: int) -> bool:
        """Could :meth:`install_migration` of an ``n_pages`` bundle
        succeed right now? Free slot + free pages (minus in-flight
        prefetch reservations); migrations bypass the fresh-admission
        high-water mark for the same reason resumes do — their tokens
        are already paid for."""
        free_slots = sum(1 for s in self._slots if not s.active)
        return (free_slots > len(self._prefetching)
                and n_pages <= len(self.free_pages)
                - self._reserved_prefetch_pages()
                and n_pages <= self.pages_per_seq)

    def exportable_slots(self) -> list[int]:
        """Active rows whose first token is resolved and whose budget
        is not yet exhausted — what a prefill-role replica offers the
        router for migration after a ``decode=False`` round."""
        return [i for i, s in enumerate(self._slots)
                if s.active and i not in self._pending]

    def _detach_row(self, slot: int) -> MigrationBundle:
        """Detach one active row into a :class:`MigrationBundle` and
        release its slot/pages — the snapshot half SHARED by
        :meth:`export_migration` (the plane's KV handoff) and the
        residency manager's swap-out (the host-tier eviction): both
        are "this row continues elsewhere", they differ only in where
        the pages go and in the bookkeeping around them.

        Runs at a chunk boundary with the row's device work resolved
        (a prefill-role engine never has a chunk in flight), so the
        cursor/key snapshot is a DELIBERATE sync point — the same
        contract as preemption's snapshot, and the same copy
        discipline: ``np.array`` COPIES, because the device_get view
        aliases buffers a later ``_chunk_step`` donates. The KV pages
        are GATHERED device-side (``pool[idx]`` — a new buffer, no
        host readback of K/V anywhere on the in-process path)."""
        st = self._slots[slot]
        if not st.active or slot in self._pending or st.prompt is None:
            raise ValueError(f"slot {slot} has no exportable row")
        if self.draft_params is not None:
            raise ValueError(
                "draft-assisted engines do not migrate: the draft "
                "cache's row state would have to move too")
        # jaxlint: disable=host-sync-in-dispatch — the export snapshot
        # IS a deliberate sync point at a chunk boundary (the resume
        # contract, same as _preempt's key snapshot); np.array COPIES
        pos = int(np.array(jax.device_get(self.pos))[slot])
        # jaxlint: disable=host-sync-in-dispatch — same snapshot
        limit = int(np.array(jax.device_get(self.limit))[slot])
        # jaxlint: disable=host-sync-in-dispatch — same snapshot
        token = int(np.array(jax.device_get(self.tokens))[slot])
        # jaxlint: disable=host-sync-in-dispatch — same snapshot
        key = np.array(jax.device_get(self.keys))[slot].copy()
        # jaxlint: disable=host-sync-in-dispatch — same snapshot
        temp = float(np.array(jax.device_get(self.temps))[slot])
        idx = jnp.asarray(st.pages, dtype=jnp.int32)
        payload = {
            name: tuple(pool[idx] for pool in pools)
            for name, pools in self.cache.items() if name != "table"
        }
        rec_s = self.stats.get(st.seq_id)
        bundle = MigrationBundle(
            seq_id=st.seq_id, prompt=st.prompt, out=list(st.out),
            prefix=list(st.prefix), budget=st.budget, pos=pos,
            limit=limit, token=token, key=key, temp=temp,
            temp_override=st.temp_override, priority=st.priority,
            deadline_s=st.deadline_s, t_submit=st.t_submit,
            t_first=(rec_s or {}).get("t_first"),
            preemptions=int((rec_s or {}).get("preemptions") or 0),
            n_pages=len(st.pages), page_size=self.page_size,
            pages_payload=payload,
            # prefix-resolution metadata: the leading full-prompt pages
            # hold pure-prompt K/V computed at this rung — a sharing
            # destination with the same chain cached maps its own pages
            # for that span instead of installing (byte-exact either
            # way, docs/prefix_cache.md)
            rung=int(st.padded_len),
            prefix_len=((st.prompt_len // self.page_size)
                        * self.page_size if st.padded_len else 0),
        )
        self._release_slot(slot)
        return bundle

    def export_migration(self, slot: int) -> MigrationBundle:
        """Detach one active row for a CROSS-ENGINE handoff — the
        donor half of the serving plane's KV migration (see
        :meth:`_detach_row` for the snapshot contract). The row's
        stats outcome closes as ``"migrated"``: its story continues in
        another engine's table."""
        bundle = self._detach_row(slot)
        rec_s = self.stats.get(bundle.seq_id)
        if rec_s is not None:
            rec_s["outcome"] = "migrated"
        rtr = reqtracelib.active()
        if rtr is not None:
            # decode closes into an open `migrating` segment; the copy
            # rides the bundle so the installer closes it on ITS side
            bundle.segments = rtr.export_history(bundle.seq_id)
        self._residency_release(bundle.seq_id)
        self._emit(kind="serve_migrate_out", seq_id=bundle.seq_id,
                   slot=slot, pages=bundle.n_pages,
                   tokens_done=len(bundle.out))
        m = metricslib.get_metrics()
        if m.enabled:
            m.counter("serve.migrated_out").inc()
        return bundle

    def export_swapped(self, seq_id: int) -> MigrationBundle:
        """Export a row currently parked in the HOST tier — the
        cross-TIER migration path: an exported bundle gathers pages
        from wherever they live, so the plane can migrate a row the
        residency manager had swapped out without first paging it back
        in. The payload normalizes to host numpy (the wire codec's
        form; it was already host-resident — a deliberate readback of
        bytes the device no longer owns)."""
        if self.residency is None or seq_id not in self._swapped:
            raise ValueError(
                f"seq_id {seq_id} is not swapped out of this engine")
        bundle = self._swapped.pop(seq_id)
        payload = {
            name: tuple(np.asarray(jax.device_get(a)) for a in arrs)
            for name, arrs in bundle.pages_payload.items()
        }
        bundle = replace(bundle, pages_payload=payload)
        rec_s = self.stats.get(seq_id)
        if rec_s is not None:
            rec_s["outcome"] = "migrated"
        rtr = reqtracelib.active()
        if rtr is not None:
            # the open `swapped_out` segment closes into `migrating`
            bundle.segments = rtr.export_history(seq_id)
        self._residency_release(seq_id)
        self._emit(kind="serve_migrate_out", seq_id=seq_id, slot=-1,
                   pages=bundle.n_pages, tokens_done=len(bundle.out),
                   tier="host")
        m = metricslib.get_metrics()
        if m.enabled:
            m.counter("serve.migrated_out").inc()
        return bundle

    def install_migration(self, bundle: MigrationBundle) -> int:
        """Continue a migrated row in THIS engine — the receiver half
        of the KV handoff. Dispatch-only: the table upload, the page
        scatters (:func:`_install_pages`, donated pools), and the
        cursor/key seeding all enqueue without a host readback, so an
        in-flight decode chunk is never stalled (the plane calls this
        from ``service_round``'s ``pre_collect`` hook — behind the
        chunk, the overlapped-admission discipline). Returns the slot.

        Byte-exactness: the installed cursors/key/temp are the donor's
        post-admission state and the KV pages are numerically
        identical, so the next ``_chunk_step`` consumes exactly what
        the donor's would have — the migrated row's remaining tokens
        equal a colocated engine's (the disaggregation oracle)."""
        if self.draft_params is not None:
            raise ValueError("draft-assisted engines do not migrate")
        if bundle.page_size != self.page_size:
            raise ValueError(
                f"page_size mismatch: bundle {bundle.page_size} vs "
                f"engine {self.page_size} — pools are not layout-"
                "compatible across different page sizes")
        if not self.migration_admissible(bundle.n_pages):
            raise ValueError(
                f"migration of {bundle.n_pages} page(s) not admissible "
                f"(free slots {sum(1 for s in self._slots if not s.active)}, "
                f"free pages {len(self.free_pages)})")
        if bundle.seq_id in self.finished \
                or any(r.seq_id == bundle.seq_id for r in self._queue) \
                or bundle.seq_id in self._swapped \
                or any(b.seq_id == bundle.seq_id
                       for b, _, _ in self._prefetching) \
                or any(s.active and s.seq_id == bundle.seq_id
                       for s in self._slots):
            raise ValueError(
                f"seq_id {bundle.seq_id} already known to this engine")
        slot = self._attach_row(bundle)
        if self.residency is not None:
            self.residency.register_group(
                bundle.seq_id, bundle.n_pages,
                bundle.n_pages * self._page_nbytes,
                tier="hbm", priority=bundle.priority)
        self._emit(kind="serve_migrate_in", seq_id=bundle.seq_id,
                   slot=slot, pages=bundle.n_pages, seq=bundle.seq,
                   tokens_done=len(bundle.out))
        m = metricslib.get_metrics()
        if m.enabled:
            m.counter("serve.migrated_in").inc()
            m.gauge("serve.free_pages").set(len(self.free_pages))
        return slot

    def _attach_row(self, bundle: MigrationBundle) -> int:
        """Seat a detached row in this engine — the dispatch-only
        install half SHARED by :meth:`install_migration` (cross-engine
        handoff) and the residency manager's swap-in (the prefetched
        host-tier row returning to HBM). Admissibility is the
        CALLER's to have checked. Returns the slot."""
        slot = next(i for i, s in enumerate(self._slots) if not s.active)
        # jaxlint: disable=host-sync-in-dispatch — host-list packing of
        # the wire bundle's prompt, not a device readback (the same
        # contract as _preempt's resume-Request packing)
        prompt = np.asarray(bundle.prompt, np.int32)
        # prefix resolution (sharing destinations): the bundle names
        # the page-aligned span of pure-prompt K/V and the rung it was
        # computed at — when this engine's radix index has that exact
        # chain, the span maps to the CACHED pages (incref; bitwise
        # the same bytes, same-rung determinism) and only the rest of
        # the payload installs. A cold cache materializes everything:
        # byte-exact either way.
        resolved: list[int] = []
        if self._prefix is not None and bundle.rung \
                and bundle.prefix_len:
            resolved = self._prefix.match(
                prompt[:bundle.prefix_len], bundle.rung,
                max_pages=bundle.prefix_len // self.page_size)
        m = len(resolved)
        self._incref_pages(resolved)
        pages = resolved + self._alloc_pages(bundle.n_pages - m)
        row = np.full((self.pages_per_seq,), self.trash, np.int32)
        row[:bundle.n_pages] = pages
        self._table[slot] = row
        self.cache["table"] = jnp.asarray(self._table)
        if m < bundle.n_pages:
            idx = jnp.asarray(pages[m:], dtype=jnp.int32)
            for name, pools in list(self.cache.items()):
                if name == "table":
                    continue
                payload = bundle.pages_payload[name]
                self.cache[name] = tuple(
                    _install_pages(
                        pool, idx,
                        jnp.asarray(pl)[m:] if m else jnp.asarray(pl))
                    for pool, pl in zip(pools, payload))
        self.pos = self.pos.at[slot].set(jnp.int32(bundle.pos))
        self.limit = self.limit.at[slot].set(jnp.int32(bundle.limit))
        self.tokens = self.tokens.at[slot].set(jnp.int32(bundle.token))
        self.keys = self.keys.at[slot].set(
            jnp.asarray(bundle.key, jnp.uint32))
        self.temps = self.temps.at[slot].set(jnp.float32(bundle.temp))
        st = self._slots[slot]
        st.seq_id = bundle.seq_id
        st.pages = pages
        st.prompt_len = int(prompt.size)
        st.budget = bundle.budget
        st.out = list(bundle.out)
        st.prefix = list(bundle.prefix)
        st.active = True
        st.t_submit = bundle.t_submit
        st.t_admit = time.perf_counter()
        st.prompt = prompt
        st.priority = bundle.priority
        st.deadline_s = bundle.deadline_s
        st.temp_override = bundle.temp_override
        st.padded_len = int(bundle.rung)
        st.shared_pages = m
        if bundle.rung:
            # warm this engine's index with the installed chain: the
            # next same-rung prompt sharing the prefix maps it here
            self._insert_prefix(prompt, int(bundle.rung), pages)
        prior = self.stats.get(bundle.seq_id)
        self.stats[bundle.seq_id] = {
            "priority": bundle.priority, "t_submit": bundle.t_submit,
            "t_first": bundle.t_first, "t_finish": None,
            "tokens": 0, "outcome": None,
            "preemptions": bundle.preemptions,
            # token availability stamps survive a LOCAL swap-out/in (the
            # gap across the stall is exactly what the inter-token
            # digest tiles); a migration install starts empty — the
            # donor's stamps are engine-local wall clock, not wire state
            "token_ts": list(prior.get("token_ts") or [])
            if prior is not None else [],
        }
        rtr = reqtracelib.active()
        if rtr is not None:
            # the round-18 half of "starts fresh": t_submit/t_first/
            # preemptions survived the handoff since round 14 (the
            # stats rebuild above), but the lifecycle history did not
            # — adopt the bundle's carried segments (swap-in bundles
            # carry None and keep the LOCAL history; a legacy wire
            # artifact decoded to one untracked span) and open decode
            rtr.install_history(bundle.seq_id, bundle.segments,
                                t=st.t_admit,
                                t_submit=bundle.t_submit)
        return slot


    # -- tiered residency (HBM <-> host paging, memory/residency.py) --------

    def _swap_out(self, slot: int) -> None:
        """Page one active row out to the HOST tier: detach it (the
        :meth:`_detach_row` chunk-boundary snapshot — pages gathered
        device-side, cursors/key to host, slot + HBM pages freed) and
        move the gathered payload to host memory through the manager
        (its ``mem.evict`` window; async on a real pinned-host tier).
        The row is NOT re-prefilled on return — its KV bytes come back
        exactly, which is why swap is strictly cheaper than preemption
        and byte-exactness is free."""
        st = self._slots[slot]
        sid = st.seq_id
        bundle = self._detach_row(slot)
        host_payload = self.residency.push_payload(
            bundle.pages_payload,
            attrs={"seq_id": sid, "pages": bundle.n_pages})
        self._swapped[sid] = replace(bundle,
                                     pages_payload=host_payload)
        rtr = reqtracelib.active()
        if rtr is not None:
            rtr.stamp_transition(sid, "swapped_out")
        self.residency.retier_group(sid, "host")
        self._emit(kind="serve_swap_out", seq_id=sid, slot=slot,
                   pages=bundle.n_pages, tokens_done=len(bundle.out),
                   free_pages=len(self.free_pages))
        m = metricslib.get_metrics()
        if m.enabled:
            m.counter("serve.swapped_out").inc()
            m.gauge("serve.free_pages").set(len(self.free_pages))

    def _dispatch_prefetch(self) -> None:
        """Dispatch host->HBM pulls for swapped rows that will fit —
        BEFORE the round's decode chunk, so the transfer flies under
        it (the PR 2 overlapped-admission / PR 9 migration
        discipline). Admission order: priority class first, swap-out
        order (FIFO) within a class, with skip — a big parked row must
        not starve smaller ones behind it. Pulled pages/slots are
        RESERVED (:meth:`_reserved_prefetch_pages`) until the install
        lands in ``pre_collect``."""
        if not self._swapped:
            return
        free_pages = (len(self.free_pages)
                      - self._reserved_prefetch_pages())
        free_slots = (sum(1 for s in self._slots if not s.active)
                      - len(self._prefetching))
        # a STRICTLY more urgent queued class outranks the swap-in: the
        # freed arena goes to admission this round, not to pulling a
        # less important row back (same class: the swapped row wins —
        # its tokens are already paid for, the resume-before-fresh rule)
        q_min = min((r.priority for r in self._queue), default=None)
        # the manager's fitted prefetch depth (autofit): cap in-flight
        # pulls so exposed transfers never stack — None = unlimited,
        # the pre-fit behavior
        depth = getattr(self.residency, "prefetch_depth", None)
        for sid, bundle in sorted(self._swapped.items(),
                                  key=lambda kv: kv[1].priority):
            if depth is not None and len(self._prefetching) >= depth:
                break
            if free_slots < 1:
                break
            if q_min is not None and q_min < bundle.priority:
                break
            if bundle.n_pages > free_pages:
                continue
            rtr = reqtracelib.active()
            if rtr is not None:
                # stamped BEFORE the pull dispatch so an injected
                # slow_host_transfer lands inside prefetch_wait — the
                # chaos-attribution teeth contract
                rtr.stamp_transition(sid, "prefetch_wait")
            payload, handle = self.residency.pull_payload(
                bundle.pages_payload,
                attrs={"seq_id": sid, "pages": bundle.n_pages})
            self._prefetching.append((bundle, payload, handle))
            del self._swapped[sid]
            free_pages -= bundle.n_pages
            free_slots -= 1
            self._emit(kind="serve_prefetch", seq_id=sid,
                       pages=bundle.n_pages)

    def _install_prefetched(self, overlapped: bool) -> None:
        """Seat arrived prefetches back into the arena — the
        ``pre_collect`` position: BEHIND the in-flight decode chunk
        when there is one (``overlapped``), exactly like an overlapped
        admission or a migration install. A bundle that cannot seat
        yet (its reserved slot/pages raced an admission) stays staged
        for the next round — its device payload keeps."""
        if not self._prefetching:
            return
        still = []
        for bundle, payload, handle in self._prefetching:
            free_slots = sum(1 for s in self._slots if not s.active)
            if free_slots < 1 or bundle.n_pages > len(self.free_pages):
                still.append((bundle, payload, handle))
                continue
            slot = self._attach_row(
                replace(bundle, pages_payload=payload))
            self.residency.retier_group(bundle.seq_id, "hbm")
            self._installed_prefetch.append((bundle, handle))
            self._emit(kind="serve_swap_in", seq_id=bundle.seq_id,
                       slot=slot, pages=bundle.n_pages,
                       overlapped=overlapped)
            m = metricslib.get_metrics()
            if m.enabled:
                m.counter("serve.swapped_in").inc()
        self._prefetching = still

    def _complete_prefetches(self) -> None:
        """Close this round's installed prefetch windows at an
        OBSERVED completion and fold their overlap against the decode
        chunk windows into the manager's ``prefetch_overlap_frac`` —
        the Perfetto-visible proof that the pull hid under the chunk."""
        if not self._installed_prefetch:
            return
        # jaxlint: disable=host-sync-in-dispatch — completion
        # measurement at the round boundary (the chunk readback already
        # happened); the window must not close before the install's
        # device work it claims to cover has finished
        jax.block_until_ready(self.temps)
        # NON-destructive filter: on a plane replica the router's
        # migration-overlap accounting prunes and reads this same
        # deque — popping here would delete windows its still-open
        # migrations intersect (and vice versa would understate the
        # gated overlap fractions). The deque's maxlen bounds memory.
        floor = min(h[3] for _, h in self._installed_prefetch)
        windows = [w for w in self.chunk_windows if w[1] >= floor]
        for _bundle, handle in self._installed_prefetch:
            self.residency.complete_pull(handle, chunk_windows=windows)
        self._installed_prefetch.clear()

    def _residency_balance(self) -> None:
        """Eviction decision, end of round (chunk collected, nothing
        in flight — the same boundary preemption snapshots at): free
        enough HBM for the most urgent DEMAND — the head queued
        request that could not admit, the oldest swapped row waiting
        its turn back in, or router-signaled install pressure
        (:meth:`request_pages`) — by paging policy-chosen victims to
        host; then proactively page out whatever the policy calls cold
        (``ColdAfterNPolicy``). This is how ``free_pages == 0`` became
        a policy knob instead of a refusal."""
        r = self.residency
        avail = len(self.free_pages) - self._reserved_prefetch_pages()
        # pages a victim would ACTUALLY free: shared pages stay with
        # their other readers / the prefix index, so the planning
        # credit uses the refcount-aware count where a slot exists
        slot_of = {s.seq_id: i for i, s in enumerate(self._slots)
                   if s.active}
        sizes = {g.group: (self._row_freeable_pages(slot_of[g.group])
                           if g.group in slot_of else g.n_blocks)
                 for g in r.groups("hbm")}
        victims: list = []

        def planned_avail():
            # pages already slated to free by THIS pass's earlier
            # picks count toward later demands — without the credit,
            # co-occurring demands over-evict and the surplus victims
            # pay a gratuitous host round trip each
            return avail + sum(sizes.get(v, 0) for v in victims)

        # (a) router-signaled install pressure: any victim class
        demand = self._external_demand
        self._external_demand = 0
        if demand > planned_avail():
            victims += r.victims(demand - planned_avail(),
                                 exclude=victims)
        # (b) the head queued request that cannot admit: it may only
        # displace STRICTLY less urgent residents (the preemption
        # victim rule, paging instead of re-prefilling) — a same-class
        # arrival waits for completions, exactly as it would without a
        # manager, so there is no evict/pull-back thrash loop
        if self._queue:
            req = self._queue[self._queue_order()[0]]
            need = self._request_need(req)
            fresh = req.resume_prefix is None
            if not self._admissible(need, fresh=fresh):
                # size the eviction to the BINDING constraint of the
                # _admissible check that failed: raw pages, and — for
                # fresh heads — the admit_highwater cap too (evicting
                # only to the page shortfall would leave a
                # highwater-blocked head queued while the victims paid
                # the host round trip for nothing)
                shortfall = need - planned_avail()
                if fresh:
                    # mirror _admissible's high-water accounting:
                    # reserved prefetch pages count as used, pages
                    # already slated to free this pass do not
                    used = (self.pool_pages - len(self.free_pages)
                            + self._reserved_prefetch_pages()
                            - (planned_avail() - avail))
                    hw_cap = self.admit_highwater * self.pool_pages
                    # host float math (math.ceil of plain ints/floats,
                    # no device value anywhere near it)
                    shortfall = max(shortfall,
                                    math.ceil(used + need - hw_cap))
                free_slots = (sum(1 for s in self._slots
                                  if not s.active)
                              - len(self._prefetching))
                if shortfall <= 0 and free_slots < 1:
                    # the binding failure is the SLOT, not pages: any
                    # single victim frees a whole slot (its pages ride
                    # along) — without this a slot-bound urgent head
                    # waited behind plentiful pages it could not use
                    shortfall = 1
                if shortfall > 0:
                    victims += r.victims(shortfall, exclude=victims,
                                         min_priority=req.priority + 1)
        # (c) the next swapped row due back in (priority class first,
        # swap-out order within it — sorted is stable over insertion):
        # rotation within same-or-less-urgent classes, so a parked row
        # never displaces a more important resident
        if self._swapped and not victims:
            head = sorted(self._swapped.values(),
                          key=lambda b: b.priority)[0]
            if head.n_pages > avail:
                victims += r.victims(head.n_pages - avail,
                                     exclude=victims,
                                     min_priority=head.priority)
        cold = r.cold_groups(exclude=victims)
        for sid in victims + cold:
            slot = next((i for i, s in enumerate(self._slots)
                         if s.active and s.seq_id == sid), None)
            if slot is None or slot in self._pending:
                continue
            if not r.can_host(len(self._slots[slot].pages)):
                # earlier picks in THIS pass consumed the host tier's
                # remaining room — skip, never raise mid-balance
                continue
            if sid in cold and sid not in victims \
                    and sum(1 for s in self._slots if s.active) <= 1:
                # proactive cold paging never empties the arena: one
                # row keeps decoding, so next round's pulls still have
                # a chunk to hide under (demand evictions are exempt —
                # their consumer needs the pages regardless)
                continue
            self._swap_out(slot)


class ContinuousBatcher(EngineCore):
    """The single-process serving engine: :class:`EngineCore` plus the
    classic submission transport — ``submit()`` requests, then
    :meth:`run` until everything drains. The serving plane drives the
    same core through its router instead (one EngineCore per replica);
    this class exists so the single-process path keeps its pre-split
    surface byte-identically."""

    def run(self, *, arrivals=None, max_rounds: int | None = None):
        """Serve until queue, slots, and (open-loop) arrivals drain.
        Returns ``finished``: {seq_id: np.ndarray of emitted tokens
        (<= max_new; ends at eos_id when enabled)}.

        Loop shape (``overlap=True``): DISPATCH the chunk for the rows
        already running, then do this round's admissions behind it —
        the table uploads, bucket-padded prefills, and first-token
        picks all enqueue while the chunk executes, and the chunk's
        readback is the sync point that also resolves them. Admission
        host time with no decode in flight (the first wave, or an
        admission-only iteration) is the ADMISSION BUBBLE; its fraction
        of the run lands in ``last_bubble_frac`` and the
        ``serve.admit_bubble_frac`` gauge. ``overlap=False`` keeps the
        serial order (admit, then decode) — the measurable baseline.

        ``arrivals``: OPEN-loop traffic — ``(t_rel_s, submit_kwargs)``
        pairs; each is submitted once the run clock passes its arrival
        instant (``harness/loadgen.py`` schedules replay this way —
        see ``benchmarks/bench_serving.run_scenario``). The loop idles
        in bounded sleeps when nothing is servable but arrivals remain:
        open-loop means traffic comes on the USERS' clock, so overload
        builds queues (and sheds / preempts) instead of slowing the
        offered load. ``max_rounds``: return after this many scheduler
        rounds — state parks at a chunk boundary and a later ``run()``
        continues (the staged-scenario and preemption-test handle); a
        bounded run never idle-waits for a future arrival (undelivered
        arrivals are dropped — re-pass them to the continuing call).

        Robustness hooks per round: the chaos injector's
        ``engine_round`` site fires first (a seeded stalled-host fault
        pauses the real loop), then the preemption policy runs at the
        chunk boundary (nothing in flight), then the ordinary
        dispatch/admit/collect round."""
        t_run0 = time.perf_counter()
        t_exposed = 0.0
        pending_arrivals = (deque(sorted(arrivals, key=lambda a: a[0]))
                            if arrivals else None)
        chaos_on = chaoslib.active() is not None
        rounds = 0
        while True:
            if pending_arrivals:
                now_rel = time.perf_counter() - t_run0
                while pending_arrivals \
                        and pending_arrivals[0][0] <= now_rel:
                    t_arr, kw = pending_arrivals.popleft()
                    sid = self.submit(**kw)
                    # the request entered on the SCHEDULE's clock, not
                    # when the loop got around to draining it: TTFT,
                    # deadlines, and the gated goodput must charge the
                    # queueing delay the user actually experienced
                    # (the drain can lag a whole chunk round or an
                    # injected stall behind the arrival instant)
                    t_abs = t_run0 + t_arr
                    self._queue[-1].t_submit = t_abs
                    self.stats[sid]["t_submit"] = t_abs
                    rtr = reqtracelib.active()
                    if rtr is not None:
                        # the queued segment starts where t_submit
                        # does, or the drain lag would finalize as a
                        # leading untracked gap
                        rtr.restamp_submit(sid, t_abs)
            if not self.has_work():
                if not pending_arrivals:
                    break
                if max_rounds is not None:
                    # a bounded run parks at the chunk boundary — it
                    # must not block idling for a future arrival
                    break
                # open-loop idle: nothing servable until the next
                # arrival — wait on the schedule's clock, boundedly
                wait = pending_arrivals[0][0] - (time.perf_counter()
                                                 - t_run0)
                time.sleep(min(max(wait, 0.0), 0.005))
                continue
            if max_rounds is not None and rounds >= max_rounds:
                break
            rounds += 1
            r = self.service_round(
                chaos_index=rounds - 1 if chaos_on else None)
            t_exposed += r["exposed_s"]
            if r["stalled"]:
                raise RuntimeError(
                    "serving deadlock: waiting requests but no "
                    "admissible slot/pages (pool too small for "
                    "the smallest waiting request, or "
                    "admit_highwater leaves it no headroom)"
                )
        total = time.perf_counter() - t_run0
        if self.residency is not None:
            self.residency.drain()  # close any open mem.evict windows
        self.last_bubble_frac = (t_exposed / total) if total > 0 else 0.0
        self._serve_s += total
        m = metricslib.get_metrics()
        if m.enabled:
            m.gauge("serve.admit_bubble_frac").set(self.last_bubble_frac)
            m.gauge("serve.prefill_compiles").set(prefill_cache_size())
            if self._prefix is not None:
                m.gauge("serve.prefill_skip_frac").set(
                    self.prefill_skip_frac)
        if self.slo is not None:
            # goodput (SLO-attained tok/s) lands NEXT TO raw tok/s —
            # the whole point of declaring targets; the base is the
            # engine's cumulative serve time so re-used engines stay
            # consistent across waves
            self.last_slo = slolib.attainment(self.stats, self.slo,
                                              self._serve_s)
            if m.enabled:
                tot = self.last_slo["total"]
                m.gauge("serve.tok_s").set(tot["tok_s"])
                m.gauge("serve.goodput_tok_s").set(tot["goodput_tok_s"])
        return self.finished
