"""Known-clean: the blessed key disciplines — thread the key through
split, fold_in distinct stream ids, re-split inside loops."""

import jax


def threaded(key):
    key, sub = jax.random.split(key)
    a = jax.random.normal(sub, (4,))
    key, sub = jax.random.split(key)
    b = jax.random.uniform(sub, (4,))
    return a + b


def fanout(base, n):
    # fold_in of distinct data into one base key is the sanctioned
    # fan-out (serving.request_key)
    return [jax.random.fold_in(base, i) for i in range(n)]


def loop_resplit(key, n):
    outs = []
    for _ in range(n):
        key, sub = jax.random.split(key)
        outs.append(jax.random.normal(sub, (2,)))
    return outs
