"""Known-clean: non-overlapping TRACK_BANDS registry, every module
unpacks its base/width via ``track_band()``, and the one literal
``track=`` argument sits inside a declared band. Zero findings
expected."""

TRACK_BANDS: dict[str, tuple[int, int]] = {
    "decode": (0, 1),
    "migration": (64, 8),
    "spinup": (72, 8),
}


def track_band(name):
    return TRACK_BANDS[name]


MIG_TRACK_BASE, MIG_TRACKS = track_band("migration")


def mark(rec, slot, t0):
    rec.mark_dispatch("decode", t0, track=0)
    rec.mark_dispatch("migrate", t0, track=MIG_TRACK_BASE + slot)
