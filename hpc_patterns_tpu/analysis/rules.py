"""The jaxlint rule set: five hazard classes this repo has hit or is
one typo away from.

Each rule is a pure-``ast`` visitor over one module (cross-module
resolution is deliberately out of scope: every hazard below is visible
— and was introduced — within a single file). Canonical-name matching
goes through :meth:`ModuleInfo.resolve`, so ``np``/``numpy`` and
``jnp``/``jax.numpy`` spellings are equivalent.

Catalog (docs/analysis.md has the worked examples):

- ``donation-alias``       — zero-copy host view live across a call
                             that donates the viewed buffer (the PR 2
                             ``_dispatch_chunk`` bug, verbatim)
- ``host-sync-in-dispatch``— host readback/sync inside a
                             dispatch-critical function
- ``recompile-hazard``     — ``jax.jit`` built per call / per loop
                             iteration; fresh containers as static args
- ``prng-key-reuse``       — one key consumed by two traced uses with
                             no ``split``/``fold_in`` between
- ``tracer-leak``          — traced intermediates assigned to
                             ``self.*``/globals inside a jitted body
"""

from __future__ import annotations

import ast
from typing import Iterable

from hpc_patterns_tpu.analysis.core import (
    AnalysisConfig,
    Finding,
    ModuleInfo,
    Rule,
    register,
)

# calls returning a zero-copy host view of their argument (on CPU, and
# for np.asarray/__array__ whenever XLA can hand back the host buffer)
_VIEW_CALLS = frozenset({"numpy.asarray", "memoryview"})
# jax.random calls that CONSUME the key passed as their first argument.
# fold_in is exempt: folding distinct data into one base key is the
# documented fan-out pattern (serving.request_key); PRNGKey/key CREATE.
_KEY_EXEMPT = frozenset({
    "fold_in", "PRNGKey", "key", "clone", "key_data", "wrap_key_data",
    "key_impl", "default_prng_impl",
})
_JIT_NAMES = frozenset({
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
})


def _func_name(mod: ModuleInfo, call: ast.Call) -> str | None:
    return mod.resolve(call.func)


def _is_jit_constructor(mod: ModuleInfo, call: ast.Call) -> bool:
    """``jax.jit(...)`` or ``partial(jax.jit, ...)`` (pjit included)."""
    name = _func_name(mod, call)
    if name in _JIT_NAMES:
        return True
    if name == "functools.partial" and call.args:
        return mod.resolve(call.args[0]) in _JIT_NAMES
    return False


def _int_tuple(node: ast.AST) -> tuple[int, ...] | None:
    """Literal int / tuple-or-list-of-ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _str_tuple(node: ast.AST) -> tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            elt.value for elt in node.elts
            if isinstance(elt, ast.Constant)
            and isinstance(elt.value, str)
        )
    return ()


def _jit_call_config(mod: ModuleInfo, call: ast.Call
                     ) -> dict[str, tuple]:
    """donate_argnums/donate_argnames/static_argnames literals from a
    jit constructor call (works for the ``partial(jax.jit, ...)`` form
    too — keywords live on the partial)."""
    out: dict[str, tuple] = {}
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums = _int_tuple(kw.value)
            if nums is not None:
                out["donate_argnums"] = nums
        elif kw.arg == "donate_argnames":
            out["donate_argnames"] = _str_tuple(kw.value)
        elif kw.arg == "static_argnames":
            out["static_argnames"] = _str_tuple(kw.value)
    return out


def _donor_table(mod: ModuleInfo) -> dict[str, dict[str, tuple]]:
    """name -> jit config for every donating callable visible in this
    module: decorated defs and ``name = jax.jit(f, donate_...)``."""
    donors: dict[str, dict[str, tuple]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _is_jit_constructor(
                        mod, dec):
                    cfg = _jit_call_config(mod, dec)
                    if "donate_argnums" in cfg or "donate_argnames" in cfg:
                        donors[node.name] = cfg
        elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call) and _is_jit_constructor(
                    mod, node.value):
            cfg = _jit_call_config(mod, node.value)
            if "donate_argnums" in cfg or "donate_argnames" in cfg:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        donors[tgt.id] = cfg
    return donors


def _functions(tree: ast.AST) -> Iterable[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _loop_ancestors(mod: ModuleInfo, node: ast.AST) -> set[int]:
    """ids of the For/While nodes enclosing ``node``."""
    out: set[int] = set()
    cur = mod.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.While)):
            out.add(id(cur))
        cur = mod.parents.get(cur)
    return out


@register
class DonationAliasRule(Rule):
    """The PR 2 bug class: ``v = np.asarray(x)`` is (on CPU, and
    whenever XLA can avoid the copy) a zero-copy HOST VIEW of ``x``'s
    device buffer. If ``x`` is then passed to a call that DONATES it,
    any executable honoring the donation (cache-loaded ones do, round
    6) reuses the buffer for the output — and the "snapshot" silently
    mutates under the host's feet."""

    name = "donation-alias"
    summary = ("zero-copy host view of a buffer that a later call "
               "donates")
    hint = ("snapshot with np.array(x) (a real copy) before the "
            "donating call, or defer the host read past it")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        donors = _donor_table(mod)
        if not donors:
            return
        for fn in _functions(mod.tree):
            # views: var -> (source-expr dump, assign line)
            views: dict[str, tuple[str, int, ast.AST]] = {}
            donating: list[tuple[int, str, ast.Call]] = []
            loads: dict[str, list[int]] = {}
            returns: list[tuple[int, ast.Return]] = []
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)):
                    call = node.value
                    cname = _func_name(mod, call)
                    is_view = cname in _VIEW_CALLS
                    if (cname == "numpy.array" and any(
                            kw.arg == "copy"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is False
                            for kw in call.keywords)):
                        is_view = True  # np.array(x, copy=False)
                    src: ast.AST | None = None
                    if (is_view and call.args and isinstance(
                            call.args[0], (ast.Name, ast.Attribute,
                                           ast.Subscript))):
                        src = call.args[0]
                    elif (isinstance(call.func, ast.Attribute)
                            and call.func.attr == "__array__"
                            and isinstance(
                                call.func.value,
                                (ast.Name, ast.Attribute,
                                 ast.Subscript))):
                        src = call.func.value  # x.__array__()
                    if src is not None:
                        views[node.targets[0].id] = (
                            ast.dump(src), node.lineno, node)
                elif isinstance(node, ast.Call):
                    cname = _func_name(mod, node)
                    donor = donors.get((cname or "").split(".")[-1]) \
                        if cname else None
                    if donor is not None:
                        for i in donor.get("donate_argnums", ()):
                            if i < len(node.args):
                                donating.append(
                                    (node.lineno,
                                     ast.dump(node.args[i]), node))
                        names = donor.get("donate_argnames", ())
                        for kw in node.keywords:
                            if kw.arg in names:
                                donating.append(
                                    (node.lineno, ast.dump(kw.value),
                                     node))
                elif isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Load):
                    loads.setdefault(node.id, []).append(node.lineno)
                elif isinstance(node, ast.Return):
                    returns.append((node.lineno, node))
            for var, (src_dump, vline, vnode) in views.items():
                for dline, arg_dump, call in donating:
                    if arg_dump != src_dump:
                        continue
                    if dline > vline:
                        # textual order: view taken, THEN donated
                        used_after = any(
                            ln > dline for ln in loads.get(var, ()))
                    elif _loop_ancestors(mod, vnode) & _loop_ancestors(
                            mod, call):
                        # shared loop: iteration N's view is still live
                        # when iteration N+1's donation (textually
                        # earlier) clobbers the buffer
                        used_after = any(
                            ln > vline for ln in loads.get(var, ()))
                    else:
                        continue
                    if used_after:
                        yield self.finding(
                            mod, vnode,
                            f"{var!r} is a zero-copy host view of a "
                            f"buffer donated by the call at line "
                            f"{dline}; an executable honoring the "
                            f"donation mutates the view in place",
                        )
                        break


@register
class HostSyncRule(Rule):
    """Dispatch-critical functions (the overlapped serving path, eager
    collective bodies — ``AnalysisConfig.dispatch_critical``, or any
    function decorated ``@dispatch_critical``) exist to keep the device
    queue fed. A host readback (``np.asarray``/``np.array`` of a device
    value, ``.item()``, ``float()`` of a device result,
    ``block_until_ready``, ``device_get``) stalls exactly the pipeline
    they implement."""

    name = "host-sync-in-dispatch"
    summary = "host readback/sync inside a dispatch-critical function"
    hint = ("defer the readback to the loop's sync point (the "
            "serving pattern: _resolve_pending / _collect_chunk), or "
            "keep the decision on device")

    _SYNC_CALLS = frozenset({
        "jax.block_until_ready", "jax.device_get",
        "numpy.asarray", "numpy.array",
    })
    _SYNC_METHODS = frozenset({"item", "block_until_ready"})
    _SYNC_CASTS = frozenset({"float", "int", "bool"})

    def _is_critical(self, fn: ast.FunctionDef,
                     config: AnalysisConfig) -> bool:
        if fn.name in config.dispatch_critical:
            return True
        for dec in fn.decorator_list:
            node = dec.func if isinstance(dec, ast.Call) else dec
            name = node.attr if isinstance(node, ast.Attribute) else (
                node.id if isinstance(node, ast.Name) else "")
            if name == "dispatch_critical":
                return True
        return False

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        for fn in _functions(mod.tree):
            if not self._is_critical(fn, config):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                cname = _func_name(mod, node)
                if cname in self._SYNC_CALLS:
                    yield self.finding(
                        mod, node,
                        f"{cname}() forces a host sync inside "
                        f"dispatch-critical {fn.name!r}",
                    )
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr in self._SYNC_METHODS):
                    yield self.finding(
                        mod, node,
                        f".{node.func.attr}() forces a host sync "
                        f"inside dispatch-critical {fn.name!r}",
                    )
                elif (cname in self._SYNC_CASTS and node.args
                        and isinstance(node.args[0], ast.Call)):
                    # float(f(...)): materializes the device result —
                    # the cast-of-a-call form only, so host-side
                    # int(x.size) bookkeeping stays legal
                    yield self.finding(
                        mod, node,
                        f"{cname}() of a call result reads back a "
                        f"device value inside dispatch-critical "
                        f"{fn.name!r}",
                    )


@register
class RecompileRule(Rule):
    """``jax.jit`` keys its trace cache on the wrapper object: a
    wrapper constructed per call (or per loop iteration) re-traces and
    re-compiles every time — the silent 1000x slowdown. Static args
    add the variant: a fresh unhashable container as a static arg
    fails (or, for exotic __eq__ types, recompiles) on every call."""

    name = "recompile-hazard"
    summary = ("jit constructed per call/iteration, or fresh "
               "containers as static args")
    hint = ("hoist the jit to module level (or memoize the wrapper); "
            "pass static args as hashable constants")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        # static-arg tables for same-module jitted defs
        statics: dict[str, frozenset[str]] = {}
        for fn in _functions(mod.tree):
            for dec in fn.decorator_list:
                if isinstance(dec, ast.Call) and _is_jit_constructor(
                        mod, dec):
                    names = _jit_call_config(mod, dec).get(
                        "static_argnames", ())
                    if names:
                        statics[fn.name] = frozenset(names)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_jit_constructor(mod, node):
                loop = self._enclosing(mod, node, (ast.For, ast.While))
                fn = self._enclosing(
                    mod, node, (ast.FunctionDef, ast.AsyncFunctionDef))
                parent = mod.parents.get(node)
                called_now = (isinstance(parent, ast.Call)
                              and parent.func is node)
                if loop is not None:
                    yield self.finding(
                        mod, node,
                        "jax.jit constructed inside a loop: a fresh "
                        "wrapper per iteration re-traces and "
                        "re-compiles every time",
                    )
                elif fn is not None and called_now:
                    yield self.finding(
                        mod, node,
                        f"jax.jit(...)(...) inside {fn.name!r}: the "
                        f"wrapper is rebuilt — and re-jitted — on "
                        f"every call of {fn.name!r}",
                    )
            else:
                cname = _func_name(mod, node)
                static = statics.get((cname or "").split(".")[-1]) \
                    if cname else None
                if not static:
                    continue
                for kw in node.keywords:
                    if kw.arg in static and isinstance(
                            kw.value, (ast.List, ast.Dict, ast.Set)):
                        yield self.finding(
                            mod, kw.value,
                            f"fresh {type(kw.value).__name__.lower()} "
                            f"literal passed as static arg "
                            f"{kw.arg!r} of jitted "
                            f"{(cname or '').split('.')[-1]!r}",
                            hint="static args are hashed into the "
                                 "compile cache key; pass a tuple / "
                                 "frozen constant",
                        )

    @staticmethod
    def _enclosing(mod: ModuleInfo, node: ast.AST, kinds) -> ast.AST | None:
        cur = mod.parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                return cur
            cur = mod.parents.get(cur)
        return None


@register
class PrngReuseRule(Rule):
    """A PRNG key is an affine resource: every ``jax.random`` consumer
    (including ``split``) must see a key exactly once, or two "random"
    draws are bit-identical. ``fold_in`` is the sanctioned fan-out
    (distinct data into one base — serving.request_key) and is exempt."""

    name = "prng-key-reuse"
    summary = "one key consumed by two traced uses without a re-split"
    hint = ("thread the key: `key, sub = jax.random.split(key)` before "
            "each consumer, or fold_in distinct stream ids")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        findings: list[Finding] = []
        for fn in _functions(mod.tree):
            state: dict[str, int] = {}  # var -> first-consumption line
            self._scan_block(mod, fn.body, state, findings, fn)
        seen = set()
        for f in findings:
            if (f.line, f.col) not in seen:
                seen.add((f.line, f.col))
                yield f

    # -- helpers ---------------------------------------------------------

    def _consumptions(self, mod: ModuleInfo, expr: ast.AST
                      ) -> list[tuple[str, ast.Call]]:
        out = []
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope, scanned on its own
            stack.extend(ast.iter_child_nodes(node))
            if not (isinstance(node, ast.Call) and node.args
                    and isinstance(node.args[0], ast.Name)):
                continue
            cname = _func_name(mod, node) or ""
            if (cname.startswith("jax.random.")
                    and cname.rsplit(".", 1)[1] not in _KEY_EXEMPT):
                out.append((node.args[0].id, node))
        return out

    def _targets(self, node: ast.AST) -> set[str]:
        names: set[str] = set()
        for t in ast.walk(node):
            if isinstance(t, ast.Name) and isinstance(
                    t.ctx, (ast.Store, ast.Del)):
                names.add(t.id)
        return names

    def _scan_block(self, mod, stmts, state, findings, fn):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope, scanned on its own
            if isinstance(stmt, (ast.For, ast.While)):
                # a key consumed in a loop body that never re-splits it
                # draws the SAME bits every iteration, whether the key
                # is a param, an outer local, or pre-loop state
                assigned = self._targets(stmt)
                body = stmt.body + stmt.orelse
                for sub in body:
                    for var, call in self._consumptions(mod, sub):
                        if var not in assigned:
                            findings.append(self.finding(
                                mod, call,
                                f"key {var!r} consumed inside a loop "
                                f"without a re-split in the loop body "
                                f"(every iteration sees the same "
                                f"key)",
                            ))
                self._scan_block(mod, body, state, findings, fn)
                continue
            if isinstance(stmt, ast.If):
                self._consume_expr(mod, stmt.test, state, findings)
                s1, s2 = dict(state), dict(state)
                self._scan_block(mod, stmt.body, s1, findings, fn)
                self._scan_block(mod, stmt.orelse, s2, findings, fn)
                # conservative merge: consumed in either branch counts
                state.clear()
                for d in (s1, s2):
                    for k, v in d.items():
                        state.setdefault(k, v)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._consume_expr(mod, item.context_expr, state,
                                       findings)
                self._scan_block(mod, stmt.body, state, findings, fn)
                continue
            if isinstance(stmt, ast.Try):
                self._scan_block(mod, stmt.body, state, findings, fn)
                for h in stmt.handlers:
                    self._scan_block(mod, h.body, dict(state),
                                     findings, fn)
                self._scan_block(mod, stmt.finalbody, state, findings,
                                 fn)
                continue
            # plain statement: consumptions in the value happen BEFORE
            # the rebinding takes effect (`key, sub = split(key)`)
            self._consume_expr(mod, stmt, state, findings)
            if isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
                for name in self._targets(stmt):
                    state.pop(name, None)

    def _consume_expr(self, mod, expr, state, findings):
        for var, call in self._consumptions(mod, expr):
            if var in state:
                findings.append(self.finding(
                    mod, call,
                    f"key {var!r} already consumed at line "
                    f"{state[var]}; reusing it makes both draws "
                    f"bit-identical",
                ))
            else:
                state[var] = call.lineno


@register
class TracerLeakRule(Rule):
    """Assigning a traced intermediate to ``self.*`` or a global inside
    a jit-traced function smuggles a tracer out of the trace: the
    attribute holds a tracer (crashing later uses), or — with a
    concrete-looking value — silently pins stale state from trace
    time."""

    name = "tracer-leak"
    summary = ("traced value assigned to self.*/globals inside a "
               "jitted function")
    hint = ("return the value and let the CALLER store it (the engine "
            "pattern: `self.pos, ... = _chunk_step(...)`)")

    def check(self, mod: ModuleInfo, config: AnalysisConfig
              ) -> Iterable[Finding]:
        jitted: list[ast.FunctionDef] = []
        for fn in _functions(mod.tree):
            for dec in fn.decorator_list:
                dec_call = dec if isinstance(dec, ast.Call) else None
                if (dec_call and _is_jit_constructor(mod, dec_call)) \
                        or mod.resolve(dec) in _JIT_NAMES:
                    jitted.append(fn)
                    break
        for fn in jitted:
            # nested defs (scan bodies) trace under the same jit
            for node in ast.walk(fn):
                if isinstance(node, ast.Global) and node.names:
                    yield self.finding(
                        mod, node,
                        f"global statement inside jit-traced "
                        f"{fn.name!r}: assignments leak trace-time "
                        f"values (or tracers) out of the trace",
                    )
                if not isinstance(node, (ast.Assign, ast.AugAssign,
                                         ast.AnnAssign)):
                    continue
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    for sub in ast.walk(tgt):
                        if (isinstance(sub, ast.Attribute)
                                and isinstance(sub.ctx, ast.Store)
                                and isinstance(sub.value, ast.Name)
                                and sub.value.id == "self"):
                            yield self.finding(
                                mod, node,
                                f"assignment to self.{sub.attr} "
                                f"inside jit-traced {fn.name!r} "
                                f"leaks a traced intermediate",
                            )
