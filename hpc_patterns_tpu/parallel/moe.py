"""Mixture-of-experts with expert parallelism (EP) over a mesh axis.

Completes the parallelism menu of SURVEY.md §2.2 (EP listed as a
strategy the ring/pt2pt/collective primitives must be shaped for). The
communication pattern is the ``MPI_Alltoall`` the comm layer already
exposes (collectives.all_to_all — the same primitive as Ulysses): each
rank owns E/P experts; tokens are routed top-1 (Switch style), packed
into fixed ``capacity`` slots per (source rank, expert) — static shapes,
the XLA ground rule — exchanged with one all-to-all each way, processed
by the local experts' FFNs (batched einsum, MXU-shaped), and combined
with the router gates.

Drop semantics: tokens past an expert's per-source-rank capacity are
dropped (output contribution zero), exactly as in the dense oracle
:func:`moe_dense` with the same capacity — sharded and dense results are
numerically identical per token shard, which is what the §4.2-style
oracle test asserts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.comm import collectives, ring


def _dispatch_combine(x, router_w, n_experts: int, capacity: int,
                      top_k: int = 1):
    """Top-k routing tensors for local tokens x: (N, D).

    Returns (dispatch (N, E, C) f32 0/1, combine (N, E, C) f32 gate,
    aux_loss scalar, kept_frac scalar — the fraction of routed
    (token, choice) assignments that got a capacity slot; 1 - kept_frac
    is the drop rate the training telemetry reports). Position within
    an expert's capacity is assigned in token order (cumsum), the
    Switch transformer formulation; for ``top_k > 1`` the walk is
    CHOICE-major — every token's first choice claims its slot before
    any second choice competes (GShard's priority rule, so raising k
    never evicts a first-choice assignment) — and the k gates are
    renormalized to sum to one per token.
    """
    n = x.shape[0]
    logits = jnp.dot(x.astype(jnp.float32), router_w.astype(jnp.float32))
    gates = jax.nn.softmax(logits, axis=-1)  # (N, E)
    if top_k == 1:
        expert = jnp.argmax(gates, axis=-1)  # (N,)
        onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)
        # slot index of each token within its expert (0-based, token order)
        position = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # -1 elsewhere
        kept = onehot * (position < capacity)  # overflow dropped
        pos_clamped = jnp.clip(position, 0, capacity - 1).astype(jnp.int32)
        slot_onehot = jax.nn.one_hot(pos_clamped, capacity, dtype=jnp.float32)
        dispatch = kept[..., None] * slot_onehot  # (N, E, C)
        top_gate = jnp.sum(gates * onehot, axis=-1)  # (N,)
        combine = dispatch * top_gate[:, None, None]
        first_frac = onehot.mean(axis=0)
        kept_frac = jnp.sum(kept) / n
    else:
        vals, idx = jax.lax.top_k(gates, top_k)           # (N, k)
        norm = vals / jnp.sum(vals, axis=-1, keepdims=True)
        oh = jax.nn.one_hot(idx, n_experts, dtype=jnp.float32)  # (N, k, E)
        flat = oh.transpose(1, 0, 2).reshape(top_k * n, n_experts)
        position = jnp.cumsum(flat, axis=0) * flat - 1.0
        kept = flat * (position < capacity)
        pos_clamped = jnp.clip(position, 0, capacity - 1).astype(jnp.int32)
        slot_onehot = jax.nn.one_hot(pos_clamped, capacity,
                                     dtype=jnp.float32)
        disp_choice = (kept[..., None] * slot_onehot).reshape(
            top_k, n, n_experts, capacity
        )
        dispatch = disp_choice.sum(0)  # choices hit distinct experts
        combine = jnp.einsum("knec,nk->nec", disp_choice, norm)
        first_frac = oh[:, 0].mean(axis=0)
        kept_frac = jnp.sum(kept) / (top_k * n)
    # Switch load-balancing auxiliary loss: E * sum_e f_e * P_e, with
    # f the FIRST-choice routing fraction (the k=1 definition; the
    # balance pressure targets the primary assignment)
    p = gates.mean(axis=0)
    aux = n_experts * jnp.sum(first_frac * p)
    return dispatch, combine, aux, kept_frac


def _scatter_dispatch(x, gates, n_experts: int, capacity: int,
                      top_k: int):
    """Sort/scatter routing: the O(N·D + E·C·D) replacement for the
    one-hot einsum dispatch, whose (N, E, C) tensors are O(N²·cf/E)
    and OOM a 16 GB chip near 16k tokens (measured — RESULTS.md
    "MoE top-k rows"). Same assignment semantics as the einsum path by
    construction: a STABLE argsort of the choice-major expert ids gives
    each (token, choice) the same within-expert rank the cumsum
    formulation computes, so the kept set and slot layout are
    identical (oracle-tested equal).

    Returns (xin (E, C, D), combine(out) -> y (N, D), aux, kept_frac).
    """
    n = x.shape[0]
    if top_k == 1:
        vals = jnp.max(gates, axis=-1, keepdims=True)       # (N, 1)
        idx = jnp.argmax(gates, axis=-1)[:, None]           # (N, 1)
        norm = jnp.ones_like(vals)
        first_frac = jax.nn.one_hot(idx[:, 0], n_experts,
                                    dtype=jnp.float32).mean(0)
        gate_per_choice = vals
    else:
        vals, idx = jax.lax.top_k(gates, top_k)             # (N, k)
        norm = vals / jnp.sum(vals, axis=-1, keepdims=True)
        first_frac = jax.nn.one_hot(idx[:, 0], n_experts,
                                    dtype=jnp.float32).mean(0)
        gate_per_choice = norm
    k = idx.shape[1]
    # choice-major flat (GShard priority: all first choices precede any
    # second choice), matching the einsum path's walk order
    expert_flat = idx.T.reshape(k * n)                      # (kN,)
    token_flat = jnp.tile(jnp.arange(n, dtype=jnp.int32), k)
    gate_flat = gate_per_choice.T.reshape(k * n)
    order = jnp.argsort(expert_flat, stable=True)
    sorted_e = expert_flat[order]
    start = jnp.searchsorted(sorted_e, jnp.arange(n_experts),
                             side="left")
    rank = jnp.arange(k * n, dtype=jnp.int32) - start[sorted_e].astype(
        jnp.int32
    )
    keep = rank < capacity
    kept_frac = jnp.sum(keep) / (k * n)
    # dropped entries scatter to a trash row past the real slots
    slot = jnp.where(keep, sorted_e * capacity + rank,
                     n_experts * capacity)
    src_tok = token_flat[order]
    xin_flat = jnp.zeros((n_experts * capacity + 1, x.shape[1]), x.dtype)
    xin_flat = xin_flat.at[slot].set(x[src_tok])
    xin = xin_flat[:-1].reshape(n_experts, capacity, x.shape[1])

    gate_sorted = gate_flat[order]

    def combine(out):
        out_flat = out.reshape(n_experts * capacity, -1)
        picked = jnp.where(
            keep[:, None],
            out_flat[jnp.clip(slot, 0, n_experts * capacity - 1)], 0.0
        )
        y = jnp.zeros((n, out_flat.shape[1]), out_flat.dtype)
        return y.at[src_tok].add(picked * gate_sorted[:, None].astype(
            out_flat.dtype
        ))

    p_mean = gates.mean(axis=0)
    aux = n_experts * jnp.sum(first_frac * p_mean)
    return xin, combine, aux, kept_frac


def _route(x, router_w, n_experts: int, capacity: int, top_k: int,
           dispatch: str):
    """Shared routing front-end for moe_dense and moe_ep: resolve the
    dispatch form once and return ``(xin (E, C, D), combine(out) -> y,
    aux, kept_frac)`` — the one place the einsum/scatter selection and
    the router math live, so the two entry points cannot drift."""
    if dispatch == "scatter":
        logits = jnp.dot(x.astype(jnp.float32),
                         router_w.astype(jnp.float32))
        gates = jax.nn.softmax(logits, axis=-1)
        return _scatter_dispatch(x, gates, n_experts, capacity, top_k)
    if dispatch == "einsum":
        disp, combine, aux, kept = _dispatch_combine(
            x, router_w, n_experts, capacity, top_k
        )
        # routing math stays f32; dispatch/FFN run in x's dtype
        xin = jnp.einsum("nec,nd->ecd", disp.astype(x.dtype), x)

        def combine_fn(out):
            return jnp.einsum("nec,ecd->nd", combine.astype(out.dtype),
                              out)

        return xin, combine_fn, aux, kept
    raise ValueError(f"dispatch {dispatch!r} not in ('einsum', 'scatter')")


def _expert_ffn(xin, w1, w2, activation=None):
    """Batched per-expert FFN: xin (E, C, D), w1 (E, D, F), w2 (E, F, D)."""
    act = activation or jax.nn.gelu
    h = act(jnp.einsum("ecd,edf->ecf", xin, w1.astype(xin.dtype)))
    return jnp.einsum("ecf,efd->ecd", h, w2.astype(xin.dtype))


def default_capacity(n_tokens: int, n_experts: int,
                     capacity_factor: float = 1.25) -> int:
    return max(1, int(n_tokens * capacity_factor / n_experts))


def moe_dense(x, router_w, w1, w2, *, capacity: int, activation=None,
              top_k: int = 1, with_stats: bool = False,
              dispatch: str = "einsum"):
    """Single-device oracle: all E experts local. x: (N, D); w1: (E, D,
    F); w2: (E, F, D). Returns (y (N, D), aux_loss), plus the kept
    fraction when ``with_stats`` (drop rate = 1 - kept).

    ``dispatch``: "einsum" (one-hot (N, E, C) tensors — the teaching/
    oracle form, O(N²·cf/E) memory) or "scatter" (stable-sort routing,
    O(N + E·C) — same assignments by construction, the at-scale form).
    """
    E = w1.shape[0]
    xin, combine_fn, aux, kept = _route(x, router_w, E, capacity, top_k,
                                        dispatch)
    out = _expert_ffn(xin, w1, w2, activation)
    y = combine_fn(out)
    if with_stats:
        return y.astype(x.dtype), aux, kept
    return y.astype(x.dtype), aux


def moe_ep(x, router_w, w1_local, w2_local, *, axis: str, capacity: int,
           activation=None, top_k: int = 1, with_stats: bool = False,
           dispatch: str = "einsum"):
    """Expert-parallel MoE layer (rank-local; run inside ``shard_map``).

    ``x``: (N_local, D) this rank's tokens. ``w1_local``/``w2_local``:
    (E/P, D, F)/(E/P, F, D) — this rank's expert shard. ``router_w``:
    (D, E) replicated. Two all-to-alls move (tokens→experts→tokens),
    riding ICI like every other collective in the framework (§2.3).
    Per-token results equal :func:`moe_dense` on the same token shard
    with the same capacity.
    """
    P = ring.axis_size(axis)
    e_local = w1_local.shape[0]
    E = e_local * P
    xin, combine_fn, aux, kept = _route(x, router_w, E, capacity, top_k,
                                        dispatch)
    # tokens to their experts' owners: (E, C, D) -> (E/P, P*C, D)
    xin = collectives.all_to_all(xin, axis, split_axis=0, concat_axis=1)
    out = _expert_ffn(xin, w1_local, w2_local, activation)
    # results back to the tokens' owners: (E/P, P*C, D) -> (E, C, D)
    out = collectives.all_to_all(out, axis, split_axis=1, concat_axis=0)
    y = combine_fn(out)
    # aux/kept are per-shard; average across ranks for global scalars
    aux = collectives.allreduce(aux, axis, "mean")
    if with_stats:
        return (y.astype(x.dtype), aux,
                collectives.allreduce(kept, axis, "mean"))
    return y.astype(x.dtype), aux
