"""Fused-MLP kernel decomposition bench: fwd / fwd+bwd vs XLA dense.

Standalone numbers DIAGNOSE (which pass is slow, which blocks help);
only benchmarks/bench_train.py in-situ A/Bs DECIDE (the microbench-lies
rule, benchmarks/RESULTS.md "MFU push").

Usage: python benchmarks/bench_mlp.py [--n=16384] [--d=1024] [--f=4096]
"""

import sys

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.harness.timing import amortized_seconds
from hpc_patterns_tpu.ops.fused_mlp import fused_mlp


def arg(name, default, cast):
    for a in sys.argv[1:]:
        if a.startswith(f"--{name}="):
            return cast(a.split("=", 1)[1])
    return default


def main():
    on_tpu = jax.default_backend() == "tpu"
    N = arg("n", 16384 if on_tpu else 64, int)
    D = arg("d", 1024 if on_tpu else 16, int)
    F = arg("f", 4096 if on_tpu else 32, int)
    iters = arg("iters", 32 if on_tpu else 2, int)
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(ks[0], (N, D), dt)
    w1 = jax.random.normal(ks[1], (D, F), dt) * 0.02
    w2 = jax.random.normal(ks[2], (F, D), dt) * 0.02

    flops_fwd = 2 * 2 * N * D * F
    flops_bwd = flops_fwd + 5 * 2 * N * D * F  # fwd + 5 bwd matmuls

    def dense(x, w1, w2):
        return jnp.dot(jax.nn.gelu(jnp.dot(x, w1)), w2)

    def bench(tag, f, flops):
        def run(n):
            def body(c, _):
                return f(c, w1, w2), None
            out, _ = jax.lax.scan(body, x, None, length=n)
            # SCALAR readback: a (N, D) result pulled through the
            # tunnel is ~30 MB per forced completion — the readback
            # jitter drowns the per-iteration difference entirely
            return jnp.sum(out[0].astype(jnp.float32))

        runj = jax.jit(run, static_argnums=0)
        t = amortized_seconds(lambda n: runj(n), iters=iters,
                              repetitions=3, base_iters=iters // 2)
        tf = flops / t / 1e12 if t > 0 else float("nan")
        print(f"{tag}: {t * 1e3:.3f} ms  {tf:.1f} TF/s", flush=True)
        return t

    def grad_of(mlp):
        # ALL THREE grads consumed (argnums=0 alone would let XLA drop
        # the dW transposes from the dense leg while the pallas backward
        # computes them unconditionally — a ~40% flops-crediting bias):
        # dx carries the scan, dW folds in as a broadcast epsilon
        g = jax.grad(lambda x, w1, w2: jnp.sum(mlp(x, w1, w2) ** 2),
                     argnums=(0, 1, 2))

        def f(x, w1, w2):
            dx, dw1g, dw2g = g(x, w1, w2)
            return dx + (jnp.sum(dw1g[0]) + jnp.sum(dw2g[0])) * 1e-12
        return f

    bench("dense fwd     ", lambda x, w1, w2: dense(x, w1, w2), flops_fwd)
    bench("dense fwd+bwd ", grad_of(dense), flops_bwd)
    for bt, bf in ((512, 512), (1024, 512), (512, 1024), (1024, 1024),
                   (2048, 1024)):
        fm = lambda x, w1, w2, bt=bt, bf=bf: fused_mlp(
            x, w1, w2, block_t=bt, block_f=bf)
        try:
            bench(f"fused({bt:4d},{bf:4d}) fwd", fm, flops_fwd)
            bench(f"fused({bt:4d},{bf:4d}) f+b", grad_of(fm), flops_bwd)
        except Exception as e:
            print(f"fused({bt},{bf}): FAILED {type(e).__name__}: "
                  f"{str(e)[:160]}", flush=True)


if __name__ == "__main__":
    main()
