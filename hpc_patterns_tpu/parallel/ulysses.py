"""Ulysses-style sequence parallelism: all-to-all around local attention.

The second canonical long-context strategy (vs the ring,
SURVEY.md §2.2's "TP / PP / SP ... ring + pt2pt components above are
their building blocks"): instead of circulating K/V, one
``MPI_Alltoall``-shaped exchange (comm.collectives.all_to_all,
lax.all_to_all over ICI) re-shards from sequence-sharded to
head-sharded, every rank runs *full-sequence* attention on its head
slice, and a second all-to-all restores sequence sharding.

Ring vs Ulysses is the same library-collective-vs-composed-ring tradeoff
the reference's allreduce miniapp exists to measure (§2.3(b)): Ulysses
is 2 dense collectives, ring is (size-1) neighbor hops overlapped with
compute. Both are exposed so benchmarks can race them.
"""

from __future__ import annotations

from hpc_patterns_tpu.comm import collectives, ring
from hpc_patterns_tpu.parallel.ring_attention import full_attention


def ulysses_attention(
    q,
    k,
    v,
    axis: str,
    *,
    causal: bool = False,
    scale: float | None = None,
    impl: str = "dense",
):
    """Attention over a sequence sharded on ``axis`` via head scattering
    (rank-local; run inside ``shard_map``).

    ``q``/``k``/``v``: (batch, seq_local, heads, head_dim) with ``heads``
    divisible by the axis size. K/V may be GQA-narrow (kv_heads dividing
    q's heads): when kv_heads also divides the axis size, the NARROW K/V
    ride the all-to-alls (group-factor less exchange traffic) and the
    local attention runs grouped-query; otherwise K/V are expanded to
    full heads first (head scattering needs per-rank whole heads).
    Returns the local sequence block of the full attention output, same
    shape as ``q``.

    ``impl``: the rank-local full-sequence attention — ``"dense"``
    (oracle math, any shape) or ``"flash"`` (ops.flash_attention: after
    the first all-to-all each rank holds the FULL sequence for its head
    slice, exactly the square kernel's shape; requires the global
    sequence to divide by the clamped block sizes).
    """
    if q.ndim != 4:
        raise ValueError(f"want (batch, seq, heads, head_dim), got {q.shape}")
    if impl not in ("dense", "flash"):
        raise ValueError(f"impl {impl!r} not in ('dense', 'flash')")
    size = ring.axis_size(axis)
    H, Hkv = q.shape[2], k.shape[2]
    if H % size:
        raise ValueError(f"heads {H} not divisible by axis size {size}")
    if H % max(Hkv, 1) or v.shape[2] != Hkv:
        raise ValueError(
            f"kv heads {Hkv}/{v.shape[2]} must match and divide heads {H}"
        )
    if Hkv != H and Hkv % size:
        # can't scatter partial kv heads: fall back to expanded K/V.
        # Loud (once per trace): the config silently paying group-factor
        # more exchange traffic is exactly what a user tuning GQA+SP
        # wants to know — use kv_heads % axis_size == 0 to keep the
        # narrow path.
        import warnings

        import jax.numpy as jnp

        warnings.warn(
            f"ulysses: axis size {size} does not divide kv_heads={Hkv}; "
            f"expanding K/V to {H} heads for the all-to-all (narrow-K/V "
            "exchange saving lost) — make kv_heads a multiple of the "
            "sp axis size to keep the narrow path",
            stacklevel=2,
        )
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)

    # (B, T/P, H, D) -> (B, T, H/P, D): gather sequence, scatter heads
    def seq_to_heads(x):
        return collectives.all_to_all(x, axis, split_axis=2, concat_axis=1)

    def heads_to_seq(x):
        return collectives.all_to_all(x, axis, split_axis=1, concat_axis=2)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if impl == "flash":
        from hpc_patterns_tpu.ops import flash_attention

        out = flash_attention(qh, kh, vh, causal=causal, scale=scale)
    else:
        out = full_attention(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(out)
