"""The residency manager: per-block tier tracking, eviction policies,
and the overlapped HBM<->host transfer pipeline.

The first-touch BLAS-offloading shape (arxiv 2501.00279) applied to
this repo's two big consumers of HBM: serving KV pages and training
optimizer/param blocks. The manager owns three things:

- **accounting**: every BLOCK (a KV page, an opt-state leaf) has a
  tier (``"hbm"`` / ``"host"``), a pin state, a last-touch round, and
  a priority; blocks belong to GROUPS (a serving row's page set, one
  named state tree) because migration is group-granular — a decode
  row's pages move together or the row cannot run;
- **policy**: pluggable eviction order over the unpinned resident
  groups — :class:`LRUPolicy` (longest-untouched first; for decode
  rows, which are touched every resident round, this degrades to
  longest-RESIDENT first, i.e. fair rotation), :class:`
  PriorityAwarePolicy` (numerically-highest priority class first —
  the round-8 request priorities — then LRU), and
  :class:`ColdAfterNPolicy` (a group resident/untouched for N rounds
  is cold and proactively evictable — the deterministic policy the
  tier-1 tests schedule against);
- **transfers**: the prefetch/evict pipeline, instrumented. Pulls
  (host->HBM) are DISPATCHED before the consumer — the stream-aware
  offloaded-messaging discipline (arxiv 2306.15773): dispatch the
  transfer, then let it hide under the in-flight decode chunk /
  gradient-accumulation phase — and drawn as ``mem.prefetch`` device
  windows whose overlap against the consumer's windows is MEASURED,
  not asserted (``prefetch_overlap_frac``). Evictions (HBM->host) are
  ``mem.evict`` windows dispatched behind the same compute. The
  ``host_transfer`` chaos site fires at every pull dispatch, so a
  degraded-host-bandwidth run is replayable (``slow_host_transfer``).

Tier mechanics per backend: when the backend's pinned-host tier is
real (:func:`~hpc_patterns_tpu.memory.kinds.memory_kind_transfers_work`)
the host side of a block is a ``pinned_host``-kind jax array and both
directions are async ``device_put`` dispatches; otherwise the host
side is a plain numpy copy (the CPU test fallback — the evict then
syncs at its chunk-boundary dispatch site, which is the documented
degraded mode, and the pull stays an async ``device_put``). Either
way the bytes round-trip EXACTLY, which is what the serving oracle
(constrained-HBM engine token-identical to all-HBM, docs/memory.md)
rides on.

Gauges (harness/metrics.py, no-op when disabled): ``mem.hbm_pages`` /
``mem.host_pages`` (resident block counts per tier) and
``mem.prefetch_bytes`` (cumulative bytes pulled host->HBM).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from hpc_patterns_tpu.harness import chaos as chaoslib
from hpc_patterns_tpu.harness import metrics as metricslib
from hpc_patterns_tpu.harness import trace as tracelib
from hpc_patterns_tpu.memory import kinds as kindslib

#: device-subtrack band for ``mem.prefetch`` / ``mem.evict`` windows —
#: declared in harness/trace.py's TRACK_BANDS above the admit-slot
#: band and the serving plane's migration band, so concurrently-open
#: windows never share a Chrome sync track with either
MEM_TRACK_BASE, MEM_TRACKS = tracelib.track_band("residency")


def mem_track(seq: int) -> int:
    """The device subtrack a prefetch/evict window lands on."""
    return MEM_TRACK_BASE + int(seq) % MEM_TRACKS


@dataclass
class BlockState:
    """One tracked block: a KV page or one training-state leaf."""
    key: object          # block id: (group, index)
    group: object        # migration unit: serving seq_id / tree name
    nbytes: int
    tier: str            # "hbm" | "host"
    pinned: bool = False
    priority: int = 0
    last_touch: int = 0
    resident_since: int = 0


@dataclass
class GroupView:
    """Policy-facing summary of one group's blocks."""
    group: object
    n_blocks: int
    nbytes: int
    tier: str
    pinned: bool
    priority: int
    last_touch: int
    resident_since: int


class EvictionPolicy:
    """Victim ordering over resident, unpinned groups. ``victim_order``
    returns groups most-evictable first; ``is_cold`` marks groups the
    manager should evict PROACTIVELY (without demand)."""

    name = "?"

    def victim_order(self, groups: list[GroupView],
                     round_no: int) -> list[GroupView]:
        raise NotImplementedError

    def is_cold(self, group: GroupView, round_no: int) -> bool:
        return False


class LRUPolicy(EvictionPolicy):
    """Least-recently-touched first (ties: longest-resident, then
    group id for determinism). Decode rows are touched every resident
    round, so among them LRU is longest-resident-first — the fair
    rotation that gives swapped rows their turn. Demand-driven only:
    nothing is cold without pressure."""

    name = "lru"

    def victim_order(self, groups, round_no):
        return sorted(groups, key=lambda g: (g.last_touch,
                                             g.resident_since,
                                             str(g.group)))


class PriorityAwarePolicy(LRUPolicy):
    """Numerically-highest priority class first (lower number = more
    important, the round-8 request-priority convention), LRU inside a
    class — background work pages out before interactive work."""

    name = "priority"

    def victim_order(self, groups, round_no):
        return sorted(groups, key=lambda g: (-g.priority,
                                             g.last_touch,
                                             g.resident_since,
                                             str(g.group)))


class ColdAfterNPolicy(LRUPolicy):
    """A group RESIDENT for >= ``n`` rounds is cold: proactively
    evictable even without demand (rotation by residency age — decode
    rows are touched every resident round, so touch-recency cannot be
    the clock). Deterministic given the round schedule — the policy
    the tier-1 rotation tests pin."""

    name = "cold_after_n"

    def __init__(self, n: int):
        if n < 1:
            raise ValueError(f"cold-after-n needs n >= 1, got {n}")
        self.n = int(n)

    def is_cold(self, group, round_no):
        # residency age alone decides: decode rows are touched every
        # resident round, so a touch-based clock would never fire —
        # "resident for n rounds" is the deterministic rotation rule
        return (round_no - group.resident_since) >= self.n


class ResidencyManager:
    """Tiered-residency bookkeeping + the instrumented transfer engine
    (module docstring has the design). One manager serves ONE consumer
    (an :class:`~hpc_patterns_tpu.models.serving.EngineCore` via
    ``EngineCore(residency=...)``, or a training step via
    ``make_train_step(..., residency=...)``) — the tier state is the
    consumer's, not process-global.

    ``host_blocks``: host-tier capacity in blocks (pages); the host
    pool is the larger tier the HBM arena caches. ``policy``: an
    :class:`EvictionPolicy` (default LRU). ``min_resident_rounds``: a
    group prefetched in stays unevictable this many rounds (anti-
    thrash floor). ``prefetch_depth``: advisory cap on concurrently
    in-flight pulls the consumer should dispatch (None = unlimited —
    the engine reads it at its prefetch-dispatch site; autofit sets 1
    when the recorded pulls ran exposed). ``device``: where pulls land
    (default first device)."""

    def __init__(self, *, host_blocks: int, policy: EvictionPolicy
                 | None = None, min_resident_rounds: int = 1,
                 prefetch_depth: int | None = None, device=None):
        if host_blocks < 1:
            raise ValueError(
                f"host_blocks must be >= 1, got {host_blocks}")
        if prefetch_depth is not None and prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1 or None, got "
                f"{prefetch_depth}")
        self.host_blocks = int(host_blocks)
        self.policy = policy or LRUPolicy()
        self.min_resident_rounds = int(min_resident_rounds)
        self.prefetch_depth = (None if prefetch_depth is None
                               else int(prefetch_depth))
        self._device = device
        self.blocks: dict[object, BlockState] = {}
        self.round = 0
        # pinned-host tier or numpy fallback, probed once at first use
        self._host_kind_works: bool | None = None
        # transfer telemetry
        self.swap_outs = 0
        self.swap_ins = 0
        self.prefetch_bytes = 0
        self.evict_bytes = 0
        self._win_seq = 0
        self._chaos_index = 0
        self._prefetch_overlap_s = 0.0
        self._prefetch_total_s = 0.0
        #: open ``mem.evict`` windows awaiting a cheap completion
        #: observation: (trace_stamp, track, payload leaf, attrs)
        self._open_evicts: list[tuple] = []

    @classmethod
    def from_fitted(cls, fitted, *, host_blocks: int, device=None):
        """Build a manager from an autofit ``FittedConfig``: the fitted
        ``residency`` section picks the eviction policy (``lru`` /
        ``priority`` / ``cold_after_n``), the anti-thrash floor, and
        the prefetch depth; a config with no residency section (the
        run never paged) yields the plain default manager. Capacity
        (``host_blocks``) stays the caller's — it is sized by the
        deployment, not the profile."""
        from hpc_patterns_tpu.harness import autofit as autofitlib

        fitted = autofitlib.validate_fitted(fitted)
        section = fitted.get("residency") or {}
        name = section.get("policy") or "lru"
        if name == "priority":
            policy: EvictionPolicy = PriorityAwarePolicy()
        elif name == "cold_after_n":
            policy = ColdAfterNPolicy(int(section.get("cold_after_n")
                                          or 1))
        elif name == "lru":
            policy = LRUPolicy()
        else:
            raise ValueError(
                f"fitted residency policy {name!r} unknown (expected "
                "lru / priority / cold_after_n)")
        return cls(
            host_blocks=host_blocks,
            policy=policy,
            min_resident_rounds=int(
                section.get("min_resident_rounds") or 1),
            prefetch_depth=section.get("prefetch_depth"),
            device=device,
        )

    # -- device / tier plumbing --------------------------------------------

    @property
    def device(self):
        if self._device is None:
            import jax

            self._device = jax.devices()[0]
        return self._device

    def host_tier_is_pinned(self) -> bool:
        """True when the host side is a real ``pinned_host`` jax array
        (async both ways); False = numpy fallback (the CPU mesh)."""
        if self._host_kind_works is None:
            self._host_kind_works = kindslib.memory_kind_transfers_work(
                self.device)
        return self._host_kind_works

    # -- block accounting ---------------------------------------------------

    def register_group(self, group, n_blocks: int, nbytes: int, *,
                       tier: str = "hbm", priority: int = 0) -> None:
        """Track a new group of ``n_blocks`` blocks totaling ``nbytes``
        (evenly attributed). Raises if the group exists or the host
        tier would overflow."""
        if tier not in ("hbm", "host"):
            raise ValueError(f"tier {tier!r} not in ('hbm', 'host')")
        if (group, 0) in self.blocks:
            raise ValueError(f"group {group!r} already registered")
        if tier == "host" and not self.can_host(n_blocks):
            raise ValueError(
                f"host tier full: {n_blocks} blocks over capacity "
                f"{self.host_blocks} (used {self.host_blocks_used()})")
        per = max(1, nbytes // max(1, n_blocks))
        for i in range(n_blocks):
            self.blocks[(group, i)] = BlockState(
                key=(group, i), group=group, nbytes=per, tier=tier,
                priority=priority, last_touch=self.round,
                resident_since=self.round)
        self.update_gauges()

    def release_group(self, group) -> None:
        i = 0
        while (group, i) in self.blocks:
            del self.blocks[(group, i)]
            i += 1
        self.update_gauges()

    def _group_blocks(self, group) -> list[BlockState]:
        # blocks are keyed (group, i) with i dense from register_group,
        # so group operations (touch per active slot per ROUND, pin,
        # retier) are O(group size), not O(all blocks)
        out, i = [], 0
        while (group, i) in self.blocks:
            out.append(self.blocks[(group, i)])
            i += 1
        return out

    def touch_group(self, group) -> None:
        for b in self._group_blocks(group):
            b.last_touch = self.round

    def pin_group(self, group, pinned: bool = True) -> None:
        for b in self._group_blocks(group):
            b.pinned = pinned

    def retier_group(self, group, tier: str) -> None:
        """Move a group's accounting to ``tier`` (the caller moved the
        bytes). To host counts against ``host_blocks``; to HBM stamps
        ``resident_since`` with the current round."""
        blocks = self._group_blocks(group)
        if not blocks:
            raise ValueError(f"group {group!r} not registered")
        if tier == "host" and blocks[0].tier != "host" \
                and not self.can_host(len(blocks)):
            raise ValueError(
                f"host tier full: {len(blocks)} blocks over capacity "
                f"{self.host_blocks} (used {self.host_blocks_used()})")
        for b in blocks:
            if tier == "hbm" and b.tier != "hbm":
                b.resident_since = self.round
                b.last_touch = self.round
            b.tier = tier
        self.update_gauges()

    def hbm_blocks_used(self) -> int:
        return sum(1 for b in self.blocks.values() if b.tier == "hbm")

    def host_blocks_used(self) -> int:
        return sum(1 for b in self.blocks.values() if b.tier == "host")

    def can_host(self, n_blocks: int) -> bool:
        return self.host_blocks_used() + n_blocks <= self.host_blocks

    def groups(self, tier: str | None = None) -> list[GroupView]:
        by_group: dict[object, list[BlockState]] = {}
        for b in self.blocks.values():
            by_group.setdefault(b.group, []).append(b)
        out = []
        for g, bs in by_group.items():
            if tier is not None and bs[0].tier != tier:
                continue
            out.append(GroupView(
                group=g, n_blocks=len(bs),
                nbytes=sum(b.nbytes for b in bs), tier=bs[0].tier,
                pinned=any(b.pinned for b in bs),
                priority=max(b.priority for b in bs),
                last_touch=max(b.last_touch for b in bs),
                resident_since=max(b.resident_since for b in bs)))
        return out

    # -- policy -------------------------------------------------------------

    def victims(self, need_blocks: int, *, exclude=(),
                min_priority: int | None = None) -> list[object]:
        """Groups to evict, policy-ordered, until ``need_blocks`` HBM
        blocks would be free — or every eligible victim if even that
        falls short (the caller decides whether partial progress is
        progress). Pinned groups and groups inside their
        ``min_resident_rounds`` floor are never offered.
        ``min_priority``: only groups whose priority number is >= it
        (the serving engine's demand rules: a queued request may only
        displace STRICTLY less urgent residents, rotation stays within
        same-or-less-urgent classes)."""
        cand = [g for g in self.groups("hbm")
                if not g.pinned and g.group not in exclude
                and self.round - g.resident_since
                >= self.min_resident_rounds
                and (min_priority is None
                     or g.priority >= min_priority)]
        chosen, freed = [], 0
        for g in self.policy.victim_order(cand, self.round):
            if freed >= need_blocks:
                break
            # host capacity is consumed CUMULATIVELY across this
            # pass's picks (freed blocks land on the host tier) — a
            # per-group check against the pre-pass state would
            # overbook the tier
            if not self.can_host(freed + g.n_blocks):
                continue
            chosen.append(g.group)
            freed += g.n_blocks
        # partial progress is still progress: even when the eligible
        # victims cannot cover the whole need, freeing what they hold
        # lets smaller consumers (or next round) move
        return chosen

    def cold_groups(self, *, exclude=()) -> list[object]:
        """Groups the policy marks proactively evictable this round."""
        return [g.group for g in self.groups("hbm")
                if not g.pinned and g.group not in exclude
                and self.round - g.resident_since
                >= self.min_resident_rounds
                and self.can_host(g.n_blocks)
                and self.policy.is_cold(g, self.round)]

    # -- rounds / gauges ----------------------------------------------------

    def begin_round(self) -> None:
        self.round += 1
        self._close_ripe_evicts()

    def update_gauges(self) -> None:
        m = metricslib.get_metrics()
        if not m.enabled:
            return
        m.gauge("mem.hbm_pages").set(self.hbm_blocks_used())
        m.gauge("mem.host_pages").set(self.host_blocks_used())
        m.gauge("mem.prefetch_bytes").set(self.prefetch_bytes)

    # -- transfers (the instrumented pipeline) ------------------------------

    @staticmethod
    def _payload_bytes(payload) -> int:
        import jax

        return sum(int(getattr(a, "nbytes", 0))
                   for a in jax.tree.leaves(payload))

    def push_payload(self, payload, *, attrs: dict | None = None,
                     shardings=None):
        """HBM -> host: move a payload tree to the host tier and open
        its ``mem.evict`` device window (closed lazily at the next
        round boundary — :meth:`begin_round` — or :meth:`drain`).
        ``shardings``: explicit per-leaf target shardings (the
        training path's mesh-aware host placements); default is the
        manager's tier — async per-leaf ``device_put`` when the
        pinned-host tier is real, else a synchronous numpy copy (the
        caller sits at a chunk boundary — the deliberate-sync contract
        eviction shares with preemption's snapshot)."""
        import jax

        nbytes = self._payload_bytes(payload)
        seq = self._win_seq
        self._win_seq += 1
        rec = tracelib.active()
        t_disp = 0.0
        track = mem_track(seq)
        win_attrs = {**(attrs or {}), "bytes": nbytes}
        if rec is not None:
            t_disp = rec.mark_dispatch(
                "mem.evict", {**win_attrs, "seq": seq}, track=track)
        if shardings is not None:
            out = jax.tree.map(jax.device_put, payload, shardings)
        elif self.host_tier_is_pinned():
            sh = kindslib.kind_sharding(self.device, "pinned_host")
            out = jax.tree.map(lambda a: jax.device_put(a, sh), payload)
        else:
            # jaxlint: disable=host-sync-in-dispatch — the numpy
            # fallback tier IS a host copy; the caller dispatches
            # evictions at a chunk boundary (collected), so the sync
            # stalls nothing in flight
            out = jax.tree.map(lambda a: np.asarray(jax.device_get(a)),
                               payload)
        self.swap_outs += 1
        self.evict_bytes += nbytes
        if rec is not None and t_disp:
            self._open_evicts.append((t_disp, track, out, seq,
                                      win_attrs))
        self.update_gauges()
        return out

    def pull_payload(self, payload, *, attrs: dict | None = None,
                     shardings=None):
        """Host -> HBM: dispatch the pull for a host payload tree and
        open its ``mem.prefetch`` window. ``shardings``: explicit
        per-leaf HBM targets (the training path); default pulls onto
        the manager's device. Returns ``(device_payload, handle)``;
        the caller completes the window with :meth:`complete_pull`
        once it OBSERVES completion (after the consumer's sync point)
        — the window must cover real transfer time, not dispatch
        time. The ``host_transfer`` chaos site fires here, between the
        window open and the transfer dispatch, so an injected
        ``slow_host_transfer`` delay widens exactly the window it
        claims to (and delays the real transfer behind it)."""
        import jax

        nbytes = self._payload_bytes(payload)
        seq = self._win_seq
        self._win_seq += 1
        rec = tracelib.active()
        t_disp = 0.0
        track = mem_track(seq)
        win_attrs = {**(attrs or {}), "bytes": nbytes}
        if rec is not None:
            t_disp = rec.mark_dispatch(
                "mem.prefetch", {**win_attrs, "seq": seq}, track=track)
        if chaoslib.active() is not None:
            chaoslib.maybe_inject("host_transfer", self._chaos_index)
        self._chaos_index += 1
        if shardings is not None:
            out = jax.tree.map(jax.device_put, payload, shardings)
        else:
            dev = self.device
            out = jax.tree.map(lambda a: jax.device_put(a, dev),
                               payload)
        self.swap_ins += 1
        self.prefetch_bytes += nbytes
        self.update_gauges()
        return out, (t_disp, track, seq, time.perf_counter(),
                     win_attrs)

    def complete_pull(self, handle, *, chunk_windows=()) -> None:
        """Close a pull's ``mem.prefetch`` window at an OBSERVED
        completion (the caller synced past the consumer) and fold its
        overlap against the consumer's ``chunk_windows`` — host-stamp
        ``(t0, t1)`` pairs, the serving chunk / training accumulation
        windows — into ``prefetch_overlap_frac``."""
        t_disp, track, seq, t0, attrs = handle
        t_done = time.perf_counter()
        span = max(t_done - t0, 1e-9)
        under = sum(max(0.0, min(t_done, e) - max(t0, s))
                    for s, e in chunk_windows)
        self._prefetch_total_s += span
        self._prefetch_overlap_s += min(under, span)
        rec = tracelib.active()
        if rec is not None and t_disp:
            rec.mark_complete("mem.prefetch", t_disp,
                              {**attrs, "seq": seq}, track=track)

    @property
    def prefetch_overlap_frac(self) -> float | None:
        """Measured fraction of prefetch-window time spent under the
        consumer's in-flight compute windows — the proved-overlap
        number ``bench_serving --offload`` reports and
        ``harness/regress.py`` gates. None until a pull completed."""
        if self._prefetch_total_s <= 0:
            return None
        return self._prefetch_overlap_s / self._prefetch_total_s

    def _close_ripe_evicts(self) -> None:
        """Close open ``mem.evict`` windows whose payloads are ready —
        a cheap block at the round boundary (the transfer had a whole
        round to land; numpy-fallback payloads are ready at dispatch)."""
        if not self._open_evicts:
            return
        import jax

        rec = tracelib.active()
        for t_disp, track, payload, seq, attrs in self._open_evicts:
            # jaxlint: disable=host-sync-in-dispatch — completion
            # measurement at the round boundary (the window must not
            # close before the device->host copy it covers resolved)
            jax.block_until_ready(payload)
            if rec is not None and t_disp:
                rec.mark_complete("mem.evict", t_disp,
                                  {**attrs, "seq": seq}, track=track)
        self._open_evicts.clear()

    def drain(self) -> None:
        """Close every open window (end of a run / a test's flush)."""
        self._close_ripe_evicts()
