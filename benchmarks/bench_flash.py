"""Micro-benchmark: flash attention fwd / fwd+bwd on the real chip.

Usage: python benchmarks/bench_flash.py [T ...]

Per-pass device time via the repo's tunnel-proof protocol
(harness.timing.amortized_seconds): the kernel is iterated inside ONE
dispatch with lax.fori_loop (output fed back as q so iterations chain),
then timed at two iteration counts and differenced — dispatch/readback
latency cancels.
"""

import functools
import sys

import jax
import jax.numpy as jnp
from jax import lax

from hpc_patterns_tpu.harness.timing import amortized_seconds
from hpc_patterns_tpu.ops import flash_attention
from hpc_patterns_tpu.parallel.ring_attention import full_attention


def fwd_looper(attn, q, k, v, n):
    def body(_, acc):
        out = attn(acc, k, v)
        return out.astype(acc.dtype)

    # scalar readback: the host round-trip cost must not depend on T
    return jnp.sum(lax.fori_loop(0, n, body, q).astype(jnp.float32))


def bwd_looper(attn, q, k, v, n):
    def loss(q, k, v):
        return jnp.sum(attn(q, k, v).astype(jnp.float32))

    grad = jax.grad(loss, argnums=(0, 1, 2))

    def body(_, acc):
        dq, dk, dv = grad(acc, k, v)
        # consume dk/dv or XLA dead-code-eliminates the dK/dV pass and
        # the timed "fwd+bwd" silently drops a third of the backward
        return (dq + 1e-6 * (acc + jnp.sum(dk) + jnp.sum(dv))).astype(acc.dtype)

    return jnp.sum(lax.fori_loop(0, n, body, q).astype(jnp.float32))


ITERS = 256


def per_pass(looper, attn, q, k, v, iters=None):
    iters = iters or ITERS
    jitted = jax.jit(
        functools.partial(looper, attn), static_argnums=(3,)
    )
    return amortized_seconds(
        lambda n: jitted(q, k, v, n), iters=iters, repetitions=3,
        base_iters=iters // 2,
    )


def main():
    global ITERS
    for a in sys.argv[1:]:
        if a.startswith("--iters="):
            ITERS = int(a.split("=")[1])
    Ts = [int(a) for a in sys.argv[1:] if not a.startswith("-")] or [4096, 8192]
    B, H, D = 1, 8, 128
    Hkv = H
    for a in sys.argv[1:]:
        if a.startswith("--kv="):
            Hkv = int(a.split("=")[1])
    for T in Ts:
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(kq, (B, T, H, D), jnp.bfloat16)
        k = jax.random.normal(kk, (B, T, Hkv, D), jnp.bfloat16)
        v = jax.random.normal(kv, (B, T, Hkv, D), jnp.bfloat16)

        # same workload both sides: causal (full_attention defaults to
        # causal=False — leaving it off would time half the work for
        # flash and inflate the speedup ~2x)
        flash = lambda q, k, v: flash_attention(q, k, v, causal=True)
        dense = lambda q, k, v: full_attention(q, k, v, causal=True)

        tf = per_pass(fwd_looper, flash, q, k, v)
        tfg = per_pass(bwd_looper, flash, q, k, v)
        # causal fwd: 2 matmuls x 2*T^2*D MACs x 1/2 triangle
        flops_fwd = 2 * 2 * B * H * T * T * D * 0.5
        print(f"T={T:6d} flash fwd {tf*1e3:8.3f} ms "
              f"({flops_fwd/tf/1e12:5.1f} TF/s)  fwd+bwd {tfg*1e3:8.3f} ms "
              f"({3.5*flops_fwd/tfg/1e12:5.1f} TF/s)")
        if T <= 8192 and "--flash-only" not in sys.argv and Hkv == H:
            td = per_pass(fwd_looper, dense, q, k, v)
            tdg = per_pass(bwd_looper, dense, q, k, v)
            print(f"         dense fwd {td*1e3:8.3f} ms "
                  f"({flops_fwd/td/1e12:5.1f} TF/s)  fwd+bwd {tdg*1e3:8.3f} ms"
                  f"  (flash speedup fwd {td/tf:4.2f}x, fwd+bwd {tdg/tfg:4.2f}x)")


if __name__ == "__main__":
    main()
