"""End-to-end training throughput (tokens/s) on the real chip.

One jitted function runs N optimizer steps via lax.scan (params/opt
state as carry — in-place in HBM), timed with the tunnel-proof
amortized protocol (harness.timing.amortized_seconds), so the number is
pure device time per step. With ``--offload=1`` the optimizer moments
live in pinned host RAM and the measured step time INCLUDES their
per-step PCIe round-trip (that is the cost being measured).

Usage: python benchmarks/bench_train.py [--seq=N] [--layers=N] [--attn=flash]
"""

import sys

import jax
from jax import lax

from hpc_patterns_tpu.harness.timing import amortized_seconds
from hpc_patterns_tpu.models import TransformerConfig
from hpc_patterns_tpu.models.train import (
    init_train_state,
    make_batch,
    make_optimizer,
)
from hpc_patterns_tpu.models.transformer import loss_fn
from functools import partial
import optax


def arg(name, default, cast):
    for a in sys.argv[1:]:
        if a.startswith(f"--{name}="):
            return cast(a.split("=", 1)[1])
    return default


def main():
    on_tpu = jax.default_backend() == "tpu"
    cfg = TransformerConfig(
        vocab=arg("vocab", 32768 if on_tpu else 256, int),
        d_model=arg("d", 1024 if on_tpu else 64, int),
        n_heads=arg("heads", 8 if on_tpu else 4, int),
        n_layers=arg("layers", 8 if on_tpu else 2, int),
        d_ff=arg("ff", 4096 if on_tpu else 128, int),
        max_seq=arg("seq", 2048 if on_tpu else 64, int),
        dtype="bfloat16",
        attention=arg("attn", "flash" if on_tpu else "full", str),
        remat=bool(arg("remat", 0, int)),
        n_kv_heads=arg("kv", 0, int),
        loss_chunk=arg("chunk", 0, int),
        remat_policy=arg("rp", "split", str),
        pos_embed=arg("pos", "learned", str),
        mlp_impl=arg("mlp", "dense", str),
    )
    batch = arg("batch", 8 if on_tpu else 2, int)
    seq = cfg.max_seq
    optimizer = make_optimizer()

    offload = bool(arg("offload", 0, int))
    if offload and not on_tpu:
        print("note: --offload=1 needs a TPU backend; running baseline")
        offload = False
    params, opt_state = init_train_state(jax.random.PRNGKey(0), cfg,
                                         optimizer=optimizer)
    if offload:
        from hpc_patterns_tpu.models.train import offload_opt_state

        hosted = offload_opt_state(opt_state)
        if hosted is opt_state:
            # the probe-gated identity fallback fired: measuring this
            # as the offload row would silently report a no-op tier
            print("note: pinned_host unusable on this backend; "
                  "running baseline instead of a no-op offload row")
            offload = False
        else:
            opt_state = hosted
    tokens = make_batch(jax.random.PRNGKey(1), cfg, batch, seq)

    if offload:
        from hpc_patterns_tpu.models.train import offload_shardings

        host_sh, hbm_sh = offload_shardings(opt_state)
    else:
        host_sh = hbm_sh = None

    # no donation: the timed call runs repeatedly from the same state
    # (donation would invalidate it); inside the scan the carry updates
    # in place anyway, so per-step HBM behavior matches real training
    @partial(
        jax.jit, static_argnums=(2,),
        in_shardings=((None, host_sh), None) if offload else None,
    )
    def run_t(carry, tokens, n):
        def one_step(carry, _):
            params, opt_state = carry
            if hbm_sh is not None:
                opt_state = jax.device_put(opt_state, hbm_sh)
            loss, grads = jax.value_and_grad(partial(loss_fn, cfg=cfg))(
                params, tokens
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if host_sh is not None:
                opt_state = jax.device_put(opt_state, host_sh)
            return (params, opt_state), loss

        _, losses = lax.scan(one_step, carry, None, length=n)
        return losses[-1]

    n_params = sum(x.size for x in jax.tree.leaves(params))
    iters = arg("iters", 32 if on_tpu else 4, int)
    t_step = amortized_seconds(
        lambda n: run_t((params, opt_state), tokens, n),
        iters=iters,
        repetitions=3,
        base_iters=iters // 2,
    )
    tok_per_step = batch * seq
    # decoder FLOPs/token ~ 6*N + 12*L*T*D_head*H (attention)
    flops_tok = 6 * n_params + 12 * cfg.n_layers * seq * cfg.d_model * 0.5
    print(f"config: d={cfg.d_model} L={cfg.n_layers} H={cfg.n_heads} "
          f"ff={cfg.d_ff} T={seq} B={batch} attn={cfg.attention} "
          f"remat={cfg.remat}/{cfg.remat_policy} chunk={cfg.loss_chunk} "
          f"offload={offload} params={n_params/1e6:.1f}M")
    print(f"step: {t_step*1e3:.2f} ms  throughput: "
          f"{tok_per_step/t_step:,.0f} tok/s  "
          f"model flops util: {flops_tok*tok_per_step/t_step/1e12:.1f} TF/s")


if __name__ == "__main__":
    main()
