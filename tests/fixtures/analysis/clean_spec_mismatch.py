"""Known-clean: specs consistent with the module's declared mesh axes,
multi-axis dims as tuples, and a donation whose in/out shardings
match (the buffer can alias). Variable axis names are never judged —
a module building specs for a caller-provided mesh stays silent."""

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def build(devs, cfg):
    mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "tp"))
    batch = NamedSharding(mesh, P("dp", None))
    fused = NamedSharding(mesh, P(("dp", "tp"), None))
    by_cfg = NamedSharding(mesh, P(cfg.axis, None))  # variable: unjudged
    return batch, fused, by_cfg


@partial(jax.jit, donate_argnums=(0,),
         in_shardings=(P("dp", None),),
         out_shardings=(P("dp", None), P("tp", None)))
def aliasable_donation(x):
    return x * 2, x.sum(axis=0, keepdims=True)
