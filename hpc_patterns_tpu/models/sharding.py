"""Parameter/batch sharding rules: Megatron column/row TP + dp/sp data
layout over one named mesh.

These PartitionSpecs are the annotation form of the explicit
parallel/tensor.py helpers (column-parallel = output-feature sharded,
row-parallel = input-feature sharded → XLA inserts the psum the helpers
spell out — the library-collective path of §2.3). Axis order follows
topology.make_mesh guidance: tp last (fastest-varying → ICI neighbors),
then sp, then dp.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hpc_patterns_tpu.models.sharding_util import mesh_axis_size, resolve_spec  # noqa: F401 — re-exported
from hpc_patterns_tpu.models.transformer import TransformerConfig


def param_specs(cfg: TransformerConfig) -> dict:
    """PartitionSpec pytree matching init_params' structure. Layer
    weights carry a leading (unsharded) n_layers scan axis.

    With ``cfg.fsdp``, each large weight additionally shards one of its
    feature dims over ``axis_fsdp`` (ZeRO-3: params, grads, and optax
    moments all live sharded; XLA all-gathers a layer's weights just
    before use and reduce-scatters its grads — entirely from these
    annotations). The fsdp dim is always one tp leaves unsharded, so
    tp x fsdp compose."""
    tp = cfg.axis_tp
    fs = cfg.axis_fsdp if cfg.fsdp else None
    layers = {
        "ln1_scale": P(None, None),
        "ln2_scale": P(None, None),
        "wqkv": P(None, fs, tp),         # column-parallel (heads split)
        "wo": P(None, tp, fs),           # row-parallel
    }
    if cfg.n_experts:
        ep = cfg.axis_ep
        layers["router"] = P(None, None, None)  # replicated routing table
        layers["w1"] = P(None, ep, fs, None)    # experts over ep
        layers["w2"] = P(None, ep, None, fs)
    else:
        layers["w1"] = P(None, fs, tp)   # column-parallel
        layers["w2"] = P(None, tp, fs)   # row-parallel
    pos = {} if cfg.pos_embed == "rope" else {"pos_embed": P(None, fs)}
    return {
        "embed": P(None, fs),            # lookup local; features sharded
        **pos,
        "layers": layers,
        "ln_f_scale": P(None),
        "lm_head": P(fs, tp),            # vocab-sharded logits
    }


def param_shardings(mesh: Mesh, cfg: TransformerConfig):
    """NamedSharding pytree for params (pass as jit in_shardings /
    device_put target)."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, resolve_spec(spec, mesh, cfg.mesh_axes)),
        param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P),
    )


def batch_sharding(mesh: Mesh, cfg: TransformerConfig) -> NamedSharding:
    """Tokens (batch, seq): batch over dp, sequence over sp — the rank→
    data map, ≙ the reference's rank→device policies (devices.hpp:22-59)
    lifted to arrays."""
    return NamedSharding(
        mesh, resolve_spec(P(cfg.batch_axes, cfg.axis_sp), mesh, cfg.mesh_axes)
    )


def shard_params(params, mesh: Mesh, cfg: TransformerConfig):
    """Place a (host or single-device) param pytree onto the mesh."""
    return jax.device_put(params, param_shardings(mesh, cfg))
