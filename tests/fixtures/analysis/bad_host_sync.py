"""Known-bad: host readbacks inside dispatch-critical functions —
both the configured-name form (``_dispatch_chunk``) and the
``@dispatch_critical`` marker form."""

import jax
import numpy as np

from hpc_patterns_tpu.analysis import dispatch_critical


def _dispatch_chunk(engine):
    out = engine.step()
    jax.block_until_ready(out)  # EXPECT: host-sync-in-dispatch
    val = out.item()  # EXPECT: host-sync-in-dispatch
    snap = np.asarray(engine.pos)  # EXPECT: host-sync-in-dispatch
    return val, snap


@dispatch_critical
def enqueue_next(engine):
    return float(engine.step())  # EXPECT: host-sync-in-dispatch


def _admit(engine, req):
    got = jax.device_get(engine.logits)  # EXPECT: host-sync-in-dispatch
    engine.table = np.array(engine.table)  # EXPECT: host-sync-in-dispatch
    return got
