"""Ring attention: context parallelism on the ring engine.

The reference's ring allreduce dataflow — per step: neighbor ppermute,
local combine, buffer rotation (allreduce-mpi-sycl.cpp:173-182) — with
the combine generalized from ``VC += VA`` to blockwise online-softmax
attention. This is exactly the generalization SURVEY.md §5 calls for
("per-step neighbor ppermute + local compute + buffer rotation is exactly
the ring-attention/context-parallel dataflow").

Rank r holds the r-th sequence block of Q, K, V. K/V blocks travel the
ring; each step attends local Q against the visiting K/V block and folds
the result into a numerically-stable running (max, sum, output) — the
flash-attention accumulator — so no rank ever materializes the full
sequence. Causal masking uses global positions derived from the block's
source rank, so the sharded result is bit-for-bit the attention of the
gathered sequence (the analytic-oracle test style of SURVEY.md §4.2).

On TPU the K/V ppermute rides ICI neighbor links while the MXU computes
the current block — the same DMA/compute overlap story as the
concurrency suite, one level up.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from hpc_patterns_tpu.comm import ring

_NEG_INF = -1e30  # finite mask value: avoids inf-inf=nan in the rescale


def _check_gqa(q, k, v) -> int:
    """Validate head counts; return the GQA group factor H // Hkv (1 =
    MHA). q head h attends kv head h // group — the same map as the
    flash kernel's GQA row maps (ops/flash_attention.py)."""
    H, Hkv = q.shape[2], k.shape[2]
    if H % max(Hkv, 1) or v.shape[2] != Hkv:
        raise ValueError(
            f"kv heads {Hkv}/{v.shape[2]} must match and divide "
            f"n_heads {H} (GQA attends the narrow K/V)"
        )
    return H // Hkv


def _grouped_scores(q, k, scale):
    """(B, H, T, S) f32 scores against possibly-narrow K: q head h
    scores kv head h // group, with no expanded K copy."""
    B, T, H, D = q.shape
    Hkv = k.shape[2]
    qg = q.reshape(B, T, Hkv, H // Hkv, D)
    s = jnp.einsum(
        "btkgd,bskd->bkgts", qg.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    return s.reshape(B, H, T, k.shape[1])


def _grouped_pv(p, v):
    """(B, H, T, D) f32 = P @ V with possibly-narrow V (no expansion)."""
    B, H, T, S = p.shape
    Hkv = v.shape[2]
    pg = p.reshape(B, Hkv, H // Hkv, T, S)
    out = jnp.einsum("bkgts,bskd->bkgtd", pg, v.astype(jnp.float32))
    return out.reshape(B, H, T, v.shape[3])


def _block_step(q, k, v, acc, m, l, *, scale, q_offset, k_offset, causal):
    """Fold one visiting K/V block into the running accumulator.

    q: (B, T, H, D); k/v: (B, S, Hkv, D) with Hkv | H (GQA — the narrow
    block is what travels the ring); acc: (B, H, T, D) f32;
    m, l: (B, H, T) f32 running max / normalizer.
    """
    s = _grouped_scores(q, k, scale)
    if causal:
        t_idx = q_offset + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s_idx = k_offset + lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(s_idx <= t_idx, s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # exp(_NEG_INF - m_new) underflows to 0 — masked rows stay masked
    p = jnp.exp(s - m_new[..., None])
    rescale = jnp.exp(m - m_new)
    l_new = l * rescale + p.sum(axis=-1)
    acc_new = acc * rescale[..., None] + _grouped_pv(p, v)
    return acc_new, m_new, l_new


def _kv_rotate(axis: str, shift_impl: str):
    """The per-step K/V neighbor hop, selectable between the XLA
    collective permute (``"ppermute"``) and the device-initiated Pallas
    remote-DMA shift (``"fused"``, comm/fused.py) — the same
    algorithm-selection axis the Communicator exposes for allreduce,
    at the ring-attention step. Both produce identical bytes (a shift
    is a pure permutation); what changes is who issues the transfer."""
    if shift_impl == "ppermute":
        return lambda kv: jax.tree.map(
            lambda t: ring.ring_shift(t, axis, 1), kv)
    if shift_impl == "fused":
        from hpc_patterns_tpu.comm import fused
        from hpc_patterns_tpu.ops import tiling

        # K and V shift as two data-independent kernels the scheduler
        # may overlap on chip — distinct registered collective_ids keep
        # their barrier/DMA state apart (the registry in ops/tiling.py
        # owns the numbering; hand-picked integers are a pallaslint
        # finding)
        k_id = tiling.collective_id("parallel.ring_attention.kshift")
        v_id = tiling.collective_id("parallel.ring_attention.vshift")

        def rotate(kv):
            k_blk, v_blk = kv
            return (fused.fused_ring_shift(k_blk, axis, 1,
                                           collective_id=k_id),
                    fused.fused_ring_shift(v_blk, axis, 1,
                                           collective_id=v_id))

        return rotate
    raise ValueError(
        f"shift_impl {shift_impl!r} not in ('ppermute', 'fused')")


def ring_attention(
    q,
    k,
    v,
    axis: str,
    *,
    causal: bool = False,
    scale: float | None = None,
    impl: str = "dense",
    block_q: int | None = None,
    block_k: int | None = None,
    shift_impl: str = "ppermute",
):
    """Attention over a sequence sharded on mesh ``axis`` (rank-local; run
    inside ``shard_map``).

    ``q``, ``k``, ``v``: (batch, seq_local, heads, head_dim) — the local
    sequence block; global sequence = blocks in rank order. K/V may be
    GQA-narrow (kv_heads dividing q's heads): the narrow block is what
    circulates, cutting per-step ring traffic by the group factor.
    Returns the local block of the softmax attention output, same
    shape/dtype as ``q``, numerically equal to attending the gathered
    sequence.

    ``impl``: per-step local compute. ``"dense"`` materializes the
    (T_local, S) score block (any shape); ``"flash"`` runs the Pallas
    blockwise kernel per visiting block (ops.flash_attention_block) and
    merges partials by logsumexp — O(block) VMEM on-chip, MXU-shaped,
    and causally-skipped blocks cost no fetches or matmuls. Requires
    the local sequence to divide by the (clamped) block sizes.

    ``shift_impl``: who moves the K/V block each step — ``"ppermute"``
    (XLA collective permute, the default) or ``"fused"`` (the
    device-initiated Pallas remote-DMA shift; single-axis meshes).
    """
    if q.ndim != 4:
        raise ValueError(f"want (batch, seq, heads, head_dim), got {q.shape}")
    if impl not in ("dense", "flash"):
        raise ValueError(f"impl {impl!r} not in ('dense', 'flash')")
    rotate = _kv_rotate(axis, shift_impl)
    _check_gqa(q, k, v)
    size = ring.axis_size(axis)
    me = ring.axis_index(axis)
    B, T, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    q_offset = me * T

    if impl == "flash":
        return _ring_attention_flash(
            q, k, v, axis, size=size, me=me, q_offset=q_offset,
            causal=causal, scale=scale, block_q=block_q, block_k=block_k,
            rotate=rotate,
        )

    acc = jnp.zeros((B, H, T, D), jnp.float32)
    m = jnp.full((B, H, T), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, T), jnp.float32)

    kv = (k, v)
    for step in range(size):
        k_blk, v_blk = kv
        # block visiting at step s started at rank (me - s) % size
        src = (me - step) % size
        acc, m, l = _block_step(
            q, k_blk, v_blk, acc, m, l,
            scale=scale, q_offset=q_offset, k_offset=src * k_blk.shape[1],
            causal=causal,
        )
        if step + 1 < size:
            # rotate K/V one neighbor over (ICI hop), like the reference's
            # SendRecvRing + swap(VA, VB)
            kv = rotate(kv)

    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.einsum("bhtd->bthd", out).astype(q.dtype)


def _ring_attention_flash(q, k, v, axis, *, size, me, q_offset, causal,
                          scale, block_q, block_k, rotate):
    """Flash per-step ring attention: each visiting K/V block is one
    Pallas partial attention (normalized within the block, with its
    logsumexp), merged into the running result by the standard
    logsumexp combine. Same ring dataflow, kernel-grade local compute."""
    from hpc_patterns_tpu.ops import flash_attention_block

    out = jnp.zeros(q.shape, jnp.float32)           # (B, T, H, D)
    lse = jnp.full(q.shape[:3], _NEG_INF, jnp.float32)  # (B, T, H)

    kv = (k, v)
    for step in range(size):
        k_blk, v_blk = kv
        src = (me - step) % size
        o_b, lse_b = flash_attention_block(
            q, k_blk, v_blk, q_offset, src * k_blk.shape[1],
            causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        )
        m = jnp.maximum(lse, lse_b)
        e_run = jnp.exp(lse - m)
        e_b = jnp.exp(lse_b - m)
        denom = e_run + e_b
        out = (out * e_run[..., None]
               + o_b.astype(jnp.float32) * e_b[..., None]) / denom[..., None]
        lse = m + jnp.log(denom)
        if step + 1 < size:
            kv = rotate(kv)

    return out.astype(q.dtype)


def full_attention(q, k, v, *, causal: bool = False, scale: float | None = None):
    """Single-device oracle: plain softmax attention over the full
    sequence, used by tests to validate the ring result (§4.2 style).
    K/V may be GQA-narrow (kv_heads dividing q's heads) — grouped-query
    scores, never an expanded K/V copy."""
    _check_gqa(q, k, v)
    B, T, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    s = _grouped_scores(q, k, scale)
    if causal:
        t_idx = lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s_idx = lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(s_idx <= t_idx, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = _grouped_pv(p, v)
    return jnp.einsum("bhtd->bthd", out).astype(q.dtype)
