"""Speculative greedy decoding: a small draft model proposes, the
target model verifies in one batched pass.

The serving-latency play the KV-cache machinery enables: plain greedy
decode is one big-model forward per token (cache-read-bound,
benchmarks/RESULTS.md); here a cheap draft model runs ``gamma``
sequential steps and the target scores the whole proposed chunk with
ONE ``decode.extend_step`` — large-matmul shapes instead of gamma
sequential single-token reads. With greedy acceptance the output is
PROVABLY identical to the target's own greedy decode, whatever the
draft proposes (the oracle the tests pin): accepted proposals are
exactly the tokens the target would have picked, and the first
disagreement is replaced by the target's token.

Bookkeeping invariant (both caches, one shared position cursor): at the
top of each iteration the caches hold K/V for the prompt and every
emitted token EXCEPT the last, which is ``cur`` (pending). The draft
runs gamma+1 steps (the +1 writes the last proposal's K/V so a fully
accepted round leaves no hole), the target extend writes
[cur, proposals...]; rejected rows go stale and are simply overwritten
when the cursor re-crosses them — position masking makes stale rows
invisible (the same static-shape trick as the cache itself).

Batch is 1 per call: acceptance lengths diverge per sequence, and a
per-row position cursor cannot drive a single dynamic_update_slice
(vmap over sequences instead if needed).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from hpc_patterns_tpu.models.decode import (
    decode_step,
    extend_step,
    prefill,
)
from hpc_patterns_tpu.models.transformer import TransformerConfig


@partial(jax.jit, static_argnums=(1, 3, 5, 6))
def _speculative_jit(params, cfg, draft_params, draft_cfg, prompt,
                     new_tokens, gamma):
    B, T = prompt.shape
    max_len = T + new_tokens + gamma + 1  # slack for the final round
    logits, cache = prefill(params, prompt, cfg, max_len)
    _, dcache = prefill(draft_params, prompt, draft_cfg, max_len)
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (1,)

    out = jnp.zeros((new_tokens + gamma + 1,), jnp.int32)
    out = out.at[0].set(first[0])

    def cond(state):
        _, _, _, _, n_out = state
        return n_out < new_tokens

    def iteration(state):
        cache, dcache, pos, cur, n_out = state
        # --- draft proposes gamma tokens (gamma+1 steps: the extra one
        # writes the last proposal's K/V — see module docstring)
        props = []
        tok = cur
        dc = dcache
        for j in range(gamma + 1):
            dlogits, dc = decode_step(draft_params, dc, pos + j, tok,
                                      draft_cfg)
            tok = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
            if j < gamma:
                props.append(tok[0])
        props = jnp.stack(props)  # (gamma,)

        # --- target verifies [cur, props] in ONE extend
        chunk = jnp.concatenate([cur, props])[None, :]  # (1, gamma+1)
        vlogits, cache = extend_step(params, cache, pos, chunk, cfg)
        t_all = jnp.argmax(vlogits[0], axis=-1).astype(jnp.int32)  # (gamma+1,)

        # longest accepted prefix: props[j] must equal the target's own
        # next token t_all[j]; a in [0, gamma] by construction
        matches = (props == t_all[:gamma]).astype(jnp.int32)
        a = jnp.sum(jnp.cumprod(matches))
        nxt = t_all[a]  # the target's token at the first disagreement
        # emitted this round: props[:a] then nxt (positions > a are
        # filler, overwritten by the next round's slice)
        props_padded = jnp.concatenate([props, props[-1:]])
        emit = jnp.where(jnp.arange(gamma + 1) < a, props_padded, nxt)
        return cache, dc, pos + a + 1, nxt[None], n_out + a + 1, emit

    def body(state_out):
        state, out = state_out
        n_out = state[4]
        cache, dc, pos2, cur2, n_out2, emit = iteration(state)
        out = lax.dynamic_update_slice(out, emit, (n_out,))
        return (cache, dc, pos2, cur2, n_out2), out

    state = (cache, dcache, jnp.int32(T), first, jnp.int32(1))
    (state, out) = lax.while_loop(
        lambda so: cond(so[0]),
        body,
        (state, out),
    )
    return out[:new_tokens][None, :]


def _validate(cfg, draft_cfg, prompt_len, new_tokens, gamma):
    """The shared argument guards of both entry points."""
    if cfg.vocab != draft_cfg.vocab:
        raise ValueError(
            f"draft vocab {draft_cfg.vocab} != target vocab {cfg.vocab}"
        )
    if new_tokens < 1:
        raise ValueError(f"new_tokens must be >= 1, got {new_tokens}")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if prompt_len + new_tokens + gamma + 1 > min(cfg.max_seq,
                                                 draft_cfg.max_seq):
        raise ValueError(
            f"prompt {prompt_len} + new {new_tokens} + gamma slack "
            f"{gamma + 1} exceeds max_seq "
            f"{min(cfg.max_seq, draft_cfg.max_seq)}"
        )


def speculative_generate(params, cfg: TransformerConfig, draft_params,
                         draft_cfg: TransformerConfig, prompt,
                         new_tokens: int, *, gamma: int = 4):
    """Greedy continuation (1, new_tokens) int32, token-identical to
    ``greedy_generate(params, prompt, cfg, new_tokens)`` — the draft
    only changes HOW FAST tokens come, never which tokens.

    ``prompt``: (1, T); ``gamma``: proposals per round (the draft/target
    cost ratio picks it — more acceptance, longer verified chunks).
    Both configs must share the vocabulary; compute-dtype caches.
    """
    if prompt.shape[0] != 1:
        raise ValueError(
            "speculative decoding is per-sequence (batch 1): acceptance "
            "lengths diverge per row; vmap over sequences instead"
        )
    _validate(cfg, draft_cfg, prompt.shape[1], new_tokens, gamma)
    return _speculative_jit(params, cfg, draft_params, draft_cfg, prompt,
                            new_tokens, gamma)


def speculative_generate_batched(params, cfg: TransformerConfig,
                                 draft_params,
                                 draft_cfg: TransformerConfig, prompts,
                                 new_tokens: int, *, gamma: int = 4):
    """Batched speculative decoding via ``jax.vmap`` over sequences:
    each row runs its own acceptance loop (vmap lifts the while_loop to
    run until every row finishes — rows that finish early mask). Output
    (B, new_tokens), row-wise token-identical to
    :func:`speculative_generate` (oracle-tested). Wall-clock note: the
    batch advances at the SLOWEST row's acceptance rate; per-sequence
    calls win when acceptance varies wildly."""
    if prompts.ndim != 2:
        raise ValueError(f"prompts must be (B, T), got {prompts.shape}")
    _validate(cfg, draft_cfg, prompts.shape[1], new_tokens, gamma)

    def one(row):
        return _speculative_jit(params, cfg, draft_params, draft_cfg,
                                row[None, :], new_tokens, gamma)[0]

    return jax.vmap(one)(prompts)
