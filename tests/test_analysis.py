"""jaxlint (hpc_patterns_tpu.analysis): golden fixture findings,
suppression semantics, the CI gate over the live package, and the
runtime donation-poison helper.

The fixture corpus under ``tests/fixtures/analysis/`` is the rule
catalog's executable form: one known-bad and one known-clean file per
rule, with expected findings marked line-exact by ``EXPECT: <rule>``
trailing comments — the golden comparison reads the markers, so a
fixture edit can't silently desynchronize from its expectations.
"""

from __future__ import annotations

import json
import re
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hpc_patterns_tpu.analysis import __main__ as cli
from hpc_patterns_tpu.analysis import core, runtime
from hpc_patterns_tpu.analysis.core import AnalysisConfig, ModuleInfo
from hpc_patterns_tpu.analysis.rules import _donor_table

FIXTURES = Path(__file__).parent / "fixtures" / "analysis"
PACKAGE = Path(__file__).resolve().parent.parent / "hpc_patterns_tpu"

_EXPECT_RE = re.compile(r"EXPECT:\s*([a-z\-]+(?:\s*,\s*[a-z\-]+)*)")


def _expected_findings() -> dict[tuple[str, int], set[str]]:
    """{(fixture name, line): {rules}} parsed from EXPECT markers."""
    expected: dict[tuple[str, int], set[str]] = {}
    for f in sorted(FIXTURES.glob("*.py")):
        for lineno, line in enumerate(f.read_text().splitlines(), 1):
            m = _EXPECT_RE.search(line)
            if m:
                expected[(f.name, lineno)] = {
                    r.strip() for r in m.group(1).split(",")}
    return expected


def _actual_findings() -> dict[tuple[str, int], set[str]]:
    report = core.run_paths([FIXTURES])
    actual: dict[tuple[str, int], set[str]] = {}
    for f in report.findings:
        actual.setdefault((Path(f.path).name, f.line), set()).add(f.rule)
    return actual


class TestGoldenFixtures:
    def test_findings_match_expect_markers_exactly(self):
        expected, actual = _expected_findings(), _actual_findings()
        assert expected, "fixture corpus lost its EXPECT markers"
        missing = {k: v for k, v in expected.items() if k not in actual}
        extra = {k: v for k, v in actual.items() if k not in expected}
        assert not missing and not extra, (
            f"missing={missing} extra={extra}")
        for key in expected:
            assert actual[key] == expected[key], (
                f"{key}: expected {expected[key]}, got {actual[key]}")

    def test_every_rule_demonstrated_by_a_caught_fixture(self):
        # the acceptance criterion: all five hazard rules fire on the
        # corpus, including the minimized PR 2 donation-alias replica
        caught = {r for rules in _actual_findings().values()
                  for r in rules}
        assert {"donation-alias", "host-sync-in-dispatch",
                "recompile-hazard", "prng-key-reuse",
                "tracer-leak"} <= caught

    def test_pr2_reproducer_is_caught_at_the_view_line(self):
        live, _ = core.analyze_file(
            FIXTURES / "bad_donation_alias.py")
        donation = [f for f in live if f.rule == "donation-alias"]
        assert donation, "the PR 2 reproducer must be flagged"
        src = (FIXTURES / "bad_donation_alias.py").read_text()
        flagged_line = src.splitlines()[donation[0].line - 1]
        assert "np.asarray(self.pos)" in flagged_line

    def test_clean_fixtures_stay_clean(self):
        for f in sorted(FIXTURES.glob("clean_*.py")):
            live, suppressed = core.analyze_file(f)
            assert not live, f"{f.name}: {[x.format() for x in live]}"
            assert not suppressed

    def test_findings_carry_location_and_hint(self):
        live, _ = core.analyze_file(FIXTURES / "bad_recompile.py")
        f = live[0]
        assert f.line > 0 and f.path.endswith("bad_recompile.py")
        assert f.hint  # every shipped rule must suggest the fix
        assert f"{f.path}:{f.line}" in f.format()


class TestSuppression:
    def test_named_suppressions_silence_and_are_counted(self):
        live, suppressed = core.analyze_file(FIXTURES / "suppressed.py")
        assert {f.rule for f in suppressed} == {
            "recompile-hazard", "host-sync-in-dispatch"}
        assert len(suppressed) == 2

    def test_bare_and_unknown_disable_are_findings(self):
        live, _ = core.analyze_file(FIXTURES / "suppressed.py")
        bad = [f for f in live if f.rule == "bad-suppression"]
        assert len(bad) == 2  # one bare, one unknown-rule
        # and the hazards under them stay LIVE
        assert sum(1 for f in live if f.rule == "recompile-hazard") == 2

    def test_standalone_suppression_skips_comment_lines(self):
        # the suppressed.py standalone form has a two-line
        # justification between the directive and the code
        _, suppressed = core.analyze_file(FIXTURES / "suppressed.py")
        assert any(f.rule == "host-sync-in-dispatch"
                   for f in suppressed)

    def test_bad_suppression_is_not_itself_suppressible(self, tmp_path):
        f = tmp_path / "m.py"
        f.write_text("x = 1  # jaxlint: disable  # jaxlint: disable\n")
        live, suppressed = core.analyze_file(f)
        assert any(x.rule == "bad-suppression" for x in live)


class TestEngine:
    def test_syntax_error_is_a_finding_not_a_crash(self, tmp_path):
        f = tmp_path / "broken.py"
        f.write_text("def f(:\n")
        live, _ = core.analyze_file(f)
        assert [x.rule for x in live] == ["parse-error"]

    def test_alias_resolution_sees_through_import_spellings(self):
        mod = ModuleInfo.parse(
            "m.py", "import numpy as xyz\nv = xyz.asarray(q)\n")
        call = mod.tree.body[1].value
        assert mod.resolve(call.func) == "numpy.asarray"

    def test_select_runs_only_named_rules(self):
        cfg = AnalysisConfig(select=frozenset({"prng-key-reuse"}))
        report = core.run_paths([FIXTURES], cfg)
        assert set(report.by_rule()) == {"prng-key-reuse"}

    def test_nested_function_hazard_reported_once(self, tmp_path):
        # rules walking nested defs see inner statements from both the
        # outer and inner function — the engine dedupes to one finding
        f = tmp_path / "nested.py"
        f.write_text(
            "from functools import partial\n"
            "import jax\n"
            "import numpy as np\n"
            "@partial(jax.jit, donate_argnums=(0,))\n"
            "def step(x):\n"
            "    return x\n"
            "def outer():\n"
            "    def inner(y):\n"
            "        v = np.asarray(y)\n"
            "        step(y)\n"
            "        return v.sum()\n"
            "    return inner\n")
        live, _ = core.analyze_file(f)
        assert [x.rule for x in live] == ["donation-alias"]

    def test_baseline_roundtrip_tolerates_known_findings(self, tmp_path):
        base = tmp_path / "baseline.json"
        report = core.run_paths([FIXTURES])
        core.write_baseline(base, report.findings)
        again = core.run_paths([FIXTURES],
                               baseline=core.load_baseline(base))
        assert not again.findings
        assert len(again.baselined) == len(report.findings)
        assert json.loads(base.read_text())["findings"]


class TestCLI:
    def test_ci_exits_nonzero_on_fixture_corpus(self, capsys):
        assert cli.main([str(FIXTURES), "--ci"]) == 1
        out = capsys.readouterr().out
        assert "donation-alias" in out and "jaxlint:" in out

    def test_ci_exits_zero_on_live_package(self, capsys):
        # THE tier-1 gate: the shipped tree is clean (fix-or-suppress
        # policy — no baseline file exists in the repo)
        assert cli.main([str(PACKAGE), "--ci"]) == 0
        out = capsys.readouterr().out
        assert "0 finding(s)" in out
        assert not (Path(__file__).resolve().parent.parent
                    / "jaxlint_baseline.json").exists()

    def test_default_paths_cover_the_package(self, capsys):
        assert cli.main(["--ci"]) == 0
        # the default target is the package dir: same file count as
        # pointing at it explicitly
        n = re.search(r"across (\d+) file",
                      capsys.readouterr().out).group(1)
        assert int(n) > 50

    def test_non_ci_mode_reports_but_exits_zero(self):
        assert cli.main([str(FIXTURES)]) == 0

    def test_select_rejects_unknown_rule_names(self, capsys):
        # a typo'd --select must not run zero rules and read clean
        assert cli.main([str(FIXTURES), "--ci",
                         "--select", "donation_alias"]) == 2
        assert "unknown rule(s)" in capsys.readouterr().err

    def test_log_appends_kind_analysis_record(self, tmp_path, capsys):
        log = tmp_path / "run.jsonl"
        log.write_text('{"kind": "result", "success": true}\n')
        cli.main([str(FIXTURES), "--log", str(log)])
        records = [json.loads(l) for l in
                   log.read_text().splitlines()]
        assert records[0]["kind"] == "result"  # appended, not truncated
        rec = records[-1]
        assert rec["kind"] == "analysis" and rec["ok"] is False
        assert rec["findings"] > 0 and rec["suppressed"] == 2
        assert rec["by_rule"]["donation-alias"] >= 1

    def test_list_rules_prints_catalog(self, capsys):
        assert cli.main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in ("donation-alias", "host-sync-in-dispatch",
                     "recompile-hazard", "prng-key-reuse",
                     "tracer-leak"):
            assert rule in out


class TestBurnDownPins:
    """Regression pins for the analyzer's first full-package run: the
    true-positive fixes stay fixed."""

    def test_interop_app_jits_are_module_level(self):
        from hpc_patterns_tpu.apps import interop_app

        # hoisted wrappers: same object on every access = one trace
        # cache for the life of the process (the pre-fix form rebuilt
        # them inside run())
        assert interop_app._double is interop_app._double
        x = jnp.ones((8,), jnp.float32)
        np.testing.assert_allclose(np.asarray(interop_app._double(x)),
                                   2.0)
        np.testing.assert_allclose(np.asarray(interop_app._triple(x)),
                                   3.0)

    def test_rank_filled_reuses_its_jit(self, mesh8):
        from hpc_patterns_tpu.comm.communicator import Communicator
        from hpc_patterns_tpu.harness import trace as tracelib

        c = Communicator(mesh8, "x")
        a = c.rank_filled(16)
        b = c.rank_filled(16)
        assert len(c._rank_filled_cache) == 1
        fill = next(iter(c._rank_filled_cache.values()))
        # one compiled variant despite two calls
        assert tracelib.jit_cache_size(fill, strict=True) == 1
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c.rank_filled(32)
        assert len(c._rank_filled_cache) == 2

    def test_busy_wait_single_wrap_matches_oracle(self):
        from hpc_patterns_tpu.concurrency import kernels

        x = kernels.compute_buffer(8 * 128)
        got = kernels.busy_wait(x, 3)
        want = kernels.busy_wait_reference(x, 3)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)
        # tripcount is a runtime scalar: new values must NOT add
        # compiled variants (the autotuner contract)
        from hpc_patterns_tpu.harness import trace as tracelib

        n0 = tracelib.jit_cache_size(kernels._busy_wait_call,
                                     strict=True)
        kernels.busy_wait(x, 7)
        assert tracelib.jit_cache_size(kernels._busy_wait_call,
                                       strict=True) == n0


class TestPoisonDonated:
    def test_poison_breaks_stale_zero_copy_views(self):
        f = jax.jit(lambda v: v + 1, donate_argnums=(0,))
        x = jax.block_until_ready(jnp.arange(64, dtype=jnp.int32))
        view = np.asarray(x)  # zero-copy on CPU: the PR 2 shape
        orig = view.copy()
        pf = runtime.poison_donated(f, (0,))
        y = pf(x)
        # correctness preserved...
        np.testing.assert_array_equal(np.asarray(y), orig + 1)
        # ...and the stale view now reads EITHER the donated-in-place
        # output (donation honored) or the sentinel (poisoned): never
        # the comfortable pre-call values the bug class relies on
        assert not np.array_equal(view, orig)
        if pf.poison_count:
            assert view.view(np.uint32)[0] == 0xABABABAB

    def test_poison_skips_output_aliased_buffers(self):
        # identity-ish pytree: some leaves may alias outputs; the
        # helper must never corrupt what the caller receives
        f = jax.jit(lambda d: {"a": d["a"] * 2, "b": d["b"]},
                    donate_argnums=(0,))
        d = {"a": jnp.ones((16,)), "b": jnp.zeros((16,))}
        jax.block_until_ready(d)
        pf = runtime.poison_donated(f, (0,))
        out = pf(d)
        np.testing.assert_array_equal(np.asarray(out["a"]), 2.0)
        np.testing.assert_array_equal(np.asarray(out["b"]), 0.0)

    def test_wrapper_forwards_the_jit_cache_probe(self):
        from hpc_patterns_tpu.harness import trace as tracelib

        f = jax.jit(lambda v: v * 3, donate_argnums=(0,))
        pf = runtime.poison_donated(f, (0,))
        pf(jnp.ones((4,)))
        assert tracelib.jit_cache_size(pf, strict=True) == 1

    def test_targets_mirror_serving_donate_argnums(self):
        # SERVING_POISON_TARGETS must track models/serving.py — read
        # the donate_argnums straight out of the source with the
        # analyzer's own donor table (dogfood)
        serving_py = PACKAGE / "models" / "serving.py"
        donors = _donor_table(ModuleInfo.parse(serving_py))
        for name, argnums in runtime.SERVING_POISON_TARGETS.items():
            assert donors[name]["donate_argnums"] == argnums, name

    def test_install_serving_poison_roundtrip(self):
        from hpc_patterns_tpu.models import serving

        before = {n: getattr(serving, n)
                  for n in runtime.SERVING_POISON_TARGETS}
        uninstall = runtime.install_serving_poison()
        try:
            for n in runtime.SERVING_POISON_TARGETS:
                assert getattr(serving, n) is not before[n]
                assert getattr(serving, n).__wrapped__ is before[n]
        finally:
            uninstall()
        for n in runtime.SERVING_POISON_TARGETS:
            assert getattr(serving, n) is before[n]


class TestMarker:
    def test_dispatch_critical_is_a_noop_marker(self):
        from hpc_patterns_tpu.analysis import dispatch_critical

        def g(x):
            return x + 1

        assert dispatch_critical(g) is g
