"""Known-clean: the shipped request-trace stamp discipline
(``harness/reqtrace.py``): every lifecycle stamp is a ``perf_counter``
read plus host list mutation — segment metadata comes from values the
engine already holds on the host (bundle fields, stats dict entries),
never from a device readback. Zero findings expected."""

import time


def stamp_transition(histories, seq_id, kind, t=None):
    """The stamp contract: close the open segment, open the next —
    wall-clock and list work only, clamped so a same-tick transition
    cannot produce a negative span."""
    now = time.perf_counter() if t is None else t
    segs = histories.setdefault(seq_id, [])
    if segs and segs[-1][2] is None:
        segs[-1][2] = max(now, segs[-1][1])
    segs.append([kind, now, None, None])
    return segs


def export_history(histories, seq_id):
    """Migration export: transition to ``migrating`` and return an
    immutable copy for the bundle — the KV payload's own movement is
    the DMA tier's job, not the tracer's."""
    stamp_transition(histories, seq_id, "migrating")
    return tuple(tuple(s) for s in histories[seq_id])


def install_history(histories, seq_id, segments, t, t_submit):
    """Install side of the handoff: adopt the carried segments (or
    synthesize one ``untracked`` span for a legacy wire artifact),
    close the travel segment, open ``decode`` — pure host list work
    on metadata that arrived over the wire."""
    if segments is not None:
        segs = [list(s) for s in segments]
    elif seq_id in histories:
        segs = histories[seq_id]
    else:
        segs = [["untracked", t_submit, None, None]]
    histories[seq_id] = segs
    if segs and segs[-1][2] is None:
        segs[-1][2] = max(t, segs[-1][1])
    segs.append(["decode", t, None, None])
    return segs


def finish_request(histories, stats, seq_id, t):
    """Finish stamp: the token count comes from the stats row the
    resolve step already wrote — nothing is read back here."""
    segs = histories.get(seq_id) or []
    if segs and segs[-1][2] is None:
        segs[-1][2] = max(t, segs[-1][1])
    return stats[seq_id]["tokens"], segs
