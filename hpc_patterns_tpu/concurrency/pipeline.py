"""On-chip DMA/compute overlap benchmark (the Pallas heart of C1).

The reference's concurrency suite asks: do independent copy and compute
commands *actually overlap* on one device (sycl_con.cpp:84-115)? On TPU
the equivalent boundary is HBM↔VMEM DMA vs VPU compute inside a kernel
(SURVEY.md §2.2 "intra-device stream parallelism": Pallas double-buffered
DMA/compute overlap stands in for H2D/D2H-vs-kernel overlap), and —
unlike host wall-clock games — it is measurable honestly even through a
high-latency dispatch path, because the whole experiment is ONE kernel.

Modes, all computing a checksum over the same chunk-walk (the
correctness oracle where compute participates):

in-direction (HBM→VMEM ≙ M2D) vs compute:
- ``overlap``  — double-buffered: DMA of chunk i+1 in flight while the
  busy-wait chain runs on chunk i (the out-of-order-queue analog)
- ``serial``   — DMA chunk i, wait, compute chunk i (the reference's
  serial baseline, sycl_con.cpp:101-106)
- ``dma``      — in-DMAs only (per-command baseline for M2D)
- ``compute``  — busy-wait only (per-command baseline for C)

out-direction (VMEM→HBM ≙ D2M) vs compute:
- ``overlap_out`` — compute chunk i into a slot, start its writeback,
  only wait for that slot's previous writeback before reusing it
- ``serial_out``  — compute, write back, wait, every chunk
- ``dma_out``     — writebacks only (per-command baseline for D2M)

DMA vs DMA (≙ M2D + D2M concurrently, two DMA queues):
- ``pair_overlap`` — per chunk, start the in-copy and the out-copy
  together, then wait both
- ``pair_serial``  — in-copy start+wait, then out-copy start+wait

``tripcount`` (compute per chunk) and ``passes`` (repetitions over the
whole array, amortizing fixed overheads inside the kernel) are runtime
SMEM scalars, so the C12 autotuner balances DMA vs compute without
recompiles. Speedup/verdict math reuses the shared rules
(harness.verdict.concurrency_verdict).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hpc_patterns_tpu.concurrency.kernels import FMA_UNROLL

MODES = (
    "overlap", "serial", "dma", "compute", "compute2",
    "overlap_out", "serial_out", "dma_out",
    "pair_overlap", "pair_serial",
)
_OUT_BUF_MODES = ("overlap_out", "serial_out", "dma_out",
                  "pair_overlap", "pair_serial")


def _chain(acc, trips, salt):
    # ``salt`` (pass-index-derived) keeps every pass's chain distinct so
    # the compiler cannot hoist the loop body out of the pass loop.
    add = jnp.float32(0.5) + salt

    def body(_, a):
        for _ in range(FMA_UNROLL):
            a = a * jnp.float32(0.9999999) + add
        return a

    return lax.fori_loop(0, trips, body, acc)


def _make_in_kernel(mode: str, num_chunks: int):
    """in-direction modes: overlap | serial | dma | compute | compute2.

    ``compute2`` is the C+C pair: TWO independent busy-wait chains per
    chunk (one per scratch slot, distinct salts). They share the one
    sequential core, so per-pass time ≈ 2x a single chain at the SAME
    tripcount — which is what the resource-aware verdict floor expects.
    (Comparing one chain at 2x trips instead is not equivalent: per-trip
    cost is measurably nonlinear in tripcount on real chips.)"""
    do_dma = mode in ("overlap", "serial", "dma")
    do_compute = mode in ("overlap", "serial", "compute", "compute2")

    def kernel(scalar_ref, hbm_ref, out_ref):
        trips = scalar_ref[0]
        passes = scalar_ref[1]

        def body(scratch, sem):
            def get_dma(slot, chunk):
                return pltpu.make_async_copy(
                    hbm_ref.at[chunk], scratch.at[slot], sem.at[slot]
                )

            def one_pass(p, checksum):
                if mode == "overlap":
                    # warm-up DMA for this pass's first chunk
                    get_dma(0, 0).start()

                def chunk_step(i, csum):
                    slot = lax.rem(i, 2)
                    if mode == "overlap":

                        @pl.when(i + 1 < num_chunks)
                        def _():
                            get_dma(1 - slot, i + 1).start()

                        get_dma(slot, i).wait()
                    elif do_dma:
                        dma = get_dma(slot, i)
                        dma.start()
                        dma.wait()
                    if do_compute:
                        salt = (p * num_chunks + i).astype(jnp.float32) * jnp.float32(1e-7)
                        acc = _chain(scratch[slot], trips, salt)
                        # fold EVERY chunk into the checksum so the oracle
                        # (overlap == serial) covers every DMA'd block, not
                        # just the last one
                        csum = csum + acc[:8]
                        if mode == "compute2":
                            acc2 = _chain(scratch[1 - slot], trips,
                                          salt + jnp.float32(0.5))
                            csum = csum + acc2[:8]
                    return csum

                return lax.fori_loop(0, num_chunks, chunk_step, checksum)

            out_ref[:] = lax.fori_loop(
                0, passes, one_pass, jnp.zeros((8, 128), jnp.float32)
            )

        chunk_shape = hbm_ref.shape[1:]
        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((2, *chunk_shape), jnp.float32),
            sem=pltpu.SemaphoreType.DMA((2,)),
        )

    return kernel


def _make_out_kernel(mode: str, num_chunks: int):
    """out-direction modes: overlap_out | serial_out | dma_out.
    The writeback (VMEM→HBM ≙ D2M) and the busy-wait chain are
    INDEPENDENT commands, exactly as in the reference (its copy and
    compute touch unrelated buffers): both read the seeded scratch slot,
    nothing writes it, so there is no hazard — ``overlap_out`` lets the
    writeback fly under the chunk's compute, ``serial_out`` waits it out
    first. Semaphore slots bound the queue to two in-flight writebacks."""
    do_compute = mode in ("overlap_out", "serial_out")

    def kernel(scalar_ref, hbm_ref, out_ref, hbm_out_ref):
        trips = scalar_ref[0]
        passes = scalar_ref[1]

        def body(scratch, sem):
            # deterministic seeds: the chain's input must not be whatever
            # the previous kernel left in VMEM, or the serial/overlap
            # checksum oracle can't hold
            scratch[0] = jnp.full(scratch.shape[1:], 0.25, jnp.float32)
            scratch[1] = jnp.full(scratch.shape[1:], 0.75, jnp.float32)

            def put_dma(slot, chunk):
                return pltpu.make_async_copy(
                    scratch.at[slot], hbm_out_ref.at[chunk], sem.at[slot]
                )

            def one_pass(p, checksum):
                def chunk_step(i, csum):
                    slot = lax.rem(i, 2)
                    if mode == "overlap_out":
                        # free this sem slot (DMA issued two chunks ago)
                        @pl.when(i >= 2)
                        def _():
                            put_dma(slot, i - 2).wait()
                    dma = put_dma(slot, i)
                    dma.start()
                    if mode != "overlap_out":
                        dma.wait()
                    if do_compute:
                        salt = (p * num_chunks + i).astype(jnp.float32) * jnp.float32(1e-7)
                        acc = _chain(scratch[slot], trips, salt)
                        csum = csum + acc[:8]
                    return csum

                csum = lax.fori_loop(0, num_chunks, chunk_step, checksum)
                if mode == "overlap_out":
                    # drain the last two in-flight writebacks
                    put_dma(lax.rem(num_chunks - 2, 2), num_chunks - 2).wait()
                    put_dma(lax.rem(num_chunks - 1, 2), num_chunks - 1).wait()
                return csum

            out_ref[:] = lax.fori_loop(
                0, passes, one_pass, jnp.zeros((8, 128), jnp.float32)
            )

        chunk_shape = hbm_ref.shape[1:]
        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((2, *chunk_shape), jnp.float32),
            sem=pltpu.SemaphoreType.DMA((2,)),
        )

    return kernel


def _make_pair_kernel(mode: str, num_chunks: int):
    """pair modes: a copy-through — chunk i streams HBM→VMEM (≙ M2D),
    then VMEM→HBM (≙ D2M). ``pair_overlap`` pipelines the two directions
    across chunks (in-copy of i+1 flies while the out-copy of i drains,
    both DMA paths busy); ``pair_serial`` completes each copy before
    starting the next. Checksum reads every in-copied chunk."""

    def kernel(scalar_ref, hbm_ref, out_ref, hbm_out_ref):
        passes = scalar_ref[1]

        def body(scratch, sem_in, sem_out):
            def get_dma(slot, chunk):
                return pltpu.make_async_copy(
                    hbm_ref.at[chunk], scratch.at[slot], sem_in.at[slot]
                )

            def put_dma(slot, chunk):
                return pltpu.make_async_copy(
                    scratch.at[slot], hbm_out_ref.at[chunk], sem_out.at[slot]
                )

            def one_pass(p, checksum):
                if mode == "pair_overlap":
                    get_dma(0, 0).start()

                def chunk_step(i, csum):
                    slot = lax.rem(i, 2)
                    if mode == "pair_overlap":
                        # the out-copy of chunk i-1 reads slot 1-slot;
                        # it must land before in-copy i+1 overwrites it
                        @pl.when(i >= 1)
                        def _():
                            put_dma(1 - slot, i - 1).wait()

                        @pl.when(i + 1 < num_chunks)
                        def _():
                            get_dma(1 - slot, i + 1).start()

                        get_dma(slot, i).wait()
                        put_dma(slot, i).start()
                    else:
                        get = get_dma(slot, i)
                        get.start()
                        get.wait()
                        put = put_dma(slot, i)
                        put.start()
                        put.wait()
                    return csum + scratch[slot][:8]

                csum = lax.fori_loop(0, num_chunks, chunk_step, checksum)
                if mode == "pair_overlap":
                    put_dma(lax.rem(num_chunks - 1, 2), num_chunks - 1).wait()
                return csum

            out_ref[:] = lax.fori_loop(
                0, passes, one_pass, jnp.zeros((8, 128), jnp.float32)
            )

        chunk_shape = hbm_ref.shape[1:]
        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((2, *chunk_shape), jnp.float32),
            sem_in=pltpu.SemaphoreType.DMA((2,)),
            sem_out=pltpu.SemaphoreType.DMA((2,)),
        )

    return kernel


def _make_kernel(mode: str, num_chunks: int):
    if mode in ("overlap", "serial", "dma", "compute", "compute2"):
        return _make_in_kernel(mode, num_chunks)
    if mode in ("overlap_out", "serial_out", "dma_out"):
        return _make_out_kernel(mode, num_chunks)
    return _make_pair_kernel(mode, num_chunks)


@functools.partial(jax.jit, static_argnames=("mode", "interpret"))
def _run(hbm_array, tripcount, passes, *, mode: str, interpret: bool):
    num_chunks = hbm_array.shape[0]
    scalars = jnp.asarray([tripcount, passes], jnp.int32)
    out_shape = [jax.ShapeDtypeStruct((8, 128), jnp.float32)]
    out_specs = [pl.BlockSpec(memory_space=pltpu.VMEM)]
    if mode in _OUT_BUF_MODES:
        # writeback target stays in HBM; written only by manual DMA
        out_shape.append(
            jax.ShapeDtypeStruct(hbm_array.shape, hbm_array.dtype)
        )
        out_specs.append(pl.BlockSpec(memory_space=pl.ANY))
    results = pl.pallas_call(
        _make_kernel(mode, num_chunks),
        out_shape=tuple(out_shape),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),  # stays in HBM; DMA'd manually
        ],
        out_specs=tuple(out_specs),
        interpret=interpret,
    )(scalars, hbm_array)
    return results[0] if isinstance(results, (tuple, list)) else results


def overlap_run(
    hbm_array,
    *,
    mode: str,
    tripcount: int = 64,
    passes: int = 1,
    interpret: bool | None = None,
):
    """Run one variant over ``hbm_array`` of shape (num_chunks, rows, 128)
    float32; returns the (8, 128) checksum tile (identical across modes
    that compute — the oracle for tests)."""
    if mode not in MODES:
        raise ValueError(f"mode {mode!r} not in {MODES}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if hbm_array.ndim != 3 or hbm_array.shape[2] != 128 or hbm_array.shape[1] % 8:
        raise ValueError(
            f"want (num_chunks, 8k rows, 128) float32, got {hbm_array.shape}"
        )
    if hbm_array.shape[0] < 2 and mode == "overlap_out":
        raise ValueError("overlap_out needs >= 2 chunks")
    return _run(
        hbm_array, jnp.int32(tripcount), jnp.int32(passes),
        mode=mode, interpret=interpret,
    )


def make_hbm_array(num_chunks: int = 64, chunk_rows: int = 512, seed: int = 0):
    """The HBM working set: (num_chunks, chunk_rows, 128) float32. Values
    in [0, 1) so the busy-wait chain stays bounded."""
    key = jax.random.PRNGKey(seed)
    return jax.random.uniform(
        key, (num_chunks, chunk_rows, 128), jnp.float32
    )


def per_pass_seconds(
    hbm_array,
    mode: str,
    tripcount: int,
    *,
    cal_passes: int = 1000,
    repetitions: int = 3,
    target_s: float = 1.0,
    max_passes: int = 120_000,
):
    """Steady-state seconds per pass of ``mode``, honest through
    high-latency dispatch: a differenced calibration pair sizes the
    measurement to ~``target_s`` of device time, then
    harness.timing.amortized_seconds differences two device-dominated
    pass counts so dispatch-latency jitter divides by tens of thousands
    of passes. Shared by bench.py and the concurrency app's on-chip
    engine."""
    from hpc_patterns_tpu.harness.timing import amortized_seconds, measure_forced

    run = lambda p: overlap_run(hbm_array, mode=mode, tripcount=tripcount,
                                passes=p)
    t_two = measure_forced(lambda: run(2 * cal_passes), repetitions=1).min_s
    t_one = measure_forced(lambda: run(cal_passes), repetitions=1).min_s
    est = (t_two - t_one) / cal_passes
    if est <= 0:
        # noise ate the difference; the latency-biased single-call
        # estimate only shrinks the pass count, never the reading
        est = max(t_two / (2 * cal_passes), 1e-7)
    hi = int(min(max(target_s / est, 2 * cal_passes), max_passes))
    return amortized_seconds(run, iters=hi, repetitions=repetitions,
                             base_iters=hi // 2)


def balance_tripcount(per_pass, copy_time_s, compute_mode, trips, *,
                      max_trips=4096, rounds=2):
    """Refine ``trips`` until the compute chain's per-pass time matches
    ``copy_time_s`` (the C12 balance step, sycl_con.cpp:257-268 — linear
    T(trips), iterated because one probe's noise would leave the commands
    unbalanced). Returns ``(trips, t_compute)``, measured with
    ``per_pass(mode, trips)``. Shared by bench.py and the concurrency
    app's on-chip engine so the clamp and convergence rules can't drift."""
    t_comp = per_pass(compute_mode, trips)
    for _ in range(rounds):
        if t_comp <= 0 or copy_time_s <= 0:
            break
        new_trips = min(max(1, int(trips * copy_time_s / t_comp)), max_trips)
        if abs(new_trips - trips) <= max(2, trips // 10):
            break
        trips = new_trips
        t_comp = per_pass(compute_mode, trips)
    return trips, t_comp
