"""Decoder-only transformer, TPU-first.

Architecture choices driven by the hardware (SURVEY.md preamble +
/opt/skills/guides/pallas_guide.md):

- all matmuls shaped for the MXU: bf16 compute dtype, model dims kept in
  multiples of 128, no per-layer Python loop — layers are stacked on a
  leading axis and driven by ``lax.scan`` (one traced layer body);
- attention is pluggable: ``"full"`` (single-device oracle),
  ``"flash"`` (the Pallas blockwise kernel, ops/flash_attention.py —
  single device, or any mesh that leaves the sequence unsharded),
  ``"ring"`` (context parallelism over the ``sp`` mesh axis — the
  reference's ring dataflow, parallel/ring_attention.py),
  ``"ring_flash"`` (the same ring with the Pallas kernel as each
  step's local compute), ``"ulysses"`` (all-to-all SP), or
  ``"ulysses_flash"`` (Ulysses with the Pallas kernel as the
  rank-local full-sequence attention);
- activation sharding is annotated with ``with_sharding_constraint``;
  parameter shardings live in models/sharding.py (Megatron column/row
  rules, ≙ parallel/tensor.py helpers);
- optional remat trades FLOPs for HBM (the bandwidth-vs-memory lever),
  with a policy axis (``remat_policy``): the default "split" leaves the
  attention kernel outside any remat region so its custom_vjp
  residuals persist and the flash forward runs exactly once per step
  (measured on chip — benchmarks/RESULTS.md "MFU push").

Params are a plain pytree of f32 arrays (master weights); ``forward``
casts to ``cfg.dtype`` (bf16 by default) at use.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from hpc_patterns_tpu.models.sharding_util import mesh_axis_size, resolve_spec
from hpc_patterns_tpu.topology import shard_map
from hpc_patterns_tpu.parallel.ring_attention import full_attention, ring_attention
from hpc_patterns_tpu.parallel.ulysses import ulysses_attention

ATTENTION_IMPLS = ("full", "flash", "ring", "ring_flash", "ulysses",
                   "ulysses_flash")


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32768
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    max_seq: int = 2048
    dtype: str = "bfloat16"  # compute dtype (MXU-native)
    attention: str = "full"  # full | flash | ring[_flash] | ulysses[_flash]
    # grouped-query attention: 0 = MHA (kv heads == n_heads); smaller
    # values share each KV head across n_heads/n_kv_heads query heads,
    # shrinking the qkv projection (weights + FLOPs), the KV cache, AND
    # attention-side K/V activations — every impl consumes the narrow
    # K/V (grouped-query scores, no expansion; the ring circulates
    # group-factor less K/V). n_heads must divide by n_kv_heads
    n_kv_heads: int = 0
    # remat=True recomputes layer activations in backward; remat_policy
    # picks what is SAVED anyway (the FLOPs/HBM trade):
    #   "nothing" — recompute everything (max memory saving);
    #   "attn"    — save each attention output (the flash kernel's
    #               backward only needs its out/lse residuals, so
    #               re-running the kernel forward in the backward pass
    #               is pure waste — this skips exactly that);
    #   "dots"    — save all matmul outputs with no batch dims
    #               (jax.checkpoint_policies.dots_with_no_batch_dims)
    #   "dots_attn" — both of the above (note: a remat policy CANNOT
    #               stop the flash forward kernel re-running in the
    #               backward — custom_vjp residuals (out, lse) are
    #               internal to the kernel call, and saving the named
    #               attention output doesn't save them)
    #   "split"   — checkpoint the qkv-projection block and the
    #               mlp/residual block SEPARATELY and leave attention
    #               outside any remat region, so the flash kernel's own
    #               vjp residuals persist and its forward runs exactly
    #               once (the kernel was profiled at ~25% of step time;
    #               the replay is the removable quarter of it). Costs
    #               q/k/v/out (+lse) per layer in HBM; the big per-layer
    #               interiors (d_ff gelu, qkv matmul) still recompute.
    remat_policy: str = "split"
    # scan_layers=True drives the stacked layer weights with one traced
    # lax.scan body (fast compiles, the long-model default);
    # False unrolls the layer loop — each layer's weight slice becomes
    # static, XLA drops the per-iteration dynamic-slice copies of the
    # weight stack and fuses better (measured on chip; see RESULTS.md)
    scan_layers: bool = True
    # positional scheme: "learned" absolute table, or "rope" rotary
    # embeddings (relative; the long-context default — composes with
    # ring/ulysses sequence sharding because rotation angles are a
    # function of GLOBAL position only, applied before the shard_map)
    pos_embed: str = "learned"
    rope_theta: float = 10000.0
    remat: bool = False
    # mixture-of-experts: 0 = dense MLP; >0 = Switch-style top-1 MoE
    # with experts sharded over the ep axis (parallel/moe.py)
    n_experts: int = 0
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # experts consulted per token: 1 = Switch top-1; k >= 2 routes each
    # token to its k highest-gate experts with the k gates renormalized
    # (GShard style, first choices claim capacity slots before any
    # second choice). Drop telemetry for either: moe_drop_rates
    n_experts_top_k: int = 1
    # routing dispatch: "einsum" (one-hot (N, E, C) tensors — oracle
    # form, O(N²·cf/E) memory), "scatter" (stable-sort, O(N + E·C) —
    # identical assignments, the at-scale form), or "auto" (scatter
    # once the one-hot tensors would exceed ~16 MB)
    moe_dispatch: str = "auto"
    # fully-sharded data parallelism (ZeRO-3 style): params, grads, and
    # optimizer state shard over axis_fsdp; XLA inserts the per-layer
    # all-gather (fwd/bwd) and gradient reduce-scatter from the
    # annotations alone — GSPMD is the FSDP engine, no wrapper class.
    # The batch shards over (dp, fsdp) together. Set axis_fsdp = "dp"
    # to fully shard over the data ranks with a single axis.
    fsdp: bool = False
    axis_fsdp: str = "fsdp"
    # chunked cross-entropy: 0 = dense (materialize (B, T, V) f32
    # logits); > 0 = online-logsumexp over vocab chunks of this size —
    # the logits never exist, removing the long-context memory wall
    # (see chunked_masked_causal_nll). Must divide vocab. Training-loss
    # path only (eval/decode read real logits).
    loss_chunk: int = 0
    # training MLP implementation: "dense" = two XLA einsums (gelu
    # fused by XLA; the (N, d_ff) activation materializes in HBM
    # between them), "fused" = the Pallas fused kernel
    # (ops/fused_mlp.py — matmul→gelu→matmul streamed through VMEM,
    # d_ff activation never in HBM; one-pass fused backward). Dense
    # MLP layers only (MoE routes through parallel/moe.py)
    mlp_impl: str = "dense"
    # decode-step attention against the KV cache (models/decode.py):
    # "flash" = the single-query Pallas kernel streaming the live cache
    # prefix (ops/flash_decode.py); "gather" = the XLA einsum+mask path
    # over the full static cache — required for GSPMD-sharded (tp)
    # serving, where einsums partition but a pallas_call does not;
    # "paged_flash" = the paged-pool Pallas kernel
    # (ops/paged_attention.py): pages gather through the table into
    # VMEM with a clamped index map (unfilled pages are never fetched)
    # and the attention mirrors the gather math term for term —
    # bitwise-equal to "gather" on compute-dtype pools, in-kernel
    # dequant on int8/fp8 pools. Paged routes only; the linear-cache
    # paths (prefill, decode_step) treat it as "gather", so prefill
    # bytes stay identical between the two routes.
    decode_attn: str = "flash"
    # KV-cache storage dtype for decode: "compute" (the model dtype),
    # "int8" (per-row symmetric quantization — HALF the cache bytes and
    # per-step read traffic on the cache-read-bound decode path;
    # dequantized in the kernel/einsum stream), or "fp8"
    # (float8_e4m3fn storage with the same per-row scale layout — the
    # same byte win with ~2 more bits of mantissa headroom; probe
    # backend support with dtypes.supports_fp8, docs/quantization.md)
    kv_cache_dtype: str = "compute"
    # mesh axis names (data / sequence(context) / tensor / expert)
    axis_dp: str = "dp"
    axis_sp: str = "sp"
    axis_tp: str = "tp"
    axis_ep: str = "ep"

    @property
    def mesh_axes(self) -> frozenset:
        """Declared axis names — the set resolve_spec may prune."""
        return frozenset((self.axis_dp, self.axis_sp, self.axis_tp,
                          self.axis_ep, self.axis_fsdp))

    @property
    def batch_axes(self) -> tuple:
        """Mesh axes the batch dimension shards over: (dp, fsdp) under
        FSDP (the fsdp ranks are data ranks too), else (dp,). Always a
        tuple — PartitionSpec treats a singleton tuple as the axis."""
        if self.fsdp and self.axis_fsdp != self.axis_dp:
            return (self.axis_dp, self.axis_fsdp)
        return (self.axis_dp,)

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        if self.d_model % self.n_heads:
            raise ValueError(f"d_model {self.d_model} % n_heads {self.n_heads} != 0")
        return self.d_model // self.n_heads

    def __post_init__(self):
        if self.pos_embed not in ("learned", "rope"):
            raise ValueError(
                f"pos_embed {self.pos_embed!r} not in ('learned', 'rope')"
            )
        if self.pos_embed == "rope" and self.head_dim % 2:
            raise ValueError("rope needs an even head_dim")
        if self.attention not in ATTENTION_IMPLS:
            raise ValueError(
                f"attention {self.attention!r} not in {ATTENTION_IMPLS}"
            )
        if self.loss_chunk < 0 or (self.loss_chunk and
                                   self.vocab % self.loss_chunk):
            raise ValueError(
                f"loss_chunk {self.loss_chunk} must be 0 or divide "
                f"vocab {self.vocab}"
            )
        if self.moe_dispatch not in ("auto", "einsum", "scatter"):
            raise ValueError(
                f"moe_dispatch {self.moe_dispatch!r} not in "
                "('auto', 'einsum', 'scatter')"
            )
        if self.n_experts and not (
            1 <= self.n_experts_top_k <= max(self.n_experts, 1)
        ):
            raise ValueError(
                f"n_experts_top_k {self.n_experts_top_k} outside "
                f"[1, n_experts={self.n_experts}]"
            )
        if self.kv_cache_dtype not in ("compute", "int8", "fp8"):
            raise ValueError(
                f"kv_cache_dtype {self.kv_cache_dtype!r} not in "
                "('compute', 'int8', 'fp8')"
            )
        if self.decode_attn not in ("flash", "gather", "paged_flash"):
            raise ValueError(
                f"decode_attn {self.decode_attn!r} not in "
                "('flash', 'gather', 'paged_flash')"
            )
        if self.mlp_impl not in ("dense", "fused"):
            raise ValueError(
                f"mlp_impl {self.mlp_impl!r} not in ('dense', 'fused')"
            )
        if self.remat_policy not in ("nothing", "attn", "dots", "dots_attn",
                                     "split"):
            raise ValueError(
                f"remat_policy {self.remat_policy!r} not in "
                "('nothing', 'attn', 'dots', 'dots_attn', 'split')"
            )
        if self.n_kv_heads < 0 or self.n_kv_heads > self.n_heads or (
            self.n_kv_heads and self.n_heads % self.n_kv_heads
        ):
            raise ValueError(
                f"n_kv_heads {self.n_kv_heads} must be in [1, n_heads] and "
                f"divide n_heads {self.n_heads} (0 = MHA)"
            )


def init_params(key, cfg: TransformerConfig):
    """f32 master params; layer weights stacked on a leading n_layers
    axis for ``lax.scan``."""
    D, F, L, V = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab
    k = iter(jax.random.split(key, 8))

    def initn(shape, scale):
        return jax.random.normal(next(k), shape, jnp.float32) * scale

    layers = {
        "ln1_scale": jnp.ones((L, D), jnp.float32),
        "ln2_scale": jnp.ones((L, D), jnp.float32),
        # fused q + k + v projection; with GQA the kv widths shrink to
        # kv_heads * head_dim
        "wqkv": initn((L, D, D + 2 * cfg.kv_heads * cfg.head_dim),
                      D ** -0.5),
        "wo": initn((L, D, D), (2 * D * L) ** -0.5),
    }
    pos = (
        {} if cfg.pos_embed == "rope"
        else {"pos_embed": initn((cfg.max_seq, D), 0.02)}
    )
    if cfg.n_experts:
        E = cfg.n_experts
        layers["router"] = initn((L, D, E), D ** -0.5)
        layers["w1"] = initn((L, E, D, F), D ** -0.5)
        layers["w2"] = initn((L, E, F, D), (2 * F * L) ** -0.5)
    else:
        layers["w1"] = initn((L, D, F), D ** -0.5)
        layers["w2"] = initn((L, F, D), (2 * F * L) ** -0.5)
    return {
        "embed": initn((V, D), 0.02),
        **pos,
        "layers": layers,
        "ln_f_scale": jnp.ones((D,), jnp.float32),
        "lm_head": initn((D, V), D ** -0.5),
    }


#: sibling-key suffix carrying a quantized weight's per-output-channel
#: dequant scales (see :func:`quantize_weights_int8`). Riding INSIDE
#: the params tree (not a parallel tree) keeps every existing
#: per-layer slice (``jax.tree.map(lambda a: a[l], ...)``, the prefill
#: ``lax.scan``) working unchanged — the scales slice with their
#: weights.
QUANT_SCALE_SUFFIX = "_qscale"


def _quantize_channels(w):
    """Per-output-channel symmetric int8 quantization of a matmul
    weight ``(..., d_in, d_out)``: returns (int8 values, f32 scales
    shaped ``(..., d_out)``) with ``w ~= q * scale``. Output-channel
    granularity because the matmul contracts over ``d_in``: every
    element of an output column shares one scale, so dequant folds
    into the column (lane) axis of the product stream."""
    w32 = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale[..., None, :]),
                 -127, 127).astype(jnp.int8)
    return q, scale


#: the decode-matmul weights :func:`quantize_weights_int8` covers —
#: every per-layer GEMM of the decode step (qkv projection, attention
#: output, MLP up/down) plus the lm_head below
QUANTIZED_LAYER_WEIGHTS = ("wqkv", "wo", "w1", "w2")


def quantize_weights_int8(params):
    """Opt-in int8 weight quantization for the DECODE matmuls: every
    2-D GEMM weight of the step (``wqkv``/``wo``/``w1``/``w2`` per
    layer, plus ``lm_head``) is replaced by int8 values with
    per-output-channel f32 scales under ``<name>_qscale`` sibling keys
    — 4x (vs f32 masters) fewer weight bytes per decode step, the
    second lever next to the quantized KV pools on the
    data-movement-bound decode path. Norm scales and the embedding
    table stay full precision (they are gathers/elementwise, not
    GEMMs). Dequant happens AT USE (:func:`matmul_weight`): the HBM
    read is int8, the f32 product of the dequant fuses into the matmul
    stream.

    Token identity CANNOT hold across precision — the law is pinned
    TV-distance-style by the sampling oracles instead (greedy top-1
    agreement rate + total-variation bounds, tests/test_quantization.py
    and ``bench_serving --kv-dtype``; docs/quantization.md)."""
    if "router" in params["layers"]:
        raise ValueError(
            "quantize_weights_int8 covers dense decode layers "
            f"({QUANTIZED_LAYER_WEIGHTS}); MoE expert weights would "
            "need per-expert channel scales (and paged serving is "
            "dense-only anyway)")
    layers = dict(params["layers"])
    for name in QUANTIZED_LAYER_WEIGHTS:
        q, s = _quantize_channels(layers[name])
        layers[name] = q
        layers[name + QUANT_SCALE_SUFFIX] = s
    out = dict(params)
    out["layers"] = layers
    q, s = _quantize_channels(params["lm_head"])
    out["lm_head"] = q
    out["lm_head" + QUANT_SCALE_SUFFIX] = s
    return out


def matmul_weight(tree, name, dt):
    """THE dequant-at-use accessor for a (possibly int8-quantized)
    matmul weight: plain weights cast to the compute dtype exactly as
    before; quantized weights (a ``<name>_qscale`` sibling present)
    dequantize per output channel in the einsum stream — the HBM
    traffic stays int8, the f32 multiply fuses. Shared by the training
    layer (qkv/wo/mlp/lm_head/loss-head sites) and every decode path so
    a quantized params tree serves through all of them or none; the
    pipeline-parallel stage math spells its own matmuls and REFUSES
    quantized trees instead (pp_loss_and_grads)."""
    w = tree[name]
    qs = tree.get(name + QUANT_SCALE_SUFFIX)
    if qs is None:
        return w.astype(dt)
    # scales are per OUTPUT channel (the last weight axis); the
    # explicit lane broadcast also covers a still-stacked (L, ...) tree
    return (w.astype(jnp.float32)
            * qs.astype(jnp.float32)[..., None, :]).astype(dt)


def _rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * lax.rsqrt(var + 1e-6).astype(x.dtype)) * scale.astype(x.dtype)


def apply_rope(x, positions, cfg: TransformerConfig):
    """Rotary position embedding: rotate each (even, odd-half) feature
    pair of ``x`` (..., T, heads, head_dim) by angle pos·theta^(-2i/d).
    ``positions``: (..., T) int32 GLOBAL positions — scores then depend
    only on relative distance, which is what lets the same weights serve
    any context layout (ring/ulysses shards, KV-cache decode steps).
    Rotation is computed in f32 and cast back (bf16 angle resolution is
    not enough at long range)."""
    Dh = x.shape[-1]
    half = Dh // 2
    inv_freq = cfg.rope_theta ** (
        -jnp.arange(0, half, dtype=jnp.float32) / half
    )
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # (..., T, half)
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)


def project_qkv(h, lp, cfg: TransformerConfig):
    """Fused qkv projection + head split, GQA-narrow K/V (kv_heads, not
    yet expanded). THE qkv layout definition — shared by the training
    layer (_layer) and the decode path (models/decode.py) so the two can
    never disagree on the split or head order. ``h``: (..., d_model);
    returns q (..., n_heads, Dh), k/v (..., kv_heads, Dh)."""
    *lead, D = h.shape
    dt = h.dtype
    H, Hkv, Dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    qkv = jnp.dot(h, matmul_weight(lp, "wqkv", dt))  # column-parallel
    q, k, v = jnp.split(qkv, [D, D + Hkv * Dh], axis=-1)
    return (
        q.reshape(*lead, H, Dh),
        k.reshape(*lead, Hkv, Dh),
        v.reshape(*lead, Hkv, Dh),
    )


def _attention(q, k, v, cfg: TransformerConfig, mesh):
    """Dispatch to the configured attention impl. ring/ulysses wrap the
    rank-local kernels in ``shard_map`` over (dp, sp, tp) — sequence
    travels the ``sp`` ring while heads stay tensor-sharded."""
    if cfg.attention == "flash":
        from hpc_patterns_tpu.ops import flash_attention

        if mesh is None:
            return flash_attention(q, k, v, causal=True)
        if mesh_axis_size(mesh, cfg.axis_sp) > 1:
            raise ValueError(
                "attention='flash' needs the sequence unsharded (sp=1); "
                "use 'ring_flash' to run the Pallas kernel per ring step "
                "over a sharded sequence"
            )
        # sequence unsharded: the kernel runs per-(dp, tp) shard on the
        # full local sequence
        spec = resolve_spec(P(cfg.batch_axes, None, cfg.axis_tp, None), mesh,
                            cfg.mesh_axes)
        return shard_map(
            partial(flash_attention, causal=True), mesh=mesh,
            in_specs=(spec, spec, spec), out_specs=spec,
        )(q, k, v)
    if cfg.attention == "full" or mesh is None:
        return full_attention(q, k, v, causal=True)
    spec = resolve_spec(P(cfg.batch_axes, cfg.axis_sp, cfg.axis_tp, None), mesh,
                        cfg.mesh_axes)
    base, _, variant = cfg.attention.partition("_")
    local_impl = variant or "dense"
    impl_fn = ulysses_attention if base == "ulysses" else ring_attention
    fn = partial(impl_fn, axis=cfg.axis_sp, causal=True, impl=local_impl)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def _moe_block(h, lp, cfg: TransformerConfig, mesh, with_stats=False):
    """Top-k routed experts over the ep axis (parallel/moe.py; k =
    cfg.n_experts_top_k, 1 = Switch). Returns (out, aux_loss), plus the
    kept fraction when ``with_stats`` (the telemetry moe_drop_rates
    surfaces)."""
    from hpc_patterns_tpu.parallel import moe

    B, T, D = h.shape
    k = cfg.n_experts_top_k

    def resolve_dispatch(n_local, cap):
        if cfg.moe_dispatch != "auto":
            return cfg.moe_dispatch
        # scatter once the one-hot (N, E, C) tensors stop being small:
        # measured equal-or-faster on chip at small shapes (182.6-196.3
        # vs 199.4 ms/step at 4k tokens, adjacent runs) and strictly
        # enabling at scale (the 16k-token config OOMs under einsum,
        # trains at 436.8 ms/step under scatter) — einsum remains the
        # oracle form and the tiny-shape default. The footprint counts
        # BOTH live one-hots (dispatch and combine) at their choice-major
        # (k*N, E, C) f32 shape — not one (N, E, C) tensor, which
        # undercounted by 2k and flipped to scatter late
        return ("scatter"
                if 2 * k * n_local * cfg.n_experts * cap * 4 > 16 << 20
                else "einsum")

    if mesh is None:
        # capacity scales with k: top-k routes k·N assignments, so the
        # slot budget is k·N·cf/E (GShard's sizing; k=1 is unchanged)
        cap = moe.default_capacity(B * T * k, cfg.n_experts,
                                   cfg.capacity_factor)
        out = moe.moe_dense(
            h.reshape(B * T, D), lp["router"], lp["w1"], lp["w2"],
            capacity=cap, top_k=k, with_stats=with_stats,
            dispatch=resolve_dispatch(B * T, cap),
        )
        return (out[0].reshape(B, T, D), *out[1:])

    sp, ep = cfg.axis_sp, cfg.axis_ep
    bx = cfg.batch_axes
    b_size = math.prod(mesh_axis_size(mesh, ax) for ax in bx)
    # tokens shard over the batch axes AND ep for the MoE block: ep must
    # partition the routing/FFN work, not replicate it (the reshard in
    # and out is XLA's, riding ICI). When the batch doesn't divide
    # batch*ep, fall back to batch-only token sharding (ep still
    # partitions the experts; routing work is then replicated across ep).
    batch_over_ep = B % (b_size * mesh_axis_size(mesh, ep)) == 0
    if not batch_over_ep and mesh_axis_size(mesh, ep) > 1:
        import warnings

        warnings.warn(
            f"moe: batch {B} does not divide batch_shards*ep "
            f"({b_size}*{mesh_axis_size(mesh, ep)}); routing runs "
            "replicated across ep (experts still partitioned) — pad the "
            "batch to recover partitioned routing",
            stacklevel=2,
        )
    b_shards = b_size * (mesh_axis_size(mesh, ep) if batch_over_ep else 1)
    n_local = (B // b_shards) * (T // mesh_axis_size(mesh, sp))
    cap = moe.default_capacity(n_local * k, cfg.n_experts,
                               cfg.capacity_factor)

    has = lambda ax: ax in mesh.axis_names

    disp = resolve_dispatch(n_local, cap)

    def local(hl, router, w1l, w2l):
        b, t, d = hl.shape
        if has(ep):
            y, aux, *st = moe.moe_ep(
                hl.reshape(b * t, d), router, w1l, w2l,
                axis=ep, capacity=cap, top_k=k, with_stats=with_stats,
                dispatch=disp,
            )
        else:  # no expert axis in this mesh: all experts local
            y, aux, *st = moe.moe_dense(
                hl.reshape(b * t, d), router, w1l, w2l, capacity=cap,
                top_k=k, with_stats=with_stats, dispatch=disp,
            )
        # moe_ep means aux over ep (as a comm axis); with tokens also
        # sharded on ep, fold every data axis for the global scalars
        scalars = [aux, *st]
        for ax in (*bx, sp):
            if has(ax):
                scalars = [lax.pmean(v, ax) for v in scalars]
        return (y.reshape(b, t, d), *scalars)

    tok_spec = (
        resolve_spec(P((*bx, ep), sp, None), mesh, cfg.mesh_axes)
        if has(ep) and batch_over_ep
        else resolve_spec(P(cfg.batch_axes, sp, None), mesh, cfg.mesh_axes)
    )
    out = shard_map(
        local,
        mesh=mesh,
        in_specs=(tok_spec, P(None, None),
                  resolve_spec(P(ep, None, None), mesh, cfg.mesh_axes),
                  resolve_spec(P(ep, None, None), mesh, cfg.mesh_axes)),
        out_specs=(tok_spec, P()) + ((P(),) if with_stats else ()),
        check_vma=False,  # all_to_all + pmean replication not VMA-provable
    )(h, lp["router"], lp["w1"], lp["w2"])
    return out


def _qkv_block(x, lp, cfg: TransformerConfig, mesh):
    """Pre-attention: norm + fused qkv projection + rope + the GQA
    narrow-vs-expand decision. Split out so remat_policy="split" can
    checkpoint it independently of the attention kernel."""
    B, T, D = x.shape
    H = cfg.n_heads
    h = _rmsnorm(x, lp["ln1_scale"])
    q, k, v = project_qkv(h, lp, cfg)
    if cfg.pos_embed == "rope":
        # global positions: the layer always sees the full sequence (the
        # sp shard_map lives inside _attention), so iota(T) is correct
        # under every sharding
        pos = lax.broadcasted_iota(jnp.int32, (T,), 0)
        q = apply_rope(q, pos, cfg)
        k = apply_rope(k, pos, cfg)
    if cfg.kv_heads != H:
        # GQA: every attention impl consumes the NARROW K/V (no expanded
        # copy in HBM — the group-factor memory/bandwidth saving; the
        # ring additionally circulates group-factor less K/V per step).
        # The only layout constraint here: with heads tensor-sharded, tp
        # must divide kv_heads so shards keep whole kv heads — else fall
        # back to jnp.repeat expansion. (ulysses has its own internal
        # per-rank fallback when its axis can't scatter the kv heads;
        # decode does its own grouped-cache attention, models/decode.py.)
        tp = max(mesh_axis_size(mesh, cfg.axis_tp), 1) if mesh is not None else 1
        narrow = cfg.kv_heads % tp == 0
        if not narrow:
            k = jnp.repeat(k, H // cfg.kv_heads, axis=2)
            v = jnp.repeat(v, H // cfg.kv_heads, axis=2)
    return q, k, v


def _post_attn(x, o, lp, cfg: TransformerConfig, mesh, act_spec):
    """Output projection + residual + pre-MLP norm: the first half of
    :func:`_post_block`, split out so split-remat can checkpoint it
    while the fused MLP kernel stays OUTSIDE the remat region (same
    reasoning as the attention kernel — a custom_vjp's residuals can't
    be saved by any policy from outside the call)."""
    B, T, D = x.shape
    dt = x.dtype
    o = jnp.dot(o.reshape(B, T, D), matmul_weight(lp, "wo", dt))  # row-parallel
    x = x + o
    if mesh is not None:
        x = lax.with_sharding_constraint(x, act_spec)
    return x, _rmsnorm(x, lp["ln2_scale"])


def _mlp_fused(h, lp, cfg: TransformerConfig, mesh):
    """The Pallas fused MLP on ``h`` (post-norm activations). Single
    device runs the kernel directly; under a mesh it runs shard_mapped
    (a pallas_call does not GSPMD-partition): tokens stay
    (batch, sp)-sharded, w1/w2 enter column/row-sharded over tp, and
    the row-parallel psum closes the block — the manual spelling of
    exactly the collective XLA inserts for the einsum path."""
    from hpc_patterns_tpu.ops.fused_mlp import fused_mlp

    dt = h.dtype
    # dequant-at-entry for a quantized tree: the kernel wants dense
    # compute-dtype operands, so the int8-HBM-read win doesn't apply
    # here — correctness does
    w1 = matmul_weight(lp, "w1", dt)
    w2 = matmul_weight(lp, "w2", dt)
    if mesh is None:
        return fused_mlp(h, w1, w2)
    tp = cfg.axis_tp
    has_tp = mesh_axis_size(mesh, tp) > 1
    x_spec = resolve_spec(P(cfg.batch_axes, cfg.axis_sp, None), mesh,
                          cfg.mesh_axes)
    w1_spec = resolve_spec(P(None, tp), mesh, cfg.mesh_axes)
    w2_spec = resolve_spec(P(tp, None), mesh, cfg.mesh_axes)

    def local(h, w1, w2):
        y = fused_mlp(h, w1, w2)
        return lax.psum(y, tp) if has_tp else y

    return shard_map(
        local, mesh=mesh, in_specs=(x_spec, w1_spec, w2_spec),
        out_specs=x_spec,
        check_vma=False,  # pallas_call can't declare vma
    )(h, w1, w2)


def _post_block(x, o, lp, cfg: TransformerConfig, mesh, act_spec,
                with_stats=False):
    """Post-attention: output projection, residual, norm, mlp/moe.
    Returns (x, moe_aux) — with ``with_stats`` also the MoE kept
    fraction (1.0 for dense layers)."""
    dt = x.dtype

    def c(y, spec):
        return lax.with_sharding_constraint(y, spec) if mesh is not None else y

    x, h = _post_attn(x, o, lp, cfg, mesh, act_spec)
    if cfg.n_experts:
        h, aux, *st = _moe_block(h, lp, cfg, mesh, with_stats=with_stats)
        h = h.astype(dt)
    elif cfg.mlp_impl == "fused":
        h = _mlp_fused(h, lp, cfg, mesh).astype(dt)
        aux = jnp.zeros((), jnp.float32)
        st = [jnp.ones((), jnp.float32)] if with_stats else []
    else:
        h = jax.nn.gelu(jnp.dot(h, matmul_weight(lp, "w1", dt)))  # column-parallel
        h = jnp.dot(h, matmul_weight(lp, "w2", dt))  # row-parallel (psum by XLA)
        aux = jnp.zeros((), jnp.float32)
        st = [jnp.ones((), jnp.float32)] if with_stats else []
    return (c(x + h, act_spec), aux, *st)


def _layer(x, lp, cfg: TransformerConfig, mesh, act_spec,
           split_remat: bool = False):
    """One pre-norm block: attn + mlp/moe, Megatron-sharded (wqkv/w1
    column, wo/w2 row — models/sharding.py), activations re-constrained
    after each collective-inducing matmul. Returns (x, moe_aux).

    ``split_remat``: checkpoint the qkv and post blocks separately,
    attention OUTSIDE any remat region — the flash kernel's custom_vjp
    residuals (out, lse) then persist to the backward and its forward
    runs exactly once (no policy can achieve this from outside the
    kernel call; see TransformerConfig.remat_policy)."""
    pre = partial(_qkv_block, cfg=cfg, mesh=mesh)
    post = partial(_post_block, cfg=cfg, mesh=mesh, act_spec=act_spec)
    fused_split = (split_remat and cfg.mlp_impl == "fused"
                   and not cfg.n_experts)
    if split_remat:
        # dots policy inside each block: elementwise interiors (rope,
        # norms, gelu) recompute, matmul outputs don't — recomputing
        # the qkv/mlp matmuls costs more than the HBM they free
        dots = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        pre = jax.checkpoint(pre, policy=dots)
        post = jax.checkpoint(post, policy=dots)
    q, k, v = pre(x, lp)
    o = _attention(q, k, v, cfg, mesh)
    # named so remat_policy="attn" can pin it under whole-layer remat
    o = checkpoint_name(o, "attn_out")
    if fused_split:
        # like attention, the fused MLP kernel must live OUTSIDE the
        # remat region or its one-pass backward replays the forward:
        # checkpoint only the o-proj/residual/norm half, then run the
        # kernel on the saved norm output
        pa = jax.checkpoint(
            partial(_post_attn, cfg=cfg, mesh=mesh, act_spec=act_spec),
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
        x1, hn = pa(x, o, lp)
        h = _mlp_fused(hn, lp, cfg, mesh).astype(x.dtype)
        out = x1 + h
        if mesh is not None:
            out = lax.with_sharding_constraint(out, act_spec)
        return out, jnp.zeros((), jnp.float32)
    return post(x, o, lp)


def forward(params, tokens, cfg: TransformerConfig, mesh=None, *,
            return_aux: bool = False):
    """Logits for next-token prediction. ``tokens``: (batch, seq) int32.
    ``mesh``: the device mesh for sharding constraints + ring/ulysses
    attention; None = single-device (tests/oracle). With
    ``return_aux=True`` also returns the summed MoE load-balance loss
    (zeros for dense models)."""
    x, aux = forward_hidden(params, tokens, cfg, mesh)
    logits = jnp.dot(x, matmul_weight(params, "lm_head", x.dtype))
    logits = logits.astype(jnp.float32)
    if return_aux:
        return logits, aux
    return logits


def _embed_tokens(params, tokens, cfg: TransformerConfig, mesh, dt):
    """Token + learned-position embedding lookup. Under fsdp the bf16
    working copies of the feature-sharded tables are constrained
    replicated BEFORE the gather — the explicit form of ZeRO-3's
    all-gather-weights-just-before-use. Without it the partitioner must
    inverse-reshard the batch-sharded activation cotangent into the
    feature-sharded table layout in the backward, which it can only do
    by "involuntary full rematerialization" (observed as
    spmd_partitioner warnings on the fsdp dryrun leg); the explicit
    replication compiles to a plain feature all-gather forward and a
    reduce-scatter backward instead."""
    T = tokens.shape[1]
    replicate = mesh is not None and cfg.fsdp
    emb = params["embed"].astype(dt)
    if replicate:
        emb = lax.with_sharding_constraint(
            emb, jax.sharding.NamedSharding(mesh, P())
        )
    x = emb[tokens]
    if cfg.pos_embed == "learned":
        pos = params["pos_embed"].astype(dt)
        if replicate:
            pos = lax.with_sharding_constraint(
                pos, jax.sharding.NamedSharding(mesh, P())
            )
        x = x + pos[:T]
    return x


def forward_hidden(params, tokens, cfg: TransformerConfig, mesh=None):
    """The trunk of :func:`forward` WITHOUT the LM head: final-norm
    hidden states (B, T, d_model) in compute dtype, plus the summed MoE
    aux. The chunked loss consumes this so the (B, T, vocab) logits are
    never materialized."""
    dt = jnp.dtype(cfg.dtype)
    B, T = tokens.shape
    if mesh is not None:
        act_spec = jax.sharding.NamedSharding(
            mesh, resolve_spec(P(cfg.batch_axes, cfg.axis_sp, None), mesh,
                               cfg.mesh_axes)
        )
    else:
        act_spec = None
    x = _embed_tokens(params, tokens, cfg, mesh, dt)
    if mesh is not None:
        x = lax.with_sharding_constraint(x, act_spec)

    layer = partial(_layer, cfg=cfg, mesh=mesh, act_spec=act_spec)
    if cfg.remat:
        if cfg.remat_policy == "split":
            layer = partial(layer, split_remat=True)
        else:
            cp = jax.checkpoint_policies
            policy = {
                "nothing": None,
                "attn": cp.save_only_these_names("attn_out"),
                "dots": cp.dots_with_no_batch_dims_saveable,
                "dots_attn": cp.save_from_both_policies(
                    cp.dots_with_no_batch_dims_saveable,
                    cp.save_only_these_names("attn_out"),
                ),
            }[cfg.remat_policy]
            layer = jax.checkpoint(layer, policy=policy)

    if cfg.scan_layers:
        x, auxes = lax.scan(lambda h, lp: layer(h, lp), x, params["layers"])
    else:
        aux_list = []
        for i in range(cfg.n_layers):
            lp = jax.tree.map(lambda a: a[i], params["layers"])
            x, aux_i = layer(x, lp)
            aux_list.append(aux_i)
        auxes = jnp.stack(aux_list)
    return _rmsnorm(x, params["ln_f_scale"]), jnp.sum(auxes)


def moe_drop_rates(params, tokens, cfg: TransformerConfig, mesh=None):
    """Per-layer MoE routing drop rate on this batch: (n_layers,) f32,
    the fraction of routed (token, choice) assignments that found no
    capacity slot. The visibility companion to the oracle tests —
    capacity drops during TRAINING are otherwise silent (they only show
    up as quality loss); train_app logs this alongside the loss. Uses
    the same forward math as training (routing is deterministic), no
    gradients."""
    if not cfg.n_experts:
        raise ValueError("moe_drop_rates needs an MoE config")
    dt = jnp.dtype(cfg.dtype)
    B, T = tokens.shape
    if mesh is not None:
        act_spec = jax.sharding.NamedSharding(
            mesh, resolve_spec(P(cfg.batch_axes, cfg.axis_sp, None), mesh,
                               cfg.mesh_axes)
        )
    else:
        act_spec = None
    x = _embed_tokens(params, tokens, cfg, mesh, dt)
    if mesh is not None:
        x = lax.with_sharding_constraint(x, act_spec)

    def body(h, lp):
        q, k, v = _qkv_block(h, lp, cfg, mesh)
        o = _attention(q, k, v, cfg, mesh)
        h, _aux, kept = _post_block(h, o, lp, cfg, mesh, act_spec,
                                    with_stats=True)
        return h, kept

    _, kepts = lax.scan(body, x, params["layers"])
    return 1.0 - kepts


def masked_causal_nll(logits, tokens):
    """Mean next-token NLL with the final position masked out — shared by
    loss_fn and the pipeline-parallel loss head (models/pp.py), so loss
    semantics can't drift between the two training paths."""
    B, T = tokens.shape
    targets = jnp.roll(tokens, -1, axis=1)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold
    mask = (lax.broadcasted_iota(jnp.int32, (B, T), 1) < T - 1).astype(nll.dtype)
    return jnp.sum(nll * mask) / jnp.sum(mask)


def chunked_masked_causal_nll(x, lm_head, tokens, *, chunk: int):
    """:func:`masked_causal_nll` computed WITHOUT ever materializing the
    (B, T, vocab) logits: a ``lax.scan`` over vocab chunks carries the
    online logsumexp state (running max, rescaled sumexp) and picks out
    each target's gold logit from the chunk that owns it — O(B·T·chunk)
    live memory instead of O(B·T·V). The scan body is rematted (saves
    only the small carry per chunk), so the backward recomputes each
    chunk's logits and the full f32 logits never exist in either pass
    — at long context this is THE memory wall: (B=1, T=65536, V=32768)
    f32 logits alone are 8 GB.

    ``x``: (B, T, d_model) final hidden states (forward_hidden);
    ``lm_head``: (d_model, V) in compute dtype; ``chunk`` must divide V.
    Numerically equal to the dense path (same f32 logit values, online
    logsumexp association), oracle-tested.
    """
    B, T = tokens.shape
    V = lm_head.shape[1]
    if V % chunk:
        raise ValueError(f"loss chunk {chunk} must divide vocab {V}")
    n_chunks = V // chunk
    targets = jnp.roll(tokens, -1, axis=1)
    w = lm_head.reshape(lm_head.shape[0], n_chunks, chunk)

    @jax.checkpoint
    def body(carry, wc_and_idx):
        m, s, gold = carry
        wc, c = wc_and_idx
        logits_c = jnp.dot(x, wc).astype(jnp.float32)  # (B, T, chunk)
        m_c = logits_c.max(axis=-1)
        m_new = jnp.maximum(m, m_c)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits_c - m_new[..., None]), axis=-1
        )
        local = targets - c * chunk
        in_chunk = (local >= 0) & (local < chunk)
        picked = jnp.take_along_axis(
            logits_c, jnp.clip(local, 0, chunk - 1)[..., None], axis=-1
        )[..., 0]
        gold = jnp.where(in_chunk, picked, gold)
        return (m_new, s, gold), None

    init = (
        jnp.full((B, T), -jnp.inf, jnp.float32),
        jnp.zeros((B, T), jnp.float32),
        jnp.zeros((B, T), jnp.float32),
    )
    (m, s, gold), _ = lax.scan(
        body, init,
        (jnp.moveaxis(w, 1, 0), jnp.arange(n_chunks)),
    )
    nll = m + jnp.log(s) - gold
    mask = (lax.broadcasted_iota(jnp.int32, (B, T), 1) < T - 1).astype(nll.dtype)
    return jnp.sum(nll * mask) / jnp.sum(mask)


def loss_fn(params, tokens, cfg: TransformerConfig, mesh=None):
    """Causal LM loss: predict token t+1 from prefix ≤ t (mean NLL).

    The full (batch, seq) token array feeds forward() and the final
    position is masked out of the loss — rather than slicing to seq-1 —
    so sequence shardings (seq % sp == 0) survive into the activations.
    """
    if cfg.loss_chunk:
        x, aux = forward_hidden(params, tokens, cfg, mesh)
        loss = chunked_masked_causal_nll(
            x, matmul_weight(params, "lm_head", x.dtype), tokens,
            chunk=cfg.loss_chunk,
        )
    else:
        logits, aux = forward(params, tokens, cfg, mesh, return_aux=True)
        loss = masked_causal_nll(logits, tokens)
    if cfg.n_experts:
        loss = loss + cfg.moe_aux_weight * aux
    return loss
