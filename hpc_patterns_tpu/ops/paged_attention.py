"""Exact-softmax paged-attention decode kernel (``decode_attn="paged_flash"``).

The third paged decode route, next to ``flash_decode_paged`` (online
softmax, streamed) and ``_paged_attend_gather`` (pure XLA): the kernel
GATHERS each sequence's live pages into VMEM through the page table —
per-page blocks whose index map CLAMPS past-the-fill steps to the last
live page, so unfilled pages are never fetched from HBM — and then
runs the attention in ONE pass whose math mirrors the gather route
term for term (same einsum spellings, same mask constant, same
``jax.nn.softmax``). Two properties fall out:

- **parity**: on compute-dtype (f32/bf16) pools the kernel is
  BITWISE-equal to ``cfg.decode_attn="gather"`` in interpret mode
  (tests/test_quantization.py pins it across page counts, partial
  pages, ladder rungs, and tp shards) — the serving routes can swap
  per backend without an oracle caveat. Quantized pools dequantize
  in-kernel with the same elementwise order the gather view uses, so
  they ride the same battery (tolerance-tier, see below);
- **no online-softmax rescale**: a decode step has ONE query group, so
  the (g, S) score row costs g·S·4 bytes of VMEM — cheap enough to
  hold, which removes the per-block rescale multiplies entirely
  (the FlashDecoding-- observation: online softmax exists for big
  query tiles, not single queries).

Quantized pools (``kv_cache_dtype`` "int8"/"fp8"): per-row scales ride
alongside the pool in kernel-lane layout ``(pool, Hkv, 1, P)``; the
kernel streams the one-byte pages — HALF the HBM bytes of bf16, a
QUARTER of f32, on a cache-read-bound path — and dequantizes in VMEM
before the score/value einsums exactly as the gather view does
(``kd = k.astype(f32) * scale_row``). The parity battery holds these
to tight tolerance rather than asserting bitwise (the dequant multiply
order is the one place backends may legally differ;
docs/quantization.md has the full precision matrix).

VMEM bound: the gather scratch holds the whole ALLOCATED span —
``pages·P·D`` elements of the pool dtype for K and V each, plus the
(g, pages·P) f32 score row. At chip serving shapes (S_alloc 16k,
D 128) that is ~4 MB for int8 pools and ~8 MB for bf16 — inside the
~16 MB budget quantized serving targets; f32 pools at long context
belong on the streaming (``flash``) route. HBM traffic stays
position-proportional either way: the clamped index map never fetches
a page past the fill, and Pallas elides the repeated clamped fetch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the mask constant the bitwise route-parity contract depends on; must
# equal parallel.ring_attention._NEG_INF (importing it here is circular
# via comm.ring -> ops; tests/test_quantization.py pins the equality)
_NEG_INF = -1e30


def _paged_attention_kernel(pos_ref, table_ref, q_ref, k_ref, v_ref,
                            *rest, scale: float, page_size: int,
                            quantized: bool, hkv_per_row: int):
    # grid (B·Hkv, pages): steps 0..pages-1 stage this row's (clamped)
    # page into the gather scratch; the LAST step runs the whole
    # attention — the gather route's einsum/mask/softmax sequence on
    # the staged span. The table ref is consumed by the index maps.
    del table_ref
    if quantized:
        (ks_ref, vs_ref, o_ref, k_sc, v_sc, ks_sc, vs_sc) = rest
    else:
        ks_ref = vs_ref = ks_sc = vs_sc = None
        (o_ref, k_sc, v_sc) = rest
    P = page_size
    si = pl.program_id(1)
    n_s = pl.num_programs(1)
    pos = (pos_ref[pl.program_id(0) // hkv_per_row] if hkv_per_row
           else pos_ref[0])

    # UNCONDITIONAL stage (clamped steps re-stage the last live page):
    # past-the-fill scratch slots must hold FINITE bytes — the mask
    # zeroes their probability, and 0 * garbage-NaN would poison the
    # value einsum exactly where uninitialized VMEM can surprise
    k_sc[pl.ds(si * P, P), :] = k_ref[...]
    v_sc[pl.ds(si * P, P), :] = v_ref[...]
    if quantized:
        ks_sc[:, pl.ds(si * P, P)] = ks_ref[...]
        vs_sc[:, pl.ds(si * P, P)] = vs_ref[...]

    @pl.when(si == n_s - 1)
    def _():
        # the gather route's math, term for term (_paged_attend_gather):
        # f32 dequant/upcast, HIGHEST-precision einsums, the same mask
        # constant, jax.nn.softmax — bitwise parity on compute dtypes
        q = q_ref[...].astype(jnp.float32)          # (g, D)
        kd = k_sc[...].astype(jnp.float32)          # (S_alloc, D)
        vd = v_sc[...].astype(jnp.float32)
        if quantized:
            kd = kd * ks_sc[...][0, :, None]
            vd = vd * vs_sc[...][0, :, None]
        s = jnp.einsum("gd,sd->gs", q, kd,
                       precision=lax.Precision.HIGHEST) * scale
        idx = lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(idx <= pos, s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        o_ref[...] = jnp.einsum("gs,sd->gd", p, vd,
                                precision=lax.Precision.HIGHEST)


def paged_attention_decode(
    q,
    k_pool,
    v_pool,
    table,
    pos,
    *,
    k_scale_pool=None,
    v_scale_pool=None,
    scale: float | None = None,
    interpret: bool | None = None,
):
    """Single-query attention against a paged KV pool, exact-softmax
    form (module docstring has the design).

    ``q``: (B, n_heads, head_dim); ``k_pool``/``v_pool``:
    (pool_pages, kv_heads, page_size, head_dim) in the pool dtype
    (compute dtype, int8, or float8_e4m3fn); ``table``:
    (B, pages_per_seq) int32 page ids; ``pos``: traced int32 scalar or
    (B,) per-sequence fill positions (ragged serving — each grid row
    clamps and masks by its own sequence's position).
    ``k_scale_pool``/``v_scale_pool``: (pool_pages, kv_heads, 1,
    page_size) f32 per-row dequant scales — REQUIRED for quantized
    pools, refused for compute-dtype ones. Returns (B, n_heads,
    head_dim) f32, the gather route's numbers.
    """
    B, H, D = q.shape
    n_pool, Hkv, P, Dp = k_pool.shape
    pages = table.shape[1]
    if H % Hkv or v_pool.shape != k_pool.shape or Dp != D:
        raise ValueError(
            f"shape mismatch: q {q.shape}, pools {k_pool.shape}/"
            f"{v_pool.shape}"
        )
    if table.shape[0] != B:
        raise ValueError(f"table rows {table.shape[0]} != batch {B}")
    quantized = k_scale_pool is not None
    if quantized != (v_scale_pool is not None):
        raise ValueError("k_scale_pool and v_scale_pool come together")
    storage_quantized = k_pool.dtype in (jnp.int8, jnp.float8_e4m3fn)
    if quantized != storage_quantized:
        raise ValueError(
            f"pool dtype {k_pool.dtype} "
            f"{'needs' if storage_quantized else 'refuses'} per-row "
            "scale pools (kv_cache_dtype and the scale operands must "
            "agree)")
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    g = H // Hkv

    qr = q.reshape(B * Hkv, g, D)
    ragged = jnp.ndim(pos) == 1
    if ragged and jnp.shape(pos)[0] != B:
        raise ValueError(
            f"ragged pos has {jnp.shape(pos)[0]} entries for batch {B}"
        )
    pos_arr = jnp.asarray(pos, jnp.int32).reshape(B if ragged else 1)
    table_flat = table.reshape(-1).astype(jnp.int32)

    def page_idx(r, si, pos_ref, table_ref):
        # clamp past-the-fill steps to the last live page (the fetch
        # elision shared with flash_decode_paged), then indirect
        # through this sequence's page list
        b = r // Hkv
        live = jnp.minimum(si, pos_ref[b if ragged else 0] // P)
        return table_ref[b * pages + live], r % Hkv, 0, 0

    row = functools.partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    in_specs = [
        row((None, g, D), lambda r, si, pos, tab: (r, 0, 0)),
        row((None, None, P, D), page_idx),
        row((None, None, P, D), page_idx),
    ]
    operands = [pos_arr, table_flat, qr, k_pool, v_pool]
    scratch = [
        pltpu.VMEM((pages * P, D), k_pool.dtype),   # K gather span
        pltpu.VMEM((pages * P, D), v_pool.dtype),   # V gather span
    ]
    if quantized:
        in_specs += [row((None, None, 1, P), page_idx),
                     row((None, None, 1, P), page_idx)]
        operands += [k_scale_pool, v_scale_pool]
        scratch += [pltpu.VMEM((1, pages * P), jnp.float32),
                    pltpu.VMEM((1, pages * P), jnp.float32)]
    out = pl.pallas_call(
        functools.partial(_paged_attention_kernel, scale=float(scale),
                          page_size=P, quantized=quantized,
                          hkv_per_row=Hkv if ragged else 0),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * Hkv, pages),
            in_specs=in_specs,
            out_specs=row((None, g, D), lambda r, si, pos, tab: (r, 0, 0)),
            scratch_shapes=scratch,
        ),
        out_shape=jax.ShapeDtypeStruct((B * Hkv, g, D), jnp.float32),
        interpret=interpret,
    )(*operands)
    return out.reshape(B, H, D)
