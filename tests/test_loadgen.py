"""Open-loop load generator (harness/loadgen.py): every schedule is
deterministic given (params, seed), JSON round-trips exactly (the
replay contract chaos runs depend on), and each arrival process has
its defining statistical shape."""

import numpy as np
import pytest

from hpc_patterns_tpu.harness import loadgen

CLASSES = (
    loadgen.PriorityClass("interactive", 0, weight=1.0,
                          ttft_slo_s=0.5, tpot_slo_s=0.1,
                          deadline_s=2.0),
    loadgen.PriorityClass("batch", 1, weight=3.0),
)


def _sched(process="poisson", n=64, seed=0, **kw):
    return loadgen.make_schedule(
        n, rate_rps=50.0, classes=CLASSES, prompt_lens=(8, 16, 32),
        budgets=(4, 8, 16), budget_probs=(0.5, 0.3, 0.2),
        process=process, seed=seed, **kw)


class TestDeterminism:
    @pytest.mark.parametrize("process", ["poisson", "bursty", "diurnal"])
    def test_same_seed_same_schedule(self, process):
        assert _sched(process) == _sched(process)

    def test_different_seed_different_schedule(self):
        assert _sched(seed=1) != _sched(seed=2)

    def test_json_round_trip_is_exact(self):
        s = _sched("bursty", burst_factor=4.0)
        assert loadgen.Schedule.from_json(s.to_json()) == s
        # provenance rides along: the spec names what generated it
        assert s.spec["process"] == "bursty"
        assert s.spec["burst_factor"] == 4.0


class TestShapes:
    def test_arrivals_sorted_and_positive(self):
        for process in ("poisson", "bursty", "diurnal"):
            t = [r.t_arrival_s for r in _sched(process).requests]
            assert all(b >= a for a, b in zip(t, t[1:]))
            assert all(v > 0 for v in t)

    def test_poisson_rate_is_roughly_the_mean(self):
        s = _sched("poisson", n=512, seed=3)
        # 512 arrivals at 50 rps ≈ 10.24s span; generous 30% band
        assert 512 / s.duration_s == pytest.approx(50.0, rel=0.3)

    def test_bursty_is_burstier_than_poisson(self):
        # the defining property: the variance of per-window arrival
        # counts far exceeds the (Poisson) mean — the index of
        # dispersion separates the two processes cleanly
        def dispersion(sched):
            t = np.array([r.t_arrival_s for r in sched.requests])
            counts, _ = np.histogram(t, bins=max(4, int(t[-1] / 0.1)))
            return counts.var() / max(counts.mean(), 1e-9)

        poisson = dispersion(_sched("poisson", n=512, seed=5))
        bursty = dispersion(_sched("bursty", n=512, seed=5,
                                   burst_factor=16.0))
        assert bursty > 2.0 * poisson

    def test_diurnal_rate_modulates_with_the_period(self):
        s = _sched("diurnal", n=1024, seed=7, period_s=10.0, depth=0.9)
        t = np.array([r.t_arrival_s for r in s.requests])
        phase = (t % 10.0) / 10.0
        # peak half-period (sin > 0) must carry well more traffic
        peak = np.count_nonzero(phase < 0.5)
        trough = len(t) - peak
        assert peak > 1.5 * trough

    def test_classes_split_by_weight(self):
        s = _sched(n=512, seed=9)
        n_batch = sum(r.cls == "batch" for r in s.requests)
        assert n_batch / 512 == pytest.approx(0.75, abs=0.08)
        for r in s.requests:
            if r.cls == "interactive":
                assert r.priority == 0 and r.deadline_s == 2.0
            else:
                assert r.priority == 1 and r.deadline_s is None
            assert r.prompt_len in (8, 16, 32)
            assert r.max_new in (4, 8, 16)


class TestStaged:
    def test_staged_schedule_is_literal(self):
        inter, batch = CLASSES
        s = loadgen.staged_schedule([
            (0.0, batch, 32, 160),
            (0.25, inter, 16, 16),
        ])
        assert s.n == 2 and s.spec["process"] == "staged"
        assert s.requests[1].t_arrival_s == 0.25
        assert s.requests[1].priority == 0
        assert loadgen.Schedule.from_json(s.to_json()) == s

    def test_staged_rejects_time_travel(self):
        inter, batch = CLASSES
        with pytest.raises(ValueError, match="non-decreasing"):
            loadgen.staged_schedule([(1.0, batch, 8, 4),
                                     (0.5, inter, 8, 4)])


class TestGuards:
    def test_bad_params_raise(self):
        with pytest.raises(ValueError, match="unknown process"):
            _sched("weekly")
        with pytest.raises(ValueError, match="rate_rps"):
            loadgen.make_schedule(4, rate_rps=0.0, classes=CLASSES,
                                  prompt_lens=(8,), budgets=(4,))
        with pytest.raises(ValueError, match="PriorityClass"):
            loadgen.make_schedule(4, rate_rps=1.0, classes=(),
                                  prompt_lens=(8,), budgets=(4,))
        with pytest.raises(ValueError, match="depth"):
            _sched("diurnal", depth=1.5)
        with pytest.raises(ValueError, match="burst_factor"):
            _sched("bursty", burst_factor=0.5)
